#!/usr/bin/env python
"""Crash-orphan scrubber for the object-store KV tier.

The refcount protocol (README "Object-store KV tier") has crash windows:
an object put can commit before its owner's ref marker lands (ref-less
object — nothing will ever release it), a last-ref delete can be
interrupted between the object delete and the marker delete (dangling
ref), and a manifest can outlive every run it names (dead manifest — a
wake delivers nothing).  This tool drives the EXACT same walk the
in-process janitor runs (``kafka_tpu.runtime.object_tier.fsck``) against
a store by path or URL and prints the report as JSON.

Dry-run is the DEFAULT: nothing is deleted without ``--repair``.  An
mtime grace window (``--grace``, default 1 hour) fences off in-flight
protocol steps — the crash windows are milliseconds wide, so anything
younger than the grace window is reported as ``in_grace`` and left
untouched either way.

    # report only (safe anywhere)
    python scripts/objstore_fsck.py /mnt/kv-bucket --dry-run

    # repair orphans older than 10 minutes
    python scripts/objstore_fsck.py /mnt/kv-bucket --repair --grace 600

    # S3-shaped HTTP backend (same store the server mounts via an
    # http(s):// KAFKA_TPU_KV_OBJECT_DIR)
    python scripts/objstore_fsck.py http://kv-store:9000/bucket --repair

Exit status: 0 when the store is clean (or was repaired clean), 1 when
orphans remain (dry-run found some, or repairs failed), 2 on a store
walk error.  Tier-1 smoke-tests this script end to end
(tests/test_store_guard.py), so scrub-protocol drift is caught without
hardware.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kafka_tpu.runtime.object_tier import (  # noqa: E402
    HTTPObjectStore,
    LocalFSObjectStore,
    fsck,
)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="Walk refs<->objects<->manifests of an object-store "
                    "KV tier and report (or repair) crash-window orphans."
    )
    ap.add_argument("store",
                    help="store root: a shared directory path, or an "
                         "http(s):// URL of an S3-shaped backend")
    ap.add_argument("--repair", action="store_true",
                    help="delete the orphans found (default: report only)")
    ap.add_argument("--dry-run", action="store_true",
                    help="report only (the default; explicit flag for "
                         "scripting clarity — wins over --repair)")
    ap.add_argument("--grace", type=float, default=3600.0,
                    help="mtime grace window in seconds; anything younger "
                         "is never touched (default 3600)")
    args = ap.parse_args()

    if args.store.startswith(("http://", "https://")):
        store = HTTPObjectStore(args.store)
    else:
        if not os.path.isdir(args.store):
            print(f"error: {args.store!r} is not a directory",
                  file=sys.stderr)
            return 2
        store = LocalFSObjectStore(args.store)

    repair = args.repair and not args.dry_run
    report = fsck(store, grace_s=args.grace, repair=repair)
    print(json.dumps(report, indent=2, sort_keys=True))

    orphans = (len(report["refless_objects"]) + len(report["dangling_refs"])
               + len(report["dead_manifests"]))
    if report["errors"] and not report["objects"] and not report["refs"]:
        return 2  # the walk itself failed; the report is not meaningful
    if orphans and not repair:
        return 1  # dry-run found work
    if repair and report["repaired"] < orphans:
        return 1  # some repairs failed
    return 0


if __name__ == "__main__":
    sys.exit(main())
