#!/usr/bin/env python
"""Replay signals snapshots through the autoscaler decision table.

The controller's decision function (runtime/autoscaler.decide) is pure
over (snapshot, state, config, clock) — this tool drives the EXACT same
function the live control loop runs, in dry-run, and prints the decision
trace.  Two input modes:

* **Recorded**: one or more JSON files of /admin/signals snapshots — a
  single object, a JSON array, or JSON-lines (one snapshot per line).
  Snapshots replay at a synthetic clock (`--interval` seconds apart), so
  a captured incident replays in milliseconds and a threshold change
  shows its decision diff immediately.

      python scripts/autoscale_sim.py captured_signals.jsonl

* **Live** (`--url`): poll a running server's GET /admin/signals at
  `--interval` for `--polls` rounds and trace what a controller WOULD
  do — the recommend-mode shadow run without touching the server's own
  config.  `--token` / $KAFKA_TPU_API_TOKEN authenticates against a
  token-gated deployment.

      python scripts/autoscale_sim.py --url http://localhost:8000 \
          --polls 30 --interval 2

All KAFKA_TPU_AUTOSCALE_* knobs (hysteresis bands, sustain windows,
cooldowns, dp bounds — see README "Autoscaler") apply, so operators tune
thresholds against a recording before enabling the live loop.  Tier-1
smoke-tests this script end to end (tests/test_autoscaler.py), so
decision-table drift is caught without hardware.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kafka_tpu.runtime.autoscaler import (  # noqa: E402
    HOLD,
    AutoscalerConfig,
    AutoscalerController,
    ControllerState,
)


def load_snapshots(path: str) -> list:
    """One JSON object, a JSON array, or JSON-lines -> list of dicts."""
    with open(path) as f:
        text = f.read().strip()
    if not text:
        return []
    try:
        data = json.loads(text)
        if isinstance(data, list):
            return data
        return [data]
    except json.JSONDecodeError:
        out = []
        for i, line in enumerate(text.splitlines()):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise SystemExit(f"{path}:{i + 1}: bad JSON line: {e}")
        return out


def fetch_signals(url: str, token: str = "") -> dict:
    import urllib.request

    req = urllib.request.Request(url.rstrip("/") + "/admin/signals")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read().decode())


def fmt_decision(entry, as_json: bool = False) -> str:
    if as_json:
        return json.dumps(entry)
    d = entry
    parts = [f"[{d['seq']:>4}]", f"{d['action']:<9}", d.get("cause", "")]
    if d.get("dp_target") is not None:
        parts.append(f"dp {d['dp']}->{d['dp_target']}")
    if d.get("roles_target"):
        parts.append(f"roles={d['roles_target']}")
    if d.get("ladder_target") is not None:
        parts.append(f"ladder->{d['ladder_target']}")
    if d.get("intended"):
        parts.append(f"(held: would {d['intended']}; "
                     f"veto {','.join(d.get('vetoes') or [])})")
    inp = d.get("inputs") or {}
    bits = []
    if inp.get("attainment_1m") is not None:
        bits.append(f"attain_1m={inp['attainment_1m']}")
    if inp.get("queue_depth") is not None:
        bits.append(f"q={inp['queue_depth']}")
    if inp.get("queue_trend_per_s") is not None:
        bits.append(f"trend={inp['queue_trend_per_s']}")
    if inp.get("anomalies_active"):
        bits.append(f"anomalies={inp['anomalies_active']}")
    if bits:
        parts.append("| " + " ".join(bits))
    return " ".join(str(p) for p in parts)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="replay signals snapshots through the autoscaler "
                    "decision table (dry-run)")
    ap.add_argument("files", nargs="*",
                    help="recorded /admin/signals JSON (object, array, "
                         "or JSON-lines)")
    ap.add_argument("--url", help="poll a live server instead of files")
    ap.add_argument("--token",
                    default=os.environ.get("KAFKA_TPU_API_TOKEN", ""),
                    help="bearer token for --url "
                         "(default: $KAFKA_TPU_API_TOKEN)")
    ap.add_argument("--polls", type=int, default=30,
                    help="live-mode poll rounds (default 30)")
    ap.add_argument("--interval", type=float, default=None,
                    help="seconds between polls / synthetic replay step "
                         "(default: KAFKA_TPU_AUTOSCALE_INTERVAL_S)")
    ap.add_argument("--json", action="store_true",
                    help="print full decision entries as JSON lines")
    ap.add_argument("--quiet-holds", action="store_true",
                    help="print only non-hold decisions and vetoed holds")
    args = ap.parse_args(argv)
    if bool(args.files) == bool(args.url):
        ap.error("pass snapshot files OR --url (exactly one)")

    cfg = AutoscalerConfig.from_env(mode="recommend")
    if args.interval:
        cfg.interval_s = args.interval
    ctl = AutoscalerController(provider=None, cfg=cfg)
    printed = 0

    def emit() -> None:
        nonlocal printed
        # the controller collapses identical holds; print anything new
        for entry in list(ctl.decisions)[printed:]:
            if args.quiet_holds and entry["action"] == HOLD \
                    and not entry.get("vetoes"):
                printed += 1
                continue
            print(fmt_decision(entry, as_json=args.json))
            printed += 1

    if args.url:
        now = time.monotonic()
        for i in range(args.polls):
            try:
                snap = fetch_signals(args.url, args.token)
            except Exception as e:
                print(f"# poll {i}: fetch failed: {e}", file=sys.stderr)
                time.sleep(cfg.interval_s)
                continue
            ctl.poll_once(now=time.monotonic(), snap=snap)
            emit()
            if i + 1 < args.polls:
                time.sleep(cfg.interval_s)
        _ = now
    else:
        snaps = []
        for path in args.files:
            snaps.extend(load_snapshots(path))
        if not snaps:
            raise SystemExit("no snapshots found")
        ctl.replay(snaps)
        emit()

    state: ControllerState = ctl.state
    print(f"# {ctl._seq} decision(s), ladder level {state.ladder}, "
          f"counters: " + ", ".join(
              f"{k.replace('autoscaler_', '')}={v}"
              for k, v in ctl.counters.items() if v))
    return 0


if __name__ == "__main__":
    sys.exit(main())
