"""Sweep EngineConfig.multi_step through real engine decode throughput.

Per-dispatch host+tunnel overhead is amortized over the fused-step depth;
this measures the end-to-end tok/s (tokens landed on host over wall time —
the only tunnel-robust metric) at several depths.

Usage: python scripts/sweep_multistep.py [--depths 8,16,24]
"""

from __future__ import annotations

import argparse
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir",
                  os.path.expanduser("~/.cache/kafka_tpu/xla"))
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)

from kafka_tpu.models import get_config, init_params
from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine

from bench import decode_phase, make_prompt  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama-3.2-1b")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--gen-len", type=int, default=256)
    ap.add_argument("--depths", default="8,16,24")
    args = ap.parse_args()

    cfg = get_config(args.model)
    params = init_params(cfg, jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    rng = random.Random(0)

    for depth in [int(d) for d in args.depths.split(",")]:
        ecfg = EngineConfig(
            max_batch=args.batch, page_size=16,
            max_pages_per_seq=-(-(args.prompt_len + args.gen_len + 16) // 16),
            multi_step=depth,
        )
        ecfg.num_pages = args.batch * ecfg.max_pages_per_seq + 1
        eng = InferenceEngine(cfg, params, ecfg)
        t0 = time.monotonic()
        eng.generate(make_prompt(rng, args.prompt_len, cfg.vocab_size),
                     max_new_tokens=2)
        for i in range(4):
            eng.submit(GenRequest(
                request_id=f"w{depth}-{i}",
                prompt_ids=make_prompt(rng, args.prompt_len, cfg.vocab_size),
                max_new_tokens=depth + 4))
        eng.run_to_completion()
        print(f"depth {depth:3d}: compile {time.monotonic() - t0:5.1f}s",
              flush=True)
        tps, sps = decode_phase(eng, cfg, args.batch, args.prompt_len,
                                args.gen_len, rng)
        print(f"depth {depth:3d}: {tps:7.1f} tok/s  {sps:6.1f} steps/s",
              flush=True)
        del eng


if __name__ == "__main__":
    main()
