"""Decode-step profiler: where do the ~90ms/step go?

Builds the bench configuration (llama-3.2-1b, batch 8), prefers the real
TPU, and times nested subsets of the decode step:

  A. engine.step() loop            — everything (host scheduling included)
  A'. measured dispatch latency    — the flight recorder's fetch-maturation
                                     timing (same source as the /metrics
                                     model-skew gauge), isolating device
                                     time from host scheduling
  A''. sampled per-kernel table    — KAFKA_TPU_PROFILE_SAMPLE=N kernel
                                     sampler output (same table as
                                     GET /debug/kernels): device time by
                                     XLA program, n/a when sampling is off
  B. decode_fn device loop         — jitted step only, device-resident args
  C. variant: greedy argmax only   — drops the top-k/top-p sort pipeline
  D. variant: no logits head       — drops the [H, V] projection + sampling
  E. variant: no attention gather  — decode against a contiguous window view

Prints a table of ms/step so the deltas attribute cost to each stage.

Usage: python scripts/profile_decode.py [--model llama-3.2-1b] [--steps 50]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from kafka_tpu.models import get_config, init_params
from kafka_tpu.models.llama import KVCache, PagedView, forward
from kafka_tpu.ops.sampling import SamplingParams, sample_tokens_per_slot
from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine
from kafka_tpu.runtime.kv_cache import page_table_array


def timed_loop(fn, steps: int, final=None) -> float:
    """Time `steps` pipelined dispatches, blocking ONCE at the end.

    On a tunneled TPU a per-step block_until_ready measures the ~100ms
    device->host RTT, not compute (the r03 version of this script did
    exactly that and attributed ~118ms to a 5ms step).  Queuing all
    dispatches and blocking on the final state keeps the device saturated
    the way the engine's async fetch pipeline does.
    """
    fn()  # warmup/compile
    if final is not None:
        jax.block_until_ready(final())
    jax.effects_barrier()
    t0 = time.monotonic()
    for _ in range(steps):
        fn()
    if final is not None:
        jax.block_until_ready(final())
    else:
        jax.effects_barrier()
    return (time.monotonic() - t0) / steps * 1e3


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama-3.2-1b")
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=128)
    args = ap.parse_args()

    cfg = get_config(args.model)
    print(f"# devices: {jax.devices()}")
    params = init_params(cfg, jax.random.PRNGKey(0))
    jax.block_until_ready(params)

    ecfg = EngineConfig(
        max_batch=args.batch, page_size=16,
        max_pages_per_seq=-(-(args.prompt_len + 256 + 16) // 16),
    )
    ecfg.num_pages = args.batch * ecfg.max_pages_per_seq + 1
    engine = InferenceEngine(cfg, params, ecfg)

    rng = np.random.RandomState(0)
    for i in range(args.batch):
        engine.submit(GenRequest(
            request_id=f"p-{i}",
            prompt_ids=rng.randint(4, cfg.vocab_size - 4, args.prompt_len).tolist(),
            max_new_tokens=10_000,
        ))
    while engine.num_active < args.batch:
        engine.step()

    # ---- A. full scheduler loop (divide by fused depth!) -----------------
    s0 = engine.metrics.decode_steps
    t0 = time.monotonic()
    iters = 0
    while engine.metrics.decode_steps - s0 < args.steps:
        engine.step()
        iters += 1
    dsteps = engine.metrics.decode_steps - s0
    ms_a = (time.monotonic() - t0) / dsteps * 1e3
    print(f"A engine.step() full loop      : {ms_a:8.2f} ms/device-step "
          f"({dsteps} device steps in {iters} scheduler iterations)")

    # ---- A'. measured dispatch latency (flight recorder, ISSUE 11) -------
    # The recorder derives per-dispatch device time from fetch-maturation
    # order inside the async pipeline — the SAME numbers /metrics exports
    # as kafka_tpu_dispatch_measured_seconds_total and the model-skew
    # gauge, so this section replaces the ad-hoc wall arithmetic the old
    # script attributed whole-loop time with.  Wall-clock A above keeps
    # the host scheduling overhead visible; A' isolates device time.
    util = engine.metrics.utilization_snapshot()
    dec = util.get("decode") or {}
    if dec.get("measured_dispatches"):
        meas_ms = dec["measured_busy_s"] / dec["measured_dispatches"] * 1e3
        print(f"A' measured dispatch latency   : {meas_ms:8.2f} ms/dispatch "
              f"({dec['measured_dispatches']} measured; "
              f"model skew {dec.get('model_skew', 0)}x)")
    elif engine.flight is not None:
        recs = engine.flight.records()
        meas = sorted(r["measured_ms"] for r in recs
                      if r["measured_ms"] > 0)
        if meas:
            # median: the first sample absorbs any XLA compile that ran
            # inside the window, which would wreck a mean
            print(f"A' measured dispatch latency   : "
                  f"{meas[len(meas) // 2]:8.2f} ms/iteration median "
                  f"({len(meas)} recorded iterations; no roofline on "
                  "this backend, so no model-skew figure)")
    else:
        print("A' measured dispatch latency   :     n/a "
              "(KAFKA_TPU_FLIGHT_RING=0)")

    # ---- A''. sampled per-kernel device time (ISSUE 18) ------------------
    # The kernel sampler (runtime/kernel_profiler.py) wrapped every Nth
    # engine.step above in a jax.profiler trace when
    # KAFKA_TPU_PROFILE_SAMPLE=N was set at engine construction; its
    # per-kernel table attributes the A'-level device time to the actual
    # XLA programs (fusions, matmuls, gathers) instead of whole
    # dispatches — the same table GET /debug/kernels serves.
    sampler = engine.kernel_sampler
    if sampler is not None:
        sampler.close(engine.metrics)  # flush any open trace window
        rows = sampler.table(top_k=12)
        if rows:
            print(f"A'' sampled kernel table       : "
                  f"{sampler.samples_total} sample(s)")
            print(f"   {'kind':<16} {'kernel':<40} {'count':>6} "
                  f"{'total us':>10} {'avg us':>8} {'frac':>6}")
            for r in rows:
                print(f"   {r['kind']:<16} {r['kernel'][:40]:<40} "
                      f"{r['count']:>6} {r['total_us']:>10.0f} "
                      f"{r['avg_us']:>8.1f} {r['frac']:>6.3f}")
        else:
            print("A'' sampled kernel table       :     n/a "
                  "(no samples landed — raise --steps or lower "
                  "KAFKA_TPU_PROFILE_SAMPLE)")
    else:
        print("A'' sampled kernel table       :     n/a "
              "(set KAFKA_TPU_PROFILE_SAMPLE=N to sample every Nth "
              "step)")

    # ---- device-resident args for the raw fn loops ----------------------
    B, ps, C = ecfg.max_batch, ecfg.page_size, ecfg.max_window
    table = jnp.asarray(page_table_array(
        [s.seq if s else None for s in engine.slots], ecfg.max_pages_per_seq))
    seq_lens = jnp.asarray(np.array(
        [s.seq.length if s else 0 for s in engine.slots], np.int32))
    last = jnp.asarray(np.array(
        [(s.output_ids[-1] if s and s.output_ids else 0) for s in engine.slots],
        np.int32))
    active = jnp.ones((B,), bool)
    temps = jnp.zeros((B,), jnp.float32)
    top_ks = jnp.zeros((B,), jnp.int32)
    top_ps = jnp.ones((B,), jnp.float32)
    seeds = jnp.zeros((B,), jnp.uint32)

    state = {"k": engine.k_pool, "v": engine.v_pool, "last": last}

    def run_b():
        k, v, toks, _ = engine._decode_fn(
            engine.params, state["k"], state["v"], table, state["last"],
            seq_lens, active, temps, top_ks, top_ps, seeds, None)
        state["k"], state["v"], state["last"] = k, v, toks

    ms_b = timed_loop(run_b, args.steps, final=lambda: state["last"])
    print(f"B decode_fn device loop        : {ms_b:8.2f} ms/step"
          f"   (host sched overhead: {ms_a - ms_b:.2f})")

    # ---- C/D/E variants --------------------------------------------------
    def make_variant(mode: str):
        def fn(params, k_pool, v_pool, page_table, last_tokens, seq_lens_):
            positions = seq_lens_[:, None]
            write_page = page_table[jnp.arange(B), seq_lens_ // ps]
            write_idx = (write_page * ps + seq_lens_ % ps)[:, None]
            read_idx = (
                page_table[:, :, None] * ps + jnp.arange(ps)[None, None, :]
            ).reshape(B, C)
            kv_positions = jnp.broadcast_to(jnp.arange(C)[None, :], (B, C))
            kv_valid = kv_positions <= seq_lens_[:, None]
            paged = PagedView(write_idx, read_idx, kv_positions, kv_valid)
            logits, cache = forward(
                params, cfg, last_tokens[:, None], positions,
                kv_cache=KVCache(k_pool, v_pool), paged=paged)
            if mode == "no_logits":
                tok = jnp.sum(logits[:, 0, :8], axis=-1).astype(jnp.int32) % 17
            else:  # argmax
                tok = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            return cache.k, cache.v, tok

        return jax.jit(fn, donate_argnums=(1, 2))

    for mode, label in [("argmax", "C greedy argmax (no sort)    "),
                        ("no_logits", "D no vocab head + argmax     ")]:
        fn = make_variant(mode)

        def run(fn=fn):
            k, v, toks = fn(engine.params, state["k"], state["v"], table,
                            state["last"], seq_lens)
            state["k"], state["v"], state["last"] = k, v, toks

        ms = timed_loop(run, args.steps, final=lambda: state["last"])
        print(f"{label}: {ms:8.2f} ms/step")

    # ---- E. logits head alone (bf16 vs f32-cast) -------------------------
    x = jnp.ones((B, cfg.hidden_size), cfg.activation_dtype)
    head = params["embed"]

    f32 = jax.jit(lambda x, h: jnp.einsum(
        "bh,vh->bv", x.astype(jnp.float32), h.astype(jnp.float32)))
    bf16 = jax.jit(lambda x, h: jnp.einsum(
        "bh,vh->bv", x, h, preferred_element_type=jnp.float32))
    sink = {"a": None}
    ms = timed_loop(lambda: sink.__setitem__("a", f32(x, head)),
                    args.steps, final=lambda: sink["a"])
    print(f"E logits head f32-cast         : {ms:8.2f} ms/step")
    ms = timed_loop(lambda: sink.__setitem__("a", bf16(x, head)),
                    args.steps, final=lambda: sink["a"])
    print(f"F logits head bf16->f32 accum  : {ms:8.2f} ms/step")

    # ---- G. sampling pipeline alone --------------------------------------
    logits = jnp.ones((B, cfg.vocab_size), jnp.float32)
    keys = jax.vmap(jax.random.key)(jnp.arange(B, dtype=jnp.uint32))
    samp = jax.jit(lambda lg: sample_tokens_per_slot(
        lg, SamplingParams(temps, top_ks, top_ps), keys, None))
    ms = timed_loop(lambda: sink.__setitem__("a", samp(logits)),
                    args.steps, final=lambda: sink["a"])
    print(f"G sampling pipeline (greedy)   : {ms:8.2f} ms/step")

    # ---- I. head orientation: tied [V, H] vs transposed [H, V] -----------
    # The tied-embedding logits einsum contracts the MINOR axis of a [V, H]
    # table; if XLA tiles that poorly, a one-time transposed copy (engine
    # option) buys the MXU-natural [H, V] layout.
    head_t = jnp.asarray(np.asarray(head).T)  # [H, V]
    vh = jax.jit(lambda x, h: jnp.einsum(
        "bh,vh->bv", x, h, preferred_element_type=jnp.float32))
    hv = jax.jit(lambda x, h: jnp.einsum(
        "bh,hv->bv", x, h, preferred_element_type=jnp.float32))
    ms = timed_loop(lambda: sink.__setitem__("a", vh(x, head)),
                    args.steps, final=lambda: sink["a"])
    print(f"I logits head [V,H] (tied)     : {ms:8.2f} ms/step")
    ms = timed_loop(lambda: sink.__setitem__("a", hv(x, head_t)),
                    args.steps, final=lambda: sink["a"])
    print(f"J logits head [H,V] transposed : {ms:8.2f} ms/step")

    # ---- K. decode with xla attention backend (vs auto/pallas above) -----
    if engine.cfg.attention_backend == "pallas":
        import dataclasses as _dc

        from kafka_tpu.runtime.engine import InferenceEngine as IE

        xeng = IE(cfg, engine.params,
                  _dc.replace(ecfg, attention_backend="xla"), kv_dtype=None)
        xstate = {"k": xeng.k_pool, "v": xeng.v_pool, "last": state["last"]}

        def run_x():
            k, v, toks, _ = xeng._decode_fn(
                xeng.params, xstate["k"], xstate["v"], table,
                xstate["last"], seq_lens, active, temps, top_ks, top_ps,
                seeds, None)
            xstate["k"], xstate["v"], xstate["last"] = k, v, toks

        ms = timed_loop(run_x, args.steps, final=lambda: xstate["last"])
        print(f"K decode_fn xla attention      : {ms:8.2f} ms/step")

    # ---- H. fused multi-step scan (the serving configuration) ------------
    k = ecfg.multi_step
    if k > 1:
        mfn = engine._get_multi_decode_fn(k)

        def run_m():
            kp, vp, toks_seq, last_, lens_ = mfn(
                engine.params, state["k"], state["v"], table,
                state["last"], seq_lens, active, temps, top_ks,
                top_ps, seeds)
            state["k"], state["v"], state["last"] = kp, vp, last_

        ms = timed_loop(run_m, max(4, args.steps // k),
                        final=lambda: state["last"])
        print(f"H fused {k}-step scan          : {ms / k:8.2f} ms/step "
              f"({ms:.2f} ms/dispatch)")


if __name__ == "__main__":
    main()
