"""Attribute the decode step's time to its pieces, device-resident.

Builds the real decode-layer computation at bench shapes (llama-3.2-1b,
batch 8, ctx ~336 like the r4 roofline table) and times nested variants,
each as ONE dispatch of REPEAT on-device passes (lax.scan), differencing
two dispatch counts to cancel the tunnel RTT (scripts/bench_fused_mlp.py
timing discipline — per-dispatch timing through the tunnel is noise).

Variants:
  mm    qkv + o + mlp matmuls only (the weight stream)
  rope  + rotary embedding on q/k
  attn  + paged attention (pallas kernel) reading the real pool
  write + KV pool scatter writes
  head  final-norm + logits head + greedy argmax ([B] out)

Usage: python scripts/ablate_decode.py [--ctx 336] [--batch 8]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir",
                  os.path.expanduser("~/.cache/kafka_tpu/xla"))
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)

from kafka_tpu.models import get_config, init_params
from kafka_tpu.ops.norms import rms_norm
from kafka_tpu.ops.rope import apply_rope, rope_cos_sin, rope_frequencies

REPEAT = 16


def timed(fn, state, args_, n=4, trials=3):
    """Median-of-trials differenced timing: each trial measures
    (T(3n) - T(n)) / (2n * REPEAT).  The spread between dispatch counts
    must dwarf the tunnel's RTT jitter (~100 ms), hence n*REPEAT >= 64
    device passes per measurement."""

    def run(k):
        out = fn(state, *args_)
        np.asarray(jax.tree.leaves(out)[0])
        t0 = time.monotonic()
        o = out
        for _ in range(k):
            o = fn(o, *args_)
        np.asarray(jax.tree.leaves(o)[0])
        return time.monotonic() - t0

    run(1)
    vals = []
    for _ in range(trials):
        t1 = run(n)
        t2 = run(3 * n)
        vals.append((t2 - t1) / (2 * n * REPEAT) * 1e3)
    return float(np.median(vals))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama-3.2-1b")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--ctx", type=int, default=336)
    ap.add_argument("--page-size", type=int, default=16)
    args = ap.parse_args()

    cfg = get_config(args.model).replace(attention_backend="pallas")
    B, ps, ctx = args.batch, args.page_size, args.ctx
    H, L, D = cfg.hidden_size, cfg.num_layers, cfg.head_dim
    Hq, Hkv = cfg.num_heads, cfg.num_kv_heads
    P = -(-(ctx + 4) // ps)  # pages per seq
    num_pages = B * P + 1
    print(f"# {cfg.name} B={B} ctx={ctx} pages/seq={P}")

    params = init_params(cfg, jax.random.PRNGKey(0))
    lp = params["layers"]
    k_pool = jnp.zeros((L, num_pages * ps, Hkv * D), jnp.bfloat16)
    v_pool = jnp.zeros((L, num_pages * ps, Hkv * D), jnp.bfloat16)
    table = jnp.asarray(
        np.arange(1, num_pages).reshape(B, P).astype(np.int32))
    seq_lens = jnp.full((B,), ctx, jnp.int32)
    h0 = jax.random.normal(jax.random.PRNGKey(1), (B, H)).astype(jnp.bfloat16)

    inv_freq = rope_frequencies(cfg)

    def make_stack(mode: str):
        """(h, layers, k_pool, v_pool, table, seq_lens) -> h after
        REPEAT passes through all L layers at the given ablation level."""

        def layer(h, lay, kc, vc, cos, sin, positions):
            x = rms_norm(h, lay["ln_attn"], cfg.rms_norm_eps)
            q = jnp.einsum("bh,hnd->bnd", x, lay["wq"])
            k = jnp.einsum("bh,hnd->bnd", x, lay["wk"])
            v = jnp.einsum("bh,hnd->bnd", x, lay["wv"])
            if mode in ("rope", "attn", "write"):
                q = apply_rope(q[:, None], cos, sin)[:, 0]
                k = apply_rope(k[:, None], cos, sin)[:, 0]
            if mode == "write":
                write_page = table[jnp.arange(B), seq_lens // ps]
                widx = write_page * ps + seq_lens % ps
                kc = kc.at[widx].set(k.reshape(B, Hkv * D))
                vc = vc.at[widx].set(v.reshape(B, Hkv * D))
            if mode in ("attn", "write"):
                from kafka_tpu.ops.pallas import paged_decode_attention

                o = paged_decode_attention(
                    q, kc, vc, table, seq_lens, page_size=ps)
            else:
                o = q  # stand-in with the same shape
            h = h + jnp.einsum("bnd,ndh->bh", o.astype(x.dtype), lay["wo"])
            x2 = rms_norm(h, lay["ln_mlp"], cfg.rms_norm_eps)
            g = jnp.einsum("bh,hf->bf", x2, lay["wg"])
            u = jnp.einsum("bh,hf->bf", x2, lay["wu"])
            return h + jnp.einsum("bf,fh->bh", jax.nn.silu(g) * u,
                                  lay["wd"]), kc, vc

        @jax.jit
        def fn(h, layers, k_pool, v_pool, table_, seq_lens_):
            cos, sin = rope_cos_sin(seq_lens_[:, None], inv_freq)

            def one_pass(carry, _):
                h, kp, vp = carry

                # thread pools per layer via scan over stacked leaves
                def body(h, xs):
                    lay, kc, vc = xs
                    h, kc, vc = layer(h, lay, kc, vc, cos, sin, seq_lens_)
                    return h, (kc, vc)

                h, (kp, vp) = jax.lax.scan(body, h, (layers, kp, vp))
                return (h, kp, vp), None

            (h, kp, vp), _ = jax.lax.scan(
                one_pass, (h, k_pool, v_pool), None, length=REPEAT)
            return h, kp, vp

        return fn

    state0 = (h0, k_pool, v_pool)

    for mode in ("mm", "rope", "attn", "write"):
        fn = make_stack(mode)
        wrapped = lambda st, layers, t, s, fn=fn: fn(
            st[0], layers, st[1], st[2], t, s)
        ms = timed(wrapped, state0, (lp, table, seq_lens))
        print(f"{mode:5s}: {ms:7.3f} ms/pass")

    # head: final norm + logits + argmax
    embed = params["embed"]

    @jax.jit
    def head_fn(h, fn_w, emb):
        def one(h, _):
            x = rms_norm(h, fn_w, cfg.rms_norm_eps)
            logits = jnp.einsum("bh,vh->bv", x, emb,
                                preferred_element_type=jnp.float32)
            tok = jnp.argmax(logits, axis=-1)
            # fold the argmax back so the scan carries a dependency
            return h + (tok[:, None] % 3).astype(h.dtype) * 1e-6, None

        h, _ = jax.lax.scan(one, h, None, length=REPEAT)
        return h

    ms = timed(head_fn, h0, (params["final_norm"], embed))
    print(f"head : {ms:7.3f} ms/pass")


if __name__ == "__main__":
    main()
