"""A/B the fused-MLP Pallas kernel vs the XLA 3-einsum formulation.

Times a scan over L stacked layers (the decode step's real structure) at
Llama shapes, pipelined dispatches with one terminal block (the tunnel
discipline from scripts/profile_decode.py).

Usage: python scripts/bench_fused_mlp.py [--model llama-3.2-1b] [--batch 8]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

# persistent XLA compile cache (same dir the server/bench use): repeat
# runs skip the 30-70s-per-program compile through the tunnel
jax.config.update("jax_compilation_cache_dir",
                  os.path.expanduser("~/.cache/kafka_tpu/xla"))
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)

from kafka_tpu.models import get_config
from kafka_tpu.models.quant import quantize_array
from kafka_tpu.ops.norms import rms_norm
from kafka_tpu.ops.pallas.fused_mlp import fused_mlp_block, pick_block_f


REPEAT = 16  # on-device repetitions of the full layer stack per dispatch


def timed(fn, state, weights, steps=32):
    """Weights ride as ARGUMENTS: a jitted fn that merely closes over
    GB-scale device arrays embeds them as HLO constants and the compile
    never finishes (observed: >10 min for a 16-layer scan).

    Timing discipline for the tunneled chip (all three bites taken this
    session): block_until_ready is LAZY on axon so only a real fetch
    (np.asarray) syncs; per-dispatch host overhead is ~1 ms so the
    repetition must live ON DEVICE (fn scans the whole stack REPEAT
    times per dispatch, ~40 ms of device work); and the fetch RTT is
    cancelled by differencing two dispatch counts:
        ms/stack = (T(2n) - T(n)) / (n * REPEAT)
    """
    import numpy as np

    def run(n):
        out = fn(state, *weights)
        np.asarray(out)  # warm + sync
        t0 = time.monotonic()
        for _ in range(n):
            out = fn(out, *weights)
        np.asarray(out)  # force the fetch — the only real sync point
        return time.monotonic() - t0

    n = max(1, steps // REPEAT)
    run(1)
    t1 = run(n)
    t2 = run(2 * n)
    return (t2 - t1) / (n * REPEAT) * 1e3


def repeat_stack(scan_fn):
    """Wrap a (h, *weights) -> h layer-stack pass: run it REPEAT times in
    one dispatch (lax.scan over the repetition axis, device-resident)."""

    @jax.jit
    def fn(h, *weights):
        def one(h, _):
            return scan_fn(h, *weights), None

        h, _ = jax.lax.scan(one, h, None, length=REPEAT)
        return h

    return fn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama-3.2-1b")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--steps", type=int, default=64)
    args = ap.parse_args()

    cfg = get_config(args.model)
    H, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    B = args.batch
    print(f"# {cfg.name}: H={H} F={F} L={L} B={B} "
          f"block_f(bf16)={pick_block_f(H, F, 2)} "
          f"block_f(int8)={pick_block_f(H, F, 1)}")
    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    wg = (jax.random.normal(keys[0], (L, H, F)) * H**-0.5).astype(jnp.bfloat16)
    wu = (jax.random.normal(keys[1], (L, H, F)) * H**-0.5).astype(jnp.bfloat16)
    wd = (jax.random.normal(keys[2], (L, F, H)) * F**-0.5).astype(jnp.bfloat16)
    ln = jnp.ones((L, H), jnp.bfloat16)
    h0 = jax.random.normal(keys[3], (B, H), jnp.float32).astype(jnp.bfloat16)

    mlp_gb = 3 * L * H * F * 2 / 1e9

    def xla_scan(h, ln, wg, wu, wd):
        def body(h, lp):
            lnw, g_, u_, d_ = lp
            x = rms_norm(h, lnw, cfg.rms_norm_eps)
            g = jnp.einsum("bh,hf->bf", x, g_)
            u = jnp.einsum("bh,hf->bf", x, u_)
            return h + jnp.einsum("bf,fh->bh", jax.nn.silu(g) * u, d_), None

        h, _ = jax.lax.scan(body, h, (ln, wg, wu, wd))
        return h

    def pallas_scan(h, ln, wg, wu, wd):
        def body(h, lp):
            lnw, g_, u_, d_ = lp
            return fused_mlp_block(
                h, lnw, g_, u_, d_, eps=cfg.rms_norm_eps
            ), None

        h, _ = jax.lax.scan(body, h, (ln, wg, wu, wd))
        return h

    dense_w = (ln, wg, wu, wd)
    ms = timed(repeat_stack(xla_scan), h0, dense_w, args.steps)
    print(f"XLA   3-einsum scan : {ms:7.3f} ms  ({mlp_gb / ms * 1e3:6.1f} GB/s)")
    ms = timed(repeat_stack(pallas_scan), h0, dense_w, args.steps)
    print(f"Pallas fused scan   : {ms:7.3f} ms  ({mlp_gb / ms * 1e3:6.1f} GB/s)")

    # int8
    qg = quantize_array(wg, (1,))
    qu = quantize_array(wu, (1,))
    qd = quantize_array(wd, (1,))
    int8_gb = 3 * L * H * F / 1e9

    def xla_scan_q(h, ln, gq, gs, uq, us, dq, ds):
        def body(h, lp):
            lnw, gq_, gs_, uq_, us_, dq_, ds_ = lp
            x = rms_norm(h, lnw, cfg.rms_norm_eps)
            g = jnp.einsum("bh,hf->bf", x,
                           (gq_.astype(jnp.bfloat16) * gs_).astype(jnp.bfloat16))
            u = jnp.einsum("bh,hf->bf", x,
                           (uq_.astype(jnp.bfloat16) * us_).astype(jnp.bfloat16))
            return h + jnp.einsum(
                "bf,fh->bh", jax.nn.silu(g) * u,
                (dq_.astype(jnp.bfloat16) * ds_).astype(jnp.bfloat16)
            ), None

        h, _ = jax.lax.scan(body, h, (ln, gq, gs, uq, us, dq, ds))
        return h

    def pallas_scan_q(h, ln, gq, gs, uq, us, dq, ds):
        def body(h, lp):
            lnw, gq_, gs_, uq_, us_, dq_, ds_ = lp
            return fused_mlp_block(
                h, lnw, gq_, uq_, dq_, gs_, us_, ds_, eps=cfg.rms_norm_eps
            ), None

        h, _ = jax.lax.scan(body, h, (ln, gq, gs, uq, us, dq, ds))
        return h

    q_w = (ln, qg.q, qg.s, qu.q, qu.s, qd.q, qd.s)
    ms = timed(repeat_stack(xla_scan_q), h0, q_w, args.steps)
    print(f"XLA   int8 scan     : {ms:7.3f} ms  ({int8_gb / ms * 1e3:6.1f} GB/s)")
    ms = timed(repeat_stack(pallas_scan_q), h0, q_w, args.steps)
    print(f"Pallas int8 scan    : {ms:7.3f} ms  ({int8_gb / ms * 1e3:6.1f} GB/s)")


if __name__ == "__main__":
    main()
