"""Flight-recorder viewer: pretty-print the scheduler's dispatch timeline.

Reads either a LIVE ring from a running server::

    python scripts/flightview.py --url http://localhost:8000 --replica 0

or a postmortem dump (written next to the persisted traces on engine
failure / quarantine / failed recovery)::

    python scripts/flightview.py /path/to/postmortem.*.flight.json
    python scripts/flightview.py --latest          # newest dump in the
                                                   # configured dump dir

Output: one line per scheduler iteration — seq, wall time, inter-
iteration gap, dispatch kinds, batch composition, queue/page pressure,
modeled vs measured dispatch time, cause codes — followed by the anomaly
state and (for postmortems) the active-lane table and headline metrics
(including the live-HBM ``memory`` section when present, ISSUE 18).
The record schema and cause-code table are documented in README
"Flight recorder".

``--url ... --compiles`` switches to the compile observatory's ring
(GET /debug/compiles): one line per XLA compilation — label, phase,
cache hit/miss/off, wall seconds — plus storm state and totals.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional


def _fetch_live(url: str, replica: int,
                token: Optional[str] = None) -> Dict[str, Any]:
    return _fetch(url, f"/debug/flight/{replica}", token)


def _fetch(url: str, path: str,
           token: Optional[str] = None) -> Dict[str, Any]:
    from urllib.request import Request, urlopen

    req = Request(
        f"{url.rstrip('/')}{path}",
        headers={"Authorization": f"Bearer {token}"} if token else {},
    )
    with urlopen(req, timeout=10) as r:
        return json.load(r)


def _load_file(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def _fmt_t(t: Optional[float]) -> str:
    if not t:
        return "-"
    return time.strftime("%H:%M:%S", time.localtime(t)) + f".{int(t % 1 * 1e3):03d}"


def _fmt_kinds(kinds: List[str]) -> str:
    short = {"prefill": "P", "decode": "D", "multi": "M",
             "verify": "V", "mixed": "X"}
    return "".join(short.get(k, "?") for k in kinds) or "-"


def _fmt_causes(causes: Dict[str, int]) -> str:
    if not causes:
        return ""
    return " ".join(f"{k}x{n}" if n > 1 else k
                    for k, n in sorted(causes.items()))


def print_records(records: List[Dict[str, Any]], tail: int) -> None:
    if tail > 0:
        records = records[-tail:]
    hdr = (f"{'seq':>7} {'time':>12} {'gap':>8} {'disp':>5} "
           f"{'lanes':>5} {'toks':>5} {'pf.tk':>5} {'spec':>4} "
           f"{'q':>3} {'act':>3} {'park':>4} {'pend':>4} "
           f"{'pg.free':>7} {'model ms':>8} {'meas ms':>8}  causes")
    print(hdr)
    print("-" * len(hdr))
    for r in records:
        print(
            f"{r['seq']:>7} {_fmt_t(r.get('t')):>12} "
            f"{r.get('gap_ms', 0):>7.1f}m {_fmt_kinds(r.get('kinds', [])):>5} "
            f"{r.get('lanes', 0):>5} {r.get('toks', 0):>5} "
            f"{r.get('prefill_toks', 0):>5} {r.get('spec_cands', 0):>4} "
            f"{r.get('queue_depth', 0):>3} {r.get('active', 0):>3} "
            f"{r.get('parked', 0):>4} {r.get('pending', 0):>4} "
            f"{r.get('pages_free', 0):>7} "
            f"{r.get('modeled_ms', 0):>8.3f} {r.get('measured_ms', 0):>8.3f}"
            f"  {_fmt_causes(r.get('causes', {}))}"
        )


def print_anomalies(anomalies: Dict[str, Any]) -> None:
    active = anomalies.get("active") or []
    if isinstance(anomalies, dict) and not active:
        # postmortem shape: {kind: {active, since, detail}}
        active = [
            {"kind": k, **v} for k, v in anomalies.items()
            if isinstance(v, dict) and v.get("active")
        ]
    if active:
        print("\nACTIVE ANOMALIES:")
        for a in active:
            rep = f" replica={a['replica']}" if "replica" in a else ""
            print(f"  !! {a['kind']}{rep} since {_fmt_t(a.get('since'))}: "
                  f"{a.get('detail')}")
    else:
        print("\nno active anomalies")


def print_lanes(lanes: List[Dict[str, Any]]) -> None:
    if not lanes:
        return
    print(f"\nLANES ({len(lanes)}):")
    hdr = (f"  {'request_id':<28} {'state':<10} {'slot':>4} {'age s':>7} "
           f"{'out':>5} {'disp':>5} {'drain':>5} {'pages':>5}  flags")
    print(hdr)
    for ln in lanes:
        flags = []
        if ln.get("grammar"):
            flags.append("grammar")
        if ln.get("host_constrained"):
            flags.append("host-mask")
        if ln.get("spec_ahead"):
            flags.append(f"spec+{ln['spec_ahead']}")
        if ln.get("cached_tokens"):
            flags.append(f"cached:{ln['cached_tokens']}"
                         f"({ln.get('cache_source')})")
        if ln.get("prefetch_staged_bytes"):
            flags.append(f"prefetch:{ln['prefetch_staged_bytes']}B")
        if ln.get("background"):
            flags.append("bg")
        if ln.get("awaiting_tool"):
            # mid-tool-call gap (ISSUE 20): lingering = demote timer
            # still running; demoted = pages already moved down-tier
            flags.append("await-tool" + ("(linger)" if ln.get("lingering")
                                         else ""))
            if ln.get("demoted_pages"):
                flags.append(f"demoted:{ln['demoted_pages']}pg")
        print(
            f"  {ln.get('request_id', '?'):<28} {ln.get('state', '?'):<10} "
            f"{ln.get('slot', -1):>4} {ln.get('age_s') or 0:>7.2f} "
            f"{ln.get('output_tokens', 0):>5} {ln.get('dispatched', 0):>5} "
            f"{ln.get('drained', 0):>5} {ln.get('pages', 0):>5}  "
            f"{' '.join(flags)}"
        )


def print_metrics_headline(m: Dict[str, Any]) -> None:
    if not m:
        return
    print("\nMETRICS AT CAPTURE:")
    req = m.get("requests") or {}
    print(f"  requests: {req}")
    slo = m.get("slo") or {}
    if slo:
        print(f"  slo: attainment={slo.get('slo_attainment')} "
              f"1m={slo.get('slo_attainment_1m')} "
              f"goodput_tok_s={slo.get('goodput_tok_s')}")
    util = m.get("utilization") or {}
    for kind in ("prefill", "decode", "verify"):
        u = util.get(kind) or {}
        if u.get("dispatches"):
            print(f"  {kind}: dispatches={u['dispatches']} "
                  f"mfu={u.get('mfu')} skew={u.get('model_skew')} "
                  f"measured_s={u.get('measured_busy_s')}")
    print_memory(m.get("memory") or {})


def print_memory(mem: Dict[str, Any]) -> None:
    """Live HBM accounting (ISSUE 18) — the `memory` metrics section."""
    if not mem or mem.get("source") == "none":
        return
    mib = 1 / (1024 * 1024)
    print(f"  memory[{mem.get('source')}]: "
          f"in_use={mem.get('hbm_bytes_in_use', 0) * mib:.1f}MiB "
          f"peak={mem.get('hbm_bytes_peak', 0) * mib:.1f}MiB "
          f"limit={mem.get('hbm_bytes_limit', 0) * mib:.1f}MiB "
          f"headroom={mem.get('hbm_headroom_bytes', 0) * mib:.1f}MiB "
          f"plan_skew={mem.get('hbm_plan_skew')} "
          f"pressure={mem.get('hbm_pressure', 0)}")
    comp = mem.get("hbm_component_bytes") or {}
    if comp:
        parts = " ".join(f"{k}={v * mib:.1f}MiB"
                         for k, v in comp.items())
        print(f"    components: {parts}")


def print_compiles(payload: Dict[str, Any], tail: int) -> None:
    """The compile observatory ring (GET /debug/compiles, ISSUE 18)."""
    totals = payload.get("totals") or {}
    storm = payload.get("storm") or {}
    print(f"ring: {len(payload.get('records', []))} records "
          f"(size {payload.get('ring_size')}, "
          f"{payload.get('next_seq')} total)  phase: "
          f"{payload.get('phase')}  cache_dir: "
          f"{payload.get('cache_dir') or '-'}")
    print(f"totals: {totals.get('compiles', 0)} compiles, "
          f"{totals.get('seconds', 0.0):.2f}s  "
          f"by_cache={totals.get('by_cache')}  "
          f"by_phase={totals.get('by_phase')}")
    if storm.get("active"):
        print(f"!! COMPILE STORM ACTIVE (threshold {storm.get('n')} in "
              f"{storm.get('window_s')}s; {storm.get('storms_total')} "
              f"storm(s) total)")
    records = payload.get("records") or []
    if tail > 0:
        records = records[-tail:]
    hdr = (f"{'seq':>6} {'time':>12} {'phase':>13} {'cache':>5} "
           f"{'secs':>8}  label")
    print(hdr)
    print("-" * len(hdr))
    for r in records:
        print(f"{r.get('seq', 0):>6} {_fmt_t(r.get('t')):>12} "
              f"{r.get('phase', '?'):>13} {r.get('cache', '?'):>5} "
              f"{r.get('seconds', 0.0):>8.3f}  {r.get('label', '?')}")


def main() -> None:
    ap = argparse.ArgumentParser(
        description="Pretty-print a flight-recorder ring or postmortem")
    ap.add_argument("path", nargs="?",
                    help="postmortem JSON file (or - for stdin)")
    ap.add_argument("--url", help="fetch the live ring from a server")
    ap.add_argument("--replica", type=int, default=0,
                    help="replica index for --url (default 0)")
    ap.add_argument("--token", default=os.environ.get("KAFKA_TPU_API_TOKEN"),
                    help="bearer token for --url against a server with an "
                         "API token configured (default: "
                         "$KAFKA_TPU_API_TOKEN)")
    ap.add_argument("--latest", action="store_true",
                    help="open the newest postmortem in the dump dir")
    ap.add_argument("--compiles", action="store_true",
                    help="with --url: show the compile observatory ring "
                         "(GET /debug/compiles) instead of the flight "
                         "ring")
    ap.add_argument("-n", "--tail", type=int, default=64,
                    help="show only the last N records (0 = all)")
    ap.add_argument("--json", action="store_true",
                    help="dump the raw payload instead of the table")
    args = ap.parse_args()

    if args.url and args.compiles:
        payload = _fetch(args.url, "/debug/compiles", args.token)
        if args.json:
            json.dump(payload, sys.stdout, indent=2)
            print()
            return
        print("== COMPILE OBSERVATORY ==")
        print_compiles(payload, args.tail)
        return
    if args.compiles:
        ap.error("--compiles needs --url (it reads the live ring)")
        return
    if args.url:
        payload = _fetch_live(args.url, args.replica, args.token)
        title = f"LIVE ring, replica {payload.get('replica')}"
    elif args.latest:
        import sys as _sys

        _sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        from kafka_tpu.runtime.flight_recorder import list_postmortems

        paths = list_postmortems()
        if not paths:
            print("no postmortem dumps found (set KAFKA_TPU_FLIGHT_DIR "
                  "or KAFKA_TPU_TRACE_PERSIST_DIR)", file=sys.stderr)
            raise SystemExit(1)
        payload = _load_file(paths[0])
        title = f"POSTMORTEM {paths[0]}"
    elif args.path:
        if args.path == "-":
            payload = json.load(sys.stdin)
            title = "POSTMORTEM <stdin>"
        else:
            payload = _load_file(args.path)
            title = f"POSTMORTEM {args.path}"
    else:
        ap.error("give a postmortem file, --latest, or --url")
        return

    if args.json:
        json.dump(payload, sys.stdout, indent=2)
        print()
        return

    print(f"== {title} ==")
    if payload.get("reason"):
        print(f"reason: {payload['reason']}  replica: "
              f"{payload.get('replica')}  pid: {payload.get('pid')}  "
              f"at: {_fmt_t(payload.get('t_wall'))}")
    print(f"ring: {len(payload.get('records', []))} records "
          f"(size {payload.get('ring_size')}, "
          f"{payload.get('next_seq')} total)")
    print_records(payload.get("records", []), args.tail)
    print_anomalies(payload.get("anomalies") or {})
    print_lanes(payload.get("lanes") or [])
    print_metrics_headline(payload.get("metrics") or {})


if __name__ == "__main__":
    main()
