"""Benchmark harness: one JSON line for the driver.

Measures, on whatever accelerator jax exposes (one real TPU chip under the
driver; CPU works for smoke runs):

  * prefill p50 TTFT (128-token prompt -> first sampled token) on the
    flagship single-chip model (Llama-3.2-1B architecture, bf16, randomly
    initialised — throughput is weight-value independent),
  * steady-state continuous-batching decode throughput (batch 8).

The reference publishes no numbers (BASELINE.md: its LLM compute lived
behind the Portkey HTTPS proxy), so `vs_baseline` is computed against the
only numeric target on record — BASELINE.json's north star of 200 ms p50
TTFT — as `200 / measured_ttft_ms` (>1.0 = beating the target).  Decode
throughput and related stats ride along in "extras".

Usage: python bench.py [--model llama-3.2-1b] [--quick]
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama-3.2-1b")
    ap.add_argument("--quick", action="store_true",
                    help="tiny model + short runs (CI smoke)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--gen-len", type=int, default=256)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from kafka_tpu.models import get_config, init_params
    from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine

    if args.quick:
        cfg = get_config("tiny-gqa")
        args.prompt_len, args.gen_len = 32, 32
    else:
        cfg = get_config(args.model)
    platform = jax.devices()[0].platform
    print(f"# bench: {cfg.name} on {platform} "
          f"({len(jax.devices())} device(s))", file=sys.stderr)

    t0 = time.monotonic()
    params = init_params(cfg, jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    print(f"# params init: {time.monotonic() - t0:.1f}s", file=sys.stderr)

    ecfg = EngineConfig(
        max_batch=args.batch,
        page_size=16,
        max_pages_per_seq=max(
            2, -(-(args.prompt_len + args.gen_len + 16) // 16)
        ),
    )
    # pool sized for active batch AND the prefix caches of the concurrent-
    # thread phase — an undersized pool measures reclaim churn, not the
    # engine (~300 MB of KV for the 1B default: deployment-realistic)
    ecfg.num_pages = 3 * args.batch * ecfg.max_pages_per_seq + 1
    engine = InferenceEngine(cfg, params, ecfg)

    rng = __import__("random").Random(0)
    def prompt():
        return [rng.randrange(4, cfg.vocab_size - 4)
                for _ in range(args.prompt_len)]

    # ---- warmup: compile prefill bucket + decode step --------------------
    t0 = time.monotonic()
    engine.generate(prompt(), max_new_tokens=4)
    print(f"# warmup/compile: {time.monotonic() - t0:.1f}s", file=sys.stderr)
    # warmup included XLA compiles; reset so percentiles reflect serving
    from kafka_tpu.runtime.metrics import EngineMetrics

    engine.metrics = EngineMetrics()

    # ---- TTFT: prompt submit -> first token, solo requests ---------------
    ttfts = []
    for _ in range(5 if args.quick else 10):
        req = engine.generate(prompt(), max_new_tokens=1)
        ttfts.append((req.first_token_time - req.submit_time) * 1e3)
    ttft_p50 = statistics.median(ttfts)

    # ---- cache-hit TTFT: same thread, prompt grown by one turn -----------
    # (BASELINE config 2: the second turn shares the first turn's pages and
    # prefills only the suffix)
    base = prompt()
    turn1 = GenRequest(request_id="warm-t1", prompt_ids=base,
                       max_new_tokens=8, prefix_key="bench-thread")
    engine.submit(turn1)
    engine.run_to_completion()
    hit_ttfts = []
    grown = base + turn1.output_ids
    for i in range(3 if args.quick else 5):
        r = GenRequest(request_id=f"warm-t{i + 2}",
                       prompt_ids=grown + [7 + i], max_new_tokens=1,
                       prefix_key="bench-thread")
        engine.submit(r)
        engine.run_to_completion()
        hit_ttfts.append((r.first_token_time - r.submit_time) * 1e3)
        grown = grown + [7 + i] + r.output_ids
    cache_hit_ttft_p50 = statistics.median(hit_ttfts)

    # ---- decode throughput: full batch, steady state ---------------------
    reqs = []
    for i in range(args.batch):
        r = GenRequest(request_id=f"bench-{i}", prompt_ids=prompt(),
                       max_new_tokens=args.gen_len)
        engine.submit(r)
        reqs.append(r)
    while engine.num_active < args.batch:  # admit everyone (prefill)
        engine.step()
    # Flush in-flight fetches and discard their buffered events so the
    # clock covers only tokens whose dispatch AND drain fall inside the
    # measured window (the async pipeline would otherwise credit pre-clock
    # prefill/decode work to the measurement).
    engine._drain(block=True)
    engine._out_events.clear()
    t0 = time.monotonic()
    tokens = 0
    while engine.has_work:
        for ev in engine.step():
            if ev.token_id is not None:
                tokens += 1
    wall = time.monotonic() - t0
    decode_tps = tokens / wall

    # ---- concurrent-thread req/s (BASELINE metric 3): 4x oversubscribed
    # queue of short thread turns through the continuous batcher ----------
    n_threads = 8 if args.quick else 32
    for i in range(n_threads):
        engine.submit(GenRequest(
            request_id=f"ct-{i}",
            prompt_ids=prompt()[: args.prompt_len // 2],
            max_new_tokens=32, prefix_key=f"ct-thread-{i}",
        ))
    t0 = time.monotonic()
    done_ct = 0
    while engine.has_work:
        for ev in engine.step():
            if ev.finished:
                done_ct += 1
    ct_wall = time.monotonic() - t0
    concurrent_req_s = done_ct / ct_wall

    # the same counters GET /metrics exports (runtime/metrics.py) — bench
    # and the server report one source of truth
    snap = engine.metrics.snapshot(engine)

    # Headline = BASELINE.json's first metric (tokens/sec/chip). The
    # reference publishes no numbers, so vs_baseline is the improvement over
    # this framework's own round-1 measurement (88.6 tok/s/chip,
    # BENCH_r01.json) — the only prior number on record for this metric.
    R01_DECODE_TPS = 88.6
    result = {
        "metric": f"decode_tokens_per_sec_per_chip_{cfg.name}_batch{args.batch}",
        "value": round(decode_tps, 1),
        "unit": "tok/s",
        "vs_baseline": round(decode_tps / R01_DECODE_TPS, 2),
        "extras": {
            "p50_ttft_ms": round(ttft_p50, 2),
            "p50_cache_hit_ttft_ms": round(cache_hit_ttft_p50, 2),
            "ttft_vs_200ms_north_star": round(200.0 / ttft_p50, 3),
            "metrics": {  # same counters the server's GET /metrics exports
                "ttft_ms": snap["ttft_ms"],
                "tpot_ms": snap["tpot_ms"],
                "batch_occupancy": snap["decode"]["batch_occupancy"],
                "generated_tokens": snap["tokens"]["generated"],
                "prefix_cache": snap.get("prefix_cache"),
            },
            "concurrent_thread_req_per_s": round(concurrent_req_s, 2),
            "concurrent_threads": n_threads,
            "decode_batch": args.batch,
            "gen_len": args.gen_len,
            "ttft_all_ms": [round(t, 2) for t in ttfts],
            "platform": platform,
            "model": cfg.name,
            "note": ("vs_baseline = decode tok/s/chip over round-1's 88.6 "
                     "(reference publishes no numbers, BASELINE.md). TTFT is "
                     "host-observed first-token latency incl. device->host "
                     "fetch."),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
