"""Benchmark harness: one JSON line for the driver.

Measures, on whatever accelerator jax exposes (one real TPU chip under the
driver; CPU works for smoke runs):

  * prefill p50 TTFT (128-token prompt -> first sampled token) on the
    flagship single-chip model (Llama-3.2-1B architecture, bf16, randomly
    initialised — throughput is weight-value independent),
  * the prefix cache's latency win at EQUAL prompt length: cold prefill of
    an L-token prompt vs the same-length prompt whose first L-8 tokens are
    cached pages (only the 8-token suffix prefills),
  * steady-state continuous-batching decode throughput at batch 8 (headline)
    plus batch 16/32 scaling points, each with an HBM-bandwidth-utilization
    estimate (weights + KV traffic per step / step time vs the chip's
    nominal bandwidth) — how far from the roofline decode runs,
  * concurrent-thread req/s (BASELINE metric 3) on a 4x oversubscribed
    queue of short thread turns.

The reference publishes no numbers (BASELINE.md: its LLM compute lived
behind the Portkey HTTPS proxy), so `vs_baseline` is computed against this
framework's own round-1 measurement — the only prior number on record for
the headline metric.

Usage: python bench.py [--model llama-3.2-1b] [--quick]
"""

from __future__ import annotations

import argparse
import json
import random
import statistics
import sys
import time


def log(msg: str) -> None:
    print(f"# {msg}", file=sys.stderr)


def param_bytes(params) -> int:
    import jax

    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))


def make_prompt(rng: random.Random, n: int, vocab: int):
    return [rng.randrange(4, vocab - 4) for _ in range(n)]


def decode_phase(engine, cfg, batch: int, prompt_len: int, gen_len: int,
                 rng: random.Random):
    """Fill the batch, flush the pipeline, measure steady-state decode."""
    from kafka_tpu.runtime import GenRequest

    for i in range(batch):
        engine.submit(GenRequest(
            request_id=f"bench-b{batch}-{i}",
            prompt_ids=make_prompt(rng, prompt_len, cfg.vocab_size),
            max_new_tokens=gen_len))
    # admit everyone AND finish their (interleaved) prefills: num_active
    # counts PREFILLING lanes too, so gate on decode-ready state
    while sum(1 for s in engine.slots
              if s is not None and s.state == "active") < batch:
        engine.step()
    # Flush in-flight fetches and discard their buffered events so the
    # clock covers only tokens whose dispatch AND drain fall inside the
    # measured window (the async pipeline would otherwise credit pre-clock
    # prefill/decode work to the measurement).
    engine._drain(block=True)
    engine._out_events.clear()
    steps0 = engine.metrics.decode_steps
    t0 = time.monotonic()
    tokens = 0
    while engine.has_work:
        for ev in engine.step():
            if ev.token_id is not None:
                tokens += 1
    wall = time.monotonic() - t0
    steps = engine.metrics.decode_steps - steps0
    return tokens / wall, steps / wall


def hbm_traffic_per_step(engine, pbytes: int, batch: int,
                         ctx_len: int) -> int:
    """Estimated HBM bytes one decode step moves: every weight byte read
    once (batch small enough that weights, not activations, dominate) plus
    the KV context read + one-token write per active sequence."""
    cfg = engine.cfg
    kv_row = 2 * cfg.num_layers * cfg.num_kv_heads * cfg.head_dim  # k+v
    kv_dtype_bytes = engine.k_pool.dtype.itemsize  # follows model dtype
    kv_read = batch * ctx_len * kv_row * kv_dtype_bytes
    kv_write = batch * kv_row * kv_dtype_bytes
    return pbytes + kv_read + kv_write


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama-3.2-1b")
    ap.add_argument("--quick", action="store_true",
                    help="tiny model + short runs (CI smoke)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--gen-len", type=int, default=256)
    ap.add_argument("--cache-prompt-len", type=int, default=2048,
                    help="prompt length for the equal-length cache proof")
    ap.add_argument("--batch-sweep", type=str, default="16,32",
                    help="extra decode batch points (comma list; '' = none)")
    args = ap.parse_args()

    import jax

    from kafka_tpu.models import get_config, init_params
    from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine
    from kafka_tpu.runtime.metrics import EngineMetrics

    if args.quick:
        cfg = get_config("tiny-gqa")
        args.prompt_len, args.gen_len = 32, 32
        args.cache_prompt_len = 64
        args.batch_sweep = ""
    else:
        cfg = get_config(args.model)
    platform = jax.devices()[0].platform
    device_kind = getattr(jax.devices()[0], "device_kind", "unknown")
    log(f"bench: {cfg.name} on {platform}/{device_kind} "
        f"({len(jax.devices())} device(s))")

    t0 = time.monotonic()
    params = init_params(cfg, jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    pbytes = param_bytes(params)
    log(f"params init: {time.monotonic() - t0:.1f}s "
        f"({pbytes / 1e9:.2f} GB)")

    ecfg = EngineConfig(
        max_batch=args.batch,
        page_size=16,
        max_pages_per_seq=max(
            2, -(-(args.prompt_len + args.gen_len + 16) // 16)
        ),
    )
    # pool sized for active batch AND the prefix caches of the concurrent-
    # thread phase — an undersized pool measures reclaim churn, not the
    # engine (~300 MB of KV for the 1B default: deployment-realistic)
    ecfg.num_pages = 3 * args.batch * ecfg.max_pages_per_seq + 1
    engine = InferenceEngine(cfg, params, ecfg)

    rng = random.Random(0)

    def prompt(n=None):
        return make_prompt(rng, n or args.prompt_len, cfg.vocab_size)

    # ---- warmup: compile prefill buckets + decode programs ---------------
    # every prompt length the bench uses gets its bucket compiled here —
    # a bucket compiling inside a measured phase once cost the concurrent-
    # thread metric a silent 15s (r02/r03 measured ~2 req/s; real ~25)
    t0 = time.monotonic()
    engine.generate(prompt(), max_new_tokens=4)
    engine.generate(prompt(args.prompt_len // 2), max_new_tokens=2)
    if args.batch >= 2:
        # concurrent same-bucket admissions take the BATCHED prefill
        # program; compile it for the concurrent-thread phase's bucket
        for i in range(2):
            engine.submit(GenRequest(
                request_id=f"warm-bp-{i}",
                prompt_ids=prompt(args.prompt_len // 2), max_new_tokens=2))
        engine.run_to_completion()
    if args.batch >= 3 and ecfg.multi_step > 1:
        # the fused multi-step decode program compiles on its first busy
        # batch — trigger that here, not inside the measured decode phase
        for i in range(min(4, args.batch)):
            engine.submit(GenRequest(
                request_id=f"warm-ms-{i}", prompt_ids=prompt(),
                max_new_tokens=ecfg.multi_step + 4))
        engine.run_to_completion()
    log(f"warmup/compile: {time.monotonic() - t0:.1f}s")
    # warmup included XLA compiles; reset so percentiles reflect serving
    engine.metrics = EngineMetrics()

    # ---- TTFT: prompt submit -> first token, solo requests ---------------
    ttfts = []
    for _ in range(5 if args.quick else 10):
        req = engine.generate(prompt(), max_new_tokens=1)
        ttfts.append((req.first_token_time - req.submit_time) * 1e3)
    ttft_p50 = statistics.median(ttfts)
    log(f"p50 TTFT {ttft_p50:.1f} ms")

    # ---- prefix cache proof: EQUAL-length cold vs hit TTFT ---------------
    # (BASELINE config 2.)  Both measurements prefill a prompt of exactly
    # cache_prompt_len tokens; the hit turn shares all but an 8-token
    # suffix through thread-keyed cached pages.  A dedicated engine keeps
    # the long-window pool and compile footprint out of the other phases.
    L = args.cache_prompt_len
    suffix = 8
    cache_ecfg = EngineConfig(
        max_batch=2, page_size=16,
        max_pages_per_seq=max(2, -(-(L + 32) // 16)),
    )
    cache_ecfg.num_pages = 6 * cache_ecfg.max_pages_per_seq + 1
    cache_engine = InferenceEngine(cfg, params, cache_ecfg)
    cache_engine.generate(prompt(L), max_new_tokens=1)  # compile buckets
    base = prompt(L - suffix)
    seed_req = GenRequest(request_id="warm-seed", prompt_ids=base,
                          max_new_tokens=1, prefix_key="bench-thread")
    cache_engine.submit(seed_req)
    cache_engine.run_to_completion()
    # a hit prefills only the suffix -> the smallest bucket; compile it
    # OUTSIDE the measured loop (compile-in-window was exactly the r02/r03
    # concurrent-thread pollution)
    warm_hit = GenRequest(request_id="warm-hit",
                          prompt_ids=base + prompt(suffix),
                          max_new_tokens=1, prefix_key="bench-thread")
    cache_engine.submit(warm_hit)
    cache_engine.run_to_completion()
    cold_ttfts, hit_ttfts = [], []
    reused0 = cache_engine.prefix_cache.tokens_reused
    n_pairs = 3 if args.quick else 5
    for i in range(n_pairs):
        cold = GenRequest(request_id=f"cold-{i}", prompt_ids=prompt(L),
                          max_new_tokens=1)
        cache_engine.submit(cold)
        cache_engine.run_to_completion()
        cold_ttfts.append((cold.first_token_time - cold.submit_time) * 1e3)
        hit = GenRequest(request_id=f"hit-{i}",
                         prompt_ids=base + prompt(suffix),
                         max_new_tokens=1, prefix_key="bench-thread")
        cache_engine.submit(hit)
        cache_engine.run_to_completion()
        hit_ttfts.append((hit.first_token_time - hit.submit_time) * 1e3)
    cold_p50 = statistics.median(cold_ttfts)
    hit_p50 = statistics.median(hit_ttfts)
    tokens_reused = cache_engine.prefix_cache.tokens_reused - reused0
    suffix_prefilled = L - tokens_reused // n_pairs if n_pairs else 0
    log(f"cache proof @ {L} tokens: cold {cold_p50:.1f} ms, "
        f"hit {hit_p50:.1f} ms (prefilled ~{suffix_prefilled} of {L})")

    # ---- decode throughput: full batch, steady state ---------------------
    decode_tps, steps_per_s = decode_phase(
        engine, cfg, args.batch, args.prompt_len, args.gen_len, rng
    )
    ctx = args.prompt_len + args.gen_len // 2  # mean context during decode
    step_bytes = hbm_traffic_per_step(engine, pbytes, args.batch, ctx)
    hbm_gb_s = step_bytes * steps_per_s / 1e9
    # nominal HBM bandwidth by chip family; fall back to v5e-class
    HBM_BW = {"TPU v4": 1228.0, "TPU v5e": 819.0, "TPU v5 lite": 819.0,
              "TPU v5p": 2765.0, "TPU v6e": 1640.0}
    bw_nominal = next(
        (v for k, v in HBM_BW.items() if k.lower() in str(device_kind).lower()),
        819.0,
    )
    log(f"decode b{args.batch}: {decode_tps:.1f} tok/s, "
        f"{steps_per_s:.1f} steps/s, ~{hbm_gb_s:.0f} GB/s "
        f"({100 * hbm_gb_s / bw_nominal:.0f}% of {bw_nominal:.0f})")

    # ---- batch scaling points (fresh engine per width: the decode step is
    # compiled at its static batch width, so reusing a 32-wide engine for a
    # batch of 8 would measure the wrong program) ------------------------
    sweep = {}
    for b in [int(x) for x in args.batch_sweep.split(",") if x]:
        secfg = EngineConfig(
            max_batch=b, page_size=16,
            max_pages_per_seq=max(2, -(-(args.prompt_len + 128 + 16) // 16)),
        )
        secfg.num_pages = b * secfg.max_pages_per_seq + 1
        seng = InferenceEngine(cfg, params, secfg)
        t0 = time.monotonic()
        seng.generate(prompt(), max_new_tokens=2)
        for i in range(min(4, b)):  # compile the fused multi-step program
            seng.submit(GenRequest(request_id=f"warm-b{b}-{i}",
                                   prompt_ids=prompt(),
                                   max_new_tokens=secfg.multi_step + 4))
        seng.run_to_completion()
        log(f"batch {b} compile: {time.monotonic() - t0:.1f}s")
        tps, sps = decode_phase(seng, cfg, b, args.prompt_len, 128, rng)
        sb = hbm_traffic_per_step(seng, pbytes, b, args.prompt_len + 64)
        sweep[str(b)] = {
            "decode_tok_s": round(tps, 1),
            "steps_per_s": round(sps, 1),
            "hbm_gb_s_est": round(sb * sps / 1e9, 1),
            "hbm_util_est": round(sb * sps / 1e9 / bw_nominal, 3),
        }
        log(f"decode b{b}: {tps:.1f} tok/s "
            f"({100 * sb * sps / 1e9 / bw_nominal:.0f}% HBM)")
        del seng

    # ---- concurrent-thread req/s (BASELINE metric 3): 4x oversubscribed
    # queue of short thread turns through the continuous batcher ----------
    n_threads = 8 if args.quick else 32
    for i in range(n_threads):
        engine.submit(GenRequest(
            request_id=f"ct-{i}",
            prompt_ids=prompt()[: args.prompt_len // 2],
            max_new_tokens=32, prefix_key=f"ct-thread-{i}",
        ))
    t0 = time.monotonic()
    done_ct = 0
    while engine.has_work:
        for ev in engine.step():
            if ev.finished:
                done_ct += 1
    ct_wall = time.monotonic() - t0
    concurrent_req_s = done_ct / ct_wall

    # the same counters GET /metrics exports (runtime/metrics.py) — bench
    # and the server report one source of truth
    snap = engine.metrics.snapshot(engine)

    # Headline = BASELINE.json's first metric (tokens/sec/chip). The
    # reference publishes no numbers, so vs_baseline is the improvement over
    # this framework's own round-1 measurement (88.6 tok/s/chip,
    # BENCH_r01.json) — the only prior number on record for this metric.
    R01_DECODE_TPS = 88.6
    R02_DECODE_TPS = 1149.6
    result = {
        "metric": f"decode_tokens_per_sec_per_chip_{cfg.name}_batch{args.batch}",
        "value": round(decode_tps, 1),
        "unit": "tok/s",
        "vs_baseline": round(decode_tps / R01_DECODE_TPS, 2),
        "extras": {
            "p50_ttft_ms": round(ttft_p50, 2),
            "ttft_vs_200ms_north_star": round(200.0 / ttft_p50, 3),
            "prefix_cache_proof": {
                "prompt_len": L,
                "cold_p50_ttft_ms": round(cold_p50, 2),
                "hit_p50_ttft_ms": round(hit_p50, 2),
                "speedup": round(cold_p50 / hit_p50, 2) if hit_p50 else None,
                "suffix_tokens_prefilled_on_hit": suffix_prefilled,
                "note": "equal-length prompts; hit shares all but the "
                        "suffix through thread-keyed cached KV pages",
            },
            "hbm": {
                "bytes_per_step_est": step_bytes,
                "achieved_gb_s_est": round(hbm_gb_s, 1),
                "bw_nominal_gb_s": bw_nominal,
                "hbm_util_est": round(hbm_gb_s / bw_nominal, 3),
                "device_kind": str(device_kind),
                "note": "weights read once per step + KV read/write; "
                        "nominal BW by chip family table",
            },
            "batch_sweep": sweep,
            "metrics": {  # same counters the server's GET /metrics exports
                "ttft_ms": snap["ttft_ms"],
                "tpot_ms": snap["tpot_ms"],
                "emission": snap["emission"],
                "batch_occupancy": snap["decode"]["batch_occupancy"],
                "generated_tokens": snap["tokens"]["generated"],
                "prefix_cache": snap.get("prefix_cache"),
                "rtt_est_ms": snap["engine"]["rtt_est_ms"],
            },
            "concurrent_thread_req_per_s": round(concurrent_req_s, 2),
            "concurrent_threads": n_threads,
            "concurrent_note": (
                f"{n_threads} short thread turns, oversubscribed over "
                f"batch {args.batch} on "
                "ONE chip; BASELINE config 3's 256-thread target assumes "
                "v5e-8 (8 chips x dp) — per-chip this is the comparable "
                "shape. Varies ~10% with tunnel RTT jitter."
            ),
            "decode_batch": args.batch,
            "gen_len": args.gen_len,
            "ttft_all_ms": [round(t, 2) for t in ttfts],
            "platform": platform,
            "model": cfg.name,
            "vs_r02": round(decode_tps / R02_DECODE_TPS, 2),
            "note": ("vs_baseline = decode tok/s/chip over round-1's 88.6 "
                     "(reference publishes no numbers, BASELINE.md); vs_r02 "
                     "= over round-2's 1149.6. TTFT is host-observed "
                     "first-token latency incl. device->host fetch."),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
