"""Benchmark harness: one JSON line for the driver.

Measures, on whatever accelerator jax exposes (one real TPU chip under the
driver; CPU works for smoke runs):

  * prefill p50 TTFT (128-token prompt -> first sampled token) on the
    flagship single-chip model (Llama-3.2-1B architecture, bf16, randomly
    initialised — throughput is weight-value independent),
  * the prefix cache's latency win at EQUAL prompt length: cold prefill of
    an L-token prompt vs the same-length prompt whose first L-8 tokens are
    cached pages (only the 8-token suffix prefills),
  * steady-state continuous-batching decode throughput at batch 8 (headline)
    plus batch 16/32 scaling points, each with an HBM-bandwidth-utilization
    estimate (weights + KV traffic per step / step time vs the chip's
    nominal bandwidth) — how far from the roofline decode runs,
  * concurrent-thread req/s (BASELINE metric 3) on a 4x oversubscribed
    queue of short thread turns.

The reference publishes no numbers (BASELINE.md: its LLM compute lived
behind the Portkey HTTPS proxy), so `vs_baseline` is computed against this
framework's own round-1 measurement — the only prior number on record for
the headline metric.

Usage: python bench.py [--model llama-3.2-1b] [--quick]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import random
import statistics
import sys
import time


def log(msg: str) -> None:
    print(f"# {msg}", file=sys.stderr)


def param_bytes(params) -> int:
    from kafka_tpu.models.quant import param_bytes as _pb

    return _pb(params)  # one accounting for dense AND QTensor trees


def make_prompt(rng: random.Random, n: int, vocab: int):
    return [rng.randrange(4, vocab - 4) for _ in range(n)]


def decode_phase(engine, cfg, batch: int, prompt_len: int, gen_len: int,
                 rng: random.Random):
    """Fill the batch, flush the pipeline, measure steady-state decode."""
    from kafka_tpu.runtime import GenRequest

    for i in range(batch):
        engine.submit(GenRequest(
            request_id=f"bench-b{batch}-{i}",
            prompt_ids=make_prompt(rng, prompt_len, cfg.vocab_size),
            max_new_tokens=gen_len))
    # admit everyone AND finish their (interleaved) prefills: num_active
    # counts PREFILLING lanes too, so gate on decode-ready state
    while sum(1 for s in engine.slots
              if s is not None and s.state == "active") < batch:
        engine.step()
    # Flush in-flight fetches and discard their buffered events so the
    # clock covers only tokens whose dispatch AND drain fall inside the
    # measured window (the async pipeline would otherwise credit pre-clock
    # prefill/decode work to the measurement).
    engine._drain(block=True)
    engine._out_events.clear()
    steps0 = engine.metrics.decode_steps
    t0 = time.monotonic()
    tokens = 0
    while engine.has_work:
        for ev in engine.step():
            if ev.token_id is not None:
                tokens += 1
    wall = time.monotonic() - t0
    steps = engine.metrics.decode_steps - steps0
    return tokens / wall, steps / wall


def hbm_traffic_per_step(engine, pbytes: int, batch: int,
                         ctx_len: int) -> int:
    """Estimated HBM bytes one decode step moves: every weight byte read
    once (batch small enough that weights, not activations, dominate) plus
    the KV context read + one-token write per active sequence."""
    cfg = engine.cfg
    kv_row = 2 * cfg.num_layers * cfg.num_kv_heads * cfg.head_dim  # k+v
    kv_dtype_bytes = engine.k_pool.dtype.itemsize  # follows model dtype
    kv_read = batch * ctx_len * kv_row * kv_dtype_bytes
    kv_write = batch * kv_row * kv_dtype_bytes
    return pbytes + kv_read + kv_write


def percentiles_ms(samples, pts=(50, 90, 99)):
    """Client-side nearest-rank percentiles over raw latency samples.
    (Engine-side distributions are streaming histograms since ISSUE 10;
    these client arrays are the cross-check against them.)"""
    from kafka_tpu.runtime.metrics import _percentiles

    s = [x * 1e3 for x in samples if x is not None]
    if not s:
        return {f"p{p}": None for p in pts}
    return {k: round(v, 1) for k, v in _percentiles(s, pts).items()}


def phase_slo(engine) -> dict:
    """A phase's SLO attainment + goodput, read back from the SAME
    snapshot GET /metrics serves (ISSUE 10) — never recomputed from
    client-side timing, so the BENCH json and a scraped dashboard can
    only agree."""
    snap = engine.metrics.snapshot(engine)
    s = snap["slo"]
    return {
        "slo_attainment": s["slo_attainment"],
        "goodput_tok_s": s["goodput_tok_s"],
        "goodput_frac": s["goodput_frac"],
        "slo_ttft_target_ms": s["slo_ttft_target_ms"],
    }


class SloProbe:
    """Delta-probe for phases sharing a long-lived engine: captures the
    SLO counters at construction, reports the phase-local attainment and
    goodput rate from the /metrics counter deltas."""

    def __init__(self, engine):
        self._engine = engine
        m = engine.metrics
        self._met = m.slo_met_requests
        self._missed = m.slo_missed_requests
        self._good = m.goodput_tokens
        self._t0 = time.monotonic()

    def report(self) -> dict:
        m = self._engine.metrics
        met = m.slo_met_requests - self._met
        missed = m.slo_missed_requests - self._missed
        good = m.goodput_tokens - self._good
        wall = time.monotonic() - self._t0
        return {
            "slo_attainment": round(met / (met + missed), 4)
            if (met + missed) else 1.0,
            "goodput_tok_s": round(good / wall, 2) if wall > 0 else 0.0,
        }


def telemetry_overhead_phase(engine, cfg, args, rng) -> dict:
    """Decode tok/s with the telemetry plane ON vs OFF (ISSUE 10
    acceptance: <=1% regression).  KAFKA_TPU_TELEMETRY=0 builds an
    EngineMetrics whose histogram/SLO/utilization recording is disabled
    (plain counters keep working), so the SAME compiled engine runs the
    same workload in both modes — interleaved twice, best-of compared, to
    keep thermal/link noise out of a sub-1% comparison."""
    import os as _os

    from kafka_tpu.runtime.metrics import EngineMetrics

    saved = _os.environ.get("KAFKA_TPU_TELEMETRY")
    gen = 48 if args.quick else 192
    batch = min(args.batch, 8)
    tps = {"on": [], "off": []}
    try:
        # best-of-3 per mode: single CPU runs of a tiny model wobble ±5%
        # (scheduler/turbo noise), far above the plane's real cost — the
        # max converges on each mode's capability ceiling
        for _round in range(3):
            for mode in ("off", "on"):
                _os.environ["KAFKA_TPU_TELEMETRY"] = (
                    "1" if mode == "on" else "0"
                )
                engine.metrics = EngineMetrics()
                t, _ = decode_phase(engine, cfg, batch,
                                    args.prompt_len // 2, gen, rng)
                tps[mode].append(t)
    finally:
        if saved is None:
            _os.environ.pop("KAFKA_TPU_TELEMETRY", None)
        else:
            _os.environ["KAFKA_TPU_TELEMETRY"] = saved
        engine.metrics = EngineMetrics()
    on, off = max(tps["on"]), max(tps["off"])
    return {
        "tok_s_on": round(on, 1),
        "tok_s_off": round(off, 1),
        "regression_frac": round(max(0.0, 1 - on / off), 4) if off else 0.0,
        "note": ("same engine/programs, interleaved runs, best-of-3 per "
                 "mode; regression_frac is the telemetry plane's decode "
                 "throughput cost (acceptance: <= 0.01)"),
    }


def flight_overhead_phase(engine, cfg, args, rng) -> dict:
    """Decode tok/s with the flight recorder ON vs OFF (ISSUE 11
    acceptance: recorder cost within noise).  The recorder is pure host
    bookkeeping on an unchanged set of compiled programs, so the SAME
    engine runs the same workload with `engine.flight` attached vs
    detached — interleaved best-of-3, mirroring telemetry_overhead_phase
    (sub-1% comparisons need the noise discipline)."""
    from kafka_tpu.runtime.flight_recorder import FlightRecorder
    from kafka_tpu.runtime.metrics import EngineMetrics

    saved_flight = engine.flight
    gen = 48 if args.quick else 192
    batch = min(args.batch, 8)
    tps = {"on": [], "off": []}
    try:
        for _round in range(3):
            for mode in ("off", "on"):
                engine.flight = (
                    FlightRecorder(256) if mode == "on" else None
                )
                engine.metrics = EngineMetrics()
                t, _ = decode_phase(engine, cfg, batch,
                                    args.prompt_len // 2, gen, rng)
                tps[mode].append(t)
    finally:
        engine.flight = saved_flight
        engine.metrics = EngineMetrics()
    on, off = max(tps["on"]), max(tps["off"])
    return {
        "tok_s_on": round(on, 1),
        "tok_s_off": round(off, 1),
        "regression_frac": round(max(0.0, 1 - on / off), 4) if off else 0.0,
        "note": ("same engine/programs, interleaved runs, best-of-3 per "
                 "mode; regression_frac is the flight recorder's decode "
                 "throughput cost (acceptance: within noise, <= 0.01)"),
    }


def device_truth_phase(engine, cfg, args, rng, sample_period: int = 32) -> dict:
    """Device-truth telemetry costs (ISSUE 18): two proofs.

    * sampling A/B — the SAME engine decodes with the kernel sampler
      detached vs attached at N=`sample_period` (every-Nth-step
      jax.profiler trace window), interleaved best-of-3 like the other
      sub-1% overhead phases.  OFF is the shipped default — sampler
      None, engine.step untouched — so tok_s_off doubles as the
      bit-identical-when-off baseline; acceptance is bounded overhead
      at N=32 (the 1/N amortization keeps even a ~ms trace start/stop
      under a few percent).
    * rebuild compile-outage window — wall seconds from fresh-engine
      construction to its first generated token: WARM reuses the
      process jit caches the /admin/resize rebuild path shares (the
      module _FN_CACHE), COLD clears them first (what a crashed/replaced
      process pays, modulo the persistent XLA disk cache when one is
      mounted).  Both legs run under the compile observatory's
      "rebuild" phase, so the ring attributes their compiles to
      by_phase["rebuild"] — the same attribution /debug/compiles shows
      after a live resize.
    """
    import tempfile as _tempfile

    from kafka_tpu.runtime import GenRequest, InferenceEngine, compile_log
    from kafka_tpu.runtime.kernel_profiler import KernelSampler
    from kafka_tpu.runtime.metrics import EngineMetrics

    compile_log.init()  # idempotent; the server does this in app.py
    obs = compile_log.get()

    saved_sampler = getattr(engine, "kernel_sampler", None)
    gen = 48 if args.quick else 192
    batch = min(args.batch, 8)
    spill = _tempfile.mkdtemp(prefix="kafka_tpu_bench_kernels_")
    tps = {"on": [], "off": []}
    samples = 0
    kernels_seen = 0
    try:
        for _round in range(3):
            for mode in ("off", "on"):
                sampler = (KernelSampler(sample_period, spill_dir=spill)
                           if mode == "on" else None)
                engine.kernel_sampler = sampler
                engine.metrics = EngineMetrics()
                t, _ = decode_phase(engine, cfg, batch,
                                    args.prompt_len // 2, gen, rng)
                if sampler is not None:
                    sampler.close(engine.metrics)
                    samples += sampler.samples_total
                    kernels_seen = max(kernels_seen,
                                       len(sampler.table(top_k=1000)))
                tps[mode].append(t)
    finally:
        engine.kernel_sampler = saved_sampler
        engine.metrics = EngineMetrics()
    on, off = max(tps["on"]), max(tps["off"])
    sampling = {
        "sample_period": sample_period,
        "tok_s_off": round(off, 1),
        "tok_s_on": round(on, 1),
        "overhead_frac": round(max(0.0, 1 - on / off), 4) if off else 0.0,
        "samples": samples,
        "kernels_seen": kernels_seen,
        "note": ("same engine/programs, interleaved best-of-3; OFF is "
                 "the shipped default (sampler detached, dispatch path "
                 "identical); acceptance: bounded overhead at N="
                 f"{sample_period}"),
    }

    # -- rebuild compile-outage window: warm first (the caches are hot
    # from the A/B above — exactly the /admin/resize state), then cold
    def _first_token_s(cold: bool) -> float:
        if cold:
            import jax as _jax

            from kafka_tpu.runtime import engine as _engine_mod

            _engine_mod._FN_CACHE.clear()
            _jax.clear_caches()
        compile_log.set_phase("rebuild")
        t0 = time.monotonic()
        try:
            e2 = InferenceEngine(cfg, engine.params, engine.ecfg)
            e2.submit(GenRequest(request_id=f"dt-{cold}",
                                 prompt_ids=[5] * 8, max_new_tokens=1))
            e2.run_to_completion()
        finally:
            compile_log.set_phase("first_traffic")
        return time.monotonic() - t0

    rebuilds_before = (obs.metrics_section()["by_phase"].get("rebuild", 0)
                       if obs is not None else 0)
    warm_s = _first_token_s(cold=False)
    rebuilds_warm = (obs.metrics_section()["by_phase"].get("rebuild", 0)
                     if obs is not None else 0)
    cold_s = _first_token_s(cold=True)
    rebuilds_cold = (obs.metrics_section()["by_phase"].get("rebuild", 0)
                     if obs is not None else 0)
    rebuild = {
        "warm_first_token_s": round(warm_s, 3),
        "cold_first_token_s": round(cold_s, 3),
        "cold_over_warm": round(cold_s / warm_s, 2) if warm_s else None,
        "compiles_warm_leg": rebuilds_warm - rebuilds_before,
        "compiles_cold_leg": rebuilds_cold - rebuilds_warm,
        "note": ("fresh engine to first token; warm = shared process jit "
                 "caches (the /admin/resize path), cold = caches cleared "
                 "(crashed-process restart, modulo the persistent XLA "
                 "disk cache when mounted); compile counts from the "
                 "observatory ring's by_phase['rebuild']"),
    }
    return {"sampling": sampling, "rebuild_outage": rebuild}


def shared_prefix_phase(cfg, params, n_threads: int, common_len: int,
                        suffix_len: int, gen_len: int,
                        page_size: int = 16, seed: int = 11) -> dict:
    """Cross-thread radix-cache proof: N DISTINCT threads sharing a common
    system prefix (the fan-out agent-deployment shape, BASELINE config 3).

    Under the exact-key (thread-id) cache this workload got ZERO reuse —
    every thread's first turn re-prefilled the shared prefix.  The radix
    tree prefills it once per engine: thread 1 is the cold seed, threads
    2..N prefill only their suffix.  The baseline engine (prefix cache
    disabled — identical to exact-key behavior on first turns of distinct
    threads) runs the same workload for the TTFT/prefill-FLOPs delta.

    Importable by the tier-1 smoke test (CPU backend): the counters —
    hits, tokens_reused, cross_thread_hits — must move on any backend.
    """
    from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine
    from kafka_tpu.runtime.metrics import _percentiles

    rng = random.Random(seed)
    total = common_len + suffix_len + gen_len + page_size
    ecfg = EngineConfig(
        max_batch=4, page_size=page_size,
        max_pages_per_seq=max(2, -(-total // page_size)),
        # small buckets so a suffix-only (cache-hit) prefill dispatches a
        # suffix-sized chunk, plus a big one for the cold full prompt
        prefill_buckets=(16, 64, 256, 512),
    )
    # pool holds every thread's window + the shared cache without pressure
    ecfg.num_pages = (n_threads + 2) * ecfg.max_pages_per_seq + 1
    common = make_prompt(rng, common_len, cfg.vocab_size)
    suffixes = [make_prompt(rng, suffix_len, cfg.vocab_size)
                for _ in range(n_threads)]

    def run(engine, keyed: bool):
        # compile the full-length and suffix-length buckets AND the decode
        # program outside the measured loop (an in-window XLA compile was
        # the classic bench pollution; a 1-token warm finishes at prefill
        # and never compiles decode); warm requests are unkeyed so they
        # seed no cache
        engine.generate(make_prompt(rng, common_len + suffix_len,
                                    cfg.vocab_size),
                        max_new_tokens=max(2, gen_len))
        engine.generate(make_prompt(rng, max(1, suffix_len),
                                    cfg.vocab_size),
                        max_new_tokens=max(2, gen_len))
        ttfts = []
        for i in range(n_threads):
            r = GenRequest(
                request_id=f"sp-{i}",
                prompt_ids=common + suffixes[i],
                max_new_tokens=gen_len,
                prefix_key=f"sp-thread-{i}" if keyed else None,
            )
            engine.submit(r)
            engine.run_to_completion()
            ttfts.append((r.first_token_time - r.submit_time) * 1e3)
        return ttfts

    radix = InferenceEngine(cfg, params, ecfg)
    radix_ttfts = run(radix, keyed=True)
    pc = radix.prefix_cache
    saved = pc.tokens_reused
    cross = pc.cross_thread_hits
    hits = pc.hits
    slo = phase_slo(radix)
    del radix
    base_engine = InferenceEngine(
        cfg, params, dataclasses.replace(ecfg, prefix_cache_entries=0)
    )
    base_ttfts = run(base_engine, keyed=False)
    del base_engine
    radix_p = {k: round(v, 2) for k, v in _percentiles(radix_ttfts).items()}
    base_p = {k: round(v, 2) for k, v in _percentiles(base_ttfts).items()}
    # thread 1 is the cold seed on both engines; the WARM population
    # (threads 2..N) is where the cross-thread win lives
    warm_radix = statistics.median(radix_ttfts[1:]) if n_threads > 1 else None
    warm_base = statistics.median(base_ttfts[1:]) if n_threads > 1 else None
    return {
        "n_threads": n_threads,
        "common_prefix_tokens": common_len,
        "suffix_tokens": suffix_len,
        "gen_len": gen_len,
        "radix_ttft_ms": radix_p,
        "baseline_ttft_ms": base_p,
        "warm_thread_ttft_ms": {
            "radix": round(warm_radix, 2) if warm_radix else None,
            "baseline": round(warm_base, 2) if warm_base else None,
            "speedup": round(warm_base / warm_radix, 2)
            if warm_radix and warm_base else None,
        },
        "prefill_tokens_saved": saved,
        "cache_hits": hits,
        "cross_thread_hits": cross,
        **slo,
        "note": ("N distinct threads, one shared system prefix: the radix "
                 "cache prefills it once per engine (threads 2..N prefill "
                 "only their suffix); baseline = cache disabled, identical "
                 "to the old exact-key cache on first turns of distinct "
                 "threads (zero reuse)"),
    }


def speculative_phase(cfg, params, n_lanes: int = 4, prompt_len: int = 160,
                      gen_len: int = 64, k: int = 8, page_size: int = 16,
                      seed: int = 5) -> dict:
    """Draft-free speculative decoding proof (ISSUE 5) on a tool-echo
    workload: the same greedy batch runs with speculation off (baseline)
    and on (KAFKA_TPU_SPECULATIVE_K-style EngineConfig.speculative_k=k),
    and the phase reports accepted-tokens/step, acceptance rate, and
    end-to-end tok/s uplift.  Outputs must be TOKEN-IDENTICAL between the
    two engines — speculation is a pure latency/throughput optimization.

    Prompt shape: agent tool loops echo file contents / JSON tool results
    back into the context, so each prompt embeds the same "tool result"
    span twice plus a short repeated motif — exactly the regime where
    n-gram prompt lookup finds long candidate runs (generation that
    re-derives any part of the span gets proposed its continuation).

    Importable by the tier-1 CPU smoke test (tests/test_speculative.py):
    acceptance and output-equivalence must hold on any backend; TPU
    throughput numbers land in BENCH_r06.
    """
    from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine

    rng = random.Random(seed)
    total = prompt_len + gen_len + 2 * page_size

    def mk(spec_k):
        ecfg = EngineConfig(
            max_batch=max(2, n_lanes), page_size=page_size,
            max_pages_per_seq=max(2, -(-total // page_size)),
            prefill_buckets=(32, 64, 256, 512),
            speculative_k=spec_k,
        )
        ecfg.num_pages = (n_lanes + 2) * ecfg.max_pages_per_seq + 1
        return InferenceEngine(cfg, params, ecfg)

    def echo_prompt():
        span = make_prompt(rng, max(8, prompt_len // 4), cfg.vocab_size)
        motif = make_prompt(rng, 6, cfg.vocab_size)
        head = make_prompt(rng, max(4, prompt_len // 8), cfg.vocab_size)
        p = head + span + motif + span + motif
        if len(p) < prompt_len:
            p = p + make_prompt(rng, prompt_len - len(p), cfg.vocab_size)
        return p[:prompt_len]

    prompts = [echo_prompt() for _ in range(n_lanes)]

    def run(spec_k):
        eng = mk(spec_k)
        # compile every program outside the measured window — the prefill
        # buckets, the verify step (a repetitive warm prompt guarantees a
        # proposal), and the batched-prefill + fused multi-step programs a
        # concurrent greedy batch reaches (the baseline engine decodes
        # through those; an in-window XLA compile is the classic bench
        # pollution)
        eng.generate(prompts[0], max_new_tokens=2)
        eng.generate([7] * min(prompt_len, 48), max_new_tokens=16)
        for i in range(min(4, n_lanes)):
            eng.submit(GenRequest(
                request_id=f"spec-warm-{spec_k}-{i}",
                prompt_ids=make_prompt(rng, max(4, prompt_len // 2),
                                       cfg.vocab_size),
                max_new_tokens=eng.ecfg.multi_step + 4))
        eng.run_to_completion()
        # the warmup traffic above (including the deliberately repetitive
        # prompt) lands in the same lifetime counters as the measured
        # batch — everything reported below is a POST-WARMUP delta
        steps0 = eng.metrics.decode_steps
        spec0 = eng.metrics.speculation_snapshot()
        reqs = [
            GenRequest(request_id=f"spec-{spec_k}-{i}", prompt_ids=p,
                       max_new_tokens=gen_len)
            for i, p in enumerate(prompts)
        ]
        t0 = time.monotonic()
        for r in reqs:
            eng.submit(r)
        eng.run_to_completion()
        wall = time.monotonic() - t0
        tokens = sum(len(r.output_ids) for r in reqs)
        steps = eng.metrics.decode_steps - steps0
        spec1 = eng.metrics.speculation_snapshot()
        deltas = {
            key: spec1[key] - spec0[key]
            for key in ("speculation_proposed_tokens",
                        "speculation_accepted_tokens",
                        "speculation_rejected_tokens",
                        "speculation_verify_steps")
        }
        return ([r.output_ids for r in reqs], tokens / wall, steps, deltas,
                phase_slo(eng))

    base_out, base_tps, base_steps, _, _ = run(0)
    spec_out, spec_tps, spec_steps, spec, spec_slo = run(k)
    drained = (spec["speculation_accepted_tokens"]
               + spec["speculation_rejected_tokens"])
    spec["speculation_acceptance_rate"] = round(
        spec["speculation_accepted_tokens"] / drained, 4
    ) if drained else 0.0
    spec["speculation_accepted_per_step"] = round(
        spec["speculation_accepted_tokens"]
        / spec["speculation_verify_steps"], 3
    ) if spec["speculation_verify_steps"] else 0.0
    return {
        "n_lanes": n_lanes,
        "prompt_len": prompt_len,
        "gen_len": gen_len,
        "speculative_k": k,
        "outputs_match": base_out == spec_out,
        "decode_tok_s": {"baseline": round(base_tps, 1),
                         "speculative": round(spec_tps, 1)},
        "tok_s_uplift": round(spec_tps / base_tps, 2) if base_tps else None,
        "decode_steps": {"baseline": base_steps,
                         "speculative": spec_steps},
        "acceptance_rate": spec["speculation_acceptance_rate"],
        "accepted_per_step": spec["speculation_accepted_per_step"],
        "proposed_tokens": spec["speculation_proposed_tokens"],
        "accepted_tokens": spec["speculation_accepted_tokens"],
        "verify_steps": spec["speculation_verify_steps"],
        **spec_slo,
        "note": ("tool-echo greedy workload, speculation on vs off; "
                 "outputs are token-identical by design (exact-match "
                 "acceptance with the sequential path's per-(seed, "
                 "position) sampling keys).  On TPU the uplift is "
                 "weight-stream amortization (accepted_per_step extra "
                 "tokens per weight read); CPU smoke walls are partly "
                 "fetch-pipeline-aging artifacts — acceptance_rate / "
                 "accepted_per_step are the backend-independent signal"),
    }


def constrained_phase(cfg, params, n_lanes: int = 4, gen_len: int = 96,
                      page_size: int = 16, seed: int = 7) -> dict:
    """On-device grammar FSM proof (ISSUE 7): the same greedy constrained
    batch runs through the host mask-fn path (awaited micro-batch +
    forced-token chaining) and the device-FSM path (compiled grammar
    tables, zero host round trips), plus free co-scheduled lanes.

    Token streams must be BIT-IDENTICAL between the two modes (the FSM's
    per-state allowed sets are compiled from the exact host-mask
    semantics), and the on-device mode must report
    `constrained_roundtrips_per_call ~ 0` — the host path's per-call
    round trips times the link RTT is precisely the hot-path cliff this
    mode removes.  Importable by the tier-1 CPU smoke test
    (tests/test_grammar_fsm.py); TPU tok/s uplift lands in BENCH rounds.
    """
    from kafka_tpu.llm.constrained import (
        ToolCallMaskFn,
        compile_tool_call_grammar,
        validate_tool_call_json,
    )
    from kafka_tpu.models.tokenizer import ByteTokenizer
    from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine

    tools = [
        {"type": "function", "function": {
            "name": "lookup",
            "parameters": {"type": "object", "properties": {
                "city": {"type": "string"}, "units": {"type": "string"},
            }},
        }},
        {"type": "function", "function": {
            "name": "idle",
            "parameters": {"type": "object", "properties": {}},
        }},
    ]
    tok = ByteTokenizer(vocab_size=cfg.vocab_size)
    grammar = compile_tool_call_grammar(tok, tools,
                                        vocab_size=cfg.vocab_size)
    assert grammar is not None, "grammar compile fell back"
    total = 64 + gen_len + 2 * page_size

    def run(ondevice: bool):
        ecfg = EngineConfig(
            max_batch=max(2, n_lanes), page_size=page_size,
            max_pages_per_seq=max(2, -(-total // page_size)),
            prefill_buckets=(32, 64, 128),
        )
        ecfg.num_pages = (n_lanes + 2) * ecfg.max_pages_per_seq + 1
        eng = InferenceEngine(cfg, params, ecfg)
        # compile outside the measured window (prefill buckets, masked
        # prefill, the plain/FSM decode programs)
        warm = GenRequest(
            request_id=f"warm-{ondevice}", prompt_ids=[3] * 16,
            max_new_tokens=6, stop_token_ids=tuple(tok.stop_ids),
            logits_mask_fn=ToolCallMaskFn(tok, tools),
            grammar=grammar if ondevice else None,
        )
        eng.submit(warm)
        eng.generate([5] * 16, max_new_tokens=4)
        eng.run_to_completion()
        rt0 = eng.metrics.constrained_roundtrips
        reqs = []
        for i in range(n_lanes):
            if i % 2 == 0:
                reqs.append(GenRequest(
                    request_id=f"con-{ondevice}-{i}",
                    prompt_ids=tok.encode(f"call a tool for city {i}"),
                    max_new_tokens=gen_len,
                    stop_token_ids=tuple(tok.stop_ids),
                    logits_mask_fn=ToolCallMaskFn(tok, tools),
                    grammar=grammar if ondevice else None,
                ))
            else:
                reqs.append(GenRequest(
                    request_id=f"free-{ondevice}-{i}",
                    prompt_ids=tok.encode(f"stream some text {i}"),
                    max_new_tokens=gen_len,
                ))
        t0 = time.monotonic()
        for r in reqs:
            eng.submit(r)
        eng.run_to_completion()
        wall = time.monotonic() - t0
        con = [r for r in reqs if r.logits_mask_fn is not None]
        free = [r for r in reqs if r.logits_mask_fn is None]
        texts = [
            tok.decode([t for t in r.output_ids
                        if t not in tok.stop_ids])
            for r in con
        ]
        for t in texts:
            assert validate_tool_call_json(t, tools), t
        roundtrips = eng.metrics.constrained_roundtrips - rt0
        return {
            "outputs_con": [list(r.output_ids) for r in con],
            "outputs_free": [list(r.output_ids) for r in free],
            "roundtrips_per_call": round(roundtrips / len(con), 1),
            "ondevice_tokens": eng.metrics.constrained_ondevice_tokens,
            "constrained_tok_s": round(
                sum(len(r.output_ids) for r in con) / wall, 1),
            "free_tok_s": round(
                sum(len(r.output_ids) for r in free) / wall, 1),
            "wall_s": round(wall, 3),
            "slo": phase_slo(eng),
        }

    host = run(False)
    dev = run(True)

    def wrap_free_prefix(out):
        # positions where budget_left > dist + wrap_slack sit outside BOTH
        # paths' wrap-up windows (the FSM's jump-aware slack >= the host's
        # fixed 4): masks are provably equal there, so streams must match.
        # Near the budget, wrap TIMING legitimately differs.
        state, n = 0, 0
        for i, t in enumerate(out):
            if gen_len - i <= int(grammar.dist[state]) + grammar.wrap_slack:
                break
            n = i + 1
            state = grammar.walk([t], start=state)
            if state < 0:
                break  # stop token (not a DFA edge)
        return n

    # free co-scheduled lanes must match EXACTLY (all-True FSM mask rows
    # leave the sampler bit-identical); constrained lanes match exactly or
    # on their full wrap-free prefix
    matches = [h == d for h, d in
               zip(host["outputs_free"], dev["outputs_free"])]
    for h, d in zip(host["outputs_con"], dev["outputs_con"]):
        if h == d:
            matches.append(True)
            continue
        n = wrap_free_prefix(h)
        matches.append(n > 0 and h[:n] == d[:n])
    return {
        "n_lanes": n_lanes,
        "gen_len": gen_len,
        "grammar_states": grammar.num_states,
        "grammar_classes": grammar.num_classes,
        "grammar_table_kib": round(grammar.table_bytes / 1024, 1),
        "outputs_match": all(matches),
        "roundtrips_per_call": {
            "host": host["roundtrips_per_call"],
            "ondevice": dev["roundtrips_per_call"],
        },
        "ondevice_tokens": dev["ondevice_tokens"],
        "constrained_tok_s": {
            "host": host["constrained_tok_s"],
            "ondevice": dev["constrained_tok_s"],
        },
        "free_tok_s": {
            "host": host["free_tok_s"],
            "ondevice": dev["free_tok_s"],
        },
        **dev["slo"],
        "note": ("greedy mixed batch (constrained + free lanes), host "
                 "mask path vs device-FSM grammar tables; token streams "
                 "bit-identical outside the wrap-up window (the FSM's "
                 "jump-aware slack engages wrap earlier near the budget). "
                 "On tunneled links the host mode pays roundtrips_per_call"
                 " x RTT per agent call; on-device mode pays ~0 "
                 "(constrained lanes rejoin the batched dispatch)"),
    }


def kv_tier_phase(cfg, params, n_churn: int = 3, prompt_len: int = 2048,
                  gen_len: int = 32, page_size: int = 16, seed: int = 23,
                  disk_dir=None) -> dict:
    """Tiered-KV cold-resume proof (ISSUE 9): a thread whose KV was
    evicted under page pressure RESUMES — promote-from-host-tier vs the
    full re-prefill the engine paid before the tier existed.

    Shape: thread A prefills `prompt_len` tokens, generates, retires (its
    KV lands in the radix cache).  `n_churn` other threads then churn
    through an undersized pool, forcing reclaim of A's cached pages —
    with the tier enabled they DEMOTE (async D2H) instead of dropping.
    A then returns with its whole history plus a short new turn:
      * tiered engine: lookup promotes the host run, prefill starts at
        the promoted page boundary (cache_source="host_tier"),
      * baseline engine (tier off): the same eviction dropped the KV, so
        the resume re-prefills everything.
    Reports both resume TTFTs, the demote/promote copy bandwidth, and the
    tier hit/traffic counters.  Outputs are asserted token-identical
    between the two engines (greedy).

    Importable by the tier-1 CPU smoke test: counters and the promoted
    boundary must hold on any backend; the TTFT ordering (promote <
    re-prefill) is the acceptance criterion and holds by construction —
    a page-run memcpy plus a one-bucket suffix prefill vs a full-prompt
    prefill.
    """
    import tempfile

    from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine

    rng = random.Random(seed)
    win_pages = max(4, -(-(prompt_len + 2 * gen_len + 2 * page_size)
                         // page_size))
    own_disk = disk_dir is None
    if own_disk:
        disk_dir = tempfile.mkdtemp(prefix="kafka-kv-tier-")

    def mk(tier_mb: int):
        ecfg = EngineConfig(
            max_batch=2, page_size=page_size,
            max_pages_per_seq=win_pages,
            # pool < (active window + A's cached run): churn admission
            # must reclaim A's pages, which is the demotion under test
            num_pages=win_pages + win_pages // 2 + 2,
            prefill_buckets=(16, 64, 256, 512, 1024, 2048, 4096),
            kv_host_tier_mb=tier_mb,
            kv_disk_tier_dir=disk_dir if tier_mb else None,
        )
        return InferenceEngine(cfg, params, ecfg)

    prompt_a = make_prompt(rng, prompt_len, cfg.vocab_size)
    churn_prompts = [make_prompt(rng, prompt_len, cfg.vocab_size)
                     for _ in range(n_churn)]
    tail = make_prompt(rng, max(4, gen_len // 2), cfg.vocab_size)

    def run(tier_mb: int) -> dict:
        eng = mk(tier_mb)
        # compile the buckets + decode outside the measured resume (the
        # classic bench pollution): one full-length and one tail-length
        # unkeyed warm generation
        eng.generate(make_prompt(rng, prompt_len, cfg.vocab_size),
                     max_new_tokens=2)
        eng.generate(make_prompt(rng, max(1, len(tail)), cfg.vocab_size),
                     max_new_tokens=2)
        if tier_mb:
            # compile the ship (gather/scatter) programs at A's bucket
            # size outside the measured resume: one throwaway keyed
            # thread is stored, demoted, promoted, and invalidated
            w = GenRequest(request_id="tier-W",
                           prompt_ids=make_prompt(rng, prompt_len,
                                                  cfg.vocab_size),
                           max_new_tokens=gen_len,
                           prefix_key="tier-warm")
            eng.submit(w)
            eng.run_to_completion()
            pc0 = eng.prefix_cache
            pc0.reclaim(eng.pool.free_pages + pc0.total_pages)
            warm_hit = pc0.lookup("tier-warm",
                                  w.prompt_ids + w.output_ids + [1])
            if warm_hit is not None:
                eng.pool.release(warm_hit.pages)
            pc0.invalidate("tier-warm")
        a = GenRequest(request_id="tier-A", prompt_ids=prompt_a,
                       max_new_tokens=gen_len, prefix_key="tier-thread-A")
        eng.submit(a)
        eng.run_to_completion()
        for i, p in enumerate(churn_prompts):
            r = GenRequest(request_id=f"tier-C{i}", prompt_ids=p,
                           max_new_tokens=4, prefix_key=f"tier-churn-{i}")
            eng.submit(r)
            eng.run_to_completion()
        pc = eng.prefix_cache
        demoted_nodes = pc.host_nodes
        resume_prompt = prompt_a + list(a.output_ids) + tail
        a2 = GenRequest(request_id="tier-A2", prompt_ids=resume_prompt,
                        max_new_tokens=gen_len,
                        prefix_key="tier-thread-A")
        eng.submit(a2)
        eng.run_to_completion()
        out = {
            "resume_ttft_ms": round(
                (a2.first_token_time - a2.submit_time) * 1e3, 2),
            "resume_cached_tokens": a2.cached_tokens,
            "resume_promoted_tokens": a2.promoted_tokens,
            "cache_source": a2.cache_source,
            "demoted_nodes_before_resume": demoted_nodes,
            "first_output": list(a.output_ids),
            "resume_output": list(a2.output_ids),
            "host_tier_hits": pc.host_tier_hits,
            "hits": pc.hits,
        }
        tier = eng.kv_tier
        if tier is not None:
            tier.flush()
            out["tier"] = tier.snapshot()
            # Direct SYNCHRONOUS bandwidth probe.  The manager's copy
            # timers measure the async enqueue, not the transfer — bytes
            # over that would wildly overstate D2H bandwidth on real
            # hardware (the gather returns before the copy lands).  So
            # time a blocking export+resolve (D2H) and import+block (H2D)
            # of a trash-page run: reads garbage, writes garbage INTO the
            # trash page, no pool state changes.
            import jax as _jax

            ship = tier.shipper
            n_probe = min(32, eng.ecfg.num_pages - 2)
            probe = [0] * n_probe
            probe_bytes = n_probe * ship.bytes_per_page()
            t0 = time.monotonic()
            k_l, v_l = ship.resolve(ship.export_run(probe))
            d2h_s = time.monotonic() - t0
            t0 = time.monotonic()
            ship.import_run(k_l, v_l, n_probe, probe)
            _jax.block_until_ready(eng.k_pool)
            h2d_s = time.monotonic() - t0
            out["demote_bw_mbps"] = round(probe_bytes / d2h_s / 1e6, 1)
            out["promote_bw_mbps"] = round(probe_bytes / h2d_s / 1e6, 1)
        out["slo"] = phase_slo(eng)
        del eng
        return out

    tiered = run(tier_mb=256)
    base = run(tier_mb=0)
    if own_disk:
        import shutil

        shutil.rmtree(disk_dir, ignore_errors=True)
    assert tiered["first_output"] == base["first_output"], \
        "tier changed the first generation"
    assert tiered["resume_output"] == base["resume_output"], \
        "tier changed the resume generation"
    speedup = (
        round(base["resume_ttft_ms"] / tiered["resume_ttft_ms"], 2)
        if tiered["resume_ttft_ms"] else None
    )
    return {
        "prompt_tokens": prompt_len,
        "resume_ttft_ms": {
            "promote": tiered["resume_ttft_ms"],
            "reprefill": base["resume_ttft_ms"],
            "speedup": speedup,
        },
        "resume_cached_tokens": tiered["resume_cached_tokens"],
        "resume_promoted_tokens": tiered["resume_promoted_tokens"],
        "cache_source": tiered["cache_source"],
        "baseline_cached_tokens": base["resume_cached_tokens"],
        "demote_bw_mbps": tiered.get("demote_bw_mbps"),
        "promote_bw_mbps": tiered.get("promote_bw_mbps"),
        "tier_counters": tiered.get("tier"),
        "host_tier_hit_ratio": round(
            tiered["host_tier_hits"] / tiered["hits"], 3
        ) if tiered["hits"] else 0.0,
        **tiered["slo"],
        "note": ("thread A evicted under churn pressure resumes with its "
                 "full history: tiered engine promotes the demoted run "
                 "and prefills only the new turn; baseline re-prefills "
                 "the whole prompt (outputs token-identical both ways)"),
    }


def sleep_wake_phase(cfg, params, n_threads: int = 4, common_len: int = 512,
                     suffix_len: int = 64, gen_len: int = 16,
                     page_size: int = 16, seed: int = 41,
                     object_dir=None) -> dict:
    """Object-store sleep/wake proof (ISSUE 14): N threads with a shared
    system prefix go dormant PAST the disk tier (replica drained to the
    shared object store), then wake on a DIFFERENT replica — a fresh
    engine that never served them, standing in for any host mounting the
    same store after the original was torn down.

    Measures the two things the tier exists for:
      * cold-resume TTFT A/B — waking from the object store (fetch +
        H2D import + suffix-only prefill, ``cache_source="object_tier"``)
        vs the full re-prefill a storeless fresh replica pays, with
        0 prompt tokens recomputed inside the woken span;
      * the store itself — put/get MB/s and the cross-host dedupe ratio
        (the wake replica re-drained: its archive of the shared prefix
        must find every object already present).

    Outputs are asserted token-identical against a never-slept reference
    engine serving the same two turns — the portability proof: moving a
    thread across hosts changes WHERE it decodes, never WHAT.

    Importable by the tier-1 CPU smoke (tests/test_object_tier.py): the
    wake < re-prefill TTFT ordering holds by construction — an object
    fetch + page import vs a full-prompt prefill."""
    import shutil
    import tempfile

    from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine

    rng = random.Random(seed)
    own_dir = object_dir is None
    if own_dir:
        object_dir = tempfile.mkdtemp(prefix="kafka-kv-object-")
    total = common_len + suffix_len + 2 * gen_len
    win_pages = max(4, -(-(total + 2 * page_size) // page_size))

    def mk(with_store: bool):
        ecfg = EngineConfig(
            max_batch=2, page_size=page_size,
            max_pages_per_seq=win_pages,
            num_pages=(n_threads + 2) * win_pages + 2,
            prefill_buckets=(16, 64, 256, 512, 1024, 2048),
            kv_host_tier_mb=256,
            kv_object_dir=object_dir if with_store else None,
        )
        return InferenceEngine(cfg, params, ecfg)

    common = make_prompt(rng, common_len, cfg.vocab_size)
    suffixes = [make_prompt(rng, suffix_len, cfg.vocab_size)
                for _ in range(n_threads)]
    tails = [make_prompt(rng, max(4, gen_len // 2), cfg.vocab_size)
             for _ in range(n_threads)]

    def serve_first_turns(eng):
        outs = []
        for i, sfx in enumerate(suffixes):
            r = GenRequest(request_id=f"sw-{i}", prompt_ids=common + sfx,
                           max_new_tokens=gen_len, prefix_key=f"sw-t{i}")
            eng.submit(r)
            eng.run_to_completion()
            outs.append(list(r.output_ids))
        return outs

    def warm_compiles(eng):
        # compile the buckets + decode + the tier's ship programs
        # outside any measured resume (the classic bench pollution):
        # the wake path prefills only the short post-wake suffix, so its
        # small bucket needs compiling too
        for n in (total, 32, max(4, gen_len // 2)):
            eng.generate(make_prompt(rng, n, cfg.vocab_size),
                         max_new_tokens=2)
        eng.warmup_kv_tier()

    # ---- replica A: serve, then drain to the store ----------------------
    a_eng = mk(with_store=True)
    warm_compiles(a_eng)
    first_outputs = serve_first_turns(a_eng)
    t0 = time.monotonic()
    sleep_stats = a_eng.sleep_to_object()
    sleep_s = time.monotonic() - t0
    obj_a = a_eng.kv_tier.object
    put_bytes = obj_a.object_bytes_put
    del a_eng  # replica A is gone (autoscaler scale-in / host loss)

    # ---- replica B: fresh engine, same store — wake ---------------------
    def resume_all(eng, label):
        rows = []
        for i in range(n_threads):
            prompt = common + suffixes[i] + first_outputs[i] + tails[i]
            r = GenRequest(request_id=f"{label}-{i}", prompt_ids=prompt,
                           max_new_tokens=gen_len, prefix_key=f"sw-t{i}")
            eng.submit(r)
            eng.run_to_completion()
            rows.append(r)
        return rows

    b_eng = mk(with_store=True)
    warm_compiles(b_eng)
    t0 = time.monotonic()
    woken = resume_all(b_eng, "wake")
    wake_s = time.monotonic() - t0
    obj_b = b_eng.kv_tier.object
    got_bytes = obj_b.object_bytes_got
    # stored whole-page history per thread (what a wake can cover)
    ps = page_size
    recomputed = 0
    for i, r in enumerate(woken):
        # the final sampled token's KV is never materialized (it is the
        # pending decode input), so the storable history is one short
        stored = common_len + suffix_len + len(first_outputs[i]) - 1
        coverable = min((stored // ps) * ps,
                        ((len(r.prompt_ids) - 1) // ps) * ps)
        recomputed += max(0, coverable - r.cached_tokens)
    wake_ttft_ms = [round((r.first_token_time - r.submit_time) * 1e3, 2)
                    for r in woken]
    # cross-host dedupe: replica B drains too — every shared-prefix
    # object must already be present (one object per run fleet-wide).
    # Deltas, not lifetime counters: organic archive activity on B
    # before this drain must not skew the drain's own ratio.
    dedupe0 = obj_b.dedupe_hits
    puts0 = obj_b.object_puts
    b_eng.sleep_to_object()
    dedupe = obj_b.dedupe_hits - dedupe0
    tried = (obj_b.object_puts - puts0) + dedupe

    # ---- baseline: fresh storeless replica = full re-prefill ------------
    c_eng = mk(with_store=False)
    warm_compiles(c_eng)
    cold = resume_all(c_eng, "cold")
    cold_ttft_ms = [round((r.first_token_time - r.submit_time) * 1e3, 2)
                    for r in cold]

    # ---- reference: never-slept engine, token-exactness -----------------
    ref_eng = mk(with_store=False)
    ref_first = serve_first_turns(ref_eng)
    ref = resume_all(ref_eng, "ref")
    outputs_match = (
        ref_first == first_outputs
        and all(list(ref[i].output_ids) == list(woken[i].output_ids)
                for i in range(n_threads))
        and all(list(ref[i].output_ids) == list(cold[i].output_ids)
                for i in range(n_threads))
    )

    snap_obj = obj_b.snapshot()
    if own_dir:
        shutil.rmtree(object_dir, ignore_errors=True)
    # The A/B is the FIRST resume on each fresh replica: it alone pays
    # the full cold cost (object wake vs full-history re-prefill).  Once
    # it lands, the shared prefix is LOCAL on both sides — later threads
    # compare tail-resume vs tail-resume, which measures the radix
    # cache, not the store (their figures ride along as the lists).
    return {
        "n_threads": n_threads,
        "common_prefix_tokens": common_len,
        "wake_ttft_ms": wake_ttft_ms,
        "reprefill_ttft_ms": cold_ttft_ms,
        "cold_resume_ttft_ms": {
            "object_wake": wake_ttft_ms[0],
            "reprefill": cold_ttft_ms[0],
        },
        "speedup": round(cold_ttft_ms[0] / wake_ttft_ms[0], 2)
        if wake_ttft_ms[0] else None,
        "cache_sources": [r.cache_source for r in woken],
        "object_tokens": [r.object_tokens for r in woken],
        "prompt_tokens_recomputed": recomputed,
        "sleep": sleep_stats,
        "store_put_mb_s": round(put_bytes / sleep_s / 1e6, 1)
        if sleep_s else None,
        "store_get_mb_s": round(got_bytes / wake_s / 1e6, 1)
        if wake_s else None,
        "cross_host_dedupe_hits": dedupe,
        "cross_host_dedupe_ratio": round(
            dedupe / tried, 3) if tried else 0.0,
        "wake_threads": snap_obj["wake_threads"],
        "store_bytes": snap_obj["store_bytes"],
        "store_objects": snap_obj["store_objects"],
        "outputs_match": outputs_match,
        "note": ("N threads drained past disk into the shared object "
                 "store by replica A wake on a FRESH replica B "
                 "(cache_source=object_tier, 0 coverable prompt tokens "
                 "recomputed) vs a storeless replica's full re-prefill; "
                 "replica B's own drain dedupes against A's objects "
                 "(content-addressed prefixes, one object fleet-wide)"),
    }


def agent_gap_phase(cfg, params, n_agents: int = 3, agent_len: int = 448,
                    gen_len: int = 8, churn_requests: int = 6,
                    churn_len: int = 256, page_size: int = 8,
                    tool_s: float = 0.05, tail_s: float = 0.15,
                    seed: int = 61, object_dir=None) -> dict:
    """Agent-native scheduling proof (ISSUE 20): N agent threads emit a
    tool call and sit idle for the tool's (failpoint-injected) runtime
    while interactive traffic churns through the same engine.  A/B over
    the one knob that matters:

      * OFF (``agent_demote=""``, the knobs-off baseline): the idle
        threads' KV squats in HBM until the churn's allocation pressure
        evicts it — and with the host tier's first rung missing
        (``kv_host_tier_mb=0``, an HBM-heavy replica with no host
        budget) eviction DROPS it, so every follow-up turn is a full
        re-prefill.
      * ON (``agent_demote="object"``): the linger expires mid-gap, the
        chain archives to the object store and its pages free NOW
        (measured as the pool's free-page delta); the return hint kicks
        the wake prefetcher during the tool's tail, and the follow-up
        wakes from the store — cache_source="object_tier", 0 coverable
        prompt tokens recomputed.

    Both arms serve identical token streams (same engine shape, same
    prompts, greedy sampling), so outputs are asserted bit-identical —
    the knob moves WHERE the KV waits, never WHAT the model says.  A
    background-class rider (tool-result prefill) runs beside interactive
    work on the ON arm to show the yield discipline's cost on
    interactive TPOT.

    Importable by the tier-1 CPU smoke (tests/test_agent_sched.py): the
    gap-on < gap-off follow-up TTFT ordering holds by construction — a
    prefetch-staged object wake vs a full-history re-prefill."""
    import shutil
    import tempfile

    from kafka_tpu.failpoints import armed as fp_armed
    from kafka_tpu.failpoints import failpoint as fp_fire
    from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine

    rng = random.Random(seed)
    own_dir = object_dir is None
    if own_dir:
        object_dir = tempfile.mkdtemp(prefix="kafka-kv-agent-")
    ps = page_size
    win_pages = -(-max(agent_len + 2 * gen_len + 8,
                       churn_len + 2 * gen_len) // ps) + 4
    agent_pages = -(-(agent_len + gen_len) // ps)
    # sized so the OFF arm's churn MUST evict the idle agents' KV: free
    # HBM after turn 1 is smaller than one churn request's footprint
    num_pages = n_agents * agent_pages + win_pages - 4

    def mk(demote: str, store_dir):
        ecfg = EngineConfig(
            max_batch=2, page_size=ps, max_pages_per_seq=win_pages,
            num_pages=num_pages,
            prefill_buckets=(16, 64, 256, 512, 1024),
            # park admission off: the ON arm's freed HBM would otherwise
            # park churn off-slot (a path the OFF arm can't reach while
            # page-blocked), compiling mid-measurement and skewing the A/B
            max_parked=0,
            kv_host_tier_mb=0, kv_object_dir=store_dir,
            agent_demote=demote, agent_linger_s=0.0,
        )
        return InferenceEngine(cfg, params, ecfg)

    prompts = [make_prompt(rng, agent_len, cfg.vocab_size)
               for _ in range(n_agents)]
    tool_results = [make_prompt(rng, 4, cfg.vocab_size)
                    for _ in range(n_agents)]
    churn = [make_prompt(rng, churn_len, cfg.vocab_size)
             for _ in range(churn_requests)]
    bg_prompts = [make_prompt(rng, churn_len, cfg.vocab_size)
                  for _ in range(3)]

    def warm_compiles(eng):
        # buckets for turn 1 / churn (256) and the post-wake remainder
        # (16), decode, and the tier's ship programs — compiled outside
        # any measured span.  The two-lane CONCURRENT pass matters: the
        # batched prefill/decode programs only compile with both lanes
        # live, and only the gap-on arm (free HBM mid-gap) reaches them
        # during the measured churn — a sequential warmup would hand the
        # OFF arm an accidental compile-skew win.
        for n in (agent_len, churn_len, 16):
            eng.generate(make_prompt(rng, n, cfg.vocab_size),
                         max_new_tokens=2)
        pair = [GenRequest(request_id=f"warm-{k}",
                           prompt_ids=make_prompt(rng, churn_len,
                                                  cfg.vocab_size),
                           max_new_tokens=4)
                for k in range(2)]
        for r in pair:
            eng.submit(r)
        eng.run_to_completion()
        eng.warmup_kv_tier()

    def step_serve(eng, reqs):
        """Submit, drive, and timestamp every decoded token (client-side
        TPOT truth — one decode token per request per step)."""
        for r in reqs:
            eng.submit(r)
        seen = {r.request_id: 0 for r in reqs}
        tok_times = {r.request_id: [] for r in reqs}
        while eng.has_work:
            eng.step()
            now = time.monotonic()
            for r in reqs:
                if len(r.output_ids) > seen[r.request_id]:
                    seen[r.request_id] = len(r.output_ids)
                    tok_times[r.request_id].append(now)
        return tok_times

    def tok_gaps(tok_times, ids):
        return [b - a for rid in ids for a, b in
                zip(tok_times[rid], tok_times[rid][1:])]

    def run_arm(demote: str, store_dir):
        eng = mk(demote, store_dir)
        warm_compiles(eng)
        # ---- turn 1: the agent threads' working context ----------------
        turn1 = []
        for i, p in enumerate(prompts):
            r = GenRequest(request_id=f"ag-{i}", prompt_ids=list(p),
                           max_new_tokens=gen_len, prefix_key=f"ag-t{i}")
            eng.submit(r)
            eng.run_to_completion()
            turn1.append(list(r.output_ids))
        # ---- the gap: tool call emitted, linger expires ----------------
        free0 = eng.pool.free_pages
        for i in range(n_agents):
            eng.note_tool_gap(f"ag-t{i}")
        eng.step()  # linger 0: demotions fire on the next iteration
        pages_freed = eng.pool.free_pages - free0
        # ---- the tool runs (failpoint-injected latency) while
        #      interactive traffic churns through the freed HBM ---------
        with fp_armed("agent.tool", "delay", arg=tool_s):
            for _ in range(n_agents):
                fp_fire("agent.tool")
        churn_reqs = [GenRequest(request_id=f"ch-{demote or 'off'}-{j}",
                                 prompt_ids=list(c),
                                 max_new_tokens=gen_len,
                                 prefix_key=f"ch-t{j}")
                      for j, c in enumerate(churn)]
        churn_times = step_serve(eng, churn_reqs)
        churn_gaps = tok_gaps(churn_times,
                              [r.request_id for r in churn_reqs])
        churn_ttft = [r.first_token_time - r.submit_time
                      for r in churn_reqs]
        # ---- tool returned: hint + prefetch overlap the tail -----------
        for i in range(n_agents):
            eng.note_tool_return(f"ag-t{i}")
        time.sleep(tail_s)  # the tail the wake prefetch overlaps
        # ---- follow-up turn: context + turn-1 output + tool result -----
        follow = []
        for i in range(n_agents):
            p2 = prompts[i] + turn1[i] + tool_results[i]
            r = GenRequest(request_id=f"fu-{i}", prompt_ids=p2,
                           max_new_tokens=gen_len, prefix_key=f"ag-t{i}")
            eng.submit(r)
            eng.run_to_completion()
            follow.append(r)
        recomputed = 0
        for i, r in enumerate(follow):
            stored = agent_len + len(turn1[i]) - 1
            coverable = min((stored // ps) * ps,
                            ((len(r.prompt_ids) - 1) // ps) * ps)
            recomputed += max(0, coverable - r.cached_tokens)
        # ---- background rider: interactive TPOT beside a bg prefill ----
        bg = GenRequest(request_id="bg-0", prompt_ids=list(bg_prompts[0]),
                        max_new_tokens=gen_len, prefix_key="bg-t0",
                        background=True)
        fg = [GenRequest(request_id=f"fg-{j}",
                         prompt_ids=list(bg_prompts[1 + j]),
                         max_new_tokens=gen_len, prefix_key=f"fg-t{j}")
              for j in range(2)]
        bg_times = step_serve(eng, [bg] + fg)
        fg_gaps = tok_gaps(bg_times, [r.request_id for r in fg])
        return {
            "eng": eng,
            "turn1": turn1,
            "follow": follow,
            "pages_freed": pages_freed,
            "churn_ttft": churn_ttft,
            "churn_gaps": churn_gaps,
            "churn_out": [list(r.output_ids) for r in churn_reqs],
            "recomputed": recomputed,
            "fg_gaps": fg_gaps,
        }

    on = run_arm("object", os.path.join(object_dir, "on"))
    off = run_arm("", os.path.join(object_dir, "off"))

    on_ttft = [round((r.first_token_time - r.submit_time) * 1e3, 2)
               for r in on["follow"]]
    off_ttft = [round((r.first_token_time - r.submit_time) * 1e3, 2)
                for r in off["follow"]]
    outputs_match = (
        on["turn1"] == off["turn1"]
        and on["churn_out"] == off["churn_out"]
        and all(list(a.output_ids) == list(b.output_ids)
                for a, b in zip(on["follow"], off["follow"]))
    )
    agent_snap = on["eng"].agent_section()
    if own_dir:
        shutil.rmtree(object_dir, ignore_errors=True)
    return {
        "n_agents": n_agents,
        "tool_latency_s": tool_s,
        "followup_ttft_ms": {"gap_on": on_ttft, "gap_off": off_ttft},
        "followup_ttft_mean_ms": {
            "gap_on": round(sum(on_ttft) / len(on_ttft), 2),
            "gap_off": round(sum(off_ttft) / len(off_ttft), 2),
        },
        "speedup": round(
            (sum(off_ttft) / len(off_ttft))
            / (sum(on_ttft) / len(on_ttft)), 2)
        if sum(on_ttft) else None,
        "hbm_pages_freed_mid_gap": {"gap_on": on["pages_freed"],
                                    "gap_off": off["pages_freed"]},
        "cache_sources_on": [r.cache_source for r in on["follow"]],
        "prompt_tokens_recomputed": {"gap_on": on["recomputed"],
                                     "gap_off": off["recomputed"]},
        "interactive_churn_ttft_ms": {
            "gap_on": percentiles_ms(on["churn_ttft"]),
            "gap_off": percentiles_ms(off["churn_ttft"]),
        },
        "interactive_churn_tpot_ms": {
            "gap_on": percentiles_ms(on["churn_gaps"]),
            "gap_off": percentiles_ms(off["churn_gaps"]),
        },
        "interactive_tpot_with_bg_ms": percentiles_ms(on["fg_gaps"]),
        "bg": {"admitted": agent_snap["bg_admitted"],
               "chunks": agent_snap["bg_chunks"],
               "yields": agent_snap["bg_yields"]},
        "agent": {k: agent_snap[k] for k in
                  ("agent_gaps", "agent_gap_demotions",
                   "agent_gap_pages_demoted", "agent_hint_hits",
                   "agent_hint_misses")},
        "outputs_match": outputs_match,
        "note": ("N agent threads mid-tool-call under interactive churn, "
                 "host tier's first rung missing (kv_host_tier_mb=0): "
                 "gap-on archives to the object store at the linger and "
                 "frees HBM mid-gap, the return hint prefetches during "
                 "the tool tail, and the follow-up wakes "
                 "(cache_source=object_tier, 0 coverable prompt tokens "
                 "recomputed) vs gap-off's pressure-evicted full "
                 "re-prefill; outputs bit-identical across arms"),
    }


def store_outage_phase(cfg, params, n_threads: int = 5,
                       common_len: int = 128, suffix_len: int = 16,
                       gen_len: int = 8, page_size: int = 8,
                       seed: int = 43, object_dir=None) -> dict:
    """Object-store outage containment proof (ISSUE 17): with the object
    tier enabled and the store killed MID-RUN (failpoint storm on every
    store op), the StoreGuard breaker opens, no request ever stalls on a
    store op — submit→first-dispatch stays within noise of a storeless
    baseline paying the same re-prefills — and after the store returns a
    drained thread wakes with ``cache_source="object_tier"`` again,
    token-exact.

    Timeline on the wake replica (fresh engine B mounting the store
    replica A drained into):
      1. pre-outage resume — store healthy, wake from the object tier;
      2. the store dies (``kv.object_put/get/head`` armed ``error``):
         each newly-probed thread records one breaker failure, the
         breaker opens at the threshold, later probes are negatively
         cached / fast-failed — every resume completes as a plain
         re-prefill at baseline latency;
      3. the store returns, the open window elapses: the next resume is
         the half-open probe, the breaker closes, and the thread wakes
         from its sleep manifest.

    Every output is asserted token-identical against a never-slept
    reference — degradation changes WHERE tokens come from, never what
    they are.  Importable by the tier-1 CPU smoke
    (tests/test_store_guard.py)."""
    import os
    import shutil
    import tempfile

    from kafka_tpu.failpoints import clear as fp_clear
    from kafka_tpu.failpoints import configure as fp_configure
    from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine

    rng = random.Random(seed)
    own_dir = object_dir is None
    if own_dir:
        object_dir = tempfile.mkdtemp(prefix="kafka-kv-outage-")
    total = common_len + suffix_len + 2 * gen_len
    win_pages = max(4, -(-(total + 2 * page_size) // page_size))
    open_window_s = 0.75
    # a fast-tripping guard: the phase proves the state machine, not the
    # production trip threshold
    knobs = {
        "KAFKA_TPU_KV_OBJECT_BREAKER_FAILURES": "3",
        "KAFKA_TPU_KV_OBJECT_BREAKER_OPEN_S": str(open_window_s),
        "KAFKA_TPU_KV_OBJECT_RETRIES": "0",
        "KAFKA_TPU_KV_OBJECT_BACKOFF_S": "0",
    }
    saved = {k: os.environ.get(k) for k in knobs}
    os.environ.update(knobs)

    def mk(with_store: bool):
        ecfg = EngineConfig(
            max_batch=2, page_size=page_size,
            max_pages_per_seq=win_pages,
            num_pages=(n_threads + 2) * win_pages + 2,
            prefill_buckets=(16, 64, 256, 512, 1024),
            kv_host_tier_mb=256,
            kv_object_dir=object_dir if with_store else None,
        )
        return InferenceEngine(cfg, params, ecfg)

    common = make_prompt(rng, common_len, cfg.vocab_size)
    suffixes = [make_prompt(rng, suffix_len, cfg.vocab_size)
                for _ in range(n_threads)]
    tails = [make_prompt(rng, max(4, gen_len // 2), cfg.vocab_size)
             for _ in range(n_threads)]

    def warm_compiles(eng):
        for n in (total, 32, max(4, gen_len // 2)):
            eng.generate(make_prompt(rng, n, cfg.vocab_size),
                         max_new_tokens=2)
        eng.warmup_kv_tier()

    def serve_first_turns(eng):
        outs = []
        for i, sfx in enumerate(suffixes):
            r = GenRequest(request_id=f"so-{i}", prompt_ids=common + sfx,
                           max_new_tokens=gen_len, prefix_key=f"so-t{i}")
            eng.submit(r)
            eng.run_to_completion()
            outs.append(list(r.output_ids))
        return outs

    def resume(eng, i, label, first_outputs):
        prompt = common + suffixes[i] + first_outputs[i] + tails[i]
        r = GenRequest(request_id=f"{label}-{i}", prompt_ids=prompt,
                       max_new_tokens=gen_len, prefix_key=f"so-t{i}")
        eng.submit(r)
        eng.run_to_completion()
        return r

    def ttft_ms(r):
        return round((r.first_token_time - r.submit_time) * 1e3, 2)

    # thread roles: [0] pre-outage wake, [1:-1] resumed DURING the
    # outage, [-1] resumed after the store comes back
    outage_ids = list(range(1, n_threads - 1))
    try:
        # ---- replica A: serve + drain to the store ------------------
        a_eng = mk(with_store=True)
        warm_compiles(a_eng)
        first_outputs = serve_first_turns(a_eng)
        sleep_stats = a_eng.sleep_to_object()
        del a_eng

        # ---- storeless baseline: fresh replica, pure re-prefill -----
        c_eng = mk(with_store=False)
        warm_compiles(c_eng)
        cold = [resume(c_eng, i, "cold", first_outputs)
                for i in range(n_threads)]
        baseline_ttft = [ttft_ms(cold[i]) for i in outage_ids]
        del c_eng

        # ---- replica B: wake, outage mid-run, recovery --------------
        b_eng = mk(with_store=True)
        warm_compiles(b_eng)
        obj = b_eng.kv_tier.object
        pre = resume(b_eng, 0, "pre", first_outputs)
        for site in ("kv.object_put", "kv.object_get", "kv.object_head"):
            fp_configure(site, "error")
        try:
            during = [resume(b_eng, i, "down", first_outputs)
                      for i in outage_ids]
        finally:
            for site in ("kv.object_put", "kv.object_get",
                         "kv.object_head"):
                fp_clear(site)
        state_during = obj.breaker_state()
        snap_during = obj.snapshot()
        outage_ttft = [ttft_ms(r) for r in during]
        # the store is back; let the open window elapse so the next
        # resume is the half-open probe
        time.sleep(open_window_s + 0.1)
        recovered = resume(b_eng, n_threads - 1, "rec", first_outputs)
        snap_after = obj.snapshot()

        # ---- never-slept reference: token-exactness -----------------
        ref_eng = mk(with_store=False)
        ref_first = serve_first_turns(ref_eng)
        ref = [resume(ref_eng, i, "ref", first_outputs)
               for i in range(n_threads)]
        del ref_eng
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if own_dir:
            shutil.rmtree(object_dir, ignore_errors=True)

    base_p99 = max(baseline_ttft)
    out_p99 = max(outage_ttft)
    # "within noise": the outage resumes pay exactly the baseline's
    # re-prefill (store ops fast-fail / are negatively cached), so p99
    # stays inside a generous CPU-jitter envelope of the baseline
    contained = out_p99 <= base_p99 * 3.0 + 100.0
    attainment_during = sum(
        1 for t in outage_ttft if t <= base_p99 * 3.0 + 100.0
    ) / max(1, len(outage_ttft))
    outputs_match = (
        ref_first == first_outputs
        and list(pre.output_ids) == list(ref[0].output_ids)
        and all(list(during[j].output_ids)
                == list(ref[outage_ids[j]].output_ids)
                for j in range(len(outage_ids)))
        and list(recovered.output_ids)
        == list(ref[n_threads - 1].output_ids)
        and all(list(cold[i].output_ids) == list(ref[i].output_ids)
                for i in range(n_threads))
    )
    return {
        "n_threads": n_threads,
        "sleep": sleep_stats,
        "pre_outage_cache_source": pre.cache_source,
        "breaker_opened": snap_during["store_breaker_opens"] >= 1,
        "breaker_state_during": state_during,
        "breaker_state_after": snap_after["store_breaker_state"],
        "probe_neg_cached": snap_after["store_probe_neg_cached"],
        "ttft_p99_ms": {"baseline_reprefill": base_p99,
                        "store_down": out_p99},
        "outage_ttft_ms": outage_ttft,
        "baseline_ttft_ms": baseline_ttft,
        "contained": contained,
        "attainment_during_outage": round(attainment_during, 3),
        "outage_cache_sources": [r.cache_source for r in during],
        "recovered_cache_source": recovered.cache_source,
        "recovered_object_tokens": recovered.object_tokens,
        "outputs_match": outputs_match,
        "note": ("store killed mid-run via kv.object_* failpoint storm: "
                 "breaker opens after the trip threshold, every resume "
                 "completes as a baseline-latency re-prefill (no store "
                 "stall), and after the store returns the half-open "
                 "probe closes the breaker — the last thread wakes from "
                 "its sleep manifest, token-exact"),
    }


def disagg_phase(cfg, params, n_chatty: int = 4, n_long: int = 4,
                 chatty_prompt: int = 48, chatty_gen: int = 96,
                 long_prompt: int = 1025, long_gen: int = 8,
                 page_size: int = 16, seed: int = 31,
                 min_prefill_tokens: int = 128,
                 stagger_steps: int = 8) -> dict:
    """Disaggregated prefill/decode A/B (ISSUE 12): mixed open-loop
    traffic — chatty decode threads streaming tokens while long-prefill
    threads keep arriving — on dp=2 colocated vs ``prefill:1,decode:1``.

    The TPOT-p99 killer under test: a long prompt admitted next to
    decode lanes steals one prefill chunk's compute from them every
    scheduler iteration until it finishes.  Colocated, every replica
    serves mixed traffic, so chatty lanes eat that stall; disaggregated,
    long prompts prefill on the prefill replica and their KV pages ship
    to the decode replica at first-token time, so decode lanes never
    share an iteration with a long chunk.  multi_step is pinned to 1 so
    the inter-token gap measures scheduler interleaving, not fusion
    cadence.

    Reports decode-lane TPOT p99 (client-observed inter-token gaps),
    TTFT p99 for both classes, ship MB/s, the shipped-thread
    zero-re-prefill proof (cache_source="shipped", 0 prompt tokens
    recomputed beyond the mandatory boundary token), and
    slo_attainment/goodput from the PR 10 plane.  Outputs are asserted
    token-identical between the two configurations (greedy) — the
    acceptance criterion for the split changing WHERE work runs, never
    WHAT it computes.
    """
    import jax as _jax

    from kafka_tpu.runtime import EngineConfig, GenRequest
    from kafka_tpu.runtime.dp_router import DataParallelEngines
    from kafka_tpu.runtime.metrics import EngineMetrics

    rng = random.Random(seed)
    win_pages = max(
        4, -(-(long_prompt + long_gen + 2 * page_size) // page_size)
    )
    ecfg = EngineConfig(
        max_batch=max(2, n_chatty),
        page_size=page_size,
        max_pages_per_seq=win_pages,
        num_pages=(n_chatty + 2 * n_long + 2) * win_pages // 2 + 8,
        # bucket cap = chunk size: long prompts prefill in repeated
        # 256-token chunks, the interleaved shape whose per-chunk stalls
        # are the decode-lane interference under test (a single
        # whole-prompt bucket would collapse the A/B into one stall)
        prefill_buckets=(16, 64, 256),
        multi_step=1,
        # prompt emission on both sides: the default 150ms fetch-age
        # bound paces 3+-stream replicas differently than 2-stream ones
        # (the adaptive tightening engages only at <=2), which would
        # compare emission cadence, not scheduler interference
        fetch_wait_s=0.01,
    )
    chatty_prompts = [make_prompt(rng, chatty_prompt, cfg.vocab_size)
                      for _ in range(n_chatty)]
    long_prompts = [make_prompt(rng, long_prompt, cfg.vocab_size)
                   for _ in range(n_long)]

    def run(roles) -> dict:
        dp = DataParallelEngines(
            cfg, params, ecfg, dp=2, tp=1,
            dp_roles=roles, disagg_min_prefill_tokens=min_prefill_tokens,
        )
        # Compile EVERYTHING the measured run dispatches, outside it (the
        # classic bench pollution — a mid-measurement XLA compile reads
        # as a 100ms+ inter-token gap and buries the effect under test):
        # the long bucket, the 1-token resume-suffix bucket, the batched
        # prefill at the admission-storm widths (4-wide disagg decode
        # pool, 2-wide colocated spread), decode, and the ship programs.
        for n, e in enumerate(dp.engines):
            for j, blen in enumerate((long_prompt, max(4, page_size // 2))):
                e.submit(GenRequest(request_id=f"__w{n}_{j}",
                                    prompt_ids=[3] * blen,
                                    max_new_tokens=2))
                e.run_to_completion()
            for width in (2, 4):
                for i in range(width):
                    e.submit(GenRequest(request_id=f"__wb{n}_{width}_{i}",
                                        prompt_ids=[3 + i] * chatty_prompt,
                                        max_new_tokens=2))
                e.run_to_completion()
        dp.warmup_disagg()
        for e in dp.engines:
            e.metrics = EngineMetrics()
        chatty = [
            GenRequest(request_id=f"c{i}", prompt_ids=list(p),
                       max_new_tokens=chatty_gen, prefix_key=f"chat-{i}")
            for i, p in enumerate(chatty_prompts)
        ]
        longs = [
            GenRequest(request_id=f"l{i}", prompt_ids=list(p),
                       max_new_tokens=long_gen, prefix_key=f"long-{i}")
            for i, p in enumerate(long_prompts)
        ]
        # Per-replica step-time intervals, for the host-serialization
        # correction below: on real accelerators e.step() is an async
        # enqueue (~0 wall), but the CPU backend dispatches
        # SYNCHRONOUSLY, so one router thread driving dp replicas
        # serializes every replica's chunk compute into every other
        # replica's cadence — a 1-core emulation artifact the
        # disaggregation cannot (and on TPU need not) remove.  Each
        # decode-lane gap is therefore also reported net of time the
        # router spent inside OTHER replicas' steps: the decode
        # replica's own serialized timeline, i.e. what a
        # parallel-device host observes.  Ship/handoff time runs
        # outside any e.step() and stays charged to every gap — the
        # true cost of disaggregation is never subtracted.
        intervals: list = []
        for i, e in enumerate(dp.engines):
            def _wrap(orig, idx):
                def stepper():
                    t0 = time.monotonic()
                    try:
                        return orig()
                    finally:
                        intervals.append((t0, time.monotonic(), idx))
                return stepper
            e.step = _wrap(e.step, i)
        for r in chatty:
            dp.submit(r)
        homes = {r.request_id: dp._route[r.request_id] for r in chatty}
        # open loop: long prompts keep arriving every `stagger_steps`
        # scheduler iterations regardless of progress (arrival process,
        # not closed-loop backpressure)
        t_tok: dict = {r.request_id: [] for r in chatty}
        pending = list(longs)
        steps = 0
        warm_steps = 12  # let the decode lanes reach steady cadence
        while dp.has_work or pending:
            if pending and steps >= warm_steps and (
                (steps - warm_steps) % stagger_steps == 0
            ):
                dp.submit(pending.pop(0))
            evs = dp.step()
            now = time.monotonic()
            for ev in evs:
                if ev.token_id is not None and ev.request_id in t_tok:
                    t_tok[ev.request_id].append(now)
            steps += 1
        gaps = [
            b - a
            for times in t_tok.values()
            for a, b in zip(times, times[1:])
        ]

        def _other_replica_time(a: float, b: float, home: int) -> float:
            return sum(
                min(b, t1) - max(a, t0)
                for t0, t1, i in intervals
                if i != home and t1 > a and t0 < b
            )

        net_gaps = [
            max(0.0, (b - a) - _other_replica_time(a, b, homes[rid]))
            for rid, times in t_tok.items()
            for a, b in zip(times, times[1:])
        ]
        shipped = [r for r in longs if r.cache_source == "shipped"]
        recomputed = [
            max(0, (len(r.prompt_ids) - 1) - r.cached_tokens)
            for r in shipped
        ]
        disagg = dp.disagg.snapshot()
        ship_s = disagg["ship_ms"]["sum"] / 1e3
        out = {
            "tpot_ms": percentiles_ms(gaps),
            "tpot_net_ms": percentiles_ms(net_gaps),
            "chatty_ttft_ms": percentiles_ms(
                [r.first_token_time - r.submit_time for r in chatty]
            ),
            "long_ttft_ms": percentiles_ms(
                [r.first_token_time - r.submit_time for r in longs]
            ),
            "shipped_threads": len(shipped),
            "shipped_runs": disagg["disagg_shipped_runs"],
            "shipped_pages": disagg["disagg_shipped_pages"],
            "ship_mb_s": round(
                disagg["disagg_shipped_bytes"] / ship_s / 1e6, 1
            ) if ship_s > 0 else None,
            "ship_failures": disagg["disagg_ship_failures"],
            "prefill_tokens_recomputed": sum(recomputed),
            "long_cache_sources": sorted(
                {r.cache_source or "none" for r in longs}
            ),
            "outputs": {
                r.request_id: list(r.output_ids) for r in chatty + longs
            },
            "slo": phase_slo(dp),
        }
        del dp
        return out

    disagg = run("prefill:1,decode:1")
    base = run(None)
    assert disagg["outputs"] == base["outputs"], \
        "disaggregation changed generated tokens"
    assert disagg["shipped_threads"] == len(long_prompts), \
        f"expected every long thread shipped: {disagg['long_cache_sources']}"
    assert disagg["prefill_tokens_recomputed"] == 0, \
        "shipped threads re-prefilled prompt tokens on the decode pool"
    assert (
        disagg["tpot_net_ms"]["p99"] < base["tpot_net_ms"]["p99"]
    ), (
        "decode-lane TPOT p99 under concurrent long prefill must be "
        f"strictly better disaggregated ({disagg['tpot_net_ms']['p99']}ms)"
        f" than colocated ({base['tpot_net_ms']['p99']}ms)"
    )
    speedup = (
        round(base["tpot_net_ms"]["p99"] / disagg["tpot_net_ms"]["p99"], 2)
        if disagg["tpot_net_ms"]["p99"] else None
    )
    return {
        # headline: the host-serialization-corrected figure (identical
        # to raw on async-dispatch accelerators; on the CPU backend it
        # removes only the one-thread-drives-every-replica emulation
        # artifact, never the ship/hand-off cost)
        "decode_tpot_p99_ms": {
            "colocated": base["tpot_net_ms"]["p99"],
            "disaggregated": disagg["tpot_net_ms"]["p99"],
            "improvement": speedup,
        },
        "decode_tpot_ms": {"colocated": base["tpot_net_ms"],
                           "disaggregated": disagg["tpot_net_ms"]},
        "decode_tpot_raw_wall_ms": {"colocated": base["tpot_ms"],
                                    "disaggregated": disagg["tpot_ms"]},
        "chatty_ttft_p99_ms": {
            "colocated": base["chatty_ttft_ms"]["p99"],
            "disaggregated": disagg["chatty_ttft_ms"]["p99"],
        },
        "long_ttft_p99_ms": {
            "colocated": base["long_ttft_ms"]["p99"],
            "disaggregated": disagg["long_ttft_ms"]["p99"],
        },
        "shipped_runs": disagg["shipped_runs"],
        "shipped_pages": disagg["shipped_pages"],
        "ship_mb_s": disagg["ship_mb_s"],
        "ship_failures": disagg["ship_failures"],
        "prefill_tokens_recomputed": disagg["prefill_tokens_recomputed"],
        "slo": {"colocated": base["slo"], "disaggregated": disagg["slo"]},
        "note": ("mixed open-loop traffic on dp=2: chatty decode lanes + "
                 "staggered long-prefill arrivals, colocated vs "
                 "prefill:1,decode:1 (outputs token-identical; shipped "
                 "threads admit with cache_source='shipped' and zero "
                 "prompt re-prefill on the decode pool)"),
    }


def zero_copy_phase(cfg, params, n_long: int = 2, long_prompt: int = 257,
                    long_gen: int = 4, n_groups: int = 2,
                    c_len: int = 96, m_len: int = 48, x_len: int = 16,
                    gen_len: int = 8, page_size: int = 8, seed: int = 47,
                    min_prefill_tokens: int = 64,
                    store_delay_s: float = 0.1) -> dict:
    """Zero-host-copy movement A/Bs (ISSUE 19), two independent proofs:

    * **ship transport** (needs >= 2 devices): the same disaggregated
      hand-off workload under ``KAFKA_TPU_SHIP_TRANSPORT=host`` vs
      ``device`` — outputs must be token-identical (the transport moves
      the SAME bytes, only the route changes), the device run's ship
      counters must show zero host-staged runs and a zero staging-bytes
      peak (the "no numpy materialization" proof), and both report ship
      MB/s.
    * **wake prefetch**: threads slept to the object store wake on a
      fresh router with every ``kv.object_get`` delayed
      ``store_delay_s`` (the injected store RTT).  Each woken thread's
      sleep manifest spans THREE runs (its first turn diverged from two
      siblings at two radix depths, so its path is three nodes);
      prefetch-on stages all of them in parallel at submit, prefetch-off
      pays one RTT per run serially inside admission.  Reports the
      wake-TTFT A/B and asserts speedup >= 1.5x with 0 coverable prompt
      tokens recomputed and outputs token-identical across the modes.
    """
    import os as _os
    import shutil
    import tempfile

    import jax as _jax

    from kafka_tpu import failpoints
    from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine
    from kafka_tpu.runtime.dp_router import DataParallelEngines
    from kafka_tpu.runtime.kv_tier import ENV_SHIP_TRANSPORT
    from kafka_tpu.runtime.metrics import EngineMetrics
    from kafka_tpu.runtime.object_tier import ENV_WAKE_PREFETCH_MB

    rng = random.Random(seed)
    out: dict = {}

    # ---- part 1: ship-bandwidth A/B, host vs device transport -----------
    if len(_jax.devices()) >= 2:
        win_pages = max(
            4, -(-(long_prompt + long_gen + 2 * page_size) // page_size)
        )
        ecfg = EngineConfig(
            max_batch=2, page_size=page_size,
            max_pages_per_seq=win_pages,
            num_pages=(2 * n_long + 2) * win_pages + 8,
            prefill_buckets=(16, 64, 256),
            multi_step=1,
        )
        long_prompts = [make_prompt(rng, long_prompt, cfg.vocab_size)
                        for _ in range(n_long)]

        def run_ship(transport: str) -> dict:
            _os.environ[ENV_SHIP_TRANSPORT] = transport
            try:
                dp = DataParallelEngines(
                    cfg, params, ecfg, dp=2, tp=1,
                    dp_roles="prefill:1,decode:1",
                    disagg_min_prefill_tokens=min_prefill_tokens,
                )
                for n, e in enumerate(dp.engines):
                    e.submit(GenRequest(request_id=f"__w{n}",
                                        prompt_ids=[3] * long_prompt,
                                        max_new_tokens=2))
                    e.run_to_completion()
                dp.warmup_disagg()
                for e in dp.engines:
                    e.metrics = EngineMetrics()
                dp.disagg.snapshot()  # re-arm the staging-peak gauge
                reqs = [
                    GenRequest(request_id=f"zc-{transport}-{i}",
                               prompt_ids=list(p), max_new_tokens=long_gen,
                               prefix_key=f"zc-{i}")
                    for i, p in enumerate(long_prompts)
                ]
                for r in reqs:
                    dp.submit(r)
                dp.run_to_completion()
                snap = dp.disagg.snapshot()
                ship_s = snap["ship_ms"]["sum"] / 1e3
                res = {
                    "shipped_runs": snap["disagg_shipped_runs"],
                    "shipped_pages": snap["disagg_shipped_pages"],
                    "shipped_bytes": snap["disagg_shipped_bytes"],
                    "host_runs": snap["disagg_ship_host_runs"],
                    "device_runs": snap["disagg_ship_device_runs"],
                    "staging_peak_bytes": snap["disagg_ship_staging_bytes"],
                    "ship_mb_s": round(
                        snap["disagg_shipped_bytes"] / ship_s / 1e6, 1
                    ) if ship_s > 0 else None,
                    "outputs": {r.request_id.split("-", 1)[1].split("-")[1]:
                                list(r.output_ids) for r in reqs},
                    "cache_sources": sorted(
                        {r.cache_source or "none" for r in reqs}),
                }
                del dp
                return res
            finally:
                _os.environ.pop(ENV_SHIP_TRANSPORT, None)

        host = run_ship("host")
        device = run_ship("device")
        assert host["outputs"] == device["outputs"], \
            "ship transport changed generated tokens"
        assert device["shipped_runs"] > 0, "nothing shipped"
        assert device["device_runs"] == device["shipped_runs"], \
            "device-transport run shipped through the host path"
        assert device["host_runs"] == 0 and \
            device["staging_peak_bytes"] == 0, \
            "device-transport run materialized host staging bytes"
        assert host["host_runs"] == host["shipped_runs"], \
            "host-transport run used the device path"
        out["ship_transport"] = {
            "ship_mb_s": {"host": host["ship_mb_s"],
                          "device": device["ship_mb_s"]},
            "shipped_runs": device["shipped_runs"],
            "shipped_pages": device["shipped_pages"],
            "shipped_bytes": device["shipped_bytes"],
            "host_staging_peak_bytes": host["staging_peak_bytes"],
            "device_staging_peak_bytes": device["staging_peak_bytes"],
            "outputs_match": True,
            "note": ("same hand-off workload, host-staged vs "
                     "device-to-device ship; token-identical outputs, "
                     "device run asserted zero host staging"),
        }
    else:
        out["ship_transport"] = None

    # ---- part 2: wake-TTFT A/B, prefetch on vs off ----------------------
    # Per-group thread family: thread `a` (the one woken later) shares
    # c+m with sibling `b` and c alone with sibling `c`, so after the
    # first turns its radix path is three nodes — and its sleep manifest
    # three runs.  Groups share nothing with each other: every wake
    # fetches all three of its runs from the store (no cross-wake local
    # radix reuse quietly shrinking the off-path's serial RTT bill).
    object_dir = tempfile.mkdtemp(prefix="kafka-kv-zerocopy-")
    total = c_len + m_len + x_len + 2 * gen_len
    wake_win = max(4, -(-(total + 2 * page_size) // page_size))

    def mk_cfg():
        return EngineConfig(
            max_batch=1, page_size=page_size,
            max_pages_per_seq=wake_win,
            num_pages=(3 * n_groups + 3) * wake_win + 2,
            prefill_buckets=(16, 64, 256, 512),
            kv_host_tier_mb=256,
            kv_object_dir=object_dir,
        )

    groups = [
        {
            "c": make_prompt(rng, c_len, cfg.vocab_size),
            "m": make_prompt(rng, m_len, cfg.vocab_size),
            "xa": make_prompt(rng, x_len, cfg.vocab_size),
            "xb": make_prompt(rng, x_len, cfg.vocab_size),
            "y": make_prompt(rng, x_len, cfg.vocab_size),
            "tail": make_prompt(rng, max(4, gen_len // 2), cfg.vocab_size),
        }
        for _ in range(n_groups)
    ]

    def warm_compiles(eng):
        for n in (total, c_len + x_len, max(4, gen_len // 2)):
            eng.generate(make_prompt(rng, n, cfg.vocab_size),
                         max_new_tokens=2)
        eng.warmup_kv_tier()

    a_eng = InferenceEngine(cfg, params, mk_cfg())
    warm_compiles(a_eng)
    first_outputs = []
    for i, g in enumerate(groups):
        # serve order a, b, c: each sibling splits thread a's radix path
        # one level deeper ([c+m+xa] -> [c+m][xa] -> [c][m][xa])
        turns = [("a", g["c"] + g["m"] + g["xa"]),
                 ("b", g["c"] + g["m"] + g["xb"]),
                 ("c", g["c"] + g["y"])]
        for name, prompt in turns:
            r = GenRequest(request_id=f"zcw-{i}{name}",
                           prompt_ids=list(prompt),
                           max_new_tokens=gen_len,
                           prefix_key=f"zc-{i}{name}")
            a_eng.submit(r)
            a_eng.run_to_completion()
            if name == "a":
                first_outputs.append(list(r.output_ids))
    a_eng.sleep_to_object()
    del a_eng

    ps = page_size

    def run_wake(prefetch_mb: int) -> dict:
        if prefetch_mb:
            _os.environ[ENV_WAKE_PREFETCH_MB] = str(prefetch_mb)
        try:
            dp = DataParallelEngines(cfg, params, mk_cfg(), dp=1, tp=1)
            eng = dp.engines[0]
            warm_compiles(eng)
            eng.metrics = EngineMetrics()
            rows = []
            failpoints.configure("kv.object_get", "delay",
                                 str(store_delay_s))
            try:
                for i, g in enumerate(groups):
                    prompt = (g["c"] + g["m"] + g["xa"]
                              + first_outputs[i] + g["tail"])
                    r = GenRequest(request_id=f"zcr-{prefetch_mb}-{i}",
                                   prompt_ids=prompt,
                                   max_new_tokens=gen_len,
                                   prefix_key=f"zc-{i}a")
                    dp.submit(r)
                    dp.run_to_completion()
                    rows.append(r)
            finally:
                failpoints.clear("kv.object_get")
            obj = eng.kv_tier.object
            recomputed = 0
            for i, r in enumerate(rows):
                stored = (c_len + m_len + x_len
                          + len(first_outputs[i]) - 1)
                coverable = min((stored // ps) * ps,
                                ((len(r.prompt_ids) - 1) // ps) * ps)
                recomputed += max(0, coverable - r.cached_tokens)
            res = {
                "ttft_ms": [round(
                    (r.first_token_time - r.submit_time) * 1e3, 2)
                    for r in rows],
                "cache_sources": [r.cache_source for r in rows],
                "outputs": [list(r.output_ids) for r in rows],
                "recomputed": recomputed,
                "prefetch_hits": obj.prefetch_hits,
                "prefetch_wasted": obj.prefetch_wasted,
            }
            del dp
            return res
        finally:
            _os.environ.pop(ENV_WAKE_PREFETCH_MB, None)

    off = run_wake(0)
    on = run_wake(64)
    shutil.rmtree(object_dir, ignore_errors=True)
    assert on["outputs"] == off["outputs"], \
        "wake prefetch changed generated tokens"
    assert on["recomputed"] == 0, \
        f"prefetch-on wake recomputed {on['recomputed']} prompt tokens"
    assert on["prefetch_hits"] >= 2 * n_groups, \
        f"expected staged-run consumption: hits={on['prefetch_hits']}"
    on_ms = statistics.median(on["ttft_ms"])
    off_ms = statistics.median(off["ttft_ms"])
    assert on_ms > 0 and off_ms / on_ms >= 1.5, (
        f"prefetch-on wake TTFT must be >= 1.5x better under injected "
        f"store RTT: off {off_ms}ms vs on {on_ms}ms"
    )
    out["wake_prefetch"] = {
        "store_delay_ms": round(store_delay_s * 1e3, 1),
        "wake_ttft_ms": {"prefetch_off": off["ttft_ms"],
                         "prefetch_on": on["ttft_ms"]},
        "wake_ttft_p50_ms": {"prefetch_off": round(off_ms, 2),
                             "prefetch_on": round(on_ms, 2)},
        "speedup": round(off_ms / on_ms, 2) if on_ms else None,
        "prefetch_hits": on["prefetch_hits"],
        "prefetch_wasted": on["prefetch_wasted"],
        "prompt_tokens_recomputed": on["recomputed"],
        "cache_sources": on["cache_sources"],
        "outputs_match": True,
        "note": ("threads with three-run sleep manifests wake on a fresh "
                 "router with every kv.object_get delayed; prefetch-on "
                 "stages all runs in parallel at submit, prefetch-off "
                 "pays one RTT per run serially inside admission"),
    }
    return out


def traffic_ramp_phase(cfg, params, n_warm: int = 3, n_ramp: int = 12,
                       n_post: int = 5, prompt_len: int = 32,
                       gen_len: int = 28, page_size: int = 8,
                       seed: int = 23, poll_every_steps: int = 8,
                       max_steps: int = 20000) -> dict:
    """Open-loop traffic ramp with the autoscaler loop CLOSED (ISSUE 13)
    — the ROADMAP's missing proof that the control loop reacts mid-run.

    Timeline: a warm trickle establishes the served TTFT baseline (the
    SLO target is set at 3x its median, so the target scales with the
    host instead of hard-coding a wall-clock number); then an open-loop
    burst arrives faster than one replica can serve — the queue deepens,
    TTFT blows through the target, and 1m window attainment collapses.
    The controller (act mode, polled at the driver's cadence — the bench
    drives the loop inline so the single-writer engine rule holds)
    observes the collapse through the REAL provider signals contract and
    scales dp 1 -> 2 through the real rebuild seam: queued requests ride
    through the rebuild and the post-ramp arrivals meet the target
    again.  Reported: the decision trace, the attainment timeline the
    controller saw, and per-arrival-segment attainment computed from
    client-observed TTFT (warm / ramp / post-action) — the recovery
    proof is post > ramp.

    The rebuild's XLA compile stall on the fresh replicas is charged to
    whatever is queued when it happens (honest: that is what a real
    scale-out costs) — the post-action segment starts only after the
    resize returns, so its attainment measures the new topology, not
    the transition."""
    import jax as _jax

    from kafka_tpu.llm.tpu_provider import TPULLMProvider
    from kafka_tpu.runtime import EngineConfig, GenRequest
    from kafka_tpu.runtime.autoscaler import (
        SCALE_OUT,
        AutoscalerConfig,
        AutoscalerController,
    )
    from kafka_tpu.runtime.dp_router import DataParallelEngines
    from kafka_tpu.runtime.metrics import EngineMetrics, configure_slo

    if len(_jax.devices()) < 2:
        return {"skipped": "traffic_ramp needs >= 2 devices for the "
                           "dp 1 -> 2 scale-out"}

    rng = random.Random(seed)
    win_pages = max(4, -(-(prompt_len + gen_len + 2 * page_size)
                         // page_size))
    ecfg = EngineConfig(
        max_batch=2,
        page_size=page_size,
        max_pages_per_seq=win_pages,
        num_pages=(n_warm + n_ramp + n_post + 2) * win_pages + 8,
        prefill_buckets=(16, max(32, prompt_len)),
        multi_step=1,
        fetch_wait_s=0.01,
        # parked off-slot prefill hides queue wait from TTFT until
        # max_parked exhausts — at production scale the ramp exhausts
        # it, at smoke scale disabling it reaches the same overload
        # regime (queue wait surfaces in TTFT) with 10 requests
        max_parked=0,
    )
    dp = DataParallelEngines(cfg, params, ecfg, dp=1, tp=1)

    class _SignalShim:
        """The provider's signals()/replica surface over a bare router —
        the bench drives engines directly (no worker thread), but the
        controller must consume the REAL /admin/signals contract."""

        autoscaler = None

        def __init__(self, router):
            self.engine = router

        _replicas = TPULLMProvider._replicas
        signals = TPULLMProvider.signals

    # -- compile everything the measured run dispatches, outside it ----
    e0 = dp.engines[0]
    for j, blen in enumerate((prompt_len, 8)):
        e0.submit(GenRequest(request_id=f"__w{j}", prompt_ids=[3] * blen,
                             max_new_tokens=2))
        e0.run_to_completion()
    for i in range(2):
        e0.submit(GenRequest(request_id=f"__wb{i}",
                             prompt_ids=[3 + i] * prompt_len,
                             max_new_tokens=3))
    e0.run_to_completion()

    # -- SLO target: 3x the warm-path TTFT median ----------------------
    probe_ttfts = []
    for i in range(2):
        r = GenRequest(request_id=f"__p{i}",
                       prompt_ids=make_prompt(rng, prompt_len,
                                              cfg.vocab_size),
                       max_new_tokens=4)
        e0.submit(r)
        e0.run_to_completion()
        probe_ttfts.append(r.first_token_time - r.submit_time)
    target_s = max(0.02, 3.0 * statistics.median(probe_ttfts))
    configure_slo(ttft_ms=target_s * 1e3)
    for e in dp.engines:
        e.metrics = EngineMetrics()

    shim = _SignalShim(dp)
    events_sink: list = []

    def started(e) -> bool:
        return bool(e.num_active or e.parked or e._pending or e.handoffs)

    resize_log: list = []

    def resize_fn(dp_target, roles):
        # the provider's resize_dp drains started lanes with the worker
        # parked; the bench driver IS the single writer, so the same
        # drain runs inline at step cadence — waiting requests ride
        # through the rebuild untouched, exactly the serving-path
        # semantics
        deadline = time.monotonic() + 60.0
        while any(started(e) for e in dp.engines):
            events_sink.extend(dp.step())
            if time.monotonic() > deadline:
                raise RuntimeError("ramp resize drain did not converge")
        dp.rebuild(dp=dp_target)
        # warm the fresh engines the way server boot warmup does (the
        # rebuild built cold engines; an XLA compile mid-serving would
        # charge the transition cost to the post-action segment and
        # measure the compiler, not the topology).  run_to_completion
        # also serves the queued ramp backlog that rode through the
        # rebuild — those verdicts stay in the ramp segment, where the
        # overload that delayed them belongs.
        for n, e in enumerate(dp.engines):
            for i in range(2):
                e.submit(GenRequest(
                    request_id=f"__rw{n}_{i}",
                    prompt_ids=[3 + i] * prompt_len, max_new_tokens=3,
                ))
        dp.run_to_completion()
        resize_log.append({"dp": dp_target, "t": time.monotonic()})
        return True

    acfg = AutoscalerConfig(
        mode="act", interval_s=0.05, min_dp=1, max_dp=2,
        attain_out=0.9, attain_in=0.98, trend_out=0.5,
        sustain_out=2, sustain_in=10 ** 6,   # no scale-in mid-phase
        cooldown_out_s=120.0, cooldown_in_s=10 ** 6,
        ladder_cooldown_s=10 ** 6, min_window_requests=2,
    )
    ctl = AutoscalerController(shim, acfg, resize_fn=resize_fn)

    # -- arrival schedule (open loop, step-indexed) --------------------
    def mk(i, seg):
        return GenRequest(
            request_id=f"{seg}{i}",
            prompt_ids=make_prompt(rng, prompt_len, cfg.vocab_size),
            max_new_tokens=gen_len,
        ), seg

    ramp_start = 12 * n_warm + 6
    schedule = {}
    for i in range(n_warm):
        schedule[12 * i] = mk(i, "warm")
    for i in range(n_ramp):
        # one arrival per scheduler step: an open-loop burst well past
        # one replica's service rate, so queue wait (not service time)
        # dominates the late arrivals' TTFT
        schedule[ramp_start + i] = mk(i, "ramp")

    reqs: list = []
    timeline: list = []
    step = 0
    post_scheduled = False
    from kafka_tpu.runtime.engine import AdmissionError

    while step < max_steps:
        if step in schedule:
            req, seg = schedule.pop(step)
            try:
                dp.submit(req)
                reqs.append((req, seg))
            except AdmissionError:
                # ladder rung 1 tightened the bound mid-phase: shed
                # arrivals are part of the story, count them as missed
                reqs.append((req, seg))
        if dp.has_work:
            events_sink.extend(dp.step())
        step += 1
        if step >= ramp_start and step % poll_every_steps == 0:
            d = ctl.poll_once()
            timeline.append({
                "step": step,
                "dp": len(dp.engines),
                "action": d.action,
                "cause": d.cause,
                "attainment_1m": d.inputs.get("attainment_1m"),
                "queue_depth": d.inputs.get("queue_depth"),
            })
        if resize_log and not post_scheduled:
            post_scheduled = True
            for i in range(n_post):
                schedule[step + 4 + 18 * i] = mk(i, "post")
        if not schedule and not dp.has_work:
            break

    def seg_attain(seg):
        rows = [r for r, s in reqs if s == seg]
        met = [
            r for r in rows
            if r.first_token_time is not None
            and (r.first_token_time - r.submit_time) <= target_s
        ]
        return (round(len(met) / len(rows), 3) if rows else None,
                len(rows))

    warm_a, warm_n = seg_attain("warm")
    ramp_a, ramp_n = seg_attain("ramp")
    post_a, post_n = seg_attain("post")
    acted = ctl.counters["autoscaler_scale_outs"] >= 1
    decisions = [
        {k: v for k, v in e.items() if k != "inputs"}
        for e in ctl.snapshot()["decisions"]
    ]
    out = {
        "acted": acted,
        "dp": {"before": 1, "after": len(dp.engines)},
        "resizes": ctl.counters["autoscaler_scale_outs"],
        "slo_ttft_target_ms": round(target_s * 1e3, 1),
        "attainment_by_segment": {
            "warm": {"attainment": warm_a, "requests": warm_n},
            "ramp_overload": {"attainment": ramp_a, "requests": ramp_n},
            "post_action": {"attainment": post_a, "requests": post_n},
        },
        "final_signals_attainment_1m": (
            timeline[-1]["attainment_1m"] if timeline else None
        ),
        "ladder_final": ctl.state.ladder,
        "decisions": decisions,
        "timeline": timeline,
        "note": ("open-loop ramp on dp=1, act-mode controller polled at "
                 "driver cadence; scale-out through the real rebuild "
                 "seam; segment attainment from client-observed TTFT "
                 "vs a 3x-warm-median target"),
    }
    assert acted, f"controller never scaled out: {decisions}"
    assert ctl.counters["autoscaler_scale_outs"] == 1, \
        "more than one resize within the cooldown window"
    assert len(dp.engines) == 2
    if post_a is not None and ramp_a is not None:
        assert post_a > ramp_a, (
            f"attainment did not recover after the controller acted "
            f"(ramp {ramp_a} -> post {post_a})"
        )
    return out


def serving_phase(cfg, params, args, quick: bool):
    """Measure the SERVED path end to end: real aiohttp app, real SSE
    clients, agent loop + constrained tool calls (VERDICT r3 next #1;
    BASELINE configs 3-4 name this surface, not the raw engine).

    Boots create_app around a fresh engine sharing `params`, drives N
    concurrent SSE clients through POST /v1/threads/{id}/chat/completions
    (two turns per thread: turn 2 replays history through the thread store
    and hits the thread-keyed prefix cache), then M concurrent agent runs
    through POST /v1/agent/run with a scripted tool and a FORCED tool call
    (constrained JSON decode in the sampler).  All latencies are measured
    at the HTTP client — they include tokenization, the worker handoff,
    the agent loop, SSE encoding, and aiohttp, unlike the engine-only
    phases above (reference serve path: server.py:384-411).
    """
    import asyncio
    import tempfile

    async def run():
        import aiohttp
        from aiohttp import web

        from kafka_tpu.llm.tpu_provider import TPULLMProvider
        from kafka_tpu.models.tokenizer import ByteTokenizer
        from kafka_tpu.runtime import EngineConfig, InferenceEngine
        from kafka_tpu.runtime.metrics import EngineMetrics
        from kafka_tpu.server import ServingConfig, create_app
        from kafka_tpu.tools import Tool

        n_threads = 4 if quick else 32
        n_agents = 2 if quick else 8
        gen_len = 8 if quick else 32
        # window 1536: system prompt + tool defs run ~700 byte-tokens, and
        # turn 2 replays the whole turn-1 conversation on top
        ecfg = EngineConfig(
            max_batch=args.batch,
            page_size=16,
            max_pages_per_seq=96,
            prefill_buckets=(64, 256, 512),
        )
        ecfg.num_pages = 3 * args.batch * ecfg.max_pages_per_seq + 1
        engine = InferenceEngine(cfg, params, ecfg)
        tokenizer = ByteTokenizer(vocab_size=cfg.vocab_size)
        provider = TPULLMProvider(engine, tokenizer, model_name=cfg.name)

        def lookup(city: str):
            return {"city": city, "population": 1234567, "weather": "sunny"}

        tmp = tempfile.mkdtemp(prefix="kafka_bench_")
        scfg = ServingConfig(
            model_name=cfg.name,
            db_path=f"{tmp}/threads.db",
            system_prompt="You are a concise assistant. Answer briefly.",
            warmup=False,  # warmed explicitly below, then metrics reset
        )
        app = await create_app(
            cfg=scfg,
            llm_provider=provider,
            tools=[Tool(
                name="lookup",
                description="Look up basic facts about a city.",
                parameters={
                    "type": "object",
                    "properties": {"city": {"type": "string"}},
                    "required": ["city"],
                },
                handler=lookup,
            )],
            mcp_servers=[],
        )
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        base = f"http://127.0.0.1:{port}"
        out = {}
        try:
            async with aiohttp.ClientSession() as sess:
                async def turn(tid, content, gen):
                    """One streamed thread turn; returns (ttft, total)."""
                    t0 = time.monotonic()
                    ttft = None
                    url = f"{base}/v1/threads/{tid}/chat/completions"
                    async with sess.post(url, json={
                        "model": cfg.name, "stream": True,
                        "max_tokens": gen, "temperature": 0.0,
                        "messages": [{"role": "user", "content": content}],
                    }) as r:
                        assert r.status == 200, await r.text()
                        async for line in r.content:
                            if line.startswith(b'data: {"type":"error"'):
                                raise RuntimeError(
                                    f"served-path error: {line!r}")
                            if ttft is None and b'"content"' in line:
                                ttft = time.monotonic() - t0
                    return ttft, time.monotonic() - t0

                # warm: compile every serving program outside the measured
                # window.  TWO rounds per warm thread so both measured
                # shapes compile: round 1 = cold full prefill (large
                # buckets + batched prefill + fused decode), round 2 =
                # thread-history replay with a prefix-cache hit (small
                # suffix buckets) — r04's first TPU run had the suffix
                # bucket compiling inside measured turn 2 (42s p90).
                t0 = time.monotonic()
                for r in range(2):
                    await asyncio.gather(*(
                        turn(f"warm-{i}",
                             f"warm round {r} for client {i} padding",
                             gen_len)
                        for i in range(min(4, n_threads))
                    ))
                # SOLO turns: a lone prefilling lane takes the
                # single-sequence prefill program, which the concurrent
                # rounds never compile (uniform-length storms always group
                # into the batched program) — but a fragmented measured
                # storm does, and an uncompiled single-seq bucket once put
                # a ~60s XLA compile inside measured turn 1 (p90 17s)
                for r in range(2):
                    await turn("warm-solo",
                               f"solo warm turn {r} for the single path",
                               gen_len)
                log(f"serving warmup/compile: {time.monotonic() - t0:.1f}s")
                engine.metrics = EngineMetrics()

                # ---- server_path: 2 turns x n_threads concurrent SSE ----
                t0 = time.monotonic()
                r1 = await asyncio.gather(*(
                    turn(f"bench-t{i}",
                         f"hello from client {i}, tell me something",
                         gen_len)
                    for i in range(n_threads)
                ))
                wall1 = time.monotonic() - t0
                t0 = time.monotonic()
                r2 = await asyncio.gather(*(
                    turn(f"bench-t{i}", f"and a follow-up question {i}",
                         gen_len)
                    for i in range(n_threads)
                ))
                wall2 = time.monotonic() - t0
                snap = engine.metrics.snapshot(engine)
                out["server_path"] = {
                    "n_threads": n_threads,
                    "turns_per_thread": 2,
                    "gen_len": gen_len,
                    "req_per_s": round(2 * n_threads / (wall1 + wall2), 2),
                    "ttft_ms": percentiles_ms(
                        [t for t, _ in r1] + [t for t, _ in r2]),
                    "turn1_ttft_ms": percentiles_ms([t for t, _ in r1]),
                    "turn2_ttft_ms": percentiles_ms([t for t, _ in r2]),
                    "e2e_latency_ms": percentiles_ms(
                        [w for _, w in r1] + [w for _, w in r2]),
                    "engine_ttft_ms": snap["ttft_ms"],
                    # queue-wait / prefill / first-fetch phases per request
                    # (VERDICT r4 #5): scheduler work and link jitter stop
                    # being one confounded number
                    "engine_ttft_breakdown_ms": snap["ttft_breakdown_ms"],
                    "prefix_cache": snap.get("prefix_cache"),
                    "fetch_pipeline_waste_frac":
                        snap["tokens"]["fetch_pipeline_waste_frac"],
                    # read back from the SAME snapshot /metrics serves
                    # (ISSUE 10): SLO attainment + goodput next to tok/s
                    "slo_attainment": snap["slo"]["slo_attainment"],
                    "goodput_tok_s": snap["slo"]["goodput_tok_s"],
                    "slo_ttft_target_ms":
                        snap["slo"]["slo_ttft_target_ms"],
                    "note": ("client-observed over HTTP/SSE incl. "
                             "tokenization, agent loop, worker handoff, "
                             "aiohttp; turn 2 replays thread history "
                             "(prefix-cache hit)"),
                }
                log(f"server_path: {out['server_path']['req_per_s']} req/s, "
                    f"ttft p50 {out['server_path']['ttft_ms']['p50']} ms "
                    f"p90 {out['server_path']['ttft_ms']['p90']} ms")

                # ---- agent_path: forced tool call w/ constrained decode --
                async def agent_run(i):
                    t0 = time.monotonic()
                    first_tool = total = None
                    done_reason = None
                    async with sess.post(f"{base}/v1/agent/run", json={
                        "model": cfg.name, "max_tokens": 48,
                        "temperature": 0.0,
                        "messages": [{
                            "role": "user",
                            "content": f"look up city number {i}",
                        }],
                        "tool_choice": {"type": "function",
                                        "function": {"name": "lookup"}},
                    }) as r:
                        assert r.status == 200, await r.text()
                        async for line in r.content:
                            if line.startswith(b'data: {"type":"error"'):
                                raise RuntimeError(
                                    f"agent-path error: {line!r}")
                            if (first_tool is None
                                    and b'"tool_result"' in line):
                                first_tool = time.monotonic() - t0
                            if b'"agent_done"' in line:
                                m = json.loads(
                                    line.decode()[len("data: "):])
                                done_reason = m.get("reason")
                    total = time.monotonic() - t0
                    return first_tool, total, done_reason

                await agent_run(999)  # constrained-path warmup/compile
                rt0 = engine.metrics.constrained_roundtrips
                slo_probe = SloProbe(engine)
                t0 = time.monotonic()
                runs = await asyncio.gather(*(
                    agent_run(i) for i in range(n_agents)))
                wall = time.monotonic() - t0
                roundtrips = engine.metrics.constrained_roundtrips - rt0
                out["agent_path"] = {
                    "n_agents": n_agents,
                    "req_per_s": round(n_agents / wall, 2),
                    # awaited choice points per call: the on-prem latency
                    # projection is now roundtrips * RTT arithmetic, not
                    # assertion (forced-singleton tokens chain RTT-free)
                    "constrained_roundtrips_per_call": round(
                        roundtrips / n_agents, 1),
                    # on-device grammar FSM (KAFKA_TPU_GRAMMAR_ONDEVICE,
                    # default on): constrained lanes advance inside the
                    # jitted step, so roundtrips/call reads ~0 here
                    "grammar_ondevice": __import__(
                        "kafka_tpu.llm.constrained",
                        fromlist=["grammar_ondevice_enabled"],
                    ).grammar_ondevice_enabled(),
                    "rtt_est_ms": snap["engine"]["rtt_est_ms"],
                    "time_to_tool_result_ms": percentiles_ms(
                        [ft for ft, _, _ in runs]),
                    "e2e_latency_ms": percentiles_ms(
                        [t for _, t, _ in runs]),
                    "tool_result_seen": sum(
                        1 for ft, _, _ in runs if ft is not None),
                    "done_reasons": sorted(
                        {str(dr) for _, _, dr in runs}),
                    **slo_probe.report(),
                    "note": ("POST /v1/agent/run with tool_choice forcing "
                             "a scripted tool: constrained JSON decode in "
                             "the sampler -> tool execution -> free final "
                             "turn (BASELINE config 4 shape). Only genuine "
                             "choice points await a device->host round "
                             "trip (constrained_roundtrips_per_call x "
                             "rtt_est_ms of the e2e is link time; on-prem "
                             "ICI-attached serving pays ~1ms per trip)"),
                }
                log(f"agent_path: {out['agent_path']['req_per_s']} req/s, "
                    f"tool result p50 "
                    f"{out['agent_path']['time_to_tool_result_ms']['p50']}"
                    f" ms")
        finally:
            await runner.cleanup()
            await provider.aclose()
        return out

    return asyncio.run(run())


def scale_phase(args, base_cfg, base_params) -> dict:
    """Bigger-model headline numbers (VERDICT r3 next #4).

    * llama-3.2-1b int8: decode throughput AND greedy token match rate vs
      the bf16 engine (same weights — the shipped quality sanity check).
    * llama-3.2-3b bf16 and llama-3-8b int8: single-chip decode
      throughput.  8B bf16 is 16 GB and does NOT fit a v5e chip — int8
      weight-only (models/quant.py) is what makes the literal BASELINE
      metric ("tokens/sec/chip, Llama-3-8B") servable at all.  Throughput
      is weight-value independent, so the big models use constant-fill
      params (random-init of 8B on a tunneled chip costs ~8 minutes of
      pure RNG; quality is covered by the 1B match rate above).
    """
    import jax
    import jax.numpy as jnp

    from kafka_tpu.models import get_config, quantize_params
    from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine

    rng = random.Random(7)
    out = {}

    def mk_engine(cfg, params, batch=8, gen=128):
        ecfg = EngineConfig(
            max_batch=batch, page_size=16,
            max_pages_per_seq=max(2, -(-(args.prompt_len + gen + 16) // 16)),
        )
        ecfg.num_pages = batch * ecfg.max_pages_per_seq + 1
        return InferenceEngine(cfg, params, ecfg)

    def _shapes(cfg):
        from kafka_tpu.models import init_params

        return jax.eval_shape(
            lambda k: init_params(cfg, k), jax.random.PRNGKey(0)
        )

    def fill_params(cfg):
        """Constant-fill weights (throughput-only models): init_params'
        EXACT pytree via eval_shape (zero RNG/compute — random-init of 8B
        through the tunnel costs minutes), constant values."""
        return jax.tree.map(
            lambda sd: jnp.full(sd.shape, 0.01, sd.dtype), _shapes(cfg)
        )

    def fill_params_int8(cfg):
        """Constant-fill DIRECTLY in int8 QTensor form.

        quantize_params(fill_params(...)) would materialize the bf16 tree
        first — 16 GB for 8B, which is exactly what does not fit the chip
        (the reason int8 exists).  Throughput needs shapes, not values.
        """
        from kafka_tpu.models import QTensor
        from kafka_tpu.models.quant import _CONTRACT, _CONTRACT_MOE

        contract = dict(_CONTRACT)
        if cfg.is_moe:
            contract.update(_CONTRACT_MOE)

        def qt(sd, axes):
            sshape = tuple(
                1 if i in axes else d for i, d in enumerate(sd.shape)
            )
            return QTensor(q=jnp.ones(sd.shape, jnp.int8),
                           s=jnp.full(sshape, 0.01, jnp.float32))

        shapes = _shapes(cfg)
        layers = {
            name: qt(sd, contract[name]) if name in contract
            else jnp.full(sd.shape, 0.01, sd.dtype)
            for name, sd in shapes["layers"].items()
        }
        out = {
            "embed": qt(shapes["embed"], (1,)),
            "final_norm": jnp.ones(shapes["final_norm"].shape, jnp.bfloat16),
            "layers": layers,
        }
        if "lm_head" in shapes:
            out["lm_head"] = qt(shapes["lm_head"], (0,))
        return out

    def decode_tps(cfg, params, label, gen=128):
        eng = mk_engine(cfg, params, batch=8, gen=gen)
        t0 = time.monotonic()
        eng.generate(make_prompt(rng, args.prompt_len, cfg.vocab_size),
                     max_new_tokens=2)
        for i in range(4):
            eng.submit(GenRequest(
                request_id=f"w{label}{i}",
                prompt_ids=make_prompt(rng, args.prompt_len, cfg.vocab_size),
                max_new_tokens=eng.ecfg.multi_step + 4))
        eng.run_to_completion()
        log(f"{label} compile: {time.monotonic() - t0:.1f}s")
        tps, sps = decode_phase(eng, cfg, 8, args.prompt_len, gen, rng)
        pb = param_bytes(params)
        ctx = args.prompt_len + gen // 2
        gbs = hbm_traffic_per_step(eng, pb, 8, ctx) * sps / 1e9
        del eng
        return tps, sps, pb, gbs

    # ---- 1B int8: throughput + LOGIT-LEVEL quality (VERDICT r4 #2) ------
    # Both variants fit the chip, so the quality claim is measured, not
    # asserted: max |dlogit| bounds where greedy can flip (only inside the
    # < 2*dmax top-1 margin band), KL bounds sampling drift.  Random
    # weights remain the adversarial case for ARGMAX (their margins sit
    # inside the band — margin_p50 tells that story in the output), but
    # the logit error itself transfers to real checkpoints.
    from kafka_tpu.models.quant_quality import logit_quality_metrics

    q1 = quantize_params(base_params, base_cfg)
    quality = logit_quality_metrics(
        base_cfg, base_params, q1,
        [make_prompt(rng, 48, base_cfg.vocab_size) for _ in range(3)],
    )
    log(f"1b int8 logit quality: {quality}")
    tps, sps, pb, gbs = decode_tps(base_cfg, q1, "1b-int8")
    del q1
    out["llama-3.2-1b-int8"] = {
        "decode_tok_s_b8": round(tps, 1),
        "weight_gb": round(pb / 1e9, 2),
        "hbm_gb_s_est": round(gbs, 1),
        "logit_quality_vs_bf16": quality,
        "quality_note": ("flips are confined to bf16 top-1 margins < "
                         "2*max_abs_dlogit (analytic bound, gated in "
                         "tests/test_quant.py on a real-architecture "
                         "checkpoint)"),
    }
    log(f"1b int8: {tps:.1f} tok/s")

    # ---- 3B bf16 / 8B int8 ----------------------------------------------
    cfg3 = get_config("llama-3.2-3b")
    p3 = fill_params(cfg3)
    tps, sps, pb, gbs = decode_tps(cfg3, p3, "3b-bf16")
    del p3
    out["llama-3.2-3b-bf16"] = {
        "decode_tok_s_b8": round(tps, 1),
        "weight_gb": round(pb / 1e9, 2),
        "hbm_gb_s_est": round(gbs, 1),
    }
    log(f"3b bf16: {tps:.1f} tok/s")

    cfg8 = get_config("llama-3-8b")
    p8 = fill_params_int8(cfg8)
    tps, sps, pb, gbs = decode_tps(cfg8, p8, "8b-int8")
    del p8
    out["llama-3-8b-int8"] = {
        "decode_tok_s_b8": round(tps, 1),
        "weight_gb": round(pb / 1e9, 2),
        "hbm_gb_s_est": round(gbs, 1),
        "note": ("THE BASELINE metric model: 8B bf16 (16 GB) does not fit "
                 "one v5e chip; int8 weight-only serves it single-chip"),
    }
    log(f"8b int8: {tps:.1f} tok/s")

    # ---- MoE decode on the real chip (VERDICT r4 #6) --------------------
    # 1B attention dims + 4 SwiGLU experts top-2: the largest routed model
    # one chip holds in bf16 (~4.7 GB; Mixtral-8x7B int8 is ~49 GB — no
    # single-chip shape exists).  Dense reference: the SAME 1B dims, so
    # the ratio prices the whole routed path (router + 4x expert weight
    # streaming at decode + combine) against its dense sibling.
    tps_dense, _, _, _ = decode_tps(base_cfg, base_params, "1b-dense-ref")
    cfg_moe = get_config("llama-3.2-1b").replace(
        name="1b-moe-4e", num_experts=4, num_experts_per_tok=2)
    p_moe = fill_params(cfg_moe)
    tps, sps, pb, gbs = decode_tps(cfg_moe, p_moe, "1b-moe4")
    del p_moe
    out["llama-1b-moe-4e"] = {
        "decode_tok_s_b8": round(tps, 1),
        "weight_gb": round(pb / 1e9, 2),
        "hbm_gb_s_est": round(gbs, 1),
        "dense_sibling_tok_s": round(tps_dense, 1),
        "routed_overhead_ratio": round(tps_dense / tps, 2),
        "note": ("Mixtral-style top-2-of-4 routed MLP at llama-3.2-1b "
                 "dims (models/llama.py _moe_block, dense dispatch: every "
                 "expert computes every token, selection zeros the rest). "
                 "Decode streams ALL expert weights each step — the "
                 "bandwidth-bound cost the ratio prices; ep-sharding "
                 "divides that stream across chips (dryrun's ep x tp "
                 "engine)"),
    }
    log(f"1b moe-4e: {tps:.1f} tok/s (dense ref {tps_dense:.1f}, "
        f"ratio {tps_dense / tps:.2f}x)")
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("scenario", nargs="?", default="all",
                    choices=("all", "speculative", "constrained", "kv_tier",
                             "sleep_wake", "store_outage", "disagg",
                             "autoscale", "device_truth", "zero_copy",
                             "agent_gap"),
                    help="'speculative' runs ONLY the speculative-decoding "
                         "A/B phase; 'constrained' runs ONLY the on-device "
                         "grammar FSM vs host-mask A/B; 'kv_tier' runs ONLY "
                         "the tiered-KV cold-resume A/B (promote vs "
                         "re-prefill); 'sleep_wake' runs ONLY the "
                         "object-store sleep/wake A/B (drain replica A, "
                         "wake on a fresh replica B vs full re-prefill); "
                         "'store_outage' runs ONLY the object-store "
                         "outage containment proof (store killed "
                         "mid-run: breaker opens, serving degrades to "
                         "re-prefill at baseline latency, wake resumes "
                         "after recovery); "
                         "'disagg' runs ONLY the disaggregated "
                         "prefill/decode A/B (colocated vs "
                         "prefill:1,decode:1 under mixed open-loop traffic); "
                         "'autoscale' runs ONLY the traffic-ramp phase with "
                         "the autoscaler control loop closed (dp 1 -> 2 "
                         "mid-run); 'device_truth' runs ONLY the kernel-"
                         "sampling overhead A/B + the warm-vs-cold rebuild "
                         "compile-outage measurement; 'zero_copy' runs ONLY "
                         "the zero-host-copy movement A/Bs (host vs device "
                         "ship transport, wake prefetch on vs off under "
                         "injected store RTT)")
    ap.add_argument("--model", default="llama-3.2-1b")
    ap.add_argument("--quick", action="store_true",
                    help="tiny model + short runs (CI smoke)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--spec-k", type=int, default=8,
                    help="speculative_k for the speculative phase")
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--gen-len", type=int, default=256)
    ap.add_argument("--cache-prompt-len", type=int, default=2048,
                    help="prompt length for the equal-length cache proof")
    ap.add_argument("--batch-sweep", type=str, default="16,32",
                    help="extra decode batch points (comma list; '' = none)")
    ap.add_argument("--no-serve", action="store_true",
                    help="skip the HTTP/SSE served-path phase")
    ap.add_argument("--no-scale", action="store_true",
                    help="skip the 1B-int8/3B/8B model-scale phase")
    args = ap.parse_args()

    if args.scenario in ("disagg", "autoscale", "zero_copy"):
        # dp=2 replicas need 2 devices; on a CPU host force the device
        # count BEFORE jax initializes (the flag only affects the host
        # platform — real TPU device sets are untouched)
        import os as _os

        _flags = _os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in _flags:
            _os.environ["XLA_FLAGS"] = (
                _flags + " --xla_force_host_platform_device_count=2"
            ).strip()

    import jax

    # persistent XLA compile cache (same knob the server sets,
    # server/app.py): repeat bench runs on one machine skip the ~30-70s
    # per-program compiles that otherwise dominate wall time
    import os as _os

    _cache = _os.path.expanduser("~/.cache/kafka_tpu/xla")
    _os.makedirs(_cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)

    from kafka_tpu.models import get_config, init_params
    from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine
    from kafka_tpu.runtime.metrics import EngineMetrics

    if args.quick:
        # vocab must cover the ByteTokenizer's byte+special range (262) so
        # the serving phase's constrained tool-call masks stay in-vocab
        cfg = get_config("tiny-gqa").replace(vocab_size=262)
        args.prompt_len, args.gen_len = 32, 32
        args.cache_prompt_len = 64
        args.batch_sweep = ""
    else:
        cfg = get_config(args.model)
    platform = jax.devices()[0].platform
    device_kind = getattr(jax.devices()[0], "device_kind", "unknown")
    log(f"bench: {cfg.name} on {platform}/{device_kind} "
        f"({len(jax.devices())} device(s))")

    t0 = time.monotonic()
    params = init_params(cfg, jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    pbytes = param_bytes(params)
    log(f"params init: {time.monotonic() - t0:.1f}s "
        f"({pbytes / 1e9:.2f} GB)")

    if args.scenario == "speculative":
        # bench.py speculative: ONLY the draft-free speculation A/B
        out = speculative_phase(
            cfg, params,
            n_lanes=4 if args.quick else min(8, args.batch),
            prompt_len=48 if args.quick else 160,
            gen_len=24 if args.quick else 128,
            k=args.spec_k,
            page_size=8 if args.quick else 16,
        )
        log(f"speculative: uplift {out['tok_s_uplift']}x, acceptance "
            f"{out['acceptance_rate']}, accepted/step "
            f"{out['accepted_per_step']}")
        print(json.dumps({
            "metric": f"speculative_decode_tok_s_uplift_{cfg.name}",
            "value": out["tok_s_uplift"],
            "unit": "x",
            "extras": out,
        }))
        return

    if args.scenario == "constrained":
        # bench.py constrained: ONLY the grammar-FSM vs host-mask A/B
        out = constrained_phase(
            cfg, params,
            n_lanes=4 if args.quick else min(8, args.batch),
            gen_len=48 if args.quick else 96,
            page_size=8 if args.quick else 16,
        )
        log(f"constrained: roundtrips/call host "
            f"{out['roundtrips_per_call']['host']} -> ondevice "
            f"{out['roundtrips_per_call']['ondevice']}, outputs_match "
            f"{out['outputs_match']}")
        print(json.dumps({
            "metric": f"constrained_roundtrips_per_call_{cfg.name}",
            "value": out["roundtrips_per_call"]["ondevice"],
            "unit": "roundtrips",
            "extras": out,
        }))
        return

    if args.scenario == "device_truth":
        # bench.py device_truth: ONLY the kernel-sampling overhead A/B +
        # the warm-vs-cold rebuild compile-outage window (ISSUE 18)
        ps = 8 if args.quick else 16
        ecfg = EngineConfig(
            max_batch=min(args.batch, 8), page_size=ps,
            max_pages_per_seq=max(
                2, -(-(args.prompt_len + args.gen_len + ps) // ps)),
        )
        ecfg.num_pages = ecfg.max_batch * ecfg.max_pages_per_seq + 1
        eng = InferenceEngine(cfg, params, ecfg)
        rng = random.Random(0)
        # compile the A/B's programs OUTSIDE the measured loops
        eng.generate(make_prompt(rng, args.prompt_len // 2,
                                 cfg.vocab_size), max_new_tokens=4)
        eng.metrics = EngineMetrics()
        out = device_truth_phase(eng, cfg, args, rng)
        log(f"device_truth: sampling overhead "
            f"{100 * out['sampling']['overhead_frac']:.2f}% at N="
            f"{out['sampling']['sample_period']} "
            f"({out['sampling']['samples']} samples, "
            f"{out['sampling']['kernels_seen']} kernels); rebuild "
            f"first-token warm {out['rebuild_outage']['warm_first_token_s']}s "
            f"vs cold {out['rebuild_outage']['cold_first_token_s']}s")
        print(json.dumps({
            "metric": f"kernel_sampling_overhead_frac_{cfg.name}",
            "value": out["sampling"]["overhead_frac"],
            "unit": "frac",
            "extras": out,
        }))
        return

    if args.scenario == "kv_tier":
        # bench.py kv_tier: ONLY the tiered-KV cold-resume A/B
        out = kv_tier_phase(
            cfg, params,
            n_churn=2 if args.quick else 3,
            prompt_len=192 if args.quick else 2048,
            gen_len=8 if args.quick else 32,
            page_size=8 if args.quick else 16,
        )
        log(f"kv_tier: resume TTFT promote "
            f"{out['resume_ttft_ms']['promote']}ms vs re-prefill "
            f"{out['resume_ttft_ms']['reprefill']}ms "
            f"({out['resume_ttft_ms']['speedup']}x), promoted "
            f"{out['resume_promoted_tokens']} tokens, demote/promote bw "
            f"{out['demote_bw_mbps']}/{out['promote_bw_mbps']} MB/s")
        print(json.dumps({
            "metric": f"kv_tier_cold_resume_speedup_{cfg.name}",
            "value": out["resume_ttft_ms"]["speedup"],
            "unit": "x",
            "extras": out,
        }))
        return

    if args.scenario == "sleep_wake":
        # bench.py sleep_wake: ONLY the object-store sleep/wake A/B
        out = sleep_wake_phase(
            cfg, params,
            n_threads=3 if args.quick else 4,
            common_len=496 if args.quick else 512,
            suffix_len=16 if args.quick else 64,
            gen_len=8 if args.quick else 16,
            page_size=8 if args.quick else 16,
        )
        log(f"sleep_wake: cold-resume TTFT object-wake "
            f"{out['cold_resume_ttft_ms']['object_wake']}ms vs "
            f"re-prefill {out['cold_resume_ttft_ms']['reprefill']}ms "
            f"({out['speedup']}x), {out['prompt_tokens_recomputed']} "
            f"prompt tokens recomputed, store put/get "
            f"{out['store_put_mb_s']}/{out['store_get_mb_s']} MB/s, "
            f"dedupe ratio {out['cross_host_dedupe_ratio']}, "
            f"outputs_match {out['outputs_match']}")
        print(json.dumps({
            "metric": f"sleep_wake_cross_host_resume_speedup_{cfg.name}",
            "value": out["speedup"],
            "unit": "x",
            "extras": out,
        }))
        return

    if args.scenario == "agent_gap":
        # bench.py agent_gap: ONLY the agent tool-call-gap A/B
        out = agent_gap_phase(
            cfg, params,
            n_agents=3,
            agent_len=448 if args.quick else 960,
            churn_requests=6 if args.quick else 8,
            churn_len=256 if args.quick else 512,
            page_size=8 if args.quick else 16,
        )
        log(f"agent_gap: follow-up TTFT gap-on "
            f"{out['followup_ttft_mean_ms']['gap_on']}ms vs gap-off "
            f"{out['followup_ttft_mean_ms']['gap_off']}ms "
            f"({out['speedup']}x), "
            f"{out['hbm_pages_freed_mid_gap']['gap_on']} HBM pages freed "
            f"mid-gap, recomputed "
            f"{out['prompt_tokens_recomputed']['gap_on']} (on) vs "
            f"{out['prompt_tokens_recomputed']['gap_off']} (off) prompt "
            f"tokens, outputs_match {out['outputs_match']}")
        print(json.dumps({
            "metric": f"agent_gap_followup_ttft_speedup_{cfg.name}",
            "value": out["speedup"],
            "unit": "x",
            "extras": out,
        }))
        return

    if args.scenario == "store_outage":
        # bench.py store_outage: ONLY the outage containment proof
        out = store_outage_phase(
            cfg, params,
            n_threads=5,
            common_len=96 if args.quick else 128,
            suffix_len=16,
            gen_len=8,
            page_size=8,
        )
        log(f"store_outage: breaker_opened {out['breaker_opened']} "
            f"(state during outage: {out['breaker_state_during']}), "
            f"TTFT p99 store-down {out['ttft_p99_ms']['store_down']}ms "
            f"vs baseline re-prefill "
            f"{out['ttft_p99_ms']['baseline_reprefill']}ms "
            f"(contained {out['contained']}), recovered wake "
            f"{out['recovered_cache_source']}, outputs_match "
            f"{out['outputs_match']}")
        print(json.dumps({
            "metric": f"store_outage_ttft_p99_ratio_{cfg.name}",
            "value": round(
                out["ttft_p99_ms"]["store_down"]
                / out["ttft_p99_ms"]["baseline_reprefill"], 3)
            if out["ttft_p99_ms"]["baseline_reprefill"] else None,
            "unit": "x",
            "extras": out,
        }))
        return

    if args.scenario == "disagg":
        # bench.py disagg: ONLY the disaggregated prefill/decode A/B
        out = disagg_phase(
            cfg, params,
            n_chatty=4,
            n_long=3 if args.quick else 4,
            chatty_prompt=32 if args.quick else 48,
            chatty_gen=64 if args.quick else 128,
            long_prompt=513 if args.quick else 2049,
            long_gen=4 if args.quick else 16,
            page_size=8 if args.quick else 16,
            min_prefill_tokens=64 if args.quick else 256,
        )
        log(f"disagg: decode TPOT p99 colocated "
            f"{out['decode_tpot_p99_ms']['colocated']}ms -> "
            f"disaggregated {out['decode_tpot_p99_ms']['disaggregated']}ms "
            f"({out['decode_tpot_p99_ms']['improvement']}x), shipped "
            f"{out['shipped_pages']} pages at {out['ship_mb_s']} MB/s, "
            f"{out['prefill_tokens_recomputed']} prompt tokens recomputed")
        print(json.dumps({
            "metric": f"disagg_decode_tpot_p99_improvement_{cfg.name}",
            "value": out["decode_tpot_p99_ms"]["improvement"],
            "unit": "x",
            "extras": out,
        }))
        return

    if args.scenario == "autoscale":
        # bench.py autoscale: ONLY the closed-loop traffic-ramp phase
        out = traffic_ramp_phase(
            cfg, params,
            n_ramp=8 if args.quick else 12,
            prompt_len=24 if args.quick else 48,
            gen_len=20 if args.quick else 32,
            page_size=8 if args.quick else 16,
        )
        seg = out.get("attainment_by_segment") or {}
        log(f"autoscale: acted={out.get('acted')} dp "
            f"{out.get('dp', {}).get('before')} -> "
            f"{out.get('dp', {}).get('after')}, attainment ramp "
            f"{(seg.get('ramp_overload') or {}).get('attainment')} -> "
            f"post {(seg.get('post_action') or {}).get('attainment')}")
        print(json.dumps({
            "metric": f"autoscale_ramp_post_action_attainment_{cfg.name}",
            "value": (seg.get("post_action") or {}).get("attainment"),
            "unit": "frac",
            "extras": out,
        }))
        return

    if args.scenario == "zero_copy":
        # bench.py zero_copy: ONLY the zero-host-copy movement A/Bs
        out = zero_copy_phase(
            cfg, params,
            n_long=2 if args.quick else 3,
            long_prompt=257 if args.quick else 1025,
            long_gen=4 if args.quick else 8,
            n_groups=2 if args.quick else 3,
            c_len=96 if args.quick else 192,
            m_len=48 if args.quick else 96,
            x_len=16 if args.quick else 32,
            gen_len=8 if args.quick else 16,
            page_size=8 if args.quick else 16,
            min_prefill_tokens=64 if args.quick else 256,
        )
        ship = out.get("ship_transport") or {}
        wake = out["wake_prefetch"]
        if ship:
            log(f"zero_copy: ship {ship['shipped_pages']} pages host "
                f"{ship['ship_mb_s']['host']} MB/s -> device "
                f"{ship['ship_mb_s']['device']} MB/s "
                f"(device staging peak {ship['device_staging_peak_bytes']}B)")
        else:
            log("zero_copy: ship transport A/B skipped (needs >= 2 devices)")
        log(f"zero_copy: wake TTFT p50 prefetch-off "
            f"{wake['wake_ttft_p50_ms']['prefetch_off']}ms -> on "
            f"{wake['wake_ttft_p50_ms']['prefetch_on']}ms "
            f"({wake['speedup']}x) under {wake['store_delay_ms']}ms "
            f"injected store RTT, {wake['prompt_tokens_recomputed']} "
            f"prompt tokens recomputed")
        print(json.dumps({
            "metric": f"zero_copy_wake_prefetch_speedup_{cfg.name}",
            "value": wake["speedup"],
            "unit": "x",
            "extras": out,
        }))
        return

    ecfg = EngineConfig(
        max_batch=args.batch,
        page_size=16,
        max_pages_per_seq=max(
            2, -(-(args.prompt_len + args.gen_len + 16) // 16)
        ),
    )
    # pool sized for active batch AND the prefix caches of the concurrent-
    # thread phase — an undersized pool measures reclaim churn, not the
    # engine (~300 MB of KV for the 1B default: deployment-realistic)
    ecfg.num_pages = 3 * args.batch * ecfg.max_pages_per_seq + 1
    engine = InferenceEngine(cfg, params, ecfg)

    rng = random.Random(0)

    def prompt(n=None):
        return make_prompt(rng, n or args.prompt_len, cfg.vocab_size)

    # ---- warmup: compile prefill buckets + decode programs ---------------
    # every prompt length the bench uses gets its bucket compiled here —
    # a bucket compiling inside a measured phase once cost the concurrent-
    # thread metric a silent 15s (r02/r03 measured ~2 req/s; real ~25)
    t0 = time.monotonic()
    engine.generate(prompt(), max_new_tokens=4)
    engine.generate(prompt(args.prompt_len // 2), max_new_tokens=2)
    if args.batch >= 2:
        # concurrent same-bucket admissions take the BATCHED prefill
        # program; compile it for the concurrent-thread phase's bucket
        for i in range(2):
            engine.submit(GenRequest(
                request_id=f"warm-bp-{i}",
                prompt_ids=prompt(args.prompt_len // 2), max_new_tokens=2))
        engine.run_to_completion()
    if args.batch >= 3 and ecfg.multi_step > 1:
        # the fused multi-step decode program compiles on its first busy
        # batch — trigger that here, not inside the measured decode phase
        for i in range(min(4, args.batch)):
            engine.submit(GenRequest(
                request_id=f"warm-ms-{i}", prompt_ids=prompt(),
                max_new_tokens=ecfg.multi_step + 4))
        engine.run_to_completion()
    log(f"warmup/compile: {time.monotonic() - t0:.1f}s")
    # warmup included XLA compiles; reset so percentiles reflect serving
    engine.metrics = EngineMetrics()

    # ---- TTFT: prompt submit -> first token, solo requests ---------------
    ttfts = []
    for _ in range(5 if args.quick else 10):
        req = engine.generate(prompt(), max_new_tokens=1)
        ttfts.append((req.first_token_time - req.submit_time) * 1e3)
    ttft_p50 = statistics.median(ttfts)
    log(f"p50 TTFT {ttft_p50:.1f} ms")

    # ---- prefix cache proof: EQUAL-length cold vs hit TTFT ---------------
    # (BASELINE config 2.)  Both measurements prefill a prompt of exactly
    # cache_prompt_len tokens; the hit turn shares all but an 8-token
    # suffix through thread-keyed cached pages.  A dedicated engine keeps
    # the long-window pool and compile footprint out of the other phases.
    L = args.cache_prompt_len
    suffix = 8
    cache_ecfg = EngineConfig(
        max_batch=2, page_size=16,
        max_pages_per_seq=max(2, -(-(L + 32) // 16)),
    )
    cache_ecfg.num_pages = 6 * cache_ecfg.max_pages_per_seq + 1
    cache_engine = InferenceEngine(cfg, params, cache_ecfg)
    cache_engine.generate(prompt(L), max_new_tokens=1)  # compile buckets
    base = prompt(L - suffix)
    seed_req = GenRequest(request_id="warm-seed", prompt_ids=base,
                          max_new_tokens=1, prefix_key="bench-thread")
    cache_engine.submit(seed_req)
    cache_engine.run_to_completion()
    # a hit prefills only the suffix -> the smallest bucket; compile it
    # OUTSIDE the measured loop (compile-in-window was exactly the r02/r03
    # concurrent-thread pollution)
    warm_hit = GenRequest(request_id="warm-hit",
                          prompt_ids=base + prompt(suffix),
                          max_new_tokens=1, prefix_key="bench-thread")
    cache_engine.submit(warm_hit)
    cache_engine.run_to_completion()
    cold_ttfts, hit_ttfts = [], []
    reused0 = cache_engine.prefix_cache.tokens_reused
    n_pairs = 3 if args.quick else 5
    for i in range(n_pairs):
        cold = GenRequest(request_id=f"cold-{i}", prompt_ids=prompt(L),
                          max_new_tokens=1)
        cache_engine.submit(cold)
        cache_engine.run_to_completion()
        cold_ttfts.append((cold.first_token_time - cold.submit_time) * 1e3)
        hit = GenRequest(request_id=f"hit-{i}",
                         prompt_ids=base + prompt(suffix),
                         max_new_tokens=1, prefix_key="bench-thread")
        cache_engine.submit(hit)
        cache_engine.run_to_completion()
        hit_ttfts.append((hit.first_token_time - hit.submit_time) * 1e3)
    cold_p50 = statistics.median(cold_ttfts)
    hit_p50 = statistics.median(hit_ttfts)
    tokens_reused = cache_engine.prefix_cache.tokens_reused - reused0
    suffix_prefilled = L - tokens_reused // n_pairs if n_pairs else 0
    log(f"cache proof @ {L} tokens: cold {cold_p50:.1f} ms, "
        f"hit {hit_p50:.1f} ms (prefilled ~{suffix_prefilled} of {L})")

    # ---- shared_prefix: cross-thread radix reuse (fan-out shape) ---------
    # N distinct threads, one common system prefix: radix vs no-cache
    # (the exact-key baseline's behavior on this workload was zero reuse)
    sp_common = 48 if args.quick else 512
    sp_suffix = 16 if args.quick else 32
    shared_prefix = shared_prefix_phase(
        cfg, params,
        n_threads=4 if args.quick else 8,
        common_len=sp_common, suffix_len=sp_suffix,
        gen_len=4 if args.quick else 16,
        page_size=8 if args.quick else 16,
    )
    log(f"shared_prefix: saved {shared_prefix['prefill_tokens_saved']} "
        f"prefill tokens over {shared_prefix['n_threads']} threads "
        f"({shared_prefix['cross_thread_hits']} cross-thread hits); warm "
        f"TTFT {shared_prefix['warm_thread_ttft_ms']}")

    # ---- kv_tier: cold-resume promote vs re-prefill (ISSUE 9) -----------
    kv_tier = kv_tier_phase(
        cfg, params,
        n_churn=2 if args.quick else 3,
        prompt_len=192 if args.quick else 1024,
        gen_len=8 if args.quick else 32,
        page_size=8 if args.quick else 16,
    )
    log(f"kv_tier: resume TTFT promote "
        f"{kv_tier['resume_ttft_ms']['promote']}ms vs re-prefill "
        f"{kv_tier['resume_ttft_ms']['reprefill']}ms "
        f"({kv_tier['resume_ttft_ms']['speedup']}x)")

    # ---- sleep_wake: object-store cross-host resume (ISSUE 14) ----------
    sleep_wake = sleep_wake_phase(
        cfg, params,
        n_threads=3 if args.quick else 4,
        common_len=496 if args.quick else 512,
        suffix_len=16 if args.quick else 64,
        gen_len=8 if args.quick else 16,
        page_size=8 if args.quick else 16,
    )
    log(f"sleep_wake: cold-resume TTFT object-wake "
        f"{sleep_wake['cold_resume_ttft_ms']['object_wake']}ms vs "
        f"re-prefill {sleep_wake['cold_resume_ttft_ms']['reprefill']}ms "
        f"({sleep_wake['speedup']}x), dedupe ratio "
        f"{sleep_wake['cross_host_dedupe_ratio']}")

    # ---- store_outage: breaker containment under a dead store -----------
    store_outage = store_outage_phase(
        cfg, params,
        n_threads=5,
        common_len=96 if args.quick else 128,
        suffix_len=16,
        gen_len=8,
        page_size=8,
    )
    log(f"store_outage: breaker_opened {store_outage['breaker_opened']}, "
        f"TTFT p99 store-down "
        f"{store_outage['ttft_p99_ms']['store_down']}ms vs baseline "
        f"{store_outage['ttft_p99_ms']['baseline_reprefill']}ms, "
        f"recovered wake {store_outage['recovered_cache_source']}")

    # ---- agent_gap: tool-call-gap demote + wake prefetch (ISSUE 20) -----
    agent_gap = agent_gap_phase(
        cfg, params,
        n_agents=3,
        agent_len=448 if args.quick else 960,
        churn_requests=6 if args.quick else 8,
        churn_len=256 if args.quick else 512,
        page_size=8 if args.quick else 16,
    )
    log(f"agent_gap: follow-up TTFT gap-on "
        f"{agent_gap['followup_ttft_mean_ms']['gap_on']}ms vs gap-off "
        f"{agent_gap['followup_ttft_mean_ms']['gap_off']}ms "
        f"({agent_gap['speedup']}x), "
        f"{agent_gap['hbm_pages_freed_mid_gap']['gap_on']} HBM pages "
        f"freed mid-gap, outputs_match {agent_gap['outputs_match']}")

    # ---- disaggregated prefill/decode: colocated vs role pools ----------
    disagg = None
    if len(jax.devices()) >= 2:
        disagg = disagg_phase(
            cfg, params,
            n_chatty=4,
            n_long=3 if args.quick else 4,
            chatty_prompt=32 if args.quick else 48,
            chatty_gen=64 if args.quick else 128,
            long_prompt=257 if args.quick else 2049,
            long_gen=4 if args.quick else 16,
            page_size=8 if args.quick else 16,
            min_prefill_tokens=64 if args.quick else 256,
        )
        log(f"disagg: decode TPOT p99 colocated "
            f"{disagg['decode_tpot_p99_ms']['colocated']}ms -> "
            f"disaggregated "
            f"{disagg['decode_tpot_p99_ms']['disaggregated']}ms "
            f"({disagg['decode_tpot_p99_ms']['improvement']}x)")
    else:
        log("disagg: skipped (needs >= 2 devices for dp=2 pools)")

    # ---- zero-host-copy movement: ship transport + wake prefetch --------
    zero_copy = zero_copy_phase(
        cfg, params,
        n_long=2 if args.quick else 3,
        long_prompt=257 if args.quick else 1025,
        long_gen=4 if args.quick else 8,
        n_groups=2 if args.quick else 3,
        c_len=96 if args.quick else 192,
        m_len=48 if args.quick else 96,
        x_len=16 if args.quick else 32,
        gen_len=8 if args.quick else 16,
        page_size=8 if args.quick else 16,
        min_prefill_tokens=64 if args.quick else 256,
    )
    _zs = zero_copy.get("ship_transport") or {}
    _zw = zero_copy["wake_prefetch"]
    if _zs:
        log(f"zero_copy: ship host {_zs['ship_mb_s']['host']} -> device "
            f"{_zs['ship_mb_s']['device']} MB/s (device staging peak "
            f"{_zs['device_staging_peak_bytes']}B)")
    log(f"zero_copy: wake TTFT p50 off "
        f"{_zw['wake_ttft_p50_ms']['prefetch_off']}ms -> on "
        f"{_zw['wake_ttft_p50_ms']['prefetch_on']}ms ({_zw['speedup']}x)")

    # ---- autoscaler: closed-loop traffic ramp (ISSUE 13) -----------------
    autoscale = None
    if len(jax.devices()) >= 2:
        autoscale = traffic_ramp_phase(
            cfg, params,
            n_ramp=8 if args.quick else 12,
            prompt_len=24 if args.quick else 48,
            gen_len=20 if args.quick else 32,
            page_size=8 if args.quick else 16,
        )
        _seg = autoscale.get("attainment_by_segment") or {}
        log(f"autoscale: acted={autoscale.get('acted')} dp 1 -> "
            f"{autoscale.get('dp', {}).get('after')}, attainment ramp "
            f"{(_seg.get('ramp_overload') or {}).get('attainment')} -> "
            f"post {(_seg.get('post_action') or {}).get('attainment')}")
    else:
        log("autoscale: skipped (needs >= 2 devices for dp 1 -> 2)")

    # ---- speculative decoding: tool-echo A/B (spec on vs off) ------------
    speculative = speculative_phase(
        cfg, params,
        n_lanes=4 if args.quick else min(8, args.batch),
        prompt_len=48 if args.quick else 160,
        gen_len=24 if args.quick else 128,
        k=args.spec_k,
        page_size=8 if args.quick else 16,
    )
    log(f"speculative: uplift {speculative['tok_s_uplift']}x, acceptance "
        f"{speculative['acceptance_rate']}, accepted/step "
        f"{speculative['accepted_per_step']}, outputs_match "
        f"{speculative['outputs_match']}")

    # ---- decode throughput: full batch, steady state ---------------------
    decode_tps, steps_per_s = decode_phase(
        engine, cfg, args.batch, args.prompt_len, args.gen_len, rng
    )
    ctx = args.prompt_len + args.gen_len // 2  # mean context during decode
    step_bytes = hbm_traffic_per_step(engine, pbytes, args.batch, ctx)
    hbm_gb_s = step_bytes * steps_per_s / 1e9
    # nominal HBM bandwidth by chip family; fall back to v5e-class
    HBM_BW = {"TPU v4": 1228.0, "TPU v5e": 819.0, "TPU v5 lite": 819.0,
              "TPU v5p": 2765.0, "TPU v6e": 1640.0}
    bw_nominal = next(
        (v for k, v in HBM_BW.items() if k.lower() in str(device_kind).lower()),
        819.0,
    )
    log(f"decode b{args.batch}: {decode_tps:.1f} tok/s, "
        f"{steps_per_s:.1f} steps/s, ~{hbm_gb_s:.0f} GB/s "
        f"({100 * hbm_gb_s / bw_nominal:.0f}% of {bw_nominal:.0f})")

    # ---- fused-depth ablation at the SAME link --------------------------
    # Tunnel RTT swings 2x across a day, so cross-round absolute tok/s
    # conflate scheduler work with link weather; measuring multi_step=8
    # (the pre-r5 default) in the same run makes the depth-16 gain a
    # controlled comparison (r5 sweep on one link: 1111 -> 1576 tok/s).
    depth_ablation = None
    # fusion engages only with >=3 active streams, so smaller batches
    # would compare two identical single-step programs
    if not args.quick and engine.ecfg.multi_step != 8 and args.batch >= 3:
        ecfg8 = EngineConfig(
            max_batch=args.batch, page_size=16,
            max_pages_per_seq=engine.ecfg.max_pages_per_seq,
            num_pages=engine.ecfg.num_pages, multi_step=8,
        )
        eng8 = InferenceEngine(cfg, engine.params, ecfg8)
        t0 = time.monotonic()
        eng8.generate(make_prompt(rng, args.prompt_len, cfg.vocab_size),
                      max_new_tokens=2)
        for i in range(4):
            eng8.submit(GenRequest(request_id=f"wd8-{i}",
                                   prompt_ids=make_prompt(
                                       rng, args.prompt_len, cfg.vocab_size),
                                   max_new_tokens=12))
        eng8.run_to_completion()
        log(f"depth-8 compile: {time.monotonic() - t0:.1f}s")
        tps8, _ = decode_phase(eng8, cfg, args.batch, args.prompt_len,
                               args.gen_len, rng)
        del eng8
        depth = engine.ecfg.multi_step
        depth_ablation = {
            "multi_step_8_tok_s": round(tps8, 1),
            f"multi_step_{depth}_tok_s": round(decode_tps, 1),
            "speedup": round(decode_tps / tps8, 2),
            "note": ("link-dependent: ~1.0x on a calm link (dispatch "
                     "already amortized at depth 8), up to 1.42x measured "
                     "when the tunnel degrades — deeper fusion is weather "
                     "insurance, collapsing throughput variance"),
        }
        log(f"depth ablation: 8={tps8:.1f} {depth}={decode_tps:.1f} "
            f"({decode_tps / tps8:.2f}x same link)")

    # ---- batch scaling points (fresh engine per width: the decode step is
    # compiled at its static batch width, so reusing a 32-wide engine for a
    # batch of 8 would measure the wrong program) ------------------------
    sweep = {}
    def sweep_point(secfg, b, label):
        """Build + warm (incl. the fused multi-step program) + measure one
        sweep engine; one warmup protocol for every A/B row."""
        seng = InferenceEngine(cfg, params, secfg)
        t0 = time.monotonic()
        seng.generate(prompt(), max_new_tokens=2)
        for i in range(min(4, b)):
            seng.submit(GenRequest(request_id=f"warm-{label}-{i}",
                                   prompt_ids=prompt(),
                                   max_new_tokens=secfg.multi_step + 4))
        seng.run_to_completion()
        log(f"{label} compile: {time.monotonic() - t0:.1f}s")
        # warmup compiles pollute attainment; phase-local metrics
        seng.metrics = EngineMetrics()
        # gen 256: short sweeps absorb the fixed ~RTT drain tail of the
        # fetch pipeline into tok/s (measured: b16 varied 1.7-2.9k tok/s
        # at gen 128 purely with tunnel RTT)
        tps, sps = decode_phase(seng, cfg, b, args.prompt_len, 256, rng)
        sb = hbm_traffic_per_step(seng, pbytes, b, args.prompt_len + 128)
        slo = phase_slo(seng)
        del seng
        return tps, sps, sb, slo

    for b in [int(x) for x in args.batch_sweep.split(",") if x]:
        secfg = EngineConfig(
            max_batch=b, page_size=16,
            max_pages_per_seq=max(2, -(-(args.prompt_len + 256 + 16) // 16)),
        )
        secfg.num_pages = b * secfg.max_pages_per_seq + 1
        tps, sps, sb, slo = sweep_point(secfg, b, f"b{b}")
        sweep[str(b)] = {
            "decode_tok_s": round(tps, 1),
            "steps_per_s": round(sps, 1),
            "hbm_gb_s_est": round(sb * sps / 1e9, 1),
            "hbm_util_est": round(sb * sps / 1e9 / bw_nominal, 3),
            **slo,
        }
        log(f"decode b{b}: {tps:.1f} tok/s "
            f"({100 * sb * sps / 1e9 / bw_nominal:.0f}% HBM)")

        sweep_batches = [int(x) for x in args.batch_sweep.split(",") if x]
        if b == max(sweep_batches):
            # int8 KV at the largest sweep batch: the KV window gather is
            # the GROWING share of the step there (roofline note), so
            # that is where halved KV traffic shows (VERDICT r4 #4)
            kcfg = dataclasses.replace(secfg, kv_quantize="int8")
            tps, sps, _, _ = sweep_point(kcfg, b, f"b{b}-int8kv")
            sweep[f"{b}-int8kv"] = {
                "decode_tok_s": round(tps, 1),
                "steps_per_s": round(sps, 1),
                "note": ("per-slot int8 KV pool; on TPU 'auto' now "
                         "resolves to the int8 pallas kernel "
                         "(paged_decode_attention_int8: int8 page DMAs — "
                         "half the KV bytes — with the per-slot dequant "
                         "fused into scores/probs).  HALF the KV bytes -> "
                         "2x window capacity (planner).  Same-link A/B at "
                         "b32 1B: int8-pallas 4667, int8-xla-gather 3455, "
                         "bf16-pallas 4756 tok/s — int8 KV costs ~2% vs "
                         "bf16 now, not the r5-early 17% (xla-gather "
                         "3822 vs 4623; slot-granular gather was 2385)"),
            }
            log(f"decode b{b} int8-kv: {tps:.1f} tok/s")

    # ---- concurrent-thread req/s (BASELINE metric 3): 4x oversubscribed
    # queue of short thread turns through the continuous batcher ----------
    n_threads = 8 if args.quick else 32
    ct_probe = SloProbe(engine)
    for i in range(n_threads):
        engine.submit(GenRequest(
            request_id=f"ct-{i}",
            prompt_ids=prompt()[: args.prompt_len // 2],
            max_new_tokens=32, prefix_key=f"ct-thread-{i}",
        ))
    t0 = time.monotonic()
    done_ct = 0
    while engine.has_work:
        for ev in engine.step():
            if ev.finished:
                done_ct += 1
    ct_wall = time.monotonic() - t0
    concurrent_req_s = done_ct / ct_wall
    concurrent_slo = ct_probe.report()

    # ---- telemetry overhead A/B (ISSUE 10 acceptance: <=1% tok/s) -------
    # runs BEFORE the serving phase so the main engine's compiled decode
    # programs are reused; snapshot for the headline is taken first below
    snap_pre_telemetry = engine.metrics.snapshot(engine)
    telemetry = telemetry_overhead_phase(engine, cfg, args, rng)
    log(f"telemetry overhead: on {telemetry['tok_s_on']} vs off "
        f"{telemetry['tok_s_off']} tok/s "
        f"({100 * telemetry['regression_frac']:.2f}% regression)")

    # ---- flight-recorder overhead A/B (ISSUE 11: within noise) ----------
    flight = flight_overhead_phase(engine, cfg, args, rng)
    log(f"flight recorder overhead: on {flight['tok_s_on']} vs off "
        f"{flight['tok_s_off']} tok/s "
        f"({100 * flight['regression_frac']:.2f}% regression)")

    # ---- device-truth telemetry (ISSUE 18): sampling A/B + rebuild ------
    # outage.  Runs LAST among the main-engine phases: the cold leg
    # clears the process jit caches, so anything after it would recompile
    device_truth = device_truth_phase(engine, cfg, args, rng)
    log(f"device_truth: sampling overhead "
        f"{100 * device_truth['sampling']['overhead_frac']:.2f}% at N="
        f"{device_truth['sampling']['sample_period']}; rebuild "
        f"first-token warm "
        f"{device_truth['rebuild_outage']['warm_first_token_s']}s vs cold "
        f"{device_truth['rebuild_outage']['cold_first_token_s']}s")

    # ---- served path: HTTP/SSE through the real app (VERDICT r3 #1) -----
    if args.no_serve:
        served = {}
    else:
        served = serving_phase(cfg, params, args, args.quick)

    # the same counters GET /metrics exports (runtime/metrics.py) — bench
    # and the server report one source of truth.  Taken BEFORE the
    # telemetry-overhead A/B wiped the main engine's counters.
    snap = snap_pre_telemetry

    # ---- bigger models: 1B int8 quality/thpt, 3B bf16, 8B int8 ----------
    scale = {}
    if not args.quick and not args.no_scale:
        del engine  # free the main pool before the big models come up
        scale = scale_phase(args, cfg, params)

    # Headline = BASELINE.json's first metric (tokens/sec/chip). The
    # reference publishes no numbers, so vs_baseline is the improvement over
    # this framework's own round-1 measurement (88.6 tok/s/chip,
    # BENCH_r01.json) — the only prior number on record for this metric.
    R01_DECODE_TPS = 88.6
    R02_DECODE_TPS = 1149.6
    result = {
        "metric": f"decode_tokens_per_sec_per_chip_{cfg.name}_batch{args.batch}",
        "value": round(decode_tps, 1),
        "unit": "tok/s",
        "vs_baseline": round(decode_tps / R01_DECODE_TPS, 2),
        "extras": {
            "p50_ttft_ms": round(ttft_p50, 2),
            "ttft_vs_200ms_north_star": round(200.0 / ttft_p50, 3),
            "prefix_cache_proof": {
                "prompt_len": L,
                "cold_p50_ttft_ms": round(cold_p50, 2),
                "hit_p50_ttft_ms": round(hit_p50, 2),
                "speedup": round(cold_p50 / hit_p50, 2) if hit_p50 else None,
                "suffix_tokens_prefilled_on_hit": suffix_prefilled,
                "note": "equal-length prompts; hit shares all but the "
                        "suffix through thread-keyed cached KV pages",
            },
            "hbm": {
                "bytes_per_step_est": step_bytes,
                "achieved_gb_s_est": round(hbm_gb_s, 1),
                "bw_nominal_gb_s": bw_nominal,
                "hbm_util_est": round(hbm_gb_s / bw_nominal, 3),
                "device_kind": str(device_kind),
                "note": "weights read once per step + KV read/write; "
                        "nominal BW by chip family table",
            },
            "shared_prefix": shared_prefix,
            "kv_tier": kv_tier,
            "sleep_wake": sleep_wake,
            "store_outage": store_outage,
            "agent_gap": agent_gap,
            "disagg": disagg,
            "zero_copy": zero_copy,
            "autoscale": autoscale,
            "speculative": speculative,
            "batch_sweep": sweep,
            "fused_depth_ablation": depth_ablation,
            "metrics": {  # same counters the server's GET /metrics exports
                "ttft_ms": snap["ttft_ms"],
                "tpot_ms": snap["tpot_ms"],
                "emission": snap["emission"],
                "batch_occupancy": snap["decode"]["batch_occupancy"],
                "generated_tokens": snap["tokens"]["generated"],
                "prefix_cache": snap.get("prefix_cache"),
                "rtt_est_ms": snap["engine"]["rtt_est_ms"],
                # the SLO telemetry plane (ISSUE 10): attainment/goodput
                # + per-dispatch-kind MFU / HBM-BW utilization, read from
                # the same snapshot the autoscaler feed serves
                "slo": {k: v for k, v in snap["slo"].items()
                        if not k.startswith("window_")},
                "utilization": snap["utilization"],
                "queue": snap["queue"],
            },
            "telemetry_overhead": telemetry,
            "flight_overhead": flight,
            "device_truth": device_truth,
            "concurrent_slo": concurrent_slo,
            "server_path": served.get("server_path"),
            "agent_path": served.get("agent_path"),
            "model_scale": scale or None,
            "concurrent_thread_req_per_s": round(concurrent_req_s, 2),
            "concurrent_threads": n_threads,
            "concurrent_note": (
                f"{n_threads} short thread turns, oversubscribed over "
                f"batch {args.batch} on "
                "ONE chip; BASELINE config 3's 256-thread target assumes "
                "v5e-8 (8 chips x dp) — per-chip this is the comparable "
                "shape. Varies ~10% with tunnel RTT jitter."
            ),
            "decode_batch": args.batch,
            "gen_len": args.gen_len,
            "ttft_all_ms": [round(t, 2) for t in ttfts],
            "platform": platform,
            "model": cfg.name,
            "vs_r02": round(decode_tps / R02_DECODE_TPS, 2),
            "note": ("vs_baseline = decode tok/s/chip over round-1's 88.6 "
                     "(reference publishes no numbers, BASELINE.md); vs_r02 "
                     "= over round-2's 1149.6. TTFT is host-observed "
                     "first-token latency incl. device->host fetch."),
        },
    }
    # Also write the full JSON next to the repo: BENCH_r04's server_path
    # block was truncated out of the driver's captured stdout tail, so the
    # canonical record must not depend on terminal capture (VERDICT r4 #5).
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_LOCAL.json"), "w") as f:
            json.dump(result, f, indent=1)
    except OSError:
        pass
    print(json.dumps(result))


if __name__ == "__main__":
    main()
