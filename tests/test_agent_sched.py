"""Agent-native scheduling (ISSUE 20): exploit the tool-call gap.

The load-bearing claims:
  * a thread that finishes a turn with a tool call demotes its KV down
    the tier ladder after the linger window, resumes token-identical to
    a never-demoted engine (cache_source="host_tier"), and the return
    hint cancels a still-lingering demote so sub-linger tools never pay
    the round trip,
  * the return hint kicks the wake prefetcher with the thread's
    locally-resident depth,
  * background-class requests (tool-result prefill, compaction
    summarization) yield to interactive work every scheduler iteration,
    admit only into idle capacity, and produce byte-identical outputs
    to a foreground run,
  * with KAFKA_TPU_AGENT_DEMOTE unset every hook is a no-op and
    scheduling is unchanged,
  * AGENT_METRIC_KEYS is a both-directions registry across
    runtime/metrics.py and server/prometheus.py, and agent_section()
    matches it exactly,
  * EngineWorker routes note_tool_gap/note_tool_return through its
    inbox (engine is single-writer), the DP router pins
    expected-return hints to the thread's affinity replica,
  * HTTPObjectStore signs requests (AWS SigV4 / GCS bearer) that a
    stub verifying by INDEPENDENT recomputation accepts — and rejects
    with 403/401 when the credentials are wrong.
"""

import asyncio
import hashlib
import os
import re
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.models import ModelConfig, init_params
from kafka_tpu.runtime import (
    AdmissionError,
    EngineConfig,
    GenRequest,
    InferenceEngine,
)
from kafka_tpu.runtime.dp_router import DataParallelEngines
from kafka_tpu.runtime.engine import (
    AGENT_DEMOTE_ENV,
    AGENT_LINGER_ENV,
    agent_demote_default,
    agent_linger_default,
)
from kafka_tpu.runtime.flight_recorder import CAUSES
from kafka_tpu.runtime.metrics import AGENT_METRIC_KEYS
from kafka_tpu.runtime.object_tier import (
    ENV_OBJECT_AUTH,
    ENV_OBJECT_BEARER,
    HTTPObjectStore,
    _load_object_auth,
    _sigv4_headers,
)

from objstore_stub import StubS3Server


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(name="agent-test", vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(7))
    return cfg, params


def make_engine(cfg, params, **kw):
    defaults = dict(max_batch=2, page_size=8, num_pages=24,
                    max_pages_per_seq=16,
                    prefill_buckets=(8, 16, 32, 64, 128),
                    kv_host_tier_mb=64,
                    agent_demote="host", agent_linger_s=0.0)
    defaults.update(kw)
    return InferenceEngine(cfg, params, EngineConfig(**defaults),
                           kv_dtype=jnp.float32)


def _req(rid, prompt, key=None, max_new=8, background=False):
    return GenRequest(request_id=rid, prompt_ids=list(prompt),
                      max_new_tokens=max_new, prefix_key=key,
                      background=background)


def _prompt(seed, n=64):
    return [int(x) for x in np.random.default_rng(seed).integers(1, 120, n)]


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------


class TestKnobs:
    def test_defaults_off(self, monkeypatch):
        monkeypatch.delenv(AGENT_DEMOTE_ENV, raising=False)
        monkeypatch.delenv(AGENT_LINGER_ENV, raising=False)
        assert agent_demote_default() == ""
        assert agent_linger_default() == pytest.approx(0.25)
        assert EngineConfig().agent_demote == ""

    def test_env_knobs(self, monkeypatch):
        monkeypatch.setenv(AGENT_DEMOTE_ENV, "on")
        monkeypatch.setenv(AGENT_LINGER_ENV, "100")
        assert agent_demote_default() == "host"
        assert agent_linger_default() == pytest.approx(0.1)
        monkeypatch.setenv(AGENT_DEMOTE_ENV, "object")
        assert agent_demote_default() == "object"
        monkeypatch.setenv(AGENT_DEMOTE_ENV, "bogus")
        assert agent_demote_default() == ""  # nonsense = off, not a crash
        monkeypatch.setenv(AGENT_LINGER_ENV, "not-a-number")
        assert agent_linger_default() == pytest.approx(0.25)

    def test_invalid_mode_rejected(self, model):
        cfg, params = model
        with pytest.raises(ValueError, match="agent_demote"):
            make_engine(cfg, params, agent_demote="bogus")


# ---------------------------------------------------------------------------
# gap lifecycle
# ---------------------------------------------------------------------------


class TestGapLifecycle:
    def test_demote_then_resume_token_exact(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, flight_ring=64)
        prompt = _prompt(3)
        a = _req("A", prompt, key="thread-A")
        eng.submit(a)
        eng.run_to_completion()
        pc = eng.prefix_cache
        assert pc.host_nodes == 0

        # the turn ended in a tool call; linger=0 -> next step demotes
        eng.note_tool_gap("thread-A")
        assert eng.agent_gaps == 1
        eng.step()
        assert eng.agent_gap_demotions == 1
        assert eng.agent_gap_pages_demoted > 0
        assert eng.agent_gap_bytes_demoted > 0
        assert pc.host_nodes > 0, "gap must demote the thread's KV"
        assert eng.awaiting_tool_keys() == ["thread-A"]
        sec = eng.agent_section()
        assert sec["agent_awaiting_threads"] == 1
        assert sec["agent_awaiting_bytes"] > 0
        assert any("agent_demote" in r.get("causes", {})
                   for r in eng.flight.records())
        assert not eng.self_check()

        # the tool finished: hint fires, awaiting state clears
        eng.note_tool_return("thread-A")
        assert eng.agent_hint_hits == 1
        assert eng.awaiting_tool_keys() == []
        assert eng.agent_section()["agent_awaiting_threads"] == 0

        # follow-up turn resumes from the host tier, token-identical
        resume = prompt + list(a.output_ids) + [7, 9, 11]
        a2 = _req("A2", resume, key="thread-A")
        eng.submit(a2)
        eng.run_to_completion()
        assert a2.cache_source == "host_tier"
        assert a2.promoted_tokens > 0

        base = make_engine(cfg, params, kv_host_tier_mb=0, agent_demote="")
        b1 = _req("b1", prompt, key="t")
        base.submit(b1)
        base.run_to_completion()
        assert b1.output_ids == a.output_ids
        b2 = _req("b2", resume, key="t")
        base.submit(b2)
        base.run_to_completion()
        assert b2.output_ids == a2.output_ids

    def test_sub_linger_return_cancels_demote(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, agent_linger_s=60.0)
        a = _req("A", _prompt(4), key="thread-A")
        eng.submit(a)
        eng.run_to_completion()
        eng.note_tool_gap("thread-A")
        eng.step()  # linger far in the future: nothing demotes
        assert eng.agent_gap_demotions == 0
        assert eng.prefix_cache.host_nodes == 0
        eng.note_tool_return("thread-A")  # quick tool: cancel in linger
        assert eng.agent_gap_cancelled == 1
        assert eng.agent_hint_hits == 1
        assert eng.prefix_cache.host_nodes == 0
        assert eng.awaiting_tool_keys() == []
        eng.step()
        assert eng.agent_gap_demotions == 0

    def test_resubmit_cancels_pending_gap(self, model):
        # the thread came back via a fresh submit (the return hint was
        # lost, or the client skipped it): admission must cancel the gap
        cfg, params = model
        eng = make_engine(cfg, params, agent_linger_s=60.0)
        prompt = _prompt(5)
        a = _req("A", prompt, key="thread-A")
        eng.submit(a)
        eng.run_to_completion()
        eng.note_tool_gap("thread-A")
        a2 = _req("A2", prompt + list(a.output_ids) + [3], key="thread-A")
        eng.submit(a2)
        assert "thread-A" not in eng._agent_gaps
        eng.run_to_completion()
        assert eng.agent_gap_demotions == 0

    def test_idle_engine_still_fires_linger(self, model):
        # has_work includes pending gaps: run_to_completion on an
        # otherwise-idle engine keeps stepping until the demote fires
        cfg, params = model
        eng = make_engine(cfg, params, agent_linger_s=0.05)
        a = _req("A", _prompt(6), key="thread-A")
        eng.submit(a)
        eng.run_to_completion()
        eng.note_tool_gap("thread-A")
        assert eng.has_work
        eng.run_to_completion()
        assert eng.agent_gap_demotions == 1
        assert eng.prefix_cache.host_nodes > 0

    def test_return_kicks_wake_prefetcher(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        a = _req("A", _prompt(8), key="thread-A")
        eng.submit(a)
        eng.run_to_completion()
        eng.note_tool_gap("thread-A")
        eng.step()
        assert eng.agent_gap_demotions == 1

        calls = []

        class _Pre:
            def prefetch_thread(self, key, min_depth=0):
                calls.append((key, min_depth))

            def staged_bytes_for(self, key):
                return 0

        class _Obj:
            prefetcher = _Pre()

        eng.kv_tier.object = _Obj()
        eng.note_tool_return("thread-A")
        assert calls and calls[0][0] == "thread-A"
        # host runs still hold the whole chain: min_depth covers it, so
        # the prefetcher won't issue object GETs below that depth
        assert calls[0][1] > 0

    def test_unknown_return_is_a_hint_miss(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        eng.note_tool_return("nobody")
        assert eng.agent_hint_misses == 1
        assert eng.agent_hint_hits == 0

    def test_knob_off_is_inert(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, agent_demote="")
        a = _req("A", _prompt(9), key="thread-A")
        eng.submit(a)
        eng.run_to_completion()
        eng.note_tool_gap("thread-A")
        eng.note_tool_return("thread-A")
        eng.step()
        sec = eng.agent_section()
        assert all(sec[k] == 0 for k in AGENT_METRIC_KEYS)
        assert eng.awaiting_tool_keys() == []
        assert eng.prefix_cache.host_nodes == 0

    def test_lane_table_flags_awaiting_thread(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        a = _req("A", _prompt(10), key="thread-A")
        eng.submit(a)
        eng.run_to_completion()
        eng.note_tool_gap("thread-A")
        eng.step()
        rows = [r for r in eng.lane_table() if r.get("awaiting_tool")]
        assert len(rows) == 1
        row = rows[0]
        assert row["state"] == "awaiting_tool"
        assert row["demoted_pages"] > 0
        assert not row["lingering"]

    def test_object_mode_drops_to_store_when_host_tier_refuses(
            self, model, tmp_path):
        """The ladder's first rung missing (kv_host_tier_mb=0): a durable
        archive licenses the direct-to-object drop — pages free at the
        gap, the follow-up wakes from the store, token-identical."""
        cfg, params = model
        eng = make_engine(cfg, params, num_pages=48, max_pages_per_seq=32,
                          kv_host_tier_mb=0,
                          kv_object_dir=str(tmp_path / "on"),
                          agent_demote="object")
        prompt = _prompt(3, n=160)
        a = _req("A", prompt, key="thread-A")
        eng.submit(a)
        eng.run_to_completion()
        free0 = eng.pool.free_pages
        eng.note_tool_gap("thread-A")
        eng.step()
        # host tier refused every run (budget 0) yet HBM freed anyway:
        # the chain dropped to the object rung, not to a host run
        assert eng.pool.free_pages > free0
        assert eng.agent_gap_pages_demoted > 0
        assert eng.prefix_cache._host_nodes == 0
        eng.note_tool_return("thread-A")
        assert eng.agent_hint_hits == 1
        follow = list(prompt) + list(a.output_ids) + [5, 6, 7, 8]
        time.sleep(0.1)  # prefetch staging window (sync wake also works)
        b = _req("B", follow, key="thread-A")
        eng.submit(b)
        eng.run_to_completion()
        assert b.cache_source == "object_tier"
        assert b.cached_tokens >= (len(prompt) // 8) * 8
        # token identity against a knobs-off untiered engine
        ref = make_engine(cfg, params, num_pages=48, max_pages_per_seq=32,
                          agent_demote="")
        ra = _req("A", prompt, key="thread-A")
        ref.submit(ra)
        ref.run_to_completion()
        rb = _req("B", follow, key="thread-A")
        ref.submit(rb)
        ref.run_to_completion()
        assert list(ra.output_ids) == list(a.output_ids)
        assert list(rb.output_ids) == list(b.output_ids)

    def test_object_mode_without_manifest_never_drops(self, model,
                                                      tmp_path, monkeypatch):
        """A failed archive (store write fault) must fall back to the
        never-drop rule: refused host demote + no durable manifest keeps
        the chain in HBM."""
        from kafka_tpu import failpoints as fp

        cfg, params = model
        eng = make_engine(cfg, params, num_pages=48, max_pages_per_seq=32,
                          kv_host_tier_mb=0,
                          kv_object_dir=str(tmp_path / "on"),
                          agent_demote="object")
        prompt = _prompt(4, n=160)
        a = _req("A", prompt, key="thread-A")
        eng.submit(a)
        eng.run_to_completion()
        free0 = eng.pool.free_pages
        eng.note_tool_gap("thread-A")
        with fp.armed("kv.object_put", "error"):
            eng.step()
        # archive torn -> no manifest -> refusal keeps the chain hot
        assert eng.pool.free_pages == free0
        assert eng.agent_gap_pages_demoted == 0
        follow = list(prompt) + list(a.output_ids) + [5, 6, 7, 8]
        b = _req("B", follow, key="thread-A")
        eng.submit(b)
        eng.run_to_completion()
        assert b.cached_tokens > 0  # still device-resident


# ---------------------------------------------------------------------------
# background priority class
# ---------------------------------------------------------------------------


class TestBackgroundClass:
    # both 96-token prompts must fit the pool TOGETHER (admission defers
    # on pages, not class, otherwise) and prefill must take several
    # 32-bucket chunks — one 128-bucket chunk leaves nothing to yield
    BG_ECFG = dict(num_pages=64, prefill_buckets=(8, 16, 32),
                   flight_ring=256)

    def test_bg_yields_to_interactive_and_output_identical(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, **self.BG_ECFG)
        bg_prompt = _prompt(11, 96)
        fg_prompt = _prompt(12, 96)
        bg = _req("bg", bg_prompt, background=True, max_new=6)
        fg = _req("fg", fg_prompt, max_new=6)
        eng.submit(bg)
        eng.submit(fg)
        assert eng.agent_section()["bg_queue_depth"] == 1
        eng.run_to_completion()
        assert fg.finish_reason and bg.finish_reason
        # the interactive lane's prefill never waited on the bg dump
        assert fg.first_token_time < bg.first_token_time
        assert eng.bg_admitted == 1
        assert eng.bg_yields > 0
        assert eng.bg_chunks > 0
        causes = set()
        for r in eng.flight.records():
            causes.update(r.get("causes", {}))
        assert {"bg_admit", "bg_yield", "bg_prefill"} <= causes

        # scheduling priority must not change bytes: same request run
        # FOREGROUND on a fresh engine produces identical tokens
        ref = make_engine(cfg, params, **self.BG_ECFG)
        ref_r = _req("ref", bg_prompt, max_new=6)
        ref.submit(ref_r)
        ref.run_to_completion()
        assert ref_r.output_ids == bg.output_ids

    def test_bg_admits_only_into_idle_capacity(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        fgs = [_req(f"fg{i}", _prompt(20 + i, 48), max_new=5)
               for i in range(3)]
        bg = _req("bg", _prompt(30, 48), background=True, max_new=5)
        eng.submit(bg)
        for r in fgs:
            eng.submit(r)
        eng.run_to_completion()
        assert eng.bg_admitted == 1
        assert all(r.finish_reason for r in fgs + [bg])
        # every interactive request got its first token before the
        # background dump (bg was submitted FIRST — class, not FIFO)
        assert bg.first_token_time > max(r.first_token_time for r in fgs)

    def test_bg_exempt_from_max_waiting(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, max_waiting=1)
        eng.submit(_req("fg0", [1, 2, 3]))  # queue now full
        with pytest.raises(AdmissionError):
            eng.submit(_req("fg1", [1, 2, 4]))
        # background is deferred work — rejecting it with Retry-After
        # would just convert it into interactive retry pressure
        eng.submit(_req("bg", [1, 2, 7], background=True))
        eng.run_to_completion()

    def test_bg_reclaims_cold_cache_on_idle_engine(self, model):
        """A cache-saturated but otherwise idle engine must not starve
        its background queue: bg admission reclaims cold radix KV (the
        same eviction interactive admission runs) while honoring the
        park reserve."""
        cfg, params = model
        eng = make_engine(cfg, params, **self.BG_ECFG)
        # saturate the pool with cold cached KV
        for i in range(4):
            eng.submit(_req(f"w{i}", _prompt(40 + i, n=96),
                            key=f"w-t{i}", max_new=4))
            eng.run_to_completion()
        reserve = 2 * eng.ecfg.max_batch
        bg = _req("bg", _prompt(50, n=96), key="bg-t", background=True)
        needed = -(-(96 + 1) // eng.ecfg.page_size)  # no shared prefix
        assert needed > eng.pool.free_pages - reserve
        eng.submit(bg)
        eng.run_to_completion()
        assert eng.bg_admitted == 1
        assert len(bg.output_ids) == 8

    def test_cancel_waiting_background(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        bg = _req("bg", [1, 2, 3], background=True)
        eng.submit(bg)
        assert eng.cancel("bg")
        assert not eng.waiting_bg
        assert eng.agent_section()["bg_queue_depth"] == 0


# ---------------------------------------------------------------------------
# metric registry + exposition
# ---------------------------------------------------------------------------


class TestAgentMetricsRegistry:
    def _source(self, relpath):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(root, relpath)) as f:
            return f.read()

    def test_registry_both_directions(self):
        metrics_src = self._source("kafka_tpu/runtime/metrics.py")
        prom_src = self._source("kafka_tpu/server/prometheus.py")
        for key in AGENT_METRIC_KEYS:
            assert f'"{key}"' in metrics_src, (
                f"{key} missing from runtime/metrics.py"
            )
            assert f'"{key}"' in prom_src, (
                f"{key} missing from server/prometheus.py"
            )

    def test_agent_section_matches_registry_exactly(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        assert set(eng.agent_section()) == set(AGENT_METRIC_KEYS)

    def test_new_flight_causes_registered(self):
        for cause in ("agent_demote", "bg_admit", "bg_prefill", "bg_yield"):
            assert cause in CAUSES, cause

    def test_snapshot_and_prometheus_families(self, model):
        from kafka_tpu.server.prometheus import render_prometheus

        cfg, params = model
        eng = make_engine(cfg, params)
        a = _req("A", _prompt(13), key="thread-A")
        eng.submit(a)
        eng.run_to_completion()
        eng.note_tool_gap("thread-A")
        eng.step()
        snap = eng.metrics.snapshot(eng)
        assert snap["agent"]["agent_gap_demotions"] == 1
        text = render_prometheus(snap)
        for family in ("kafka_tpu_agent_events_total",
                       "kafka_tpu_agent_gap_pages_demoted_total",
                       "kafka_tpu_agent_gap_bytes_demoted_total",
                       "kafka_tpu_agent_awaiting_threads",
                       "kafka_tpu_agent_awaiting_bytes",
                       "kafka_tpu_bg_queue_depth",
                       "kafka_tpu_bg_events_total"):
            assert f"# TYPE {family}" in text, family
        assert 'event="demote"' in text
        assert "kafka_tpu_agent_awaiting_threads 1" in text


# ---------------------------------------------------------------------------
# worker inbox routing (engine is single-writer)
# ---------------------------------------------------------------------------


class TestWorkerInbox:
    def test_gap_and_return_run_on_engine_thread(self, model):
        from kafka_tpu.llm.worker import EngineWorker

        cfg, params = model
        eng = make_engine(cfg, params)
        worker = EngineWorker(eng).start()
        try:
            async def go():
                loop = asyncio.get_running_loop()
                q = worker.submit(
                    _req("w1", _prompt(14), key="thread-A"), loop
                )
                while True:
                    ev = await asyncio.wait_for(q.get(), timeout=30)
                    if ev.finished:
                        return

            asyncio.run(go())
            worker.note_tool_gap("thread-A")
            deadline = time.monotonic() + 10
            while (eng.agent_gap_demotions < 1
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert eng.agent_gap_demotions == 1
            worker.note_tool_return("thread-A")
            deadline = time.monotonic() + 10
            while eng.agent_hint_hits < 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert eng.agent_hint_hits == 1
        finally:
            worker.stop()


# ---------------------------------------------------------------------------
# DP router: expected-return hints ride thread affinity
# ---------------------------------------------------------------------------


class TestRouterHints:
    ECFG = dict(max_batch=2, page_size=8, num_pages=32, max_pages_per_seq=8,
                prefill_buckets=(8, 16, 32), kv_host_tier_mb=64,
                agent_demote="host", agent_linger_s=60.0)

    def test_hint_pinned_to_affinity_replica(self, model):
        cfg, params = model
        dp = DataParallelEngines(cfg, params, EngineConfig(**self.ECFG),
                                 dp=2, tp=1, kv_dtype=jnp.float32)
        p = list(np.random.RandomState(9).randint(1, 128, 10))
        dp.submit(_req("t1", p, key="thread-A", max_new=4))
        dp.run_to_completion()
        idx = dp._affinity["thread-A"]
        other = 1 - idx
        dp.note_tool_gap("thread-A")
        assert dp._expected_returns["thread-A"] == idx
        assert dp.engines[idx].agent_gaps == 1
        assert dp.engines[other].agent_gaps == 0
        dp.note_tool_return("thread-A")
        assert "thread-A" not in dp._expected_returns
        assert dp.engines[idx].agent_gap_cancelled == 1
        assert dp.engines[other].agent_gap_cancelled == 0
        # aggregate /metrics sums the per-replica agent sections
        agg = dp.metrics.snapshot()
        assert agg["agent"]["agent_gaps"] == 1
        assert agg["agent"]["agent_gap_cancelled"] == 1

    def test_unknown_thread_is_a_noop(self, model):
        cfg, params = model
        dp = DataParallelEngines(cfg, params, EngineConfig(**self.ECFG),
                                 dp=2, tp=1, kv_dtype=jnp.float32)
        dp.note_tool_gap("ghost")    # no affinity: nothing locatable
        dp.note_tool_return("ghost")
        assert not dp._expected_returns
        assert all(e.agent_gaps == 0 for e in dp.engines)

    def test_expected_returns_lru_capped(self, model):
        cfg, params = model
        dp = DataParallelEngines(cfg, params, EngineConfig(**self.ECFG),
                                 dp=2, tp=1, kv_dtype=jnp.float32)
        dp._expected_cap = 2
        for k in ("a", "b", "c"):
            dp._affinity[k] = 0
            dp.note_tool_gap(k)
        assert list(dp._expected_returns) == ["b", "c"]


# ---------------------------------------------------------------------------
# agent loop + compaction integration
# ---------------------------------------------------------------------------


class _Chunk:
    """Minimal StreamChunk stand-in for the agent loop."""

    def __init__(self, content=None, tool_calls=None, finish_reason=None):
        self.content = content
        self.tool_calls = tool_calls
        self.finish_reason = finish_reason
        self.usage = None
        self.id = "c1"

    def to_openai_dict(self):
        return {"id": self.id}


class _ScriptedLLM:
    """Two scripted turns: a tool call, then text. Records the return
    hint and every stream_completion kwarg set."""

    provider_name = "fake"
    supports_background = True

    def __init__(self):
        self.returned = []
        self.seen_kwargs = []

    def note_tool_return(self, prefix_key):
        self.returned.append(prefix_key)

    async def stream_completion(self, messages, **kw):
        self.seen_kwargs.append(kw)
        if len(self.seen_kwargs) == 1:
            yield _Chunk(tool_calls=[{
                "index": 0, "id": "call_1",
                "function": {"name": "add", "arguments": '{"a":1,"b":2}'},
            }])
            yield _Chunk(finish_reason="tool_calls")
        else:
            yield _Chunk(content="done")
            yield _Chunk(finish_reason="stop")


def _make_agent(llm, **kw):
    from kafka_tpu.agents.base import Agent
    from kafka_tpu.tools.provider import AgentToolProvider, Tool

    def add(a: int, b: int):
        return a + b

    tools = AgentToolProvider(tools=[
        Tool(name="add", description="add",
             parameters={"type": "object", "properties": {
                 "a": {"type": "integer"}, "b": {"type": "integer"}}},
             handler=add),
    ])
    return Agent(llm, tools, system_prompt="sys", **kw)


class TestAgentLoopIntegration:
    def test_return_hint_fires_after_tool_batch(self):
        llm = _ScriptedLLM()
        agent = _make_agent(llm)

        async def go():
            events = []
            async for ev in agent.run(
                [{"role": "user", "content": "hi"}], prefix_key="thread-A"
            ):
                events.append(ev)
            return events

        events = asyncio.run(go())
        assert events[-1]["type"] == "agent_done"
        # the hint fired exactly once, between the tool batch and the
        # follow-up turn, carrying the thread identity
        assert llm.returned == ["thread-A"]
        # not opted in: no turn rode the background class
        assert not any(kw.get("background") for kw in llm.seen_kwargs)

    def test_tool_result_turn_rides_background_class(self):
        llm = _ScriptedLLM()
        agent = _make_agent(llm, background_tool_turns=True)

        async def go():
            async for _ in agent.run([{"role": "user", "content": "hi"}]):
                pass

        asyncio.run(go())
        assert len(llm.seen_kwargs) == 2
        # turn 1 (the user prompt) is interactive; turn 2's prompt is
        # dominated by tool results — that one rides the bg class
        assert not llm.seen_kwargs[0].get("background")
        assert llm.seen_kwargs[1].get("background") is True

    def test_compaction_summarization_rides_background(self):
        from kafka_tpu.core.types import CompletionResponse
        from kafka_tpu.llm.base import LLMProvider
        from kafka_tpu.llm.compaction.v1 import (
            SummarizationCompactionProvider,
        )

        class _Summarizer(LLMProvider):
            provider_name = "fake"
            supports_background = True

            def __init__(self):
                self.kwargs = []

            async def stream_completion(self, messages, **kw):
                raise AssertionError("unused")
                yield  # pragma: no cover

            async def completion(self, messages, **kw):
                self.kwargs.append(kw)
                return CompletionResponse(content="SUMMARY",
                                          finish_reason="stop")

        llm = _Summarizer()
        prov = SummarizationCompactionProvider(llm, min_messages=2)
        msgs = [{"role": "user", "content": f"m{i}"} for i in range(12)]
        out = asyncio.run(prov.compact(msgs))
        assert llm.kwargs and llm.kwargs[0].get("background") is True
        assert any("SUMMARY" in str(m.get("content")) for m in out)
        # a provider without the capability never sees the kwarg
        llm2 = _Summarizer()
        llm2.supports_background = False
        prov2 = SummarizationCompactionProvider(llm2, min_messages=2)
        asyncio.run(prov2.compact(msgs))
        assert "background" not in llm2.kwargs[0]


# ---------------------------------------------------------------------------
# object-store auth: AWS SigV4 + bearer
# ---------------------------------------------------------------------------

AKID, SECRET = "AKIDEXAMPLE", "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY"


def _sigv4_env(monkeypatch, secret=SECRET, token=""):
    monkeypatch.setenv(ENV_OBJECT_AUTH, "sigv4")
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", AKID)
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", secret)
    monkeypatch.setenv("AWS_REGION", "us-east-1")
    if token:
        monkeypatch.setenv("AWS_SESSION_TOKEN", token)
    else:
        monkeypatch.delenv("AWS_SESSION_TOKEN", raising=False)


class TestObjectAuth:
    def test_sigv4_round_trip_stub_verifies_signature(self, monkeypatch):
        _sigv4_env(monkeypatch)
        with StubS3Server() as srv:
            srv.auth_secret = (AKID, SECRET)
            st = HTTPObjectStore(srv.url)
            payload = os.urandom(2048)
            st.put("objects/x.npz", payload)
            assert st.get("objects/x.npz") == payload
            assert st.head("objects/x.npz")[0] == len(payload)
            st.put("refs/x/a", b"")
            st.put("refs/x/b", b"")
            # the listing query ('/' in the prefix) exercises query
            # canonicalization — loose encoding breaks the signature
            assert sorted(st.list("refs/x/")) == ["refs/x/a", "refs/x/b"]
            assert st.put_if_absent("objects/x.npz", payload) is False
            st.delete("objects/x.npz")
            assert st.get("objects/x.npz") is None

            hdrs = srv.captured_headers[0]
            auth = hdrs["authorization"]
            assert auth.startswith(
                f"AWS4-HMAC-SHA256 Credential={AKID}/"
            )
            assert "/us-east-1/s3/aws4_request" in auth
            assert "host;x-amz-content-sha256;x-amz-date" in auth
            assert re.fullmatch(r"\d{8}T\d{6}Z", hdrs["x-amz-date"])
            assert hdrs["x-amz-content-sha256"] == hashlib.sha256(
                payload
            ).hexdigest()

    def test_sigv4_wrong_secret_rejected(self, monkeypatch):
        _sigv4_env(monkeypatch, secret="the-wrong-secret")
        with StubS3Server() as srv:
            srv.auth_secret = (AKID, SECRET)
            st = HTTPObjectStore(srv.url)
            with pytest.raises(OSError, match="403"):
                st.put("objects/x.npz", b"payload")
            assert not srv.objects  # rejected writes never land

    def test_sigv4_session_token_is_signed(self, monkeypatch):
        _sigv4_env(monkeypatch, token="THE-SESSION-TOKEN")
        with StubS3Server() as srv:
            srv.auth_secret = (AKID, SECRET)
            st = HTTPObjectStore(srv.url)
            st.put("objects/t", b"tok")
            assert st.get("objects/t") == b"tok"
            hdrs = srv.captured_headers[0]
            assert hdrs["x-amz-security-token"] == "THE-SESSION-TOKEN"
            assert "x-amz-security-token" in hdrs["authorization"]

    def test_bearer_round_trip_and_rejection(self, monkeypatch):
        monkeypatch.setenv(ENV_OBJECT_AUTH, "bearer")
        monkeypatch.setenv(ENV_OBJECT_BEARER, "sesame")
        with StubS3Server() as srv:
            srv.bearer_token = "sesame"
            st = HTTPObjectStore(srv.url)
            st.put("objects/x", b"data")
            assert st.get("objects/x") == b"data"
            assert srv.captured_headers[0]["authorization"] == (
                "Bearer sesame"
            )
            monkeypatch.setenv(ENV_OBJECT_BEARER, "wrong")
            bad = HTTPObjectStore(srv.url)
            with pytest.raises(OSError, match="401"):
                bad.put("objects/y", b"data")

    def test_unauthed_request_rejected_when_stub_requires(self, monkeypatch):
        monkeypatch.delenv(ENV_OBJECT_AUTH, raising=False)
        with StubS3Server() as srv:
            srv.auth_secret = (AKID, SECRET)
            st = HTTPObjectStore(srv.url)
            with pytest.raises(OSError, match="403"):
                st.put("objects/x", b"data")

    def test_load_object_auth_validation(self, monkeypatch):
        monkeypatch.delenv(ENV_OBJECT_AUTH, raising=False)
        assert _load_object_auth() == ("", {})
        monkeypatch.setenv(ENV_OBJECT_AUTH, "sigv4")
        monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
        monkeypatch.delenv("AWS_SECRET_ACCESS_KEY", raising=False)
        with pytest.raises(ValueError, match="AWS_ACCESS_KEY_ID"):
            _load_object_auth()
        monkeypatch.setenv(ENV_OBJECT_AUTH, "bearer")
        monkeypatch.delenv(ENV_OBJECT_BEARER, raising=False)
        with pytest.raises(ValueError, match="BEARER"):
            _load_object_auth()
        monkeypatch.setenv(ENV_OBJECT_AUTH, "kerberos")
        with pytest.raises(ValueError, match="kerberos"):
            _load_object_auth()

    def test_sigv4_headers_deterministic_with_pinned_clock(self):
        now = time.gmtime(1722816000)  # 2024-08-05T00:00:00Z
        kw = dict(method="PUT", host="bucket.example.com",
                  path="/objects/a%2Fb?list-type=2&prefix=refs/x/",
                  headers={"Content-Length": "3"}, body=b"abc",
                  access_key=AKID, secret_key=SECRET, region="eu-west-1")
        h1 = _sigv4_headers(now=now, **kw)
        h2 = _sigv4_headers(now=now, **kw)
        assert h1 == h2
        assert h1["x-amz-date"] == "20240805T000000Z"
        assert h1["Host"] == "bucket.example.com"
        assert h1["x-amz-content-sha256"] == hashlib.sha256(
            b"abc"
        ).hexdigest()
        assert "Credential=AKIDEXAMPLE/20240805/eu-west-1/s3/aws4_request" \
            in h1["Authorization"]
        sig = re.search(r"Signature=([0-9a-f]{64})$", h1["Authorization"])
        assert sig is not None
        # the signature covers the body: a different payload re-signs
        h3 = _sigv4_headers(now=now, **{**kw, "body": b"abd"})
        assert h3["Authorization"] != h1["Authorization"]


# ---------------------------------------------------------------------------
# tool-execution failpoint (agent.tool)
# ---------------------------------------------------------------------------


class TestToolFailpoint:
    def _provider(self):
        from kafka_tpu.tools.provider import AgentToolProvider
        from kafka_tpu.tools.types import Tool

        prov = AgentToolProvider()
        prov.register_tool(Tool(
            name="add",
            description="add two ints",
            parameters={"type": "object", "properties": {
                "a": {"type": "integer"}, "b": {"type": "integer"}},
                "required": ["a", "b"]},
            handler=lambda a, b: str(a + b),
        ))
        return prov

    def test_delay_injects_tool_latency(self):
        from kafka_tpu import failpoints as fp

        prov = self._provider()

        async def call():
            evs = []
            async for ev in prov.run_tool_stream("add", {"a": 1, "b": 2},
                                                 tool_call_id="c1"):
                evs.append(ev)
            return evs

        with fp.armed("agent.tool", "delay", arg=0.2):
            t0 = time.monotonic()
            evs = asyncio.run(call())
            took = time.monotonic() - t0
        assert took >= 0.2
        assert any(ev.kind != "error" for ev in evs)

    def test_error_surfaces_as_tool_error_event(self):
        from kafka_tpu import failpoints as fp

        prov = self._provider()

        async def call():
            return [ev async for ev in prov.run_tool_stream(
                "add", {"a": 1, "b": 2}, tool_call_id="c2")]

        with fp.armed("agent.tool", "error"):
            evs = asyncio.run(call())
        assert evs and evs[0].kind == "error"
        assert "injected" in evs[0].data


# ---------------------------------------------------------------------------
# bench smoke: the agent_gap A/B phase on CPU
# ---------------------------------------------------------------------------


class TestBenchSmoke:
    def test_agent_gap_phase_cpu(self, model):
        import importlib.util
        import sys

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "bench", os.path.join(root, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        sys.modules["bench"] = bench
        spec.loader.exec_module(bench)
        cfg, params = model
        out = bench.agent_gap_phase(cfg, params, n_agents=3,
                                    agent_len=448, churn_requests=6,
                                    churn_len=256, page_size=8)
        # the acceptance set (ISSUE 20): identical token streams, pages
        # measurably released mid-gap only with the knob on, the gap-on
        # follow-up strictly faster with ZERO recomputed prompt tokens
        assert out["outputs_match"]
        assert out["cache_sources_on"] == ["object_tier"] * 3
        assert out["prompt_tokens_recomputed"]["gap_on"] == 0
        assert out["prompt_tokens_recomputed"]["gap_off"] > 0
        assert out["hbm_pages_freed_mid_gap"]["gap_on"] > 0
        assert out["hbm_pages_freed_mid_gap"]["gap_off"] == 0
        on = out["followup_ttft_mean_ms"]["gap_on"]
        off = out["followup_ttft_mean_ms"]["gap_off"]
        assert on < off, out
        assert out["agent"]["agent_hint_hits"] == 3
        assert out["bg"]["admitted"] == 1
