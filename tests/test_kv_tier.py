"""Tiered KV cache (ISSUE 9): host-RAM page tier under the PagePool.

The load-bearing claims:
  * page runs round-trip byte-exact through the host tier AND the disk
    tier (demote -> overwrite the source pages -> promote -> compare),
  * a thread whose KV was evicted under page pressure resumes with its
    prefill starting at the promoted page boundary
    (cache_source="host_tier"), token-identical to an untiered engine,
  * randomized store/demote/promote/evict/invalidate interleavings keep
    PagePool.check_consistency + reconcile clean and every promoted page
    byte-exact,
  * a failed/torn promote degrades to re-prefill (never corrupt KV), a
    failed demote falls back to plain eviction — both via the kv.demote /
    kv.promote failpoints,
  * with the tier knobs unset nothing is built and dispatch/eviction
    behavior is unchanged,
  * KV_TIER_METRIC_KEYS is a both-directions registry across
    runtime/metrics.py and server/prometheus.py,
  * the span ring persists alongside the disk tier and survives reset,
  * large-vocab grammar compiles defer to the background worker
    (constrained_compile_pending gauge) instead of stalling the first
    call.
"""

import os
import random
import re
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.models import ModelConfig, init_params
from kafka_tpu.runtime import (
    EngineConfig,
    GenRequest,
    InferenceEngine,
    PagePool,
)
from kafka_tpu.runtime import failpoints, tracing
from kafka_tpu.runtime.kv_tier import (
    SHIP_BUCKETS,
    KVTierManager,
    LocalPageShipper,
    _bucketize,
)
from kafka_tpu.runtime.prefix_cache import PrefixCache


class _Owner:
    """Minimal pool-array holder standing in for the engine (the shipper
    only needs mutable k_pool/v_pool)."""

    def __init__(self, num_pages, page_size, layers=2, width=8, seed=0,
                 dtype=np.float32):
        rng = np.random.default_rng(seed)
        shape = (layers, num_pages * page_size, width)
        self.k_pool = jnp.asarray(
            rng.normal(size=shape).astype(np.float32)
        ).astype(dtype)
        self.v_pool = jnp.asarray(
            rng.normal(size=shape).astype(np.float32)
        ).astype(dtype)


def _rows(owner, pages, page_size, pool="k"):
    arr = np.asarray(owner.k_pool if pool == "k" else owner.v_pool)
    return np.concatenate(
        [arr[:, p * page_size:(p + 1) * page_size] for p in pages], axis=1
    )


def _write_rows(owner, pages, page_size, k_rows, v_rows):
    for i, p in enumerate(pages):
        sl = slice(p * page_size, (p + 1) * page_size)
        src = slice(i * page_size, (i + 1) * page_size)
        owner.k_pool = owner.k_pool.at[:, sl].set(k_rows[:, src])
        owner.v_pool = owner.v_pool.at[:, sl].set(v_rows[:, src])


class TestShipper:
    def test_bucketize(self):
        assert _bucketize(1) == [1]
        assert _bucketize(3) == [4]
        assert _bucketize(64) == [64]
        assert _bucketize(65) == [64, 1]
        assert _bucketize(200) == [64, 64, 64, 8]
        assert sum(_bucketize(37)) >= 37

    def test_host_round_trip_exact(self):
        ps = 4
        o = _Owner(16, ps, seed=1)
        ship = LocalPageShipper(o, ps)
        mgr = KVTierManager(ship, host_budget_bytes=1 << 30, page_size=ps)
        pages = [3, 7, 5]
        want_k = _rows(o, pages, ps, "k")
        want_v = _rows(o, pages, ps, "v")
        rid = mgr.demote(pages)
        assert rid is not None
        mgr.drain(force=True)
        # clobber the source pages: promote must restore from the copy
        for p in pages:
            o.k_pool = o.k_pool.at[:, p * ps:(p + 1) * ps].set(0.0)
        dest = [1, 2, 9]
        assert mgr.promote(rid, dest)
        assert np.array_equal(_rows(o, dest, ps, "k"), want_k)
        assert np.array_equal(_rows(o, dest, ps, "v"), want_v)

    def test_multi_chunk_run_round_trips(self):
        # a run longer than the largest ship bucket crosses chunks
        ps, n = 2, SHIP_BUCKETS[-1] + 3
        o = _Owner(n + 10, ps, layers=1, width=4, seed=2)
        ship = LocalPageShipper(o, ps)
        mgr = KVTierManager(ship, host_budget_bytes=1 << 30, page_size=ps)
        pages = list(range(2, 2 + n))
        want = _rows(o, pages, ps, "k")
        rid = mgr.demote(pages)
        assert rid is not None
        dest = list(range(2, 2 + n))  # reuse the same slots
        o.k_pool = jnp.zeros_like(o.k_pool)
        assert mgr.promote(rid, dest)
        assert np.array_equal(_rows(o, dest, ps, "k"), want)

    def test_disk_round_trip_exact_bf16(self, tmp_path):
        ps = 4
        o = _Owner(16, ps, seed=3, dtype=jnp.bfloat16)
        ship = LocalPageShipper(o, ps)
        mgr = KVTierManager(ship, host_budget_bytes=0, page_size=ps,
                            disk_dir=str(tmp_path))
        mgr.host_budget_bytes = ship.bytes_per_page() * 2  # one 2-page run
        pages = [6, 7]
        want = _rows(o, pages, ps, "k")
        rid = mgr.demote(pages)
        assert rid is not None
        mgr.drain(force=True)
        rid2 = mgr.demote([1, 2])  # overflows the budget: rid spills
        assert rid2 is not None
        mgr.flush()
        snap = mgr.snapshot()
        # at least the over-budget run spilled; drain()'s budget
        # re-enforcement may also spill the second while the first's
        # write is still charged as host bytes (honest accounting —
        # both stay promotable either way)
        assert snap["disk_spills"] >= 1
        assert snap["disk_runs"] == snap["disk_spills"]
        assert os.listdir(tmp_path)
        o.k_pool = jnp.zeros_like(o.k_pool)
        assert mgr.promote(rid, [10, 11])
        assert np.array_equal(_rows(o, [10, 11], ps, "k"), want)
        assert mgr.snapshot()["disk_loads"] == 1

    def test_second_chance_keeps_touched_run(self, tmp_path):
        ps = 2
        o = _Owner(32, ps, layers=1, width=4, seed=4)
        ship = LocalPageShipper(o, ps)
        mgr = KVTierManager(ship, host_budget_bytes=0, page_size=ps)
        mgr.host_budget_bytes = ship.bytes_per_page() * 4  # two 2-page runs
        r1 = mgr.demote([1, 2])
        r2 = mgr.demote([3, 4])
        mgr.drain(force=True)
        mgr.touch(r1)  # reference bit: r1 gets a second chance
        r3 = mgr.demote([5, 6])  # overflow: victim should be r2, not r1
        assert r3 is not None
        assert mgr.snapshot()["host_evictions"] == 1
        assert mgr.promote(r1, [10, 11])  # survived
        assert not mgr.promote(r2, [12, 13])  # dropped -> promote fails

    def test_split_preserves_bytes(self):
        ps = 2
        o = _Owner(32, ps, layers=1, width=4, seed=5)
        ship = LocalPageShipper(o, ps)
        mgr = KVTierManager(ship, host_budget_bytes=1 << 30, page_size=ps)
        pages = [4, 5, 6]
        want = _rows(o, pages, ps, "k")
        rid = mgr.demote(pages)
        parts = mgr.split(rid, 1)
        assert parts is not None
        front, back = parts
        assert mgr.promote(front, [10])
        assert mgr.promote(back, [11, 12])
        got = _rows(o, [10, 11, 12], ps, "k")
        assert np.array_equal(got, want)

    def test_oversized_run_refused(self):
        ps = 2
        o = _Owner(16, ps, layers=1, width=4)
        ship = LocalPageShipper(o, ps)
        mgr = KVTierManager(ship, host_budget_bytes=1, page_size=ps)
        assert mgr.demote([1, 2, 3]) is None  # never fits: refused


class TestPrefixCacheTier:
    def _setup(self, num_pages=32, ps=4, budget=1 << 30, disk=None):
        o = _Owner(num_pages, ps, seed=11)
        pool = PagePool(num_pages=num_pages, page_size=ps)
        mgr = KVTierManager(LocalPageShipper(o, ps),
                            host_budget_bytes=budget, page_size=ps,
                            disk_dir=disk)
        cache = PrefixCache(pool, tier=mgr)
        return o, pool, mgr, cache

    def _store(self, o, pool, cache, key, tokens, rng):
        """Alloc pages, stamp them with a token-derived pattern (stand-in
        for real KV writes), store, release the sequence's holds."""
        ps = pool.page_size
        n = len(tokens) // ps
        pages = pool.alloc(n)
        k = np.empty((2, n * ps, 8), np.float32)
        v = np.empty((2, n * ps, 8), np.float32)
        for i in range(n):
            k[:, i * ps:(i + 1) * ps] = float(tokens[i * ps]) + 0.25
            v[:, i * ps:(i + 1) * ps] = float(tokens[i * ps]) + 0.5
        _write_rows(o, pages, ps, k, v)
        cache.store(key, tokens, pages)
        pool.release(pages)

    def _verify_hit(self, o, ps, prompt, hit):
        """Every returned page must carry the pattern of its token page."""
        for i, p in enumerate(hit.pages):
            tok = float(prompt[i * ps])
            k = np.asarray(o.k_pool)[:, p * ps:(p + 1) * ps]
            v = np.asarray(o.v_pool)[:, p * ps:(p + 1) * ps]
            assert np.all(k == tok + 0.25), f"K page {i} corrupt"
            assert np.all(v == tok + 0.5), f"V page {i} corrupt"

    def test_demote_then_promote_hit(self):
        o, pool, mgr, cache = self._setup()
        rng = random.Random(0)
        tokens = [rng.randrange(100) for _ in range(12)]
        self._store(o, pool, cache, "t1", tokens, rng)
        assert cache.reclaim(pool.free_pages + 3)
        assert cache.host_nodes == 1 and cache.total_pages == 0
        # still matchable: the router counts host runs as affinity
        assert cache.match_tokens(tokens + [1]) == 12
        hit = cache.lookup("t1", tokens + [1])
        assert hit is not None and hit.source == "host_tier"
        assert hit.promoted_tokens == 12 and hit.tokens == 12
        self._verify_hit(o, pool.page_size, tokens, hit)
        pool.release(hit.pages)
        assert not pool.check_consistency()
        assert not pool.reconcile(cache.page_owners())

    def test_promotion_reclaims_other_leaves(self):
        # pool too small to hold the promoted run AND the other cached
        # run: promotion must demote the cold one, never truncate
        o, pool, mgr, cache = self._setup(num_pages=12, ps=4)
        rng = random.Random(1)
        hot = [rng.randrange(50) for _ in range(24)]       # 6 pages
        cold = [50 + rng.randrange(50) for _ in range(24)]  # 6 pages
        self._store(o, pool, cache, "hot", hot, rng)
        assert cache.reclaim(pool.free_pages + 6)  # demote hot
        self._store(o, pool, cache, "cold", cold, rng)
        assert pool.free_pages < 6  # cold's pages crowd the pool
        hit = cache.lookup("hot", hot + [1])
        assert hit is not None and hit.promoted_tokens == 24
        self._verify_hit(o, 4, hot, hit)
        assert cache.host_nodes == 1  # cold got demoted to make room
        pool.release(hit.pages)
        assert not pool.check_consistency()

    def test_store_adopts_host_run(self):
        o, pool, mgr, cache = self._setup()
        rng = random.Random(2)
        tokens = [rng.randrange(100) for _ in range(8)]
        self._store(o, pool, cache, "a", tokens, rng)
        assert cache.reclaim(pool.free_pages + 2)
        assert cache.host_nodes == 1
        # a sibling stores the same prefix with freshly-computed pages
        self._store(o, pool, cache, "b", tokens, rng)
        assert cache.host_nodes == 0 and cache.total_pages == 2
        assert mgr.snapshot()["host_runs"] == 0  # run discarded (adopted)
        hit = cache.lookup("b", tokens + [1])
        assert hit.source == "own" and hit.promoted_tokens == 0
        pool.release(hit.pages)

    def test_invalidate_discards_host_runs(self):
        o, pool, mgr, cache = self._setup()
        rng = random.Random(3)
        tokens = [rng.randrange(100) for _ in range(8)]
        self._store(o, pool, cache, "a", tokens, rng)
        assert cache.reclaim(pool.free_pages + 2)
        cache.invalidate("a")
        assert len(cache) == 0 and cache.host_nodes == 0
        assert mgr.snapshot()["host_runs"] == 0
        assert not pool.check_consistency()

    def test_lost_run_degrades_to_miss_and_removes_node(self):
        o, pool, mgr, cache = self._setup()
        rng = random.Random(4)
        tokens = [rng.randrange(100) for _ in range(8)]
        self._store(o, pool, cache, "a", tokens, rng)
        assert cache.reclaim(pool.free_pages + 2)
        # simulate the tier losing the run (budget drop on a dir-less tier)
        run_id = next(iter(mgr._runs))
        mgr.discard(run_id)
        hit = cache.lookup("a", tokens + [1])
        assert hit is None  # degrade to re-prefill
        assert len(cache) == 0  # node removed
        assert mgr.promote_failures >= 1
        assert not pool.check_consistency()

    def test_randomized_tier_chaos(self):
        """store/demote/promote/evict/invalidate interleavings: allocator
        invariants hold after EVERY op and every hit's pages are
        byte-exact against the token-derived pattern."""
        o, pool, mgr, cache = self._setup(num_pages=48, ps=4, budget=0)
        mgr.host_budget_bytes = (
            mgr.shipper.bytes_per_page() * 20
        )  # tight: forces drops too
        rng = random.Random(1234)
        ps = 4
        threads = {}
        live_holds = []  # (pages,) retained by "live requests"

        def owners():
            own = dict(cache.page_owners())
            for pages in live_holds:
                for p in pages:
                    own[p] = own.get(p, 0) + 1
            return own

        for step in range(300):
            op = rng.randrange(7)
            if op <= 2 or not threads:  # store a (possibly shared) run
                if threads and rng.random() < 0.4:
                    base = list(rng.choice(list(threads.values())))
                    base = base[: ps * rng.randrange(
                        1, max(2, len(base) // ps + 1))]
                else:
                    base = []
                tail_pages = rng.randrange(1, 4)
                tokens = base + [rng.randrange(90)
                                 for _ in range(tail_pages * ps)]
                tokens = tokens[: (len(tokens) // ps) * ps]
                key = f"t{rng.randrange(8)}"
                if len(tokens) // ps > pool.free_pages:
                    cache.reclaim(len(tokens) // ps)
                if len(tokens) // ps <= pool.free_pages:
                    self._store(o, pool, cache, key, tokens, rng)
                    threads[key] = tokens
            elif op == 3:  # lookup (may promote) + verify + hold a bit
                key = rng.choice(list(threads))
                prompt = threads[key] + [rng.randrange(90)]
                hit = cache.lookup(key, prompt)
                if hit is not None:
                    self._verify_hit(o, ps, prompt, hit)
                    if rng.random() < 0.5 and len(live_holds) < 3:
                        live_holds.append(hit.pages)
                    else:
                        pool.release(hit.pages)
            elif op == 4:  # pressure reclaim (demotes or drops)
                cache.reclaim(pool.free_pages + rng.randrange(1, 6))
            elif op == 5:  # invalidate a thread
                key = rng.choice(list(threads))
                cache.invalidate(key)
                threads.pop(key, None)
            else:  # a live request retires
                if live_holds:
                    pool.release(live_holds.pop(
                        rng.randrange(len(live_holds))))
            if rng.random() < 0.3:
                mgr.drain(force=True)
            problems = pool.check_consistency()
            assert not problems, f"step {step}: {problems}"
            reports = pool.reconcile(owners())
            assert not reports, f"step {step}: {reports}"
        for pages in live_holds:
            pool.release(pages)
        cache.clear()
        mgr.flush()
        assert not pool.check_consistency()
        assert pool.free_pages == pool.num_pages - 1


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(name="tier-test", vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(7))
    return cfg, params


def make_engine(cfg, params, **kw):
    defaults = dict(max_batch=2, page_size=8, num_pages=24,
                    max_pages_per_seq=16,
                    prefill_buckets=(8, 16, 32, 64, 128),
                    kv_host_tier_mb=64)
    defaults.update(kw)
    return InferenceEngine(cfg, params, EngineConfig(**defaults),
                           kv_dtype=jnp.float32)


def _churn(eng, rng, n=3, prompt_len=96):
    for i in range(n):
        r = GenRequest(
            request_id=f"churn-{i}-{int(rng.integers(1 << 30))}",
            prompt_ids=[int(x) for x in rng.integers(1, 120, prompt_len)],
            max_new_tokens=4, prefix_key=f"churn-{i}",
        )
        eng.submit(r)
        eng.run_to_completion()


class TestEngineTierResume:
    def test_resume_starts_at_promoted_boundary(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        assert eng.kv_tier is not None
        rng = np.random.default_rng(3)
        prompt = [int(x) for x in rng.integers(1, 120, 64)]
        a = GenRequest(request_id="A", prompt_ids=prompt,
                       max_new_tokens=8, prefix_key="thread-A")
        eng.submit(a)
        eng.run_to_completion()
        _churn(eng, rng)
        pc = eng.prefix_cache
        assert pc.host_nodes > 0, "pressure must demote, not drop"

        tracing.reset()
        root = tracing.start_trace(request_id="resume-A")
        resume = prompt + list(a.output_ids) + [
            int(x) for x in rng.integers(1, 120, 12)
        ]
        a2 = GenRequest(request_id="A2", prompt_ids=resume,
                        max_new_tokens=8, prefix_key="thread-A",
                        trace=tracing.current())
        eng.submit(a2)
        eng.run_to_completion()
        tracing.finish_trace(root)

        assert a2.cache_source == "host_tier"
        assert a2.promoted_tokens > 0
        assert a2.cached_tokens >= a2.promoted_tokens
        # prefill began at the promoted boundary, not token zero
        assert a2.cached_tokens % eng.ecfg.page_size == 0
        assert pc.host_tier_hits == 1
        tr = tracing.get_trace("resume-A")
        names = [s.name for s in tr.spans]
        assert "kv.promote" in names
        pf = next(s for s in tr.spans if s.name == "engine.prefill")
        assert pf.attrs["cache_source"] == "host_tier"
        assert pf.attrs["promoted_tokens"] == a2.promoted_tokens
        assert pf.attrs["cached_tokens"] == a2.cached_tokens
        assert not eng.self_check()

        # token-identical to an untiered engine on the same sequence
        base = make_engine(cfg, params, kv_host_tier_mb=0)
        assert base.kv_tier is None
        b1 = GenRequest(request_id="b1", prompt_ids=prompt,
                        max_new_tokens=8, prefix_key="t")
        base.submit(b1)
        base.run_to_completion()
        assert b1.output_ids == a.output_ids
        b2 = GenRequest(request_id="b2", prompt_ids=resume,
                        max_new_tokens=8, prefix_key="t")
        base.submit(b2)
        base.run_to_completion()
        assert b2.output_ids == a2.output_ids

    def test_tier_off_is_default_and_builds_nothing(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, kv_host_tier_mb=0)
        assert eng.kv_tier is None
        assert eng.prefix_cache.tier is None
        # default EngineConfig: off
        assert EngineConfig().kv_host_tier_mb == 0

    def test_negative_budget_rejected(self, model):
        cfg, params = model
        with pytest.raises(ValueError, match="kv_host_tier_mb"):
            make_engine(cfg, params, kv_host_tier_mb=-1)

    def test_warmup_kv_tier_compiles_without_state_change(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        free0 = eng.pool.free_pages
        eng.warmup_kv_tier()
        assert eng.pool.free_pages == free0
        assert not eng.self_check()
        # untiered engine: strict no-op
        base = make_engine(cfg, params, kv_host_tier_mb=0)
        base.warmup_kv_tier()

    def test_disk_tier_spill_and_resume(self, model, tmp_path):
        cfg, params = model
        eng = make_engine(cfg, params, kv_host_tier_mb=1,
                          kv_disk_tier_dir=str(tmp_path))
        # force the budget down to ~one-and-a-half runs so the second
        # demotion overflows the host tier and spills the first to disk
        eng.kv_tier.host_budget_bytes = (
            eng.kv_tier.shipper.bytes_per_page() * 14
        )
        rng = np.random.default_rng(5)
        prompt = [int(x) for x in rng.integers(1, 120, 64)]
        a = GenRequest(request_id="A", prompt_ids=prompt,
                       max_new_tokens=8, prefix_key="thread-A")
        eng.submit(a)
        eng.run_to_completion()
        out_a = list(a.output_ids)
        _churn(eng, rng, n=4)
        eng.kv_tier.flush()
        snap = eng.kv_tier.snapshot()
        assert snap["disk_spills"] > 0, snap
        resume = prompt + out_a + [int(x) for x in rng.integers(1, 120, 8)]
        a2 = GenRequest(request_id="A2", prompt_ids=resume,
                        max_new_tokens=8, prefix_key="thread-A")
        eng.submit(a2)
        eng.run_to_completion()
        # the resume either promoted (from host or disk) or re-prefilled
        # cleanly; either way the engine stays consistent and the output
        # matches the untiered engine
        assert not eng.self_check()
        base = make_engine(cfg, params, kv_host_tier_mb=0)
        r1 = GenRequest(request_id="r1", prompt_ids=prompt,
                        max_new_tokens=8, prefix_key="t")
        base.submit(r1)
        base.run_to_completion()
        r2 = GenRequest(request_id="r2", prompt_ids=resume,
                        max_new_tokens=8, prefix_key="t")
        base.submit(r2)
        base.run_to_completion()
        assert a2.output_ids == r2.output_ids


class TestTierFailpoints:
    def test_demote_fault_falls_back_to_plain_eviction(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        rng = np.random.default_rng(7)
        prompt = [int(x) for x in rng.integers(1, 120, 64)]
        a = GenRequest(request_id="A", prompt_ids=prompt,
                       max_new_tokens=8, prefix_key="thread-A")
        eng.submit(a)
        eng.run_to_completion()
        with failpoints.armed("kv.demote", "error", "torn demote"):
            _churn(eng, rng)
        assert eng.prefix_cache.host_nodes == 0  # demotes all failed
        assert eng.kv_tier.demote_failures > 0
        assert not eng.self_check()
        # resume still works — it just re-prefills
        resume = prompt + list(a.output_ids) + [3, 4, 5]
        a2 = GenRequest(request_id="A2", prompt_ids=resume,
                        max_new_tokens=4, prefix_key="thread-A")
        eng.submit(a2)
        eng.run_to_completion()
        assert a2.cache_source != "host_tier"
        assert not eng.self_check()

    def test_torn_promote_degrades_to_reprefill(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        rng = np.random.default_rng(9)
        prompt = [int(x) for x in rng.integers(1, 120, 64)]
        a = GenRequest(request_id="A", prompt_ids=prompt,
                       max_new_tokens=8, prefix_key="thread-A")
        eng.submit(a)
        eng.run_to_completion()
        _churn(eng, rng)
        assert eng.prefix_cache.host_nodes > 0
        resume = prompt + list(a.output_ids) + [3, 4, 5]
        # the error fires INSIDE the promote's chunk loop: destination
        # pages are freed, the node removed, the request re-prefills
        with failpoints.armed("kv.promote", "error", "torn promote"):
            a2 = GenRequest(request_id="A2", prompt_ids=resume,
                            max_new_tokens=8, prefix_key="thread-A")
            eng.submit(a2)
            eng.run_to_completion()
        assert a2.cache_source != "host_tier"
        assert eng.kv_tier.promote_failures > 0
        assert not eng.self_check(), eng.self_check()
        # output equals the clean-path output: degraded, never corrupted
        base = make_engine(cfg, params, kv_host_tier_mb=0)
        r1 = GenRequest(request_id="r1", prompt_ids=prompt,
                        max_new_tokens=8, prefix_key="t")
        base.submit(r1)
        base.run_to_completion()
        r2 = GenRequest(request_id="r2", prompt_ids=resume,
                        max_new_tokens=8, prefix_key="t")
        base.submit(r2)
        base.run_to_completion()
        assert a2.output_ids == r2.output_ids

    def test_torn_multichunk_copy_unit(self):
        """nth=2 error on a multi-chunk promote: chunk 1 lands, chunk 2
        faults — the manager reports failure and the caller's pages are
        safe to free (nothing shared)."""
        ps, n = 2, SHIP_BUCKETS[-1] + 3  # 2 chunks
        o = _Owner(2 * n + 10, ps, layers=1, width=4, seed=13)
        ship = LocalPageShipper(o, ps)
        mgr = KVTierManager(ship, host_budget_bytes=1 << 30, page_size=ps)
        pages = list(range(1, 1 + n))
        rid = mgr.demote(pages)
        assert rid is not None
        dest = list(range(1 + n, 1 + 2 * n))
        with failpoints.armed("kv.promote", "error", "torn", nth=2):
            assert not mgr.promote(rid, dest)
        assert mgr.promote_failures == 1

    def test_sites_registered(self):
        assert "kv.demote" in failpoints.SITES
        assert "kv.promote" in failpoints.SITES


class TestTierMetricsRegistry:
    def _source(self, relpath):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(root, relpath)) as f:
            return f.read()

    def test_registry_both_directions(self):
        from kafka_tpu.runtime.metrics import KV_TIER_METRIC_KEYS

        metrics_src = self._source("kafka_tpu/runtime/metrics.py")
        prom_src = self._source("kafka_tpu/server/prometheus.py")
        for key in KV_TIER_METRIC_KEYS:
            assert f'"{key}"' in metrics_src, (
                f"{key} missing from runtime/metrics.py"
            )
            assert f'"{key}"' in prom_src, (
                f"{key} missing from server/prometheus.py"
            )

    def test_snapshot_matches_registry_exactly(self):
        from kafka_tpu.runtime.metrics import KV_TIER_METRIC_KEYS

        o = _Owner(8, 2, layers=1, width=4)
        mgr = KVTierManager(LocalPageShipper(o, 2),
                            host_budget_bytes=1024, page_size=2)
        assert set(mgr.snapshot()) == set(KV_TIER_METRIC_KEYS)

    def test_engine_snapshot_and_prometheus_families(self, model):
        from kafka_tpu.server.prometheus import render_prometheus

        cfg, params = model
        eng = make_engine(cfg, params)
        rng = np.random.default_rng(15)
        prompt = [int(x) for x in rng.integers(1, 120, 64)]
        a = GenRequest(request_id="A", prompt_ids=prompt,
                       max_new_tokens=8, prefix_key="thread-A")
        eng.submit(a)
        eng.run_to_completion()
        _churn(eng, rng)
        a2 = GenRequest(
            request_id="A2",
            prompt_ids=prompt + list(a.output_ids) + [3, 4],
            max_new_tokens=4, prefix_key="thread-A",
        )
        eng.submit(a2)
        eng.run_to_completion()
        snap = eng.metrics.snapshot(eng)
        assert "kv_tier" in snap
        assert snap["kv_tier"]["demotions"] > 0
        assert snap["kv_tier"]["promotions"] > 0
        assert snap["prefix_cache"]["host_tier_hits"] == 1
        text = render_prometheus(snap)
        for family in ("kafka_tpu_kv_tier_bytes", "kafka_tpu_kv_tier_runs",
                       "kafka_tpu_kv_tier_total",
                       "kafka_tpu_kv_tier_pages_total",
                       "kafka_tpu_kv_tier_bytes_total",
                       "kafka_tpu_prefix_cache_host_resident"):
            assert f"# TYPE {family}" in text, family
        assert 'kind="host_tier_hits"' in text
        assert 'event="demotions"' in text
        # untiered engines export NO kv_tier family at all
        base = make_engine(cfg, params, kv_host_tier_mb=0)
        text0 = render_prometheus(base.metrics.snapshot(base))
        assert "kv_tier" not in text0

    def test_span_registry_carries_tier_spans(self):
        assert "kv.demote" in tracing.SPANS
        assert "kv.promote" in tracing.SPANS


class TestRingPersistence:
    def test_trace_survives_reset_via_disk(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KAFKA_TPU_TRACE_PERSIST_DIR", str(tmp_path))
        tracing.reset()
        root = tracing.start_trace(request_id="persist-req")
        with tracing.span("agent.turn"):
            pass
        tracing.finish_trace(root)
        files = [f for f in os.listdir(tmp_path)
                 if f.endswith(".trace.json")]
        assert len(files) == 1
        tid = tracing.get_trace("persist-req").trace_id
        # a fresh process: ring empty, disk still there
        tracing.reset()
        tr = tracing.get_trace("persist-req")
        assert tr is not None and tr.trace_id == tid and tr.done
        assert tracing.chrome_trace("persist-req") is not None
        assert tracing.get_trace(tid) is not None  # by trace id too
        monkeypatch.delenv("KAFKA_TPU_TRACE_PERSIST_DIR")
        tracing.reset()

    def test_defaults_alongside_disk_tier(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KAFKA_TPU_KV_DISK_TIER_DIR", str(tmp_path))
        monkeypatch.delenv("KAFKA_TPU_TRACE_PERSIST_DIR", raising=False)
        tracing.reset()
        root = tracing.start_trace(request_id="alongside")
        tracing.finish_trace(root)
        assert os.path.isdir(os.path.join(str(tmp_path), "traces"))
        assert os.listdir(os.path.join(str(tmp_path), "traces"))
        # explicit "" is the hard off switch even with a disk tier
        monkeypatch.setenv("KAFKA_TPU_TRACE_PERSIST_DIR", "")
        tracing.reset()
        root = tracing.start_trace(request_id="off")
        tracing.finish_trace(root)
        traces_dir = os.path.join(str(tmp_path), "traces")
        assert len(os.listdir(traces_dir)) == 1  # nothing new landed
        monkeypatch.delenv("KAFKA_TPU_KV_DISK_TIER_DIR")
        monkeypatch.delenv("KAFKA_TPU_TRACE_PERSIST_DIR")
        tracing.reset()


class TestDeferredGrammarCompile:
    def test_large_vocab_defers_and_lands(self, monkeypatch):
        from kafka_tpu.llm.constrained import (
            build_tool_call_mask_fn,
            compile_grammar_for_mask_fn,
            compile_pending,
        )
        from kafka_tpu.models import ByteTokenizer

        tok = ByteTokenizer()
        tools = [{"type": "function", "function": {
            "name": "defer_probe",
            "parameters": {"type": "object",
                           "properties": {"q": {"type": "string"}}}}}]
        mf = build_tool_call_mask_fn(tok, tools, "required")
        # every vocab counts as "large": the threshold is the env knob
        monkeypatch.setenv("KAFKA_TPU_GRAMMAR_SYNC_VOCAB", "1")
        g = compile_grammar_for_mask_fn(mf, tok.vocab_size)
        assert g is None  # first call: host-mask path, no stall
        deadline = time.monotonic() + 30
        while compile_pending() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert compile_pending() == 0
        g2 = compile_grammar_for_mask_fn(mf, tok.vocab_size)
        assert g2 is not None  # flipped to on-device once the table landed

    def test_small_vocab_stays_synchronous(self):
        from kafka_tpu.llm.constrained import (
            build_tool_call_mask_fn,
            compile_grammar_for_mask_fn,
        )
        from kafka_tpu.models import ByteTokenizer

        tok = ByteTokenizer()
        tools = [{"type": "function", "function": {
            "name": "sync_probe",
            "parameters": {"type": "object",
                           "properties": {"n": {"type": "number"}}}}}]
        mf = build_tool_call_mask_fn(tok, tools, "required")
        g = compile_grammar_for_mask_fn(mf, tok.vocab_size)
        assert g is not None  # byte vocab < default threshold: inline

    def test_gauge_exported(self):
        from kafka_tpu.runtime.metrics import (
            CONSTRAINED_METRIC_KEYS,
            EngineMetrics,
        )

        assert "constrained_compile_pending" in CONSTRAINED_METRIC_KEYS
        snap = EngineMetrics().snapshot()
        assert "constrained_compile_pending" in snap["constrained"]


class TestPlannerHostTier:
    def test_plan_charges_host_tier_as_host_ram(self):
        from kafka_tpu.runtime.planner import plan_for_serving
        from kafka_tpu.server.config import ServingConfig

        scfg = ServingConfig(tiny_model=True, kv_host_tier_mb=512)
        plan = plan_for_serving(scfg, hbm_bytes=16 << 30,
                                model_cfg=_tiny_model_cfg())
        assert plan.kv_host_tier_bytes == 512 << 20
        assert plan.summary()["kv_host_tier_mib"] == 512.0
        # host RAM, not HBM: the tier must not change the fit verdict
        base = plan_for_serving(ServingConfig(tiny_model=True),
                                hbm_bytes=16 << 30,
                                model_cfg=_tiny_model_cfg())
        assert plan.total_bytes == base.total_bytes

    def test_config_env_round_trip(self, monkeypatch):
        from kafka_tpu.server.config import ServingConfig

        monkeypatch.setenv("KAFKA_TPU_KV_HOST_TIER_MB", "128")
        monkeypatch.setenv("KAFKA_TPU_KV_DISK_TIER_DIR", "/tmp/kvtier")
        cfg = ServingConfig.from_env()
        assert cfg.kv_host_tier_mb == 128
        assert cfg.kv_disk_tier_dir == "/tmp/kvtier"
        monkeypatch.setenv("KAFKA_TPU_KV_HOST_TIER_MB", "-5")
        assert ServingConfig.from_env().kv_host_tier_mb == 0


def _tiny_model_cfg():
    from kafka_tpu.models.config import get_config

    return get_config("tiny")


class TestBenchSmoke:
    def test_kv_tier_phase_counters_move_on_cpu(self, model):
        import importlib.util
        import sys

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "bench", os.path.join(root, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        sys.modules["bench"] = bench
        spec.loader.exec_module(bench)
        cfg, params = model
        out = bench.kv_tier_phase(cfg, params, n_churn=2, prompt_len=96,
                                  gen_len=8, page_size=8)
        assert out["resume_cached_tokens"] > 0
        assert out["cache_source"] == "host_tier"
        assert out["baseline_cached_tokens"] == 0  # untiered: evicted
        tier = out["tier_counters"]
        assert tier["demotions"] > 0 and tier["promotions"] > 0
        assert out["resume_ttft_ms"]["promote"] < \
            out["resume_ttft_ms"]["reprefill"], out["resume_ttft_ms"]
