"""Sandbox tier tests: the in-tree sandbox server protocol (health/claim/
run SSE/reset), LocalSandbox byte-level SSE client, shell/notebook
persistence, SandboxManager lifecycle (ready cache, pending dedupe,
reuse/restart/create), LazySandbox resolution, and warm pools."""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestServer

from kafka_tpu.db import LocalDBClient
from kafka_tpu.sandbox import (
    LazySandbox,
    LocalSandbox,
    SandboxConfig,
    SandboxError,
    SandboxFactory,
    SandboxManager,
    SandboxTool,
    notebook_tools,
    shell_tools,
)
from kafka_tpu.sandbox.server import create_sandbox_app
from kafka_tpu.sandbox.warm import HTTPWarmSandboxFactory, ProcessWarmPool


def run(coro):
    return asyncio.run(coro)


async def start_sandbox(sandbox_id="sbx-test"):
    """In-process sandbox server + a LocalSandbox client bound to it."""
    server = TestServer(create_sandbox_app(sandbox_id))
    await server.start_server()
    url = f"http://127.0.0.1:{server.port}"
    return server, LocalSandbox(url, sandbox_id)


async def drain(sandbox, name, args):
    events = []
    async for ev in sandbox.run_tool(name, args):
        events.append(ev)
    return events


class TestSandboxProtocol:
    def test_health_and_claim(self):
        async def go():
            server, sbx = await start_sandbox()
            try:
                h = await sbx.check_health()
                assert h["healthy"] and not h["claimed"]
                ok = await sbx.claim(SandboxConfig(thread_id="t1"))
                assert ok
                h = await sbx.check_health()
                assert h["claimed"]
                # same thread re-claims fine
                assert await sbx.claim(SandboxConfig(thread_id="t1"))
                # different thread is rejected
                assert not await sbx.claim(SandboxConfig(thread_id="t2"))
                # reset clears the claim
                await sbx.reset()
                assert not (await sbx.check_health())["claimed"]
            finally:
                await sbx.aclose()
                await server.close()

        run(go())

    def test_shell_exec_streams_and_persists(self):
        async def go():
            server, sbx = await start_sandbox()
            try:
                evs = await drain(sbx, "create_shell", {"shell_id": "s1"})
                assert evs[-1].kind == "result"
                assert json.loads(evs[-1].data)["shell_id"] == "s1"

                evs = await drain(sbx, "shell_exec",
                                  {"shell_id": "s1", "command": "cd /tmp && pwd"})
                assert evs[-1].kind == "result"
                assert "/tmp" in evs[-1].data
                # cwd persisted across calls in the same shell
                evs = await drain(sbx, "shell_exec",
                                  {"shell_id": "s1", "command": "pwd"})
                assert "/tmp" in evs[-1].data
                # deltas streamed before the result
                evs = await drain(
                    sbx, "shell_exec",
                    {"shell_id": "s1", "command": "echo a; echo b"})
                deltas = [e for e in evs if e.kind == "delta"]
                assert [d.data.strip() for d in deltas] == ["a", "b"]
            finally:
                await sbx.aclose()
                await server.close()

        run(go())

    def test_shell_nonzero_exit_reported(self):
        async def go():
            server, sbx = await start_sandbox()
            try:
                evs = await drain(sbx, "shell_exec",
                                  {"command": "exit 3"})
                assert evs[-1].kind == "result"
                assert "[exit code: 3]" in evs[-1].data
            finally:
                await sbx.aclose()
                await server.close()

        run(go())

    def test_shell_timeout_recovers(self):
        async def go():
            server, sbx = await start_sandbox()
            try:
                evs = await drain(sbx, "shell_exec",
                                  {"command": "sleep 5", "timeout": 0.5})
                assert evs[-1].kind == "error"
                assert "timed out" in evs[-1].data
                # the session was replaced and still works
                evs = await drain(sbx, "shell_exec", {"command": "echo ok"})
                assert evs[-1].kind == "result" and "ok" in evs[-1].data
            finally:
                await sbx.aclose()
                await server.close()

        run(go())

    def test_notebook_state_persists(self):
        async def go():
            server, sbx = await start_sandbox()
            try:
                evs = await drain(sbx, "notebook_run_cell", {"code": "x = 41"})
                assert evs[-1].kind == "result"
                evs = await drain(sbx, "notebook_run_cell", {"code": "x + 1"})
                assert evs[-1].data.strip() == "42"
                # stdout captured
                evs = await drain(sbx, "notebook_run_cell",
                                  {"code": "print('hi'); x"})
                assert evs[-1].data == "hi\n41\n"
                # errors are data
                evs = await drain(sbx, "notebook_run_cell", {"code": "1/0"})
                assert evs[-1].kind == "error"
                assert "ZeroDivisionError" in evs[-1].data
            finally:
                await sbx.aclose()
                await server.close()

        run(go())

    def test_unknown_tool_and_dead_sandbox(self):
        async def go():
            server, sbx = await start_sandbox()
            try:
                evs = await drain(sbx, "no_such", {})
                assert evs[-1].kind == "error"
            finally:
                await sbx.aclose()
                await server.close()
            # after shutdown: connection error surfaces as error event
            dead = LocalSandbox(f"http://127.0.0.1:{server.port}", "dead")
            try:
                evs = await drain(dead, "shell_exec", {"command": "echo"})
                assert evs[-1].kind == "error"
                assert not (await dead.check_health())["healthy"]
            finally:
                await dead.aclose()

        run(go())


class TestSandboxAuth:
    def test_run_requires_key_once_claimed_with_one(self):
        async def go():
            server, sbx = await start_sandbox()
            try:
                cfg = SandboxConfig(thread_id="t1", vm_api_key="vmk_secret")
                assert await sbx.claim(cfg)
                # the claiming client remembers the key: authorized
                evs = await drain(sbx, "shell_exec", {"command": "echo hi"})
                assert evs[-1].kind == "result" and "hi" in evs[-1].data
                # a stranger without the key is rejected
                other = LocalSandbox(sbx.url, "other")
                try:
                    evs = await drain(other, "shell_exec",
                                      {"command": "echo hi"})
                    assert evs[-1].kind == "error"
                    assert "401" in evs[-1].data
                finally:
                    await other.aclose()
            finally:
                await sbx.aclose()
                await server.close()

        run(go())

    def test_keyless_reclaim_cannot_wipe_key(self):
        async def go():
            server, sbx = await start_sandbox()
            try:
                cfg = SandboxConfig(thread_id="t1", vm_api_key="vmk_secret")
                assert await sbx.claim(cfg)
                # an empty claim (no key) must NOT overwrite the claim
                # config and drop the auth requirement
                stranger = LocalSandbox(sbx.url, "stranger")
                try:
                    assert not await stranger.claim(SandboxConfig(thread_id="t1"))
                    evs = [e async for e in stranger.run_tool(
                        "shell_exec", {"command": "echo x"})]
                    assert evs[-1].kind == "error" and "401" in evs[-1].data
                finally:
                    await stranger.aclose()
                # same-thread re-claim presenting the key still works
                assert await sbx.claim(cfg)
            finally:
                await sbx.aclose()
                await server.close()

        run(go())

    def test_reconnect_relearns_key_via_reclaim(self):
        async def go():
            server, sbx = await start_sandbox()
            try:
                cfg = SandboxConfig(thread_id="t1", vm_api_key="vmk_secret")
                assert await sbx.claim(cfg)
                # orchestrator restart: fresh client, sandbox still claimed.
                # Re-claiming with the same key (from the DB) re-arms the
                # client; without it, every tool call would 401.
                fresh = LocalSandbox(sbx.url, "fresh")
                try:
                    assert await fresh.claim(cfg)
                    evs = [e async for e in fresh.run_tool(
                        "shell_exec", {"command": "echo back"})]
                    assert evs[-1].kind == "result" and "back" in evs[-1].data
                finally:
                    await fresh.aclose()
            finally:
                await sbx.aclose()
                await server.close()

        run(go())

    def test_stdin_consuming_command_cannot_spoof_sentinel(self):
        # `cat` swallows the sentinel printf line and echoes it as DATA;
        # the split-argument printf means the echoed command text never
        # contains the contiguous sentinel, so exec times out (correct)
        # instead of false-matching and returning garbage forever.
        async def go():
            server, sbx = await start_sandbox()
            try:
                evs = await drain(sbx, "shell_exec",
                                  {"command": "cat", "timeout": 2})
                assert evs[-1].kind == "error"
                assert "timed out" in evs[-1].data
                # the session respawned; the next exec is clean
                evs = await drain(sbx, "shell_exec",
                                  {"command": "echo clean"})
                assert evs[-1].kind == "result"
                assert "clean" in evs[-1].data
            finally:
                await sbx.aclose()
                await server.close()

        run(go())

    def test_header_auth_reclaim_preserves_key(self):
        # A key-holder refresh authenticated via the Authorization header
        # whose body omits vm_api_key must not wipe the stored key.
        async def go():
            import httpx

            server, sbx = await start_sandbox()
            try:
                cfg = SandboxConfig(thread_id="t1", vm_api_key="vmk_secret")
                assert await sbx.claim(cfg)
                async with httpx.AsyncClient() as client:
                    r = await client.post(
                        f"{sbx.url}/claim",
                        json={"thread_id": "t1"},
                        headers={"Authorization": "Bearer vmk_secret"},
                    )
                    assert r.status_code == 200 and r.json()["claimed"]
                    # auth is still enforced: unauthenticated /run 401s
                    r = await client.post(
                        f"{sbx.url}/run",
                        json={"tool": "shell_exec",
                              "arguments": {"command": "echo x"}},
                    )
                    assert r.status_code == 401
            finally:
                await sbx.aclose()
                await server.close()

        run(go())

    def test_malformed_claim_body_rejected(self):
        # Garbage claim bodies must not become real claims that 409-block
        # the legitimate owner.
        async def go():
            import httpx

            server, sbx = await start_sandbox()
            try:
                async with httpx.AsyncClient() as client:
                    r = await client.post(
                        f"{sbx.url}/claim",
                        content=b"{not json",
                        headers={"Content-Type": "application/json"},
                    )
                    assert r.status_code == 400
                    r = await client.post(f"{sbx.url}/claim", json=[1, 2])
                    assert r.status_code == 400
                h = await sbx.check_health()
                assert not h["claimed"]
                assert await sbx.claim(SandboxConfig(thread_id="t1"))
            finally:
                await sbx.aclose()
                await server.close()

        run(go())

    def test_threadless_keyless_claim_can_be_taken_over(self):
        # A probe's `{}` claim binds no thread; the real owner's claim
        # must still succeed rather than 409.
        async def go():
            import httpx

            server, sbx = await start_sandbox()
            try:
                async with httpx.AsyncClient() as client:
                    r = await client.post(f"{sbx.url}/claim", json={})
                    assert r.status_code == 200
                assert await sbx.claim(SandboxConfig(thread_id="t1"))
                h = await sbx.check_health()
                assert h["claimed"]
            finally:
                await sbx.aclose()
                await server.close()

        run(go())

    def test_no_key_claim_stays_open(self):
        async def go():
            server, sbx = await start_sandbox()
            try:
                assert await sbx.claim(SandboxConfig(thread_id="t1"))
                evs = await drain(sbx, "shell_exec", {"command": "echo open"})
                assert evs[-1].kind == "result" and "open" in evs[-1].data
            finally:
                await sbx.aclose()
                await server.close()

        run(go())


class TestSandboxTools:
    def test_shell_tool_through_tool_interface(self):
        async def go():
            server, sbx = await start_sandbox()
            try:
                create, execute = shell_tools(sbx)
                out = await execute.run({"command": "echo via-tool"})
                assert "via-tool" in out
                (nb,) = notebook_tools(sbx)
                out = await nb.run({"code": "2**10"})
                assert out.strip() == "1024"
            finally:
                await sbx.aclose()
                await server.close()

        run(go())

    def test_unbound_tool_errors_cleanly(self):
        async def go():
            (nb,) = notebook_tools(None)
            events = [e async for e in nb.run_stream({"code": "1"})]
            assert events[-1].kind == "error"
            assert "no sandbox bound" in events[-1].data

        run(go())


class FakeSandbox(LocalSandbox):
    """In-memory sandbox for manager tests (no HTTP)."""

    def __init__(self, sandbox_id, healthy=True):
        self.sandbox_id = sandbox_id
        self.healthy = healthy
        self.claimed = False
        self.claims = []

    async def check_health(self):
        return {"healthy": self.healthy, "claimed": self.claimed}

    async def claim(self, config):
        self.claimed = True
        self.claims.append(config)
        return True

    async def reset(self):
        self.claimed = False

    async def run_tool(self, name, arguments, tool_call_id=None, timeout=None):
        from kafka_tpu.tools.types import ToolEvent

        yield ToolEvent("result", f"{name} ran", tool_name=name)

    async def aclose(self):
        pass


class FakeFactory(SandboxFactory):
    def __init__(self):
        self.sandboxes = {}
        self.created = 0
        self.restarted = []

    async def create(self, thread_id):
        self.created += 1
        sbx = FakeSandbox(f"fake-{self.created}")
        self.sandboxes[sbx.sandbox_id] = sbx
        return sbx

    async def connect(self, sandbox_id):
        return self.sandboxes.get(sandbox_id)

    async def restart(self, sandbox_id):
        self.restarted.append(sandbox_id)
        sbx = self.sandboxes.get(sandbox_id)
        if sbx is not None:
            sbx.healthy = True
            sbx.claimed = False
        return sbx


@pytest.fixture()
def db(tmp_path):
    client = LocalDBClient(str(tmp_path / "sbx.db"))
    run(client.initialize())
    yield client
    run(client.close())


class TestManager:
    def test_create_then_ready_cache(self, db):
        async def go():
            factory = FakeFactory()
            mgr = SandboxManager(db, factory)
            await db.create_thread("t1")
            assert await mgr.get_sandbox_if_ready("t1") is None
            sbx = await mgr.ensure_sandbox("t1")
            assert sbx.claimed
            assert sbx.claims[0].thread_id == "t1"
            assert sbx.claims[0].env["THREAD_ID"] == "t1"
            assert sbx.claims[0].vm_api_key.startswith("vmk_")
            # id persisted; ready cache returns the same instance
            assert await db.get_thread_sandbox_id("t1") == sbx.sandbox_id
            assert await mgr.get_sandbox_if_ready("t1") is sbx
            assert factory.created == 1
            return factory

        run(go())

    def test_reuse_after_cache_loss(self, db):
        async def go():
            factory = FakeFactory()
            mgr1 = SandboxManager(db, factory)
            await db.create_thread("t1")
            sbx = await mgr1.ensure_sandbox("t1")
            # new manager (server restart): finds it via db + connect
            mgr2 = SandboxManager(db, factory)
            found = await mgr2.get_sandbox_if_ready("t1")
            assert found is sbx
            assert factory.created == 1

        run(go())

    def test_restart_when_dead(self, db):
        async def go():
            factory = FakeFactory()
            mgr = SandboxManager(db, factory)
            await db.create_thread("t1")
            sbx = await mgr.ensure_sandbox("t1")
            # kill it
            sbx.healthy = False
            mgr._ready.clear()
            sbx2 = await mgr.ensure_sandbox("t1")
            assert sbx2 is sbx  # restarted in place
            assert factory.restarted == [sbx.sandbox_id]
            assert sbx2.claimed

        run(go())

    def test_claim_reconciliation(self, db):
        async def go():
            factory = FakeFactory()
            mgr = SandboxManager(db, factory)
            await db.create_thread("t1")
            sbx = await mgr.ensure_sandbox("t1")
            sbx.claimed = False  # someone unclaimed it out-of-band
            again = await mgr.get_sandbox_if_ready("t1")
            assert again.claimed  # re-claimed on the readiness probe

        run(go())

    def test_background_creation_dedupes(self, db):
        async def go():
            factory = FakeFactory()
            mgr = SandboxManager(db, factory)
            await db.create_thread("t1")
            mgr.ensure_sandbox_background("t1")
            mgr.ensure_sandbox_background("t1")  # deduped by pending set
            for _ in range(100):
                if await mgr.get_sandbox_if_ready("t1") is not None:
                    break
                await asyncio.sleep(0.02)
            assert factory.created == 1

        run(go())

    def test_release(self, db):
        async def go():
            factory = FakeFactory()
            mgr = SandboxManager(db, factory)
            await db.create_thread("t1")
            sbx = await mgr.ensure_sandbox("t1")
            await mgr.release_sandbox("t1")
            assert not sbx.claimed  # reset
            assert await mgr.get_sandbox_if_ready("t1") is sbx  # reconnects

        run(go())


class TestLazySandbox:
    def test_resolves_when_ready(self, db):
        async def go():
            factory = FakeFactory()
            mgr = SandboxManager(db, factory)
            await db.create_thread("t1")
            lazy = LazySandbox("t1", mgr, timeout=5.0)
            mgr.ensure_sandbox_background("t1")
            events = [e async for e in lazy.run_tool("anything", {})]
            assert events[-1].kind == "result"
            assert lazy.sandbox_id.startswith("fake-")

        run(go())

    def test_timeout_yields_error_event(self, db):
        async def go():
            factory = FakeFactory()
            mgr = SandboxManager(db, factory)
            await db.create_thread("t1")
            lazy = LazySandbox("t1", mgr, timeout=0.3)
            # nothing ever creates the sandbox
            events = [e async for e in lazy.run_tool("x", {})]
            assert events[-1].kind == "error"
            assert "not ready" in events[-1].data

        run(go())


class TestWarmPools:
    def test_http_pool_unreachable_returns_none(self):
        async def go():
            pool = HTTPWarmSandboxFactory("http://127.0.0.1:1", "env")
            assert await pool.claim_warm() is None

        run(go())

    def test_process_pool_claims_and_manager_uses_it(self, db):
        async def go():
            factory = FakeFactory()
            pool = ProcessWarmPool(factory, size=1)
            await pool.fill()
            warm_id = pool._pool[0]
            mgr = SandboxManager(db, factory, warm_factory=pool)
            await db.create_thread("t1")
            sbx = await mgr.ensure_sandbox("t1")
            assert sbx.sandbox_id == warm_id  # warm sandbox was used
            await asyncio.sleep(0.05)  # let the background refill run
            assert factory.created >= 2  # refill happened

        run(go())
