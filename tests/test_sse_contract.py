"""The client contract: consume the live SSE stream exactly the way the
reference playground does (page.tsx:127-320) and prove the reconstruction.

This is the test the round-1 verdict asked for — the 4-event protocol's
real consumer semantics (per-completion-id segmentation, incremental
tool_call accumulation, tool_result streaming, tool_messages batch,
agent_done cleanup) exercised against the in-process server.
"""

import asyncio
import json

import pytest

from kafka_tpu.core.sse_client import SSEMessageReconstructor
from kafka_tpu.core.types import StreamChunk
from tests.test_server import make_client, text_turn


def split_args_tool_turn(cid="chatcmpl-t1"):
    """A tool-call turn whose JSON arguments arrive across two deltas."""
    return [
        StreamChunk(role="assistant", id=cid),
        StreamChunk(tool_calls=[{
            "index": 0, "id": "call_1", "type": "function",
            "function": {"name": "add", "arguments": '{"a": 2,'},
        }], id=cid),
        StreamChunk(tool_calls=[{
            "index": 0, "function": {"arguments": ' "b": 3}'},
        }], id=cid),
        StreamChunk(finish_reason="tool_calls", id=cid),
    ]


def drive(tmp_path, turns, body):
    """POST an agent run and feed the raw SSE bytes to the reconstructor."""
    built, llm, _ = make_client(tmp_path, turns)

    async def go():
        client = await built
        rec = SSEMessageReconstructor()
        try:
            resp = await client.post("/v1/agent/run", json=body)
            assert resp.status == 200
            raw = await resp.text()
            rec.feed_text(raw)
        finally:
            await client.close()
        return rec

    return asyncio.run(go())


class TestPlaygroundContract:
    def test_plain_text_turn(self, tmp_path):
        rec = drive(
            tmp_path,
            [text_turn("Hello ", "world")],
            {"messages": [{"role": "user", "content": "hi"}],
             "model": "fake-model", "stream": True},
        )
        assert rec.done
        assert rec.errors == []
        # one assistant message, fully accumulated
        assistants = [m for m in rec.messages if m["role"] == "assistant"]
        assert assistants[-1]["content"] == "Hello world"

    def test_tool_call_turn_reconstructs_all_four_event_kinds(self, tmp_path):
        # turn 1: the model calls the `add` tool (arguments split across
        # deltas); turn 2: final text
        turns = [
            split_args_tool_turn(),
            text_turn("2+3 is 5", cid="chatcmpl-t2"),
        ]
        rec = drive(
            tmp_path, turns,
            {"messages": [{"role": "user", "content": "add 2 and 3"}],
             "model": "fake-model", "stream": True},
        )
        assert rec.done and rec.errors == []
        roles = [m["role"] for m in rec.messages]
        # canonical transcript: assistant(tool_calls) -> tool -> assistant
        assert "tool" in roles
        tool_msg = next(m for m in rec.messages if m["role"] == "tool")
        assert tool_msg["content"]  # streamed tool_result deltas landed
        tc_msg = next(m for m in rec.messages
                      if m["role"] == "assistant" and m.get("tool_calls"))
        call = tc_msg["tool_calls"][0]
        assert call["function"]["name"] == "add"
        # incremental argument accumulation across deltas
        assert json.loads(call["function"]["arguments"]) == {"a": 2, "b": 3}
        # the final assistant text from the second completion id
        assert rec.messages[-1]["role"] == "assistant"
        assert rec.messages[-1]["content"] == "2+3 is 5"

    def test_per_completion_id_segmentation(self, tmp_path):
        """Two agent iterations (two completion ids) must become two
        assistant messages, not one concatenated blob."""
        turns = [
            split_args_tool_turn(cid="chatcmpl-seg1"),
            text_turn("done", cid="chatcmpl-seg2"),
        ]
        rec = drive(
            tmp_path, turns,
            {"messages": [{"role": "user", "content": "go"}],
             "model": "fake-model", "stream": True},
        )
        assistants = [m for m in rec.messages if m["role"] == "assistant"]
        with_calls = [m for m in assistants if m.get("tool_calls")]
        with_text = [m for m in assistants if m.get("content")]
        assert len(with_calls) == 1 and len(with_text) == 1
        assert with_calls[0] is not with_text[0]

    def test_agent_done_drops_trailing_stub(self, tmp_path):
        rec = drive(
            tmp_path,
            [text_turn("answer")],
            {"messages": [{"role": "user", "content": "q"}],
             "model": "fake-model", "stream": True},
        )
        last = rec.messages[-1]
        assert not (last["role"] == "assistant" and not last.get("content")
                    and not last.get("tool_calls"))

    def test_two_tool_cycles_both_survive(self, tmp_path):
        """Regression (review finding): cumulative batches — a second tool
        cycle must not wipe the first from the reconstructed transcript."""
        turns = [
            split_args_tool_turn(cid="chatcmpl-c1"),
            [
                StreamChunk(role="assistant", id="chatcmpl-c2"),
                StreamChunk(tool_calls=[{
                    "index": 0, "id": "call_2", "type": "function",
                    "function": {"name": "add",
                                 "arguments": '{"a": 5, "b": 5}'},
                }], id="chatcmpl-c2"),
                StreamChunk(finish_reason="tool_calls", id="chatcmpl-c2"),
            ],
            text_turn("both sums computed", cid="chatcmpl-c3"),
        ]
        rec = drive(
            tmp_path, turns,
            {"messages": [{"role": "user", "content": "two sums"}],
             "model": "fake-model", "stream": True},
        )
        assert rec.done and rec.errors == []
        tool_msgs = [m for m in rec.messages if m["role"] == "tool"]
        assert len(tool_msgs) == 2, rec.messages
        call_ids = {m["tool_call_id"] for m in tool_msgs}
        assert call_ids == {"call_1", "call_2"}
        assert rec.messages[-1]["content"] == "both sums computed"
