"""Numerics for the fused-MLP Pallas kernel (ops/pallas/fused_mlp.py).

The kernel is a recorded ablation, not a serving path (its module
docstring carries the measured verdict: XLA already runs the MLP stream
at ~90% of roofline).  These tests keep its numerics pinned against the
XLA formulation so the artifact stays trustworthy — and the int8 variant
exercises the per-output-channel post-scaling algebra the serving stack
uses elsewhere (logits head, models/llama.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafka_tpu.models.quant import dequantize, quantize_array
from kafka_tpu.ops.norms import rms_norm
from kafka_tpu.ops.pallas.fused_mlp import fused_mlp_block, pick_block_f


def _mats(B=8, H=256, F=1024, seed=0):
    k = jax.random.split(jax.random.PRNGKey(seed), 5)
    h = jax.random.normal(k[0], (B, H)).astype(jnp.bfloat16)
    ln = (jax.random.normal(k[1], (H,)) * 0.1 + 1).astype(jnp.bfloat16)
    wg = (jax.random.normal(k[2], (H, F)) * H**-0.5).astype(jnp.bfloat16)
    wu = (jax.random.normal(k[3], (H, F)) * H**-0.5).astype(jnp.bfloat16)
    wd = (jax.random.normal(k[4], (F, H)) * F**-0.5).astype(jnp.bfloat16)
    return h, ln, wg, wu, wd


def _xla(h, ln, wg, wu, wd, eps=1e-5):
    x = rms_norm(h, ln, eps)
    g = jnp.einsum("bh,hf->bf", x, wg)
    u = jnp.einsum("bh,hf->bf", x, wu)
    return h + jnp.einsum("bf,fh->bh", jax.nn.silu(g) * u, wd)


def _maxdiff(a, b):
    return float(jnp.max(jnp.abs(
        a.astype(jnp.float32) - b.astype(jnp.float32))))


class TestFusedMLP:
    def test_bf16_matches_xla(self):
        h, ln, wg, wu, wd = _mats()
        out = fused_mlp_block(h, ln, wg, wu, wd, eps=1e-5, interpret=True)
        assert _maxdiff(out, _xla(h, ln, wg, wu, wd)) < 0.05

    def test_int8_matches_xla_dequant_path(self):
        h, ln, wg, wu, wd = _mats(seed=3)
        qg, qu, qd = (quantize_array(w, (0,)) for w in (wg, wu, wd))
        ref = _xla(h, ln, dequantize(qg, jnp.bfloat16),
                   dequantize(qu, jnp.bfloat16),
                   dequantize(qd, jnp.bfloat16))
        out = fused_mlp_block(h, ln, qg.q, qu.q, qd.q, qg.s, qu.s, qd.s,
                              eps=1e-5, interpret=True)
        assert _maxdiff(out, ref) < 0.05

    def test_multiple_tile_counts(self):
        # grid length > 1 exercises the cross-tile f32 accumulation
        for F in (256, 512, 1024):
            h, ln, wg, wu, wd = _mats(H=128, F=F, seed=F)
            out = fused_mlp_block(h, ln, wg, wu, wd, eps=1e-5,
                                  block_f=128, interpret=True)
            assert _maxdiff(out, _xla(h, ln, wg, wu, wd)) < 0.05, F

    def test_pick_block_f(self):
        assert pick_block_f(2048, 8192, 2) == 256
        assert pick_block_f(2048, 8192, 1) == 512
        assert pick_block_f(4096, 14336, 2) == 128
        # indivisible F -> no tile
        assert pick_block_f(2048, 1000, 2) is None

    def test_indivisible_f_raises(self):
        h, ln, wg, wu, wd = _mats(H=128, F=384)
        with pytest.raises(ValueError):
            fused_mlp_block(h, ln, wg, wu, wd, eps=1e-5, block_f=256,
                            interpret=True)
