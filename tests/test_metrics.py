"""Observability: engine counters, the streaming-histogram/SLO telemetry
plane (ISSUE 10), and the /metrics + /admin/signals endpoints."""

import asyncio
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.models import ModelConfig, init_params
from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine
from kafka_tpu.runtime.metrics import (
    BURST_TOKEN_BOUNDS,
    LATENCY_MS_BOUNDS,
    EngineMetrics,
    StreamingHistogram,
    _percentiles,
)


def _bucket_bounds(h, value):
    """(lo, hi] bucket enclosing `value` under the histogram's bounds."""
    import bisect

    i = bisect.bisect_left(h.bounds, value)
    lo = h.bounds[i - 1] if i > 0 else 0.0
    hi = h.bounds[i] if i < len(h.bounds) else float("inf")
    return lo, hi


class TestStreamingHistogram:
    """Unit matrix for the fixed-bucket streaming histograms that replaced
    the last-512-sample deques (ISSUE 10)."""

    def test_bucket_boundaries_le_semantics(self):
        h = StreamingHistogram((1.0, 2.0, 4.0))
        for v in (0.5, 1.0, 1.5, 2.0, 4.0, 9.0):
            h.record(v)
        # le semantics: a value equal to a bound lands IN that bucket
        assert h.counts == [2, 2, 1, 1]
        assert h.count == 6
        assert h.sum == pytest.approx(18.0)
        assert h.max == 9.0

    def test_cumulative_monotone(self):
        h = StreamingHistogram(LATENCY_MS_BOUNDS)
        rng = np.random.default_rng(7)
        for v in rng.lognormal(3.0, 2.0, 500):
            h.record(float(v))
        cum = 0
        for c in h.counts:
            assert c >= 0
            cum += c
        assert cum == 500
        # cumulative series is monotone by construction
        running, prev = 0, -1
        for c in h.counts:
            running += c
            assert running >= prev
            prev = running

    def test_merge_across_replicas(self):
        a = StreamingHistogram(LATENCY_MS_BOUNDS)
        b = StreamingHistogram(LATENCY_MS_BOUNDS)
        for v in (1.0, 10.0, 100.0):
            a.record(v)
        for v in (5.0, 50.0):
            b.record(v)
        m = StreamingHistogram.merged([a, b])
        assert m.count == 5
        assert m.sum == pytest.approx(166.0)
        assert m.max == 100.0
        # merged counts are the element-wise sum
        assert m.counts == [x + y for x, y in zip(a.counts, b.counts)]
        # merging mismatched bounds must refuse, never mis-bucket
        with pytest.raises(ValueError):
            a.merge_from(StreamingHistogram((1.0, 2.0)))

    def test_quantile_within_enclosing_bucket(self):
        h = StreamingHistogram(LATENCY_MS_BOUNDS)
        values = [3.0, 7.0, 20.0, 45.0, 200.0]
        for v in values:
            h.record(v)
        for q, v in ((0.5, 20.0), (0.99, 200.0)):
            lo, hi = _bucket_bounds(h, v)
            assert lo < h.quantile(q) <= hi, (q, v, h.quantile(q))

    def test_quantile_empty_and_overflow(self):
        h = StreamingHistogram((1.0, 2.0))
        assert h.quantile(0.5) == 0.0
        h.record(1e9)  # +Inf bucket
        # the overflow bucket reports the tracked max, not a made-up bound
        assert h.quantile(0.99) == 1e9

    def test_snapshot_roundtrip(self):
        h = StreamingHistogram(BURST_TOKEN_BOUNDS)
        for v in (1, 2, 3, 700, 2000):
            h.record(float(v))
        snap = h.snapshot()
        assert snap["count"] == 5
        assert len(snap["counts"]) == len(snap["le"]) + 1
        back = StreamingHistogram.from_snapshot(snap)
        assert back.counts == h.counts
        assert back.sum == pytest.approx(h.sum)

    def test_log_spacing(self):
        ratios = [b / a for a, b in zip(LATENCY_MS_BOUNDS,
                                        LATENCY_MS_BOUNDS[1:])]
        assert all(r == pytest.approx(math.sqrt(2), rel=1e-4)
                   for r in ratios)


class TestMetricsUnit:
    def test_percentiles(self):
        # client-side helper (bench latency arrays) — still nearest-rank
        ps = _percentiles([float(i) for i in range(1, 101)])
        assert ps["p50"] == 50.0
        assert ps["p90"] == 90.0
        assert ps["p99"] == 99.0
        assert _percentiles([])["p50"] == 0.0

    def test_snapshot_shape(self):
        m = EngineMetrics()
        m.record_submit(10)
        m.record_first_token(0.05)
        m.record_token()
        m.record_decode_step(3)
        m.record_decode_step(2)
        m.record_finish("stop")
        snap = m.snapshot()
        assert snap["requests"]["submitted"] == 1
        assert snap["requests"]["finished"] == 1
        assert snap["tokens"]["generated"] == 1
        # quantiles are bucket-derived now: within the enclosing bucket
        lo, hi = _bucket_bounds(m.ttft_ms, 50.0)
        assert lo < snap["ttft_ms"]["p50"] <= hi
        assert snap["decode"]["steps"] == 2
        assert snap["decode"]["batch_occupancy"] == 2.5
        assert snap["histograms"]["ttft_ms"]["count"] == 1

    def test_queue_peak_resets_per_snapshot(self):
        """queue.peak is peak-SINCE-LAST-SNAPSHOT (ISSUE 10 satellite):
        each scrape consumes the high-water mark and re-arms at the
        current depth, so a boot-time burst stops dominating forever."""
        m = EngineMetrics()
        m.record_queue_depth(9)
        m.record_queue_depth(2)
        assert m.snapshot()["queue"]["peak"] == 9
        # no new burst since: the next scrape reports the current level
        assert m.snapshot()["queue"]["peak"] == 2
        m.record_queue_depth(5)
        m.record_queue_depth(3)
        # a non-consuming read (/admin/signals) must not steal the window
        assert m.snapshot(reset_peak=False)["queue"]["peak"] == 5
        assert m.snapshot()["queue"]["peak"] == 5

    def test_telemetry_off_keeps_slo_windows(self):
        """KAFKA_TPU_TELEMETRY=0 disables per-dispatch recording, but the
        SLO window gauges must keep tracking — an autoscaler reading a
        vacuous attainment_1m=1.0 during an outage would never scale."""
        m = EngineMetrics()
        m.enabled = False
        m.record_finish("timeout")
        m.record_rejected()
        snap = m.slo_snapshot()
        assert snap["slo_attainment"] == 0.0
        assert snap["slo_attainment_1m"] == 0.0
        assert snap["slo_attainment_5m"] == 0.0


class TestSLOAccounting:
    def _m(self, ttft_ms=200.0, tpot_ms=0.0):
        m = EngineMetrics()
        m.slo_ttft_ms, m.slo_tpot_ms = ttft_ms, tpot_ms
        return m

    def test_met_and_missed_classification(self):
        m = self._m()
        assert m.record_finish("stop", ttft_s=0.05, tpot_s=0.01,
                               tokens=10) is True
        assert m.record_finish("stop", ttft_s=0.5, tpot_s=0.01,
                               tokens=10) is False
        snap = m.slo_snapshot()
        assert snap["slo_met_requests"] == 1
        assert snap["slo_missed_requests"] == 1
        assert snap["slo_ttft_violations"] == 1
        assert snap["slo_attainment"] == 0.5
        # goodput counts ONLY the met request's tokens
        assert snap["goodput_tokens"] == 10
        assert snap["goodput_frac"] == 0.0  # no record_token calls

    def test_tpot_target(self):
        m = self._m(ttft_ms=0.0, tpot_ms=50.0)  # TTFT check disabled
        assert m.record_finish("stop", ttft_s=9.9, tpot_s=0.01,
                               tokens=4) is True
        assert m.record_finish("stop", ttft_s=0.01, tpot_s=0.2,
                               tokens=4) is False
        assert m.slo_tpot_violations == 1

    def test_timeout_and_error_always_miss(self):
        m = self._m()
        assert m.record_finish("timeout") is False
        assert m.record_finish("error:engine", ttft_s=0.01,
                               tokens=3) is False
        snap = m.slo_snapshot()
        assert snap["slo_missed_requests"] == 2
        assert snap["goodput_tokens"] == 0
        # a timeout that never produced a first token is a TTFT violation
        assert snap["slo_ttft_violations"] >= 1

    def test_cancel_excluded(self):
        m = self._m()
        assert m.record_finish("cancelled") is None
        snap = m.slo_snapshot()
        assert snap["slo_met_requests"] == 0
        assert snap["slo_missed_requests"] == 0
        assert m.requests_cancelled == 1

    def test_rejected_counts_as_miss(self):
        """A 429 admission rejection IS a missed SLO: shed load must show
        as attainment loss, or the autoscaler sees overload as health."""
        m = self._m()
        m.record_finish("stop", ttft_s=0.01, tokens=2)
        m.record_rejected()
        snap = m.slo_snapshot()
        assert snap["slo_missed_requests"] == 1
        assert snap["slo_attainment"] == 0.5
        assert m.requests_rejected == 1

    def test_window_attainment_moves(self):
        m = self._m()
        for _ in range(3):
            m.record_finish("stop", ttft_s=0.01, tokens=5)
        m.record_finish("stop", ttft_s=0.9, tokens=5)
        snap = m.slo_snapshot()
        assert snap["slo_attainment_1m"] == 0.75
        assert snap["slo_attainment_5m"] == 0.75
        assert snap["goodput_tok_s_1m"] == pytest.approx(15 / 60.0)

    def test_verdict_stamped_on_trace_root(self, engine):
        """The SLO verdict lands on the request's http.request root span
        at finalize (ISSUE 10): /debug/trace and the slow-request log
        carry slo_met / slo_ttft_ms without re-deriving them."""
        from kafka_tpu import tracing

        tracing.reset()
        tracing.configure(sample=1.0)
        root = tracing.start_trace(name="http.request")
        ctx = tracing.current()
        try:
            req = GenRequest(request_id="slo-span", prompt_ids=[4, 5, 6],
                             max_new_tokens=3, trace=ctx)
            engine.submit(req)
            engine.run_to_completion()
            assert req.slo_met is not None
            assert root.attrs["slo_met"] == req.slo_met
            assert root.attrs["slo_ttft_ms"] > 0
        finally:
            tracing.finish_trace(root)

    def test_gauges_survive_failpoint_chaos(self):
        """ISSUE 10: the gauges the autoscaler reads are chaos-tested
        against the existing failpoint sites — an engine.step failure
        storm must land in slo_missed (via the worker's fail-all path or
        engine recovery), never wedge the counters, and the snapshot the
        signal feed serves must stay coherent throughout."""
        from kafka_tpu import failpoints

        cfg = ModelConfig(name="chaos-slo", vocab_size=128, hidden_size=64,
                          intermediate_size=128, num_layers=2, num_heads=4,
                          num_kv_heads=2, head_dim=16, dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(11))
        eng = InferenceEngine(
            cfg, params,
            EngineConfig(max_batch=2, page_size=8, num_pages=64,
                         max_pages_per_seq=8, prefill_buckets=(8, 16, 32)),
            kv_dtype=jnp.float32,
        )
        eng.generate([1, 2, 3], max_new_tokens=2)  # compile
        eng.submit(GenRequest(request_id="chaos-1", prompt_ids=[4, 5, 6],
                              max_new_tokens=8))
        # a STARTED lane is what engine recovery fail-stops (recovery
        # deliberately re-queues WAITING requests instead)
        while not eng.num_active:
            eng.step()
        failpoints.configure("engine.step", "error", "chaos", count=1)
        try:
            with pytest.raises(Exception):
                eng.run_to_completion()
        finally:
            failpoints.clear()
        events = eng.recover_from_failure()
        assert any(ev.finish_reason == "error:engine" for ev in events)
        snap = eng.metrics.snapshot(eng)
        # the failed request is an SLO miss with an intact snapshot
        assert snap["slo"]["slo_missed_requests"] >= 1
        assert snap["requests"]["failed"] >= 1
        assert snap["slo"]["slo_attainment"] < 1.0
        assert 0.0 <= snap["slo"]["slo_attainment_1m"] <= 1.0
        assert "utilization" in snap and "histograms" in snap
        # and the engine still serves cleanly afterwards (gauges recover)
        eng.metrics.slo_ttft_ms = 10_000.0
        r2 = eng.generate([7, 8, 9], max_new_tokens=2)
        assert r2.slo_met is True

    def test_roofline_survives_metrics_reset(self, monkeypatch):
        """Warmup/bench swap in fresh EngineMetrics objects; a known
        roofline (datasheet or env override) must be re-applied by the
        engine's cost recording, or MFU would flatline at 0 forever on
        the default (warmup=True) server path."""
        monkeypatch.setenv("KAFKA_TPU_PEAK_TFLOPS", "100")
        monkeypatch.setenv("KAFKA_TPU_PEAK_HBM_GBPS", "800")
        cfg = ModelConfig(name="roof-test", vocab_size=128, hidden_size=64,
                          intermediate_size=128, num_layers=2, num_heads=4,
                          num_kv_heads=2, head_dim=16, dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(12))
        eng = InferenceEngine(
            cfg, params,
            EngineConfig(max_batch=2, page_size=8, num_pages=64,
                         max_pages_per_seq=8, prefill_buckets=(8, 16, 32)),
            kv_dtype=jnp.float32,
        )
        assert eng.metrics.peak_source == "env"
        eng.metrics = EngineMetrics()  # the warmup-reset pattern
        assert eng.metrics.peak_source == "unknown"
        eng.generate([1, 2, 3], max_new_tokens=3)
        assert eng.metrics.peak_source == "env"
        assert eng.metrics.peak_flops == pytest.approx(100e12)
        snap = eng.metrics.snapshot(eng)
        assert snap["utilization"]["peak_tflops"] == 100.0

    def test_engine_deadline_timeout_is_slo_miss(self):
        """End-to-end: a request expiring its TTFT deadline finalizes as
        an SLO miss through the engine path (ISSUE 10 satellite)."""
        cfg = ModelConfig(name="slo-test", vocab_size=128, hidden_size=64,
                          intermediate_size=128, num_layers=2, num_heads=4,
                          num_kv_heads=2, head_dim=16, dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(9))
        eng = InferenceEngine(
            cfg, params,
            EngineConfig(max_batch=2, page_size=8, num_pages=64,
                         max_pages_per_seq=8, prefill_buckets=(8, 16, 32)),
            kv_dtype=jnp.float32,
        )
        eng.generate([1, 2, 3], max_new_tokens=2)  # compile
        met0 = eng.metrics.slo_met_requests
        # deadline 0: expired by the first _check_deadlines sweep
        req = GenRequest(request_id="slo-dl", prompt_ids=[4, 5, 6],
                         max_new_tokens=4, deadline_ttft_s=0.0)
        eng.submit(req)
        eng.run_to_completion()
        assert req.finish_reason == "timeout"
        assert req.slo_met is False
        assert eng.metrics.slo_missed_requests >= 1
        assert eng.metrics.slo_met_requests == met0
        # a clean request on the same engine is MET with goodput (target
        # widened so a loaded CI host can't flake the verdict)
        eng.metrics.slo_ttft_ms = 10_000.0
        good0 = eng.metrics.goodput_tokens
        r2 = eng.generate([7, 8, 9], max_new_tokens=3)
        assert r2.slo_met is True
        assert eng.metrics.goodput_tokens == good0 + len(r2.output_ids)


@pytest.fixture(scope="module")
def engine():
    cfg = ModelConfig(name="metrics-test", vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(5))
    return InferenceEngine(
        cfg, params,
        EngineConfig(max_batch=2, page_size=8, num_pages=64,
                     max_pages_per_seq=8, prefill_buckets=(8, 16, 32)),
        kv_dtype=jnp.float32,
    )


class TestTTFTBreakdown:
    def test_phases_recorded_and_exported(self, engine):
        engine.metrics = EngineMetrics()  # phase-local histograms
        req = engine.generate([5, 9, 23, 4], max_new_tokens=4)
        assert req.t_prefill_start is not None
        assert req.t_first_dispatch is not None
        snap = engine.metrics.snapshot(engine)
        bd = snap["ttft_breakdown_ms"]
        assert set(bd) == {"queue_wait", "prefill", "first_fetch"}
        # bucket-derived quantiles: each phase histogram recorded exactly
        # one sample whose TRUE value comes from the request's stamps —
        # the reported p50 must land in that sample's enclosing bucket
        # (catches unit mismatches and swapped stamps at bucket precision)
        truths = {
            "queue_wait": (req.t_prefill_start - req.submit_time) * 1e3,
            "prefill": (req.t_first_dispatch - req.t_prefill_start) * 1e3,
            "first_fetch": (req.first_token_time
                            - req.t_first_dispatch) * 1e3,
        }
        for phase, truth in truths.items():
            lo, hi = _bucket_bounds(engine.metrics.ttft_queue_ms,
                                    max(truth, 1e-6))
            # + slack: the JSON export rounds to 2 decimals, which can
            # nudge a value sitting exactly on the bucket bound past it
            assert lo < bd[phase]["p50"] <= hi + max(0.01, hi * 1e-5), (
                phase, truth, bd[phase]
            )
        # and the sum/count invariants hold per histogram
        for name in ("ttft_queue_ms", "ttft_prefill_ms", "ttft_fetch_ms"):
            h = snap["histograms"][name]
            assert h["count"] == sum(h["counts"]) >= 1

    def test_missing_stamp_records_nothing(self):
        m = EngineMetrics()
        m.record_ttft_breakdown(1.0, None, 2.0, 3.0)
        assert m.ttft_queue_ms.count == 0

    def test_forced_grammar_chains_without_roundtrips(self, engine):
        """A fully-forced grammar (singleton masks) never awaits a round
        trip; a genuinely ambiguous mask does.  The counter separates
        them — the arithmetic behind the on-prem latency projection."""
        rt0 = engine.metrics.constrained_roundtrips
        forced = [7, 8, 9, 10]
        req = engine.generate(
            [3, 5, 2], max_new_tokens=4,
            logits_mask_fn=lambda out: [forced[len(out)]]
            if len(out) < 4 else None,
        )
        assert req.output_ids == forced
        assert req.constrained_roundtrips == 0
        assert engine.metrics.constrained_roundtrips == rt0

        rt0 = engine.metrics.constrained_roundtrips
        req = engine.generate(
            [3, 5, 2], max_new_tokens=3,
            logits_mask_fn=lambda out: [11, 12, 13],  # always ambiguous
        )
        # token 1's mask rides the prefill dispatch (no extra trip);
        # tokens 2 and 3 each await the previous token back — 2 trips
        assert req.constrained_roundtrips == 2
        assert engine.metrics.constrained_roundtrips == rt0 + 2


class TestEngineRecording:
    def test_generation_populates_counters(self, engine):
        for i in range(3):
            engine.submit(GenRequest(
                request_id=f"m{i}",
                prompt_ids=list(np.random.RandomState(i).randint(1, 128, 9)),
                max_new_tokens=5, prefix_key=f"t{i}"))
        engine.run_to_completion()
        snap = engine.metrics.snapshot(engine)
        assert snap["requests"]["submitted"] >= 3
        assert snap["requests"]["finished"] >= 3
        assert snap["tokens"]["generated"] >= 15
        assert snap["ttft_ms"]["p50"] > 0
        assert snap["tpot_ms"]["p50"] >= 0
        assert 0 < snap["decode"]["batch_occupancy"] <= 2
        assert snap["engine"]["pages_total"] == 64
        assert snap["prefix_cache"]["entries"] == 3
        assert snap["engine"]["rtt_est_ms"] >= 0
        # bucket-derived p50 interpolates from 0 inside the lowest (0,1]
        # bucket when every burst is a single token (histogram_quantile
        # semantics), so the honest floor is >0, not >=1
        assert snap["emission"]["burst_tokens"]["p50"] > 0
        assert engine.metrics.burst_tokens.max >= 1
        # utilization estimator moved (ISSUE 10): real dispatches ran, so
        # the cost model accumulated flops/bytes against busy wall time
        util = snap["utilization"]
        assert util["decode"]["dispatches"] > 0
        assert util["decode"]["flops"] > 0
        assert util["prefill"]["tokens"] >= 27  # 3 x 9-token prompts
        assert util["decode"]["busy_s"] > 0
        # SLO verdicts were classified for every finished request
        slo = snap["slo"]
        assert (slo["slo_met_requests"] + slo["slo_missed_requests"]
                >= 3)

    def test_solo_stream_emits_smoothly(self):
        """VERDICT r2 #7: a lone interactive stream must not receive its
        tokens in fetch_wait_s-sized bursts.  With <=2 active streams the
        emit age-bound tightens to ~1.25x the measured RTT, so on a local
        link tokens pop (nearly) one per step: median burst size 1."""
        cfg = ModelConfig(name="cadence-test", vocab_size=128, hidden_size=64,
                          intermediate_size=128, num_layers=2, num_heads=4,
                          num_kv_heads=2, head_dim=16, dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(6))
        eng = InferenceEngine(
            cfg, params,
            EngineConfig(max_batch=2, page_size=8, num_pages=64,
                         max_pages_per_seq=8, prefill_buckets=(8, 16, 32),
                         fetch_wait_s=10.0),  # absurd cap: adaptivity must win
            kv_dtype=jnp.float32,
        )
        # without the adaptive bound every token would arrive in ONE
        # 40-token burst at the end (fetch_wait_s=10s, fetch_lag=96); with
        # it the typical pop is a single token across many emission events.
        # Non-adaptive behavior would be exactly two bursts: [1, 39].
        # Timing-sensitive on a loaded host (a hiccup groups tokens into a
        # larger burst), so allow a few attempts — non-adaptive code fails
        # ALL of them deterministically.
        from kafka_tpu.runtime.metrics import EngineMetrics

        last = None
        for _ in range(3):
            eng.metrics = EngineMetrics()
            eng.generate(list(range(1, 9)), max_new_tokens=40)
            snap = eng.metrics.snapshot(eng)
            last = (eng.metrics.burst_tokens.count,
                    eng.metrics.burst_tokens.max,
                    snap["emission"]["burst_gap_ms"]["p50"])
            if last[0] >= 3 and last[1] <= 30 and last[2] < 100:
                break
        else:
            raise AssertionError(f"emission stayed bursty: {last}")

    def test_emit_wait_tightens_only_when_quiet(self, engine):
        """The adaptive age bound applies at <=2 active streams and must
        NOT shrink the configured bound for busy batches (premature pops
        there would block the dispatch thread on unlanded transfers)."""
        saved_slots, saved_rtt = engine.slots, engine._rtt_est
        try:
            engine._rtt_est = 0.004
            engine.slots = [None] * engine.ecfg.max_batch
            quiet = engine._emit_wait()
            assert quiet == pytest.approx(0.005)  # 1.25 x rtt, under cap
            engine._rtt_est = 10.0
            assert engine._emit_wait() == engine.ecfg.fetch_wait_s  # capped
            engine._rtt_est = 0.004
            engine.slots = [object()] * 3 + [None] * (
                engine.ecfg.max_batch - 3
            )
            assert engine._emit_wait() == engine.ecfg.fetch_wait_s
        finally:
            engine.slots, engine._rtt_est = saved_slots, saved_rtt

    def test_burst_percentile_math(self):
        m = EngineMetrics()
        m.record_emit_burst(3)
        m.record_emit_burst(1)
        # bucket-derived: the p99 lands in 3's enclosing (2, 4] bucket
        p99 = m.snapshot()["emission"]["burst_tokens"]["p99"]
        assert 2.0 < p99 <= 4.0
        assert m.burst_tokens.max == 3.0


class TestMetricsEndpoint:
    def test_metrics_requires_local_engine(self, tmp_path, monkeypatch):
        from tests.test_server import make_client

        monkeypatch.delenv("KAFKA_TPU_PROFILING", raising=False)
        built, _, _ = make_client(tmp_path, [[{"content": "hi"}]])

        async def go():
            client = await built
            try:
                r = await client.get("/metrics")
                # FakeLLM has no engine -> 404 with a clean error body
                assert r.status == 404
                body = await r.json()
                assert "error" in body
                p = await client.post("/debug/profile", json={"seconds": 1})
                assert p.status == 403  # gated by KAFKA_TPU_PROFILING
            finally:
                await client.close()

        asyncio.run(go())

    def test_metrics_served_with_engine(self, tmp_path, engine):
        from kafka_tpu.llm import TPULLMProvider
        from kafka_tpu.models.tokenizer import ByteTokenizer
        from kafka_tpu.server.app import create_app
        from kafka_tpu.server.config import ServingConfig
        from kafka_tpu.db.local import LocalDBClient
        from aiohttp.test_utils import TestClient, TestServer

        # note: engine vocab (128) < ByteTokenizer's, but /metrics only
        # reads counters — no generation happens here
        provider = TPULLMProvider(engine, ByteTokenizer(), model_name="m")

        async def go():
            app = await create_app(
                cfg=ServingConfig(db_path=str(tmp_path / "m.db")),
                llm_provider=provider,
                db=LocalDBClient(str(tmp_path / "m.db")),
                tools=[],
            )
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get("/metrics")
                assert r.status == 200
                snap = await r.json()
                assert "ttft_ms" in snap and "engine" in snap
                assert snap["engine"]["pages_total"] == 64
                # the JSON snapshot carries the full telemetry plane
                assert "slo" in snap and "utilization" in snap
                assert "histograms" in snap

                # /admin/signals: the autoscaler input contract (ISSUE 10)
                s = await client.get("/admin/signals")
                assert s.status == 200
                sig = await s.json()
                assert sig["version"] == 9
                assert sig["dp"] == 1
                # version 4 (ISSUE 13): the autoscaler echo (null when
                # KAFKA_TPU_AUTOSCALE is off — the default here) and
                # the 1m-window verdict count behind the attainment
                # gauge
                assert sig["autoscaler"] is None
                assert isinstance(sig["slo"]["window_1m_requests"], int)
                assert set(sig["queue"]) >= {"depth", "peak",
                                             "trend_per_s"}
                # version 2 (ISSUE 11): flight-recorder anomaly state is
                # part of the contract — the "don't scale on stale math"
                # guard input
                assert sig["anomalies"]["anomalies_active"] == 0
                assert sig["anomalies"]["active"] == []
                for key in ("anomaly_queue_stall",
                            "anomaly_fetch_starvation",
                            "anomaly_mfu_collapse",
                            "anomaly_prefill_convoy"):
                    assert sig["anomalies"][key] == 0, key
                assert set(sig["batch"]) >= {"occupancy", "active",
                                             "max_batch", "slots_total"}
                for key in ("slo_attainment_1m", "slo_attainment_5m",
                            "goodput_tok_s", "slo_ttft_target_ms"):
                    assert key in sig["slo"], key
                # raw window SECTIONS stay internal to /metrics (the
                # version-4 window_1m_requests scalar is the one
                # deliberate exception)
                assert not any(isinstance(v, dict)
                               for v in sig["slo"].values())
                assert "window_1m" not in sig["slo"]
                assert set(sig["utilization"]) >= {"prefill", "decode",
                                                   "verify"}
                rep = sig["replicas"][0]
                assert rep["replica"] == 0
                assert rep["state"] == "healthy"
                for key in ("active", "waiting", "pages_free",
                            "pages_total", "utilization"):
                    assert key in rep, key
                assert set(rep["utilization"]["decode"]) == {
                    "mfu", "mfu_1m", "hbm_bw_util", "hbm_bw_util_1m",
                    "model_skew",
                }
                assert rep["anomalies_active"] == 0
                # version 3 (ISSUE 12): per-pool section — one
                # "colocated" pool when KAFKA_TPU_DP_ROLES is unset, so
                # the contract shape is role-independent
                assert sig["disagg"] is None
                (pool,) = sig["pools"]
                assert pool["role"] == "colocated"
                assert pool["replicas"] == [0]
                for key in ("queue_depth", "active", "batch_occupancy"):
                    assert key in pool, key
                assert set(pool["utilization"]) == {"prefill", "decode",
                                                    "verify"}
                assert set(pool["utilization"]["decode"]) == {
                    "mfu", "mfu_1m", "hbm_bw_util", "hbm_bw_util_1m",
                }
                assert sig["draining"] is False
                assert sig["admission"]["max_queue_depth"] == 256
            finally:
                await client.close()
                provider.worker.stop()

        asyncio.run(go())
