"""Observability: engine counters + the /metrics endpoint (VERDICT r1 #9)."""

import asyncio

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.models import ModelConfig, init_params
from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine
from kafka_tpu.runtime.metrics import EngineMetrics, _percentiles


class TestMetricsUnit:
    def test_percentiles(self):
        ps = _percentiles([float(i) for i in range(1, 101)])
        assert ps["p50"] == 50.0
        assert ps["p90"] == 90.0
        assert ps["p99"] == 99.0
        assert _percentiles([])["p50"] == 0.0

    def test_snapshot_shape(self):
        m = EngineMetrics()
        m.record_submit(10)
        m.record_first_token(0.05)
        m.record_token()
        m.record_decode_step(3)
        m.record_decode_step(2)
        m.record_finish("stop")
        snap = m.snapshot()
        assert snap["requests"]["submitted"] == 1
        assert snap["requests"]["finished"] == 1
        assert snap["tokens"]["generated"] == 1
        assert snap["ttft_ms"]["p50"] == 50.0
        assert snap["decode"]["steps"] == 2
        assert snap["decode"]["batch_occupancy"] == 2.5


@pytest.fixture(scope="module")
def engine():
    cfg = ModelConfig(name="metrics-test", vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(5))
    return InferenceEngine(
        cfg, params,
        EngineConfig(max_batch=2, page_size=8, num_pages=64,
                     max_pages_per_seq=8, prefill_buckets=(8, 16, 32)),
        kv_dtype=jnp.float32,
    )


class TestTTFTBreakdown:
    def test_phases_sum_to_ttft_and_export(self, engine):
        req = engine.generate([5, 9, 23, 4], max_new_tokens=4)
        assert req.t_prefill_start is not None
        assert req.t_first_dispatch is not None
        snap = engine.metrics.snapshot(engine)
        bd = snap["ttft_breakdown_ms"]
        assert set(bd) == {"queue_wait", "prefill", "first_fetch"}
        # the three phases reassemble the recorded TTFT exactly (all four
        # numbers derive from the same stamps; single request -> p50 is
        # that request) — catches unit mismatches and swapped stamps
        total = bd["queue_wait"]["p50"] + bd["prefill"]["p50"] \
            + bd["first_fetch"]["p50"]
        assert total == pytest.approx(snap["ttft_ms"]["p50"], abs=0.05)

    def test_missing_stamp_records_nothing(self):
        m = EngineMetrics()
        m.record_ttft_breakdown(1.0, None, 2.0, 3.0)
        assert len(m.ttft_queue_ms) == 0

    def test_forced_grammar_chains_without_roundtrips(self, engine):
        """A fully-forced grammar (singleton masks) never awaits a round
        trip; a genuinely ambiguous mask does.  The counter separates
        them — the arithmetic behind the on-prem latency projection."""
        rt0 = engine.metrics.constrained_roundtrips
        forced = [7, 8, 9, 10]
        req = engine.generate(
            [3, 5, 2], max_new_tokens=4,
            logits_mask_fn=lambda out: [forced[len(out)]]
            if len(out) < 4 else None,
        )
        assert req.output_ids == forced
        assert req.constrained_roundtrips == 0
        assert engine.metrics.constrained_roundtrips == rt0

        rt0 = engine.metrics.constrained_roundtrips
        req = engine.generate(
            [3, 5, 2], max_new_tokens=3,
            logits_mask_fn=lambda out: [11, 12, 13],  # always ambiguous
        )
        # token 1's mask rides the prefill dispatch (no extra trip);
        # tokens 2 and 3 each await the previous token back — 2 trips
        assert req.constrained_roundtrips == 2
        assert engine.metrics.constrained_roundtrips == rt0 + 2


class TestEngineRecording:
    def test_generation_populates_counters(self, engine):
        for i in range(3):
            engine.submit(GenRequest(
                request_id=f"m{i}",
                prompt_ids=list(np.random.RandomState(i).randint(1, 128, 9)),
                max_new_tokens=5, prefix_key=f"t{i}"))
        engine.run_to_completion()
        snap = engine.metrics.snapshot(engine)
        assert snap["requests"]["submitted"] >= 3
        assert snap["requests"]["finished"] >= 3
        assert snap["tokens"]["generated"] >= 15
        assert snap["ttft_ms"]["p50"] > 0
        assert snap["tpot_ms"]["p50"] >= 0
        assert 0 < snap["decode"]["batch_occupancy"] <= 2
        assert snap["engine"]["pages_total"] == 64
        assert snap["prefix_cache"]["entries"] == 3
        assert snap["engine"]["rtt_est_ms"] >= 0
        assert snap["emission"]["burst_tokens"]["p50"] >= 1

    def test_solo_stream_emits_smoothly(self):
        """VERDICT r2 #7: a lone interactive stream must not receive its
        tokens in fetch_wait_s-sized bursts.  With <=2 active streams the
        emit age-bound tightens to ~1.25x the measured RTT, so on a local
        link tokens pop (nearly) one per step: median burst size 1."""
        cfg = ModelConfig(name="cadence-test", vocab_size=128, hidden_size=64,
                          intermediate_size=128, num_layers=2, num_heads=4,
                          num_kv_heads=2, head_dim=16, dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(6))
        eng = InferenceEngine(
            cfg, params,
            EngineConfig(max_batch=2, page_size=8, num_pages=64,
                         max_pages_per_seq=8, prefill_buckets=(8, 16, 32),
                         fetch_wait_s=10.0),  # absurd cap: adaptivity must win
            kv_dtype=jnp.float32,
        )
        # without the adaptive bound every token would arrive in ONE
        # 40-token burst at the end (fetch_wait_s=10s, fetch_lag=96); with
        # it the typical pop is a single token across many emission events.
        # Non-adaptive behavior would be exactly two bursts: [1, 39].
        # Timing-sensitive on a loaded host (a hiccup groups tokens into a
        # larger burst), so allow a few attempts — non-adaptive code fails
        # ALL of them deterministically.
        from kafka_tpu.runtime.metrics import EngineMetrics

        last = None
        for _ in range(3):
            eng.metrics = EngineMetrics()
            eng.generate(list(range(1, 9)), max_new_tokens=40)
            snap = eng.metrics.snapshot(eng)
            last = (len(eng.metrics.burst_tokens),
                    max(eng.metrics.burst_tokens),
                    snap["emission"]["burst_gap_ms"]["p50"])
            if last[0] >= 3 and last[1] <= 30 and last[2] < 100:
                break
        else:
            raise AssertionError(f"emission stayed bursty: {last}")

    def test_emit_wait_tightens_only_when_quiet(self, engine):
        """The adaptive age bound applies at <=2 active streams and must
        NOT shrink the configured bound for busy batches (premature pops
        there would block the dispatch thread on unlanded transfers)."""
        saved_slots, saved_rtt = engine.slots, engine._rtt_est
        try:
            engine._rtt_est = 0.004
            engine.slots = [None] * engine.ecfg.max_batch
            quiet = engine._emit_wait()
            assert quiet == pytest.approx(0.005)  # 1.25 x rtt, under cap
            engine._rtt_est = 10.0
            assert engine._emit_wait() == engine.ecfg.fetch_wait_s  # capped
            engine._rtt_est = 0.004
            engine.slots = [object()] * 3 + [None] * (
                engine.ecfg.max_batch - 3
            )
            assert engine._emit_wait() == engine.ecfg.fetch_wait_s
        finally:
            engine.slots, engine._rtt_est = saved_slots, saved_rtt

    def test_burst_percentile_math(self):
        m = EngineMetrics()
        m.record_emit_burst(3)
        m.record_emit_burst(1)
        assert m.snapshot()["emission"]["burst_tokens"]["p99"] == 3.0


class TestMetricsEndpoint:
    def test_metrics_requires_local_engine(self, tmp_path, monkeypatch):
        from tests.test_server import make_client

        monkeypatch.delenv("KAFKA_TPU_PROFILING", raising=False)
        built, _, _ = make_client(tmp_path, [[{"content": "hi"}]])

        async def go():
            client = await built
            try:
                r = await client.get("/metrics")
                # FakeLLM has no engine -> 404 with a clean error body
                assert r.status == 404
                body = await r.json()
                assert "error" in body
                p = await client.post("/debug/profile", json={"seconds": 1})
                assert p.status == 403  # gated by KAFKA_TPU_PROFILING
            finally:
                await client.close()

        asyncio.run(go())

    def test_metrics_served_with_engine(self, tmp_path, engine):
        from kafka_tpu.llm import TPULLMProvider
        from kafka_tpu.models.tokenizer import ByteTokenizer
        from kafka_tpu.server.app import create_app
        from kafka_tpu.server.config import ServingConfig
        from kafka_tpu.db.local import LocalDBClient
        from aiohttp.test_utils import TestClient, TestServer

        # note: engine vocab (128) < ByteTokenizer's, but /metrics only
        # reads counters — no generation happens here
        provider = TPULLMProvider(engine, ByteTokenizer(), model_name="m")

        async def go():
            app = await create_app(
                cfg=ServingConfig(db_path=str(tmp_path / "m.db")),
                llm_provider=provider,
                db=LocalDBClient(str(tmp_path / "m.db")),
                tools=[],
            )
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get("/metrics")
                assert r.status == 200
                snap = await r.json()
                assert "ttft_ms" in snap and "engine" in snap
                assert snap["engine"]["pages_total"] == 64
            finally:
                await client.close()
                provider.worker.stop()

        asyncio.run(go())
