"""Draft-free speculative decoding (ISSUE 5): equivalence matrix,
distribution preservation, rollback under preemption, prefix-cache
write-span invariant, the default-off guarantee, and the speculation
metric registry.

The load-bearing property: speculation is a pure latency/throughput
optimization — greedy outputs are BIT-IDENTICAL to the non-speculative
path across any scheduler churn, and sampled outputs follow the target
distribution at any temperature (the verify step samples every position
with the sequential path's own per-(seed, position) keys and accepts
candidates exactly while sample == candidate).
"""

import math
import re
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.models import ModelConfig, forward, init_params
from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine
from kafka_tpu.runtime.speculative import LaneSpeculator


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(name="spec-test", vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(7))
    return cfg, params


def make_engine(cfg, params, spec_k=4, **kw):
    defaults = dict(max_batch=4, page_size=8, num_pages=64,
                    max_pages_per_seq=8, prefill_buckets=(8, 16, 32, 64),
                    speculative_k=spec_k)
    defaults.update(kw)
    return InferenceEngine(cfg, params, EngineConfig(**defaults),
                           kv_dtype=jnp.float32)


class ForcedSpeculator:
    """Test stand-in for LaneSpeculator with a scripted proposal fn —
    engagement becomes deterministic (the organic proposer depends on
    model-emitted repetition)."""

    def __init__(self, fn):
        self._fn = fn
        self.hist = []
        self.accept_ewma = 1.0
        self.observed = []

    def push(self, token):
        self.hist.append(token)

    def propose(self, k_max):
        return list(self._fn(k_max))[:max(0, k_max)]

    def observe(self, accepted, proposed):
        self.observed.append((accepted, proposed))


def assert_greedy_consistent(cfg, params, prompt, out):
    seq = list(prompt) + list(out)
    x = jnp.asarray([seq], jnp.int32)
    pos = jnp.arange(len(seq), dtype=jnp.int32)[None, :]
    logits, _ = forward(params, cfg, x, pos)
    preds = np.asarray(jnp.argmax(logits[0], axis=-1))
    for i in range(len(prompt) - 1, len(seq) - 1):
        assert preds[i] == seq[i + 1], (
            f"divergence at position {i}: engine={seq[i + 1]} ref={preds[i]}"
        )


class TestNgramProposer:
    def test_earliest_occurrence_anchors_long_runs(self):
        sp = LaneSpeculator([1, 2, 3, 4, 5, 1, 2])
        # suffix (1, 2) first occurred at position 0 -> continuation 3,4,5
        assert sp.propose(3) == [3, 4, 5]
        assert sp.propose(2) == [3, 4]

    def test_no_match_no_proposal(self):
        sp = LaneSpeculator([1, 2, 3, 4, 5, 6])
        assert sp.propose(4) == []

    def test_pushes_extend_history(self):
        sp = LaneSpeculator([9, 8, 9, 8])
        sp.push(9)
        sp.push(8)
        # longest anchor wins: suffix trigram (8, 9, 8) first occurred at
        # positions 1..3 -> continuation from index 4 = [9, 8]
        assert sp.propose(4) == [9, 8]

    def test_long_prompt_index_amortized(self):
        """Admitting a long prompt must not index it eagerly (that work
        runs on the single engine worker thread and would freeze token
        emission for every in-flight stream); the index catches up
        INDEX_BUDGET tokens per propose call and the lane rides plain
        decode until it covers the whole history."""
        from kafka_tpu.runtime import speculative as sd

        base = [1, 2, 3, 4, 5, 1, 2]
        prompt = list(range(6, 300)) * 40 + base  # ~11.8k tokens
        sp = LaneSpeculator(prompt)
        assert sp._indexed == 0  # construction defers all index work
        assert sp.propose(3) == []  # still warming: no anchor yet
        for _ in range(len(prompt) // sd.INDEX_BUDGET + 2):
            out = sp.propose(3)
            if out:
                break
        assert out == [3, 4, 5]  # same anchor an eager build finds
        assert sp._indexed == len(sp.hist)
        from kafka_tpu.runtime import speculative as sd

        sp = LaneSpeculator([1, 2, 1, 2])
        for _ in range(20):
            sp.observe(0, 4)  # total rejection
        assert sp.accept_ewma < sd.ACCEPT_FLOOR
        assert sp.propose(4) == []  # throttled despite a match
        for _ in range(sd.PROBE_TOKENS):
            sp.push(1)
            sp.push(2)
        assert sp.propose(4) != []  # periodic re-probe


class TestSpeculativeEquivalence:
    """Greedy bit-identity and seeded-sampling identity, spec on vs off,
    across admit/retire churn, parking, and mixed temperatures."""

    def test_solo_greedy_bit_identical(self, model):
        cfg, params = model
        prompt = [1, 9, 23, 54, 3, 17, 88, 4, 61, 12, 7]
        plain = make_engine(cfg, params, spec_k=0).generate(
            prompt, max_new_tokens=24)
        spec = make_engine(cfg, params, spec_k=4).generate(
            prompt, max_new_tokens=24)
        assert spec.output_ids == plain.output_ids
        assert spec.finish_reason == plain.finish_reason
        assert_greedy_consistent(cfg, params, prompt, spec.output_ids)

    def _batch(self, cfg, params, spec_k, n=6, gen=24, **kw):
        eng = make_engine(cfg, params, spec_k=spec_k, **kw)
        reqs = []
        for i in range(n):
            r = GenRequest(
                request_id=f"r{i}", prompt_ids=[2 + i, 9, 23, 54, 7],
                max_new_tokens=gen,
                temperature=0.0 if i % 2 == 0 else 0.9, seed=i,
            )
            eng.submit(r)
            reqs.append(r)
        eng.run_to_completion()
        return [(r.output_ids, r.finish_reason) for r in reqs], eng

    def test_churn_batch_identical_mixed_temperatures(self, model):
        """6 requests over 4 slots: admissions, retirements, parking, and
        sampled lanes alongside greedy ones — outputs must match the
        non-speculative engine token for token."""
        cfg, params = model
        plain, _ = self._batch(cfg, params, 0)
        spec, eng = self._batch(cfg, params, 4)
        assert spec == plain
        assert eng.metrics.speculation_verify_steps > 0, (
            "speculation never engaged — the equivalence was vacuous"
        )
        assert not eng.self_check()

    def test_oversubscribed_parking_identical(self, model):
        cfg, params = model

        def run(spec_k):
            eng = make_engine(cfg, params, spec_k=spec_k, max_batch=2,
                              num_pages=96, max_pages_per_seq=8)
            reqs = [GenRequest(request_id=f"p-{i}",
                               prompt_ids=[5 + i, 9, 23],
                               max_new_tokens=24) for i in range(8)]
            for r in reqs:
                eng.submit(r)
            eng.run_to_completion()
            return [r.output_ids for r in reqs], eng

        plain, _ = run(0)
        spec, eng = run(4)
        assert spec == plain
        assert eng.metrics.speculation_verify_steps > 0
        assert not eng.self_check()

    def test_stop_tokens_inside_accepted_run(self, model):
        """A stop token discovered inside an accepted speculative run must
        truncate exactly where sequential decoding would."""
        cfg, params = model
        free = make_engine(cfg, params, spec_k=0).generate(
            [1, 9, 23, 54], max_new_tokens=16)
        stop_tok = free.output_ids[5]
        first = free.output_ids.index(stop_tok)

        def with_stop(spec_k):
            r = make_engine(cfg, params, spec_k=spec_k).generate(
                [1, 9, 23, 54], max_new_tokens=16,
                stop_token_ids=(stop_tok,))
            return r.output_ids, r.finish_reason

        assert with_stop(4) == with_stop(0)
        out, reason = with_stop(4)
        assert out == free.output_ids[: first + 1]
        assert reason == "stop"

    def test_deadline_timeout_with_speculation(self, model):
        cfg, params = model
        # wide window so the budget outlives the deadline even with every
        # program pre-compiled by earlier tests (the timeout must land
        # MID-decode, with speculative dispatches in flight)
        eng = make_engine(cfg, params, spec_k=4, num_pages=96,
                          max_pages_per_seq=32)
        req = GenRequest(request_id="dl", prompt_ids=[1, 2, 3],
                         max_new_tokens=5000, deadline_s=0.02)
        eng.submit(req)
        reason = None
        t0 = time.monotonic()
        while reason is None and time.monotonic() - t0 < 60:
            for ev in eng.step():
                if ev.finished:
                    reason = ev.finish_reason
        assert reason == "timeout"
        assert all(s is None for s in eng.slots)
        assert eng.pool.free_pages == eng.pool.num_pages - 1
        assert not eng.self_check()
        # monotone counters survive the discard of in-flight verify work
        m = eng.metrics
        assert (m.speculation_accepted_tokens + m.speculation_rejected_tokens
                <= m.speculation_proposed_tokens)
        # the engine keeps serving afterwards
        ok = eng.generate([4, 5, 6], max_new_tokens=2)
        assert ok.finish_reason == "length"

    def test_constrained_lane_never_speculates(self, model):
        """Constrained lanes keep the mask contract (per-token host
        turnaround) and must coexist with speculating peers.  The peer is
        FORCED to propose (oracle speculator): verify dispatches really
        happen while the constrained lane is active, so a constrained
        lane riding a verify dispatch unmasked would fail the equality
        below (the organic proposer would not engage on this prompt and
        the coexistence would go untested)."""
        cfg, params = model
        free_truth = self._free_truth(cfg, params)

        def run(spec_k):
            eng = make_engine(cfg, params, spec_k=spec_k)
            allowed = [10, 11, 12]
            c = GenRequest(request_id="c", prompt_ids=[5, 2, 9],
                           max_new_tokens=6,
                           logits_mask_fn=lambda out: allowed)
            free = GenRequest(request_id="f", prompt_ids=[1, 9, 23],
                              max_new_tokens=12)
            eng.submit(c)
            eng.submit(free)
            assert c.spec is None  # constrained: no speculator
            if spec_k > 0:
                free.spec = ForcedSpeculator(
                    lambda k: free_truth[
                        len(free.output_ids):len(free.output_ids) + k])
            done = eng.run_to_completion()
            if spec_k > 0:
                # the coexistence was actually exercised
                assert eng.metrics.speculation_proposed_tokens > 0
            assert all(t in allowed for t in done["c"].output_ids)
            return done["c"].output_ids, done["f"].output_ids

        assert run(4) == run(0)

    def _free_truth(self, cfg, params):
        return make_engine(cfg, params, spec_k=0).generate(
            [1, 9, 23], max_new_tokens=12).output_ids


class TestAcceptancePath:
    """Deterministic exercise of full and partial acceptance via a
    patched proposer (the organic n-gram proposer's engagement depends on
    model-emitted repetition)."""

    def _true_continuation(self, cfg, params, prompt, gen):
        return make_engine(cfg, params, spec_k=0).generate(
            prompt, max_new_tokens=gen).output_ids

    def test_oracle_proposals_fully_accepted(self, model):
        cfg, params = model
        prompt = [4, 40, 77, 2]
        truth = self._true_continuation(cfg, params, prompt, 20)
        eng = make_engine(cfg, params, spec_k=4)
        req = GenRequest(request_id="o", prompt_ids=prompt,
                         max_new_tokens=20)
        eng.submit(req)
        # oracle: always propose the true greedy continuation
        req.spec = ForcedSpeculator(
            lambda k: truth[len(req.output_ids):len(req.output_ids) + k])
        eng.run_to_completion()
        assert req.output_ids == truth
        m = eng.metrics
        assert m.speculation_accepted_tokens > 0
        assert m.speculation_accepted_tokens == m.speculation_proposed_tokens
        # K+1 tokens per verify dispatch: far fewer steps than tokens
        assert m.decode_steps < len(truth)

    def test_adversarial_proposals_all_rejected_still_exact(self, model):
        cfg, params = model
        prompt = [4, 40, 77, 2]
        truth = self._true_continuation(cfg, params, prompt, 12)
        eng = make_engine(cfg, params, spec_k=4)
        req = GenRequest(request_id="j", prompt_ids=prompt,
                         max_new_tokens=12)
        eng.submit(req)
        # junk candidates never matching the model's argmax stream
        req.spec = ForcedSpeculator(lambda k: [
            (truth[min(len(req.output_ids), len(truth) - 1)] + 1) % 128
        ] * min(k, 3))
        eng.run_to_completion()
        assert req.output_ids == truth  # bonus tokens carry the stream
        m = eng.metrics
        assert m.speculation_rejected_tokens > 0
        assert m.speculation_accepted_tokens == 0

    def test_partial_acceptance_mid_run(self, model):
        cfg, params = model
        prompt = [4, 40, 77, 2]
        truth = self._true_continuation(cfg, params, prompt, 20)
        eng = make_engine(cfg, params, spec_k=4)
        req = GenRequest(request_id="h", prompt_ids=prompt,
                         max_new_tokens=20)
        eng.submit(req)

        def half_oracle(k):
            pos = len(req.output_ids)
            good = truth[pos:pos + max(1, k // 2)]
            return good + [(t + 1) % 128 for t in
                           truth[pos + len(good):pos + k]]

        req.spec = ForcedSpeculator(half_oracle)
        eng.run_to_completion()
        assert req.output_ids == truth
        m = eng.metrics
        assert m.speculation_accepted_tokens > 0
        assert m.speculation_rejected_tokens > 0


class TestDistributionPreservation:
    """The verify sampler must follow the target distribution at any
    temperature.  By construction it samples with the sequential path's
    per-(seed, position) keys, so (a) per-seed outputs are identical to
    the non-speculative engine, and (b) the empirical first-verify-token
    distribution chi-squares against the analytic softmax."""

    N_SEEDS = 400

    def _collect(self, cfg, params, spec_k, temp, seeds, force_junk):
        outs = {}
        eng = make_engine(cfg, params, spec_k=spec_k)
        for s in seeds:
            req = GenRequest(request_id=f"d{spec_k}-{temp}-{s}",
                             prompt_ids=[3, 71, 15, 8], max_new_tokens=2,
                             temperature=temp, seed=s)
            eng.submit(req)
            if force_junk and req.spec is not None:
                # always propose one junk candidate: every verify round
                # exercises the rejection/bonus sampler
                req.spec = ForcedSpeculator(lambda k: [0])
            eng.run_to_completion()
            outs[s] = list(req.output_ids)
        return outs

    @pytest.mark.parametrize("temp", [1.0, 1.5])
    def test_sampled_outputs_identical_high_temp(self, model, temp):
        """Exact per-seed identity with the non-speculative engine — the
        strongest preservation claim (the verify sampler IS the
        sequential sampler at every position)."""
        cfg, params = model
        seeds = list(range(120))
        spec = self._collect(cfg, params, 4, temp, seeds, force_junk=True)
        plain = self._collect(cfg, params, 0, temp, seeds, force_junk=False)
        assert spec == plain

    def test_sampled_outputs_identical_and_chi_square(self, model):
        """At temp 0.7 (modal first token frequent enough to condition
        on), additionally chi-square the verify-sampled SECOND token
        against the analytic conditional softmax — the end-to-end check
        that the rejection/bonus sampler preserves the target
        distribution, not just that two implementations agree."""
        temp = 0.7
        cfg, params = model
        seeds = list(range(self.N_SEEDS))
        spec = self._collect(cfg, params, 4, temp, seeds, force_junk=True)
        plain = self._collect(cfg, params, 0, temp, seeds, force_junk=False)
        assert spec == plain
        # the first token is prefill-sampled; the second is the verify
        # step's bonus sample (the junk candidate forces a verify round)
        firsts = [spec[s][0] for s in seeds]
        mode = max(set(firsts), key=firsts.count)
        cond = [spec[s][1] for s in seeds if spec[s][0] == mode]
        assert len(cond) >= 40, "modal first token too rare for the test"
        seq = jnp.asarray([[3, 71, 15, 8, mode]], jnp.int32)
        pos = jnp.arange(5, dtype=jnp.int32)[None, :]
        logits, _ = forward(params, cfg, seq, pos)
        probs = np.asarray(jax.nn.softmax(logits[0, -1] / temp))
        counts = np.bincount(cond, minlength=cfg.vocab_size).astype(float)
        n = counts.sum()
        # lump tokens with tiny expected counts into one bucket
        big = probs * n >= 5
        exp = np.concatenate([probs[big] * n, [probs[~big].sum() * n]])
        obs = np.concatenate([counts[big], [counts[~big].sum()]])
        keep = exp > 0
        chi2 = float(((obs[keep] - exp[keep]) ** 2 / exp[keep]).sum())
        df = int(keep.sum()) - 1
        # generous bound (~p > 1e-4): catches systematic bias, not noise
        limit = df + 4.0 * math.sqrt(2.0 * max(df, 1)) + 10.0
        assert chi2 < limit, (
            f"temp {temp}: chi2 {chi2:.1f} over df {df} (limit {limit:.1f})"
        )


class TestRollbackAndPreemption:
    def test_rollback_under_preemption_with_partial_acceptance(self, model):
        """Page pressure mid-speculation: the pipeline drains (reconciling
        partially accepted runs), the victim rolls back to the queue, and
        resumed outputs stay greedy-exact."""
        cfg, params = model

        def run(spec_k):
            # 6 usable pages against two lanes whose full trajectories
            # need 6 pages EACH (window-clamped budgets): page pressure
            # must preempt someone mid-generation in every scheduling,
            # however fast speculation retires tokens
            eng = make_engine(cfg, params, spec_k=spec_k, max_batch=2,
                              num_pages=7, max_pages_per_seq=5,
                              max_parked=0)
            p1 = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7]
            p2 = [2, 7, 1, 8, 2, 8, 1, 8, 2, 8, 4, 5, 9, 4]
            a = GenRequest(request_id="x", prompt_ids=p1, max_new_tokens=26)
            b = GenRequest(request_id="y", prompt_ids=p2, max_new_tokens=26)
            eng.submit(a)
            eng.submit(b)
            if spec_k and a.spec is not None:
                # half-oracle proposals keep partial acceptance happening
                # right up to the page-pressure preemption point
                truth = make_engine(cfg, params, spec_k=0).generate(
                    p1, max_new_tokens=26).output_ids

                def half(k):
                    pos = len(a.output_ids)
                    good = truth[pos:pos + max(1, k // 2)]
                    return good + [(t + 3) % 128 for t in
                                   truth[pos + len(good):pos + k]]

                a.spec = ForcedSpeculator(half)
            done = eng.run_to_completion()
            return ([done["x"].output_ids, done["y"].output_ids],
                    eng.metrics.requests_preempted, eng)

        plain, _, _ = run(0)
        spec, preempts, eng = run(4)
        assert spec == plain
        assert preempts > 0, "preemption never exercised"
        assert eng.metrics.speculation_accepted_tokens > 0
        assert eng.pool.free_pages == 7 - 1
        assert not eng.self_check()

    def test_window_limit_inside_speculative_run(self, model):
        """A lane whose window fills mid-run must finish with length at
        exactly the sequential boundary (the drain-side limit check)."""
        cfg, params = model

        def run(spec_k):
            eng = make_engine(cfg, params, spec_k=spec_k, max_batch=2,
                              num_pages=16, max_pages_per_seq=4)  # window 32
            r = eng.generate([5, 2, 9, 1], max_new_tokens=64)
            return r.output_ids, r.finish_reason

        assert run(4) == run(0)
        out, reason = run(4)
        assert reason == "length"


class TestPrefixCacheInteraction:
    def test_speculative_writes_never_touch_shared_pages(self, model):
        """Thread B reuses thread A's radix-cached prefix while
        speculating: every verify write span must be private (refcount 1,
        unknown to the cache) — asserted live by _assert_private_tail on
        every proposing dispatch."""
        cfg, params = model
        eng = make_engine(cfg, params, spec_k=4, num_pages=96)
        checks = []
        orig = eng._assert_private_tail
        eng._assert_private_tail = lambda req, cl: (
            checks.append((req.request_id, cl)), orig(req, cl))[1]
        a = GenRequest(request_id="a", prompt_ids=[7] * 20 + [3, 9],
                       max_new_tokens=16, prefix_key="tA")
        eng.submit(a)
        eng.run_to_completion()
        assert eng.prefix_cache.total_pages > 0
        b = GenRequest(request_id="b", prompt_ids=[7] * 20 + [3, 9, 4],
                       max_new_tokens=16, prefix_key="tB")
        eng.submit(b)
        eng.run_to_completion()
        assert b.cached_tokens > 0 and b.cache_source == "cross"
        assert checks, "no speculative dispatch exercised the invariant"
        assert not eng.self_check()
        # outputs still greedy-exact through cache reuse + speculation
        assert_greedy_consistent(cfg, params, b.prompt_ids, b.output_ids)

    def test_own_thread_rehit_with_speculation(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, spec_k=4, num_pages=96)
        p = [7] * 20 + [3, 9]
        a = GenRequest(request_id="a", prompt_ids=p, max_new_tokens=8,
                       prefix_key="tS")
        eng.submit(a)
        eng.run_to_completion()
        p2 = p + a.output_ids + [4, 4]
        b = GenRequest(request_id="b", prompt_ids=p2, max_new_tokens=8,
                       prefix_key="tS")
        eng.submit(b)
        eng.run_to_completion()
        assert b.cached_tokens > 0 and b.cache_source == "own"
        assert not eng.self_check()


class TestDefaultOff:
    def test_k0_compiles_no_verify_fn_and_matches(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, spec_k=0)
        reqs = [GenRequest(request_id=f"k0-{i}", prompt_ids=[2 + i, 9, 23],
                           max_new_tokens=12) for i in range(4)]
        for r in reqs:
            eng.submit(r)
        eng.run_to_completion()
        assert eng._verify_fn is None, "K=0 must never build a verify fn"
        for r in reqs:
            assert r.spec is None and r.spec_ahead == 0
            assert_greedy_consistent(cfg, params, r.prompt_ids,
                                     r.output_ids)
        m = eng.metrics
        assert m.speculation_verify_steps == 0
        assert m.speculation_proposed_tokens == 0

    def test_negative_k_rejected(self, model):
        cfg, params = model
        with pytest.raises(ValueError, match="speculative_k"):
            make_engine(cfg, params, spec_k=-1)

    def test_oversized_k_rejected(self, model):
        cfg, params = model
        with pytest.raises(ValueError, match="speculative_k"):
            make_engine(cfg, params, spec_k=64, max_pages_per_seq=2)


class TestSpeculationMetricRegistry:
    """Every speculation metric family name must appear in BOTH
    runtime/metrics.py and server/prometheus.py, and neither file may
    invent speculation metrics outside the registry — the SITES/SPANS
    both-directions pattern."""

    def _source(self, relpath):
        import os

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(root, relpath)) as f:
            return f.read()

    def test_registry_both_directions(self):
        from kafka_tpu.runtime.metrics import SPECULATION_METRIC_KEYS

        metrics_src = self._source("kafka_tpu/runtime/metrics.py")
        prom_src = self._source("kafka_tpu/server/prometheus.py")
        for key in SPECULATION_METRIC_KEYS:
            assert f'"{key}"' in metrics_src, (
                f"{key} missing from runtime/metrics.py"
            )
            assert f'"{key}"' in prom_src, (
                f"{key} missing from server/prometheus.py"
            )
        wired = set()
        for src in (metrics_src, prom_src):
            wired |= set(re.findall(r'"(speculation_[a-z_]+)"', src))
        undocumented = wired - set(SPECULATION_METRIC_KEYS)
        assert not undocumented, (
            f"speculation metrics outside the registry: {undocumented}"
        )

    def test_snapshot_carries_registry_keys(self, model):
        from kafka_tpu.runtime.metrics import (
            EngineMetrics,
            SPECULATION_METRIC_KEYS,
        )

        snap = EngineMetrics().snapshot()
        for key in SPECULATION_METRIC_KEYS:
            assert key in snap["speculation"]

    def test_waste_rename_aliases_removed(self, model):
        """The speculative_wasted_* JSON aliases PR 5 kept 'one release'
        are gone — fetch_pipeline_wasted_* is the only spelling (README
        "Metrics rename")."""
        from kafka_tpu.runtime.metrics import EngineMetrics

        m = EngineMetrics()
        m.record_wasted_token(3)
        snap = m.snapshot()
        assert snap["tokens"]["fetch_pipeline_wasted"] == 3
        assert "speculative_wasted" not in snap["tokens"]
        assert "speculative_waste_frac" not in snap["tokens"]


class TestBenchSpeculativeSmoke:
    def test_bench_speculative_cpu_smoke(self, model):
        """bench.py speculative, tier-1 shape: acceptance > 0 and output
        equivalence must hold on the CPU backend."""
        import sys
        from pathlib import Path

        sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
        from bench import speculative_phase

        cfg, params = model
        out = speculative_phase(cfg, params, n_lanes=3, prompt_len=40,
                                gen_len=24, k=6, page_size=8)
        assert out["outputs_match"], "speculation changed greedy outputs"
        assert out["acceptance_rate"] > 0
        assert out["accepted_tokens"] > 0
        assert out["verify_steps"] > 0
        # speculation must actually shrink the dispatch count
        assert (out["decode_steps"]["speculative"]
                < out["decode_steps"]["baseline"])
