"""Agent-loop tests with a scripted FakeLLMProvider (SURVEY §4): tool-call
streaming, idle/text/max-iteration termination, compaction retry, tool
errors, and parallel tool fan-out. No model, no network, no JAX."""

import asyncio
import json

import pytest

from kafka_tpu.agents import Agent, IDLE_TOOL_NAME
from kafka_tpu.core.types import ContextLengthError, StreamChunk
from kafka_tpu.llm.base import LLMProvider
from kafka_tpu.llm.compaction import ContextCompactionProvider
from kafka_tpu.tools import AgentToolProvider, Tool, ToolEvent


def run(coro):
    return asyncio.run(coro)


def text_turn(*parts, cid="chatcmpl-fake1"):
    """A scripted assistant text turn as a chunk list."""
    chunks = [StreamChunk(role="assistant", id=cid)]
    chunks += [StreamChunk(content=p, id=cid) for p in parts]
    chunks.append(StreamChunk(finish_reason="stop", id=cid))
    return chunks


def tool_turn(name, args: dict, call_id="call_1", cid="chatcmpl-fake2"):
    """A scripted tool-call turn, split into deltas like real providers."""
    args_json = json.dumps(args)
    mid = len(args_json) // 2
    return [
        StreamChunk(role="assistant", id=cid),
        StreamChunk(
            tool_calls=[{
                "index": 0, "id": call_id, "type": "function",
                "function": {"name": name, "arguments": args_json[:mid]},
            }],
            id=cid,
        ),
        StreamChunk(
            tool_calls=[{
                "index": 0, "function": {"arguments": args_json[mid:]},
            }],
            id=cid,
        ),
        StreamChunk(finish_reason="tool_calls", id=cid),
    ]


class FakeLLM(LLMProvider):
    """Plays back scripted turns; can raise a context error first."""

    provider_name = "fake"

    def __init__(self, turns, context_errors=0):
        self.turns = list(turns)
        self.context_errors = context_errors
        self.seen_messages = []

    async def stream_completion(self, messages, **kw):
        self.seen_messages.append(list(messages))
        if self.context_errors > 0:
            self.context_errors -= 1
            raise ContextLengthError(9999, 100, "fake")
        if not self.turns:
            raise AssertionError("FakeLLM ran out of scripted turns")
        for chunk in self.turns.pop(0):
            yield chunk


class FakeCompaction(ContextCompactionProvider):
    def __init__(self):
        self.calls = 0

    async def compact(self, messages, model=None, fit=None):
        self.calls += 1
        self.last_fit = fit
        return messages[-2:]  # crude but structurally fine for these tests


def make_tools():
    def add(a: int, b: int):
        return a + b

    async def fail(**kw):
        raise ValueError("deliberate failure")

    async def counter(n: int = 3):
        for i in range(n):
            yield f"tick {i}\n"

    return AgentToolProvider(tools=[
        Tool(name="add", description="add two numbers",
             parameters={"type": "object", "properties": {
                 "a": {"type": "integer"}, "b": {"type": "integer"}}},
             handler=add),
        Tool(name="fail", description="always fails", handler=fail),
        Tool(name="counter", description="streams ticks", handler=counter),
    ])


async def collect(agen):
    return [e async for e in agen]


USER = [{"role": "user", "content": "hi"}]


class TestTermination:
    def test_text_response_terminates(self):
        llm = FakeLLM([text_turn("hello", " world")])
        agent = Agent(llm, make_tools(), system_prompt="sys")
        events = run(collect(agent.run(USER)))
        done = events[-1]
        assert done["type"] == "agent_done"
        assert done["reason"] == "text_response"
        assert done["final_content"] == "hello world"
        # OpenAI chunks were forwarded
        assert any(e.get("object") == "chat.completion.chunk" for e in events)

    def test_idle_tool_terminates(self):
        llm = FakeLLM([
            tool_turn(IDLE_TOOL_NAME, {"summary": "all done"}),
        ])
        agent = Agent(llm, make_tools())
        events = run(collect(agent.run(USER)))
        done = events[-1]
        assert done["reason"] == "idle"
        assert done["final_content"] == "all done"
        # idle produced a tool_result event too
        assert any(
            e.get("type") == "tool_result" and e["name"] == IDLE_TOOL_NAME
            for e in events
        )

    def test_max_iterations(self):
        turns = [
            tool_turn("add", {"a": 1, "b": 2}, call_id=f"c{i}",
                      cid=f"chatcmpl-i{i}")
            for i in range(5)
        ]
        llm = FakeLLM(turns)
        agent = Agent(llm, make_tools(), max_iterations=3)
        events = run(collect(agent.run(USER)))
        assert events[-1]["reason"] == "max_iterations"
        assert len(llm.seen_messages) == 3

    def test_system_prompt_injected_once(self):
        llm = FakeLLM([text_turn("ok")])
        agent = Agent(llm, system_prompt="be brief")
        run(collect(agent.run(USER)))
        sent = llm.seen_messages[0]
        assert sent[0]["role"] == "system" and sent[0]["content"] == "be brief"

    def test_existing_system_prompt_not_overridden(self):
        llm = FakeLLM([text_turn("ok")])
        agent = Agent(llm, system_prompt="ignored")
        msgs = [{"role": "system", "content": "original"}] + USER
        run(collect(agent.run(msgs)))
        sent = llm.seen_messages[0]
        assert sent[0]["content"] == "original"
        assert sum(1 for m in sent if m["role"] == "system") == 1


class TestToolExecution:
    def test_tool_called_and_result_fed_back(self):
        llm = FakeLLM([
            tool_turn("add", {"a": 2, "b": 40}),
            text_turn("the answer is 42"),
        ])
        agent = Agent(llm, make_tools())
        events = run(collect(agent.run(USER)))
        results = [e for e in events if e.get("type") == "tool_result"]
        assert results and results[-1]["kind"] == "result"
        assert results[-1]["data"] == 42
        # second LLM call saw the tool message
        second = llm.seen_messages[1]
        assert second[-1]["role"] == "tool"
        assert second[-1]["content"] == "42"
        assert second[-2]["role"] == "assistant"
        assert second[-2]["tool_calls"][0]["function"]["name"] == "add"

    def test_streaming_tool_events_forwarded(self):
        llm = FakeLLM([
            tool_turn("counter", {"n": 3}),
            text_turn("done"),
        ])
        agent = Agent(llm, make_tools())
        events = run(collect(agent.run(USER)))
        deltas = [
            e for e in events
            if e.get("type") == "tool_result" and e["kind"] == "delta"
        ]
        assert len(deltas) == 3
        assert deltas[0]["data"] == "tick 0\n"
        # the fed-back tool message carries the FULL streamed output
        second = llm.seen_messages[1]
        assert second[-1]["content"] == "tick 0\ntick 1\ntick 2\n"

    def test_parallel_pump_crash_surfaces_real_error(self):
        class CrashingProvider(AgentToolProvider):
            async def run_tool_stream(self, name, arguments, tool_call_id=None):
                if name == "boom":
                    raise RuntimeError("provider exploded")
                async for ev in super().run_tool_stream(
                    name, arguments, tool_call_id
                ):
                    yield ev

        tp = CrashingProvider(tools=[
            Tool(name="add", description="", handler=lambda a, b: a + b),
        ])
        calls = [
            {"index": 0, "id": "c1", "type": "function",
             "function": {"name": "boom", "arguments": "{}"}},
            {"index": 1, "id": "c2", "type": "function",
             "function": {"name": "add", "arguments": '{"a":1,"b":2}'}},
        ]
        turn = [
            StreamChunk(role="assistant", id="chatcmpl-x"),
            StreamChunk(tool_calls=calls, id="chatcmpl-x"),
            StreamChunk(finish_reason="tool_calls", id="chatcmpl-x"),
        ]
        llm = FakeLLM([turn, text_turn("ok")])
        agent = Agent(llm, tp, parallel_tools=True)
        events = run(collect(agent.run(USER)))
        errs = [e for e in events
                if e.get("type") == "tool_result" and e["kind"] == "error"]
        assert errs and "provider exploded" in errs[0]["data"]
        second = llm.seen_messages[1]
        tool_msgs = {m["tool_call_id"]: m["content"]
                     for m in second if m["role"] == "tool"}
        assert "provider exploded" in tool_msgs["c1"]
        assert tool_msgs["c2"] == "3"

    def test_tool_error_surfaces_to_model(self):
        llm = FakeLLM([
            tool_turn("fail", {}),
            text_turn("I saw the error"),
        ])
        agent = Agent(llm, make_tools())
        events = run(collect(agent.run(USER)))
        errs = [
            e for e in events
            if e.get("type") == "tool_result" and e["kind"] == "error"
        ]
        assert errs and "deliberate failure" in errs[0]["data"]
        # error became the tool message content
        assert "Error:" in llm.seen_messages[1][-1]["content"]
        assert events[-1]["reason"] == "text_response"

    def test_unknown_tool_survives(self):
        llm = FakeLLM([
            tool_turn("no_such_tool", {}),
            text_turn("recovered"),
        ])
        agent = Agent(llm, make_tools())
        events = run(collect(agent.run(USER)))
        assert events[-1]["reason"] == "text_response"
        assert "unknown tool" in llm.seen_messages[1][-1]["content"]

    def test_parallel_tools_preserve_message_order(self):
        calls = [
            {"index": 0, "id": "cA", "type": "function",
             "function": {"name": "counter", "arguments": '{"n": 2}'}},
            {"index": 1, "id": "cB", "type": "function",
             "function": {"name": "add", "arguments": '{"a":1,"b":1}'}},
        ]
        turn = [
            StreamChunk(role="assistant", id="chatcmpl-p"),
            StreamChunk(tool_calls=calls, id="chatcmpl-p"),
            StreamChunk(finish_reason="tool_calls", id="chatcmpl-p"),
        ]
        llm = FakeLLM([turn, text_turn("done")])
        agent = Agent(llm, make_tools(), parallel_tools=True)
        events = run(collect(agent.run(USER)))
        assert events[-1]["reason"] == "text_response"
        # tool messages fed back in call order regardless of finish order
        second = llm.seen_messages[1]
        tool_msgs = [m for m in second if m["role"] == "tool"]
        assert [m["tool_call_id"] for m in tool_msgs] == ["cA", "cB"]


class TestCompactionRetry:
    def test_context_error_triggers_compaction_once(self):
        llm = FakeLLM([text_turn("after compaction")], context_errors=1)
        comp = FakeCompaction()
        agent = Agent(llm, make_tools(), context_compaction_provider=comp)
        msgs = [{"role": "user", "content": f"m{i}"} for i in range(6)]
        events = run(collect(agent.run(msgs)))
        assert comp.calls == 1
        assert events[-1]["reason"] == "text_response"

    def test_second_context_error_raises(self):
        llm = FakeLLM([], context_errors=2)
        comp = FakeCompaction()
        agent = Agent(llm, make_tools(), context_compaction_provider=comp)
        with pytest.raises(ContextLengthError):
            run(collect(agent.run(USER)))
        assert comp.calls == 1

    def test_no_compaction_provider_raises_immediately(self):
        llm = FakeLLM([], context_errors=1)
        agent = Agent(llm, make_tools())
        with pytest.raises(ContextLengthError):
            run(collect(agent.run(USER)))


class TestToolProvider:
    def test_get_tools_openai_format(self):
        tp = make_tools()
        defs = tp.get_tools()
        assert all(d["type"] == "function" for d in defs)
        names = {d["function"]["name"] for d in defs}
        assert names == {"add", "fail", "counter"}

    def test_idle_injected_into_defs(self):
        llm = FakeLLM([text_turn("x")])
        agent = Agent(llm, make_tools())
        run(collect(agent.run(USER)))
        # FakeLLM doesn't see tools (kw only) — check the def builder
        names = {d["function"]["name"] for d in agent._tool_defs()}
        assert IDLE_TOOL_NAME in names

    def test_run_tool_nonstreaming(self):
        tp = make_tools()
        assert run(tp.run_tool("add", '{"a": 3, "b": 4}')) == 7

    def test_malformed_arguments_reach_tool_as_raw(self):
        def echo(**kw):
            return kw

        tp = AgentToolProvider(tools=[Tool(name="echo", description="",
                                           handler=echo)])
        out = run(tp.run_tool("echo", "not json {"))
        assert out == {"_raw": "not json {"}
