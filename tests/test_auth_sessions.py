"""Playground session auth (VERDICT r4 next #8).

Reference: the playground authenticated users via Supabase email sessions
and listed only the session user's threads
(playground/src/components/auth-provider.tsx:19-40, sidebar.tsx:40-80).
Here: /v1/auth/signup + /v1/auth/login against the DB tier's user store
(scrypt passwords, urlsafe session tokens), session bearers resolving to
request user, thread ownership binding on touch, and per-user listing.
"""

import asyncio

import pytest

from aiohttp.test_utils import TestClient, TestServer

from kafka_tpu.db.local import LocalDBClient
from kafka_tpu.server import ServingConfig, create_app
from kafka_tpu.server.auth import hash_password, verify_password, new_salt


@pytest.fixture()
def app_client(tmp_path):
    """Server over a tiny model + local DB; yields an async-callable."""

    async def make():
        cfg = ServingConfig(
            tiny_model=True, db_path=str(tmp_path / "auth.db"),
            max_batch=2, page_size=16, num_pages=160,
            max_pages_per_seq=64, prefill_buckets=(256,),
            max_new_tokens_default=4, warmup=False,
            system_prompt="test",
        )
        app = await create_app(cfg=cfg, tools=[], mcp_servers=[])
        client = TestClient(TestServer(app))
        await client.start_server()
        return client

    return make


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


class TestPasswordPrimitives:
    def test_hash_verify_roundtrip(self):
        salt = new_salt()
        h = hash_password("hunter42", salt)
        assert verify_password("hunter42", salt, h)
        assert not verify_password("hunter43", salt, h)
        # per-user salts: same password, different hash
        assert hash_password("hunter42", new_salt()) != h


class TestSessionsOverHTTP:
    def test_signup_login_and_scoped_threads(self, app_client):
        async def go():
            client = await app_client()
            try:
                # -- signup opens a session
                r = await client.post("/v1/auth/signup", json={
                    "email": "ada@example.com", "password": "lovelace"})
                assert r.status == 200, await r.text()
                sess_a = await r.json()
                assert sess_a["token"].startswith("sess_")
                ha = {"Authorization": f"Bearer {sess_a['token']}"}

                # -- duplicate email -> 409
                r = await client.post("/v1/auth/signup", json={
                    "email": "ada@example.com", "password": "xxxxxx"})
                assert r.status == 409

                # -- wrong password -> 401
                r = await client.post("/v1/auth/login", json={
                    "email": "ada@example.com", "password": "wrong!"})
                assert r.status == 401

                # -- login works and issues a fresh token
                r = await client.post("/v1/auth/login", json={
                    "email": "ada@example.com", "password": "lovelace"})
                assert r.status == 200
                assert (await r.json())["token"] != sess_a["token"]

                # -- a second user
                r = await client.post("/v1/auth/signup", json={
                    "email": "bob@example.com", "password": "builder"})
                hb = {"Authorization":
                      f"Bearer {(await r.json())['token']}"}

                # -- ada creates a thread (bound to her session)
                r = await client.post("/v1/threads",
                                      json={"thread_id": "t-ada"},
                                      headers=ha)
                assert r.status == 201

                # -- listings are scoped per user
                r = await client.get("/v1/threads", headers=ha)
                tids = [t["thread_id"] for t in (await r.json())["threads"]]
                assert tids == ["t-ada"]
                r = await client.get("/v1/threads", headers=hb)
                assert (await r.json())["threads"] == []

                # -- bob cannot see or delete ada's thread (404, unleaked)
                r = await client.get("/v1/threads/t-ada", headers=hb)
                assert r.status == 404
                r = await client.delete("/v1/threads/t-ada", headers=hb)
                assert r.status == 404
                r = await client.get("/v1/threads/t-ada", headers=ha)
                assert r.status == 200

                # -- invalid session token 401s even on an open server
                r = await client.get("/v1/threads", headers={
                    "Authorization": "Bearer sess_bogus"})
                assert r.status == 401

                # -- anonymous requests see only unowned threads
                r = await client.get("/v1/threads")
                assert (await r.json())["threads"] == []
            finally:
                await client.close()

        run(go())

    def test_read_does_not_claim_anonymous_thread(self, app_client):
        """A GET by a logged-in user must not transfer ownership of an
        anonymous client's thread (claiming is write-path only)."""
        async def go():
            client = await app_client()
            try:
                r = await client.post("/v1/threads",
                                      json={"thread_id": "t-anon"})
                assert r.status == 201
                r = await client.post("/v1/auth/signup", json={
                    "email": "spy@example.com", "password": "looking"})
                h = {"Authorization": f"Bearer {(await r.json())['token']}"}
                r = await client.get("/v1/threads/t-anon", headers=h)
                assert r.status == 200  # unowned: visible
                # still unowned — the anonymous creator keeps access
                r = await client.get("/v1/threads")
                assert "t-anon" in [
                    t["thread_id"] for t in (await r.json())["threads"]]
            finally:
                await client.close()

        run(go())

    def test_signup_gated_by_api_token_on_closed_instance(self, tmp_path):
        """With a static api_token configured, open signup would mint
        sessions that bypass it — signup requires the token (invite
        model); login stays open."""
        from kafka_tpu.server import create_app as mk

        async def go():
            cfg = ServingConfig(
                tiny_model=True, db_path=str(tmp_path / "closed.db"),
                max_batch=2, page_size=16, num_pages=160,
                max_pages_per_seq=64, prefill_buckets=(256,),
                warmup=False, api_token="machine-secret",
            )
            app = await mk(cfg=cfg, tools=[], mcp_servers=[])
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.post("/v1/auth/signup", json={
                    "email": "a@b.c", "password": "longenough"})
                assert r.status == 401  # no api_token presented
                r = await client.post(
                    "/v1/auth/signup",
                    json={"email": "a@b.c", "password": "longenough"},
                    headers={"Authorization": "Bearer machine-secret"})
                assert r.status == 200
                token = (await r.json())["token"]
                # the session satisfies the gate (it was minted under it)
                r = await client.get(
                    "/v1/threads",
                    headers={"Authorization": f"Bearer {token}"})
                assert r.status == 200
                # login itself stays open (password is the credential)
                r = await client.post("/v1/auth/login", json={
                    "email": "a@b.c", "password": "longenough"})
                assert r.status == 200
            finally:
                await client.close()

        run(go())

    def test_chat_binds_thread_to_session_user(self, app_client):
        async def go():
            client = await app_client()
            try:
                r = await client.post("/v1/auth/signup", json={
                    "email": "eve@example.com", "password": "streams"})
                h = {"Authorization": f"Bearer {(await r.json())['token']}"}
                r = await client.post(
                    "/v1/threads/t-chat/chat/completions",
                    json={"model": "tiny", "max_tokens": 3,
                          "messages": [{"role": "user", "content": "hi"}]},
                    headers=h,
                )
                assert r.status == 200, await r.text()
                await r.json()
                r = await client.get("/v1/threads", headers=h)
                tids = [t["thread_id"] for t in (await r.json())["threads"]]
                assert "t-chat" in tids
                # anonymous listing does not include it
                r = await client.get("/v1/threads")
                assert "t-chat" not in [
                    t["thread_id"] for t in (await r.json())["threads"]]
            finally:
                await client.close()

        run(go())


class TestDBUserStore:
    def test_local_sessions_expire(self, tmp_path):
        async def go():
            db = LocalDBClient(str(tmp_path / "u.db"))
            await db.initialize()
            uid = await db.create_user("x@y.z", "h", "s")
            await db.create_session(uid, "sess_live", 2e12)
            await db.create_session(uid, "sess_dead", 1.0)
            assert await db.get_session_user("sess_live") == uid
            assert await db.get_session_user("sess_dead") is None
            assert await db.get_session_user("sess_missing") is None
            await db.close()

        run(go())

    def test_migration_adds_user_id_to_existing_db(self, tmp_path):
        import sqlite3

        path = str(tmp_path / "old.db")
        conn = sqlite3.connect(path)
        conn.execute(
            "CREATE TABLE threads (thread_id TEXT PRIMARY KEY, "
            "created_at REAL NOT NULL, updated_at REAL NOT NULL, "
            "metadata TEXT NOT NULL DEFAULT '{}', sandbox_id TEXT, "
            "config TEXT)"
        )
        conn.execute(
            "INSERT INTO threads VALUES ('t0', 1.0, 1.0, '{}', NULL, NULL)"
        )
        conn.commit()
        conn.close()

        async def go():
            db = LocalDBClient(path)
            await db.initialize()
            assert await db.get_thread_owner("t0") is None
            await db.set_thread_owner("t0", "u1")
            assert await db.get_thread_owner("t0") == "u1"
            await db.close()

        run(go())
