"""MCP client tests: stdio round-trip against a scripted server, graceful
connect failure, streamable-HTTP against an in-process server, and content
flattening. Behavior parity: reference src/tools/agent.py:63-380."""

import asyncio
import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import pytest

from kafka_tpu.tools.mcp import (
    MCPClientError,
    MCPConnection,
    _flatten_content,
    _iter_sse_datas,
)
from kafka_tpu.tools.provider import AgentToolProvider
from kafka_tpu.tools.types import MCPServerConfig

STUB = str(Path(__file__).parent / "mcp_stub_server.py")


def run(coro):
    return asyncio.run(coro)


def stdio_config(name="stub"):
    return MCPServerConfig(name=name, command=sys.executable, args=[STUB])


# ---------------------------------------------------------------------------
# stdio transport
# ---------------------------------------------------------------------------


def test_stdio_connect_and_discover():
    async def impl():
        conn = MCPConnection(stdio_config(), timeout=10.0)
        await conn.connect()
        try:
            assert conn.connected
            assert conn.server_info["name"] == "stub"
            tools = conn.discovered_tools()
            assert {t.name for t in tools} == {"echo", "progress_echo",
                                               "fail"}
            echo = next(t for t in tools if t.name == "echo")
            oai = echo.to_openai()
            assert oai["function"]["parameters"]["required"] == ["text"]
            assert echo.source == "mcp"
        finally:
            await conn.disconnect()

    run(impl())


def test_stdio_tool_call_roundtrip():
    async def impl():
        conn = MCPConnection(stdio_config(), timeout=10.0)
        await conn.connect()
        try:
            assert await conn.call_tool("echo", {"text": "hi"}) == "echo: hi"
        finally:
            await conn.disconnect()

    run(impl())


def test_stdio_progress_streams_as_log_events():
    async def impl():
        conn = MCPConnection(stdio_config(), timeout=10.0)
        await conn.connect()
        try:
            events = []
            async for ev in conn.call_tool_stream("progress_echo",
                                                  {"text": "x"}):
                events.append(ev)
            assert events[-1].kind == "result"
            assert events[-1].data == "echo: x"
            logs = [e.data for e in events if e.kind == "log"]
            assert "step 1" in logs and "step 2" in logs
        finally:
            await conn.disconnect()

    run(impl())


def test_stdio_tool_error_is_error_event():
    async def impl():
        conn = MCPConnection(stdio_config(), timeout=10.0)
        await conn.connect()
        try:
            events = [ev async for ev in conn.call_tool_stream("fail", {})]
            assert events[-1].kind == "error"
            assert "it broke" in events[-1].data
        finally:
            await conn.disconnect()

    run(impl())


def test_stdio_unknown_tool_jsonrpc_error():
    async def impl():
        conn = MCPConnection(stdio_config(), timeout=10.0)
        await conn.connect()
        try:
            events = [ev async for ev in conn.call_tool_stream("nope", {})]
            assert events[-1].kind == "error"
            assert "unknown tool" in events[-1].data
        finally:
            await conn.disconnect()

    run(impl())


def test_spawn_failure_raises_mcp_error():
    async def impl():
        cfg = MCPServerConfig(name="bad", command="/nonexistent-binary-xyz")
        conn = MCPConnection(cfg, timeout=5.0)
        with pytest.raises(MCPClientError):
            await conn.connect()

    run(impl())


# ---------------------------------------------------------------------------
# provider integration: failures warn-and-skip, successes register tools
# ---------------------------------------------------------------------------


def test_provider_skips_unreachable_server():
    async def impl():
        provider = AgentToolProvider(mcp_servers=[
            MCPServerConfig(name="dead", url="http://127.0.0.1:1",
                            transport="streamable-http"),
        ])
        # must not raise (reference src/tools/agent.py:494-496)
        await provider.connect()
        assert provider.get_tools() == []
        await provider.disconnect()

    run(impl())


def test_provider_registers_and_runs_mcp_tools():
    async def impl():
        provider = AgentToolProvider(mcp_servers=[stdio_config()])
        await provider.connect()
        try:
            names = {t["function"]["name"] for t in provider.get_tools()}
            assert "echo" in names
            events = []
            async for ev in provider.run_tool_stream(
                "echo", {"text": "yo"}, tool_call_id="call_1"
            ):
                events.append(ev)
            assert events[-1].kind == "result"
            assert events[-1].data == "echo: yo"
            assert events[-1].tool_call_id == "call_1"
        finally:
            await provider.disconnect()

    run(impl())


# ---------------------------------------------------------------------------
# streamable-HTTP transport against an in-process server
# ---------------------------------------------------------------------------


class _HTTPStub(BaseHTTPRequestHandler):
    def do_POST(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        msg = json.loads(body)
        method = msg.get("method")
        msg_id = msg.get("id")
        if msg_id is None:  # notification
            self.send_response(202)
            self.end_headers()
            return
        if method == "initialize":
            result = {
                "protocolVersion": msg["params"]["protocolVersion"],
                "capabilities": {"tools": {}},
                "serverInfo": {"name": "httpstub", "version": "1"},
            }
        elif method == "tools/list":
            result = {"tools": [{
                "name": "ping", "description": "",
                "inputSchema": {"type": "object", "properties": {}},
            }]}
        elif method == "tools/call":
            # reply as an SSE body to exercise the event-stream parse path
            payload = json.dumps({
                "jsonrpc": "2.0", "id": msg_id,
                "result": {"content": [{"type": "text", "text": "pong"}]},
            })
            data = f"event: message\ndata: {payload}\n\n".encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        else:
            result = {}
        data = json.dumps(
            {"jsonrpc": "2.0", "id": msg_id, "result": result}
        ).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Mcp-Session-Id", "sess-1")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *args):
        pass


@pytest.fixture
def http_stub():
    server = ThreadingHTTPServer(("127.0.0.1", 0), _HTTPStub)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_address[1]}/mcp"
    server.shutdown()
    thread.join(timeout=5)


def test_streamable_http_roundtrip(http_stub):
    async def impl():
        conn = MCPConnection(
            MCPServerConfig(name="h", url=http_stub,
                            transport="streamable-http"),
            timeout=10.0,
        )
        await conn.connect()
        try:
            assert conn.server_info["name"] == "httpstub"
            assert conn._transport._session_id == "sess-1"
            assert {t.name for t in conn.discovered_tools()} == {"ping"}
            assert await conn.call_tool("ping", {}) == "pong"
        finally:
            await conn.disconnect()

    run(impl())


# ---------------------------------------------------------------------------
# pure helpers
# ---------------------------------------------------------------------------


def test_flatten_content_blocks():
    assert _flatten_content({"content": [
        {"type": "text", "text": "a"},
        {"type": "text", "text": "b"},
        {"type": "resource", "resource": {"uri": "file:///x"}},
    ]}) == "a\nb\nfile:///x"
    assert _flatten_content({"structuredContent": {"k": 1}}) == '{"k": 1}'
    assert _flatten_content(None) == ""


def test_iter_sse_datas():
    body = ("event: message\ndata: {\"a\": 1}\n\n"
            "data: line1\ndata: line2\n\n")
    assert list(_iter_sse_datas(body)) == ['{"a": 1}', "line1\nline2"]


def test_default_mcp_servers_env(monkeypatch):
    from kafka_tpu.server_tools.mcp_servers import default_mcp_servers

    monkeypatch.setenv("KAFKA_TPU_MCP_SERVERS", json.dumps([
        {"name": "x", "url": "http://localhost:9"},
        {"bogus_field": 1},
    ]))
    servers = default_mcp_servers()
    assert len(servers) == 1 and servers[0].name == "x"

    monkeypatch.setenv("KAFKA_TPU_MCP_SERVERS", "[]")
    assert default_mcp_servers() == []

    monkeypatch.delenv("KAFKA_TPU_MCP_SERVERS")
    defaults = default_mcp_servers()
    assert len(defaults) == 1 and defaults[0].name == "fetch"
