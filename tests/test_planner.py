"""Memory-fit planner (runtime/planner.py): the feasibility artifact for
BASELINE topologies this single-chip environment cannot execute.

Ground truths pinned here were OBSERVED on real hardware in round 4:
llama-3-8b bf16 does not fit one v5e chip (the server OOMed; COVERAGE.md),
llama-3-8b int8 does (served at ~540 tok/s).  The unreachable-topology
numbers (v5e-8, v5p-64) are pure arithmetic over the same placement rules
parallel/sharding.py applies, so the planner's credibility rests on the
observed cases matching.
"""

import math

from kafka_tpu.models.config import get_config
from kafka_tpu.runtime.planner import (
    GiB,
    HBM_BYTES,
    kv_bytes_per_token,
    plan_memory,
    plan_for_serving,
    weight_bytes_per_device,
)
from kafka_tpu.server.config import ServingConfig


class TestWeightArithmetic:
    def test_8b_bf16_weights_match_param_count(self):
        # 8.03B params * 2 bytes, +- 1% (norms/rounding)
        cfg = get_config("llama-3-8b")
        wb = weight_bytes_per_device(cfg)
        assert math.isclose(wb, 8.03e9 * 2, rel_tol=0.01)

    def test_int8_halves_weight_bytes(self):
        cfg = get_config("llama-3-8b")
        bf16 = weight_bytes_per_device(cfg)
        int8 = weight_bytes_per_device(cfg, quantize="int8")
        assert 0.50 < int8 / bf16 < 0.53  # 1B/param + f32 scales

    def test_tp_shards_everything_but_embed(self):
        cfg = get_config("llama-3-8b")
        full = weight_bytes_per_device(cfg)
        tp8 = weight_bytes_per_device(cfg, tp=8)
        embed = cfg.vocab_size * cfg.hidden_size * 2  # replicated
        # sharded part must divide by ~8
        assert math.isclose(tp8 - embed, (full - embed) / 8, rel_tol=0.01)

    def test_grouped_kv_shard_when_tp_exceeds_kv_heads(self):
        # 70B: 8 kv heads, degree 16 -> grouped layout (tp=8 x tq=2):
        # per-chip KV is 1/8 of the pool, NOT a full copy
        # (parallel/mesh.py factor_tp_for_kv)
        cfg = get_config("llama-3-70b")
        full = kv_bytes_per_token(cfg, tp=1)
        assert kv_bytes_per_token(cfg, tp=16) == full // 8
        assert kv_bytes_per_token(cfg, tp=8) == full // 8
        # a degree sharing no factor with Hkv degrades to full replication
        assert kv_bytes_per_token(cfg, tp=3) == full

    def test_moe_experts_shard_over_ep_and_tp(self):
        cfg = get_config("mixtral-8x7b")
        full = weight_bytes_per_device(cfg)
        ep8 = weight_bytes_per_device(cfg, ep=8)
        # experts are ~96% of Mixtral's params; ep8 keeps 1/8 of them
        assert ep8 < 0.2 * full
        assert weight_bytes_per_device(cfg, ep=8, tp=4) < ep8


class TestObservedGroundTruths:
    """Cases executed on the real chip in round 4 — the planner must agree."""

    def test_8b_bf16_does_not_fit_one_v5e(self):
        plan = plan_memory(
            get_config("llama-3-8b"), num_pages=512, page_size=16,
            max_pages_per_seq=128, max_batch=8,
        )
        assert not plan.fits
        assert plan.weight_bytes > 14 * GiB  # weights alone ~15 GiB

    def test_8b_int8_fits_one_v5e(self):
        plan = plan_memory(
            get_config("llama-3-8b"), num_pages=512, page_size=16,
            max_pages_per_seq=128, max_batch=8, quantize="int8",
        )
        assert plan.fits
        assert plan.headroom_bytes > 4 * GiB

    def test_1b_bf16_fits_with_room(self):
        plan = plan_memory(
            get_config("llama-3.2-1b"), num_pages=2048, page_size=16,
            max_pages_per_seq=512, max_batch=8,
        )
        assert plan.fits and plan.headroom_bytes > 8 * GiB


class TestBaselineTopologies:
    """BASELINE configs 3 and 5: the feasibility numbers for topologies
    this environment cannot reach (VERDICT r4 weak #6)."""

    def test_config3_8b_tp8_v5e8_holds_256_threads_at_2k(self):
        # 256 concurrent threads, 2048-token windows, 8B bf16 over tp=8
        plan = plan_memory(
            get_config("llama-3-8b"), tp=8, num_pages=256 * 128 + 1,
            page_size=16, max_pages_per_seq=128, max_batch=64,
            prefill_bucket=2048,
        )
        assert plan.fits
        assert plan.max_concurrent_windows >= 256

    def test_config5_70b_tp16_sp4_v5p64_fits(self):
        scfg = ServingConfig.profile_32k()
        plan = plan_for_serving(scfg, chip="v5p")
        assert plan.fits
        # degree 16 over 8 kv heads -> grouped layout (tp=8 x tq=2): the
        # pool shards 8-ways, each head on 2 chips — partially replicated
        assert plan.kv_replicated
        assert "tp=8 x tq=2" in plan.notes
        # grouped sharding holds 61 concurrent full 32k windows in leftover
        # HBM (the fully-replicated fallback held 7)
        assert plan.max_concurrent_windows >= 61
        # per-device weights ~10.2 GiB: 140 GB of bf16 across tp=16 with
        # replicated embed; kv projections now 8-way sharded
        assert 9 * GiB < plan.weight_bytes < 12 * GiB

    def test_ulysses_config_charges_full_replication(self):
        """cp_strategy='ulysses' keeps the plain tensor axis (the engine
        rejects tq>1 with the all_to_all head scatter), so the plan must
        charge FULL kv replication — not the grouped layout the server
        would build for ring CP.  Plan and placement resolve through the
        same resolve_tensor_axes call (parallel/mesh.py)."""
        import dataclasses

        ring = ServingConfig.profile_32k()
        uly = dataclasses.replace(ring, cp_strategy="ulysses")
        ring_plan = plan_for_serving(ring, chip="v5p")
        uly_plan = plan_for_serving(uly, chip="v5p")
        # 70B, 8 kv heads, degree 16: grouped shards kv 8-ways
        assert uly_plan.kv_bytes_per_token == 8 * ring_plan.kv_bytes_per_token
        assert "plain tensor axis" in uly_plan.notes

    def test_config5_would_not_fit_on_v5e(self):
        scfg = ServingConfig.profile_32k()
        assert not plan_for_serving(scfg, chip="v5e").fits

    def test_int8_kv_doubles_32k_capacity(self):
        cfg = get_config("llama-3-70b")
        kw = dict(tp=16, sp=4, num_pages=8193, page_size=16,
                  max_pages_per_seq=2048, max_batch=4, prefill_bucket=4096,
                  chip="v5p")
        bf16 = plan_memory(cfg, **kw)
        int8 = plan_memory(cfg, kv_dtype="int8", **kw)
        assert int8.max_concurrent_windows >= 2 * bf16.max_concurrent_windows


class TestServingIntegration:
    def test_plan_for_serving_default_config(self):
        plan = plan_for_serving(ServingConfig())
        assert plan.fits
        assert plan.model == "llama-3.2-1b"

    def test_health_reports_plan(self):
        # summary() is JSON-serializable (health endpoint payload)
        import json

        s = plan_for_serving(ServingConfig()).summary()
        json.dumps(s)
        assert {"fits", "weight_gib", "max_concurrent_windows"} <= set(s)


class TestMachineReadableFactorization:
    """MemoryPlan.kv_shard/tq: the grouped tp×tq layout as fields, not
    free-text notes (ADVICE r5).  Invariant: tp == kv_shard * tq."""

    def test_grouped_layout_fields(self):
        scfg = ServingConfig.profile_32k()  # degree 16 over 8 kv heads
        plan = plan_for_serving(scfg, chip="v5p")
        assert plan.kv_shard == 8 and plan.tq == 2
        assert plan.mesh["tp"] * 1 == plan.kv_shard * plan.tq * 1
        assert plan.summary()["kv_shard"] == 8
        assert plan.summary()["tq"] == 2

    def test_full_replication_reports_tq_equal_tp(self):
        # a degree sharing no factor with Hkv: kv fully replicated, so
        # tq must equal the whole degree (tp = kv_shard * tq holds)
        plan = plan_memory(
            get_config("llama-3-70b"), tp=3, num_pages=64, page_size=16,
            max_pages_per_seq=16, max_batch=4,
        )
        assert plan.kv_shard == 1 and plan.tq == 3

    def test_unsharded_plan_is_identity(self):
        plan = plan_memory(
            get_config("llama-3.2-1b"), num_pages=64, page_size=16,
            max_pages_per_seq=16, max_batch=4,
        )
        assert plan.kv_shard == 1 and plan.tq == 1
