"""Object-store KV tier + portable thread state (ISSUE 14).

The load-bearing claims:
  * run payloads round-trip byte-exact through the store (f32 + bf16 +
    multi-run paths),
  * content addressing dedupes identical prefixes across TWO tier
    managers sharing one store directory (one object, a dedupe counter
    increment, per-owner refcounting with last-ref deletion),
  * a thread drained to the store by replica A wakes on replica B — a
    FRESH engine that never served it — with cache_source="object_tier",
    token-exact output vs a never-slept reference, and 0 coverable
    prompt tokens re-prefilled,
  * randomized sleep/wake chaos keeps PagePool.check_consistency +
    reconcile clean after every op and every woken page byte-exact,
  * a torn manifest write leaves the previous manifest intact (atomic
    rename), a get miss aborts the WHOLE wake with all its pages freed
    (kv.object_get failpoint), a torn put degrades the archive
    (kv.object_put failpoint) — serving continues via re-prefill,
  * OBJECT_TIER_METRIC_KEYS is a both-directions registry across
    runtime/metrics.py and server/prometheus.py; SITES/SPANS carry the
    new failpoints/spans,
  * with KAFKA_TPU_KV_OBJECT_DIR unset nothing is built and every
    dispatch/eviction path is byte-identical.
"""

import os
import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.models import ModelConfig, init_params
from kafka_tpu.runtime import (
    EngineConfig,
    GenRequest,
    InferenceEngine,
    PagePool,
)
from kafka_tpu.runtime import failpoints, tracing
from kafka_tpu.runtime.kv_tier import KVTierManager, LocalPageShipper
from kafka_tpu.runtime.object_tier import (
    LocalFSObjectStore,
    ObjectTier,
    _decode_run,
    _encode_run,
)
from kafka_tpu.runtime.prefix_cache import PrefixCache


class _Owner:
    """Minimal pool-array holder standing in for the engine (the shipper
    only needs mutable k_pool/v_pool)."""

    def __init__(self, num_pages, page_size, layers=2, width=8, seed=0,
                 dtype=np.float32):
        rng = np.random.default_rng(seed)
        shape = (layers, num_pages * page_size, width)
        self.k_pool = jnp.asarray(
            rng.normal(size=shape).astype(np.float32)
        ).astype(dtype)
        self.v_pool = jnp.asarray(
            rng.normal(size=shape).astype(np.float32)
        ).astype(dtype)


def _rows(owner, pages, page_size, pool="k"):
    arr = np.asarray(owner.k_pool if pool == "k" else owner.v_pool)
    return np.concatenate(
        [arr[:, p * page_size:(p + 1) * page_size] for p in pages], axis=1
    )


def _write_rows(owner, pages, page_size, k_rows, v_rows):
    for i, p in enumerate(pages):
        sl = slice(p * page_size, (p + 1) * page_size)
        src = slice(i * page_size, (i + 1) * page_size)
        owner.k_pool = owner.k_pool.at[:, sl].set(k_rows[:, src])
        owner.v_pool = owner.v_pool.at[:, sl].set(v_rows[:, src])


class TestObjectStore:
    def test_put_get_head_delete_list(self, tmp_path):
        st = LocalFSObjectStore(str(tmp_path))
        assert st.get("objects/x.npz") is None
        assert st.head("objects/x.npz") is None
        st.put("objects/x.npz", b"abc")
        assert st.get("objects/x.npz") == b"abc"
        assert st.head("objects/x.npz")[0] == 3
        st.put("refs/x/a", b"")
        st.put("refs/x/b", b"")
        assert sorted(st.list("refs/x/")) == ["refs/x/a", "refs/x/b"]
        st.delete("refs/x/a")
        assert st.list("refs/x/") == ["refs/x/b"]
        st.delete("objects/x.npz")
        assert st.get("objects/x.npz") is None
        st.delete("objects/x.npz")  # idempotent
        # no tmp litter: every put cleaned its staging file
        assert os.listdir(tmp_path / ".tmp") == []

    def test_traversal_keys_stay_inside_root(self, tmp_path):
        st = LocalFSObjectStore(str(tmp_path))
        st.put("objects/../escape", b"x")
        # ".." segments are dropped: the write lands INSIDE the root
        assert not (tmp_path.parent / "escape").exists()
        assert st.get("objects/../escape") == b"x"

    def test_usage_counts_objects(self, tmp_path):
        st = LocalFSObjectStore(str(tmp_path))
        st.put("objects/a.npz", b"1234")
        st.put("objects/b.npz", b"12")
        st._usage_cache = (0.0, (0, 0))  # bust the TTL cache
        count, total = st.usage()
        assert count == 2 and total == 6


class TestRunPayloads:
    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    def test_round_trip_byte_exact(self, dtype):
        if dtype == "bfloat16":
            import ml_dtypes

            npdt = ml_dtypes.bfloat16
        else:
            npdt = np.float32
        rng = np.random.default_rng(3)
        k = [rng.normal(size=(2, 12, 4)).astype(npdt),
             rng.normal(size=(2, 12, 2)).astype(npdt)]
        v = [rng.normal(size=(2, 12, 4)).astype(npdt),
             rng.normal(size=(2, 12, 2)).astype(npdt)]
        data = _encode_run(k, v, 3)
        k2, v2, n = _decode_run(data)
        assert n == 3
        for a, b in zip(k + v, k2 + v2):
            assert a.dtype == b.dtype
            assert np.array_equal(a.view(np.uint8), b.view(np.uint8))

    def test_put_get_run_and_spans(self, tmp_path):
        obj = ObjectTier(LocalFSObjectStore(str(tmp_path)),
                         fingerprint="f1", page_size=4)
        rng = np.random.default_rng(5)
        k = [rng.normal(size=(2, 8, 4)).astype(np.float32)]
        v = [rng.normal(size=(2, 8, 4)).astype(np.float32)]
        key = obj.put_run([1, 2, 3, 4, 5, 6, 7, 8], k, v, 2)
        assert key is not None
        got = obj.get_run(key)
        assert got is not None
        k2, v2, n, nbytes = got
        assert n == 2 and nbytes > 0
        assert np.array_equal(k[0], k2[0])
        assert np.array_equal(v[0], v2[0])
        assert obj.object_puts == 1 and obj.object_gets == 1

    def test_content_key_covers_prefix_and_fingerprint(self, tmp_path):
        obj = ObjectTier(LocalFSObjectStore(str(tmp_path)),
                         fingerprint="f1", page_size=4)
        other = ObjectTier(LocalFSObjectStore(str(tmp_path)),
                           fingerprint="f2", page_size=4)
        toks = list(range(8))  # 2 pages at page_size=4
        assert obj.run_key(toks, 2) == obj.run_key(toks, 2)
        assert obj.run_key(toks, 2) != obj.run_key(toks[:-1] + [99], 2)
        # same tokens, different pool geometry: different object space
        assert obj.run_key(toks, 2) != other.run_key(toks, 2)
        # same full path, different run span (a SPLIT's back half): a
        # collision here would let a 1-page node dedupe onto a 2-page
        # object and a later promote import the wrong half's KV
        assert obj.run_key(toks, 2) != obj.run_key(toks, 1)


class TestDedupeAndRefs:
    def _leaves(self, seed=7):
        rng = np.random.default_rng(seed)
        return ([rng.normal(size=(2, 8, 4)).astype(np.float32)],
                [rng.normal(size=(2, 8, 4)).astype(np.float32)])

    def test_two_owners_one_object(self, tmp_path):
        st_a = LocalFSObjectStore(str(tmp_path))
        st_b = LocalFSObjectStore(str(tmp_path))
        a = ObjectTier(st_a, fingerprint="f", page_size=4)
        b = ObjectTier(st_b, fingerprint="f", page_size=4)
        k, v = self._leaves()
        toks = list(range(8))
        key = a.put_run(toks, k, v, 2)
        assert key is not None and a.dedupe_hits == 0
        # owner B archives the IDENTICAL prefix: no payload moves
        key_b = b.put_run(toks, k, v, 2)
        assert key_b == key
        assert b.dedupe_hits == 1 and b.object_puts == 0
        st_a._usage_cache = (0.0, (0, 0))
        assert st_a.usage()[0] == 1  # ONE object fleet-wide
        assert len(st_a.list(f"refs/{key}/")) == 2
        # last-reference deletion: A's release keeps it, B's removes it
        a.release(key)
        assert st_a.head(f"objects/{key}.npz") is not None
        b.release(key)
        assert st_a.head(f"objects/{key}.npz") is None

    def test_budget_second_chance(self, tmp_path):
        obj = ObjectTier(LocalFSObjectStore(str(tmp_path)),
                         fingerprint="f", page_size=4)
        k, v = self._leaves()
        k1 = obj.put_run([1] * 8, k, v, 2)
        size = obj.owned_bytes
        obj.budget_bytes = 2 * size + size // 2  # fits two runs
        k2 = obj.put_run([2] * 8, k, v, 2)
        # touch k1 (ref bit) so the third put's eviction skips it once
        assert obj.get_run(k1) is not None
        k3 = obj.put_run([3] * 8, k, v, 2)
        assert obj.owned_bytes <= obj.budget_bytes
        assert obj.objects_released >= 1
        # k2 (unreferenced) was the victim; k1 survived its second chance
        assert obj.has_run(k1) and obj.has_run(k3)
        assert not obj.has_run(k2)


class TestManifests:
    def _put_path(self, obj, path_runs):
        rng = np.random.default_rng(1)
        acc = []
        for seg in path_runs:
            acc.extend(seg)
            n = len(seg) // obj.page_size
            k = [rng.normal(size=(1, len(seg), 2)).astype(np.float32)]
            v = [rng.normal(size=(1, len(seg), 2)).astype(np.float32)]
            assert obj.put_run(list(acc), k, v, n) is not None

    def test_write_read_match(self, tmp_path):
        obj = ObjectTier(LocalFSObjectStore(str(tmp_path)),
                         fingerprint="f", page_size=4)
        toks = list(range(12))
        runs = obj.manifest_runs([toks[:8], toks[8:]])
        assert obj.write_manifest("thread/1", toks, runs)
        man = obj.read_manifest("thread/1")
        assert man["tokens"] == toks and len(man["runs"]) == 2
        # runs not archived yet: the probe counts ONLY wakeable depth
        assert obj.manifest_match_tokens("thread/1", toks + [99]) == 0
        self._put_path(obj, [toks[:8], toks[8:]])
        obj._manifest_cache.clear()  # drop the memoized 0 depth
        # page-aligned match, >= 1 token always left to prefill
        assert obj.manifest_match_tokens("thread/1", toks + [99]) == 12
        assert obj.manifest_match_tokens("thread/1", toks) == 8
        assert obj.manifest_match_tokens("thread/1", [5] + toks) == 0
        assert obj.manifest_match_tokens("missing", toks) == 0

    def test_shallower_write_keeps_deeper_manifest(self, tmp_path):
        obj = ObjectTier(LocalFSObjectStore(str(tmp_path)),
                         fingerprint="f", page_size=4)
        toks = list(range(16))
        obj.write_manifest("t", toks, obj.manifest_runs([toks]))
        # an ancestor's organic archive writes a PREFIX of it: kept
        obj.write_manifest("t", toks[:8], obj.manifest_runs([toks[:8]]))
        assert obj.read_manifest("t")["tokens"] == toks
        # a DIVERGENT write replaces it (the thread's path changed)
        other = [99] * 8
        obj.write_manifest("t", other, obj.manifest_runs([other]))
        assert obj.read_manifest("t")["tokens"] == other

    def test_torn_manifest_write_keeps_previous(self, tmp_path):
        obj = ObjectTier(LocalFSObjectStore(str(tmp_path)),
                         fingerprint="f", page_size=4)
        v1 = list(range(8))
        assert obj.write_manifest("t", v1, obj.manifest_runs([v1]))
        v2 = [7] * 8
        with failpoints.armed("kv.object_put", "error", "torn"):
            assert not obj.write_manifest("t", v2, obj.manifest_runs([v2]))
        assert obj.object_put_failures == 1
        assert obj.read_manifest("t")["tokens"] == v1  # intact

    def test_fingerprint_mismatch_reads_none(self, tmp_path):
        a = ObjectTier(LocalFSObjectStore(str(tmp_path)),
                       fingerprint="fa", page_size=4)
        b = ObjectTier(LocalFSObjectStore(str(tmp_path)),
                       fingerprint="fb", page_size=4)
        toks = list(range(8))
        a.write_manifest("t", toks, a.manifest_runs([toks]))
        assert b.read_manifest("t") is None
        assert b.manifest_match_tokens("t", toks + [1]) == 0


class TestCacheSleepWake:
    """Stub-pool sleep/wake: two (pool, tier, cache) stacks — replica A
    and replica B — sharing one store directory."""

    def _stack(self, tmp_path, num_pages=32, ps=4, seed=11, name="r"):
        o = _Owner(num_pages, ps, seed=seed)
        pool = PagePool(num_pages=num_pages, page_size=ps)
        mgr = KVTierManager(LocalPageShipper(o, ps),
                            host_budget_bytes=1 << 30, page_size=ps)
        mgr.attach_object(ObjectTier(
            LocalFSObjectStore(str(tmp_path)), fingerprint="shared",
            page_size=ps,
        ))
        cache = PrefixCache(pool, tier=mgr)
        return o, pool, mgr, cache

    def _store(self, o, pool, cache, key, tokens, pattern_from=None):
        ps = pool.page_size
        n = len(tokens) // ps
        pages = pool.alloc(n)
        k = np.empty((2, n * ps, 8), np.float32)
        v = np.empty((2, n * ps, 8), np.float32)
        src = pattern_from if pattern_from is not None else tokens
        for i in range(n):
            k[:, i * ps:(i + 1) * ps] = float(src[i * ps]) + 0.25
            v[:, i * ps:(i + 1) * ps] = float(src[i * ps]) + 0.5
        _write_rows(o, pages, ps, k, v)
        cache.store(key, tokens, pages)
        pool.release(pages)

    def _verify_hit(self, o, ps, prompt, hit):
        for i, p in enumerate(hit.pages):
            tok = float(prompt[i * ps])
            k = np.asarray(o.k_pool)[:, p * ps:(p + 1) * ps]
            v = np.asarray(o.v_pool)[:, p * ps:(p + 1) * ps]
            assert np.all(k == tok + 0.25), f"K page {i} corrupt"
            assert np.all(v == tok + 0.5), f"V page {i} corrupt"

    def test_sleep_then_wake_on_second_stack(self, tmp_path):
        a_o, a_pool, a_mgr, a_cache = self._stack(tmp_path, seed=1)
        rng = random.Random(0)
        tokens = [rng.randrange(90) for _ in range(12)]
        self._store(a_o, a_pool, a_cache, "t1", tokens)
        stats = a_cache.sleep_to_object()
        assert stats["enabled"] and stats["runs_archived"] == 1
        assert stats["manifests"] == 1

        b_o, b_pool, b_mgr, b_cache = self._stack(tmp_path, seed=2)
        hit = b_cache.lookup("t1", tokens + [1])
        assert hit is not None
        assert hit.source == "object_tier"
        assert hit.tokens == 12 and hit.object_tokens == 12
        self._verify_hit(b_o, 4, tokens, hit)
        b_pool.release(hit.pages)
        assert b_mgr.object.wake_threads == 1
        assert b_mgr.object.wake_tokens == 12
        assert not b_pool.check_consistency()
        assert not b_pool.reconcile(b_cache.page_owners())
        # the woken run is ordinary content after the thread stores
        # through it: source flips back to "own"
        self._store(b_o, b_pool, b_cache, "t1", tokens + [1, 2, 3, 4][:4])
        hit2 = b_cache.lookup("t1", tokens + [1])
        assert hit2.source == "own"
        b_pool.release(hit2.pages)

    def test_sleep_dedupes_across_replicas(self, tmp_path):
        a = self._stack(tmp_path, seed=3)
        b = self._stack(tmp_path, seed=4)
        rng = random.Random(7)
        shared = [rng.randrange(90) for _ in range(8)]
        self._store(a[0], a[1], a[3], "ta", shared)
        self._store(b[0], b[1], b[3], "tb", shared)
        s1 = a[3].sleep_to_object()
        assert s1["runs_archived"] == 1 and s1["dedupe_hits"] == 0
        s2 = b[3].sleep_to_object()
        # identical prefix: ONE object, reference-only second archive
        assert s2["dedupe_hits"] == 1
        store = a[2].object.store
        store._usage_cache = (0.0, (0, 0))
        assert store.usage()[0] == 1

    def test_get_miss_aborts_wake_and_frees_everything(self, tmp_path):
        a = self._stack(tmp_path, seed=5)
        rng = random.Random(9)
        tokens = [rng.randrange(90) for _ in range(16)]
        self._store(a[0], a[1], a[3], "t", tokens)
        a[3].sleep_to_object()
        b_o, b_pool, b_mgr, b_cache = self._stack(tmp_path, seed=6)
        free0 = b_pool.free_pages
        with failpoints.armed("kv.object_get", "error", "lost"):
            hit = b_cache.lookup("t", tokens + [1])
        # whole wake aborted: no partial pages, no tree entries
        assert hit is None
        assert b_pool.free_pages == free0
        assert len(b_cache) == 0
        assert b_mgr.object.object_get_failures >= 1
        assert not b_pool.check_consistency()
        # store healthy again: the same lookup wakes
        hit = b_cache.lookup("t", tokens + [1])
        assert hit is not None and hit.source == "object_tier"
        b_pool.release(hit.pages)

    def test_delay_injection_slow_store_still_serves(self, tmp_path):
        """`delay` on both sites = a slow store link: everything still
        works, just slower (the chaos matrix's liveness leg)."""
        import time as _time

        a = self._stack(tmp_path, seed=31)
        rng = random.Random(41)
        tokens = [rng.randrange(90) for _ in range(8)]
        self._store(a[0], a[1], a[3], "t", tokens)
        with failpoints.armed("kv.object_put", "delay", "0.05"):
            t0 = _time.monotonic()
            stats = a[3].sleep_to_object()
            assert _time.monotonic() - t0 >= 0.05
        assert stats["runs_archived"] == 1
        b = self._stack(tmp_path, seed=32)
        with failpoints.armed("kv.object_get", "delay", "0.05"):
            t0 = _time.monotonic()
            hit = b[3].lookup("t", tokens + [1])
            assert _time.monotonic() - t0 >= 0.05
        assert hit is not None and hit.source == "object_tier"
        self._verify_hit(b[0], 4, tokens, hit)
        b[1].release(hit.pages)

    def test_torn_put_during_sleep_degrades(self, tmp_path):
        a = self._stack(tmp_path, seed=8)
        rng = random.Random(11)
        tokens = [rng.randrange(90) for _ in range(8)]
        self._store(a[0], a[1], a[3], "t", tokens)
        with failpoints.armed("kv.object_put", "error", "torn"):
            stats = a[3].sleep_to_object()
        assert stats["runs_failed"] == 1 and stats["runs_archived"] == 0
        assert a[2].object.object_put_failures >= 1
        # nothing landed: a fresh replica has nothing to wake
        b = self._stack(tmp_path, seed=9)
        assert b[3].lookup("t", tokens + [1]) is None
        # the local replica is untouched — its own hit still serves
        hit = a[3].lookup("t", tokens + [1])
        assert hit is not None
        a[1].release(hit.pages)

    def test_randomized_sleep_wake_chaos(self, tmp_path):
        """store/lookup/reclaim/invalidate/sleep/clear-then-wake
        interleavings on one stack sharing a store with periodic fresh
        stacks: allocator invariants hold after EVERY op and every hit's
        pages are byte-exact against the token-derived pattern."""
        ps = 4
        o, pool, mgr, cache = self._stack(tmp_path, num_pages=48, seed=21)
        rng = random.Random(4321)
        threads = {}
        live_holds = []

        def owners():
            own = dict(cache.page_owners())
            for pages in live_holds:
                for p in pages:
                    own[p] = own.get(p, 0) + 1
            return own

        for step in range(250):
            op = rng.randrange(8)
            if op <= 2 or not threads:
                if threads and rng.random() < 0.4:
                    base = list(rng.choice(list(threads.values())))
                    base = base[: ps * rng.randrange(
                        1, max(2, len(base) // ps + 1))]
                else:
                    base = []
                tail = rng.randrange(1, 4)
                tokens = base + [rng.randrange(90)
                                 for _ in range(tail * ps)]
                tokens = tokens[: (len(tokens) // ps) * ps]
                key = f"t{rng.randrange(6)}"
                if len(tokens) // ps > pool.free_pages:
                    cache.reclaim(len(tokens) // ps)
                if len(tokens) // ps <= pool.free_pages:
                    self._store(o, pool, cache, key, tokens)
                    threads[key] = tokens
            elif op == 3:
                key = rng.choice(list(threads))
                prompt = threads[key] + [rng.randrange(90)]
                hit = cache.lookup(key, prompt)
                if hit is not None:
                    self._verify_hit(o, ps, prompt, hit)
                    if rng.random() < 0.5 and len(live_holds) < 3:
                        live_holds.append(hit.pages)
                    else:
                        pool.release(hit.pages)
            elif op == 4:
                cache.reclaim(pool.free_pages + rng.randrange(1, 6))
            elif op == 5:
                key = rng.choice(list(threads))
                cache.invalidate(key)
                threads.pop(key, None)
            elif op == 6:
                cache.sleep_to_object()
            else:
                if live_holds:
                    pool.release(live_holds.pop(
                        rng.randrange(len(live_holds))))
                elif threads and rng.random() < 0.5:
                    # clear-then-wake: the store is the only copy left
                    cache.sleep_to_object()
                    for pages in live_holds:
                        pool.release(pages)
                    live_holds.clear()
                    cache.clear()
                    key = rng.choice(list(threads))
                    prompt = threads[key] + [rng.randrange(90)]
                    hit = cache.lookup(key, prompt)
                    if hit is not None:
                        assert hit.source == "object_tier"
                        self._verify_hit(o, ps, prompt, hit)
                        pool.release(hit.pages)
            problems = pool.check_consistency()
            assert not problems, f"step {step}: {problems}"
            reports = pool.reconcile(owners())
            assert not reports, f"step {step}: {reports}"
        for pages in live_holds:
            pool.release(pages)
        cache.clear()
        mgr.flush()
        assert not pool.check_consistency()
        assert pool.free_pages == pool.num_pages - 1


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(name="object-test", vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(7))
    return cfg, params


def make_engine(cfg, params, obj_dir=None, **kw):
    defaults = dict(max_batch=2, page_size=8, num_pages=24,
                    max_pages_per_seq=16,
                    prefill_buckets=(8, 16, 32, 64, 128),
                    kv_host_tier_mb=64,
                    kv_object_dir=str(obj_dir) if obj_dir else None)
    defaults.update(kw)
    return InferenceEngine(cfg, params, EngineConfig(**defaults),
                           kv_dtype=jnp.float32)


class TestEngineCrossReplicaWake:
    def test_drained_thread_wakes_on_fresh_engine_token_exact(
        self, model, tmp_path
    ):
        """THE acceptance criterion: a thread demoted to the object
        store by replica A wakes on replica B (fresh engine, A gone)
        with cache_source="object_tier", token-exact output vs the
        never-slept reference, and 0 coverable prompt tokens
        re-prefilled — with the full span evidence."""
        cfg, params = model
        rng = np.random.default_rng(3)
        prompt = [int(x) for x in rng.integers(1, 120, 64)]
        a_eng = make_engine(cfg, params, tmp_path)
        a = GenRequest(request_id="A", prompt_ids=prompt,
                       max_new_tokens=8, prefix_key="thread-A")
        a_eng.submit(a)
        a_eng.run_to_completion()
        stats = a_eng.sleep_to_object()
        assert stats["enabled"] and stats["runs_archived"] >= 1
        assert stats["manifests"] == 1
        del a_eng  # replica A drained and torn down

        b_eng = make_engine(cfg, params, tmp_path)
        resume = prompt + list(a.output_ids) + [
            int(x) for x in rng.integers(1, 120, 12)
        ]
        tracing.reset()
        root = tracing.start_trace(request_id="wake-B")
        b = GenRequest(request_id="B", prompt_ids=resume,
                       max_new_tokens=8, prefix_key="thread-A",
                       trace=tracing.current())
        b_eng.submit(b)
        b_eng.run_to_completion()
        tracing.finish_trace(root)

        assert b.cache_source == "object_tier"
        ps = b_eng.ecfg.page_size
        stored = len(prompt) + len(a.output_ids) - 1
        coverable = (stored // ps) * ps
        assert b.cached_tokens == coverable  # 0 coverable re-prefilled
        assert b.object_tokens > 0
        obj = b_eng.kv_tier.object
        assert obj.wake_threads == 1
        assert b_eng.prefix_cache.object_tier_hits == 1
        assert not b_eng.self_check()

        tr = tracing.get_trace("wake-B")
        names = [s.name for s in tr.spans]
        assert "thread.wake" in names and "kv.object_get" in names
        wake = next(s for s in tr.spans if s.name == "thread.wake")
        assert wake.attrs["source"] == "object_tier"
        assert wake.attrs["tokens"] == b.object_tokens
        assert wake.attrs["bytes"] > 0
        pf = next(s for s in tr.spans if s.name == "engine.prefill")
        assert pf.attrs["cache_source"] == "object_tier"
        assert pf.attrs["object_tokens"] == b.object_tokens
        tracing.reset()

        # token-exact vs a never-slept engine serving both turns
        ref = make_engine(cfg, params, obj_dir=None)
        r1 = GenRequest(request_id="r1", prompt_ids=prompt,
                        max_new_tokens=8, prefix_key="t")
        ref.submit(r1)
        ref.run_to_completion()
        assert r1.output_ids == a.output_ids
        r2 = GenRequest(request_id="r2", prompt_ids=resume,
                        max_new_tokens=8, prefix_key="t")
        ref.submit(r2)
        ref.run_to_completion()
        assert r2.output_ids == b.output_ids

    def test_wake_composes_with_shared_prefix(self, model, tmp_path):
        """Fan-out shape: two threads share a system prefix.  After the
        first wakes, the second's wake imports ONLY its private tail
        (the shared head is already local) — and both are token-exact."""
        cfg, params = model
        rng = np.random.default_rng(5)
        common = [int(x) for x in rng.integers(1, 120, 32)]
        sfx = [[int(x) for x in rng.integers(1, 120, 16)]
               for _ in range(2)]
        a_eng = make_engine(cfg, params, tmp_path)
        firsts = []
        for i in range(2):
            r = GenRequest(request_id=f"A{i}", prompt_ids=common + sfx[i],
                           max_new_tokens=6, prefix_key=f"th-{i}")
            a_eng.submit(r)
            a_eng.run_to_completion()
            firsts.append(list(r.output_ids))
        a_eng.sleep_to_object()
        del a_eng

        b_eng = make_engine(cfg, params, tmp_path)
        woken = []
        for i in range(2):
            r = GenRequest(
                request_id=f"B{i}",
                prompt_ids=common + sfx[i] + firsts[i] + [3, 4, 5],
                max_new_tokens=6, prefix_key=f"th-{i}",
            )
            b_eng.submit(r)
            b_eng.run_to_completion()
            woken.append(r)
        assert [r.cache_source for r in woken] == ["object_tier"] * 2
        # the second thread woke fewer tokens: the shared head was local
        assert woken[1].object_tokens < woken[0].object_tokens
        assert not b_eng.self_check()

        ref = make_engine(cfg, params, obj_dir=None)
        for i in range(2):
            r1 = GenRequest(request_id=f"c{i}",
                            prompt_ids=common + sfx[i],
                            max_new_tokens=6, prefix_key=f"c-{i}")
            ref.submit(r1)
            ref.run_to_completion()
            assert list(r1.output_ids) == firsts[i]
            r2 = GenRequest(
                request_id=f"d{i}",
                prompt_ids=common + sfx[i] + firsts[i] + [3, 4, 5],
                max_new_tokens=6, prefix_key=f"c-{i}",
            )
            ref.submit(r2)
            ref.run_to_completion()
            assert list(r2.output_ids) == list(woken[i].output_ids)

    def test_organic_archive_past_disk(self, model, tmp_path):
        """Without a disk tier, host-budget overflow archives runs into
        the object store (demotion past disk) instead of dropping them —
        and the claimants' manifests follow."""
        cfg, params = model
        eng = make_engine(cfg, params, tmp_path)
        # shrink the host tier to ~one run so churn overflows it
        eng.kv_tier.host_budget_bytes = (
            eng.kv_tier.shipper.bytes_per_page() * 9
        )
        rng = np.random.default_rng(9)
        prompt = [int(x) for x in rng.integers(1, 120, 64)]
        a = GenRequest(request_id="A", prompt_ids=prompt,
                       max_new_tokens=8, prefix_key="thread-A")
        eng.submit(a)
        eng.run_to_completion()
        for i in range(3):
            r = GenRequest(
                request_id=f"c{i}",
                prompt_ids=[int(x) for x in rng.integers(1, 120, 64)],
                max_new_tokens=4, prefix_key=f"churn-{i}",
            )
            eng.submit(r)
            eng.run_to_completion()
        obj = eng.kv_tier.object
        assert obj.object_puts >= 1, "overflow must archive, not drop"
        assert obj.manifests_written >= 1
        assert not eng.self_check()

    def test_object_dir_unset_builds_nothing_bit_identical(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, obj_dir=None)
        assert eng.kv_tier is not None  # host tier still on
        assert eng.kv_tier.object is None
        assert EngineConfig().kv_object_dir is None
        snap = eng.metrics.snapshot(eng)
        assert "object_tier" not in snap
        # no tier at all when both knobs are off
        bare = make_engine(cfg, params, obj_dir=None, kv_host_tier_mb=0)
        assert bare.kv_tier is None

    def test_object_only_config_mounts_tier(self, model, tmp_path):
        """KAFKA_TPU_KV_OBJECT_DIR without a host tier still mounts the
        store (budget-0 manager = pure mount point): drain + wake work,
        ordinary eviction just drops as before."""
        cfg, params = model
        eng = make_engine(cfg, params, tmp_path, kv_host_tier_mb=0)
        assert eng.kv_tier is not None
        assert eng.kv_tier.object is not None
        rng = np.random.default_rng(13)
        prompt = [int(x) for x in rng.integers(1, 120, 48)]
        a = GenRequest(request_id="A", prompt_ids=prompt,
                       max_new_tokens=6, prefix_key="t")
        eng.submit(a)
        eng.run_to_completion()
        stats = eng.sleep_to_object()
        assert stats["enabled"] and stats["runs_archived"] >= 1
        b_eng = make_engine(cfg, params, tmp_path, kv_host_tier_mb=0)
        b = GenRequest(request_id="B",
                       prompt_ids=prompt + list(a.output_ids) + [3, 4],
                       max_new_tokens=6, prefix_key="t")
        b_eng.submit(b)
        b_eng.run_to_completion()
        assert b.cache_source == "object_tier"
        assert not b_eng.self_check()

    def test_negative_budget_rejected(self, model, tmp_path):
        cfg, params = model
        with pytest.raises(ValueError, match="kv_object_mb"):
            make_engine(cfg, params, tmp_path, kv_object_mb=-1)

    def test_config_env_round_trip(self, monkeypatch):
        from kafka_tpu.server.config import ServingConfig

        monkeypatch.setenv("KAFKA_TPU_KV_OBJECT_DIR", "/tmp/kvobj")
        monkeypatch.setenv("KAFKA_TPU_KV_OBJECT_MB", "128")
        cfg = ServingConfig.from_env()
        assert cfg.kv_object_dir == "/tmp/kvobj"
        assert cfg.kv_object_mb == 128
        monkeypatch.setenv("KAFKA_TPU_KV_OBJECT_MB", "-5")
        assert ServingConfig.from_env().kv_object_mb == 0


class TestRouterObjectAffinity:
    def test_manifest_hit_routes_by_load(self, model, tmp_path):
        """A thread known only to the shared store is routable ANYWHERE:
        with no local match, the router sends it to the least-loaded
        replica rather than forcing a cold pin — and the wake serves it
        there (affinity became a hint, ISSUE 14)."""
        from kafka_tpu.runtime.dp_router import DataParallelEngines

        cfg, params = model
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices for dp=2")
        ecfg = EngineConfig(max_batch=2, page_size=8, num_pages=24,
                            max_pages_per_seq=16,
                            prefill_buckets=(8, 16, 32, 64, 128),
                            kv_host_tier_mb=64,
                            kv_object_dir=str(tmp_path))
        # seed the store from a standalone engine (the "old host")
        old = make_engine(cfg, params, tmp_path)
        rng = np.random.default_rng(17)
        prompt = [int(x) for x in rng.integers(1, 120, 48)]
        a = GenRequest(request_id="A", prompt_ids=prompt,
                       max_new_tokens=6, prefix_key="portable")
        old.submit(a)
        old.run_to_completion()
        old.sleep_to_object()
        del old

        dp = DataParallelEngines(cfg, params, ecfg, dp=2, tp=1,
                                 kv_dtype=jnp.float32)
        # load replica 0 so the least-loaded choice is deterministic
        dp.engines[0].submit(GenRequest(
            request_id="busy", prompt_ids=prompt[:9], max_new_tokens=2,
        ))
        r = GenRequest(request_id="B",
                       prompt_ids=prompt + list(a.output_ids) + [3, 4],
                       max_new_tokens=6, prefix_key="portable")
        assert dp._object_match(r) > 0
        picked = dp._pick(r)
        assert picked == 1  # least-loaded, NOT the empty affinity table
        dp.submit(r)
        dp.run_to_completion()
        assert r.cache_source == "object_tier"
        for e in dp.engines:
            assert not e.self_check()


class TestDrainEndpoint:
    def _serve(self, engine, tmp_path, token="tok"):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        from kafka_tpu.db.local import LocalDBClient
        from kafka_tpu.llm import TPULLMProvider
        from kafka_tpu.models.tokenizer import ByteTokenizer
        from kafka_tpu.server.app import create_app
        from kafka_tpu.server.config import ServingConfig

        provider = TPULLMProvider(engine, ByteTokenizer(), model_name="m")

        async def build():
            app = await create_app(
                cfg=ServingConfig(db_path=str(tmp_path / "d.db"),
                                  api_token=token),
                llm_provider=provider,
                db=LocalDBClient(str(tmp_path / "d.db")),
                tools=[],
            )
            client = TestClient(TestServer(app))
            await client.start_server()
            return client

        return asyncio, build, provider

    def test_drain_replica_endpoint(self, model, tmp_path):
        cfg, params = model
        store_dir = tmp_path / "store"
        eng = make_engine(cfg, params, store_dir)
        rng = np.random.default_rng(19)
        prompt = [int(x) for x in rng.integers(1, 120, 48)]
        a = GenRequest(request_id="A", prompt_ids=prompt,
                       max_new_tokens=6, prefix_key="t")
        eng.submit(a)
        eng.run_to_completion()
        asyncio, build, provider = self._serve(eng, tmp_path)

        async def go():
            client = await build()
            hdr = {"Authorization": "Bearer tok"}
            try:
                # token-gated like /admin/resize
                r = await client.post("/admin/drain/0")
                assert r.status == 401
                r = await client.post("/admin/drain/x", headers=hdr)
                assert r.status == 400
                r = await client.post("/admin/drain/7", headers=hdr)
                assert r.status == 400  # out of range
                r = await client.post("/admin/drain/0", headers=hdr)
                assert r.status == 200
                stats = await r.json()
                assert stats["enabled"] and stats["replica"] == 0
                assert stats["runs_archived"] >= 1
                assert stats["manifests"] >= 1
                # idempotent: the re-drain dedupes instead of re-writing
                r = await client.post("/admin/drain/0", headers=hdr)
                stats2 = await r.json()
                assert stats2["dedupe_hits"] >= stats2["runs_archived"] - \
                    stats2["runs_failed"] - 1 or stats2["dedupe_hits"] >= 1
                # signals v6 carries the object_tier section +
                # store health (ISSUE 17)
                s = await client.get("/admin/signals", headers=hdr)
                sig = await s.json()
                assert sig["version"] == 9
                assert sig["object_tier"]["store_objects"] >= 1
                assert "dedupe_ratio" in sig["object_tier"]
                assert sig["object_tier"]["breaker_state"] == "closed"
                assert sig["object_tier"]["store_available"] is True
            finally:
                await client.close()

        asyncio.run(go())
        # serving still works after the (non-destructive) drain
        b = GenRequest(request_id="B",
                       prompt_ids=prompt + list(a.output_ids) + [3],
                       max_new_tokens=4, prefix_key="t")
        eng.submit(b)
        eng.run_to_completion()
        assert not eng.self_check()

    def test_drain_without_store_409(self, model, tmp_path):
        cfg, params = model
        eng = make_engine(cfg, params, obj_dir=None)
        asyncio, build, provider = self._serve(eng, tmp_path)

        async def go():
            client = await build()
            try:
                r = await client.post(
                    "/admin/drain/0",
                    headers={"Authorization": "Bearer tok"},
                )
                assert r.status == 409
                body = await r.json()
                assert "KAFKA_TPU_KV_OBJECT_DIR" in body["error"]
            finally:
                await client.close()

        asyncio.run(go())


class TestRegistry:
    def _source(self, relpath):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(root, relpath)) as f:
            return f.read()

    def test_registry_both_directions(self):
        from kafka_tpu.runtime.metrics import OBJECT_TIER_METRIC_KEYS

        metrics_src = self._source("kafka_tpu/runtime/metrics.py")
        prom_src = self._source("kafka_tpu/server/prometheus.py")
        for key in OBJECT_TIER_METRIC_KEYS:
            assert f'"{key}"' in metrics_src, (
                f"{key} missing from runtime/metrics.py"
            )
            assert f'"{key}"' in prom_src, (
                f"{key} missing from server/prometheus.py"
            )

    def test_snapshot_matches_registry_exactly(self, tmp_path):
        from kafka_tpu.runtime.metrics import OBJECT_TIER_METRIC_KEYS

        obj = ObjectTier(LocalFSObjectStore(str(tmp_path)),
                         fingerprint="f", page_size=4)
        assert set(obj.snapshot()) == set(OBJECT_TIER_METRIC_KEYS)

    def test_sites_and_spans_registered(self):
        assert "kv.object_put" in failpoints.SITES
        assert "kv.object_get" in failpoints.SITES
        assert "kv.object_put" in tracing.SPANS
        assert "kv.object_get" in tracing.SPANS
        assert "thread.wake" in tracing.SPANS

    def test_prometheus_families(self, model, tmp_path):
        from kafka_tpu.server.prometheus import render_prometheus

        cfg, params = model
        a_eng = make_engine(cfg, params, tmp_path)
        rng = np.random.default_rng(15)
        prompt = [int(x) for x in rng.integers(1, 120, 48)]
        a = GenRequest(request_id="A", prompt_ids=prompt,
                       max_new_tokens=6, prefix_key="t")
        a_eng.submit(a)
        a_eng.run_to_completion()
        a_eng.sleep_to_object()
        b_eng = make_engine(cfg, params, tmp_path)
        b = GenRequest(request_id="B",
                       prompt_ids=prompt + list(a.output_ids) + [3],
                       max_new_tokens=4, prefix_key="t")
        b_eng.submit(b)
        b_eng.run_to_completion()
        snap = b_eng.metrics.snapshot(b_eng)
        assert snap["object_tier"]["wake_threads"] == 1
        assert snap["prefix_cache"]["object_tier_hits"] == 1
        text = render_prometheus(snap)
        for family in (
            "kafka_tpu_object_tier_bytes",
            "kafka_tpu_object_tier_objects",
            "kafka_tpu_object_tier_puts_total",
            "kafka_tpu_object_tier_gets_total",
            "kafka_tpu_object_tier_bytes_total",
            "kafka_tpu_object_tier_dedupe_hits_total",
            "kafka_tpu_object_tier_wake_threads_total",
            "kafka_tpu_object_tier_wake_tokens_total",
            "kafka_tpu_object_tier_manifests_total",
        ):
            assert f"# TYPE {family}" in text, family
        assert 'kind="object_tier_hits"' in text
        # storeless engines export NO object_tier FAMILY (the prefix-
        # cache hit kind stays — it is an always-present counter label)
        bare = make_engine(cfg, params, obj_dir=None)
        assert "kafka_tpu_object_tier" not in render_prometheus(
            bare.metrics.snapshot(bare)
        )

    def test_autoscaler_drains_in_registries(self):
        from kafka_tpu.runtime.autoscaler import COUNTER_KEYS
        from kafka_tpu.runtime.metrics import AUTOSCALER_METRIC_KEYS

        assert "autoscaler_drains" in COUNTER_KEYS
        assert "autoscaler_drains" in AUTOSCALER_METRIC_KEYS
        assert '"autoscaler_drains"' in self._source(
            "kafka_tpu/server/prometheus.py"
        )


class TestBenchSmoke:
    def test_sleep_wake_phase_cpu(self, model):
        import importlib.util
        import sys

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "bench", os.path.join(root, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        sys.modules["bench"] = bench
        spec.loader.exec_module(bench)
        cfg, params = model
        out = bench.sleep_wake_phase(cfg, params, n_threads=3,
                                     common_len=496, suffix_len=16,
                                     gen_len=8, page_size=8)
        assert out["outputs_match"]
        assert out["cache_sources"] == ["object_tier"] * 3
        # the acceptance pair: wake beats re-prefill, and the woken span
        # re-prefills ZERO prompt tokens
        assert out["prompt_tokens_recomputed"] == 0
        cold = out["cold_resume_ttft_ms"]
        assert cold["object_wake"] < cold["reprefill"], out
        assert out["cross_host_dedupe_hits"] > 0
        assert out["wake_threads"] == 3
        assert out["store_objects"] >= 1
