"""REAL multi-process jax.distributed coverage (SURVEY §2.2 "distributed
communication backend").

`init_distributed` was previously exercised only as a single-process
no-op; here two OS processes form a 2-host topology over CPU (Gloo
collectives stand in for DCN), build a global dp x tp mesh spanning both
processes, and run a psum through shard_map — the exact mechanics a
multi-host TPU pod uses, minus the silicon.
"""

import os
import subprocess
import sys
import textwrap


_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, %(repo)r)
    from kafka_tpu.parallel.distributed import init_distributed

    assert init_distributed(), "env-driven init did not activate"
    assert jax.process_count() == 2
    assert len(jax.devices()) == 8          # global view: 2 procs x 4
    assert len(jax.local_devices()) == 4    # local view

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "tp"))

    def f(x):
        return jax.lax.psum(x, "tp")

    g = jax.jit(jax.shard_map(f, mesh=mesh,
                              in_specs=P("dp", "tp"), out_specs=P("dp", "tp")))
    x = jax.device_put(
        jnp.arange(8.0).reshape(2, 4),
        NamedSharding(mesh, P("dp", "tp")),
    )
    out = g(x)
    # each row's psum over tp: row 0 -> 6, row 1 -> 22; verify the shards
    # THIS process can address (global fetch is illegal across processes)
    expect = {0: 6.0, 1: 22.0}
    for shard in out.addressable_shards:
        row = shard.index[0].start or 0
        np.testing.assert_allclose(np.asarray(shard.data), expect[row])
    print("MULTIHOST_OK", jax.process_index(), flush=True)
""")


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_mesh():
    port = _free_port()  # per-run coordinator port: no cross-run collisions
    procs = []
    try:
        for pid in range(2):
            env = dict(os.environ)
            env.update(
                KAFKA_TPU_COORDINATOR=f"localhost:{port}",
                KAFKA_TPU_NUM_PROCESSES="2",
                KAFKA_TPU_PROCESS_ID=str(pid),
            )
            # the workers must not inherit this process's already-
            # initialized jax via sitecustomize; they configure their own
            env.pop("PYTHONPATH", None)
            procs.append(subprocess.Popen(
                [sys.executable, "-c",
                 _WORKER % {"repo": os.path.dirname(os.path.dirname(
                     os.path.abspath(__file__)))}],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            ))
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=220)
            assert p.returncode == 0, err.decode()[-2000:]
            outs.append(out.decode())
        assert "MULTIHOST_OK 0" in outs[0] + outs[1]
        assert "MULTIHOST_OK 1" in outs[0] + outs[1]
    finally:
        for p in procs:  # never leak a worker pinning the rendezvous port
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
