"""REAL multi-process jax.distributed coverage (SURVEY §2.2 "distributed
communication backend").

`init_distributed` was previously exercised only as a single-process
no-op; here two OS processes form a 2-host topology over CPU: both join
the coordination service, see the global device view, rendezvous at a
coordination-service barrier, and run a shard_map psum over their LOCAL
devices token-exact.  (This jaxlib's CPU backend cannot execute
multiprocess XLA computations — "Multiprocess computations aren't
implemented on the CPU backend" — so the cross-process data plane is
TPU-only; what IS portable, and what multi-host fault tolerance actually
lives on, is the coordination plane tested here.)

Cross-process chaos (ISSUE 2): the `chaos`+`slow` tests kill one process
of the 2-process topology mid-psum (via an inherited
`dist.step=exit(..)` failpoint) and assert the SURVIVOR surfaces a clean
`DistributedStepError` through `guarded_collective` instead of hanging —
the crash-only contract at the mesh boundary.  Tier-1 runs the fast
single-process subset (watchdog + dist.init failpoint semantics).
"""

import os
import subprocess
import sys
import textwrap
import threading

import pytest


_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, %(repo)r)
    from kafka_tpu.parallel.distributed import barrier, init_distributed

    assert init_distributed(), "env-driven init did not activate"
    assert jax.process_count() == 2
    assert len(jax.devices()) == 8          # global view: 2 procs x 4
    assert len(jax.local_devices()) == 4    # local view

    # coordination plane: both processes must arrive (a dead peer would
    # time this out — that failure mode is the chaos matrix below)
    assert barrier("multihost-smoke", timeout_s=60), "barrier inactive"

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    # data plane over the LOCAL slice (this jaxlib cannot run
    # multiprocess XLA computations on CPU; on TPU the same MeshConfig
    # code paths span hosts)
    mesh = Mesh(np.array(jax.local_devices()).reshape(1, 4), ("dp", "tp"))

    def f(x):
        return jax.lax.psum(x, "tp")

    g = jax.jit(shard_map(f, mesh=mesh,
                          in_specs=P("dp", "tp"), out_specs=P("dp", "tp")))
    base = 8.0 * jax.process_index()
    x = jax.device_put(
        base + jnp.arange(4.0).reshape(1, 4),
        NamedSharding(mesh, P("dp", "tp")),
    )
    out = np.asarray(g(x))
    np.testing.assert_allclose(out, np.full((1, 4), 4 * base + 6.0))
    print("MULTIHOST_OK", jax.process_index(), flush=True)
""")


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_mesh():
    port = _free_port()  # per-run coordinator port: no cross-run collisions
    procs = []
    try:
        for pid in range(2):
            env = dict(os.environ)
            env.update(
                KAFKA_TPU_COORDINATOR=f"localhost:{port}",
                KAFKA_TPU_NUM_PROCESSES="2",
                KAFKA_TPU_PROCESS_ID=str(pid),
            )
            # the workers must not inherit this process's already-
            # initialized jax via sitecustomize; they configure their own
            env.pop("PYTHONPATH", None)
            procs.append(subprocess.Popen(
                [sys.executable, "-c",
                 _WORKER % {"repo": os.path.dirname(os.path.dirname(
                     os.path.abspath(__file__)))}],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            ))
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=220)
            assert p.returncode == 0, err.decode()[-2000:]
            outs.append(out.decode())
        assert "MULTIHOST_OK 0" in outs[0] + outs[1]
        assert "MULTIHOST_OK 1" in outs[0] + outs[1]
    finally:
        for p in procs:  # never leak a worker pinning the rendezvous port
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)


class TestGuardedCollectiveSingleProcess:
    """Fast tier-1 subset: the watchdog + failpoint semantics that do not
    need a second OS process."""

    def test_passthrough_result_and_errors(self):
        from kafka_tpu.parallel import guarded_collective

        assert guarded_collective(lambda a, b: a + b, 2, 3,
                                  timeout_s=5) == 5
        with pytest.raises(ZeroDivisionError):
            guarded_collective(lambda: 1 / 0, timeout_s=5)

    def test_hang_becomes_terminal_error(self):
        from kafka_tpu.parallel import (
            DistributedStepError,
            guarded_collective,
        )

        gate = threading.Event()
        with pytest.raises(DistributedStepError, match="peer process"):
            guarded_collective(gate.wait, timeout_s=0.2, label="psum")
        gate.set()  # release the watchdog thread

    def test_dist_init_failpoint_gates_on_multihost(self):
        """dist.init fires only when multi-host init is actually
        requested — a single-process run must not trip an armed rule."""
        from kafka_tpu.parallel.distributed import init_distributed
        from kafka_tpu.runtime import failpoints as fp

        with fp.armed("dist.init", "error", "init-chaos"):
            assert init_distributed() is False  # no env: no-op, no fire
            with pytest.raises(fp.FailpointError, match="init-chaos"):
                init_distributed(
                    coordinator_address="127.0.0.1:1",
                    num_processes=2, process_id=0,
                )

    def test_dist_step_failpoint_fires_in_guard(self):
        from kafka_tpu.parallel import guarded_collective
        from kafka_tpu.runtime import failpoints as fp

        with fp.armed("dist.step", "error", "step-chaos"):
            with pytest.raises(fp.FailpointError, match="step-chaos"):
                guarded_collective(lambda: 1, timeout_s=5)


class TestTopologyReformation:
    """ISSUE 13 satellite (PR 2 follow-up): a missed collective deadline
    attempts ONE barrier-coordinated re-formation over the survivors
    before fail-stop — a transient stall (peer alive, merely wedged)
    completes the ORIGINAL in-flight collective inside one post-reform
    grace window (never a second execution: the wedged daemon thread is
    still inside the runtime collective, and re-entering it locally
    would pair an extra op against peers participating once); a dead
    peer still surfaces the clean DistributedStepError (the
    dist.step=exit chaos kill matrix exercises that branch across real
    processes)."""

    def test_transient_stall_reforms_and_completes_in_place(
            self, monkeypatch):
        from kafka_tpu.parallel import distributed as dist

        monkeypatch.setattr(dist, "_INITIALIZED", True)
        barriers = []
        gate = threading.Event()

        def healing_barrier(name, timeout_s=60.0):
            barriers.append(name)
            gate.set()  # the stall heals while the survivors rendezvous
            return True

        monkeypatch.setattr(dist, "barrier", healing_barrier)
        calls = []

        def fn():
            calls.append(1)
            gate.wait()  # wedges past the first watchdog window
            return 42

        before = dict(dist.reform_stats)
        try:
            assert dist.guarded_collective(fn, timeout_s=0.2,
                                           label="psum") == 42
        finally:
            gate.set()
        assert len(calls) == 1  # the original attempt, never re-executed
        assert len(barriers) == 1 and barriers[0].startswith("kafka-reform-")
        assert dist.reform_stats["attempts"] == before["attempts"] + 1
        assert dist.reform_stats["successes"] == before["successes"] + 1

    def test_reformed_but_still_stuck_fail_stops(self, monkeypatch):
        """Every peer answers the barrier but the collective still never
        materializes: the grace window expires and the process
        fail-stops — one re-formation, never a loop."""
        from kafka_tpu.parallel import DistributedStepError
        from kafka_tpu.parallel import distributed as dist

        monkeypatch.setattr(dist, "_INITIALIZED", True)
        barriers = []
        monkeypatch.setattr(
            dist, "barrier",
            lambda name, timeout_s=60.0: barriers.append(name) or True,
        )
        gate = threading.Event()
        calls = []

        def fn():
            calls.append(1)
            gate.wait()

        try:
            with pytest.raises(DistributedStepError, match="peer process"):
                dist.guarded_collective(fn, timeout_s=0.2, label="psum")
        finally:
            gate.set()
        assert len(calls) == 1
        assert len(barriers) == 1

    def test_dead_peer_barrier_failure_fail_stops(self, monkeypatch):
        from kafka_tpu.parallel import DistributedStepError
        from kafka_tpu.parallel import distributed as dist

        monkeypatch.setattr(dist, "_INITIALIZED", True)

        def dead_barrier(name, timeout_s=60.0):
            raise RuntimeError("DEADLINE_EXCEEDED: barrier timed out")

        monkeypatch.setattr(dist, "barrier", dead_barrier)
        gate = threading.Event()
        calls = []

        def fn():
            calls.append(1)
            gate.wait()

        try:
            with pytest.raises(DistributedStepError, match="peer process"):
                dist.guarded_collective(fn, timeout_s=0.2, label="psum")
        finally:
            gate.set()
        assert len(calls) == 1  # no retry against a dead topology

    def test_single_process_never_reforms(self):
        """_INITIALIZED False (no multi-host): the pre-existing behavior
        is untouched — straight to the terminal error, no barrier."""
        from kafka_tpu.parallel import DistributedStepError
        from kafka_tpu.parallel import distributed as dist

        gate = threading.Event()
        before = dict(dist.reform_stats)
        try:
            with pytest.raises(DistributedStepError, match="peer process"):
                dist.guarded_collective(gate.wait, timeout_s=0.2,
                                        label="psum")
        finally:
            gate.set()
        assert dist.reform_stats == before

    def test_env_disable(self, monkeypatch):
        from kafka_tpu.parallel import distributed as dist

        monkeypatch.setattr(dist, "_INITIALIZED", True)
        monkeypatch.setenv("KAFKA_TPU_DIST_REFORM", "0")

        def must_not_run(name, timeout_s=60.0):  # pragma: no cover
            raise AssertionError("reform barrier ran while disabled")

        monkeypatch.setattr(dist, "barrier", must_not_run)
        assert dist.reform_topology("psum") is False


# Worker for the kill matrix: both processes run guarded steps in
# lockstep — each step is a local psum plus a coordination-service
# rendezvous (the cross-process sync point a multi-host decode step
# rides on).  The victim's inherited `dist.step=exit(..)` failpoint
# kills it at step 2, and the survivor must convert the resulting
# missing-peer stall into a clean terminal error and exit with a
# distinct code — never hang.
_CHAOS_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, %(repo)r)
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from kafka_tpu.parallel import (
        DistributedStepError, barrier, guarded_collective,
        init_distributed,
    )

    assert init_distributed(), "env-driven init did not activate"
    mesh = Mesh(np.array(jax.local_devices()).reshape(1, 4), ("dp", "tp"))
    g = jax.jit(shard_map(lambda x: jax.lax.psum(x, "tp"), mesh=mesh,
                          in_specs=P("dp", "tp"),
                          out_specs=P("dp", "tp")))
    x = jax.device_put(jnp.arange(4.0).reshape(1, 4),
                       NamedSharding(mesh, P("dp", "tp")))

    step = 0

    def one_step():
        jax.block_until_ready(g(x))          # device work
        barrier("chaos-step-%%d" %% step, timeout_s=10)  # peer rendezvous

    try:
        for step in range(4):
            # the victim's dist.step=exit rule fires inside this call on
            # its nth evaluation; the survivor's next psum then has a
            # dead peer and must hit the watchdog deadline
            guarded_collective(one_step, timeout_s=15, label="psum")
            print("STEP_OK", step, flush=True)
    except DistributedStepError as e:
        print("SURVIVOR_CLEAN", jax.process_index(), str(e)[:80],
              flush=True)
        # a watchdog thread is still stuck inside the dead collective:
        # hard-exit the way a supervised server would after failing its
        # in-flight requests
        os._exit(17)
    except Exception as e:
        # some transports DETECT the dead peer instead of hanging (reset
        # connection / coordination-service heartbeat): that is also a
        # clean terminal error, not a hang — same survivor contract
        print("SURVIVOR_CLEAN", jax.process_index(),
              type(e).__name__, str(e)[:80], flush=True)
        os._exit(17)
    print("ALL_STEPS_DONE", jax.process_index(), flush=True)
""")


@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.parametrize("victim", [0, 1],
                         ids=["kill-coordinator", "kill-worker"])
def test_killed_process_mid_psum_survivor_fails_clean(victim):
    """Kill the coordinator (process 0) or a worker (process 1) mid-step:
    the survivor must TERMINATE within the watchdog budget — never hang.

    Worker kill: the coordinator-side process sees the barrier deadline,
    guarded_collective surfaces the clean DistributedStepError path, and
    the survivor exits 17.  Coordinator kill: the jax runtime's own
    missed-heartbeat policy may hard-abort the survivor from C++ before
    the clean Python path wins the race — fail-stop, which still honors
    crash-only semantics (die loudly rather than serve from a headless
    mesh); both terminations are accepted, a hang never is."""
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    try:
        for pid in range(2):
            env = dict(os.environ)
            env.update(
                KAFKA_TPU_COORDINATOR=f"localhost:{port}",
                KAFKA_TPU_NUM_PROCESSES="2",
                KAFKA_TPU_PROCESS_ID=str(pid),
            )
            env.pop("PYTHONPATH", None)
            if pid == victim:
                # failpoint env inheritance: the kill rule rides the
                # environment into the worker process and fires at its
                # 2nd guarded step — a crash mid-topology, not at boot
                env["KAFKA_TPU_FAILPOINTS"] = "dist.step=exit(31):nth=2"
            else:
                env.pop("KAFKA_TPU_FAILPOINTS", None)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _CHAOS_WORKER % {"repo": repo}],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            ))
        outs = {}
        for pid, p in enumerate(procs):
            out, err = p.communicate(timeout=220)
            outs[pid] = (p.returncode, out.decode(), err.decode())
        survivor = 1 - victim
        vrc, vout, _ = outs[victim]
        src, sout, serr = outs[survivor]
        # the victim died by the injected exit, after at least one step
        assert vrc == 31, outs[victim]
        assert "STEP_OK 0" in vout, outs[victim]
        # the survivor terminated (communicate() above bounds the wait:
        # a hang would TimeoutExpired).  Worker kill must take the clean
        # DistributedStepError path; coordinator kill may also be
        # fail-stopped by the runtime's heartbeat abort.
        if victim == 0:
            assert src != 0, (src, sout, serr[-2000:])
            assert src == 17 or "SURVIVOR_CLEAN" in sout or src < 0, (
                src, sout, serr[-2000:]
            )
        else:
            assert src == 17, (src, sout, serr[-2000:])
            assert "SURVIVOR_CLEAN" in sout, (sout, serr[-2000:])
    finally:
        for p in procs:  # never leak a worker pinning the rendezvous port
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
