"""Scripted stdio MCP server used by tests/test_mcp.py.

Speaks newline-delimited JSON-RPC 2.0 on stdin/stdout: answers
`initialize`, `tools/list` (an `echo` tool and a `progress_echo` tool that
emits two progress notifications first), and `tools/call`.
"""

import json
import sys

TOOLS = [
    {
        "name": "echo",
        "description": "Echo the input back.",
        "inputSchema": {
            "type": "object",
            "properties": {"text": {"type": "string"}},
            "required": ["text"],
        },
    },
    {
        "name": "progress_echo",
        "description": "Echo with progress notifications.",
        "inputSchema": {
            "type": "object",
            "properties": {"text": {"type": "string"}},
        },
    },
    {
        "name": "fail",
        "description": "Always reports a tool error.",
        "inputSchema": {"type": "object", "properties": {}},
    },
]


def send(msg):
    sys.stdout.write(json.dumps(msg) + "\n")
    sys.stdout.flush()


def main():
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        msg = json.loads(line)
        method = msg.get("method")
        msg_id = msg.get("id")
        if method == "initialize":
            send({
                "jsonrpc": "2.0",
                "id": msg_id,
                "result": {
                    "protocolVersion": msg["params"]["protocolVersion"],
                    "capabilities": {"tools": {}},
                    "serverInfo": {"name": "stub", "version": "1.0"},
                },
            })
        elif method == "notifications/initialized":
            pass
        elif method == "tools/list":
            send({"jsonrpc": "2.0", "id": msg_id,
                  "result": {"tools": TOOLS}})
        elif method == "tools/call":
            params = msg.get("params", {})
            name = params.get("name")
            args = params.get("arguments", {})
            token = params.get("_meta", {}).get("progressToken")
            if name == "progress_echo" and token is not None:
                for i in (1, 2):
                    send({
                        "jsonrpc": "2.0",
                        "method": "notifications/progress",
                        "params": {"progressToken": token, "progress": i,
                                   "total": 2, "message": f"step {i}"},
                    })
            if name in ("echo", "progress_echo"):
                send({
                    "jsonrpc": "2.0", "id": msg_id,
                    "result": {"content": [
                        {"type": "text",
                         "text": f"echo: {args.get('text', '')}"}
                    ]},
                })
            elif name == "fail":
                send({
                    "jsonrpc": "2.0", "id": msg_id,
                    "result": {"isError": True, "content": [
                        {"type": "text", "text": "it broke"}
                    ]},
                })
            else:
                send({
                    "jsonrpc": "2.0", "id": msg_id,
                    "error": {"code": -32602,
                              "message": f"unknown tool {name}"},
                })
        else:
            if msg_id is not None:
                send({
                    "jsonrpc": "2.0", "id": msg_id,
                    "error": {"code": -32601,
                              "message": f"unknown method {method}"},
                })


if __name__ == "__main__":
    main()
