"""Kafka orchestrator tests: thread replay/persistence, event
re-accumulation, per-thread config wiring (global_prompt, playbooks,
model override), and the V1 provider lifecycle. Uses the FakeLLM pattern
from test_agent (SURVEY §4) — no JAX, no network."""

import asyncio
import json

import pytest

from kafka_tpu.core.types import StreamChunk
from kafka_tpu.db import LocalDBClient
from kafka_tpu.kafka import (
    KafkaAgent,
    KafkaV1Provider,
    MessageAccumulator,
    playbooks_to_markdown,
)
from kafka_tpu.llm.base import LLMProvider
from kafka_tpu.tools import Tool


def run(coro):
    return asyncio.run(coro)


def text_turn(*parts, cid="chatcmpl-k1"):
    chunks = [StreamChunk(role="assistant", id=cid)]
    chunks += [StreamChunk(content=p, id=cid) for p in parts]
    chunks.append(StreamChunk(finish_reason="stop", id=cid))
    return chunks


def tool_turn(name, args, call_id="call_1", cid="chatcmpl-k2"):
    return [
        StreamChunk(role="assistant", id=cid),
        StreamChunk(
            tool_calls=[{
                "index": 0, "id": call_id, "type": "function",
                "function": {"name": name, "arguments": json.dumps(args)},
            }],
            id=cid,
        ),
        StreamChunk(finish_reason="tool_calls", id=cid),
    ]


class FakeLLM(LLMProvider):
    provider_name = "fake"

    def __init__(self, turns):
        self.turns = list(turns)
        self.seen_messages = []
        self.seen_models = []

    async def stream_completion(self, messages, model=None, **kw):
        self.seen_messages.append(list(messages))
        self.seen_models.append(model)
        for chunk in self.turns.pop(0):
            yield chunk


@pytest.fixture()
def db(tmp_path):
    client = LocalDBClient(str(tmp_path / "kafka.db"))
    run(client.initialize())
    yield client
    run(client.close())


async def collect(agen):
    return [e async for e in agen]


def make_kafka(llm, db=None, **kw):
    kw.setdefault("system_prompt", "test prompt")
    return KafkaV1Provider(llm, thread_db=db, **kw)


class TestRunWithThread:
    def test_history_replayed_and_persisted(self, db):
        llm = FakeLLM([text_turn("first answer"),
                       text_turn("second answer")])
        kafka = make_kafka(llm, db)

        async def go():
            await kafka.initialize()
            ev1 = await collect(kafka.run_with_thread(
                "t-1", [{"role": "user", "content": "q1"}]))
            ev2 = await collect(kafka.run_with_thread(
                "t-1", [{"role": "user", "content": "q2"}]))
            return ev1, ev2

        ev1, ev2 = run(go())
        assert ev1[-1]["type"] == "agent_done"
        # second run saw q1 + first answer in history
        second_input = llm.seen_messages[1]
        roles = [(m["role"], m.get("content")) for m in second_input]
        assert ("user", "q1") in roles
        assert ("assistant", "first answer") in roles
        assert ("user", "q2") in roles
        # db now holds all four messages
        stored = run(db.get_thread_messages("t-1"))
        contents = [m.get("content") for m in stored]
        assert contents == ["q1", "first answer", "q2", "second answer"]

    def test_tool_turns_persisted_as_pairs(self, db):
        def add(a: int, b: int):
            return a + b

        llm = FakeLLM([tool_turn("add", {"a": 1, "b": 2}),
                       text_turn("it is 3", cid="chatcmpl-k9")])
        kafka = make_kafka(llm, db, tools=[
            Tool(name="add", description="", handler=add)])

        async def go():
            await kafka.initialize()
            return await collect(kafka.run_with_thread(
                "t-2", [{"role": "user", "content": "1+2?"}]))

        run(go())
        stored = run(db.get_thread_messages("t-2"))
        roles = [m["role"] for m in stored]
        assert roles == ["user", "assistant", "tool", "assistant"]
        assert stored[1]["tool_calls"][0]["function"]["name"] == "add"
        assert stored[2]["content"] == "3"
        assert stored[2]["tool_call_id"] == "call_1"
        # replay of this thread is sanitizer-clean
        from kafka_tpu.core.sanitize import sanitize_messages_for_openai
        from kafka_tpu.core.types import Message

        msgs = [Message.from_dict(m) for m in stored]
        assert len(sanitize_messages_for_openai(msgs)) == len(msgs)

    def test_thread_autocreated(self, db):
        llm = FakeLLM([text_turn("hi")])
        kafka = make_kafka(llm, db)

        async def go():
            await kafka.initialize()
            await collect(kafka.run_with_thread(
                "t-new", [{"role": "user", "content": "x"}]))
            return await db.thread_exists("t-new")

        assert run(go())

    def test_requires_db(self):
        kafka = make_kafka(FakeLLM([]))

        async def go():
            await kafka.initialize()
            await collect(kafka.run_with_thread(
                "t", [{"role": "user", "content": "x"}]))

        with pytest.raises(RuntimeError, match="thread store"):
            run(go())


class TestThreadConfig:
    def test_model_override_and_prompt_sections(self, db):
        llm = FakeLLM([text_turn("ok")])

        async def go():
            await db.create_thread("t-cfg")
            await db.set_thread_config("t-cfg", {
                "model": "custom-model",
                "global_prompt": "SPEAK LIKE A PIRATE",
                "playbooks": [
                    {"name": "deploy", "trigger": "deploys",
                     "content": "step1\nstep2"},
                ],
            })
            kafka = KafkaV1Provider(
                llm, thread_db=db, thread_id="t-cfg")
            await kafka.initialize()
            await collect(kafka.run_with_thread(
                "t-cfg", [{"role": "user", "content": "hi"}]))
            return kafka

        kafka = run(go())
        assert llm.seen_models == ["custom-model"]
        sys_prompt = llm.seen_messages[0][0]
        assert sys_prompt["role"] == "system"
        assert "SPEAK LIKE A PIRATE" in sys_prompt["content"]
        assert "| deploy | deploys |" in sys_prompt["content"]

    def test_playbooks_markdown(self):
        table = playbooks_to_markdown([
            {"name": "a|b", "trigger": "t", "content": "l1\nl2"},
        ])
        assert "a\\|b" in table
        assert "l1<br>l2" in table
        assert playbooks_to_markdown([]) == ""


class TestMessageAccumulator:
    def test_multi_completion_segmentation(self):
        acc = MessageAccumulator()
        for c in text_turn("part1 ", "part2", cid="id-A"):
            acc.add_event(c.to_openai_dict())
        for c in tool_turn("f", {"x": 1}, cid="id-B"):
            acc.add_event(c.to_openai_dict())
        acc.add_event({
            "type": "tool_result", "tool_call_id": "call_1", "name": "f",
            "kind": "result", "data": 42, "done": True,
        })
        acc.add_event({"type": "agent_done", "reason": "text_response",
                       "final_content": "part1 part2"})
        msgs = acc.messages
        assert [m.role for m in msgs] == ["assistant", "assistant", "tool"]
        assert msgs[0].content == "part1 part2"
        assert msgs[1].tool_calls[0]["function"]["name"] == "f"
        assert msgs[2].content == "42"
        assert acc.final_content == "part1 part2"
        assert acc.done_reason == "text_response"

    def test_error_tool_result(self):
        acc = MessageAccumulator()
        acc.add_event({
            "type": "tool_result", "tool_call_id": "c", "name": "f",
            "kind": "error", "data": "boom", "done": True,
        })
        assert acc.messages[0].content == "Error: boom"

    def test_non_terminal_tool_events_skipped(self):
        acc = MessageAccumulator()
        acc.add_event({
            "type": "tool_result", "tool_call_id": "c", "name": "f",
            "kind": "delta", "data": "tick", "done": False,
        })
        assert acc.messages == []


class TestLifecycle:
    def test_context_manager(self, db):
        llm = FakeLLM([text_turn("hi")])

        async def go():
            async with make_kafka(llm, db) as kafka:
                assert kafka._initialized
                assert isinstance(kafka, KafkaAgent)
            return kafka

        kafka = run(go())
        assert not kafka._initialized

    def test_get_tools(self):
        kafka = make_kafka(FakeLLM([]), tools=[
            Tool(name="t1", description="", handler=lambda: 1)])

        async def go():
            await kafka.initialize()
            return kafka.get_tools()

        tools = run(go())
        assert tools[0]["function"]["name"] == "t1"
