"""Thread-store tests: round-trips, ordering, config, sandbox affinity,
vm-key idempotency, and concurrent writers. All against :memory: SQLite."""

import asyncio

import pytest

from kafka_tpu.db import DBClient, LocalDBClient


def run(coro):
    return asyncio.run(coro)


@pytest.fixture()
def db(tmp_path):
    client = LocalDBClient(str(tmp_path / "threads.db"))
    run(client.initialize())
    yield client
    run(client.close())


class TestThreads:
    def test_create_and_exists(self, db):
        async def go():
            tid = await db.create_thread()
            assert tid.startswith("thread_")
            assert await db.thread_exists(tid)
            assert not await db.thread_exists("nope")
            return tid

        run(go())

    def test_create_with_explicit_id_idempotent(self, db):
        async def go():
            t1 = await db.create_thread("t-1", metadata={"a": 1})
            t2 = await db.create_thread("t-1", metadata={"b": 2})
            assert t1 == t2 == "t-1"
            meta = await db.get_thread_metadata("t-1")
            assert meta["metadata"] == {"a": 1}  # first write wins

        run(go())

    def test_delete_thread_cascades(self, db):
        async def go():
            await db.create_thread("t-del")
            await db.add_message("t-del", {"role": "user", "content": "x"})
            await db.get_or_create_vm_api_key("t-del")
            await db.delete_thread("t-del")
            assert not await db.thread_exists("t-del")
            assert await db.get_thread_messages("t-del") == []

        run(go())

    def test_list_threads_newest_first(self, db):
        async def go():
            await db.create_thread("t-a")
            await db.create_thread("t-b")
            await db.add_message("t-a", {"role": "user", "content": "bump"})
            rows = await db.list_threads()
            assert [r["thread_id"] for r in rows] == ["t-a", "t-b"]

        run(go())


class TestMessages:
    def test_round_trip_preserves_structure(self, db):
        msg = {
            "role": "assistant",
            "content": None,
            "tool_calls": [{
                "id": "c1", "type": "function",
                "function": {"name": "f", "arguments": '{"x": 1}'},
            }],
        }

        async def go():
            await db.create_thread("t-m")
            await db.add_message("t-m", msg)
            out = await db.get_thread_messages("t-m")
            assert out == [msg]

        run(go())

    def test_insertion_order(self, db):
        async def go():
            await db.create_thread("t-o")
            msgs = [{"role": "user", "content": str(i)} for i in range(20)]
            await db.add_messages("t-o", msgs)
            out = await db.get_thread_messages("t-o")
            assert [m["content"] for m in out] == [str(i) for i in range(20)]

        run(go())

    def test_concurrent_writers(self, db):
        async def go():
            await db.create_thread("t-c")
            await asyncio.gather(*(
                db.add_message("t-c", {"role": "user", "content": f"w{i}"})
                for i in range(30)
            ))
            out = await db.get_thread_messages("t-c")
            assert len(out) == 30

        run(go())

    def test_delete_messages_keeps_thread(self, db):
        async def go():
            await db.create_thread("t-dm")
            await db.add_message("t-dm", {"role": "user", "content": "x"})
            await db.delete_thread_messages("t-dm")
            assert await db.thread_exists("t-dm")
            assert await db.get_thread_messages("t-dm") == []

        run(go())


class TestConfigAndKeys:
    def test_config_none_fallback(self, db):
        async def go():
            await db.create_thread("t-cfg")
            assert await db.get_thread_config("t-cfg") is None
            cfg = {"model": "llama-3.2-1b", "global_prompt": "be kind",
                   "playbooks": [{"name": "p1", "content": "steps"}]}
            await db.set_thread_config("t-cfg", cfg)
            assert await db.get_thread_config("t-cfg") == cfg
            await db.set_thread_config("t-cfg", None)
            assert await db.get_thread_config("t-cfg") is None

        run(go())

    def test_sandbox_affinity(self, db):
        async def go():
            await db.create_thread("t-sb")
            assert await db.get_thread_sandbox_id("t-sb") is None
            await db.update_thread_sandbox_id("t-sb", "sbx-1")
            assert await db.get_thread_sandbox_id("t-sb") == "sbx-1"
            await db.update_thread_sandbox_id("t-sb", None)
            assert await db.get_thread_sandbox_id("t-sb") is None

        run(go())

    def test_vm_key_stable(self, db):
        async def go():
            await db.create_thread("t-k")
            k1 = await db.get_or_create_vm_api_key("t-k")
            k2 = await db.get_or_create_vm_api_key("t-k")
            assert k1 == k2 and k1.startswith("vmk_")
            ks = await asyncio.gather(*(
                db.get_or_create_vm_api_key("t-k") for _ in range(10)
            ))
            assert set(ks) == {k1}

        run(go())


def test_abc_conformance():
    assert issubclass(LocalDBClient, DBClient)
