"""Int8 KV-cache quantization (VERDICT r4 next #4).

The paged pools become QTensor pytrees (int8 rows + per-slot f32 scales,
runtime/kv_cache.py) and the attention layer quantizes at write /
dequantizes at gather (models/llama.py _kv_write/_kv_read).  Covered:
roundtrip error bounds, engine serving vs the dense-KV engine, pool
sharing (prefix cache) with quantized pages, TP-mesh consistency, and the
config wiring.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.models import ModelConfig, init_params
from kafka_tpu.models.llama import _kv_read, _kv_write
from kafka_tpu.models.quant import QTensor
from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine
from kafka_tpu.runtime.kv_cache import make_kv_pool_arrays


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(name="kvq-test", vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(5))
    return cfg, params


def make_engine(cfg, params, kv_quantize="", mesh=None,
                attention_backend="auto"):
    return InferenceEngine(
        cfg, params,
        EngineConfig(max_batch=2, page_size=8, num_pages=64,
                     max_pages_per_seq=8, prefill_buckets=(8, 16, 32),
                     kv_quantize=kv_quantize,
                     attention_backend=attention_backend),
        kv_dtype=jnp.float32, mesh=mesh,
    )


class TestPoolPrimitives:
    def test_make_quantized_pool_shapes(self):
        cfg = ModelConfig(num_layers=3, num_kv_heads=2, head_dim=16)
        k, v = make_kv_pool_arrays(cfg, num_pages=10, page_size=8,
                                   quantize="int8")
        assert isinstance(k, QTensor) and k.q.dtype == jnp.int8
        assert k.q.shape == (3, 80, 32)
        assert k.s.shape == (3, 80, 1) and k.s.dtype == jnp.float32
        with pytest.raises(ValueError):
            make_kv_pool_arrays(cfg, 10, 8, quantize="fp4")

    def test_write_read_roundtrip_bound(self):
        pool = QTensor(q=jnp.zeros((40, 128), jnp.int8),
                       s=jnp.zeros((40, 1), jnp.float32))
        rows = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 128),
                                 jnp.float32) * 5.0
        idx = jnp.array([[1, 2, 3], [10, 11, 12]])
        pool = _kv_write(pool, idx, rows)
        back = _kv_read(pool, idx, jnp.float32)
        # symmetric per-row int8: |err| <= row_max/254 + eps
        bound = np.abs(np.asarray(rows)).max(-1, keepdims=True) / 254 + 1e-5
        assert (np.abs(np.asarray(back) - np.asarray(rows)) <= bound).all()

    def test_dense_path_unchanged(self):
        pool = jnp.zeros((40, 32), jnp.float32)
        rows = jnp.ones((1, 2, 32))
        pool = _kv_write(pool, jnp.array([[4, 5]]), rows)
        assert float(pool[4].sum()) == 32.0
        assert _kv_read(pool, jnp.array([[4]]), jnp.float32).shape == (1, 1, 32)


class TestQuantizedKVServing:
    def test_greedy_match_vs_dense_kv(self, model):
        """f32 weights + int8 KV vs f32 weights + f32 KV: the KV rounding
        is the only difference; greedy streams should mostly agree (random
        weights leave near-ties, so exact match is not required)."""
        cfg, params = model
        dense = make_engine(cfg, params)
        q_eng = make_engine(cfg, params, kv_quantize="int8")
        assert q_eng.cfg.attention_backend == "xla"
        match = total = 0
        for i in range(4):
            prompt = [3 + i, 17, 92, 5, 44 + i]
            a = dense.generate(prompt, max_new_tokens=16).output_ids
            b = q_eng.generate(prompt, max_new_tokens=16).output_ids
            total += len(a)
            match += sum(1 for x, y in zip(a, b) if x == y)
        assert match / total > 0.7, f"match rate {match}/{total}"

    def test_serves_batch_with_preemption_shapes(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, kv_quantize="int8")
        for i in range(3):
            eng.submit(GenRequest(request_id=f"kq{i}",
                                  prompt_ids=[5 + i, 2, 9],
                                  max_new_tokens=8))
        done = eng.run_to_completion()
        assert len(done) == 3
        assert all(len(r.output_ids) == 8 for r in done.values())

    def test_prefix_cache_shares_quantized_pages(self, model):
        """Shared prefix pages carry their scales with them (scales are
        per-slot, slots are shared): the second request reuses the pages
        and still decodes sanely."""
        cfg, params = model
        eng = make_engine(cfg, params, kv_quantize="int8")
        p1 = [(i * 7) % 120 + 3 for i in range(20)]
        r1 = eng.generate(p1, max_new_tokens=6, prefix_key="t1")
        hits0 = eng.prefix_cache.hits
        # second turn extends the thread (the cache-hit shape): shared
        # full pages are reused with their quantized rows + scales
        p2 = p1 + r1.output_ids + [9, 4]
        r2 = eng.generate(p2, max_new_tokens=6, prefix_key="t1")
        assert eng.prefix_cache.hits > hits0
        # ground truth: same request on a fresh quantized engine, no cache
        ref = make_engine(cfg, params, kv_quantize="int8").generate(
            p2, max_new_tokens=6)
        assert r2.output_ids == ref.output_ids

    def test_forced_pallas_int8_matches_xla_int8(self, model):
        """The int8 decode kernel (paged_decode_attention_int8: int8 page
        DMAs + fused per-slot dequant) through the engine matches the XLA
        dequantizing-gather path token-for-token — both read the SAME
        quantized pool, so the kernels must agree."""
        cfg, params = model
        outs = {}
        for backend in ("xla", "pallas"):
            eng = make_engine(cfg, params, kv_quantize="int8",
                              attention_backend=backend)
            assert eng.cfg.attention_backend == backend
            outs[backend] = eng.generate(
                [3, 17, 92, 5, 44, 8, 29], max_new_tokens=12
            ).output_ids
        assert outs["pallas"] == outs["xla"]


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
class TestQuantizedKVTP:
    def test_tp_matches_single_device(self, model):
        from kafka_tpu.parallel import MeshConfig, make_mesh

        cfg, params = model
        base = make_engine(cfg, params, kv_quantize="int8")
        eng = make_engine(cfg, params, kv_quantize="int8",
                          mesh=make_mesh(MeshConfig(tp=2)))
        prompt = [5, 99, 23, 4, 17]
        want = base.generate(prompt, max_new_tokens=10).output_ids
        got = eng.generate(prompt, max_new_tokens=10).output_ids
        assert got == want

    def test_tp_pallas_int8_matches_xla(self, model):
        """The sharded int8 kernel (shard_map per-shard DMAs, scales
        replicated) through a tp mesh engine matches the xla int8 mesh
        engine token-for-token.  Child-isolated (tests/_isolation.py)."""
        from _isolation import isolated

        if not isolated(
            "tests/test_kv_quant.py::TestQuantizedKVTP::"
            "test_tp_pallas_int8_matches_xla"
        ):
            return
        from kafka_tpu.parallel import MeshConfig, make_mesh

        cfg, params = model
        outs = {}
        for backend in ("xla", "pallas"):
            eng = make_engine(cfg, params, kv_quantize="int8",
                              attention_backend=backend,
                              mesh=make_mesh(MeshConfig(tp=2)))
            outs[backend] = eng.generate(
                [5, 99, 23, 4, 17], max_new_tokens=10
            ).output_ids
        assert outs["pallas"] == outs["xla"]


class TestConfigWiring:
    def test_env(self, monkeypatch):
        from kafka_tpu.server import ServingConfig

        monkeypatch.setenv("KAFKA_TPU_KV_QUANTIZE", "int8")
        assert ServingConfig.from_env().kv_quantize == "int8"

    def test_planner_models_int8_kv(self):
        from kafka_tpu.models.config import get_config
        from kafka_tpu.runtime.planner import kv_bytes_per_token

        cfg = get_config("llama-3-8b")
        assert kv_bytes_per_token(cfg, kv_dtype="int8") * 2 == \
            kv_bytes_per_token(cfg, kv_dtype="bfloat16")
