"""Numerics: our functional-JAX Llama must match transformers' torch Llama.

Builds a tiny random HF LlamaForCausalLM on CPU, converts its state dict via
models.loader, and compares logits in float32. This is the ground-truth test
the survey prescribes for the model tier (SURVEY.md §4) — checkpoints can't
be downloaded in this environment, so weight *conversion* + architecture are
what's verified, on random weights.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from kafka_tpu.models import ModelConfig, convert_hf_state_dict, forward, init_kv_cache


def make_pair(tie=True, rope_scaling=None, num_heads=4, num_kv=2, layers=2):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=97,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=layers,
        num_attention_heads=num_heads,
        num_key_value_heads=num_kv,
        head_dim=8,
        max_position_embeddings=64,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=tie,
        attention_bias=False,
        mlp_bias=False,
        rope_scaling=rope_scaling,
    )
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()

    cfg = ModelConfig(
        name="test",
        vocab_size=97,
        hidden_size=32,
        intermediate_size=64,
        num_layers=layers,
        num_heads=num_heads,
        num_kv_heads=num_kv,
        head_dim=8,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        max_context=64,
        tie_word_embeddings=tie,
        dtype="float32",
        rope_scaling_factor=(rope_scaling or {}).get("factor"),
        rope_low_freq_factor=(rope_scaling or {}).get("low_freq_factor", 1.0),
        rope_high_freq_factor=(rope_scaling or {}).get("high_freq_factor", 4.0),
        rope_original_max_position=(rope_scaling or {}).get(
            "original_max_position_embeddings", 64
        ),
    )
    params = convert_hf_state_dict(hf.state_dict(), cfg, dtype=jnp.float32)
    return hf, cfg, params


def hf_logits(hf, ids):
    with torch.no_grad():
        return hf(torch.tensor(ids)).logits.float().numpy()


@pytest.mark.parametrize("tie", [True, False])
def test_logits_match_hf(tie):
    hf, cfg, params = make_pair(tie=tie)
    ids = np.array([[1, 5, 9, 42, 7, 3, 88, 11]], dtype=np.int32)
    positions = np.arange(8, dtype=np.int32)[None, :]
    ours, _ = forward(params, cfg, jnp.asarray(ids), jnp.asarray(positions))
    theirs = hf_logits(hf, ids)
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=5e-3, atol=2.5e-3)


def test_logits_match_hf_llama3_rope_scaling():
    rs = {
        "rope_type": "llama3",
        "factor": 8.0,
        "low_freq_factor": 1.0,
        "high_freq_factor": 4.0,
        "original_max_position_embeddings": 64,
    }
    hf, cfg, params = make_pair(rope_scaling=rs)
    ids = np.array([[2, 4, 6, 8, 10, 12]], dtype=np.int32)
    pos = np.arange(6, dtype=np.int32)[None, :]
    ours, _ = forward(params, cfg, jnp.asarray(ids), jnp.asarray(pos))
    np.testing.assert_allclose(np.asarray(ours), hf_logits(hf, ids), rtol=5e-3, atol=2.5e-3)


def test_batched_matches_unbatched():
    hf, cfg, params = make_pair()
    a = np.array([[1, 2, 3, 4]], dtype=np.int32)
    b = np.array([[9, 8, 7, 6]], dtype=np.int32)
    pos = np.arange(4, dtype=np.int32)[None, :]
    la, _ = forward(params, cfg, jnp.asarray(a), jnp.asarray(pos))
    lb, _ = forward(params, cfg, jnp.asarray(b), jnp.asarray(pos))
    both, _ = forward(
        params,
        cfg,
        jnp.concatenate([jnp.asarray(a), jnp.asarray(b)]),
        jnp.concatenate([jnp.asarray(pos), jnp.asarray(pos)]),
    )
    np.testing.assert_allclose(np.asarray(both[0]), np.asarray(la[0]), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(both[1]), np.asarray(lb[0]), rtol=1e-5, atol=1e-5)


def test_incremental_cache_matches_full_forward():
    """Decode with the contiguous KV cache == full forward, token by token."""
    hf, cfg, params = make_pair()
    ids = np.array([[5, 17, 33, 2, 64, 21]], dtype=np.int32)
    S = ids.shape[1]
    pos = np.arange(S, dtype=np.int32)[None, :]
    full, _ = forward(params, cfg, jnp.asarray(ids), jnp.asarray(pos))

    cache = init_kv_cache(cfg, batch=1, capacity=16, dtype=jnp.float32)
    valid = jnp.zeros((1, 16), dtype=bool)
    # prefill first 3 tokens in one chunk, then decode the rest one-by-one
    chunk = jnp.asarray(ids[:, :3])
    cpos = jnp.arange(3, dtype=jnp.int32)[None, :]
    valid = valid.at[:, :3].set(True)
    logits, cache = forward(
        params, cfg, chunk, cpos, kv_cache=cache, kv_valid=valid
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, :3]), rtol=5e-3, atol=2.5e-3
    )
    for t in range(3, S):
        tok = jnp.asarray(ids[:, t : t + 1])
        tpos = jnp.full((1, 1), t, dtype=jnp.int32)
        valid = valid.at[:, t].set(True)
        logits, cache = forward(
            params, cfg, tok, tpos, kv_cache=cache, kv_valid=valid
        )
        np.testing.assert_allclose(
            np.asarray(logits[:, 0]), np.asarray(full[:, t]), rtol=5e-3, atol=2.5e-3
        )


def test_forward_is_jittable_static_shapes():
    hf, cfg, params = make_pair()
    jitted = jax.jit(lambda p, i, q: forward(p, cfg, i, q))
    ids = jnp.asarray(np.array([[1, 2, 3, 4]], dtype=np.int32))
    pos = jnp.arange(4, dtype=jnp.int32)[None, :]
    l1, _ = jitted(params, ids, pos)
    l2, _ = jitted(params, ids + 0, pos)  # second call: cached compile
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2))
