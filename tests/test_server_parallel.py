"""End-to-end server tests for the parallelism wiring.

Round-2 verdict item 1: dp/sp must be reachable *product* surface, not
library objects — these tests boot the real server stack (create_app →
build_tpu_provider → DataParallelEngines / sp-mesh engine) from a
ServingConfig alone on the 8-device virtual CPU mesh (conftest), then
serve actual completions through HTTP.
"""

import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

from kafka_tpu.server import ServingConfig, create_app
from kafka_tpu.server.app import STATE_KEY


def _cfg(tmp_path, **kw):
    # the full agent system prompt is ~700 tokens (ByteTokenizer), so the
    # window must hold a real conversation: 128 pages x 16 = 2048 tokens
    base = dict(
        tiny_model=True,
        db_path=str(tmp_path / "threads.db"),
        max_batch=2,
        page_size=16,
        num_pages=320,
        max_pages_per_seq=128,
        prefill_buckets=(256,),
        max_new_tokens_default=8,
    )
    base.update(kw)
    return ServingConfig(**base)


async def _boot(cfg) -> TestClient:
    app = await create_app(cfg=cfg, tools=[], mcp_servers=[])
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


def _engine(client):
    return client.server.app[STATE_KEY]["llm"].engine


class TestDPServing:
    """KAFKA_TPU_DP=2 x TP=2: replica engines built by the server itself."""

    def test_dp2_tp2_end_to_end(self, tmp_path):
        async def run():
            client = await _boot(_cfg(tmp_path, dp_size=2, tp_size=2))
            try:
                engine = _engine(client)
                # the server built the DP router, replicas on disjoint slices
                assert len(engine.engines) == 2
                d0 = {d for d in engine.engines[0].mesh.devices.flat}
                d1 = {d for d in engine.engines[1].mesh.devices.flat}
                assert len(d0) == 2 and len(d1) == 2 and not (d0 & d1)

                resp = await client.post(
                    "/v1/chat/completions",
                    json={
                        "model": "tiny",
                        "messages": [{"role": "user", "content": "hi"}],
                        "stream": False,
                        "max_tokens": 4,
                    },
                )
                assert resp.status == 200
                body = await resp.json()
                assert body["object"] == "chat.completion"
                assert body["choices"][0]["message"]["role"] == "assistant"

                # /metrics aggregates over replicas
                m = await (await client.get("/metrics")).json()
                assert m["dp"] == 2
                assert len(m["replicas"]) == 2
                assert m["requests"]["finished"] >= 1
                assert m["engine"]["pages_total"] == 2 * 320
                # pooled latency percentiles, not zeroed placeholders
                assert m["ttft_ms"]["p50"] > 0

                h = await (await client.get("/health")).json()
                assert h["engine"]["dp"] == 2
                assert h["engine"]["total_pages"] == 2 * 320
            finally:
                await client.close()

        asyncio.run(run())

    def test_thread_affinity_through_server(self, tmp_path):
        """Two turns on one thread route to the same replica and hit its
        prefix cache (BASELINE config 2 composed with DP)."""

        async def run():
            client = await _boot(_cfg(tmp_path, dp_size=2, tp_size=1))
            try:
                engine = _engine(client)
                resp = await client.post("/v1/threads", json={})
                tid = (await resp.json())["thread_id"]
                for _ in range(2):
                    resp = await client.post(
                        f"/v1/threads/{tid}/chat/completions",
                        json={
                            "model": "tiny",
                            "messages": [{"role": "user", "content": "go"}],
                            "stream": False,
                            "max_tokens": 4,
                        },
                    )
                    assert resp.status == 200
                assert tid in engine._affinity
                replica = engine._affinity[tid]
                assert engine.engines[replica].prefix_cache.hits >= 1
                other = engine.engines[1 - replica]
                assert other.metrics.requests_finished == 0
            finally:
                await client.close()

        asyncio.run(run())


class TestSPServing:
    """sp ring-prefill engine reachable straight from ServingConfig."""

    def test_sp2_tp2_end_to_end(self, tmp_path):
        async def run():
            client = await _boot(_cfg(tmp_path, sp_size=2, tp_size=2))
            try:
                engine = _engine(client)
                assert engine.mesh.shape["sp"] == 2
                assert engine.mesh.shape["tp"] == 2
                assert engine.cfg.prefill_ring  # ring prefill is active
                resp = await client.post(
                    "/v1/chat/completions",
                    json={
                        "model": "tiny",
                        "messages": [
                            {"role": "user", "content": "tell me a story"}
                        ],
                        "stream": False,
                        "max_tokens": 4,
                    },
                )
                assert resp.status == 200
                body = await resp.json()
                assert body["choices"][0]["finish_reason"] == "stop"
            finally:
                await client.close()

        asyncio.run(run())


class TestPPServing:
    """pp stage-sharded engine reachable straight from ServingConfig."""

    def test_pp2_tp2_end_to_end(self, tmp_path):
        async def run():
            client = await _boot(_cfg(tmp_path, pp_size=2, tp_size=2))
            try:
                engine = _engine(client)
                assert engine.mesh.shape["pp"] == 2
                assert engine._pp == 2
                resp = await client.post(
                    "/v1/chat/completions",
                    json={
                        "model": "tiny",
                        "messages": [{"role": "user", "content": "hi"}],
                        "stream": False,
                        "max_tokens": 4,
                    },
                )
                assert resp.status == 200
                body = await resp.json()
                assert body["choices"][0]["finish_reason"] == "stop"
            finally:
                await client.close()

        asyncio.run(run())

    def test_dp_pp_compose_rejected(self, tmp_path):
        async def run():
            with pytest.raises(ValueError, match="cannot compose"):
                await create_app(
                    cfg=_cfg(tmp_path, dp_size=2, pp_size=2),
                    tools=[], mcp_servers=[],
                )

        asyncio.run(run())


class TestParallelConfig:
    def test_env_spellings(self, monkeypatch):
        monkeypatch.setenv("KAFKA_TPU_DP", "2")
        monkeypatch.setenv("KAFKA_TPU_SP_SIZE", "4")
        monkeypatch.setenv("KAFKA_TPU_TP_SIZE", "2")
        cfg = ServingConfig.from_env()
        assert (cfg.dp_size, cfg.sp_size, cfg.tp_size) == (2, 4, 2)

    def test_size_suffix_wins_over_short(self, monkeypatch):
        monkeypatch.setenv("KAFKA_TPU_DP", "8")
        monkeypatch.setenv("KAFKA_TPU_DP_SIZE", "2")
        assert ServingConfig.from_env().dp_size == 2

    def test_too_many_devices_is_a_clear_error(self, tmp_path):
        async def run():
            with pytest.raises(ValueError, match="devices"):
                await create_app(
                    cfg=_cfg(tmp_path, dp_size=8, tp_size=2),
                    tools=[], mcp_servers=[],
                )

        asyncio.run(run())


class TestWarmup:
    def test_boot_warmup_precompiles_and_resets_metrics(self, tmp_path):
        async def run():
            client = await _boot(_cfg(tmp_path))  # warmup defaults on
            try:
                engine = _engine(client)
                # the decode program and a prefill bucket compiled at boot
                assert engine._prefill_fns, "warmup compiled no prefill"
                # ...and the warmup generation does not pollute metrics
                m = await (await client.get("/metrics")).json()
                assert m["requests"]["submitted"] == 0
                assert m["requests"]["finished"] == 0
            finally:
                await client.close()

        asyncio.run(run())

    def test_warmup_disabled_by_config(self, tmp_path):
        async def run():
            client = await _boot(_cfg(tmp_path, warmup=False))
            try:
                assert not _engine(client)._prefill_fns
            finally:
                await client.close()

        asyncio.run(run())


class TestDisconnectCancel:
    """VERDICT r3 weak #7 / next #8: a client disconnect mid-stream must
    cancel the engine request THROUGH THE HTTP LAYER (provider-level cancel
    is covered by tests/test_llm_provider.py) — the slot frees instead of
    decoding the rest of the stream for a dead socket."""

    def test_disconnect_mid_stream_cancels_engine_request(self, tmp_path):
        async def run():
            client = await _boot(_cfg(
                tmp_path, max_new_tokens_default=1500, warmup=False,
            ))
            try:
                engine = _engine(client)
                resp = await client.post(
                    "/v1/chat/completions",
                    json={"model": "tiny", "stream": True,
                          "messages": [{"role": "user", "content": "go"}]},
                )
                assert resp.status == 200
                # wait for streaming to actually start (engine admitted)
                await resp.content.readany()
                for _ in range(300):
                    if engine.num_active or engine.waiting:
                        break
                    await asyncio.sleep(0.02)
                assert engine.num_active or engine.waiting
                # drop the connection mid-stream
                resp.close()
                for _ in range(300):
                    if (engine.metrics.requests_cancelled >= 1
                            and engine.num_active == 0
                            and not engine.waiting):
                        break
                    await asyncio.sleep(0.02)
                assert engine.metrics.requests_cancelled >= 1
                assert engine.num_active == 0 and not engine.waiting
                # tokens dispatched after the cancel are counted as
                # fetch-pipeline waste, not generation (runtime/metrics.py;
                # the deprecated speculative_wasted alias is gone)
                snap = engine.metrics.snapshot(engine)
                assert "fetch_pipeline_wasted" in snap["tokens"]
                assert "speculative_wasted" not in snap["tokens"]
            finally:
                await client.close()

        asyncio.run(run())


class TestEPServing:
    """KAFKA_TPU_EP=2 x TP=2 with a MoE model: the server builds an
    expert-sharded engine from ServingConfig alone and serves through HTTP
    (VERDICT r3 #5: ep as reachable product surface, not a library axis)."""

    def test_ep2_tp2_moe_end_to_end(self, tmp_path):
        async def run():
            client = await _boot(_cfg(
                tmp_path, tiny_model=False, model_name="tiny-moe",
                dtype="float32", ep_size=2, tp_size=2,
            ))
            try:
                engine = _engine(client)
                assert engine.cfg.is_moe
                assert engine.mesh.shape["ep"] == 2
                assert engine.mesh.shape["tp"] == 2
                # expert weights really shard over ep
                wg = engine.params["layers"]["wg"]
                assert "ep" in str(wg.sharding.spec)
                resp = await client.post(
                    "/v1/chat/completions",
                    json={
                        "model": "tiny-moe",
                        "messages": [{"role": "user", "content": "hi"}],
                        "stream": False,
                        "max_tokens": 4,
                    },
                )
                assert resp.status == 200
                body = await resp.json()
                assert body["choices"][0]["message"]["role"] == "assistant"
            finally:
                await client.close()

        asyncio.run(run())
