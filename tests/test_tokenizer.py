"""Tests for tokenization, chat templating, and tool-call text parsing."""

import json

from kafka_tpu.models import ByteTokenizer, get_config, parse_tool_call_text
from kafka_tpu.models.config import CONFIGS


class TestByteTokenizer:
    def test_roundtrip(self):
        tok = ByteTokenizer()
        for s in ["hello world", "héllo → ünïcode", ""]:
            assert tok.decode(tok.encode(s)) == s

    def test_specials_single_ids(self):
        tok = ByteTokenizer()
        ids = tok.encode("<|begin_of_text|>hi<|eot_id|>")
        assert ids[0] == tok.bos_id and ids[-1] == tok.eot_id
        assert len(ids) == 4  # bos + 'h' + 'i' + eot

    def test_specials_stripped_on_decode(self):
        tok = ByteTokenizer()
        assert tok.decode(tok.encode("<|eot_id|>ok")) == "ok"

    def test_chat_template(self):
        tok = ByteTokenizer()
        text = tok.apply_chat_template(
            [
                {"role": "system", "content": "be brief"},
                {"role": "user", "content": "hi"},
            ]
        )
        assert text.startswith("<|begin_of_text|><|start_header_id|>system")
        assert text.endswith("<|start_header_id|>assistant<|end_header_id|>\n\n")
        assert "be brief<|eot_id|>" in text

    def test_chat_template_tools_merged_into_system(self):
        tok = ByteTokenizer()
        tools = [{"type": "function", "function": {"name": "f", "parameters": {}}}]
        text = tok.apply_chat_template(
            [{"role": "user", "content": "x"}], tools=tools
        )
        assert text.count("<|start_header_id|>system") == 1
        assert '"name": "f"' in text

    def test_tool_role_rendered_as_ipython(self):
        tok = ByteTokenizer()
        text = tok.apply_chat_template(
            [{"role": "tool", "content": "42", "tool_call_id": "c1"}],
            add_generation_prompt=False,
        )
        assert "<|start_header_id|>ipython" in text


class TestParseToolCallText:
    def test_single_call(self):
        calls = parse_tool_call_text('{"name": "get_weather", "parameters": {"city": "Paris"}}')
        assert calls and calls[0]["function"]["name"] == "get_weather"
        assert json.loads(calls[0]["function"]["arguments"]) == {"city": "Paris"}

    def test_list_of_calls(self):
        calls = parse_tool_call_text('[{"name": "a", "parameters": {}}, {"name": "b", "parameters": {}}]')
        assert [c["function"]["name"] for c in calls] == ["a", "b"]

    def test_plain_text_is_none(self):
        assert parse_tool_call_text("The weather is nice.") is None
        assert parse_tool_call_text("") is None
        assert parse_tool_call_text('{"not_a_call": 1}') is None
        assert parse_tool_call_text("{broken json") is None


class TestConfigs:
    def test_known_sizes(self):
        c8 = get_config("llama-3-8b")
        assert c8.num_layers == 32 and c8.num_kv_heads == 8
        c70 = get_config("Llama-3-70B-Instruct")
        assert c70.num_layers == 80 and c70.hidden_size == 8192

    def test_param_counts_roughly_right(self):
        # embed + layers + head; sanity that configs aren't typo'd
        def nparams(c):
            per_layer = (
                c.hidden_size * c.num_heads * c.head_dim * 2  # wq, wo
                + c.hidden_size * c.num_kv_heads * c.head_dim * 2  # wk, wv
                + 3 * c.hidden_size * c.intermediate_size
            )
            total = c.vocab_size * c.hidden_size * (1 if c.tie_word_embeddings else 2)
            return total + c.num_layers * per_layer

        assert 0.9e9 < nparams(get_config("llama-3.2-1b")) < 1.4e9
        assert 7e9 < nparams(get_config("llama-3-8b")) < 9e9
        assert 65e9 < nparams(get_config("llama-3-70b")) < 75e9

    def test_all_configs_heads_divide(self):
        for name, c in CONFIGS.items():
            assert c.num_heads % c.num_kv_heads == 0, name
