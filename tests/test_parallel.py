"""Parallelism tests on the virtual 8-device CPU mesh.

Covers: TP-sharded engine == unsharded engine (token-exact under f32),
param placement matches the sharding rules, ring attention == reference
attention with the sequence sharded 8 ways, Ulysses likewise.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from kafka_tpu.models import ModelConfig, init_params
from kafka_tpu.ops.attention import causal_attention
from kafka_tpu.parallel import (
    MeshConfig,
    factor_tp_for_kv,
    make_mesh,
    param_specs,
    ring_attention_sharded,
    shard_params,
    ulysses_attention_sharded,
)
from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(name="par-test", vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=8,
                      num_kv_heads=4, head_dim=8, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(3))
    return cfg, params


class TestTPSharding:
    def test_param_placement(self, model):
        cfg, params = model
        mesh = make_mesh(MeshConfig(tp=4))
        sharded = shard_params(params, cfg, mesh)
        wq = sharded["layers"]["wq"]
        # heads axis (2) split 4 ways
        assert wq.sharding.spec == P(None, None, "tp", None)
        shard_shape = wq.addressable_shards[0].data.shape
        assert shard_shape[2] == cfg.num_heads // 4
        # norms replicated
        assert sharded["final_norm"].sharding.spec == P()

    def test_tp_engine_matches_single_device(self, model):
        cfg, params = model
        ecfg = dict(max_batch=2, page_size=8, num_pages=32, max_pages_per_seq=8,
                    prefill_buckets=(8, 16))
        base = InferenceEngine(cfg, params, EngineConfig(**ecfg), kv_dtype=jnp.float32)
        prompt = [5, 99, 23, 4, 17, 42]
        want = base.generate(prompt, max_new_tokens=10).output_ids

        mesh = make_mesh(MeshConfig(tp=4))
        eng = InferenceEngine(cfg, params, EngineConfig(**ecfg),
                              kv_dtype=jnp.float32, mesh=mesh)
        got = eng.generate(prompt, max_new_tokens=10).output_ids
        assert got == want

    def test_dp_tp_engine_matches(self, model):
        cfg, params = model
        ecfg = dict(max_batch=4, page_size=8, num_pages=32, max_pages_per_seq=8,
                    prefill_buckets=(8, 16))
        base = InferenceEngine(cfg, params, EngineConfig(**ecfg), kv_dtype=jnp.float32)
        mesh = make_mesh(MeshConfig(dp=2, tp=4))
        eng = InferenceEngine(cfg, params, EngineConfig(**ecfg),
                              kv_dtype=jnp.float32, mesh=mesh)
        prompts = {"a": [3, 9, 27, 81], "b": [100] * 11, "c": [7, 6, 5]}
        for rid, p in prompts.items():
            base.submit(GenRequest(request_id=rid, prompt_ids=p, max_new_tokens=6))
            eng.submit(GenRequest(request_id=rid, prompt_ids=p, max_new_tokens=6))
        want = base.run_to_completion()
        got = eng.run_to_completion()
        for rid in prompts:
            assert got[rid].output_ids == want[rid].output_ids, rid

    def test_tp_engine_fused_multistep_matches(self, model):
        """Fused multi-step decode (lax.scan) on a tp mesh is token-exact
        vs the single-step path.

        Fusion engages only with >=3 active unconstrained lanes
        (engine._pick_multi_step) — a regime no other mesh test reaches —
        so this pins the fused scan's mesh behavior explicitly, and asserts
        the fused dispatch actually ran (not silently fell back to k=1).
        """
        cfg, params = model
        ecfg = dict(max_batch=4, page_size=8, num_pages=64,
                    max_pages_per_seq=8, prefill_buckets=(8, 16))
        base = InferenceEngine(cfg, params,
                               EngineConfig(**ecfg, multi_step=1),
                               kv_dtype=jnp.float32)
        mesh = make_mesh(MeshConfig(tp=4))
        eng = InferenceEngine(cfg, params,
                              EngineConfig(**ecfg, multi_step=4),
                              kv_dtype=jnp.float32, mesh=mesh)
        fused_depths = []
        orig_dispatch = eng._dispatch_multi
        eng._dispatch_multi = lambda k: (fused_depths.append(k),
                                         orig_dispatch(k))[1]
        prompts = {"a": [3, 9, 27, 81], "b": [100] * 11,
                   "c": [7, 6, 5], "d": [1, 2]}
        for rid, p in prompts.items():
            base.submit(GenRequest(request_id=rid, prompt_ids=p,
                                   max_new_tokens=16))
            eng.submit(GenRequest(request_id=rid, prompt_ids=p,
                                  max_new_tokens=16))
        want = base.run_to_completion()
        got = eng.run_to_completion()
        assert fused_depths and set(fused_depths) == {4}
        for rid in prompts:
            assert got[rid].output_ids == want[rid].output_ids, rid

    def test_kv_head_replication_when_tp_exceeds_kv(self, model):
        """A raw mesh whose tp axis exceeds Hkv still degrades to kv
        replication (the last-resort fallback callers get when they skip
        factor_tp_for_kv)."""
        cfg, params = model  # 4 kv heads
        mesh = make_mesh(MeshConfig(tp=8))  # tp > kv heads, no tq split
        specs = param_specs(cfg, mesh)
        assert specs["layers"]["wk"] == P(None, None, None, None)  # replicated kv
        assert specs["layers"]["wq"] == P(None, None, "tp", None)

    def test_grouped_gqa_specs_and_placement(self, model):
        """factor_tp_for_kv(8, Hkv=4) -> (tp=4, tq=2): q heads shard the
        full degree over ("tp","tq"), kv params shard over "tp" alone —
        each kv head lives on tq=2 chips instead of all 8."""
        cfg, params = model  # Hq=8, Hkv=4
        assert factor_tp_for_kv(8, cfg.num_kv_heads) == (4, 2)
        mesh = make_mesh(MeshConfig(tp=4, tq=2))
        specs = param_specs(cfg, mesh)
        assert specs["layers"]["wq"] == P(None, None, ("tp", "tq"), None)
        assert specs["layers"]["wk"] == P(None, None, "tp", None)
        assert specs["layers"]["wd"] == P(None, ("tp", "tq"), None)
        sharded = shard_params(params, cfg, mesh)
        # full-degree q split: 8 heads over 8 chips
        assert sharded["layers"]["wq"].addressable_shards[0].data.shape[2] == 1
        # kv split 4-ways only: 1 head per shard, replicated over tq
        assert sharded["layers"]["wk"].addressable_shards[0].data.shape[2] == 1
        assert len({
            s.device.id for s in sharded["layers"]["wk"].addressable_shards
        }) == 8

    def test_grouped_gqa_engine_matches_single_device(self, model):
        """The grouped layout (tp=4 x tq=2 over 8 devices, Hkv=4) serves
        token-exact vs the unsharded engine — the BASELINE config-5 70B
        layout (degree 16 over 8 kv heads) at test shape."""
        cfg, params = model
        ecfg = dict(max_batch=2, page_size=8, num_pages=32,
                    max_pages_per_seq=8, prefill_buckets=(8, 16))
        base = InferenceEngine(cfg, params, EngineConfig(**ecfg),
                               kv_dtype=jnp.float32)
        prompt = [5, 99, 23, 4, 17, 42]
        want = base.generate(prompt, max_new_tokens=10).output_ids

        mesh = make_mesh(MeshConfig(tp=4, tq=2))
        eng = InferenceEngine(cfg, params, EngineConfig(**ecfg),
                              kv_dtype=jnp.float32, mesh=mesh)
        got = eng.generate(prompt, max_new_tokens=10).output_ids
        assert got == want

    def test_grouped_gqa_ring_prefill_matches(self):
        """sp x tp x tq: ring chunked prefill with the grouped head split
        engaged (one kv head per shard, q heads over ("tp","tq")) is
        token-exact vs the single-device engine."""
        cfg = ModelConfig(name="par-ring-grouped", vocab_size=128,
                          hidden_size=64, intermediate_size=128,
                          num_layers=2, num_heads=8, num_kv_heads=2,
                          head_dim=8, dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(7))
        assert factor_tp_for_kv(4, cfg.num_kv_heads) == (2, 2)
        ecfg = dict(max_batch=2, page_size=8, num_pages=32,
                    max_pages_per_seq=8, prefill_buckets=(8, 16))
        prompt = [3, 17, 92, 5, 44, 8, 29, 61, 7, 12, 90, 2]  # > bucket/sp
        base = InferenceEngine(cfg, params, EngineConfig(**ecfg),
                               kv_dtype=jnp.float32)
        want = base.generate(prompt, max_new_tokens=6).output_ids
        mesh = make_mesh(MeshConfig(sp=2, tp=2, tq=2))
        eng = InferenceEngine(cfg, params, EngineConfig(**ecfg),
                              kv_dtype=jnp.float32, mesh=mesh)
        assert eng.cfg.prefill_ring
        got = eng.generate(prompt, max_new_tokens=6).output_ids
        assert got == want

    def test_grouped_ring_falls_back_with_multiple_kv_heads_per_shard(self):
        """When the kv sub-axis leaves >1 kv head per shard (gcd split,
        e.g. Hkv=6 at degree 4 -> tp=2 x tq=2, 3 heads/shard), the ring
        must NOT engage the grouped q split — ring_attention's local
        m // n_rep head map assumes one kv head per shard.  The fallback
        (q and kv both plain-"tp", replicated over tq) stays token-exact."""
        cfg = ModelConfig(name="par-ring-gcd", vocab_size=128,
                          hidden_size=96, intermediate_size=128,
                          num_layers=2, num_heads=12, num_kv_heads=6,
                          head_dim=8, dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(8))
        assert factor_tp_for_kv(4, cfg.num_kv_heads) == (2, 2)
        ecfg = dict(max_batch=2, page_size=8, num_pages=32,
                    max_pages_per_seq=8, prefill_buckets=(8, 16))
        prompt = [3, 17, 92, 5, 44, 8, 29, 61, 7, 12, 90, 2]
        base = InferenceEngine(cfg, params, EngineConfig(**ecfg),
                               kv_dtype=jnp.float32)
        want = base.generate(prompt, max_new_tokens=6).output_ids
        mesh = make_mesh(MeshConfig(sp=2, tp=2, tq=2))
        eng = InferenceEngine(cfg, params, EngineConfig(**ecfg),
                              kv_dtype=jnp.float32, mesh=mesh)
        assert eng.cfg.prefill_ring
        got = eng.generate(prompt, max_new_tokens=6).output_ids
        assert got == want

    def test_grouped_gqa_with_int8_weights_and_kv(self, model):
        """Grouped layout composed with BOTH quantization tiers: int8
        QTensor params place under tuple ("tp","tq") specs (the scale
        follows with contraction dims unsharded) and the int8 KV pool
        shards over "tp" alone.  Token-exact vs the same-quantized
        unsharded engine."""
        from kafka_tpu.models import quantize_params

        cfg, params = model
        qp = quantize_params(params, cfg)
        ecfg = dict(max_batch=2, page_size=8, num_pages=32,
                    max_pages_per_seq=8, prefill_buckets=(8, 16),
                    kv_quantize="int8")
        base = InferenceEngine(cfg, qp, EngineConfig(**ecfg),
                               kv_dtype=jnp.float32)
        prompt = [5, 99, 23, 4, 17, 42]
        want = base.generate(prompt, max_new_tokens=10).output_ids

        mesh = make_mesh(MeshConfig(tp=4, tq=2))
        eng = InferenceEngine(cfg, qp, EngineConfig(**ecfg),
                              kv_dtype=jnp.float32, mesh=mesh)
        got = eng.generate(prompt, max_new_tokens=10).output_ids
        assert got == want


class TestTensorAxisResolution:
    def test_factorization_cases(self):
        # (degree, Hkv) -> (tp, tq)
        assert factor_tp_for_kv(16, 8) == (8, 2)    # 70B BASELINE config 5
        assert factor_tp_for_kv(8, 8) == (8, 1)     # clean split
        assert factor_tp_for_kv(4, 8) == (4, 1)     # degree divides Hkv
        assert factor_tp_for_kv(4, 6) == (2, 2)     # gcd split
        assert factor_tp_for_kv(3, 8) == (1, 3)     # coprime -> replicate
        assert factor_tp_for_kv(1, 8) == (1, 1)

    def test_resolver_keeps_plain_axis_for_ulysses_and_pp(self):
        from kafka_tpu.parallel import resolve_tensor_axes

        assert resolve_tensor_axes(16, 8) == (8, 2)
        assert resolve_tensor_axes(
            16, 8, cp_strategy="ulysses", sp=4) == (16, 1)
        # ulysses WITHOUT sp is not context parallelism — grouped applies
        assert resolve_tensor_axes(
            16, 8, cp_strategy="ulysses", sp=1) == (8, 2)
        assert resolve_tensor_axes(16, 8, pp=2) == (16, 1)


class TestRingAttention:
    def _qkv(self, B=2, S=32, H=4, Hkv=2, D=16, seed=0):
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, Hkv, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, Hkv, D), jnp.float32)
        pos = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
        return q, k, v, pos

    def test_ring_matches_reference(self):
        q, k, v, pos = self._qkv()
        mesh = make_mesh(MeshConfig(sp=8))
        out = ring_attention_sharded(mesh, q, k, v, pos, pos)
        ref = causal_attention(q, k, v, q_positions=pos, kv_positions=pos)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_ring_nonzero_position_offset(self):
        # chunked-prefill style: absolute positions offset by 100
        q, k, v, pos = self._qkv(S=16)
        pos = pos + 100
        mesh = make_mesh(MeshConfig(sp=8))
        out = ring_attention_sharded(mesh, q, k, v, pos, pos)
        ref = causal_attention(q, k, v, q_positions=pos, kv_positions=pos)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_ulysses_matches_reference(self):
        q, k, v, pos = self._qkv(H=8, Hkv=4)
        mesh = make_mesh(MeshConfig(sp=8))
        out = ulysses_attention_sharded(mesh, q, k, v, pos)
        ref = causal_attention(q, k, v, q_positions=pos, kv_positions=pos)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
