"""Compaction tests: classifier, tool-pair-safe splitting, structural
validation, truncation and summarization strategies (with a fake LLM)."""

import asyncio

import pytest

from kafka_tpu.core.types import (
    CompletionResponse,
    ContextLengthError,
    LLMProviderError,
)
from kafka_tpu.llm.base import LLMProvider
from kafka_tpu.llm.compaction import (
    SummarizationCompactionProvider,
    TruncationCompactionProvider,
    find_safe_split_point,
    is_context_length_error,
    validate_message_structure,
)


def run(coro):
    return asyncio.run(coro)


class FakeLLM(LLMProvider):
    """Scripted provider for compaction tests (SURVEY §4 FakeLLMProvider)."""

    provider_name = "fake"

    def __init__(self, summary="SUMMARY", fail=False):
        self.summary = summary
        self.fail = fail
        self.calls = []

    async def stream_completion(self, messages, **kw):  # pragma: no cover
        raise NotImplementedError
        yield

    async def completion(self, messages, **kw):
        self.calls.append(messages)
        if self.fail:
            raise LLMProviderError("boom", provider="fake")
        return CompletionResponse(content=self.summary, finish_reason="stop")


def tool_call_msg(ids):
    return {
        "role": "assistant",
        "tool_calls": [
            {"id": i, "type": "function",
             "function": {"name": "t", "arguments": "{}"}}
            for i in ids
        ],
    }


def tool_result(i):
    return {"role": "tool", "tool_call_id": i, "content": "r"}


class TestClassifier:
    def test_typed_error(self):
        assert is_context_length_error(ContextLengthError(100, 50))

    @pytest.mark.parametrize("text", [
        "Error code: 400 - context_length_exceeded",
        "prompt is too long: 20000 tokens > 16384 maximum",
        "input is too long for requested model",
        "This model's maximum context length is 8192 tokens",
    ])
    def test_string_patterns(self, text):
        assert is_context_length_error(RuntimeError(text))

    def test_negative(self):
        assert not is_context_length_error(RuntimeError("rate limited"))


class TestSafeSplit:
    def test_plain_messages_split_at_target(self):
        msgs = [{"role": "user", "content": str(i)} for i in range(10)]
        assert find_safe_split_point(msgs, 5) == 5

    def test_never_orphans_tool_results(self):
        msgs = [
            {"role": "user", "content": "q"},
            tool_call_msg(["a"]),
            tool_result("a"),
            {"role": "assistant", "content": "done"},
        ]
        # target=2 would keep the result but summarize its call
        s = find_safe_split_point(msgs, 2)
        assert s <= 1
        # target=1 would split between assistant-with-calls... also unsafe
        assert find_safe_split_point(msgs, 2) in (0, 1)
        # splitting after the full pair is fine
        assert find_safe_split_point(msgs, 3) == 3

    def test_multi_result_pair(self):
        msgs = [
            tool_call_msg(["a", "b"]),
            tool_result("a"),
            tool_result("b"),
            {"role": "user", "content": "next"},
        ]
        assert find_safe_split_point(msgs, 1) == 0
        assert find_safe_split_point(msgs, 2) == 0
        assert find_safe_split_point(msgs, 3) == 3

    def test_bounds(self):
        assert find_safe_split_point([], 5) == 0
        msgs = [{"role": "user", "content": "x"}]
        assert find_safe_split_point(msgs, 99) == 1


class TestValidate:
    def test_drops_orphan_tool_results(self):
        msgs = [
            tool_result("ghost"),
            {"role": "user", "content": "hi"},
        ]
        out = validate_message_structure(msgs)
        assert [m["role"] for m in out] == ["user"]

    def test_drops_empty_assistant(self):
        msgs = [
            {"role": "user", "content": "hi"},
            {"role": "assistant", "content": None},
            {"role": "assistant", "content": "ok"},
        ]
        out = validate_message_structure(msgs)
        assert len(out) == 2

    def test_keeps_valid_pairs(self):
        msgs = [
            {"role": "user", "content": "q"},
            tool_call_msg(["a"]),
            tool_result("a"),
        ]
        assert validate_message_structure(msgs) == msgs


class TestTruncation:
    def test_keeps_system_and_tail(self):
        msgs = [{"role": "system", "content": "sys"}] + [
            {"role": "user", "content": str(i)} for i in range(100)
        ]
        out = run(TruncationCompactionProvider(keep_last=10).compact(msgs))
        assert out[0]["role"] == "system"
        assert len(out) == 11
        assert out[-1]["content"] == "99"

    def test_noop_when_short(self):
        msgs = [{"role": "user", "content": "hi"}]
        assert run(TruncationCompactionProvider().compact(msgs)) == msgs


class TestSummarization:
    def make_convo(self, n=20):
        return [{"role": "system", "content": "sys"}] + [
            {"role": "user" if i % 2 == 0 else "assistant", "content": f"m{i}"}
            for i in range(n)
        ]

    def test_summarizes_oldest_75pct(self):
        llm = FakeLLM(summary="the story so far")
        prov = SummarizationCompactionProvider(llm)
        msgs = self.make_convo(20)
        out = run(prov.compact(msgs))
        # structure: original system, summary system, kept tail
        assert out[0]["content"] == "sys"
        assert "the story so far" in out[1]["content"][0]["text"]
        assert out[1]["content"][0]["cache_control"] == {"type": "ephemeral"}
        # kept 25% of 20 = 5 messages
        assert len(out) == 2 + 5
        assert out[-1]["content"] == "m19"
        assert len(llm.calls) == 1

    def test_fallback_on_llm_failure(self):
        prov = SummarizationCompactionProvider(FakeLLM(fail=True))
        msgs = self.make_convo(20)
        out = run(prov.compact(msgs))
        # truncation fallback keeps system + tail, no summary message
        assert out[0]["content"] == "sys"
        assert all(not isinstance(m.get("content"), list) for m in out)

    def test_short_conversation_falls_back(self):
        llm = FakeLLM()
        prov = SummarizationCompactionProvider(llm, min_messages=10)
        msgs = self.make_convo(4)
        out = run(prov.compact(msgs))
        assert llm.calls == []  # no summarization attempted
        assert len(out) == len(msgs)

    def test_tool_pairs_survive(self):
        llm = FakeLLM()
        prov = SummarizationCompactionProvider(llm)
        msgs = [{"role": "system", "content": "sys"}]
        for i in range(8):
            msgs.append({"role": "user", "content": f"q{i}"})
            msgs.append(tool_call_msg([f"c{i}"]))
            msgs.append(tool_result(f"c{i}"))
        out = run(prov.compact(msgs))
        # no orphan tool message anywhere in the output
        open_ids = set()
        for m in out:
            if m.get("role") == "assistant" and m.get("tool_calls"):
                open_ids = {tc["id"] for tc in m["tool_calls"]}
            elif m.get("role") == "tool":
                assert m["tool_call_id"] in open_ids
