"""Pallas kernel numerics: each kernel vs the XLA reference formulation.

Kernels run in interpret mode here (CPU); on TPU the same code compiles to
Mosaic. The reference is ops.attention.causal_attention driven exactly the
way the engine's decode step drives it (PagedView index plan).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.ops.attention import causal_attention
from kafka_tpu.ops.pallas import paged_decode_attention


def make_paged_case(seed, B, P, ps, Hq, Hkv, D, num_pages):
    """Random paged layout: each sequence owns a random page list."""
    rng = np.random.RandomState(seed)
    total = num_pages * ps
    k_pool = rng.randn(total, Hkv, D).astype(np.float32)
    v_pool = rng.randn(total, Hkv, D).astype(np.float32)
    q = rng.randn(B, Hq, D).astype(np.float32)
    # distinct physical pages per sequence (page 0 = trash)
    free = list(range(1, num_pages))
    rng.shuffle(free)
    table = np.zeros((B, P), np.int32)
    seq_lens = rng.randint(1, P * ps - 1, size=B).astype(np.int32)
    for b in range(B):
        need = int(np.ceil((seq_lens[b] + 1) / ps))
        for i in range(need):
            table[b, i] = free.pop()
    return q, k_pool, v_pool, table, seq_lens


def xla_reference(q, k_pool, v_pool, table, seq_lens, ps):
    """Drive causal_attention through the same index plan the engine builds."""
    B, P = table.shape
    C = P * ps
    read_idx = (table[:, :, None] * ps + np.arange(ps)[None, None, :]).reshape(B, C)
    kv_positions = np.broadcast_to(np.arange(C)[None, :], (B, C))
    kv_valid = kv_positions <= seq_lens[:, None]
    k_win = jnp.asarray(k_pool)[jnp.asarray(read_idx)]  # [B, C, Hkv, D]
    v_win = jnp.asarray(v_pool)[jnp.asarray(read_idx)]
    out = causal_attention(
        jnp.asarray(q)[:, None],  # [B, 1, Hq, D]
        k_win,
        v_win,
        q_positions=jnp.asarray(seq_lens)[:, None],
        kv_positions=jnp.asarray(kv_positions),
        kv_valid=jnp.asarray(kv_valid),
    )
    return np.asarray(out[:, 0])  # [B, Hq, D]


class TestPagedDecodeAttention:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_matches_xla_gather_path(self, seed):
        q, k_pool, v_pool, table, seq_lens, = make_paged_case(
            seed, B=4, P=6, ps=8, Hq=8, Hkv=4, D=32, num_pages=32
        )
        ref = xla_reference(q, k_pool, v_pool, table, seq_lens, ps=8)
        out = paged_decode_attention(
            jnp.asarray(q),
            jnp.asarray(k_pool).reshape(k_pool.shape[0], -1),
            jnp.asarray(v_pool).reshape(v_pool.shape[0], -1),
            jnp.asarray(table), jnp.asarray(seq_lens),
            page_size=8, interpret=True,
        )
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)

    def test_mqa_single_kv_head(self):
        q, k_pool, v_pool, table, seq_lens = make_paged_case(
            7, B=2, P=4, ps=8, Hq=4, Hkv=1, D=16, num_pages=16
        )
        ref = xla_reference(q, k_pool, v_pool, table, seq_lens, ps=8)
        out = paged_decode_attention(
            jnp.asarray(q),
            jnp.asarray(k_pool).reshape(k_pool.shape[0], -1),
            jnp.asarray(v_pool).reshape(v_pool.shape[0], -1),
            jnp.asarray(table), jnp.asarray(seq_lens),
            page_size=8, interpret=True,
        )
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)

    def test_single_token_sequence(self):
        """seq_len=0: only the freshly written slot is attended."""
        q, k_pool, v_pool, table, _ = make_paged_case(
            3, B=2, P=4, ps=8, Hq=4, Hkv=2, D=16, num_pages=16
        )
        seq_lens = np.zeros(2, np.int32)
        ref = xla_reference(q, k_pool, v_pool, table, seq_lens, ps=8)
        out = paged_decode_attention(
            jnp.asarray(q),
            jnp.asarray(k_pool).reshape(k_pool.shape[0], -1),
            jnp.asarray(v_pool).reshape(v_pool.shape[0], -1),
            jnp.asarray(table), jnp.asarray(seq_lens),
            page_size=8, interpret=True,
        )
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("seed", [0, 4])
    def test_int8_kernel_matches_dequantized_reference(self, seed):
        """paged_decode_attention_int8 (int8 page DMAs + fused per-slot
        dequant) == the XLA path on the explicitly dequantized window:
        score[h,j] = (qx . k_q^T)[h,j] * s_k[j] and pexp * s_v must equal
        attention over q*s exactly (up to f32 associativity)."""
        from kafka_tpu.models.quant import quantize_array
        from kafka_tpu.ops.pallas import paged_decode_attention_int8

        q, k_pool, v_pool, table, seq_lens = make_paged_case(
            seed, B=4, P=6, ps=8, Hq=8, Hkv=4, D=32, num_pages=32
        )
        HD = k_pool.shape[1] * k_pool.shape[2]
        kq = quantize_array(jnp.asarray(k_pool).reshape(-1, HD), (1,))
        vq = quantize_array(jnp.asarray(v_pool).reshape(-1, HD), (1,))
        # reference attends the DEQUANTIZED values — the kernel's fused
        # scale application must match it, not the original f32 pool
        kd = np.asarray(kq.q, np.float32).reshape(k_pool.shape) * \
            np.asarray(kq.s)[:, None]
        vd = np.asarray(vq.q, np.float32).reshape(v_pool.shape) * \
            np.asarray(vq.s)[:, None]
        ref = xla_reference(q, kd, vd, table, seq_lens, ps=8)
        out = paged_decode_attention_int8(
            jnp.asarray(q), kq.q, kq.s, vq.q, vq.s,
            jnp.asarray(table), jnp.asarray(seq_lens),
            page_size=8, interpret=True,
        )
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)

    def test_bf16_pools(self):
        q, k_pool, v_pool, table, seq_lens = make_paged_case(
            11, B=2, P=4, ps=8, Hq=8, Hkv=4, D=32, num_pages=16
        )
        out = paged_decode_attention(
            jnp.asarray(q, jnp.bfloat16),
            jnp.asarray(k_pool, jnp.bfloat16).reshape(k_pool.shape[0], -1),
            jnp.asarray(v_pool, jnp.bfloat16).reshape(v_pool.shape[0], -1),
            jnp.asarray(table), jnp.asarray(seq_lens),
            page_size=8, interpret=True,
        )
        ref = xla_reference(
            q.astype(np.float32), k_pool, v_pool, table, seq_lens, ps=8
        )
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), ref, atol=0.05, rtol=0.05
        )


class TestEnginePallasBackend:
    def test_engine_end_to_end_pallas_interpret(self):
        """Forced-pallas engine (interpret off-TPU) matches the XLA engine
        token-for-token at f32 — covers both kernels through the real
        prefill/decode scheduler."""
        from kafka_tpu.models import ModelConfig, init_params
        from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine

        cfg = ModelConfig(name="pallas-e2e", vocab_size=128, hidden_size=64,
                          intermediate_size=128, num_layers=2, num_heads=8,
                          num_kv_heads=2, head_dim=16, dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(13))
        prompt = list(np.random.RandomState(2).randint(1, 128, size=21))
        outs = {}
        for backend in ("xla", "pallas"):
            eng = InferenceEngine(
                cfg, params,
                EngineConfig(max_batch=2, page_size=16, num_pages=32,
                             max_pages_per_seq=8, prefill_buckets=(16,),
                             attention_backend=backend),
                kv_dtype=jnp.float32,
            )
            outs[backend] = eng.generate(prompt, max_new_tokens=6).output_ids
        assert outs["pallas"] == outs["xla"]

    @pytest.mark.skipif(len(jax.devices()) < 8,
                        reason="needs 8 virtual devices")
    @pytest.mark.parametrize("mesh_axes", [
        {"tp": 2},            # plain Megatron split (1 kv head/shard)
        {"tp": 2, "tq": 2},   # grouped GQA (q over tp*tq, kv over tp)
    ])
    def test_engine_pallas_on_mesh_matches_xla(self, mesh_axes):
        """Forced-pallas engine ON A MESH (decode kernel per-shard via
        shard_map, prefill on the XLA path) is token-exact vs the forced-
        xla mesh engine AND the single-device engine — the capability
        GSPMD alone cannot provide (it cannot partition a custom call).

        Runs in a child interpreter: shard_map-wrapped interpret-mode
        kernels destabilize the shared test process (tests/_isolation.py).
        """
        from _isolation import isolated

        pid = "mesh_axes1" if "tq" in mesh_axes else "mesh_axes0"
        if not isolated(
            "tests/test_pallas_kernels.py::TestEnginePallasBackend::"
            f"test_engine_pallas_on_mesh_matches_xla[{pid}]"
        ):
            return
        from kafka_tpu.models import ModelConfig, init_params
        from kafka_tpu.parallel import MeshConfig, make_mesh
        from kafka_tpu.runtime import EngineConfig, InferenceEngine

        cfg = ModelConfig(name="pallas-mesh", vocab_size=128,
                          hidden_size=64, intermediate_size=128,
                          num_layers=2, num_heads=8, num_kv_heads=2,
                          head_dim=16, dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(13))
        prompt = list(np.random.RandomState(3).randint(1, 128, size=21))
        ecfg = dict(max_batch=2, page_size=16, num_pages=32,
                    max_pages_per_seq=8, prefill_buckets=(16,))
        want = InferenceEngine(
            cfg, params, EngineConfig(**ecfg), kv_dtype=jnp.float32
        ).generate(prompt, max_new_tokens=6).output_ids
        outs = {}
        for backend in ("xla", "pallas"):
            eng = InferenceEngine(
                cfg, params,
                EngineConfig(**ecfg, attention_backend=backend),
                kv_dtype=jnp.float32,
                mesh=make_mesh(MeshConfig(**mesh_axes)),
            )
            outs[backend] = eng.generate(prompt, max_new_tokens=6).output_ids
        assert outs["pallas"] == outs["xla"] == want

    def test_pallas_mesh_ok_gates(self):
        from kafka_tpu.ops.pallas import pallas_mesh_ok
        from kafka_tpu.parallel import MeshConfig, make_mesh

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        # plain tp over dividing kv heads: ok at any local kv count
        assert pallas_mesh_ok(make_mesh(MeshConfig(tp=2)), 8, 4)
        assert pallas_mesh_ok(make_mesh(MeshConfig(tp=2)), 8, 2)
        # grouped: exactly one kv head per shard required
        assert pallas_mesh_ok(make_mesh(MeshConfig(tp=2, tq=2)), 8, 2)
        assert not pallas_mesh_ok(make_mesh(MeshConfig(tp=2, tq=2)), 8, 4)
        # tp must divide kv heads
        assert not pallas_mesh_ok(make_mesh(MeshConfig(tp=4)), 8, 2)
        # non-tensor axes exclude the per-shard kernel
        assert not pallas_mesh_ok(make_mesh(MeshConfig(sp=2, tp=2)), 8, 2)
        assert not pallas_mesh_ok(make_mesh(MeshConfig(dp=2, tp=2)), 8, 2)

    @pytest.mark.skipif(len(jax.devices()) < 8,
                        reason="needs 8 virtual devices")
    def test_explicit_pallas_on_bad_mesh_raises(self):
        from kafka_tpu.models import ModelConfig, init_params
        from kafka_tpu.parallel import MeshConfig, make_mesh
        from kafka_tpu.runtime import EngineConfig, InferenceEngine

        cfg = ModelConfig(name="pallas-badmesh", vocab_size=128,
                          hidden_size=64, intermediate_size=128,
                          num_layers=2, num_heads=8, num_kv_heads=2,
                          head_dim=16, dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(13))
        with pytest.raises(ValueError, match="pure tp"):
            InferenceEngine(
                cfg, params,
                EngineConfig(max_batch=2, page_size=16, num_pages=32,
                             max_pages_per_seq=8, prefill_buckets=(16,),
                             attention_backend="pallas"),
                kv_dtype=jnp.float32,
                mesh=make_mesh(MeshConfig(tp=4)),  # 4 !| 2 kv heads
            )

    @pytest.mark.skipif(len(jax.devices()) < 8,
                        reason="needs 8 virtual devices")
    def test_engine_pallas_mesh_fused_multistep_matches(self):
        """The serving default wraps the decode body in a fused lax.scan
        (multi_step) — the per-shard pallas kernel must compose with the
        scan on a mesh.  Token-exact vs the single-step xla mesh engine,
        and the fused dispatch must actually engage.  Child-isolated
        (tests/_isolation.py)."""
        from _isolation import isolated

        if not isolated(
            "tests/test_pallas_kernels.py::TestEnginePallasBackend::"
            "test_engine_pallas_mesh_fused_multistep_matches"
        ):
            return
        from kafka_tpu.models import ModelConfig, init_params
        from kafka_tpu.parallel import MeshConfig, make_mesh
        from kafka_tpu.runtime import (
            EngineConfig, GenRequest, InferenceEngine,
        )

        cfg = ModelConfig(name="pallas-mesh-fused", vocab_size=128,
                          hidden_size=64, intermediate_size=128,
                          num_layers=2, num_heads=8, num_kv_heads=2,
                          head_dim=16, dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(13))
        ecfg = dict(max_batch=4, page_size=16, num_pages=64,
                    max_pages_per_seq=8, prefill_buckets=(16,))
        base = InferenceEngine(
            cfg, params, EngineConfig(**ecfg, multi_step=1),
            kv_dtype=jnp.float32,
        )
        eng = InferenceEngine(
            cfg, params,
            EngineConfig(**ecfg, multi_step=4, attention_backend="pallas"),
            kv_dtype=jnp.float32,
            mesh=make_mesh(MeshConfig(tp=2, tq=2)),
        )
        fused = []
        orig = eng._dispatch_multi
        eng._dispatch_multi = lambda k: (fused.append(k), orig(k))[1]
        prompts = {"a": [3, 9, 27, 81], "b": [100] * 11,
                   "c": [7, 6, 5], "d": [1, 2]}
        for rid, p in prompts.items():
            base.submit(GenRequest(request_id=rid, prompt_ids=p,
                                   max_new_tokens=12))
            eng.submit(GenRequest(request_id=rid, prompt_ids=p,
                                  max_new_tokens=12))
        want = base.run_to_completion()
        got = eng.run_to_completion()
        assert fused and set(fused) == {4}
        for rid in prompts:
            assert got[rid].output_ids == want[rid].output_ids, rid


class TestPagedVerifyAttention:
    """K+1-query speculative-verify kernel (ISSUE 5) vs the XLA reference
    driven with per-query causal masking — interpret mode on CPU."""

    def _case(self, seed, B=3, P=6, ps=8, Hq=4, Hkv=2, D=16, num_pages=24,
              S=4):
        rng = np.random.RandomState(seed)
        total = num_pages * ps
        k_pool = rng.randn(total, Hkv * D).astype(np.float32)
        v_pool = rng.randn(total, Hkv * D).astype(np.float32)
        q = rng.randn(B, S, Hq, D).astype(np.float32)
        free = list(range(1, num_pages))
        rng.shuffle(free)
        table = np.zeros((B, P), np.int32)
        # leave room for the S fresh positions inside the table
        seq_lens = rng.randint(1, P * ps - S - 1, size=B).astype(np.int32)
        q_lens = rng.randint(1, S + 1, size=B).astype(np.int32)
        for b in range(B):
            need = int(np.ceil((seq_lens[b] + S + 1) / ps))
            for i in range(need):
                table[b, i] = free.pop()
        return q, k_pool, v_pool, table, seq_lens, q_lens

    def _xla_reference(self, q, k_pool, v_pool, table, seq_lens, q_lens, ps):
        B, S = q.shape[:2]
        P = table.shape[1]
        C = P * ps
        D = q.shape[-1]
        Hkv = k_pool.shape[1] // D
        read_idx = (
            table[:, :, None] * ps + np.arange(ps)[None, None, :]
        ).reshape(B, C)
        kv_positions = np.broadcast_to(np.arange(C)[None, :], (B, C))
        kv_valid = kv_positions <= (seq_lens + q_lens - 1)[:, None]
        k_win = jnp.asarray(k_pool)[jnp.asarray(read_idx)].reshape(
            B, C, Hkv, D)
        v_win = jnp.asarray(v_pool)[jnp.asarray(read_idx)].reshape(
            B, C, Hkv, D)
        pos = seq_lens[:, None] + np.arange(S)[None, :]
        out = causal_attention(
            jnp.asarray(q),  # [B, S, Hq, D]
            k_win, v_win,
            q_positions=jnp.asarray(pos),
            kv_positions=jnp.asarray(kv_positions),
            kv_valid=jnp.asarray(kv_valid),
        )
        return np.asarray(out)  # [B, S, Hq, D]

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_matches_xla_per_query_causal(self, seed):
        from kafka_tpu.ops.pallas import paged_verify_attention

        ps = 8
        q, k_pool, v_pool, table, seq_lens, q_lens = self._case(seed, ps=ps)
        # materialize the S fresh positions' KV like the engine does
        # (writes happen before the kernel reads)
        out = paged_verify_attention(
            jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(table), jnp.asarray(seq_lens),
            jnp.asarray(q_lens), page_size=ps, interpret=True,
        )
        ref = self._xla_reference(q, k_pool, v_pool, table, seq_lens,
                                  q_lens, ps)
        S = q.shape[1]
        for b in range(q.shape[0]):
            # only the q_lens[b] valid query rows carry a contract
            valid = int(q_lens[b])
            np.testing.assert_allclose(
                np.asarray(out)[b, :valid], ref[b, :valid],
                rtol=2e-4, atol=2e-4,
            )

    def test_engine_end_to_end_pallas_speculative(self):
        """Forced-pallas engine WITH speculation (verify kernel in
        interpret mode) matches the XLA speculative engine and the plain
        non-speculative engine token-for-token."""
        from kafka_tpu.models import ModelConfig, init_params
        from kafka_tpu.runtime import EngineConfig, InferenceEngine

        cfg = ModelConfig(name="pallas-spec", vocab_size=128,
                          hidden_size=64, intermediate_size=128,
                          num_layers=2, num_heads=8, num_kv_heads=2,
                          head_dim=16, dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(13))
        prompt = list(np.random.RandomState(5).randint(1, 128, size=15))
        outs = {}
        engines = {}
        for backend, k in (("xla", 0), ("xla", 4), ("pallas", 4)):
            eng = InferenceEngine(
                cfg, params,
                EngineConfig(max_batch=2, page_size=16, num_pages=32,
                             max_pages_per_seq=8, prefill_buckets=(16,),
                             attention_backend=backend, speculative_k=k),
                kv_dtype=jnp.float32,
            )
            outs[(backend, k)] = eng.generate(
                prompt, max_new_tokens=16).output_ids
            engines[(backend, k)] = eng
        assert outs[("xla", 4)] == outs[("xla", 0)]
        assert outs[("pallas", 4)] == outs[("xla", 0)]
        # the pallas run must have actually exercised the verify kernel
        assert engines[("pallas", 4)].metrics.speculation_verify_steps > 0
