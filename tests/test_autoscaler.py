"""Autoscaler control loop (ISSUE 13): decision-table unit matrix,
degradation-ladder actuation, chaos e2e (error storm -> quarantine ->
hold-then-act, token-exact streams across controller rebuilds),
KAFKA_TPU_AUTOSCALE=0 bit-identity, metric registry, sim + bench smoke."""

import dataclasses
import json
import os
import subprocess
import sys
import time
from types import SimpleNamespace

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.models import ModelConfig, init_params
from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine
from kafka_tpu.runtime import failpoints
from kafka_tpu.runtime.autoscaler import (
    DEGRADE,
    HOLD,
    LADDER_MAX,
    LADDER_RUNGS,
    RECOVER,
    SCALE_IN,
    SCALE_OUT,
    AutoscalerConfig,
    AutoscalerController,
    ControllerState,
    DegradationLadder,
    background_deferred,
    decide,
    parse_mode,
    set_background_deferred,
)
from kafka_tpu.runtime.dp_router import DataParallelEngines
from kafka_tpu.runtime.metrics import (
    AUTOSCALER_METRIC_KEYS,
    EngineMetrics,
    configure_slo,
)


# ---------------------------------------------------------------------------
# synthetic signals snapshots (the /admin/signals v4 shape)
# ---------------------------------------------------------------------------


def sig(dp=1, attain=1.0, wr=10, depth=0, trend=0.0, occ=0.5, mfu=0.3,
        anomalies=0, states=None, pools=None, draining=False):
    # defaults describe a HEALTHY BUSY fleet (occupancy/MFU above the
    # idle thresholds) so "steady" means steady, not idle-pending
    states = states if states is not None else ["healthy"] * dp
    snap = {
        "version": 4,
        "dp": dp,
        "slo": {"slo_attainment_1m": attain, "window_1m_requests": wr},
        "queue": {"depth": depth, "trend_per_s": trend, "peak": depth},
        "batch": {"occupancy_frac": occ, "active": 0, "max_batch": 8},
        "utilization": {
            "decode": {"mfu_1m": mfu, "hbm_bw_util_1m": mfu},
        },
        "anomalies": {"anomalies_active": anomalies},
        "replicas": [
            {"replica": i, "state": s} for i, s in enumerate(states)
        ],
        "pools": pools or [],
    }
    if draining:
        snap["draining"] = True
    return snap


def cfg_(**over):
    base = AutoscalerConfig(
        mode="recommend", interval_s=1.0, min_dp=1, max_dp=4,
        attain_out=0.9, attain_in=0.98, trend_out=0.5,
        idle_occupancy=0.25, idle_mfu=0.05,
        sustain_out=2, sustain_in=3, sustain_recover=2,
        cooldown_out_s=10.0, cooldown_in_s=20.0, ladder_cooldown_s=5.0,
        min_window_requests=3,
    )
    return dataclasses.replace(base, **over)


class TestDecisionTable:
    """The pure matrix: synthetic snapshots -> expected action/veto, no
    engine needed (the chaos e2e below exercises the same function
    against live signals)."""

    def test_steady_holds(self):
        st = ControllerState()
        d = decide(sig(), st, cfg_(), 0.0)
        assert d.action == HOLD and d.cause == "steady"
        assert not d.vetoes

    def test_attainment_collapse_scales_out_after_sustain(self):
        st, c = ControllerState(), cfg_()
        d1 = decide(sig(attain=0.5, depth=4), st, c, 0.0)
        assert d1.action == HOLD and d1.cause == "overload_pending"
        d2 = decide(sig(attain=0.5, depth=4), st, c, 1.0)
        assert d2.action == SCALE_OUT
        assert d2.cause == "attainment_collapse"
        assert d2.dp_target == 2 and d2.roles_target is None

    def test_low_attainment_needs_window_samples(self):
        st, c = ControllerState(), cfg_()
        for t in range(4):
            d = decide(sig(attain=0.0, wr=2), st, c, float(t))
            assert d.action == HOLD, "2 verdicts must not trigger a resize"
        # a v3 feed without the field is trusted (None = unknown)
        st2 = ControllerState()
        snap = sig(attain=0.5)
        del snap["slo"]["window_1m_requests"]
        decide(snap, st2, c, 0.0)
        d = decide(snap, st2, c, 1.0)
        assert d.action == SCALE_OUT

    def test_queue_growth_scales_out(self):
        st, c = ControllerState(), cfg_()
        decide(sig(depth=8, trend=2.0), st, c, 0.0)
        d = decide(sig(depth=12, trend=2.0), st, c, 1.0)
        assert d.action == SCALE_OUT and d.cause == "queue_growth"

    def test_anomaly_vetoes_every_action_then_acts(self):
        st, c = ControllerState(), cfg_()
        decide(sig(attain=0.2, anomalies=1), st, c, 0.0)
        d = decide(sig(attain=0.2, anomalies=1), st, c, 1.0)
        assert d.action == HOLD
        assert d.intended == SCALE_OUT
        assert "anomaly_active" in d.vetoes
        # evidence survives the veto: the first clean poll acts
        d = decide(sig(attain=0.2, anomalies=0), st, c, 2.0)
        assert d.action == SCALE_OUT

    def test_probation_vetoes_resizes_only(self):
        st, c = ControllerState(), cfg_()
        states = ["healthy", "probation"]
        decide(sig(dp=2, attain=0.2, states=states), st, c, 0.0)
        d = decide(sig(dp=2, attain=0.2, states=states), st, c, 1.0)
        assert d.action == HOLD and "replica_probation" in d.vetoes
        # ladder moves are NOT probation-vetoed (all-quarantined storms
        # force-probate — the ladder must still be reachable)
        st2, c2 = ControllerState(), cfg_(max_dp=2)
        decide(sig(dp=2, attain=0.2, states=states), st2, c2, 0.0)
        d = decide(sig(dp=2, attain=0.2, states=states), st2, c2, 1.0)
        assert d.action == DEGRADE and d.ladder_target == 1

    def test_draining_vetoes(self):
        st, c = ControllerState(), cfg_()
        decide(sig(attain=0.2, draining=True), st, c, 0.0)
        d = decide(sig(attain=0.2, draining=True), st, c, 1.0)
        assert d.action == HOLD and "draining" in d.vetoes

    def test_capped_descends_ladder_in_order_then_saturates(self):
        """At max dp the overload response is the ladder, one rung per
        cooldown window, in the documented order."""
        c = cfg_(max_dp=1, ladder_cooldown_s=5.0)
        ctl = AutoscalerController(provider=None, cfg=c)
        now = 0.0
        rungs = []
        for _ in range(40):
            d = ctl.poll_once(now=now, snap=sig(attain=0.2, depth=4))
            if d.action == DEGRADE:
                rungs.append(d.ladder_target)
            now += 2.0
            if ctl.state.ladder == LADDER_MAX and d.cause == "saturated":
                break
        assert rungs == [1, 2, 3]
        assert ctl.state.ladder == LADDER_MAX
        # at the floor: no further action, cause says so
        d = ctl.poll_once(now=now + 10, snap=sig(attain=0.2, depth=4))
        assert d.action == HOLD and d.cause == "saturated"

    def test_ladder_climbs_back_in_reverse_on_recovery(self):
        c = cfg_(max_dp=1, ladder_cooldown_s=1.0, sustain_recover=2)
        ctl = AutoscalerController(provider=None, cfg=c)
        now = 0.0
        while ctl.state.ladder < LADDER_MAX:
            ctl.poll_once(now=now, snap=sig(attain=0.2, depth=4))
            now += 2.0
        climbs = []
        for _ in range(40):
            d = ctl.poll_once(now=now, snap=sig(attain=1.0))
            if d.action == RECOVER:
                climbs.append(d.ladder_target)
            now += 2.0
            if ctl.state.ladder == 0:
                break
        assert climbs == [2, 1, 0]
        assert ctl.counters["autoscaler_recovers"] == 3

    def test_all_quarantined_goes_to_ladder_not_resize(self):
        st, c = ControllerState(), cfg_()  # dp < max_dp: room to grow
        states = ["quarantined", "quarantined"]
        decide(sig(dp=2, attain=0.2, states=states), st, c, 0.0)
        d = decide(sig(dp=2, attain=0.2, states=states), st, c, 1.0)
        assert d.action == DEGRADE
        assert "all_quarantined" in d.cause

    def test_idle_scale_in_after_long_sustain(self):
        st, c = ControllerState(), cfg_()
        idle = sig(dp=3, attain=1.0, occ=0.05, mfu=0.01)
        d = None
        for t in range(3):
            d = decide(idle, st, c, float(t))
        assert d.action == SCALE_IN and d.dp_target == 2
        assert d.cause == "idle"

    def test_scale_in_not_below_min_dp(self):
        st, c = ControllerState(), cfg_(min_dp=1)
        for t in range(6):
            d = decide(sig(dp=1, attain=1.0), st, c, float(t))
            assert d.action == HOLD

    def test_busy_device_blocks_scale_in(self):
        st, c = ControllerState(), cfg_()
        for t in range(6):
            d = decide(sig(dp=2, attain=1.0, mfu=0.6), st, c, float(t))
            assert d.action == HOLD, "high MFU is not idle"

    def test_cooldown_allows_one_resize_per_window(self):
        c = cfg_(cooldown_out_s=10.0)
        ctl = AutoscalerController(provider=None, cfg=c)
        overload = lambda: sig(attain=0.2, depth=6)  # noqa: E731
        ctl.poll_once(now=0.0, snap=overload())
        d = ctl.poll_once(now=1.0, snap=overload())
        assert d.action == SCALE_OUT
        vetoed = 0
        for t in range(2, 10):
            d = ctl.poll_once(now=float(t), snap=overload())
            assert d.action == HOLD
            if "cooldown" in d.vetoes:
                vetoed += 1
                assert d.intended == SCALE_OUT
        assert vetoed > 0
        # window expired: the next sustained overload may act again
        d = ctl.poll_once(now=12.0, snap=overload())
        assert d.action == SCALE_OUT

    def test_pools_grow_the_pressured_pool(self):
        st, c = ControllerState(), cfg_()
        pools = [
            {"role": "prefill", "replicas": [0], "queue_depth": 6},
            {"role": "decode", "replicas": [1], "queue_depth": 0},
        ]
        decide(sig(dp=2, attain=0.2, pools=pools), st, c, 0.0)
        d = decide(sig(dp=2, attain=0.2, pools=pools), st, c, 1.0)
        assert d.action == SCALE_OUT
        assert d.dp_target == 3
        assert d.roles_target == "prefill:2,decode:1"

    def test_pools_scale_in_shrinks_cooler_pool_and_floors(self):
        st, c = ControllerState(), cfg_()
        pools = [
            {"role": "prefill", "replicas": [0, 1], "queue_depth": 0},
            {"role": "decode", "replicas": [2], "queue_depth": 1},
        ]
        d = None
        for t in range(3):
            d = decide(sig(dp=3, attain=1.0, occ=0.05, mfu=0.01,
                           pools=pools), st, c, float(t))
        assert d.action == SCALE_IN
        assert d.roles_target == "prefill:1,decode:1"
        # both pools at one replica: dp=2 is the pool floor
        st2 = ControllerState()
        floor = [
            {"role": "prefill", "replicas": [0], "queue_depth": 0},
            {"role": "decode", "replicas": [1], "queue_depth": 0},
        ]
        for t in range(6):
            d = decide(sig(dp=2, attain=1.0, occ=0.05, mfu=0.01,
                           pools=floor), st2, c, float(t))
            assert d.action == HOLD

    def test_decision_log_collapses_steady_holds(self):
        ctl = AutoscalerController(provider=None, cfg=cfg_())
        for t in range(20):
            ctl.poll_once(now=float(t), snap=sig())
        assert len(ctl.decisions) == 1
        entry = ctl.decisions[0]
        assert entry["action"] == HOLD and entry["count"] == 20

    def test_parse_mode(self):
        assert parse_mode(None) == "off"
        assert parse_mode("0") == "off"
        assert parse_mode("nonsense") == "off"
        assert parse_mode("1") == "act"
        assert parse_mode("act") == "act"
        assert parse_mode("recommend") == "recommend"
        assert parse_mode("dry-run") == "recommend"


# ---------------------------------------------------------------------------
# degradation-ladder actuation
# ---------------------------------------------------------------------------


class _FakeProvider:
    def __init__(self, engines):
        self.engines = engines

    def _replicas(self):
        return self.engines


def _fake_engines(n=2, max_waiting=40):
    ecfg = EngineConfig(max_batch=4, page_size=8, num_pages=32,
                       max_pages_per_seq=4, max_waiting=max_waiting)
    return [SimpleNamespace(ecfg=ecfg, spec_k_cap=None) for _ in range(n)]


class TestDegradationLadder:
    def teardown_method(self):
        set_background_deferred(False)

    def test_rungs_apply_and_revert_in_order(self):
        engines = _fake_engines(max_waiting=40)
        ladder = DegradationLadder(_FakeProvider(engines))
        ecfg = engines[0].ecfg
        ladder.apply(1)
        assert ecfg.max_waiting == 10
        assert engines[0].spec_k_cap is None
        assert not background_deferred()
        ladder.apply(3)
        assert all(e.spec_k_cap == 0 for e in engines)
        assert background_deferred()
        ladder.apply(0)
        assert ecfg.max_waiting == 40
        assert all(e.spec_k_cap is None for e in engines)
        assert not background_deferred()

    def test_unbounded_admission_gets_a_bound(self):
        engines = _fake_engines(n=3, max_waiting=0)
        ladder = DegradationLadder(_FakeProvider(engines))
        ladder.apply(1)
        assert engines[0].ecfg.max_waiting == 2 * 4 * 3
        ladder.apply(0)
        assert engines[0].ecfg.max_waiting == 0

    def test_reassert_restamps_fresh_engines(self):
        provider = _FakeProvider(_fake_engines())
        ladder = DegradationLadder(provider)
        ladder.apply(2)
        provider.engines = _fake_engines()  # "rebuild" swapped objects
        assert provider.engines[0].spec_k_cap is None
        ladder.reassert()
        assert all(e.spec_k_cap == 0 for e in provider.engines)
        ladder.apply(0)

    def test_kv_tier_demote_refused_while_deferred(self):
        from kafka_tpu.runtime.kv_tier import KVTierManager

        class _Shipper:
            def bytes_per_page(self):
                return 64

        mgr = KVTierManager(_Shipper(), host_budget_bytes=1 << 20,
                            page_size=8)
        set_background_deferred(True)
        assert mgr.demote([1, 2]) is None
        set_background_deferred(False)


# ---------------------------------------------------------------------------
# live-engine fixtures (chaos e2e + bit-identity)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(name="as-test", vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(3))
    return cfg, params


ECFG = dict(max_batch=2, page_size=8, num_pages=64, max_pages_per_seq=8,
            prefill_buckets=(8, 16, 32))


def _shim(router_or_engine):
    """The provider's signals surface over a bare router/engine — the
    controller consumes the REAL /admin/signals contract while the test
    drives the engines directly (single-writer: the test thread)."""
    from kafka_tpu.llm.tpu_provider import TPULLMProvider

    class _SignalShim:
        autoscaler = None

        def __init__(self, engine):
            self.engine = engine

        _replicas = TPULLMProvider._replicas
        signals = TPULLMProvider.signals

    return _SignalShim(router_or_engine)


def _prompts(n, length=9, seed=5):
    return [list(np.random.RandomState(seed + i).randint(1, 128, length))
            for i in range(n)]


@pytest.fixture
def slo_restore():
    yield
    configure_slo(None, None)


class TestControllerChaosE2E:
    def test_attainment_collapse_scales_out_token_exact(
        self, model, slo_restore
    ):
        """Acceptance core: under an attainment collapse the controller
        scales out within 3 poll intervals through the real rebuild
        seam, queued streams ride through the rebuild TOKEN-EXACT, and
        at most one resize lands per cooldown window."""
        cfg, params = model
        # an impossible TTFT target: every finished request is an SLO
        # miss, which is exactly a window-attainment collapse
        configure_slo(ttft_ms=0.0001)
        dp = DataParallelEngines(cfg, params, EngineConfig(**ECFG),
                                 dp=1, tp=1, kv_dtype=jnp.float32)
        resize_calls = []

        def resize_fn(dp_target, roles):
            assert roles is None
            dp.rebuild(dp=dp_target)
            resize_calls.append(dp_target)
            return True

        ctl = AutoscalerController(
            _shim(dp),
            cfg_(mode="act", max_dp=2, min_window_requests=1,
                 cooldown_out_s=60.0),
            resize_fn=resize_fn,
        )
        # two finished requests = two window misses -> collapse
        for i, p in enumerate(_prompts(2)):
            dp.submit(GenRequest(request_id=f"m{i}", prompt_ids=p,
                                 max_new_tokens=3))
        dp.run_to_completion()
        # queue work WITHOUT stepping: these must survive the rebuild
        queued = _prompts(4, seed=40)
        for i, p in enumerate(queued):
            dp.submit(GenRequest(request_id=f"q{i}", prompt_ids=list(p),
                                 max_new_tokens=5))
        d1 = ctl.poll_once(now=0.0)
        assert d1.action == HOLD and d1.cause == "overload_pending"
        d2 = ctl.poll_once(now=2.0)
        assert d2.action == SCALE_OUT and resize_calls == [2]
        assert len(dp.engines) == 2
        # further overload polls inside the cooldown: no second resize
        for t in (3.0, 4.0, 5.0):
            ctl.poll_once(now=t)
        assert resize_calls == [2]
        assert ctl.counters["autoscaler_scale_outs"] == 1
        # queued requests complete on the new topology, token-exact
        done = dp.run_to_completion()
        ref = InferenceEngine(cfg, params, EngineConfig(**ECFG),
                              kv_dtype=jnp.float32)
        for i, p in enumerate(queued):
            assert done[f"q{i}"].output_ids == ref.generate(
                list(p), max_new_tokens=5
            ).output_ids, f"q{i} diverged across the controller rebuild"

    def test_error_storm_quarantine_hold_then_act(
        self, model, monkeypatch, slo_restore
    ):
        """engine.step error storm -> quarantine -> the controller holds
        while a flight-recorder anomaly is active, acts once it clears
        and the replicas are healthy again, and never exceeds one resize
        per cooldown window."""
        cfg, params = model
        monkeypatch.setenv("KAFKA_TPU_ANOMALY_STALL_S", "0.05")
        ecfg = EngineConfig(**{**ECFG, "max_batch": 1, "max_parked": 0})
        dp = DataParallelEngines(
            cfg, params, ecfg, dp=2, tp=1, kv_dtype=jnp.float32,
            quarantine_threshold=2, quarantine_window_s=0.2,
            probation_steps=3, rebuild_threshold=0,
        )
        resize_calls = []

        def resize_fn(dp_target, roles):
            dp.rebuild(dp=dp_target)
            resize_calls.append(dp_target)
            return True

        ctl = AutoscalerController(
            _shim(dp),
            cfg_(mode="act", max_dp=3, min_window_requests=1,
                 sustain_out=1, cooldown_out_s=60.0,
                 sustain_in=10 ** 6),  # scale-in is not under test here
            resize_fn=resize_fn,
        )
        # error storm: both replicas trip the breaker; their requests
        # fail (= SLO misses, the attainment collapse)
        for i, p in enumerate(_prompts(4, seed=60)):
            dp.submit(GenRequest(request_id=f"s{i}", prompt_ids=p,
                                 max_new_tokens=4))
        with failpoints.armed("engine.step", "error", "storm", count=4):
            for _ in range(40):
                if not dp.has_work:
                    break
                try:
                    dp.step()
                except Exception:
                    dp.recover_from_failure()
        assert dp.supervisor.quarantines >= 1
        snap = dp.metrics.snapshot(reset_peak=False)
        assert snap["slo"]["slo_missed_requests"] >= 1

        # engineer an ACTIVE anomaly (queue stall) on replica 0: one
        # active lane, one waiting, a >stall_s gap between steps
        e = dp.engines[0]
        for i, p in enumerate(_prompts(2, seed=80)):
            e.submit(GenRequest(request_id=f"a{i}", prompt_ids=p,
                                 max_new_tokens=30))
        e.step()
        time.sleep(0.08)
        e.step()
        assert e.flight is not None
        assert e.flight.active_anomalies(), "stall detector did not fire"

        d = ctl.poll_once(now=0.0)
        assert d.action == HOLD
        assert "anomaly_active" in d.vetoes
        assert d.intended in (SCALE_OUT, DEGRADE)
        assert resize_calls == []

        # clear the anomaly (fast steps drain the queue) and finish the
        # stall lanes; then rehabilitate the replicas: quarantine windows
        # expire into probation, clean steps promote back to healthy
        while e.has_work:
            e.step()
        assert not e.flight.active_anomalies()
        time.sleep(0.45)  # both quarantine windows expire
        for i, p in enumerate(_prompts(4, seed=90)):
            dp.submit(GenRequest(request_id=f"h{i}", prompt_ids=p,
                                 max_new_tokens=6))
        for _ in range(200):
            if not dp.has_work:
                break
            dp.step()
        states = {h.state for h in dp.health}
        assert states == {"healthy"}, states

        # anomaly cleared, replicas healthy, attainment still collapsed
        # (the storm's misses sit in the 1m window): the controller acts
        d = ctl.poll_once(now=1.0)
        assert d.action == SCALE_OUT, (d.action, d.cause, d.vetoes)
        assert resize_calls == [3]
        # and holds through the rest of the cooldown window
        for t in (2.0, 3.0, 10.0, 30.0):
            ctl.poll_once(now=t)
        assert resize_calls == [3]
        assert ctl.counters["autoscaler_scale_outs"] == 1
        dp.run_to_completion()

    def test_roles_resize_through_rebuild(self, model):
        """/admin/resize roles plumbing (satellite): rebuild(roles=...)
        re-shapes the pools, validates the spec, and "" dissolves."""
        cfg, params = model
        dp = DataParallelEngines(cfg, params, EngineConfig(**ECFG),
                                 dp=2, tp=1, kv_dtype=jnp.float32)
        assert dp._prefill_pool == []
        dp.rebuild(dp=2, roles="prefill:1,decode:1")
        assert dp._prefill_pool == [0] and dp._decode_pool == [1]
        with pytest.raises(ValueError, match="names 3 replicas"):
            dp.rebuild(dp=2, roles="prefill:1,decode:2")
        with pytest.raises(ValueError, match="unknown pool role"):
            dp.rebuild(dp=2, roles="bogus:2")
        # bad spec refused up front: pools unchanged
        assert dp._prefill_pool == [0] and dp._decode_pool == [1]
        dp.rebuild(dp=3, roles="prefill:1,decode:2")
        assert dp._prefill_pool == [0] and dp._decode_pool == [1, 2]
        dp.rebuild(dp=2, roles="")
        assert dp._prefill_pool == [] and dp._decode_pool == []
        # omitting roles keeps the current spec (colocated here)
        dp.rebuild(dp=1)
        assert dp._prefill_pool == []


class TestBitIdentity:
    def test_autoscale_off_paths_byte_identical(self, model):
        """KAFKA_TPU_AUTOSCALE=0 contract: with no controller (and with
        a recommend-mode controller polling mid-serve) every dispatch
        and admission path produces byte-identical streams, and no
        engine/config knob moves."""
        cfg, params = model
        prompts = _prompts(3, length=12, seed=7)

        def run(with_controller):
            eng = InferenceEngine(cfg, params, EngineConfig(**ECFG),
                                  kv_dtype=jnp.float32)
            ctl = None
            if with_controller:
                ctl = AutoscalerController(_shim(eng),
                                           cfg_(mode="recommend"))
            reqs = [
                GenRequest(request_id=f"r{i}", prompt_ids=list(p),
                           max_new_tokens=8)
                for i, p in enumerate(prompts)
            ]
            for r in reqs:
                eng.submit(r)
            steps = 0
            while eng.has_work:
                eng.step()
                steps += 1
                if ctl is not None and steps % 3 == 0:
                    ctl.poll_once()
            return eng, ctl, {r.request_id: r.output_ids for r in reqs}

        eng_a, _, outs_a = run(False)
        eng_b, ctl, outs_b = run(True)
        assert outs_a == outs_b
        # no knob moved: the off/recommend paths left everything alone
        assert eng_b.spec_k_cap is None
        assert eng_b.ecfg.max_waiting == eng_a.ecfg.max_waiting
        assert not background_deferred()
        assert ctl is not None and ctl._seq > 0  # the loop really ran

    def test_default_config_builds_no_controller(self, monkeypatch):
        from kafka_tpu.server.config import ServingConfig

        monkeypatch.delenv("KAFKA_TPU_AUTOSCALE", raising=False)
        cfg = ServingConfig.from_env()
        assert parse_mode(cfg.autoscale) == "off"


# ---------------------------------------------------------------------------
# metric registry + prometheus exposition
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_metrics_section_matches_registry(self):
        ctl = AutoscalerController(provider=None, cfg=cfg_())
        ctl.poll_once(now=0.0, snap=sig())
        section = ctl.metrics_section()
        assert set(section) == set(AUTOSCALER_METRIC_KEYS)

    def test_prometheus_renders_registry_both_directions(self):
        import re

        from kafka_tpu.server import prometheus as prom_mod
        from kafka_tpu.server.prometheus import render_prometheus

        src = open(prom_mod.__file__.rstrip("c")).read()
        used = set(re.findall(r'"(autoscaler_[a-z_]+)"', src))
        assert used == set(AUTOSCALER_METRIC_KEYS), (
            "server/prometheus.py and AUTOSCALER_METRIC_KEYS drifted: "
            f"{used ^ set(AUTOSCALER_METRIC_KEYS)}"
        )
        import kafka_tpu.runtime.autoscaler as asc_mod

        asrc = open(asc_mod.__file__.rstrip("c")).read()
        aused = set(re.findall(r'"(autoscaler_[a-z_]+)"', asrc))
        assert aused <= set(AUTOSCALER_METRIC_KEYS)

    def test_exposition_parses(self, model):
        from kafka_tpu.server.prometheus import render_prometheus

        cfg, params = model
        eng = InferenceEngine(cfg, params, EngineConfig(**ECFG),
                              kv_dtype=jnp.float32)
        eng.generate([5, 6, 7], max_new_tokens=3)
        ctl = AutoscalerController(_shim(eng), cfg_(mode="recommend"))
        ctl.poll_once(now=0.0)
        snap = eng.metrics.snapshot(eng, reset_peak=False)
        snap["autoscaler"] = ctl.metrics_section()
        text = render_prometheus(snap)
        assert 'kafka_tpu_autoscaler_events_total{event="poll"} 1' in text
        assert "kafka_tpu_autoscaler_ladder_level 0" in text
        assert "kafka_tpu_autoscaler_dp 1" in text
        from test_prometheus import parse_exposition

        parse_exposition(text)

    def test_signals_v4_shape(self, model):
        cfg, params = model
        eng = InferenceEngine(cfg, params, EngineConfig(**ECFG),
                              kv_dtype=jnp.float32)
        shim = _shim(eng)
        snap = shim.signals()
        assert snap["version"] == 9
        assert snap["autoscaler"] is None
        assert "window_1m_requests" in snap["slo"]
        ctl = AutoscalerController(shim, cfg_(mode="recommend"))
        ctl.poll_once(now=0.0)
        snap = shim.signals()
        sec = snap["autoscaler"]
        assert sec["mode"] == "recommend"
        assert sec["ladder_rung"] == LADDER_RUNGS[0]
        assert sec["decisions_logged"] == 1
        assert set(sec["cooldown"]) == {"scale_out_remaining_s",
                                        "scale_in_remaining_s"}


# ---------------------------------------------------------------------------
# autoscale_sim smoke (satellite: decision-table drift caught in tier-1)
# ---------------------------------------------------------------------------


class TestSimSmoke:
    def test_replay_prints_decision_trace(self, tmp_path):
        snaps = [sig()] + [sig(attain=0.3, depth=6, trend=1.0)] * 4 + [
            sig(attain=1.0)
        ] * 3
        path = tmp_path / "signals.json"
        path.write_text(json.dumps(snaps))
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, os.path.join(root, "scripts",
                                          "autoscale_sim.py"),
             str(path)],
            capture_output=True, text=True, timeout=180, env=env,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        assert "scale_out" in out.stdout
        assert "attainment_collapse" in out.stdout
        assert "decision(s)" in out.stdout

    def test_replay_api_traces_ladder(self):
        cfg = cfg_(mode="recommend", max_dp=1, ladder_cooldown_s=0.5)
        ctl = AutoscalerController(provider=None, cfg=cfg)
        decisions = ctl.replay(
            [sig(attain=0.2, depth=5)] * 8, interval_s=1.0
        )
        assert any(d.action == DEGRADE for d in decisions)
        assert ctl.counters["autoscaler_degrades"] >= 1


# ---------------------------------------------------------------------------
# bench traffic-ramp smoke (acceptance: CPU smoke in tier-1)
# ---------------------------------------------------------------------------


class TestBenchSmoke:
    def test_traffic_ramp_phase_quick(self, model, slo_restore):
        import importlib.util

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "bench", os.path.join(root, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        sys.modules["bench"] = bench
        spec.loader.exec_module(bench)
        cfg, params = model
        out = bench.traffic_ramp_phase(
            cfg, params, n_warm=2, n_ramp=10, n_post=4,
            prompt_len=16, gen_len=16, page_size=8,
            poll_every_steps=4,
        )
        assert out["acted"] is True
        assert out["dp"] == {"before": 1, "after": 2}
        assert out["resizes"] == 1
        seg = out["attainment_by_segment"]
        assert seg["post_action"]["requests"] >= 1
        # recovery proof: post-action arrivals meet the target the ramp
        # blew through (asserted inside the phase too)
        assert seg["post_action"]["attainment"] > \
            seg["ramp_overload"]["attainment"]
