"""Failpoint fault-injection subsystem + chaos matrix.

The contract under test (ISSUE 1 acceptance criteria): with any failpoint
armed, every affected request still receives exactly one terminal event,
the KV-pool leak detector reports zero leaked pages, no slot is left
stuck, and the engine keeps serving new requests after recovery.  Also
covers the rule/trigger machinery itself (parse syntax, env activation,
nth/count scoping, the delay action) and the per-tier sites: sandbox.exec
degrades to a terminal error ToolEvent, db.write surfaces as an exception
without corrupting the store.
"""

import asyncio
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.llm.worker import EngineWorker
from kafka_tpu.models import ModelConfig, init_params
from kafka_tpu.runtime import (
    EngineConfig,
    FailpointError,
    GenRequest,
    InferenceEngine,
)
from kafka_tpu.runtime import failpoints as fp
from kafka_tpu.runtime.kv_cache import PagePool


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.clear()
    yield
    fp.clear()


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(name="failpoint-test", vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(7))
    return cfg, params


def make_engine(cfg, params, **kw):
    defaults = dict(max_batch=2, page_size=8, num_pages=32,
                    max_pages_per_seq=4, prefill_buckets=(8, 16, 32))
    defaults.update(kw)
    return InferenceEngine(cfg, params, EngineConfig(**defaults),
                           kv_dtype=jnp.float32)


class TestRuleMachinery:
    def test_disabled_is_noop(self):
        fp.failpoint("engine.step")  # nothing armed: must not raise

    def test_error_fires_and_clears(self):
        fp.configure("x.y", "error", "boom")
        with pytest.raises(FailpointError, match="boom"):
            fp.failpoint("x.y")
        fp.clear("x.y")
        fp.failpoint("x.y")

    def test_nth_trigger_fires_exactly_once(self):
        rule = fp.configure("x.y", "error", nth=3)
        fp.failpoint("x.y")
        fp.failpoint("x.y")
        with pytest.raises(FailpointError):
            fp.failpoint("x.y")
        fp.failpoint("x.y")  # disarmed after the nth call
        assert rule.calls == 4 and rule.fired == 1

    def test_count_caps_firings(self):
        fp.configure("x.y", "error", count=2)
        for _ in range(2):
            with pytest.raises(FailpointError):
                fp.failpoint("x.y")
        fp.failpoint("x.y")

    def test_delay_action_sleeps(self):
        fp.configure("x.y", "delay", "0.05")
        t0 = time.monotonic()
        fp.failpoint("x.y")
        assert time.monotonic() - t0 >= 0.045

    def test_parse_syntax(self):
        rules = fp.parse(
            "engine.step=error(boom):nth=3; kv.alloc=delay(0.05):count=2"
        )
        assert rules[0].site == "engine.step"
        assert rules[0].action == "error"
        assert rules[0].arg == "boom"
        assert rules[0].nth == 3
        assert rules[1].site == "kv.alloc"
        assert rules[1].action == "delay"
        assert rules[1].count == 2

    @pytest.mark.parametrize("bad", [
        "nonsense", "a.b=explode", "a.b=error(x):often=2", "a.b=error(x",
    ])
    def test_parse_rejects_bad_specs(self, bad):
        with pytest.raises(ValueError):
            fp.parse(bad)

    def test_env_activation(self):
        assert fp.load_env("x.y=error(env-armed)") == 1
        with pytest.raises(FailpointError, match="env-armed"):
            fp.failpoint("x.y")

    def test_armed_context_manager_restores(self):
        with fp.armed("x.y", "error"):
            assert fp.active_rules()
        assert not fp.active_rules()


class TestCrossProcessSpecs:
    """Serialization + inheritance machinery for PID-crossing chaos."""

    def test_parse_round_trips_all_documented_sites(self):
        spec = ";".join(f"{site}=error(x)" for site in fp.SITES)
        rules = fp.parse(spec)
        assert [r.site for r in rules] == list(fp.SITES)
        assert fp.parse(fp.format_rules(rules))[0].site == fp.SITES[0]

    def test_format_rules_round_trip(self):
        spec = ("sandbox.server.exec=error(boom):count=2;"
                "dist.step=exit(3);sandbox.boot=delay(0.05):nth=4")
        first = fp.parse(spec)
        second = fp.parse(fp.format_rules(first))
        assert [
            (r.site, r.action, r.arg, r.nth, r.count) for r in first
        ] == [
            (r.site, r.action, r.arg, r.nth, r.count) for r in second
        ]

    def test_format_rejects_unserializable_args(self):
        rule = fp.Rule(site="a.b", action="error", arg="has;semicolon")
        with pytest.raises(ValueError, match="metacharacters"):
            fp.format_rules([rule])

    def test_exit_action_parses_and_validates(self):
        (rule,) = fp.parse("dist.step=exit(7):nth=2")
        assert (rule.action, rule.arg, rule.nth) == ("exit", "7", 2)
        with pytest.raises(ValueError):
            fp.configure("a.b", "exit", "not-a-code")

    def test_subprocess_env_inherits_armed_rules(self):
        with fp.armed("sandbox.server.exec", "error", "chaos", count=1):
            env = fp.subprocess_env({"PATH": "/bin"})
            (rule,) = fp.parse(env[fp.ENV_VAR])
            assert rule.site == "sandbox.server.exec"
            assert rule.action == "error" and rule.arg == "chaos"
            assert rule.count == 1
        # disarmed parent scrubs any stale spec: no pre-armed children
        env = fp.subprocess_env({fp.ENV_VAR: "a.b=error(stale)"})
        assert fp.ENV_VAR not in env


class TestSiteRegistry:
    """Tooling satellite: every failpoint("<site>") call site in
    kafka_tpu/ must appear in the documented SITES registry (and the
    registry must not advertise sites nothing calls) — new sites cannot
    ship undocumented."""

    def _wired_sites(self):
        import pathlib
        import re

        import kafka_tpu

        root = pathlib.Path(kafka_tpu.__file__).parent
        wired = set()
        for path in root.rglob("*.py"):
            if path.name == "failpoints.py":
                continue  # the definition module, not a call site
            for site in re.findall(
                r'failpoint\(\s*["\']([^"\']+)["\']', path.read_text()
            ):
                wired.add(site)
        return wired

    def test_every_wired_site_is_documented(self):
        wired = self._wired_sites()
        undocumented = wired - set(fp.SITES)
        assert not undocumented, (
            f"failpoint sites wired but missing from SITES: {undocumented}"
        )

    def test_every_documented_site_is_wired(self):
        wired = self._wired_sites()
        dead = set(fp.SITES) - wired
        assert not dead, f"SITES documents unwired sites: {dead}"

    def test_readme_documents_every_site(self):
        import pathlib

        readme = (pathlib.Path(__file__).parent.parent / "README.md"
                  ).read_text()
        missing = [s for s in fp.SITES if f"`{s}`" not in readme]
        assert not missing, f"README missing failpoint sites: {missing}"


def run_chaos(eng, n_requests=3, max_new=3, step_cap=500):
    """Drive the engine the way EngineWorker does (step, recover on
    exception) until idle; returns {request_id: finish_reason}."""
    for i in range(n_requests):
        eng.submit(GenRequest(request_id=f"r{i}", prompt_ids=[1, 2, 3],
                              max_new_tokens=max_new))
    terminal = {}
    steps = 0
    while eng.has_work and steps < step_cap:
        steps += 1
        try:
            events = eng.step()
        except Exception:
            events = eng.recover_from_failure()
        for ev in events:
            if ev.finished:
                assert ev.request_id not in terminal, (
                    f"{ev.request_id} got TWO terminal events"
                )
                terminal[ev.request_id] = ev.finish_reason
    return terminal


def assert_invariants(eng, terminal, n_requests=3):
    # every request got exactly one terminal event (dup asserted inline)
    assert len(terminal) == n_requests, terminal
    # zero leaked pages: everything back in the free list
    assert eng.pool.free_pages == eng.pool.num_pages - 1
    # zero stuck slots, clean route/page accounting
    assert all(s is None for s in eng.slots)
    assert not eng.self_check(), eng.self_check()
    assert not eng._requests


CHAOS_MATRIX = [
    ("engine.step", 1), ("engine.step", 4), ("engine.step", 9),
    ("engine.prefill", 1), ("engine.prefill", 3),
    ("kv.alloc", 1), ("kv.alloc", 2), ("kv.alloc", 3),
]


class TestChaosMatrix:
    @pytest.mark.parametrize("site,nth", CHAOS_MATRIX)
    def test_injected_fault_preserves_invariants(self, model, site, nth):
        cfg, params = model
        eng = make_engine(cfg, params)
        with fp.armed(site, "error", nth=nth):
            terminal = run_chaos(eng)
        assert_invariants(eng, terminal)
        # the engine must keep serving after recovery
        req = eng.generate([5, 6, 7], max_new_tokens=2)
        assert req.finish_reason == "length"

    def test_step_delay_does_not_break_anything(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        with fp.armed("engine.step", "delay", "0.02", count=2):
            terminal = run_chaos(eng)
        assert_invariants(eng, terminal)
        assert all(r in ("length", "stop") for r in terminal.values())

    def test_waiting_requests_survive_recovery(self, model):
        """A step failure fails STARTED requests but queued ones are kept
        and served after recovery (improvement over fail-everything)."""
        cfg, params = model
        eng = make_engine(cfg, params, max_batch=1, max_parked=0)
        with fp.armed("engine.step", "error", nth=2):
            terminal = run_chaos(eng, n_requests=3)
        assert_invariants(eng, terminal)
        # the batch holds one request; the two queued behind it must have
        # finished normally
        normal = [r for r in terminal.values() if r == "length"]
        assert len(normal) >= 2, terminal

    def test_repeated_faults_still_converge(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        with fp.armed("engine.step", "error", count=3):
            # count=3 without nth: the first three steps all die
            terminal = run_chaos(eng)
        assert_invariants(eng, terminal)


class TestLeakDetector:
    def test_clean_pool_passes(self):
        pool = PagePool(8, 4)
        assert not pool.check_consistency()

    def test_detects_leaked_refcount(self):
        pool = PagePool(8, 4)
        pages = pool.alloc(2)
        problems = pool.reconcile({}, repair=False)
        assert len(problems) == 2 and "leaked" in problems[0]
        # repair force-releases them back to the free list
        pool.reconcile({}, repair=True)
        assert pool.free_pages == 7
        assert not pool.check_consistency()

    def test_detects_double_free(self):
        pool = PagePool(8, 4)
        pages = pool.alloc(1)
        expected = {pages[0]: 1}
        pool.release(pages)  # owner did not give its reference up
        problems = pool.reconcile(expected, repair=True)
        assert problems and "double-freed" in problems[0]
        assert int(pool.refcount[pages[0]]) == 1
        assert pages[0] not in pool._free

    def test_engine_self_check_spots_manufactured_leak(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        leaked = eng.pool.alloc(1)  # nobody owns this
        problems = eng.self_check()
        assert any("leaked" in p for p in problems)
        eng.self_check(repair=True)
        assert not eng.self_check()
        assert eng.pool.free_pages == eng.pool.num_pages - 1

    def test_self_check_respects_prefix_cache_retains(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, prefix_cache_entries=4)
        eng.submit(GenRequest(request_id="p1", prompt_ids=[1] * 9,
                              max_new_tokens=2, prefix_key="thread-1"))
        eng.run_to_completion()
        # cache holds retained pages; they are owners, not leaks
        assert len(eng.prefix_cache) == 1
        assert not eng.self_check(), eng.self_check()


class TestWorkerRecovery:
    def _collect(self, worker, events_q):
        async def go():
            got = []
            while True:
                ev = await asyncio.wait_for(events_q.get(), timeout=30)
                got.append(ev)
                if ev.finished:
                    return got
        return go

    def test_streams_get_terminal_events_through_worker(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        worker = EngineWorker(eng).start()
        try:
            with fp.armed("engine.step", "error", nth=3):
                async def go():
                    loop = asyncio.get_running_loop()
                    queues = [
                        worker.submit(
                            GenRequest(request_id=f"w{i}",
                                       prompt_ids=[1, 2, 3],
                                       max_new_tokens=4),
                            loop,
                        )
                        for i in range(3)
                    ]

                    async def drain(q):
                        reasons = []
                        while True:
                            ev = await asyncio.wait_for(q.get(), timeout=30)
                            if ev.finished:
                                return ev.finish_reason
                    return await asyncio.gather(*(drain(q) for q in queues))

                reasons = asyncio.run(go())
            # every stream terminated (error or clean), none hung
            assert len(reasons) == 3
            # engine is servable again and accounting is clean
            deadline = time.monotonic() + 10
            # quiesce on the WORKER's route table too, not just engine
            # state: the consumer observes its terminal event the moment
            # call_soon_threadsafe schedules it, a beat before the worker
            # thread reaches the route pop in _deliver — has_work alone
            # races that last beat (a genuine leak still fails at the
            # deadline)
            while (eng.has_work or worker.check_routes()) \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert not eng.self_check(), eng.self_check()
            assert not worker.check_routes()
        finally:
            worker.stop()

    def test_dispatch_fault_does_not_hang_stream(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        worker = EngineWorker(eng).start()
        try:
            with fp.armed("worker.dispatch", "error", nth=2):
                async def go():
                    loop = asyncio.get_running_loop()
                    q = worker.submit(
                        GenRequest(request_id="d1", prompt_ids=[1, 2, 3],
                                   max_new_tokens=4),
                        loop,
                    )
                    while True:
                        ev = await asyncio.wait_for(q.get(), timeout=30)
                        if ev.finished:
                            return ev.finish_reason

                reason = asyncio.run(go())
            assert reason in ("length", "stop")
        finally:
            worker.stop()

    def test_terminal_event_survives_repeated_dispatch_faults(self, model):
        """A fault that keeps firing across dispatch attempts must not
        lose the terminal event: failed terminal dispatches requeue
        through the inbox and deliver once the bounded rule expires."""
        cfg, params = model
        eng = make_engine(cfg, params)
        worker = EngineWorker(eng).start()
        try:
            with fp.armed("worker.dispatch", "error", count=4):
                async def go():
                    loop = asyncio.get_running_loop()
                    q = worker.submit(
                        GenRequest(request_id="rd1", prompt_ids=[1, 2, 3],
                                   max_new_tokens=2),
                        loop,
                    )
                    while True:
                        ev = await asyncio.wait_for(q.get(), timeout=30)
                        if ev.finished:
                            return ev.finish_reason

                reason = asyncio.run(go())
            assert reason in ("length", "stop")
            # same route-pop race as above: give the worker thread its
            # last dispatch beat before probing the table
            deadline = time.monotonic() + 10
            while worker.check_routes() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert not worker.check_routes()
        finally:
            worker.stop()

    def test_unbounded_dispatch_fault_cannot_hang_stream(self, model):
        """Even a rule that NEVER stops firing must not hang a consumer:
        after the paced retry budget, the terminal event is delivered
        with the failpoint bypassed (last-resort path)."""
        cfg, params = model
        eng = make_engine(cfg, params)
        worker = EngineWorker(eng).start()
        try:
            with fp.armed("worker.dispatch", "error"):  # unbounded
                async def go():
                    loop = asyncio.get_running_loop()
                    q = worker.submit(
                        GenRequest(request_id="ub1", prompt_ids=[1, 2, 3],
                                   max_new_tokens=2),
                        loop,
                    )
                    while True:
                        ev = await asyncio.wait_for(q.get(), timeout=60)
                        if ev.finished:
                            return ev.finish_reason

                reason = asyncio.run(go())
            assert reason in ("length", "stop")
        finally:
            worker.stop()


class TestSandboxExecSite:
    def test_injected_fault_yields_terminal_tool_error(self):
        from kafka_tpu.sandbox.local import LocalSandbox

        sbx = LocalSandbox("http://127.0.0.1:1")  # never dialed

        async def go():
            events = []
            with fp.armed("sandbox.exec", "error", "chaos"):
                async for ev in sbx.run_tool("shell_exec", {"cmd": "true"}):
                    events.append(ev)
            await sbx.aclose()
            return events

        events = asyncio.run(go())
        assert len(events) == 1
        assert events[0].kind == "error"
        assert events[0].terminal
        assert "chaos" in events[0].text()


class TestDbWriteSite:
    def test_write_fault_surfaces_and_store_survives(self, tmp_path):
        from kafka_tpu.db import LocalDBClient

        async def go():
            db = LocalDBClient(str(tmp_path / "chaos.db"))
            await db.initialize()
            with fp.armed("db.write", "error", "disk gone"):
                with pytest.raises(FailpointError):
                    await db.create_thread(thread_id="t-fault")
            # the store is intact after the fault clears
            tid = await db.create_thread(thread_id="t-ok")
            assert await db.thread_exists(tid)
            assert not await db.thread_exists("t-fault")
            await db.close()

        asyncio.run(go())

    def test_reads_not_gated_by_db_write_site(self, tmp_path):
        from kafka_tpu.db import LocalDBClient

        async def go():
            db = LocalDBClient(str(tmp_path / "reads.db"))
            await db.initialize()
            tid = await db.create_thread(thread_id="t1")
            with fp.armed("db.write", "error"):
                assert await db.thread_exists(tid)  # SELECT: unaffected
            await db.close()

        asyncio.run(go())


class TestGracefulDrainProvider:
    def test_drain_lets_inflight_finish(self, model):
        from kafka_tpu.llm import TPULLMProvider
        from kafka_tpu.models.tokenizer import ByteTokenizer

        tok = ByteTokenizer()
        cfg, params = model
        cfg = cfg.replace(vocab_size=tok.vocab_size)
        params = init_params(cfg, jax.random.PRNGKey(3))
        eng = make_engine(cfg, params, num_pages=64, max_pages_per_seq=8,
                          page_size=16)
        provider = TPULLMProvider(eng, tok, model_name="drain-test")

        async def go():
            chunks = []

            async def consume():
                async for c in provider.stream_completion(
                    [{"role": "user", "content": "hi"}], max_tokens=6
                ):
                    chunks.append(c)

            task = asyncio.create_task(consume())
            await asyncio.sleep(0.05)  # let it enter the engine
            clean = await provider.drain(timeout_s=30)
            await task
            return clean, chunks

        clean, chunks = asyncio.run(go())
        assert clean is True
        assert chunks and chunks[-1].finish_reason in ("stop", "length")
        asyncio.run(provider.aclose())

    def test_drain_timeout_cancels_leftovers(self, model):
        from kafka_tpu.llm import TPULLMProvider
        from kafka_tpu.models.tokenizer import ByteTokenizer

        tok = ByteTokenizer()
        cfg, params = model
        cfg = cfg.replace(vocab_size=tok.vocab_size)
        params = init_params(cfg, jax.random.PRNGKey(3))
        eng = make_engine(cfg, params, num_pages=64, max_pages_per_seq=8,
                          page_size=16)
        provider = TPULLMProvider(eng, tok, model_name="drain-test")

        async def go():
            loop = asyncio.get_running_loop()
            # no stop tokens + a step-delay failpoint: the request cannot
            # finish inside the drain budget, forcing the cancel sweep
            q = provider.worker.submit(
                GenRequest(request_id="slow", prompt_ids=[1, 2, 3],
                           max_new_tokens=2000),
                loop,
            )
            with fp.armed("engine.step", "delay", "0.02"):
                # wait for the worker thread to move the submit from its
                # inbox into the engine before draining
                deadline = time.monotonic() + 10
                while not eng.has_work and time.monotonic() < deadline:
                    await asyncio.sleep(0.005)
                clean = await provider.drain(timeout_s=0.2)
            while True:
                ev = await asyncio.wait_for(q.get(), timeout=30)
                if ev.finished:
                    return clean, ev.finish_reason

        clean, reason = asyncio.run(go())
        # the request could not finish inside the timeout: it was
        # cancelled, and its stream still observed a terminal event
        assert clean is False
        assert reason == "cancelled"
        asyncio.run(provider.aclose())
