"""Scheduler flight recorder (ISSUE 11).

The load-bearing claims:
  * one record per scheduler iteration lands in the ring — dispatch
    kinds, batch composition, cause codes, pressure gauges — and the
    ring wraps allocation-free at the configured size,
  * with KAFKA_TPU_FLIGHT_RING=0 no recorder is built and the dispatch
    paths produce BIT-IDENTICAL outputs to a recorder-on engine (the
    hooks are pure observation),
  * measured dispatch latency is derived from fetch-maturation timing
    and, against an env-overridden roofline, feeds the per-kind
    modeled-vs-measured skew gauge (kafka_tpu_dispatch_model_skew),
  * the anomaly detectors fire edge-triggered on queue stall / fetch
    starvation / MFU collapse / prefill convoy, increment the
    ANOMALY_METRIC_KEYS counters, and surface in /admin/signals,
  * a failpoint-killed engine and a quarantined DP replica each leave a
    readable postmortem JSON (schema asserted, file names sanitized like
    the persisted traces) whose last records explain the failing step,
  * FLIGHT/ANOMALY are both-directions registries across
    runtime/metrics.py and server/prometheus.py,
  * the bench recorder-overhead A/B phase runs.
"""

import dataclasses
import glob
import json
import os
import re
import time
from types import SimpleNamespace

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.models import ModelConfig, init_params
from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine
from kafka_tpu.runtime import failpoints
from kafka_tpu.runtime.flight_recorder import (
    ANOMALY_KINDS,
    CAUSES,
    FlightRecorder,
    list_postmortems,
    postmortem_dir,
    ring_default,
    sanitize_name,
)
from kafka_tpu.runtime.metrics import (
    ANOMALY_METRIC_KEYS,
    FLIGHT_METRIC_KEYS,
    EngineMetrics,
)


def tiny_cfg():
    return ModelConfig(
        name="flight-test", vocab_size=300, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, dtype="float32",
    )


def make_engine(params=None, cfg=None, **ecfg_kw):
    cfg = cfg or tiny_cfg()
    if params is None:
        params = init_params(cfg, jax.random.PRNGKey(0))
    kw = dict(max_batch=2, page_size=8, num_pages=64, max_pages_per_seq=8,
              prefill_buckets=(8, 16, 32), flight_ring=64)
    kw.update(ecfg_kw)
    return InferenceEngine(cfg, params, EngineConfig(**kw),
                           kv_dtype=jnp.float32)


@pytest.fixture(scope="module")
def shared():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def run_requests(engine, n=3, prompt_len=15, gen=8, seed_base=0):
    for i in range(n):
        engine.submit(GenRequest(
            request_id=f"r{seed_base}-{i}",
            prompt_ids=list(range(5, 5 + prompt_len)),
            max_new_tokens=gen,
        ))
    return engine.run_to_completion()


# ---------------------------------------------------------------------------
# recorder unit behavior
# ---------------------------------------------------------------------------


class _StubEngine:
    """Duck-typed engine for detector unit tests (injected clock)."""

    def __init__(self):
        self.waiting = []
        self.parked = []
        self.metrics = EngineMetrics()
        self._pending = []
        self._pending_steps = 0
        self.pool = SimpleNamespace(free_pages=10, num_pages=16)
        self.prefix_cache = None
        self.kv_tier = None
        self._requests = {}
        self._active = 0

    @property
    def num_active(self):
        return self._active


class TestRecorderUnit:
    def test_ring_wraps_at_size(self):
        fl = FlightRecorder(4)
        eng = _StubEngine()
        for i in range(11):
            fl.note_dispatch(2, 1, 1)
            fl.finish_step(eng, now=float(i))
        recs = fl.records()
        assert fl.next_seq == 11
        assert len(recs) == 4
        assert [r["seq"] for r in recs] == [7, 8, 9, 10]

    def test_stage_resets_between_steps(self):
        fl = FlightRecorder(8)
        eng = _StubEngine()
        fl.note_dispatch(2, 2, 2)
        fl.note_cause("admit", 2)
        fl.finish_step(eng, now=0.0)
        fl.finish_step(eng, now=1.0)
        recs = fl.records()
        assert recs[0]["lanes"] == 2 and recs[0]["causes"] == {"admit": 2}
        assert recs[1]["lanes"] == 0 and recs[1]["causes"] == {}
        assert recs[1]["gap_ms"] == pytest.approx(1000.0)

    def test_every_cause_code_round_trips(self):
        fl = FlightRecorder(4)
        eng = _StubEngine()
        for name in CAUSES:
            fl.note_cause(name)
        fl.finish_step(eng, now=0.0)
        assert fl.records()[-1]["causes"] == {name: 1 for name in CAUSES}

    def test_ring_default_env(self, monkeypatch):
        monkeypatch.setenv("KAFKA_TPU_FLIGHT_RING", "17")
        assert ring_default() == 17
        monkeypatch.setenv("KAFKA_TPU_FLIGHT_RING", "-3")
        assert ring_default() == 0
        monkeypatch.setenv("KAFKA_TPU_FLIGHT_RING", "junk")
        assert ring_default() == 256
        monkeypatch.delenv("KAFKA_TPU_FLIGHT_RING")
        assert ring_default() == 256

    def test_sanitize_name_defangs_traversal(self):
        stem = sanitize_name("../../etc/passwd")
        assert "/" not in stem and ".." not in stem.split(".")[0]
        assert re.fullmatch(r"[A-Za-z0-9._-]+\.[0-9a-f]{12}", stem)
        # distinct hostile inputs stay distinct via the digest
        assert stem != sanitize_name("../../etc/shadow")

    def test_postmortem_dir_resolution(self, monkeypatch, tmp_path):
        monkeypatch.setenv("KAFKA_TPU_FLIGHT_DIR", str(tmp_path))
        assert postmortem_dir() == str(tmp_path)
        monkeypatch.setenv("KAFKA_TPU_FLIGHT_DIR", "")  # explicit off
        assert postmortem_dir() is None


class TestDetectorsUnit:
    def _recorder(self, monkeypatch, stall="0.5"):
        monkeypatch.setenv("KAFKA_TPU_ANOMALY_STALL_S", stall)
        return FlightRecorder(16)

    def test_queue_stall_fires_and_clears(self, monkeypatch):
        fl = self._recorder(monkeypatch)
        eng = _StubEngine()
        # arm: one dispatch-bearing iteration
        fl.note_dispatch(2, 1, 1)
        fl.finish_step(eng, now=0.0)
        # queue sits undisipatched past the stall bound
        eng.waiting = [object()]
        fl.finish_step(eng, now=1.0)
        assert eng.metrics.anomaly_queue_stall == 1
        active = fl.active_anomalies()
        assert [a["kind"] for a in active] == ["queue_stall"]
        # level-holds: no double count
        fl.finish_step(eng, now=2.0)
        assert eng.metrics.anomaly_queue_stall == 1
        # a dispatch arriving AFTER a >stall gap is still part of the
        # same episode (chronic slow cadence): one edge, stays active
        fl.note_dispatch(2, 1, 1)
        fl.finish_step(eng, now=2.6)
        assert eng.metrics.anomaly_queue_stall == 1
        assert [a["kind"] for a in fl.active_anomalies()] == ["queue_stall"]
        # normal cadence resumes: the episode ends
        fl.note_dispatch(2, 1, 1)
        fl.finish_step(eng, now=2.7)
        assert fl.active_anomalies() == []
        # re-fires on the next stall (edge re-arm)
        fl.finish_step(eng, now=5.0)
        assert eng.metrics.anomaly_queue_stall == 2

    def test_chronic_slow_cadence_is_one_episode(self, monkeypatch):
        """A queue stepping every 2x the stall bound — each iteration
        dispatching — must count ONE firing and stay continuously
        active (the autoscaler's poll must see it), not fire+clear per
        iteration."""
        fl = self._recorder(monkeypatch)  # stall_s = 0.5
        eng = _StubEngine()
        eng.waiting = [object()]
        fl.note_dispatch(2, 1, 1)
        fl.finish_step(eng, now=0.0)
        for i in range(1, 6):
            fl.note_dispatch(2, 1, 1)
            fl.finish_step(eng, now=i * 1.0)
            assert [a["kind"] for a in fl.active_anomalies()] == \
                ["queue_stall"], i
        assert eng.metrics.anomaly_queue_stall == 1

    def test_gate_rejects_drain_into_ring(self, monkeypatch):
        """Gate-level 429s (event-loop thread) land in the next
        committed record's reject cause — an overload burst's ring must
        show the shed traffic the serving gate absorbed."""
        fl = self._recorder(monkeypatch)
        eng = _StubEngine()
        for _ in range(3):
            fl.note_gate_reject()
        fl.finish_step(eng, now=0.0)
        assert fl.records()[-1]["causes"] == {"reject": 3}
        fl.finish_step(eng, now=0.1)
        assert fl.records()[-1]["causes"] == {}  # drained, not re-counted

    def test_queue_stall_not_armed_before_first_dispatch(self, monkeypatch):
        fl = self._recorder(monkeypatch)
        eng = _StubEngine()
        eng.waiting = [object()]
        fl.finish_step(eng, now=100.0)  # cold start: admission, not stall
        assert eng.metrics.anomaly_queue_stall == 0

    def test_fetch_starvation(self, monkeypatch):
        fl = self._recorder(monkeypatch)
        eng = _StubEngine()
        eng._pending = [SimpleNamespace(t0=0.0)]
        fl.finish_step(eng, now=1.0)
        assert eng.metrics.anomaly_fetch_starvation == 1
        eng._pending = []
        fl.finish_step(eng, now=1.1)
        assert fl.active_anomalies() == []

    def test_prefill_convoy(self, monkeypatch):
        monkeypatch.setenv("KAFKA_TPU_ANOMALY_CONVOY_S", "0.5")
        fl = FlightRecorder(16)
        eng = _StubEngine()
        eng.waiting = [object()]
        for i, t in enumerate((0.0, 0.3, 0.6)):
            fl.note_prefill(1, 8)
            fl.finish_step(eng, now=t)
        assert eng.metrics.anomaly_prefill_convoy == 1
        # a decode dispatch breaks the convoy
        fl.note_prefill(1, 8)
        fl.note_dispatch(2, 1, 1)
        fl.finish_step(eng, now=0.9)
        assert fl.active_anomalies() == []

    def test_mfu_collapse(self, monkeypatch):
        fl = FlightRecorder(16)
        eng = _StubEngine()
        m = eng.metrics
        m.set_roofline(1e12, 1e12, "env")
        u = m.util["decode"]
        u.busy_s = 100.0
        u.flops = 50.0 * 1e12  # since-boot mfu = 0.5
        now = time.monotonic()
        # last minute: busy but nearly no flops -> mfu_1m ~ 0.005
        m._util_window["decode"].add((5e9, 0.0, 2.0), now=now)
        fl._mfu_check_t = now - 2.0  # bypass the 1 Hz throttle
        fl.finish_step(eng, now=now)
        assert m.anomaly_mfu_collapse == 1
        assert [a["kind"] for a in fl.active_anomalies()] == ["mfu_collapse"]


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------


class TestEngineIntegration:
    def test_ring_records_dispatches_and_causes(self, shared):
        cfg, params = shared
        eng = make_engine(params, cfg)
        run_requests(eng, n=3)
        recs = eng.flight.records()
        assert recs, "no flight records after a full run"
        assert recs == sorted(recs, key=lambda r: r["seq"])
        kinds = {k for r in recs for k in r["kinds"]}
        assert {"prefill", "decode"} <= kinds
        causes = {}
        for r in recs:
            for c, n in r["causes"].items():
                causes[c] = causes.get(c, 0) + n
        # 3 requests over a 2-slot batch: two slot admissions, one park
        assert causes.get("admit", 0) >= 2
        assert causes.get("park", 0) >= 1
        assert causes.get("admit_parked", 0) >= 1
        # pressure gauges are live
        assert all(r["pages_total"] == 64 for r in recs)
        # measured fetch-maturation timing landed on some records
        assert any(r["measured_ms"] > 0 for r in recs)
        # the metrics snapshot exports the ring state
        snap = eng.metrics.snapshot(eng, reset_peak=False)
        assert snap["flight"]["flight_ring_size"] == 64
        assert snap["flight"]["flight_records"] == eng.flight.next_seq > 0

    def test_preempt_cause_recorded(self, shared):
        cfg, params = shared
        # starve the pool so decode growth must preempt: 2 lanes, pages
        # for barely one window.  Ring sized to hold the WHOLE run — the
        # preempt happens early and must not wrap away before the assert.
        eng = make_engine(params, cfg, num_pages=17, max_pages_per_seq=16,
                          prefix_cache_entries=0, max_parked=0,
                          flight_ring=4096)
        for i in range(2):
            eng.submit(GenRequest(
                request_id=f"p{i}", prompt_ids=list(range(5, 60)),
                max_new_tokens=80,
            ))
        eng.run_to_completion()
        assert eng.metrics.requests_preempted > 0, "scenario lost pressure"
        causes = {}
        for r in eng.flight.records():
            for c, n in r["causes"].items():
                causes[c] = causes.get(c, 0) + n
        assert causes.get("preempt", 0) >= 1

    def test_measured_skew_with_roofline(self, shared, monkeypatch):
        cfg, params = shared
        monkeypatch.setenv("KAFKA_TPU_PEAK_TFLOPS", "0.001")
        monkeypatch.setenv("KAFKA_TPU_PEAK_HBM_GBPS", "1")
        eng = make_engine(params, cfg)
        assert eng.metrics.peak_source == "env"
        run_requests(eng, n=2, gen=12)
        util = eng.metrics.utilization_snapshot()
        dec = util["decode"]
        assert dec["measured_dispatches"] > 0
        assert dec["measured_busy_s"] > 0
        assert dec["modeled_busy_s"] > 0
        assert dec["model_skew"] > 0
        from kafka_tpu.server.prometheus import render_prometheus

        text = render_prometheus(eng.metrics.snapshot(eng))
        assert 'kafka_tpu_dispatch_model_skew{kind="decode"}' in text
        assert 'kafka_tpu_measured_dispatches_total{kind="decode"}' in text

    def test_ring_off_is_bit_identical(self, shared):
        cfg, params = shared
        outs = {}
        for ring in (0, 32):
            eng = make_engine(params, cfg, flight_ring=ring)
            if ring == 0:
                assert eng.flight is None
            done = run_requests(eng, n=3, gen=10)
            outs[ring] = {k: v.output_ids for k, v in done.items()}
        assert outs[0] == outs[32]

    def test_flight_section_absent_when_off(self, shared):
        cfg, params = shared
        eng = make_engine(params, cfg, flight_ring=0)
        snap = eng.metrics.snapshot(eng, reset_peak=False)
        assert "flight" not in snap
        # anomaly counters still export (zeros) — the registry holds
        assert snap["anomalies"]["anomalies_active"] == 0

    def test_negative_ring_rejected(self, shared):
        cfg, params = shared
        with pytest.raises(ValueError, match="flight_ring"):
            make_engine(params, cfg, flight_ring=-1)


class TestQueueStallEndToEnd:
    def test_delay_failpoint_trips_detector_and_counter(
        self, shared, monkeypatch
    ):
        """Acceptance (ISSUE 11): a synthetic queue stall — the engine
        stepping slowly while a request waits — trips the queue_stall
        detector and the kafka_tpu_anomalies_total counter."""
        cfg, params = shared
        monkeypatch.setenv("KAFKA_TPU_ANOMALY_STALL_S", "0.05")
        eng = make_engine(params, cfg, max_batch=1, max_parked=0)
        eng.submit(GenRequest(request_id="fg", prompt_ids=list(range(5, 20)),
                              max_new_tokens=60))
        # warm the decode path so the delayed iterations below measure
        # scheduling, not XLA compiles
        for _ in range(6):
            eng.step()
        eng.submit(GenRequest(request_id="queued",
                              prompt_ids=list(range(5, 20)),
                              max_new_tokens=4))
        with failpoints.armed("engine.step", "delay", "0.1", count=4):
            for _ in range(6):
                eng.step()
                if eng.metrics.anomaly_queue_stall:
                    break
        assert eng.metrics.anomaly_queue_stall >= 1
        from kafka_tpu.server.prometheus import render_prometheus

        text = render_prometheus(eng.metrics.snapshot(eng))
        m = re.search(
            r'kafka_tpu_anomalies_total\{kind="queue_stall"\} (\d+)', text
        )
        assert m and int(m.group(1)) >= 1
        # /admin/signals carries the anomaly section (version 2 contract)
        run_requests(eng, n=0)  # drain helper no-op; finish the run
        eng.run_to_completion()


# ---------------------------------------------------------------------------
# postmortem capture
# ---------------------------------------------------------------------------


POSTMORTEM_NAME_RE = re.compile(
    r"postmortem\.[A-Za-z0-9._-]+\.[0-9a-f]{12}\.flight\.json"
)


def _assert_postmortem_schema(pm):
    assert pm["version"] == 1
    assert pm["kind"] == "flight_postmortem"
    assert isinstance(pm["records"], list) and pm["records"]
    for rec in pm["records"]:
        for key in ("seq", "t", "kinds", "lanes", "toks", "queue_depth",
                    "pages_free", "causes", "measured_ms", "modeled_ms"):
            assert key in rec, key
    assert isinstance(pm["lanes"], list)
    for lane in pm["lanes"]:
        for key in ("request_id", "state", "slot", "dispatched",
                    "drained", "output_tokens"):
            assert key in lane, key
    assert set(pm["anomalies"]) == set(ANOMALY_KINDS)
    assert "requests" in pm["metrics"]


class TestPostmortem:
    def test_step_error_storm_leaves_readable_dump(
        self, shared, tmp_path, monkeypatch
    ):
        """Acceptance: a failpoint-killed engine leaves a postmortem
        whose last records explain the failing step, retrievable after
        restart (read back from disk alone)."""
        cfg, params = shared
        monkeypatch.setenv("KAFKA_TPU_FLIGHT_DIR", str(tmp_path))
        eng = make_engine(params, cfg)
        eng.submit(GenRequest(request_id="victim",
                              prompt_ids=list(range(5, 25)),
                              max_new_tokens=30))
        for _ in range(3):
            eng.step()
        with failpoints.armed("engine.step", "error", count=2):
            for _ in range(2):
                with pytest.raises(failpoints.FailpointError):
                    eng.step()
                eng.recover_from_failure()
        files = glob.glob(str(tmp_path / "*.flight.json"))
        assert files, "no postmortem written"
        for f in files:
            assert POSTMORTEM_NAME_RE.fullmatch(os.path.basename(f))
        # "after restart": nothing but the file — fresh parse from disk
        pm = json.loads(open(sorted(files)[0]).read())
        _assert_postmortem_schema(pm)
        assert pm["reason"] == "engine_failure"
        # the dump explains the pre-failure scheduling: the victim lane
        # is present and the records carry its dispatch history
        lanes = {ln["request_id"]: ln for ln in pm["lanes"]}
        assert "victim" in lanes
        assert lanes["victim"]["dispatched"] > 0
        assert any(r["kinds"] for r in pm["records"])
        assert list_postmortems(str(tmp_path))
        # the engine keeps serving afterwards and counts the dumps
        assert eng.flight.postmortems == len(files)
        snap = eng.metrics.snapshot(eng, reset_peak=False)
        assert snap["flight"]["flight_postmortems"] == len(files)

    def test_quarantine_dumps_postmortem(
        self, shared, tmp_path, monkeypatch
    ):
        from kafka_tpu.runtime.dp_router import DataParallelEngines

        cfg, params = shared
        monkeypatch.setenv("KAFKA_TPU_FLIGHT_DIR", str(tmp_path))
        dp = DataParallelEngines(
            cfg, params,
            EngineConfig(max_batch=2, page_size=8, num_pages=64,
                         max_pages_per_seq=8, prefill_buckets=(8, 16, 32),
                         flight_ring=32),
            dp=1, tp=1, quarantine_threshold=2, kv_dtype=jnp.float32,
        )
        assert dp.engines[0].flight.replica == 0
        dp.submit(GenRequest(request_id="q-victim",
                             prompt_ids=list(range(5, 20)),
                             max_new_tokens=20))
        dp.step()
        with failpoints.armed("engine.step", "error", count=2):
            for _ in range(2):
                with pytest.raises(failpoints.FailpointError):
                    dp.step()
        assert dp.health[0].state == "quarantined"
        files = glob.glob(str(tmp_path / "*.flight.json"))
        assert files
        pms = [json.loads(open(f).read()) for f in files]
        reasons = {pm["reason"] for pm in pms}
        assert "quarantine" in reasons
        pm = next(p for p in pms if p["reason"] == "quarantine")
        _assert_postmortem_schema(pm)
        assert pm["replica"] == 0

    def test_dump_skipped_without_dir(self, shared, monkeypatch):
        cfg, params = shared
        monkeypatch.setenv("KAFKA_TPU_FLIGHT_DIR", "")
        monkeypatch.delenv("KAFKA_TPU_TRACE_PERSIST_DIR", raising=False)
        monkeypatch.delenv("KAFKA_TPU_KV_DISK_TIER_DIR", raising=False)
        eng = make_engine(params, cfg)
        assert eng.dump_postmortem("test") is None


# ---------------------------------------------------------------------------
# registries + bench smoke
# ---------------------------------------------------------------------------


class TestFlightRegistry:
    """ISSUE 11 satellite: FLIGHT_METRIC_KEYS and ANOMALY_METRIC_KEYS are
    both-directions registries across runtime/metrics.py and
    server/prometheus.py, matching the SLO/KV-tier/constrained pattern."""

    def _source(self, relpath):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(root, "kafka_tpu", relpath)) as f:
            return f.read()

    def test_registry_both_directions(self):
        metrics_src = self._source("runtime/metrics.py")
        prom_src = self._source("server/prometheus.py")
        for key in FLIGHT_METRIC_KEYS + ANOMALY_METRIC_KEYS:
            assert f'"{key}"' in metrics_src, (
                f"{key} missing from runtime/metrics.py"
            )
            assert f'"{key}"' in prom_src, (
                f"{key} missing from server/prometheus.py"
            )

    def test_no_unregistered_flight_metrics(self):
        """Neither file invents flight_*/anomaly_* names outside the
        registries (the invent-proof direction)."""
        pattern = re.compile(
            r'"((?:flight|anomaly|anomalies)_[a-z0-9_]+)"'
        )
        allowed = set(FLIGHT_METRIC_KEYS) | set(ANOMALY_METRIC_KEYS)
        for rel in ("runtime/metrics.py", "server/prometheus.py"):
            for name in pattern.findall(self._source(rel)):
                assert name in allowed, f"{name} in {rel} not registered"

    def test_anomaly_snapshot_matches_registry(self):
        snap = EngineMetrics().anomalies_snapshot()
        flat = {k for k in snap if k != "active"}
        assert flat == set(ANOMALY_METRIC_KEYS)

    def test_anomaly_fields_in_engine_export_lint(self):
        from kafka_tpu.runtime.metrics import ENGINE_METRIC_EXPORTS

        fields = {f.name for f in dataclasses.fields(EngineMetrics)}
        for key in ANOMALY_METRIC_KEYS:
            if key == "anomalies_active":
                continue  # gauge derived from the recorder, not a field
            assert key in fields
            assert ENGINE_METRIC_EXPORTS[key] == ("anomalies", key)

    def test_flight_keys_render(self, shared):
        cfg, params = shared
        eng = make_engine(params, cfg)
        from kafka_tpu.server.prometheus import render_prometheus

        text = render_prometheus(eng.metrics.snapshot(eng))
        assert "kafka_tpu_flight_ring_size 64" in text
        assert "kafka_tpu_flight_records_total" in text
        assert "kafka_tpu_flight_postmortems_total" in text
        assert "kafka_tpu_anomalies_active 0" in text


class TestServerEndpoints:
    def _app_client(self, provider, tmp_path, **cfg_kw):
        from aiohttp.test_utils import TestClient, TestServer
        from kafka_tpu.db.local import LocalDBClient
        from kafka_tpu.server.app import create_app
        from kafka_tpu.server.config import ServingConfig

        async def build():
            app = await create_app(
                cfg=ServingConfig(db_path=str(tmp_path / "f.db"), **cfg_kw),
                llm_provider=provider,
                db=LocalDBClient(str(tmp_path / "f.db")),
                tools=[],
            )
            client = TestClient(TestServer(app))
            await client.start_server()
            return client

        return build

    def test_debug_flight_serves_live_ring(self, shared, tmp_path):
        import asyncio

        from kafka_tpu.llm import TPULLMProvider
        from kafka_tpu.models.tokenizer import ByteTokenizer

        cfg, params = shared
        eng = make_engine(params, cfg)
        run_requests(eng, n=2, gen=6)
        provider = TPULLMProvider(eng, ByteTokenizer(), model_name="m")
        build = self._app_client(provider, tmp_path)

        async def go():
            client = await build()
            try:
                r = await client.get("/debug/flight/0")
                assert r.status == 200
                payload = await r.json()
                assert payload["ring_size"] == 64
                assert payload["records"]
                assert set(payload["records"][-1]) >= {
                    "seq", "t", "kinds", "causes", "measured_ms",
                }
                assert payload["causes"] == list(CAUSES)
                # out-of-range and non-integer replicas answer cleanly
                assert (await client.get("/debug/flight/9")).status == 404
                assert (await client.get("/debug/flight/x")).status == 400
            finally:
                await client.close()
                provider.worker.stop()

        asyncio.run(go())

    def test_debug_flight_404_when_disabled(self, shared, tmp_path):
        import asyncio

        from kafka_tpu.llm import TPULLMProvider
        from kafka_tpu.models.tokenizer import ByteTokenizer

        cfg, params = shared
        eng = make_engine(params, cfg, flight_ring=0)
        provider = TPULLMProvider(eng, ByteTokenizer(), model_name="m")
        build = self._app_client(provider, tmp_path)

        async def go():
            client = await build()
            try:
                r = await client.get("/debug/flight/0")
                assert r.status == 404
                assert "disabled" in (await r.json())["error"]
            finally:
                await client.close()
                provider.worker.stop()

        asyncio.run(go())

    def test_profile_requires_machine_token(self, shared, tmp_path,
                                            monkeypatch):
        """ISSUE 11 satellite: with an api_token configured,
        POST /debug/profile demands exactly that token — and a granted
        capture reports the flight window covering it."""
        import asyncio

        from kafka_tpu.llm import TPULLMProvider
        from kafka_tpu.models.tokenizer import ByteTokenizer

        monkeypatch.setenv("KAFKA_TPU_PROFILING", "1")
        cfg, params = shared
        eng = make_engine(params, cfg)
        provider = TPULLMProvider(eng, ByteTokenizer(), model_name="m")
        build = self._app_client(provider, tmp_path, api_token="sekrit")

        async def go():
            client = await build()
            hdr = {"Authorization": "Bearer sekrit"}
            try:
                # wrong/missing token: 401 even though the middleware
                # would have been satisfied by a session token
                r = await client.post("/debug/profile",
                                      json={"seconds": 0.1})
                assert r.status == 401
                r = await client.post(
                    "/debug/profile", json={"seconds": 0.1},
                    headers={"Authorization": "Bearer wrong"},
                )
                assert r.status == 401
                r = await client.post("/debug/profile",
                                      json={"seconds": 0.1}, headers=hdr)
                assert r.status == 200
                body = await r.json()
                fw = body["flight_window"]
                assert fw is not None
                assert fw["t_end"] >= fw["t_start"]
                reps = {w["replica"]: w for w in fw["replicas"]}
                assert 0 in reps
                assert reps[0]["end_seq"] >= reps[0]["start_seq"]
            finally:
                await client.close()
                provider.worker.stop()

        asyncio.run(go())


class TestBenchSmoke:
    def test_flight_overhead_phase_runs(self, shared):
        import random
        import sys

        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        from bench import flight_overhead_phase

        cfg, params = shared
        eng = make_engine(params, cfg)
        args = SimpleNamespace(quick=True, batch=2, prompt_len=16)
        out = flight_overhead_phase(eng, cfg, args, random.Random(0))
        assert out["tok_s_on"] > 0 and out["tok_s_off"] > 0
        assert 0.0 <= out["regression_frac"] < 1.0
        # the phase restores the engine's recorder
        assert eng.flight is not None
