"""Engine tests: paged attention correctness, continuous batching, sampling.

The load-bearing invariant: paged decode through the engine must produce the
same tokens as a plain full-context forward (greedy), for any batch mix.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.models import ModelConfig, forward, init_params
from kafka_tpu.ops.sampling import SamplingParams, apply_top_k, apply_top_p, sample_tokens
from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine, PagePool
from kafka_tpu.runtime.kv_cache import OutOfPagesError


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(name="engine-test", vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(7))
    return cfg, params


def assert_greedy_consistent(cfg, params, prompt, out):
    """Check `out` is the greedy continuation of `prompt` with ONE forward.

    Runs the uncached model over prompt+out once; every position from the
    last prompt token onward must argmax-predict the next emitted token.
    Equivalent to comparing against step-by-step greedy generation (greedy
    is self-consistent), but ~n_new times faster.
    """
    seq = list(prompt) + list(out)
    x = jnp.asarray([seq], jnp.int32)
    pos = jnp.arange(len(seq), dtype=jnp.int32)[None, :]
    logits, _ = forward(params, cfg, x, pos)
    preds = np.asarray(jnp.argmax(logits[0], axis=-1))
    for i in range(len(prompt) - 1, len(seq) - 1):
        assert preds[i] == seq[i + 1], (
            f"divergence at position {i}: engine={seq[i + 1]} ref={preds[i]}"
        )


def make_engine(cfg, params, **kw):
    defaults = dict(max_batch=4, page_size=8, num_pages=64, max_pages_per_seq=8,
                    prefill_buckets=(8, 16, 32, 64))
    defaults.update(kw)
    return InferenceEngine(cfg, params, EngineConfig(**defaults), kv_dtype=jnp.float32)


class TestEngineCorrectness:
    def test_greedy_matches_uncached_forward(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        prompt = [1, 9, 23, 54, 3, 17, 88, 4, 61, 12, 7]  # crosses a page boundary
        req = eng.generate(prompt, max_new_tokens=12)
        assert_greedy_consistent(cfg, params, prompt, req.output_ids)
        assert len(req.output_ids) == 12
        assert req.finish_reason == "length"

    def test_chunked_prefill_matches(self, model):
        cfg, params = model
        # prompt longer than largest bucket forces multi-chunk prefill
        eng = make_engine(cfg, params, prefill_buckets=(8,), max_pages_per_seq=8)
        prompt = list(np.random.RandomState(0).randint(1, 128, size=21))
        req = eng.generate(prompt, max_new_tokens=6)
        assert_greedy_consistent(cfg, params, prompt, req.output_ids)
        assert len(req.output_ids) == 6

    def test_concurrent_requests_match_solo_runs(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        prompts = {
            "a": [5, 2, 9],
            "b": [88, 13, 54, 70, 21, 99, 6],
            "c": [1] * 17,
            "d": [42, 42, 7, 100],
        }
        for rid, p in prompts.items():
            eng.submit(GenRequest(request_id=rid, prompt_ids=p, max_new_tokens=8))
        done = eng.run_to_completion()
        assert set(done) == set(prompts)
        for rid, p in prompts.items():
            assert len(done[rid].output_ids) == 8, rid
            assert_greedy_consistent(cfg, params, p, done[rid].output_ids)

    def test_queueing_beyond_batch_size(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, max_batch=2)
        for i in range(5):
            eng.submit(GenRequest(request_id=f"r{i}", prompt_ids=[i + 1, 3, 5],
                                  max_new_tokens=4))
        done = eng.run_to_completion()
        assert len(done) == 5
        for i in range(5):
            assert_greedy_consistent(cfg, params, [i + 1, 3, 5], done[f"r{i}"].output_ids)

    def test_stop_token_terminates(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        prompt = [1, 9, 23, 54]
        free = eng.generate(prompt, max_new_tokens=10)
        stop_tok = free.output_ids[2]
        first_idx = free.output_ids.index(stop_tok)  # may appear before idx 2
        req = eng.generate(prompt, max_new_tokens=10, stop_token_ids=(stop_tok,))
        assert req.output_ids == free.output_ids[: first_idx + 1]
        assert req.finish_reason == "stop"

    def test_preemption_resumes_correctly(self, model):
        cfg, params = model
        # tiny pool: 2 long-running requests must fight for pages
        eng = make_engine(cfg, params, max_batch=2, num_pages=9, max_pages_per_seq=8)
        p1, p2 = [3, 1, 4, 1, 5, 9, 2, 6], [2, 7, 1, 8, 2, 8]
        eng.submit(GenRequest(request_id="x", prompt_ids=p1, max_new_tokens=20))
        eng.submit(GenRequest(request_id="y", prompt_ids=p2, max_new_tokens=20))
        done = eng.run_to_completion()
        assert len(done["x"].output_ids) == 20 and len(done["y"].output_ids) == 20
        assert_greedy_consistent(cfg, params, p1, done["x"].output_ids)
        assert_greedy_consistent(cfg, params, p2, done["y"].output_ids)
        # all pages back in the pool afterwards
        assert eng.pool.free_pages == 9 - 1

    def test_seeded_sampling_reproducible_across_batching(self, model):
        cfg, params = model
        kw = dict(max_new_tokens=10, temperature=0.9, top_p=0.95, seed=1234)
        eng1 = make_engine(cfg, params)
        solo = eng1.generate([7, 7, 7], **kw)
        eng2 = make_engine(cfg, params)
        eng2.submit(GenRequest(request_id="noise", prompt_ids=[9, 2], max_new_tokens=10,
                               temperature=1.3, seed=77))
        eng2.submit(GenRequest(request_id="probe", prompt_ids=[7, 7, 7], **kw))
        done = eng2.run_to_completion()
        assert done["probe"].output_ids == solo.output_ids

    def test_constrained_decoding_mask(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        allowed = [10, 11, 12]
        req = GenRequest(request_id="c", prompt_ids=[5, 2, 9], max_new_tokens=6,
                         logits_mask_fn=lambda out: allowed)
        eng.submit(req)
        done = eng.run_to_completion()
        assert all(t in allowed for t in done["c"].output_ids)


class TestSamplingOps:
    def test_top_k_masks(self):
        logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0]])
        out = apply_top_k(logits, jnp.asarray([2]))
        assert np.asarray(out[0, 0]) < -1e29 and np.asarray(out[0, 3]) < -1e29
        assert float(out[0, 1]) == 5.0 and float(out[0, 2]) == 3.0

    def test_top_k_zero_disables(self):
        logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0]])
        np.testing.assert_array_equal(np.asarray(apply_top_k(logits, jnp.asarray([0]))),
                                      np.asarray(logits))

    def test_top_p_keeps_head(self):
        logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
        out = apply_top_p(logits, jnp.asarray([0.7]))
        assert np.asarray(out[0, 0]) > -1e29 and np.asarray(out[0, 1]) > -1e29
        assert np.asarray(out[0, 2]) < -1e29 and np.asarray(out[0, 3]) < -1e29

    def test_greedy_is_argmax(self):
        logits = jnp.asarray([[0.1, 0.9, 0.2], [0.8, 0.1, 0.3]])
        sp = SamplingParams.make(2, temperature=0.0)
        toks = sample_tokens(logits, sp, jax.random.key(0))
        assert list(np.asarray(toks)) == [1, 0]

    def test_allowed_mask_restricts(self):
        logits = jnp.asarray([[0.1, 0.9, 0.2]])
        mask = jnp.asarray([[True, False, True]])
        sp = SamplingParams.make(1, temperature=0.0)
        toks = sample_tokens(logits, sp, jax.random.key(0), allowed_mask=mask)
        assert int(toks[0]) == 2

    def test_fully_masked_row_falls_back(self):
        logits = jnp.asarray([[0.1, 0.9, 0.2]])
        mask = jnp.zeros((1, 3), bool)
        sp = SamplingParams.make(1, temperature=0.0)
        toks = sample_tokens(logits, sp, jax.random.key(0), allowed_mask=mask)
        assert int(toks[0]) == 1  # unconstrained argmax


class TestPagePool:
    def test_alloc_release_refcount(self):
        pool = PagePool(num_pages=8, page_size=4)
        pages = pool.alloc(3)
        assert pool.free_pages == 4
        pool.retain(pages)
        pool.release(pages)
        assert pool.free_pages == 4  # still held once
        pool.release(pages)
        assert pool.free_pages == 7

    def test_exhaustion_raises(self):
        pool = PagePool(num_pages=4, page_size=4)
        pool.alloc(3)
        with pytest.raises(OutOfPagesError):
            pool.alloc(1)

    def test_trash_page_never_allocated(self):
        pool = PagePool(num_pages=4, page_size=4)
        assert 0 not in pool.alloc(3)


class TestReviewRegressions:
    def test_overlong_prompt_rejected_cleanly(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)  # window = 8 pages * 8 = 64
        with pytest.raises(ValueError, match="attention window"):
            eng.submit(GenRequest(request_id="big", prompt_ids=list(range(1, 80))))
        assert eng.pool.free_pages == 63  # nothing leaked

    def test_top_p_zero_is_argmax(self):
        logits = jnp.asarray([[0.1, 2.0, 0.3, 0.2]])
        sp = SamplingParams.make(1, temperature=1.0, top_p=0.0)
        toks = sample_tokens(logits, sp, jax.random.key(3))
        assert int(toks[0]) == 1

    def test_repeated_preemption_context_not_corrupted(self, model):
        cfg, params = model
        # 3 slots + 9 pages: constant page pressure -> multiple preemptions
        eng = make_engine(cfg, params, max_batch=3, num_pages=9, max_pages_per_seq=8)
        prompts = {"p0": [3, 1, 4, 1, 5], "p1": [2, 7, 1, 8], "p2": [9, 9, 8, 2, 6, 5]}
        for rid, p in prompts.items():
            eng.submit(GenRequest(request_id=rid, prompt_ids=p, max_new_tokens=24))
        done = eng.run_to_completion()
        for rid, p in prompts.items():
            assert len(done[rid].output_ids) == 24, rid
            assert_greedy_consistent(cfg, params, p, done[rid].output_ids)
            # prompt itself must be untouched by preemption bookkeeping
            assert done[rid].prompt_ids == p

    def test_registry_drained_after_completion(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        eng.generate([1, 2, 3], max_new_tokens=3)
        assert eng._requests == {}


class TestMultiStepDecode:
    """Fused k-step decode dispatches (EngineConfig.multi_step): engage
    only for busy stable batches and stay token-identical to single-step
    scheduling (position-keyed RNG makes fusion invisible to outputs)."""

    def _run_batch(self, cfg, params, multi_step, n_req=4, seeds=(0, 1, 2, 3)):
        eng = make_engine(cfg, params, max_batch=4, num_pages=96,
                          max_pages_per_seq=12, multi_step=multi_step)
        dispatched_multi = []
        orig = eng._dispatch_multi
        eng._dispatch_multi = lambda k: (dispatched_multi.append(k), orig(k))[1]
        reqs = []
        for i in range(n_req):
            r = GenRequest(
                request_id=f"ms-{i}", prompt_ids=[2 + i, 9, 23, 54, 7],
                max_new_tokens=24,
                temperature=0.0 if i % 2 == 0 else 0.9, seed=seeds[i],
            )
            eng.submit(r)
            reqs.append(r)
        eng.run_to_completion()
        return [r.output_ids for r in reqs], dispatched_multi

    def test_multi_step_token_exact_vs_single_step(self, model):
        cfg, params = model
        multi, ks = self._run_batch(cfg, params, multi_step=8)
        single, ks1 = self._run_batch(cfg, params, multi_step=1)
        assert multi == single
        assert ks and max(ks) >= 4, f"multi-step never engaged: {ks}"
        assert ks1 == []

    def test_stop_token_mid_burst_truncates(self, model):
        cfg, params = model
        # find each request's natural stop candidate from the single-step
        # run, then re-run WITH stop tokens under multi-step: the burst may
        # overshoot the stop on device, but emission must truncate exactly
        single, _ = self._run_batch(cfg, params, multi_step=1)
        stops = [out[5] for out in single]

        def with_stops(multi_step):
            eng = make_engine(cfg, params, max_batch=4, num_pages=96,
                              max_pages_per_seq=12, multi_step=multi_step)
            reqs = []
            for i in range(4):
                r = GenRequest(
                    request_id=f"st-{i}", prompt_ids=[2 + i, 9, 23, 54, 7],
                    max_new_tokens=24,
                    temperature=0.0 if i % 2 == 0 else 0.9, seed=i,
                    stop_token_ids=(stops[i],),
                )
                eng.submit(r)
                reqs.append(r)
            eng.run_to_completion()
            return [(r.output_ids, r.finish_reason) for r in reqs]

        assert with_stops(8) == with_stops(1)

    def test_multi_step_engages_under_queue_pressure(self, model):
        """Sustained load (queued requests, every slot busy) is exactly
        where fused dispatches matter: fusion must stay ON — admission can
        only happen at iteration boundaries anyway — and oversubscribed
        runs must still produce correct outputs."""
        cfg, params = model
        eng = make_engine(cfg, params, max_batch=4, num_pages=96,
                          max_pages_per_seq=12, multi_step=8)
        # queued requests now prefill off-slot and PARK awaiting a decode
        # slot (EngineConfig.max_parked), so "queue pressure" = waiting OR
        # parked lanes at fused-dispatch time
        fused_while_waiting = []
        orig = eng._dispatch_multi
        eng._dispatch_multi = lambda k: (
            fused_while_waiting.append(bool(eng.waiting or eng.parked)),
            orig(k))[1]
        reqs = []
        for i in range(8):  # 8 requests > 4 slots -> sustained queue
            r = GenRequest(request_id=f"q-{i}",
                           prompt_ids=[3 + i, 9, 23], max_new_tokens=32)
            eng.submit(r)
            reqs.append(r)
        eng.run_to_completion()
        assert any(fused_while_waiting), (
            "fusion never engaged under queue pressure"
        )
        for r in reqs:
            assert len(r.output_ids) == 32
            assert_greedy_consistent(cfg, params, r.prompt_ids, r.output_ids)


class TestInterleavedPrefill:
    """Admitting a long prompt must not stall co-scheduled decode streams:
    prefill advances one chunk per scheduler iteration while active lanes
    keep decoding (continuous-batching prefill/decode interleave)."""

    def test_decode_continues_during_long_prefill(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, max_batch=2, num_pages=96,
                          max_pages_per_seq=16, prefill_buckets=(8,))
        a = GenRequest(request_id="a", prompt_ids=[1, 2, 3, 4],
                       max_new_tokens=64)
        eng.submit(a)
        while a.state != "active":
            eng.step()
        base = a.dispatched
        # 40-token prompt through 8-token chunks = 5 prefill iterations
        b = GenRequest(request_id="b", prompt_ids=list(range(1, 41)),
                       max_new_tokens=4)
        eng.submit(b)
        saw_prefilling = False
        for _ in range(50):
            if b.state not in ("waiting", "prefilling"):
                break
            if b.state == "prefilling":
                saw_prefilling = True
            eng.step()
        assert saw_prefilling, "prefill never interleaved (inlined?)"
        # the co-scheduled stream kept decoding during b's prefill
        assert a.dispatched - base >= 3
        eng.run_to_completion()
        # and both outputs are still exactly right
        assert_greedy_consistent(cfg, params, a.prompt_ids, a.output_ids)
        assert_greedy_consistent(cfg, params, b.prompt_ids, b.output_ids)

    def test_solo_long_prompt_still_correct(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, max_batch=2, num_pages=96,
                          max_pages_per_seq=16, prefill_buckets=(8, 16))
        prompt = list(np.random.RandomState(12).randint(1, 128, size=45))
        req = eng.generate(prompt, max_new_tokens=6)
        assert_greedy_consistent(cfg, params, prompt, req.output_ids)


class TestBatchedPrefill:
    """Same-bucket prefills fuse into one dispatch; outputs must be
    token-identical to solo runs (position-keyed sampling; f32 tests)."""

    def test_batched_admission_token_exact(self, model):
        cfg, params = model
        mk = lambda: make_engine(cfg, params, max_batch=4, num_pages=96,
                                 max_pages_per_seq=12)
        # solo baselines
        solo = []
        ref_eng = mk()
        for i in range(4):
            r = ref_eng.generate([5 + i, 9, 23, 54, 7, 2, 11, 3],
                                 max_new_tokens=12,
                                 temperature=0.0 if i % 2 == 0 else 1.1,
                                 seed=i)
            solo.append(r.output_ids)
        # batched admission: all 4 submitted before stepping -> the 4
        # same-bucket first chunks ride ONE dispatch
        eng = mk()
        batched_calls = []
        orig = eng._advance_prefill_batch
        eng._advance_prefill_batch = (
            lambda b, rs, w: (batched_calls.append(len(rs)), orig(b, rs, w))[1])
        reqs = []
        for i in range(4):
            r = GenRequest(request_id=f"bp-{i}",
                           prompt_ids=[5 + i, 9, 23, 54, 7, 2, 11, 3],
                           max_new_tokens=12,
                           temperature=0.0 if i % 2 == 0 else 1.1, seed=i)
            eng.submit(r)
            reqs.append(r)
        eng.run_to_completion()
        assert batched_calls and max(batched_calls) >= 2, batched_calls
        assert [r.output_ids for r in reqs] == solo

    def test_constrained_lane_never_fuses(self, model):
        """A constrained request admitted alongside same-bucket peers must
        take the single-sequence path (its final chunk pops the sampled
        token synchronously so the first decode mask sees complete
        output_ids) — fusing it reorders token visibility and breaks the
        mask contract."""
        cfg, params = model

        def run(with_peers):
            eng = make_engine(cfg, params, max_batch=4, num_pages=96,
                              max_pages_per_seq=12)
            mask = lambda out: None if not out else [out[0] + 1, out[0] + 2]
            c = GenRequest(request_id="c", prompt_ids=[5, 9, 23, 54],
                           max_new_tokens=6, logits_mask_fn=mask)
            eng.submit(c)
            if with_peers:
                for i in range(3):
                    eng.submit(GenRequest(request_id=f"p{i}",
                                          prompt_ids=[6 + i, 9, 23, 54],
                                          max_new_tokens=6))
            eng.run_to_completion()
            return c.output_ids

        solo = run(with_peers=False)
        assert run(with_peers=True) == solo

    def test_mixed_bucket_admissions_split_correctly(self, model):
        """Different prompt lengths land in different buckets: each group
        fuses, singletons go solo, everything stays correct."""
        cfg, params = model
        eng = make_engine(cfg, params, max_batch=4, num_pages=96,
                          max_pages_per_seq=12, prefill_buckets=(8, 32))
        lens = [6, 7, 20, 25]  # two in bucket 8, two in bucket 32
        reqs = []
        for i, n in enumerate(lens):
            r = GenRequest(
                request_id=f"mix-{i}",
                prompt_ids=list(np.random.RandomState(i).randint(1, 128, n)),
                max_new_tokens=6)
            eng.submit(r)
            reqs.append(r)
        eng.run_to_completion()
        for r in reqs:
            assert_greedy_consistent(cfg, params, r.prompt_ids, r.output_ids)


class TestOffSlotAdmission:
    """Parking (EngineConfig.max_parked): when every decode slot is busy,
    waiting requests prefill off-slot and emit their FIRST token without
    waiting for a slot — TTFT under oversubscription is bounded by prefill
    latency, not queue wait (VERDICT r3 weak #2).  Parked pages must be
    reclaimed before any active lane is preempted, and outputs must stay
    token-exact through park/seat/rollback."""

    def test_first_tokens_precede_queue_drain(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, max_batch=2, num_pages=96,
                          max_pages_per_seq=8)
        reqs = [GenRequest(request_id=f"p-{i}", prompt_ids=[5 + i, 9, 23],
                           max_new_tokens=24) for i in range(8)]
        for r in reqs:
            eng.submit(r)
        # step until every request has its first token
        finished_when_all_started = None
        for _ in range(3000):
            eng.step()
            if all(r.first_token_time is not None for r in reqs):
                finished_when_all_started = sum(
                    1 for r in reqs if r.state == "finished")
                break
        assert finished_when_all_started is not None, "first tokens missing"
        # 8 requests over 2 slots: first tokens must NOT have required the
        # queue to drain (without parking, request 8's first token arrives
        # after ~3 full turns retire)
        assert finished_when_all_started <= 4
        eng.run_to_completion()
        for r in reqs:
            assert len(r.output_ids) == 24, r.request_id
            assert_greedy_consistent(cfg, params, r.prompt_ids, r.output_ids)

    def test_parked_rollback_under_page_pressure(self, model):
        cfg, params = model
        # tight pool: 2 slots of long-ish generations + parked extras force
        # page-pressure rollback of parked lanes (never active preemption)
        eng = make_engine(cfg, params, max_batch=2, num_pages=14,
                          max_pages_per_seq=6, park_reserve_pages=2)
        reqs = [GenRequest(request_id=f"r-{i}", prompt_ids=[7 + i, 3],
                           max_new_tokens=30) for i in range(6)]
        for r in reqs:
            eng.submit(r)
        eng.run_to_completion()
        for r in reqs:
            assert len(r.output_ids) == 30, r.request_id
            assert_greedy_consistent(cfg, params, r.prompt_ids, r.output_ids)

    def test_cancel_parked_request_frees_pages(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, max_batch=2, num_pages=96,
                          max_pages_per_seq=8)
        reqs = [GenRequest(request_id=f"c-{i}", prompt_ids=[11 + i, 2, 9],
                           max_new_tokens=20) for i in range(5)]
        for r in reqs:
            eng.submit(r)
        # step until something parks, then cancel it
        for _ in range(500):
            eng.step()
            if eng.parked:
                break
        assert eng.parked, "nothing parked"
        victim = eng.parked[0]
        assert eng.cancel(victim.request_id)
        assert victim not in eng.parked and victim.seq is None
        eng.run_to_completion()
        for r in reqs:
            if r is victim:
                continue
            assert len(r.output_ids) == 20, r.request_id
            assert_greedy_consistent(cfg, params, r.prompt_ids, r.output_ids)

    def test_disabled_parking_keeps_fifo_waiting(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, max_batch=2, num_pages=96,
                          max_pages_per_seq=8, max_parked=0)
        reqs = [GenRequest(request_id=f"d-{i}", prompt_ids=[4 + i, 8],
                           max_new_tokens=8) for i in range(5)]
        for r in reqs:
            eng.submit(r)
        eng.step()
        assert not eng.parked and len(eng.waiting) == 3
        eng.run_to_completion()
        for r in reqs:
            assert len(r.output_ids) == 8


class TestConstrainedChaining:
    """Singleton-mask chaining: grammar-forced tokens dispatch at
    scheduler cadence instead of one device->host round trip each (the
    dominant cost of constrained tool-call JSON on high-RTT links)."""

    def test_forced_sequence_chains_without_blocking_pops(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, max_batch=2)
        seq = [9, 23, 54, 3, 17, 88, 4, 61, 12, 7, 33, 90]

        def mask_fn(out):
            return [seq[len(out)]] if len(out) < len(seq) else [2]

        pops = []
        orig = eng._pop_entry_now
        eng._pop_entry_now = lambda e: (pops.append(1), orig(e))[1]
        req = GenRequest(request_id="chain", prompt_ids=[5, 2, 9],
                         max_new_tokens=len(seq) + 1,
                         logits_mask_fn=mask_fn)
        eng.submit(req)
        done = eng.run_to_completion()
        assert done["chain"].output_ids == seq + [2]
        # the prefill's synchronous pop is expected; the forced decode run
        # must NOT have popped per token (13 tokens -> <= a few pops)
        assert len(pops) <= 3, f"{len(pops)} blocking pops for forced run"

    def test_mixed_forced_and_free_steps_still_correct(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, max_batch=2)
        forced_prefix = [11, 45, 2]

        def mask_fn(out):
            if len(out) < len(forced_prefix):
                return [forced_prefix[len(out)]]
            return None  # free generation afterwards

        req = GenRequest(request_id="mix", prompt_ids=[7, 3],
                         max_new_tokens=8, logits_mask_fn=mask_fn)
        eng.submit(req)
        done = eng.run_to_completion()
        out = done["mix"].output_ids
        assert out[:3] == forced_prefix and len(out) == 8
        # the free tail must be the model's real greedy continuation
        assert_greedy_consistent(cfg, params, [7, 3] + forced_prefix,
                                 out[3:])

    def test_chained_alongside_unconstrained_lane(self, model):
        cfg, params = model
        eng = make_engine(cfg, params, max_batch=2)
        seq = [8, 19, 42, 5, 77, 1]

        def mask_fn(out):
            return [seq[len(out)]] if len(out) < len(seq) else [2]

        free = GenRequest(request_id="free", prompt_ids=[1, 9, 23],
                          max_new_tokens=12)
        conq = GenRequest(request_id="con", prompt_ids=[5, 2, 9],
                          max_new_tokens=len(seq) + 1,
                          logits_mask_fn=mask_fn)
        eng.submit(free)
        eng.submit(conq)
        done = eng.run_to_completion()
        assert done["con"].output_ids == seq + [2]
        assert_greedy_consistent(cfg, params, [1, 9, 23],
                                 done["free"].output_ids)

    def test_forced_stop_token_ends_chain_without_mask_overrun(self, model):
        """A grammar whose table ends at the stop token must not be called
        past its end (the chain stops at a predicted stop token), and a
        mask fn that DOES get called out of range must degrade the step,
        not kill the engine thread."""
        cfg, params = model
        eng = make_engine(cfg, params, max_batch=2)
        seq = [9, 23, 54, 99]

        def mask_fn(out):
            return [seq[len(out)]]  # IndexError if called past the end

        req = GenRequest(request_id="stop-chain", prompt_ids=[5, 2],
                         max_new_tokens=20, stop_token_ids=(99,),
                         logits_mask_fn=mask_fn)
        eng.submit(req)
        done = eng.run_to_completion()
        assert done["stop-chain"].output_ids == seq
        assert done["stop-chain"].finish_reason == "stop"

    def test_exhausted_mask_table_degrades_not_crashes(self, model):
        """Grammar ends but generation continues: the raising mask fn
        degrades the lane to unconstrained instead of failing every
        in-flight request."""
        cfg, params = model
        eng = make_engine(cfg, params, max_batch=2)
        seq = [9, 23, 54]  # no stop token: generation outlives the table

        def mask_fn(out):
            return [seq[len(out)]]

        req = GenRequest(request_id="exhaust", prompt_ids=[5, 2],
                         max_new_tokens=8, logits_mask_fn=mask_fn)
        eng.submit(req)
        done = eng.run_to_completion()
        out = done["exhaust"].output_ids
        assert out[:3] == seq and len(out) == 8


class TestLifecycleHardening:
    """Deadlines + admission backpressure (ISSUE 1 request-lifecycle
    hardening): timeouts finish with finish_reason="timeout" and free slot
    + pages exactly like a cancel; a submit past the bounded queue raises
    AdmissionError with a Retry-After estimate."""

    def test_total_deadline_times_out_waiting_request(self, model):
        import time as _time

        cfg, params = model
        eng = make_engine(cfg, params, max_total_s=0.0)
        eng.submit(GenRequest(request_id="t1", prompt_ids=[1, 2, 3]))
        _time.sleep(0.005)
        events = eng.step()
        terminal = [e for e in events if e.finished]
        assert len(terminal) == 1
        assert terminal[0].finish_reason == "timeout"
        assert eng.pool.free_pages == eng.pool.num_pages - 1
        assert not eng.waiting and not eng._requests
        assert eng.metrics.requests_timeout == 1

    def test_deadline_frees_slot_and_pages_mid_decode(self, model):
        import time as _time

        from kafka_tpu.runtime import failpoints as _fp

        cfg, params = model
        eng = make_engine(cfg, params)
        req = GenRequest(request_id="mid", prompt_ids=[1, 2, 3],
                         max_new_tokens=500, deadline_s=0.05)
        eng.submit(req)
        reason = None
        t0 = _time.monotonic()
        # slow each scheduler iteration so the deadline ALWAYS expires
        # mid-decode — with warm compiled programs (XLA cache shared
        # across modules) 500 tokens can otherwise finish inside 50ms
        # and the finish reason races to "length"
        with _fp.armed("engine.step", "delay", "0.005"):
            while reason is None and _time.monotonic() - t0 < 30:
                for ev in eng.step():
                    if ev.finished:
                        reason = ev.finish_reason
        assert reason == "timeout"
        assert all(s is None for s in eng.slots)
        assert eng.pool.free_pages == eng.pool.num_pages - 1
        assert not eng.self_check(), eng.self_check()
        # the engine keeps serving afterwards
        ok = eng.generate([4, 5, 6], max_new_tokens=2)
        assert ok.finish_reason == "length"

    def test_ttft_deadline_spares_request_that_got_first_token(self, model):
        import time as _time

        cfg, params = model
        # generous TTFT bound: the first token arrives well inside it, so
        # the request must run to its full budget even after the bound
        eng = make_engine(cfg, params, max_ttft_s=30.0)
        req = eng.generate([1, 2, 3], max_new_tokens=4)
        assert req.finish_reason == "length"
        assert len(req.output_ids) == 4

    def test_per_request_deadline_overrides_config(self, model):
        import time as _time

        cfg, params = model
        eng = make_engine(cfg, params, max_total_s=300.0)
        eng.submit(GenRequest(request_id="o1", prompt_ids=[1, 2, 3],
                              deadline_s=0.0))
        _time.sleep(0.005)
        events = eng.step()
        assert any(e.finished and e.finish_reason == "timeout"
                   for e in events)

    def test_bounded_queue_rejects_with_retry_after(self, model):
        from kafka_tpu.runtime import AdmissionError

        cfg, params = model
        eng = make_engine(cfg, params, max_waiting=2)
        rejected = None
        for i in range(16):
            try:
                eng.submit(GenRequest(request_id=f"q{i}",
                                      prompt_ids=[1, 2], max_new_tokens=2))
            except AdmissionError as e:
                rejected = e
                break
        assert rejected is not None
        assert rejected.retry_after_s >= 1.0
        assert eng.metrics.requests_rejected == 1
        # everything admitted before the bound still completes
        done = eng.run_to_completion()
        assert len(done) == i
        assert eng.metrics.queue_depth_peak >= 1

    def test_unbounded_queue_by_default(self, model):
        cfg, params = model
        eng = make_engine(cfg, params)
        for i in range(20):
            eng.submit(GenRequest(request_id=f"u{i}", prompt_ids=[1, 2],
                                  max_new_tokens=1))
        assert len(eng.run_to_completion()) == 20
