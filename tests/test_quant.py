"""Int8 weight-only quantization (VERDICT r3 next #4).

Covers: quantize/dequantize error bounds, the quantized engine serving
token streams with a high greedy match rate vs the bf16/f32 model, QTensor
sharding on a tp mesh, and the ServingConfig wiring.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.models import (
    ModelConfig, QTensor, dequantize, init_params, quantize_params,
)
from kafka_tpu.models.quant import quantize_array
from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(name="quant-test", vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(5))
    return cfg, params


def make_engine(cfg, params, mesh=None):
    return InferenceEngine(
        cfg, params,
        EngineConfig(max_batch=2, page_size=8, num_pages=64,
                     max_pages_per_seq=8, prefill_buckets=(8, 16, 32)),
        kv_dtype=jnp.float32, mesh=mesh,
    )


class TestQuantizeArray:
    def test_roundtrip_error_bound(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (4, 64, 32), jnp.float32)
        qt = quantize_array(w, (1,))
        assert qt.q.dtype == jnp.int8 and qt.s.shape == (4, 1, 32)
        deq = np.asarray(dequantize(qt, jnp.float32))
        # symmetric per-channel: |err| <= scale/2 per element
        bound = np.asarray(qt.s.astype(jnp.float32)) / 2 + 1e-6
        assert (np.abs(deq - np.asarray(w)) <= bound).all()

    def test_quantize_params_coverage(self, model):
        cfg, params = model
        qp = quantize_params(params, cfg)
        assert isinstance(qp["embed"], QTensor)
        for name in ("wq", "wk", "wv", "wo", "wg", "wu", "wd"):
            assert isinstance(qp["layers"][name], QTensor), name
        # norms stay dense
        assert not isinstance(qp["layers"]["ln_attn"], QTensor)
        assert not isinstance(qp["final_norm"], QTensor)
        # stored weight bytes roughly halve vs f32/4 (int8 + small scales)
        from kafka_tpu.models.quant import param_bytes

        dense = sum(x.size * x.dtype.itemsize
                    for x in jax.tree.leaves(params))
        assert param_bytes(qp) < 0.35 * dense

    def test_moe_experts_quantize(self):
        cfg = ModelConfig(name="qmoe", vocab_size=64, hidden_size=32,
                          intermediate_size=48, num_layers=2, num_heads=4,
                          num_kv_heads=2, head_dim=8, dtype="float32",
                          num_experts=4)
        qp = quantize_params(init_params(cfg, jax.random.PRNGKey(1)), cfg)
        assert isinstance(qp["layers"]["wg"], QTensor)
        assert not isinstance(qp["layers"]["router"], QTensor)


class TestQuantizedServing:
    def test_greedy_match_rate_vs_dense(self, model):
        """The int8 engine's greedy stream matches the dense engine's on
        most steps (random weights are the adversarial case: logit gaps
        are tiny, so near-ties flip; real checkpoints match higher)."""
        cfg, params = model
        dense = make_engine(cfg, params)
        q_eng = make_engine(cfg, quantize_params(params, cfg))
        match = total = 0
        for i in range(4):
            prompt = [3 + i, 17, 92, 5, 44 + i]
            a = dense.generate(prompt, max_new_tokens=16).output_ids
            b = q_eng.generate(prompt, max_new_tokens=16).output_ids
            total += len(a)
            match += sum(1 for x, y in zip(a, b) if x == y)
        assert match / total > 0.5, f"match rate {match}/{total}"

    def test_quantized_engine_serves_batch(self, model):
        cfg, params = model
        eng = make_engine(cfg, quantize_params(params, cfg))
        for i in range(3):
            eng.submit(GenRequest(request_id=f"q{i}",
                                  prompt_ids=[5 + i, 2, 9],
                                  max_new_tokens=8))
        done = eng.run_to_completion()
        assert len(done) == 3
        assert all(len(r.output_ids) == 8 for r in done.values())


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
class TestQuantizedTP:
    def test_qtensor_shards_on_tp_mesh(self, model):
        from jax.sharding import PartitionSpec as P

        from kafka_tpu.parallel import MeshConfig, make_mesh, shard_params

        cfg, params = model
        qp = quantize_params(params, cfg)
        mesh = make_mesh(MeshConfig(tp=4))
        sharded = shard_params(qp, cfg, mesh)
        wq = sharded["layers"]["wq"]
        assert wq.q.sharding.spec == P(None, None, "tp", None)
        assert wq.s.sharding.spec == P(None, None, "tp", None)
        # row-parallel wo: q shards the contraction, scale is replicated
        wo = sharded["layers"]["wo"]
        assert wo.q.sharding.spec == P(None, "tp", None, None)
        assert all(ax is None for ax in wo.s.sharding.spec)

    def test_tp_quantized_engine_matches_single_device(self, model):
        from kafka_tpu.parallel import MeshConfig, make_mesh

        cfg, params = model
        qp = quantize_params(params, cfg)
        base = make_engine(cfg, qp)
        eng = make_engine(cfg, qp, mesh=make_mesh(MeshConfig(tp=4)))
        prompt = [5, 99, 23, 4, 17]
        want = base.generate(prompt, max_new_tokens=10).output_ids
        got = eng.generate(prompt, max_new_tokens=10).output_ids
        assert got == want


class TestServingConfigWiring:
    def test_env_quantize(self, monkeypatch):
        from kafka_tpu.server import ServingConfig

        monkeypatch.setenv("KAFKA_TPU_QUANTIZE", "int8")
        assert ServingConfig.from_env().quantize == "int8"


class TestLogitQuality:
    """Logit-level int8 evidence (VERDICT r4 weak #1): gates on logit
    error, not on greedy match over random weights.  The model is a REAL
    Llama architecture with transformers' own init (the
    test_checkpoint_serving.py recipe), loaded through the HF loader."""

    @pytest.fixture(scope="class")
    def real_arch(self, tmp_path_factory):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")

        from kafka_tpu.models.loader import load_checkpoint

        d = tmp_path_factory.mktemp("quality-ckpt")
        hf_cfg = transformers.LlamaConfig(
            vocab_size=262, hidden_size=128, intermediate_size=256,
            num_hidden_layers=4, num_attention_heads=8,
            num_key_value_heads=4, head_dim=16,
            max_position_embeddings=2048, rms_norm_eps=1e-5,
            rope_theta=10000.0, tie_word_embeddings=False,
            attention_bias=False, mlp_bias=False, torch_dtype="float32",
        )
        torch.manual_seed(7)
        transformers.LlamaForCausalLM(hf_cfg).eval().save_pretrained(
            str(d), safe_serialization=True
        )
        return load_checkpoint(str(d))

    def test_logit_error_bounds_on_real_architecture(self, real_arch):
        from kafka_tpu.models.quant_quality import logit_quality_metrics

        cfg, params = real_arch
        qp = quantize_params(params, cfg)
        rng = np.random.RandomState(0)
        prompts = [rng.randint(4, 258, 48).tolist() for _ in range(4)]
        m = logit_quality_metrics(cfg, params, qp, prompts)
        # measured on this recipe: max|dlogit| 0.024, KL 1e-5 — gates
        # carry an order of magnitude of headroom
        assert m["max_abs_dlogit"] < 0.25, m
        assert m["kl_mean"] < 1e-3, m
        assert m["kl_p99"] < 1e-2, m
        # the analytic confinement bound: an argmax flip requires the
        # dense top-1 margin to be under 2*max|dlogit|; no flip may occur
        # at a confident position
        assert m["flip_margin_max"] <= 2 * m["max_abs_dlogit"] + 1e-6, m

    def test_gates_catch_a_broken_quantizer(self, real_arch):
        """Negative control: the logit gates must be FALSIFIABLE.  A
        quantizer with corrupted scales (4x too large — the kind of bug a
        wrong contraction axis produces) must blow through the bounds the
        real quantizer passes."""
        from kafka_tpu.models.quant_quality import logit_quality_metrics

        cfg, params = real_arch
        qp = quantize_params(params, cfg)
        broken = jax.tree.map(
            lambda v: QTensor(q=v.q, s=v.s * 4.0)
            if isinstance(v, QTensor) else v,
            qp, is_leaf=lambda v: isinstance(v, QTensor),
        )
        rng = np.random.RandomState(0)
        prompts = [rng.randint(4, 258, 48).tolist() for _ in range(2)]
        m = logit_quality_metrics(cfg, params, broken, prompts)
        assert m["max_abs_dlogit"] > 0.25 or m["kl_mean"] > 1e-3, m
