"""Flash prefill kernel numerics vs the XLA gather path (interpret mode)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.ops.attention import causal_attention
from kafka_tpu.ops.pallas import paged_prefill_attention


def make_case(seed, S, start, chunk_len, ps, P, Hq, Hkv, D):
    """Pool holds [0, start) from earlier chunks plus this chunk's KV
    (positions start..start+chunk_len), page-ordered."""
    rng = np.random.RandomState(seed)
    num_pages = P + 4
    HD = Hkv * D
    k_pool = rng.randn(num_pages * ps, HD).astype(np.float32)
    v_pool = rng.randn(num_pages * ps, HD).astype(np.float32)
    q = rng.randn(S, Hq, D).astype(np.float32)
    page_row = np.arange(1, P + 1, dtype=np.int32)  # page 0 = trash
    return q, k_pool, v_pool, page_row


def reference(q, k_pool, v_pool, page_row, start, chunk_len, ps, Hkv, D):
    P = len(page_row)
    C = P * ps
    read_idx = (page_row[:, None] * ps + np.arange(ps)[None, :]).reshape(C)
    k_win = k_pool[read_idx].reshape(1, C, Hkv, D)
    v_win = v_pool[read_idx].reshape(1, C, Hkv, D)
    S = q.shape[0]
    q_pos = (start + np.arange(S))[None, :]
    kv_pos = np.arange(C)[None, :]
    kv_valid = kv_pos < (start + chunk_len)
    out = causal_attention(
        jnp.asarray(q)[None], jnp.asarray(k_win), jnp.asarray(v_win),
        q_positions=jnp.asarray(q_pos), kv_positions=jnp.asarray(kv_pos),
        kv_valid=jnp.asarray(kv_valid),
    )
    return np.asarray(out[0])


class TestFlashPrefill:
    @pytest.mark.parametrize("start,chunk_len,S", [
        (0, 16, 16),     # first chunk, full
        (0, 11, 16),     # first chunk, padded tail
        (32, 16, 16),    # later chunk with context
        (48, 5, 16),     # short final chunk
    ])
    def test_matches_reference(self, start, chunk_len, S):
        ps, P, Hq, Hkv, D = 8, 12, 8, 4, 32
        q, k_pool, v_pool, page_row = make_case(0, S, start, chunk_len, ps, P,
                                                Hq, Hkv, D)
        out = paged_prefill_attention(
            jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(page_row), jnp.int32(start), jnp.int32(chunk_len),
            page_size=ps, q_block=8, interpret=True,
        )
        ref = reference(q, k_pool, v_pool, page_row, start, chunk_len, ps,
                        Hkv, D)
        # rows past chunk_len are garbage on both paths — compare real rows
        np.testing.assert_allclose(
            np.asarray(out)[:chunk_len], ref[:chunk_len],
            atol=2e-5, rtol=2e-5,
        )

    def test_multi_qblock_long_chunk(self):
        ps, P, Hq, Hkv, D = 8, 24, 4, 2, 16
        S, start, chunk_len = 64, 96, 64
        q, k_pool, v_pool, page_row = make_case(5, S, start, chunk_len, ps, P,
                                                Hq, Hkv, D)
        out = paged_prefill_attention(
            jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(page_row), jnp.int32(start), jnp.int32(chunk_len),
            page_size=ps, q_block=16, interpret=True,
        )
        ref = reference(q, k_pool, v_pool, page_row, start, chunk_len, ps,
                        Hkv, D)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)

    def test_mqa(self):
        ps, P, Hq, Hkv, D = 8, 8, 4, 1, 16
        S, start, chunk_len = 16, 8, 16
        q, k_pool, v_pool, page_row = make_case(7, S, start, chunk_len, ps, P,
                                                Hq, Hkv, D)
        out = paged_prefill_attention(
            jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(page_row), jnp.int32(start), jnp.int32(chunk_len),
            page_size=ps, q_block=8, interpret=True,
        )
        ref = reference(q, k_pool, v_pool, page_row, start, chunk_len, ps,
                        Hkv, D)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)
