"""End-to-end request tracing (ISSUE 3): span registry static checks,
engine span integration, cross-process stitching through a REAL sandbox
subprocess, supervisor span events, slow-request logs, structured JSON
logging, and the /debug/trace HTTP surface."""

import asyncio
import json
import logging
import os
import pathlib
import re
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu import tracing
from kafka_tpu.models import ModelConfig, init_params
from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine


@pytest.fixture(autouse=True)
def _fresh_tracer(monkeypatch):
    """Every test starts with an empty ring and default config."""
    monkeypatch.delenv(tracing.ENV_SAMPLE, raising=False)
    monkeypatch.delenv(tracing.ENV_SLOW_TTFT, raising=False)
    monkeypatch.delenv(tracing.ENV_SLOW_TOTAL, raising=False)
    tracing.reset()
    yield
    tracing.reset()


# ---------------------------------------------------------------------------
# span registry static check (satellite: SITES-style schema enforcement)
# ---------------------------------------------------------------------------


class TestSpanRegistry:
    """Every span name emitted in kafka_tpu/ must appear in the documented
    SPANS registry (and vice versa); same for trace-level EVENTS — the
    trace schema cannot silently drift, mirroring failpoints.SITES."""

    SPAN_PATTERNS = (
        r"\.span\(\s*[\"']([\w.]+)[\"']",              # tracing/collector.span("x")
        r"\brecord_span\(\s*[^,]+,\s*[\"']([\w.]+)[\"']",  # engine hot path
        r"start_trace\([^)]*?name=[\"']([\w.]+)[\"']",     # root spans
    )
    EVENT_PATTERN = r"\badd_event\(\s*[^,]+,\s*[\"']([\w.]+)[\"']"

    def _scan(self, patterns):
        import kafka_tpu

        root = pathlib.Path(kafka_tpu.__file__).parent
        wired = set()
        for path in root.rglob("*.py"):
            if path.name == "tracing.py":
                continue  # the definition modules, not call sites
            text = path.read_text()
            for pat in patterns:
                wired.update(re.findall(pat, text))
        return wired

    def test_every_wired_span_is_documented(self):
        wired = self._scan(self.SPAN_PATTERNS)
        undocumented = wired - set(tracing.SPANS)
        assert not undocumented, (
            f"span names wired but missing from SPANS: {undocumented}"
        )

    def test_every_documented_span_is_wired(self):
        wired = self._scan(self.SPAN_PATTERNS)
        dead = set(tracing.SPANS) - wired
        assert not dead, f"SPANS documents unwired names: {dead}"

    def test_events_registry_both_directions(self):
        wired = self._scan((self.EVENT_PATTERN,))
        assert not wired - set(tracing.EVENTS), (
            f"event names wired but undocumented: "
            f"{wired - set(tracing.EVENTS)}"
        )
        assert not set(tracing.EVENTS) - wired, (
            f"EVENTS documents unwired names: "
            f"{set(tracing.EVENTS) - wired}"
        )

    def test_readme_documents_every_span_and_event(self):
        readme = (pathlib.Path(__file__).parent.parent / "README.md"
                  ).read_text()
        missing = [n for n in (*tracing.SPANS, *tracing.EVENTS)
                   if f"`{n}`" not in readme]
        assert not missing, f"README missing span/event names: {missing}"


# ---------------------------------------------------------------------------
# tracer unit behavior
# ---------------------------------------------------------------------------


class TestTracerUnit:
    def test_trace_lifecycle_and_nesting(self):
        root = tracing.start_trace(request_id="u1", name="http.request")
        assert root is not None
        with tracing.span("agent.turn", attrs={"iteration": 1}) as turn:
            with tracing.span("tool.exec", attrs={"tool": "x"}) as tool:
                assert tool.parent_id == turn.span_id
        tracing.finish_trace(root, status=200)
        tr = tracing.get_trace("u1")
        assert tr.done
        assert [s.name for s in tr.spans] == [
            "http.request", "agent.turn", "tool.exec"]
        assert tr.spans[1].parent_id == root.span_id
        assert all(s.t1 is not None for s in tr.spans)
        assert root.attrs["status"] == 200

    def test_sampled_out_is_one_none(self):
        tracing.configure(sample=0.0)
        assert tracing.start_trace(request_id="nope") is None
        assert tracing.current() is None
        # explicit-context sites no-op on None (the engine's one branch)
        tracing.record_span(None, "engine.decode", 0.01)
        tracing.add_event(None, "preempt")
        # sample 0 is a HARD off switch: even an adopted id records
        # nothing (a proxy stamping X-Request-Id must not re-enable
        # tracing a deployment turned off)
        assert tracing.start_trace(request_id="want",
                                   trace_id="want") is None
        # between 0 and 1, an adopted id bypasses the coin flip
        tracing.configure(sample=1e-9)
        assert tracing.start_trace(request_id="named",
                                   trace_id="named") is not None

    def test_span_cap_bounds_trace_growth(self):
        tracing.configure(span_cap=3)
        root = tracing.start_trace(request_id="cap1")
        ctx = tracing.current()
        for _ in range(10):
            tracing.record_span(ctx, "engine.decode", 0.001)
        with tracing.span("agent.turn") as s:
            assert s is None  # cap reached: context spans refuse too
        assert tracing.stitch({
            "trace_id": ctx.trace_id,
            "spans": [{"name": "sandbox.exec", "span_id": "x",
                       "t0": 0.0, "t1": 1.0}],
        }) == 0
        tracing.finish_trace(root)
        tr = tracing.get_trace("cap1")
        assert len(tr.spans) == 3  # root + 2 admitted decode spans
        assert tr.dropped_spans == 10  # 8 decode + 1 span() + 1 stitched
        idx = next(t for t in tracing.recent_traces()
                   if t["request_id"] == "cap1")
        assert idx["dropped_spans"] == 10

    def test_ring_eviction_bounds_memory(self):
        tracing.configure(ring=4)
        for i in range(10):
            root = tracing.start_trace(request_id=f"r{i}")
            tracing.finish_trace(root)
        idx = tracing.recent_traces()
        assert len(idx) == 4
        assert tracing.get_trace("r0") is None
        assert tracing.get_trace("r9") is not None

    def test_chrome_export_is_perfetto_shaped(self):
        root = tracing.start_trace(request_id="c1")
        with tracing.span("agent.turn"):
            pass
        tracing.add_event(tracing.current(), "preempt", {"k": 1})
        tracing.finish_trace(root)
        data = tracing.chrome_trace("c1")
        # must round-trip as JSON (the HTTP endpoint serves it verbatim)
        data = json.loads(json.dumps(data))
        events = data["traceEvents"]
        complete = [e for e in events if e["ph"] == "X"]
        instants = [e for e in events if e["ph"] == "i"]
        metas = [e for e in events if e["ph"] == "M"]
        assert {e["name"] for e in complete} == {"http.request",
                                                "agent.turn"}
        assert all(
            set(e) >= {"name", "ts", "dur", "pid", "tid", "args"}
            for e in complete
        )
        assert instants and instants[0]["name"] == "preempt"
        assert metas  # named lanes for Perfetto
        assert data["otherData"]["request_id"] == "c1"

    def test_stitch_merges_child_spans_by_trace_id(self):
        root = tracing.start_trace(request_id="s1")
        ctx = tracing.current()
        child = tracing.ChildSpans(ctx.trace_id, ctx.span_id)
        with child.span("sandbox.exec", attrs={"tool": "shell_exec"}):
            time.sleep(0.001)
        n = tracing.stitch(child.export())
        assert n == 1
        tracing.finish_trace(root)
        tr = tracing.get_trace("s1")
        stitched = [s for s in tr.spans if s.name == "sandbox.exec"]
        assert stitched and stitched[0].parent_id == root.span_id
        assert tracing.counters()["stitched_spans"] == 1
        # unknown trace ids drop silently (ring rolled over)
        assert tracing.stitch({"trace_id": "gone", "spans": [{}]}) == 0

    def test_subprocess_env_carries_live_config(self):
        tracing.configure(sample=0.25)
        env = tracing.subprocess_env({"PATH": "/bin"})
        assert float(env[tracing.ENV_SAMPLE]) == 0.25

    def test_traceparent_shape_understood_by_server_helper(self):
        from kafka_tpu.server.app import _incoming_trace

        class Req:
            headers = {"traceparent":
                       f"00-{'a' * 32}-{'b' * 16}-01"}
        tid, parent = _incoming_trace(Req())
        assert tid == "a" * 32 and parent == "b" * 16

        class Req2:
            headers = {"X-Request-Id": "my-req"}
        assert _incoming_trace(Req2()) == ("my-req", None)


# ---------------------------------------------------------------------------
# engine integration: the span tree a served request produces
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def engine():
    cfg = ModelConfig(name="trace-test", vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(7))
    return InferenceEngine(
        cfg, params,
        EngineConfig(max_batch=2, page_size=8, num_pages=64,
                     max_pages_per_seq=8, prefill_buckets=(8, 16, 32)),
        kv_dtype=jnp.float32,
    )


class TestEngineSpans:
    def test_request_produces_queue_prefill_decode_emit(self, engine):
        root = tracing.start_trace(request_id="e1")
        engine.submit(GenRequest(
            request_id="er1", prompt_ids=[5, 9, 23, 4], max_new_tokens=4,
            trace=tracing.current(),
        ))
        engine.run_to_completion()
        tracing.finish_trace(root)
        tr = tracing.get_trace("e1")
        names = [s.name for s in tr.spans]
        for expected in ("engine.queue", "engine.prefill",
                        "engine.decode", "emit"):
            assert expected in names, (expected, names)
        # decode spans carry burst annotations (fused-step count + batch
        # occupancy) and every engine span parents to the carried context
        decode = [s for s in tr.spans if s.name == "engine.decode"]
        assert all(s.attrs["steps"] >= 1 and s.attrs["busy"] >= 1
                   for s in decode)
        assert all(s.parent_id == root.span_id for s in tr.spans
                   if s.name.startswith("engine."))
        # the emit span records the fetch/emit runway and stamps TTFT
        emit = next(s for s in tr.spans if s.name == "emit")
        assert emit.attrs["ttft_ms"] > 0

    def test_profiler_annotation_scope_keyed_by_trace_id(self, engine):
        """KAFKA_TPU_PROFILING=1: decode dispatches run inside a
        jax.profiler.TraceAnnotation scope named by the dispatched trace
        ids — the xplane/server-span correlation key.  Disabled (the
        default) it degrades to a nullcontext."""
        import contextlib

        req = GenRequest(request_id="prof-r", prompt_ids=[1, 2],
                         max_new_tokens=2)
        assert isinstance(engine._dispatch_scope([req]),
                          contextlib.nullcontext)
        tracing.configure(profiling=True)
        try:
            root = tracing.start_trace(request_id="prof1")
            req.trace = tracing.current()
            scope = engine._dispatch_scope([req, None])
            assert not isinstance(scope, contextlib.nullcontext)
            with scope:
                pass  # TraceAnnotation is harmless without a live capture
            # a traced end-to-end generation still works under the flag
            engine.submit(req)
            engine.run_to_completion()
            tracing.finish_trace(root)
        finally:
            tracing.configure(profiling=False)
        tr = tracing.get_trace("prof1")
        assert any(s.name == "engine.decode" for s in tr.spans)

    def test_untraced_request_records_nothing(self, engine):
        before = len(tracing.recent_traces())
        engine.submit(GenRequest(
            request_id="plain", prompt_ids=[1, 2, 3], max_new_tokens=3,
        ))
        engine.run_to_completion()
        assert len(tracing.recent_traces()) == before

    def test_preempt_event_lands_on_victim_trace(self, engine):
        root = tracing.start_trace(request_id="pe1")
        req = GenRequest(request_id="victim", prompt_ids=[1, 2, 3],
                         max_new_tokens=2, trace=tracing.current())
        engine._preempt(req)  # synthetic victim: no device state needed
        engine.waiting.remove(req)  # undo _preempt's re-queue
        tracing.finish_trace(root)
        tr = tracing.get_trace("pe1")
        assert [e["name"] for e in tr.events] == ["preempt"]


class TestQuarantineEvents:
    def test_quarantine_and_migrate_punctuate_the_trace(self):
        """A quarantine mid-request appears as a span event carrying the
        replica id; a queued request migrated off the sick replica gets a
        migrate event naming both replicas (acceptance: satellite 4)."""
        from kafka_tpu.runtime.dp_router import DataParallelEngines

        cfg = ModelConfig(name="trace-dp", vocab_size=128, hidden_size=64,
                          intermediate_size=128, num_layers=2, num_heads=4,
                          num_kv_heads=2, head_dim=16, dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(8))
        dp = DataParallelEngines(
            cfg, params,
            EngineConfig(max_batch=1, page_size=8, num_pages=64,
                         max_pages_per_seq=8, prefill_buckets=(8, 16),
                         max_parked=0),
            dp=2, tp=1, kv_dtype=jnp.float32,
            quarantine_threshold=1, quarantine_window_s=5.0,
        )
        assert [e.replica for e in dp.engines] == [0, 1]
        # two requests pinned to one replica: one starts (batch of 1),
        # one queues behind it and will migrate on quarantine
        roots, ctxs = [], []
        for i in range(2):
            roots.append(tracing.start_trace(request_id=f"dp{i}"))
            ctxs.append(tracing.current())
            dp.submit(GenRequest(
                request_id=f"q{i}", prompt_ids=[1, 2, 3],
                max_new_tokens=20, prefix_key="thread-q",
                trace=ctxs[-1],
            ))
        victim = dp._route["q0"]
        dp.step()  # q0 starts compute
        orig = dp.engines[victim].step

        def dead_step():
            raise RuntimeError("device lost")

        dp.engines[victim].step = dead_step
        terminal = {}
        for _ in range(200):
            try:
                events = dp.step()
            except Exception:
                events = dp.recover_from_failure()
            for ev in events:
                if ev.finished:
                    terminal[ev.request_id] = ev.finish_reason
            if not dp.has_work:
                break
        dp.engines[victim].step = orig
        for r in roots:
            tracing.finish_trace(r)
        assert terminal["q0"] == "error:engine"
        t0 = tracing.get_trace("dp0")
        ev_names = {e["name"] for e in t0.events}
        assert "quarantine" in ev_names
        q_ev = next(e for e in t0.events if e["name"] == "quarantine")
        assert q_ev["attrs"]["replica"] == victim
        assert "engine.recover" in ev_names
        # the queued request migrated (and finished on the survivor)
        t1 = tracing.get_trace("dp1")
        mig = [e for e in t1.events if e["name"] == "migrate"]
        assert mig and mig[0]["attrs"]["from_replica"] == victim
        assert terminal["q1"] == "length"


# ---------------------------------------------------------------------------
# cross-process propagation through a REAL sandbox subprocess
# ---------------------------------------------------------------------------


class TestCrossProcessStitching:
    def test_sandbox_child_spans_stitch_under_tool_exec(self):
        """Acceptance: a traced tool call executing in a real sandbox
        subprocess yields ONE stitched trace whose sandbox.exec span was
        recorded on the far side of the PID boundary (its pid differs)
        and parents under the client-side tool.exec span."""
        from kafka_tpu.sandbox.process import ProcessSandboxFactory
        from kafka_tpu.tools.provider import AgentToolProvider
        from kafka_tpu.sandbox.tools import shell_tools

        async def go():
            factory = ProcessSandboxFactory(boot_timeout_s=30,
                                            supervise=False)
            try:
                sbx = await factory.create("t-trace")
                provider = AgentToolProvider(
                    tools=[t.bind(sbx) for t in shell_tools()]
                )
                root = tracing.start_trace(request_id="xp1")
                events = []
                async for ev in provider.run_tool_stream(
                    "shell_exec", {"command": "echo traced"}, "call-1"
                ):
                    events.append(ev)
                tracing.finish_trace(root)
                assert any(
                    ev.kind == "result" and "traced" in (ev.data or "")
                    for ev in events
                )
                await sbx.aclose()
            finally:
                await factory.aclose()

        asyncio.run(go())
        tr = tracing.get_trace("xp1")
        tool = next(s for s in tr.spans if s.name == "tool.exec")
        child = next(s for s in tr.spans if s.name == "sandbox.exec")
        # recorded inside the subprocess: a DIFFERENT pid, stitched by
        # trace id, parented under the client-side tool.exec span
        assert child.pid != 0 and child.pid != os.getpid()
        assert tool.pid == os.getpid()
        assert child.parent_id == tool.span_id
        assert child.attrs["tool"] == "shell_exec"
        assert child.t1 is not None and child.t1 >= child.t0
        # the spans frame never leaked into tool output (asserted above:
        # only delta/result events were yielded)
        # and the chrome export shows both processes
        data = tracing.chrome_trace("xp1")
        pids = {e["pid"] for e in data["traceEvents"] if e["ph"] == "X"}
        assert len(pids) == 2


# ---------------------------------------------------------------------------
# slow-request log + counter (satellite)
# ---------------------------------------------------------------------------


class TestSlowRequests:
    def test_slow_total_threshold_logs_breakdown_and_counts(self, caplog):
        tracing.configure(slow_total_ms=0.001)
        root = tracing.start_trace(request_id="slow1")
        with tracing.span("agent.turn"):
            time.sleep(0.005)
        with caplog.at_level(logging.WARNING, logger="kafka_tpu.tracing"):
            tracing.finish_trace(root)
        assert tracing.slow_count() == 1
        rec = next(r for r in caplog.records
                   if getattr(r, "slow_request", False))
        assert rec.trace_id == tracing.get_trace("slow1").trace_id
        assert rec.total_ms > 0
        names = [s["name"] for s in rec.spans]
        assert names == ["http.request", "agent.turn"]

    def test_fast_request_does_not_count(self):
        tracing.configure(slow_total_ms=60_000)
        root = tracing.start_trace(request_id="fast1")
        tracing.finish_trace(root)
        assert tracing.slow_count() == 0

    def test_ttft_threshold_uses_emit_span(self):
        tracing.configure(slow_ttft_ms=0.001)
        root = tracing.start_trace(request_id="ttft1")
        ctx = tracing.current()
        time.sleep(0.004)
        tracing.record_span(ctx, "emit", 0.002)  # first token late
        tracing.finish_trace(root)
        assert tracing.slow_count() == 1


# ---------------------------------------------------------------------------
# structured JSON logging
# ---------------------------------------------------------------------------


class TestJsonLogging:
    def test_json_lines_carry_trace_and_thread_ids(self):
        from kafka_tpu.logs import JsonFormatter

        root = tracing.start_trace(request_id="log1")
        record = logging.LogRecord(
            "kafka_tpu.test", logging.INFO, __file__, 1,
            "hello %s", ("world",), None,
        )
        line = JsonFormatter().format(record)
        tracing.finish_trace(root)
        payload = json.loads(line)
        assert payload["msg"] == "hello world"
        assert payload["trace_id"] == tracing.get_trace("log1").trace_id
        assert payload["span_id"]
        assert isinstance(payload["thread_id"], int)
        assert payload["pid"] == os.getpid()

    def test_extra_fields_ride_along_and_win(self):
        from kafka_tpu.logs import JsonFormatter

        record = logging.LogRecord(
            "kafka_tpu.test", logging.WARNING, __file__, 1, "slow", (),
            None,
        )
        record.trace_id = "explicit-id"
        record.spans = [{"name": "emit", "dur_ms": 3}]
        payload = json.loads(JsonFormatter().format(record))
        assert payload["trace_id"] == "explicit-id"
        assert payload["spans"][0]["name"] == "emit"

    def test_setup_logging_is_idempotent(self):
        from kafka_tpu.logs import JsonFormatter, setup_logging

        root = logging.getLogger()
        before = list(root.handlers)
        try:
            setup_logging("json")
            setup_logging("json")
            assert len(root.handlers) == max(1, len(before))
            assert all(isinstance(h.formatter, JsonFormatter)
                       for h in root.handlers)
        finally:
            setup_logging("text")


# ---------------------------------------------------------------------------
# HTTP surface: middleware + /debug/trace endpoints
# ---------------------------------------------------------------------------


class TestTraceHTTP:
    def test_request_id_adoption_and_debug_endpoints(self, tmp_path):
        from tests.test_server import make_client, text_turn

        built, _, _ = make_client(tmp_path, [text_turn("hello")])

        async def go():
            client = await built
            try:
                r = await client.post(
                    "/v1/chat/completions",
                    json={"model": "fake-model",
                          "messages": [{"role": "user", "content": "hi"}]},
                    headers={"X-Request-Id": "req-abc"},
                )
                assert r.status == 200
                assert r.headers.get("X-Request-Id") == "req-abc"

                idx = await (await client.get("/debug/traces")).json()
                assert any(t["request_id"] == "req-abc"
                           for t in idx["traces"])

                d = await client.get("/debug/trace/req-abc")
                assert d.status == 200
                data = await d.json()
                names = {e["name"] for e in data["traceEvents"]
                         if e["ph"] == "X"}
                assert {"http.request", "agent.turn"} <= names
                root = next(e for e in data["traceEvents"]
                            if e["ph"] == "X"
                            and e["name"] == "http.request")
                assert root["args"]["status"] == 200

                missing = await client.get("/debug/trace/ghost")
                assert missing.status == 404
            finally:
                await client.close()

        asyncio.run(go())

    def test_threads_agent_path_with_sandboxed_tool_one_stitched_trace(
        self, tmp_path
    ):
        """Acceptance: one traced request through the threads agent path
        whose tool call executes in a REAL sandbox subprocess yields one
        Perfetto-loadable trace from /debug/trace/{request_id} holding
        http.request, agent.turn, tool.exec AND the sandbox.exec child
        recorded on the far side of the PID boundary (engine spans are
        covered by TestEngineSpans against a real engine)."""
        from aiohttp.test_utils import TestClient, TestServer
        from kafka_tpu.db import LocalDBClient
        from kafka_tpu.sandbox.process import ProcessSandboxFactory
        from kafka_tpu.sandbox.tools import shell_tools
        from kafka_tpu.server import ServingConfig, create_app
        from tests.test_server import FakeLLM, text_turn, tool_turn

        llm = FakeLLM([
            tool_turn("shell_exec", {"command": "echo from-sandbox"}),
            text_turn("done", cid="chatcmpl-tr2"),
        ])

        async def go():
            factory = ProcessSandboxFactory(boot_timeout_s=30,
                                            supervise=False)
            sbx = await factory.create("t-accept")
            app = await create_app(
                cfg=ServingConfig(db_path=str(tmp_path / "tr.db")),
                llm_provider=llm,
                db=LocalDBClient(str(tmp_path / "tr.db")),
                tools=[t.bind(sbx) for t in shell_tools()],
            )
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.post(
                    "/v1/threads/t-accept/chat/completions",
                    json={"model": "fake-model", "stream": True,
                          "messages": [{"role": "user",
                                        "content": "run it"}]},
                    headers={"X-Request-Id": "accept-1"},
                )
                assert r.status == 200
                body = await r.text()
                assert "from-sandbox" in body
                d = await client.get("/debug/trace/accept-1")
                assert d.status == 200
                return await d.json()
            finally:
                await client.close()
                await sbx.aclose()
                await factory.aclose()

        data = asyncio.run(go())
        spans = [e for e in data["traceEvents"] if e["ph"] == "X"]
        names = {e["name"] for e in spans}
        assert {"http.request", "agent.turn", "tool.exec",
                "sandbox.exec"} <= names
        child = next(e for e in spans if e["name"] == "sandbox.exec")
        tool = next(e for e in spans if e["name"] == "tool.exec")
        assert child["pid"] != os.getpid()  # recorded inside the sandbox
        assert child["args"]["parent_id"] == tool["args"]["span_id"]

    def test_sampled_out_requests_leave_no_trace(self, tmp_path):
        from tests.test_server import make_client, text_turn

        # build through make_client then dial sampling to 0 post-boot
        built, _, _ = make_client(tmp_path, [text_turn("ok")])

        async def go():
            client = await built
            try:
                tracing.configure(sample=0.0)
                r = await client.post(
                    "/v1/chat/completions",
                    json={"model": "fake-model",
                          "messages": [{"role": "user", "content": "hi"}]},
                )
                assert r.status == 200
                assert "X-Request-Id" not in r.headers
                idx = await (await client.get("/debug/traces")).json()
                assert idx["traces"] == []
            finally:
                await client.close()

        asyncio.run(go())
