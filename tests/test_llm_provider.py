"""LLM provider tier tests: TPULLMProvider streaming, tool-call decoding,
pre-flight context errors, usage accounting, cancellation, and the
incremental detokenizer.

Runs a tiny random-init model on the CPU backend (conftest forces 8 virtual
devices); the ByteTokenizer makes text<->token behavior exact and cheap.
"""

import asyncio

import pytest

import jax

from kafka_tpu.core.types import ContextLengthError, Message
from kafka_tpu.llm import IncrementalDetokenizer, TPULLMProvider
from kafka_tpu.llm.base import LLMProvider
from kafka_tpu.llm.utils import count_images, infer_provider_from_model, prune_images
from kafka_tpu.models import ModelConfig, init_params
from kafka_tpu.models.tokenizer import ByteTokenizer
from kafka_tpu.runtime import EngineConfig, InferenceEngine


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(scope="module")
def provider():
    tok = ByteTokenizer()
    cfg = ModelConfig(
        name="llm-test", vocab_size=tok.vocab_size, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, dtype="float32", max_context=2048,
    )
    params = init_params(cfg, jax.random.PRNGKey(11))
    eng = InferenceEngine(
        cfg, params,
        EngineConfig(max_batch=4, page_size=16, num_pages=128,
                     max_pages_per_seq=8, prefill_buckets=(16, 32, 64, 128)),
        kv_dtype=None,
    )
    p = TPULLMProvider(eng, tok, model_name="tiny-test")
    yield p
    run(p.aclose())


MESSAGES = [
    {"role": "system", "content": "You are a test model."},
    {"role": "user", "content": "Say something."},
]


class TestStreaming:
    def test_stream_shape(self, provider):
        async def go():
            chunks = []
            async for c in provider.stream_completion(
                MESSAGES, max_tokens=8, temperature=0.0
            ):
                chunks.append(c)
            return chunks

        chunks = run(go())
        # first chunk: role header; last: finish + usage
        assert chunks[0].role == "assistant"
        assert chunks[-1].finish_reason in ("stop", "length")
        assert chunks[-1].usage["completion_tokens"] >= 1
        assert chunks[-1].usage["prompt_tokens"] > 0
        # all chunks share one completion id
        assert len({c.id for c in chunks}) == 1

    def test_concurrent_streams_batch_together(self, provider):
        async def one(i):
            text = []
            async for c in provider.stream_completion(
                [{"role": "user", "content": f"prompt {i}"}],
                max_tokens=6, temperature=0.0,
            ):
                if c.content:
                    text.append(c.content)
            return "".join(text)

        async def go():
            return await asyncio.gather(*(one(i) for i in range(4)))

        outs = run(go())
        assert len(outs) == 4

    def test_completion_drains_stream(self, provider):
        resp = run(provider.completion(MESSAGES, max_tokens=6, temperature=0.0))
        assert resp.finish_reason in ("stop", "length")
        assert resp.usage["total_tokens"] > 0

    def test_deterministic_greedy(self, provider):
        r1 = run(provider.completion(MESSAGES, max_tokens=8, temperature=0.0))
        r2 = run(provider.completion(MESSAGES, max_tokens=8, temperature=0.0))
        assert r1.content == r2.content

    def test_context_length_preflight(self, provider):
        big = [{"role": "user", "content": "x" * 5000}]
        with pytest.raises(ContextLengthError) as ei:
            run(provider.completion(big))
        # error string must satisfy the reference-style classifier
        from kafka_tpu.llm.compaction import is_context_length_error

        assert is_context_length_error(ei.value)

    def test_validate_rejects_orphan_tool_message(self, provider):
        from kafka_tpu.core.types import LLMProviderError

        bad = [
            {"role": "user", "content": "hi"},
            {"role": "tool", "content": "res", "tool_call_id": "call_x"},
        ]
        with pytest.raises(LLMProviderError):
            run(provider.completion(bad))

    def test_image_parts_rejected_loudly(self, provider):
        """VERDICT r3 missing #1 decision: the text-only engine REJECTS
        image parts with a typed 400 instead of silently flattening them
        (reference forwarded them to multimodal models,
        src/llm/portkey.py:276)."""
        from kafka_tpu.core.types import UnsupportedContentError

        msgs = [{"role": "user", "content": [
            {"type": "text", "text": "what is this?"},
            {"type": "image_url", "image_url": {"url": "data:image/png;base64,x"}},
        ]}]
        with pytest.raises(UnsupportedContentError) as ei:
            run(provider.completion(msgs))
        assert ei.value.status_code == 400
        assert ei.value.n_parts == 1
        # text-only multi-part content still serves
        ok = [{"role": "user", "content": [{"type": "text", "text": "hi"}]}]
        resp = run(provider.completion(ok, max_tokens=2))
        assert resp.finish_reason in ("stop", "length")

    def test_cancellation_frees_engine(self, provider):
        async def go():
            agen = provider.stream_completion(
                [{"role": "user", "content": "long"}], max_tokens=400,
                temperature=0.0,
            )
            async for c in agen:
                if c.content:
                    break
            await agen.aclose()
            # give the worker a beat to process the cancel
            for _ in range(100):
                if provider.engine.num_active == 0 and not provider.engine.waiting:
                    break
                await asyncio.sleep(0.02)
            return provider.engine.num_active, len(provider.engine.waiting)

        active, waiting = run(go())
        assert active == 0 and waiting == 0

    def test_message_objects_accepted(self, provider):
        msgs = [Message(role="user", content="hello")]
        resp = run(provider.completion(msgs, max_tokens=4))
        assert resp.role == "assistant"


class TestToolCallDecoding:
    def test_constrained_tool_call_stream(self, provider):
        """Force the model to emit a tool-call JSON via constrained decoding
        and check it surfaces as OpenAI tool_calls, not content."""
        tok = provider.tokenizer
        script = '{"name": "get_weather", "parameters": {"city": "Paris"}}'
        script_ids = tok.encode(script) + [tok.eot_id]

        def mask(output_ids):
            i = len(output_ids)
            return [script_ids[i]] if i < len(script_ids) else [tok.eot_id]

        async def go():
            chunks = []
            async for c in provider.stream_completion(
                [{"role": "user", "content": "weather?"}],
                max_tokens=len(script_ids) + 2,
                temperature=0.0,
                logits_mask_fn=mask,
            ):
                chunks.append(c)
            return chunks

        chunks = run(go())
        final = chunks[-1]
        assert final.finish_reason == "tool_calls"
        tc_chunks = [c for c in chunks if c.tool_calls]
        assert len(tc_chunks) == 1
        call = tc_chunks[0].tool_calls[0]
        assert call["function"]["name"] == "get_weather"
        assert '"Paris"' in call["function"]["arguments"]
        # no content chunks leaked while buffering
        assert not any(c.content for c in chunks)

    def test_plain_text_streams_incrementally(self, provider):
        tok = provider.tokenizer
        script = "hello world, this is streamed"
        script_ids = tok.encode(script) + [tok.eot_id]

        def mask(output_ids):
            i = len(output_ids)
            return [script_ids[i]] if i < len(script_ids) else [tok.eot_id]

        async def go():
            content_chunks = 0
            text = []
            async for c in provider.stream_completion(
                [{"role": "user", "content": "speak"}],
                max_tokens=len(script_ids) + 2, temperature=0.0,
                logits_mask_fn=mask,
            ):
                if c.content:
                    content_chunks += 1
                    text.append(c.content)
            return content_chunks, "".join(text)

        n, text = run(go())
        assert text == script
        assert n > 1  # streamed, not buffered into one chunk


class TestDetokenizer:
    def test_utf8_multibyte_held_back(self):
        tok = ByteTokenizer()
        detok = IncrementalDetokenizer(tok)
        ids = tok.encode("héllo ✓")
        out = []
        for t in ids:
            out.append(detok.push(t))
        out.append(detok.flush())
        assert "".join(out) == "héllo ✓"
        # no replacement characters ever emitted
        assert "�" not in "".join(out)

    def test_flush_emits_partial(self):
        tok = ByteTokenizer()
        detok = IncrementalDetokenizer(tok)
        ids = tok.encode("é")  # two bytes
        assert detok.push(ids[0]) == ""  # incomplete, held
        assert detok.push(ids[1]) == "é"
        assert detok.flush() == ""


class TestUtils:
    def test_provider_routing(self):
        assert infer_provider_from_model("gpt-4o") == "openai"
        assert infer_provider_from_model("claude-sonnet-4-5") == "anthropic"
        assert infer_provider_from_model("gemini-2.0-flash") == "google"
        assert infer_provider_from_model("llama-3.2-1b") == "tpu"

    def test_prune_images_keeps_newest(self):
        def img(i):
            return {"type": "image_url", "image_url": {"url": f"u{i}"}}

        msgs = [
            {"role": "user", "content": [img(0), {"type": "text", "text": "a"}]},
            {"role": "user", "content": [img(1), img(2)]},
        ]
        out = prune_images(msgs, max_images=1)
        assert count_images(out) == 1
        # the newest image survives
        assert out[1]["content"][1]["type"] == "image_url"
        # originals untouched
        assert count_images(msgs) == 3

    def test_prune_images_noop_under_cap(self):
        msgs = [{"role": "user", "content": "no images"}]
        assert prune_images(msgs, 19) is msgs


class TestModelInfo:
    def test_get_model_info(self, provider):
        info = provider.get_model_info()
        assert info["provider"] == "tpu"
        assert info["max_context"] == 2048
        assert info["supports_tools"]

    def test_available_models(self, provider):
        models = provider.get_available_models()
        assert models[0]["id"] == "tiny-test"

    def test_abc_contract(self):
        assert issubclass(TPULLMProvider, LLMProvider)
