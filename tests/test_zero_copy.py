"""Zero-host-copy KV movement (ISSUE 19): device-to-device page
shipping, wake prefetch, and multipart object puts.

The load-bearing claims:
  * the DeviceShipper round-trips page runs byte-exact (float32 + bf16,
    single- and multi-chunk) with the same torn-chunk chaos contract as
    the host transport (kv.ship fires once per chunk, error:nth=2
    raises mid-run),
  * KAFKA_TPU_SHIP_TRANSPORT resolves conservatively: unset/unknown ->
    host, auto -> device only when BOTH owners' pools are in-process
    jax arrays, explicit modes taken at their word,
  * host and device transports land byte-identical destination pools,
    and only the host path ever arms the process-wide staging
    accounting (device ship pins zero host bytes),
  * the WakePrefetcher is an overlap optimization, never a correctness
    dependency: single-flight per content key, staged payloads are the
    same bytes the sync fetch returns, queued-unstarted entries are
    reclaimed for the sync path, failures/cancellations degrade with no
    staged residue, the byte budget evicts oldest-ready-first, and a
    tripped store breaker stops scheduling entirely,
  * HTTPObjectStore puts above KAFKA_TPU_KV_OBJECT_MULTIPART_MB go
    initiate/part/complete, abort server-side on failure (no orphan
    object, no orphan upload), and reland identically under StoreGuard
    retry,
  * with every knob unset the three legs are bit-identical to the old
    behavior: host transport, no prefetcher, monolithic puts.
"""

import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.runtime import failpoints
from kafka_tpu.runtime.kv_tier import (
    ENV_SHIP_TRANSPORT,
    CrossReplicaPageShipper,
    DeviceShipper,
    resolve_ship_transport,
    ship_staging_bytes,
    ship_staging_peak,
    ship_transport_from_env,
)
from kafka_tpu.runtime.object_tier import (
    ENV_OBJECT_MULTIPART_MB,
    ENV_WAKE_PREFETCH_MB,
    HTTPObjectStore,
    LocalFSObjectStore,
    ObjectTier,
    WakePrefetcher,
    object_multipart_bytes,
)
from kafka_tpu.runtime.store_guard import (
    BREAKER_OPEN,
    CircuitBreaker,
    StoreGuard,
)

from objstore_stub import StubS3Server

MiB = 1 << 20


class _Owner:
    """Minimal pool-array holder standing in for a replica engine (the
    shipper only needs mutable k_pool/v_pool)."""

    def __init__(self, num_pages, page_size, layers=2, width=8, seed=0,
                 dtype=np.float32):
        rng = np.random.default_rng(seed)
        shape = (layers, num_pages * page_size, width)
        self.k_pool = jnp.asarray(
            rng.normal(size=shape).astype(np.float32)
        ).astype(dtype)
        self.v_pool = jnp.asarray(
            rng.normal(size=shape).astype(np.float32)
        ).astype(dtype)


class _HostOwner:
    """An owner whose pools are NOT jax arrays (a cross-process
    transport stub holding opaque handles): auto must pick host."""

    def __init__(self, num_pages, page_size, layers=1, width=4):
        shape = (layers, num_pages * page_size, width)
        self.k_pool = np.zeros(shape, np.float32)
        self.v_pool = np.zeros(shape, np.float32)


def _rows(owner, pages, page_size, pool="k"):
    arr = np.asarray(owner.k_pool if pool == "k" else owner.v_pool)
    return np.concatenate(
        [arr[:, p * page_size:(p + 1) * page_size] for p in pages], axis=1
    )


# ---------------------------------------------------------------------------
# leg (a): device-to-device ship transport
# ---------------------------------------------------------------------------


class TestDeviceShipper:
    @pytest.mark.parametrize("dtype", [np.float32, "bfloat16"])
    def test_round_trip_byte_exact(self, dtype):
        if dtype == "bfloat16":
            import ml_dtypes

            dtype = ml_dtypes.bfloat16
        ps = 4
        src = _Owner(16, ps, seed=11, dtype=dtype)
        dst = _Owner(16, ps, seed=12, dtype=dtype)
        ship = CrossReplicaPageShipper(src, dst, ps, transport="device")
        assert ship.transport == "device"
        src_pages, dst_pages = [3, 7, 5], [9, 2, 11]
        want_k = _rows(src, src_pages, ps, "k")
        want_v = _rows(src, src_pages, ps, "v")
        nbytes = ship.ship(src_pages, dst_pages)
        assert nbytes == len(src_pages) * ship.bytes_per_page()
        np.testing.assert_array_equal(
            _rows(dst, dst_pages, ps, "k").view(np.uint8),
            want_k.view(np.uint8),
        )
        np.testing.assert_array_equal(
            _rows(dst, dst_pages, ps, "v").view(np.uint8),
            want_v.view(np.uint8),
        )

    def test_multi_chunk_round_trip(self):
        # 67 pages exceed the largest SHIP_BUCKET (64): two chunks
        ps = 2
        src = _Owner(80, ps, layers=1, width=4, seed=13)
        dst = _Owner(80, ps, layers=1, width=4, seed=14)
        ship = CrossReplicaPageShipper(src, dst, ps, transport="device")
        src_pages = list(range(1, 68))
        dst_pages = list(range(10, 77))
        want = _rows(src, src_pages, ps, "k")
        ship.ship(src_pages, dst_pages)
        np.testing.assert_array_equal(
            _rows(dst, dst_pages, ps, "k"), want
        )

    def test_torn_chunk_raises(self):
        # the kv.ship failpoint must fire once per chunk on the device
        # path too, so chaos rules behave identically across transports
        ps = 2
        src = _Owner(80, ps, layers=1, width=4, seed=15)
        dst = _Owner(80, ps, layers=1, width=4, seed=16)
        ship = CrossReplicaPageShipper(src, dst, ps, transport="device")
        with failpoints.armed("kv.ship", "error", "torn", nth=2):
            with pytest.raises(failpoints.FailpointError):
                ship.ship(list(range(1, 68)), list(range(10, 77)))

    def test_host_device_parity(self):
        # both transports are the same copy: identical destination bytes
        ps = 4
        src = _Owner(16, ps, seed=21)
        dst_h = _Owner(16, ps, seed=22)
        dst_d = _Owner(16, ps, seed=22)
        pages, dest = [1, 9, 4, 12], [3, 8, 0, 14]
        nb_h = CrossReplicaPageShipper(
            src, dst_h, ps, transport="host"
        ).ship(pages, dest)
        nb_d = CrossReplicaPageShipper(
            src, dst_d, ps, transport="device"
        ).ship(pages, dest)
        assert nb_h == nb_d
        np.testing.assert_array_equal(
            _rows(dst_h, dest, ps, "k").view(np.uint8),
            _rows(dst_d, dest, ps, "k").view(np.uint8),
        )
        np.testing.assert_array_equal(
            _rows(dst_h, dest, ps, "v").view(np.uint8),
            _rows(dst_d, dest, ps, "v").view(np.uint8),
        )

    def test_device_ship_pins_no_host_bytes(self):
        ps = 4
        src = _Owner(16, ps, seed=31)
        dst = _Owner(16, ps, seed=32)
        ship_staging_peak(reset=True)
        CrossReplicaPageShipper(src, dst, ps, transport="device").ship(
            [1, 2, 3], [5, 6, 7]
        )
        assert ship_staging_peak() == 0
        assert ship_staging_bytes() == 0
        # the host path DOES arm the peak (and releases on completion)
        CrossReplicaPageShipper(src, dst, ps, transport="host").ship(
            [1, 2, 3], [5, 6, 7]
        )
        assert ship_staging_peak(reset=True) > 0
        assert ship_staging_bytes() == 0


class TestTransportResolution:
    def test_env_knob_defaults_to_host(self, monkeypatch):
        monkeypatch.delenv(ENV_SHIP_TRANSPORT, raising=False)
        assert ship_transport_from_env() == "host"
        monkeypatch.setenv(ENV_SHIP_TRANSPORT, "carrier-pigeon")
        assert ship_transport_from_env() == "host"
        for mode in ("auto", "host", "device", " DEVICE "):
            monkeypatch.setenv(ENV_SHIP_TRANSPORT, mode)
            assert ship_transport_from_env() == mode.strip().lower()

    def test_auto_picks_device_for_jax_pools(self):
        src, dst = _Owner(4, 2), _Owner(4, 2)
        assert resolve_ship_transport(src, dst, "auto") == "device"

    def test_auto_picks_host_for_foreign_pools(self):
        # either side off-process (non-jax pools) forces the wire path
        jx, hp = _Owner(4, 2), _HostOwner(4, 2)
        assert resolve_ship_transport(jx, hp, "auto") == "host"
        assert resolve_ship_transport(hp, jx, "auto") == "host"
        assert resolve_ship_transport(hp, hp, "auto") == "host"

    def test_explicit_modes_taken_at_word(self):
        src, dst = _Owner(4, 2), _Owner(4, 2)
        assert resolve_ship_transport(src, dst, "host") == "host"
        assert resolve_ship_transport(src, dst, "device") == "device"

    def test_shipper_reads_env(self, monkeypatch):
        src, dst = _Owner(4, 2), _Owner(4, 2)
        monkeypatch.delenv(ENV_SHIP_TRANSPORT, raising=False)
        assert CrossReplicaPageShipper(src, dst, 2).transport == "host"
        monkeypatch.setenv(ENV_SHIP_TRANSPORT, "auto")
        assert CrossReplicaPageShipper(src, dst, 2).transport == "device"
        monkeypatch.setenv(ENV_SHIP_TRANSPORT, "device")
        shp = CrossReplicaPageShipper(src, dst, 2)
        assert shp.transport == "device"
        assert isinstance(shp._device, DeviceShipper)


# ---------------------------------------------------------------------------
# leg (b): wake prefetch
# ---------------------------------------------------------------------------


def _leaves(seed=7):
    rng = np.random.default_rng(seed)
    return ([rng.normal(size=(2, 8, 4)).astype(np.float32)],
            [rng.normal(size=(2, 8, 4)).astype(np.float32)])


def _archive_two_runs(tmp_path):
    """A tier with one thread's 2-run manifest archived: 16 tokens at
    page_size=4, runs of 8 tokens / 2 pages each (path-addressed like
    the real sleep path writes them)."""
    tier = ObjectTier(LocalFSObjectStore(str(tmp_path)),
                      fingerprint="zc", page_size=4)
    toks = list(range(100, 116))
    k1, v1 = _leaves(1)
    k2, v2 = _leaves(2)
    key1 = tier.put_run(toks[:8], k1, v1, 2)
    key2 = tier.put_run(toks, k2, v2, 2)
    assert key1 and key2
    assert tier.write_manifest("thr", toks, [
        {"key": key1, "tokens": 8, "pages": 2},
        {"key": key2, "tokens": 8, "pages": 2},
    ])
    return tier, key1, key2


def _wait(cond, timeout=5.0):
    t0 = time.monotonic()
    while not cond():
        if time.monotonic() - t0 > timeout:
            raise AssertionError("condition not met in time")
        time.sleep(0.005)


class _GatedStore:
    """LocalFS wrapper whose GETs block on an event (deterministic
    queued-vs-started staging states without wall-clock sleeps)."""

    def __init__(self, inner):
        self.inner = inner
        self.gate = threading.Event()
        self.gate.set()

    def get(self, key):
        assert self.gate.wait(timeout=10.0)
        return self.inner.get(key)

    def __getattr__(self, name):
        return getattr(self.inner, name)


class TestWakePrefetcher:
    def test_from_env(self, tmp_path, monkeypatch):
        tier = ObjectTier(LocalFSObjectStore(str(tmp_path)))
        monkeypatch.delenv(ENV_WAKE_PREFETCH_MB, raising=False)
        assert WakePrefetcher.from_env(tier) is None
        monkeypatch.setenv(ENV_WAKE_PREFETCH_MB, "not-a-number")
        assert WakePrefetcher.from_env(tier) is None
        monkeypatch.setenv(ENV_WAKE_PREFETCH_MB, "8")
        pre = WakePrefetcher.from_env(tier)
        assert pre is not None and pre.budget_bytes == 8 * MiB

    def test_fetch_run_without_prefetcher_is_get_run(self, tmp_path):
        tier, key1, _ = _archive_two_runs(tmp_path)
        assert tier.prefetcher is None
        got = tier.fetch_run(key1)
        assert got is not None and got[2] == 2
        assert tier.prefetch_hits == 0 and tier.prefetch_bytes == 0

    def test_staged_payload_matches_sync_fetch(self, tmp_path):
        tier, key1, key2 = _archive_two_runs(tmp_path)
        want = tier.get_run(key1)
        tier.prefetcher = pre = WakePrefetcher(tier, 64 * MiB)
        pre.stage_runs([key1, key2], "thr")
        got = tier.fetch_run(key1)  # waits out the inflight fetch
        assert got is not None
        for a, b in zip(want[0] + want[1], got[0] + got[1]):
            np.testing.assert_array_equal(
                a.view(np.uint8), b.view(np.uint8)
            )
        assert got[2:] == want[2:]
        assert tier.fetch_run(key2) is not None
        assert tier.prefetch_hits == 2
        assert tier.prefetch_bytes > 0
        assert pre.staged_bytes() == 0  # both consumed

    def test_single_flight_per_content_key(self, tmp_path):
        tier, key1, _ = _archive_two_runs(tmp_path)
        store = _GatedStore(tier.store)
        tier.store = store
        store.gate.clear()
        pre = WakePrefetcher(tier, 64 * MiB, workers=2)
        assert pre._begin(key1, "thr") is True
        assert pre._begin(key1, "thr") is False  # already staged
        pre.stage_runs([key1], "thr")  # idempotent too
        with pre._lock:
            assert len(pre._staged) == 1
        store.gate.set()
        assert pre.take(key1) is not None
        assert tier.prefetch_hits == 1

    def test_take_reclaims_queued_unstarted(self, tmp_path):
        # one worker, gated store: key1 starts and blocks, key2 stays
        # queued — take(key2) must hand it to the sync path, never wait
        tier, key1, key2 = _archive_two_runs(tmp_path)
        store = _GatedStore(tier.store)
        tier.store = store
        store.gate.clear()
        pre = WakePrefetcher(tier, 64 * MiB, workers=1)
        pre.stage_runs([key1, key2], "thr")
        _wait(lambda: pre._staged[key1].started)
        assert not pre._staged[key2].started
        assert pre.take(key2) is None  # reclaimed, not awaited
        with pre._lock:
            assert key2 not in pre._staged
        store.gate.set()
        assert pre.take(key1) is not None
        assert tier.prefetch_hits == 1
        # the doomed key2 worker run stages nothing when it drains
        _wait(lambda: pre.inflight() == 0)
        assert pre.staged_bytes() == 0

    def test_budget_evicts_oldest_ready_first(self, tmp_path):
        tier, key1, key2 = _archive_two_runs(tmp_path)
        n1 = tier.get_run(key1)[3]
        tier.prefetcher = pre = WakePrefetcher(tier, n1 + 1)
        pre.stage_runs([key1, key2], "thr")
        _wait(lambda: pre.staged_bytes() <= n1 + 1 and
              all(e.event.is_set() for e in list(pre._staged.values())))
        # both landed; the budget holds one: key1 (oldest) was evicted
        assert tier.prefetch_wasted == 1
        assert pre.take(key1) is None
        assert pre.take(key2) is not None

    def test_budget_full_rejects_new_staging(self, tmp_path):
        tier, key1, key2 = _archive_two_runs(tmp_path)
        n1 = tier.get_run(key1)[3]
        pre = WakePrefetcher(tier, n1)  # exactly one run fits
        assert pre._begin(key1, "thr") is True
        _wait(lambda: pre.staged_bytes() >= n1)
        assert pre._begin(key2, "thr") is False  # staging full
        assert pre.take(key2) is None  # caller falls back to sync

    def test_cancel_thread_drops_ready_payloads(self, tmp_path):
        tier, key1, key2 = _archive_two_runs(tmp_path)
        tier.prefetcher = pre = WakePrefetcher(tier, 64 * MiB)
        pre.stage_runs([key1, key2], "thr")
        _wait(lambda: pre.staged_bytes() > 0 and pre.inflight() == 0)
        pre.cancel_thread("thr")
        assert tier.prefetch_wasted == 2
        assert pre.staged_bytes() == 0
        assert pre.take(key1) is None and pre.take(key2) is None
        # degrade is clean: the sync path still serves the wake
        assert tier.fetch_run(key1) is not None

    def test_failed_fetch_degrades_to_sync(self, tmp_path):
        tier, key1, _ = _archive_two_runs(tmp_path)
        tier.prefetcher = pre = WakePrefetcher(tier, 64 * MiB)
        with failpoints.armed("kv.prefetch", "error", "boom"):
            assert pre._begin(key1, "thr") is True
            _wait(lambda: key1 not in pre._staged)
        assert pre.staged_bytes() == 0  # no residue
        assert tier.prefetch_hits == 0
        got = tier.fetch_run(key1)  # sync path, exactly today's
        assert got is not None and got[2] == 2

    def test_breaker_open_stops_scheduling(self, tmp_path):
        class _DeadStore:
            def get(self, key):
                raise OSError("store down")

        guard = StoreGuard(
            _DeadStore(), retries=0, backoff_s=0.0,
            breaker=CircuitBreaker(failure_threshold=1,
                                   open_window_s=60.0),
        )
        tier = ObjectTier(guard, fingerprint="zc", page_size=4)
        assert tier.get_run("deadbeef") is None  # trips the breaker
        assert guard.breaker.state == BREAKER_OPEN
        assert tier.available() is False
        pre = WakePrefetcher(tier, 64 * MiB)
        assert pre.prefetch_thread("thr") is False  # degrade at the gate

    def test_prefetch_thread_stages_manifest_runs(self, tmp_path):
        tier, key1, key2 = _archive_two_runs(tmp_path)
        tier.prefetcher = pre = WakePrefetcher(tier, 64 * MiB)
        assert pre.prefetch_thread("thr") is True
        _wait(lambda: pre.staged_bytes() > 0 and pre.inflight() == 0
              and len(pre._staged) == 2)
        assert tier.fetch_run(key1) is not None
        assert tier.fetch_run(key2) is not None
        assert tier.prefetch_hits == 2

    def test_prefetch_thread_skips_locally_covered_runs(self, tmp_path):
        # min_depth = the replica's radix match: run1 (8 tokens) is
        # wholly covered, so a wake would skip it — prefetch must too
        tier, key1, key2 = _archive_two_runs(tmp_path)
        tier.prefetcher = pre = WakePrefetcher(tier, 64 * MiB)
        assert pre.prefetch_thread("thr", min_depth=8) is True
        _wait(lambda: pre.inflight() == 0 and len(pre._staged) == 1)
        with pre._lock:
            assert key1 not in pre._staged and key2 in pre._staged
        assert pre.take(key2) is not None


# ---------------------------------------------------------------------------
# leg (c): multipart object puts
# ---------------------------------------------------------------------------


def _body(n, seed=5):
    return bytes(np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8
    ))


class TestMultipartPut:
    def test_threshold_routes_large_puts(self):
        with StubS3Server() as srv:
            st = HTTPObjectStore(srv.url)
            st.multipart_bytes = 256 * 1024
            small = _body(64 * 1024, 1)
            big = _body(600 * 1024, 2)  # 3 parts of <=256K
            st.put("runs/small", small)
            assert st.multipart_puts == 0  # at/below threshold: simple
            st.put("runs/big", big)
            assert st.multipart_puts == 1
            assert srv.completed_uploads == 1
            assert srv.uploads == {}  # no orphan upload state
            assert st.get("runs/small") == small
            assert st.get("runs/big") == big
            h = st.head("runs/big")
            assert h is not None and h[0] == len(big)

    def test_part_failure_aborts_server_side(self):
        with StubS3Server() as srv:
            st = HTTPObjectStore(srv.url)
            st.multipart_bytes = 256 * 1024
            srv.fail_parts = 1
            with pytest.raises(OSError):
                st.put("runs/torn", _body(600 * 1024, 3))
            assert st.multipart_aborts == 1
            assert st.multipart_puts == 0
            assert st.get("runs/torn") is None  # no partial object
            assert srv.uploads == {}  # aborted, not orphaned

    def test_guard_retry_relands_identically(self):
        with StubS3Server() as srv:
            st = HTTPObjectStore(srv.url)
            st.multipart_bytes = 256 * 1024
            g = StoreGuard(st, retries=2, backoff_s=0.0)
            srv.fail_parts = 1
            data = _body(600 * 1024, 4)
            g.put("runs/retry", data)  # attempt 1 aborts, attempt 2 lands
            assert g.retries_total >= 1
            assert st.multipart_aborts == 1
            assert st.multipart_puts == 1
            assert srv.completed_uploads == 1
            assert srv.uploads == {}
            assert st.get("runs/retry") == data

    def test_put_deadline_scales_with_request_count(self, monkeypatch):
        monkeypatch.setenv(ENV_OBJECT_MULTIPART_MB, "4")
        assert StoreGuard._put_deadline_scale(1 * MiB) == 1
        assert StoreGuard._put_deadline_scale(4 * MiB) == 1
        assert StoreGuard._put_deadline_scale(10 * MiB) == 3
        monkeypatch.delenv(ENV_OBJECT_MULTIPART_MB, raising=False)
        assert StoreGuard._put_deadline_scale(10 * MiB) == 1


# ---------------------------------------------------------------------------
# disabled-knob bit-identity
# ---------------------------------------------------------------------------


class TestKnobsOffBitIdentity:
    def test_all_three_legs_default_off(self, tmp_path, monkeypatch):
        for knob in (ENV_SHIP_TRANSPORT, ENV_WAKE_PREFETCH_MB,
                     ENV_OBJECT_MULTIPART_MB):
            monkeypatch.delenv(knob, raising=False)
        # (a) host transport, exactly the pre-ISSUE-19 path
        src, dst = _Owner(4, 2), _Owner(4, 2)
        assert CrossReplicaPageShipper(src, dst, 2).transport == "host"
        # (b) no prefetcher attaches; fetch_run degenerates to get_run
        tier, key1, _ = _archive_two_runs(tmp_path)
        assert WakePrefetcher.from_env(tier) is None
        assert tier.fetch_run(key1) is not None
        assert tier.prefetch_hits == 0
        # (c) monolithic puts only
        assert object_multipart_bytes() == 0
        with StubS3Server() as srv:
            st = HTTPObjectStore(srv.url)
            assert st.multipart_bytes == 0
            st.put("runs/x", _body(600 * 1024, 6))
            assert st.multipart_puts == 0 and srv.completed_uploads == 0
