"""Device-truth telemetry (ISSUE 18).

The load-bearing claims:
  * every XLA compilation lands in the compile observatory's bounded
    ring — label, wall seconds, cache disposition, serving phase — and
    the storm detector holds only for first_traffic-phase churn,
  * with KAFKA_TPU_COMPILE_RING=0 nothing is constructed: instrument()
    returns the function object unchanged and engine outputs are
    BIT-IDENTICAL to an observed build,
  * the MemoryMonitor reconciles measured device bytes against the
    boot MemoryPlan (worst-device aggregation, plan_skew, watermark
    pressure) and synthesizes plan-sourced samples on chips without
    memory_stats so CPU CI runs the same export path,
  * KAFKA_TPU_PROFILE_SAMPLE=N traces every Nth engine.step into a
    bounded spill dir and serves per-kernel device durations by
    dispatch kind; unset = no sampler with byte-identical outputs,
  * COMPILE/MEMORY metric keys are both-directions registries across
    runtime/metrics.py and server/prometheus.py,
  * GET /debug/compiles and /debug/kernels answer 404-when-off and
    serve the live payloads when on; /admin/signals is version 7 with
    the compiles/memory sections,
  * the bench device_truth phase (sampling overhead A/B + warm-vs-cold
    rebuild outage) runs.
"""

import os
import time
from types import SimpleNamespace

import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.models import ModelConfig, init_params
from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine
from kafka_tpu.runtime import compile_log, kernel_profiler
from kafka_tpu.runtime.compile_log import CompileObservatory
from kafka_tpu.runtime.kernel_profiler import KernelSampler
from kafka_tpu.runtime.metrics import (
    COMPILE_METRIC_KEYS,
    MEMORY_METRIC_KEYS,
    UTILIZATION_METRIC_KEYS,
    EngineMetrics,
)
from kafka_tpu.runtime.planner import MemoryMonitor


def tiny_cfg():
    # dims deliberately distinct from every other test module so this
    # module's first dispatches MISS the process _FN_CACHE and really
    # compile (the observatory integration tests depend on that)
    return ModelConfig(
        name="device-truth-test", vocab_size=322, hidden_size=64,
        intermediate_size=144, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, dtype="float32",
    )


def make_engine(params=None, cfg=None, **ecfg_kw):
    cfg = cfg or tiny_cfg()
    if params is None:
        params = init_params(cfg, jax.random.PRNGKey(0))
    kw = dict(max_batch=2, page_size=8, num_pages=64, max_pages_per_seq=8,
              prefill_buckets=(8, 16, 32))
    kw.update(ecfg_kw)
    return InferenceEngine(cfg, params, EngineConfig(**kw),
                           kv_dtype=jnp.float32)


@pytest.fixture(scope="module")
def shared():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(autouse=True)
def _reset_observatory():
    """The observatory is a process singleton; never leak one into
    other tests (its listeners are no-ops while the singleton is
    None)."""
    compile_log.reset_for_tests()
    yield
    compile_log.reset_for_tests()


def run_requests(engine, n=3, prompt_len=15, gen=8, seed_base=0):
    for i in range(n):
        engine.submit(GenRequest(
            request_id=f"dt{seed_base}-{i}",
            prompt_ids=list(range(5, 5 + prompt_len)),
            max_new_tokens=gen,
        ))
    return engine.run_to_completion()


# ---------------------------------------------------------------------------
# compile observatory unit behavior
# ---------------------------------------------------------------------------


class TestObservatoryUnit:
    def test_ring_wraps_at_size(self):
        obs = CompileObservatory(4)
        for i in range(7):
            obs.record(f"fn{i}", 0.1, now=100.0 + i)
        recs = obs.records()
        assert len(recs) == 4
        assert [r["seq"] for r in recs] == [3, 4, 5, 6]
        assert obs.compiles_total == 7
        assert obs.next_seq == 7

    def test_zero_size_rejected(self):
        with pytest.raises(ValueError, match="size"):
            CompileObservatory(0)

    def test_ring_default_env(self, monkeypatch):
        monkeypatch.delenv(compile_log.RING_ENV, raising=False)
        assert compile_log.ring_default() == 256
        monkeypatch.setenv(compile_log.RING_ENV, "0")
        assert compile_log.ring_default() == 0
        monkeypatch.setenv(compile_log.RING_ENV, "-5")
        assert compile_log.ring_default() == 0
        monkeypatch.setenv(compile_log.RING_ENV, "banana")
        assert compile_log.ring_default() == 256
        monkeypatch.setenv(compile_log.RING_ENV, "17")
        assert compile_log.ring_default() == 17

    def test_cache_disposition_defaults(self):
        obs = CompileObservatory(8)
        obs.record("a", 0.2)
        assert obs.records()[-1]["cache"] == "off"
        obs.cache_dir = "/tmp/cache"
        obs.record("b", 0.2)
        assert obs.records()[-1]["cache"] == "miss"
        # the cache-hit event rewrites the in-flight label's record
        obs._push_label("b")
        obs.mark_cache_hit()
        assert obs.records()[-1]["cache"] == "hit"
        assert obs.by_cache == {"hit": 1, "miss": 0, "off": 1}

    def test_phase_attribution(self):
        obs = CompileObservatory(8)
        assert obs.phase == "boot"
        obs.record("boot_fn", 0.1)
        obs.phase = "warmup"
        obs.record("warm_fn", 0.1)
        obs.phase = "rebuild"
        obs.record("rebuild_fn", 0.1)
        assert obs.by_phase["boot"] == 1
        assert obs.by_phase["warmup"] == 1
        assert obs.by_phase["rebuild"] == 1
        assert obs.by_phase["first_traffic"] == 0

    def test_storm_only_in_first_traffic(self, monkeypatch):
        monkeypatch.setenv(compile_log.STORM_N_ENV, "3")
        monkeypatch.setenv(compile_log.STORM_S_ENV, "60")
        obs = CompileObservatory(16)
        # boot/warmup/rebuild compiles never count toward a storm
        for phase in ("boot", "warmup", "rebuild"):
            obs.phase = phase
            for i in range(4):
                obs.record("x", 0.1, now=100.0 + i)
        assert not obs.storm_active(now=105.0)
        assert obs.storms_total == 0
        # three first_traffic compiles inside the window = a storm,
        # counted ONCE per episode (edge semantics on storms_total)
        obs.phase = "first_traffic"
        for i in range(3):
            obs.record("leak", 0.1, now=200.0 + i)
        assert obs.storm_active(now=203.0)
        assert obs.storms_total == 1
        obs.record("leak", 0.1, now=204.0)
        assert obs.storms_total == 1
        # the level clears once the window slides past the churn
        assert not obs.storm_active(now=500.0)
        # ...and a fresh burst is a SECOND counted episode
        for i in range(3):
            obs.record("leak2", 0.1, now=600.0 + i)
        assert obs.storm_active(now=603.0)
        assert obs.storms_total == 2

    def test_snapshot_and_sections_shape(self):
        obs = CompileObservatory(8)
        obs.record("fn", 0.5, now=100.0)
        snap = obs.snapshot()
        assert snap["ring_size"] == 8
        assert snap["totals"]["compiles"] == 1
        assert snap["totals"]["seconds"] == pytest.approx(0.5)
        assert set(snap["totals"]["by_phase"]) == set(compile_log.PHASES)
        assert set(snap["records"][0]) == {
            "seq", "t", "label", "seconds", "cache", "phase",
        }
        msec = obs.metrics_section()
        assert set(msec) == set(COMPILE_METRIC_KEYS) | {
            "by_cache", "by_phase",
        }
        ssec = obs.signals_section()
        assert ssec["storm_active"] is False
        assert ssec["recent"][-1]["label"] == "fn"
        assert {"ring_size", "phase", "cache_dir", "storm_n",
                "storm_window_s"} <= set(ssec)

    def test_module_singleton_lifecycle(self):
        assert compile_log.get() is None
        assert compile_log.init(0) is None  # 0 = off builds nothing
        obs = compile_log.init(4)
        assert obs is not None and compile_log.get() is obs
        assert compile_log.init(8) is obs  # idempotent
        compile_log.set_phase("warmup")
        assert compile_log.get_phase() == "warmup"
        compile_log.configure_cache("/tmp/x")
        assert obs.cache_dir == "/tmp/x"
        compile_log.configure_cache("")
        assert obs.cache_dir is None

    def test_instrument_off_returns_fn_unchanged(self):
        # the byte-identical-off contract at its sharpest: the SAME
        # function object, not a transparent wrapper
        def fn():
            return 41

        assert compile_log.get() is None
        assert compile_log.instrument("x", fn) is fn

    def test_instrument_fallback_records_first_call(self):
        compile_log.init(8)
        obs = compile_log.get()
        calls = []

        def fn(v):
            calls.append(v)
            return v + 1

        wrapped = compile_log.instrument("unit_fn", fn)
        assert wrapped is not fn and wrapped.__wrapped__ is fn
        before = obs.compiles_total
        assert wrapped(1) == 2
        # a plain python fn emits no monitoring event, so the
        # wall-clock fallback records exactly the first call
        assert obs.compiles_total == before + 1
        assert obs.records()[-1]["label"] == "unit_fn"
        assert wrapped(2) == 3
        assert obs.compiles_total == before + 1


# ---------------------------------------------------------------------------
# compile observatory against a real engine
# ---------------------------------------------------------------------------


class TestObservatoryEngine:
    def test_engine_compiles_land_in_ring(self, shared):
        cfg, params = shared
        compile_log.init(64)
        compile_log.set_phase("warmup")
        eng = make_engine(params, cfg)
        done = run_requests(eng, n=2, gen=6)
        assert len(done) == 2
        obs = compile_log.get()
        assert obs.compiles_total > 0
        labels = {r["label"] for r in obs.records()}
        # the instrumented _FN_CACHE sites attribute their labels
        assert any(lbl != "?" for lbl in labels)
        assert all(r["phase"] == "warmup" for r in obs.records())
        assert obs.by_phase["warmup"] == obs.compiles_total
        # no storm: warmup compiles are the expected cost of the phase
        assert not obs.storm_active()

    def test_off_is_bit_identical(self, shared):
        cfg, params = shared
        outs = {}
        for ring in (0, 32):
            compile_log.reset_for_tests()
            if ring:
                compile_log.init(ring)
            eng = make_engine(params, cfg)
            done = run_requests(eng, n=3, gen=10, seed_base=ring)
            outs[ring] = [done[f"dt{ring}-{i}"].output_ids
                          for i in range(3)]
        assert outs[0] == outs[32]


# ---------------------------------------------------------------------------
# live HBM accounting (MemoryMonitor)
# ---------------------------------------------------------------------------


class _Dev:
    def __init__(self, i, in_use, peak, limit):
        self.id = i
        self._stats = {"bytes_in_use": in_use, "peak_bytes_in_use": peak,
                       "bytes_limit": limit}

    def memory_stats(self):
        return dict(self._stats)


def _plan(total=100, usable=120):
    return SimpleNamespace(
        total_bytes=total, usable_bytes=usable, weight_bytes=60,
        kv_pool_bytes=25, activation_bytes=10, grammar_table_bytes=0,
    )


class TestMemoryMonitor:
    def test_worst_device_aggregation(self):
        mm = MemoryMonitor(
            [_Dev(0, 80, 90, 120), _Dev(1, 70, 95, 110)],
            plan=_plan(total=100), poll_s=0.0,
        )
        assert mm.section() is None  # no sample before the first poll
        sec = mm.poll(now=0.0)
        assert sec["source"] == "device"
        assert sec["hbm_bytes_in_use"] == 80    # max across devices
        assert sec["hbm_bytes_peak"] == 95      # max across devices
        assert sec["hbm_bytes_limit"] == 110    # min across devices
        assert sec["hbm_headroom_bytes"] == 30
        assert sec["hbm_plan_skew"] == pytest.approx(0.8)
        assert len(sec["devices"]) == 2
        assert mm.headroom_frac() == pytest.approx(30 / 110)
        # attribution: plan line items + the measured residual
        comp = sec["hbm_component_bytes"]
        assert comp["weights"] == 60 and comp["kv_pool"] == 25
        assert comp["unattributed"] == 80 - 100
        # default device watermark (3%): 30 >= 0.03 * 110, no pressure
        assert sec["hbm_pressure"] == 0 and not mm.pressure()

    def test_explicit_watermark_pressure(self, monkeypatch):
        monkeypatch.setenv("KAFKA_TPU_HBM_WATERMARK", "0.5")
        mm = MemoryMonitor([_Dev(0, 80, 80, 110)],
                           plan=_plan(), poll_s=0.0)
        sec = mm.poll(now=0.0)
        assert sec["hbm_pressure"] == 1 and mm.pressure()

    def test_plan_source_on_cpu(self, monkeypatch):
        # devices without memory_stats (CPU): the sample synthesizes
        # from the plan with skew pinned 1.0, and the watermark stays
        # DISABLED unless explicitly set — a barely-fitting plan must
        # not hold hbm_pressure forever on predicted numbers
        mm = MemoryMonitor([object()], plan=_plan(total=100, usable=101),
                           poll_s=0.0)
        sec = mm.poll(now=0.0)
        assert sec["source"] == "plan"
        assert sec["hbm_plan_skew"] == pytest.approx(1.0)
        assert sec["hbm_headroom_bytes"] == 1
        assert sec["hbm_pressure"] == 0
        monkeypatch.setenv("KAFKA_TPU_HBM_WATERMARK", "0.1")
        mm2 = MemoryMonitor([object()], plan=_plan(total=100, usable=101),
                            poll_s=0.0)
        assert mm2.poll(now=0.0)["hbm_pressure"] == 1

    def test_no_devices_no_plan(self):
        mm = MemoryMonitor([], plan=None, poll_s=0.0)
        sec = mm.poll(now=0.0)
        assert sec["source"] == "none"
        assert mm.headroom_frac() is None and not mm.pressure()

    def test_poll_throttle(self):
        dev = _Dev(0, 50, 50, 100)
        mm = MemoryMonitor([dev], plan=None, poll_s=1.0)
        s1 = mm.poll(now=0.0)
        dev._stats["bytes_in_use"] = 90
        assert mm.poll(now=0.5) is s1          # throttled
        assert mm.poll(now=0.5, force=True) is not s1
        assert mm.section()["hbm_bytes_in_use"] == 90
        assert mm.polls == 2

    def test_engine_snapshot_carries_memory_section(self, shared):
        cfg, params = shared
        eng = make_engine(params, cfg)
        assert eng.memory_monitor is not None
        eng.memory_monitor.plan = _plan(total=100, usable=120)
        run_requests(eng, n=1, gen=4, seed_base=7)
        snap = eng.metrics.snapshot(eng, reset_peak=False)
        assert "memory" in snap
        assert snap["memory"]["source"] == "plan"
        from kafka_tpu.server.prometheus import render_prometheus

        text = render_prometheus(snap)
        assert "kafka_tpu_hbm_headroom_bytes" in text
        assert "kafka_tpu_hbm_plan_skew 1\n" in text
        assert 'kafka_tpu_hbm_component_bytes{component="unattributed"}' \
            in text


# ---------------------------------------------------------------------------
# sampled kernel profiling
# ---------------------------------------------------------------------------


class TestKernelSampler:
    def test_zero_period_rejected(self):
        with pytest.raises(ValueError, match="period"):
            KernelSampler(0)

    def test_build_from_env(self, monkeypatch):
        monkeypatch.delenv(kernel_profiler.SAMPLE_ENV, raising=False)
        assert kernel_profiler.build_from_env() is None
        for junk in ("0", "-3", "nope", ""):
            monkeypatch.setenv(kernel_profiler.SAMPLE_ENV, junk)
            assert kernel_profiler.build_from_env() is None
        monkeypatch.setenv(kernel_profiler.SAMPLE_ENV, "3")
        s = kernel_profiler.build_from_env()
        assert s is not None and s.period == 3

    def test_trace_lock_collision_skips_sample(self, tmp_path):
        # the on-demand POST /debug/profile capture and the sampler
        # share one process profiler; a held lock means skip, not crash
        s = KernelSampler(1, spill_dir=str(tmp_path))
        assert kernel_profiler.try_acquire_trace()
        try:
            s.on_step_begin(EngineMetrics())
            assert s._open_dir is None
            assert s.samples_total == 0
        finally:
            kernel_profiler.release_trace()

    def test_end_to_end_sampling(self, shared, monkeypatch, tmp_path):
        """Acceptance (ISSUE 18): KAFKA_TPU_PROFILE_SAMPLE=N on a real
        engine yields a non-empty per-kernel table with device
        durations bucketed by dispatch kind."""
        cfg, params = shared
        monkeypatch.setenv(kernel_profiler.SAMPLE_ENV, "2")
        monkeypatch.setenv(kernel_profiler.SPILL_ENV, str(tmp_path))
        monkeypatch.setenv(kernel_profiler.KEEP_ENV, "2")
        # the calibration split needs modeled seconds: pin the roofline
        # via env like the model-skew test (CPU has no known peak)
        monkeypatch.setenv("KAFKA_TPU_PEAK_TFLOPS", "0.001")
        monkeypatch.setenv("KAFKA_TPU_PEAK_HBM_GBPS", "1")
        eng = make_engine(params, cfg)
        assert eng.kernel_sampler is not None
        assert eng.kernel_sampler.period == 2
        run_requests(eng, n=3, gen=8, seed_base=42)
        eng.kernel_sampler.close(eng.metrics)
        snap = eng.kernel_sampler.snapshot(top_k=10)
        assert snap["samples_total"] >= 1
        rows = snap["kernels"]
        assert rows, "no kernels parsed from the sampled traces"
        assert set(rows[0]) == {"kind", "kernel", "count", "total_us",
                                "avg_us", "frac"}
        assert rows == sorted(rows, key=lambda r: -r["total_us"])
        assert all(r["total_us"] > 0 for r in rows)
        # spill pruning keeps at most KEEP raw trace dirs behind
        import glob as _glob

        assert len(_glob.glob(str(tmp_path / "sample_*"))) <= 2
        # calibration feedback reached the metrics plane
        msnap = eng.metrics.snapshot(eng, reset_peak=False)
        util = msnap["utilization"]
        sampled = [u for u in util.values()
                   if isinstance(u, dict) and u.get("kernel_samples")]
        assert sampled and all(u["kernel_busy_s"] > 0 for u in sampled)
        from kafka_tpu.server.prometheus import render_prometheus

        text = render_prometheus(msnap)
        assert "kafka_tpu_kernel_samples_total" in text
        assert "kafka_tpu_kernel_skew" in text

    def test_off_is_bit_identical(self, shared, monkeypatch, tmp_path):
        cfg, params = shared
        outs = {}
        for period in (0, 1):
            if period:
                monkeypatch.setenv(kernel_profiler.SAMPLE_ENV,
                                   str(period))
                monkeypatch.setenv(kernel_profiler.SPILL_ENV,
                                   str(tmp_path))
            else:
                monkeypatch.delenv(kernel_profiler.SAMPLE_ENV,
                                   raising=False)
            eng = make_engine(params, cfg)
            if period == 0:
                assert eng.kernel_sampler is None
            done = run_requests(eng, n=3, gen=10, seed_base=period)
            if eng.kernel_sampler is not None:
                eng.kernel_sampler.close(eng.metrics)
            outs[period] = [done[f"dt{period}-{i}"].output_ids
                            for i in range(3)]
        assert outs[0] == outs[1]


# ---------------------------------------------------------------------------
# registries
# ---------------------------------------------------------------------------


class TestDeviceTruthRegistry:
    """COMPILE_METRIC_KEYS and MEMORY_METRIC_KEYS are both-directions
    registries across runtime/metrics.py and server/prometheus.py
    (same pattern as FLIGHT/ANOMALY in test_flight_recorder.py)."""

    def _source(self, relpath):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(root, "kafka_tpu", relpath)) as f:
            return f.read()

    def test_registry_both_directions(self):
        metrics_src = self._source("runtime/metrics.py")
        prom_src = self._source("server/prometheus.py")
        for key in COMPILE_METRIC_KEYS + MEMORY_METRIC_KEYS:
            assert f'"{key}"' in metrics_src, (
                f"{key} missing from runtime/metrics.py"
            )
            assert (f"kafka_tpu_{key}" in prom_src
                    or f'"{key}"' in prom_src), (
                f"{key} missing from server/prometheus.py"
            )

    def test_kernel_keys_registered_in_utilization(self):
        for key in ("kernel_samples", "kernel_busy_s", "kernel_skew"):
            assert key in UTILIZATION_METRIC_KEYS
            assert f'"{key}"' in self._source("server/prometheus.py")

    def test_anomaly_kinds_cover_device_truth(self):
        from kafka_tpu.runtime.flight_recorder import ANOMALY_KINDS
        from kafka_tpu.runtime.metrics import ANOMALY_METRIC_KEYS

        assert "compile_storm" in ANOMALY_KINDS
        assert "hbm_pressure" in ANOMALY_KINDS
        assert "anomaly_compile_storm" in ANOMALY_METRIC_KEYS
        assert "anomaly_hbm_pressure" in ANOMALY_METRIC_KEYS

    def test_compile_section_renders(self):
        # the compiles section is process-wide: server/app.py merges it
        # into the snapshot; prometheus renders whatever snapshot
        # carries, so feed it a merged-shape snapshot directly
        from kafka_tpu.server.prometheus import render_prometheus

        obs = CompileObservatory(8)
        obs.record("fn", 0.5, now=100.0)
        snap = EngineMetrics().snapshot()
        snap["compiles"] = obs.metrics_section()
        text = render_prometheus(snap)
        assert 'kafka_tpu_compiles_total{cache="off"} 1' in text
        assert "kafka_tpu_compile_seconds_total 0.5" in text
        assert "kafka_tpu_compile_storm_active 0" in text
        assert 'kafka_tpu_compiles_total{phase="boot"} 1' in text


# ---------------------------------------------------------------------------
# server endpoints + signals contract
# ---------------------------------------------------------------------------


class TestServerEndpoints:
    def _app_client(self, provider, tmp_path, **cfg_kw):
        from aiohttp.test_utils import TestClient, TestServer
        from kafka_tpu.db.local import LocalDBClient
        from kafka_tpu.server.app import create_app
        from kafka_tpu.server.config import ServingConfig

        async def build():
            app = await create_app(
                cfg=ServingConfig(db_path=str(tmp_path / "d.db"), **cfg_kw),
                llm_provider=provider,
                db=LocalDBClient(str(tmp_path / "d.db")),
                tools=[],
            )
            client = TestClient(TestServer(app))
            await client.start_server()
            return client

        return build

    def _provider(self, eng):
        from kafka_tpu.llm import TPULLMProvider
        from kafka_tpu.models.tokenizer import ByteTokenizer

        return TPULLMProvider(eng, ByteTokenizer(), model_name="m")

    def test_debug_compiles_endpoint(self, shared, tmp_path):
        import asyncio

        cfg, params = shared
        eng = make_engine(params, cfg)
        provider = self._provider(eng)
        build = self._app_client(provider, tmp_path)

        async def go():
            client = await build()
            try:
                # off: create_app never calls compile_log.init (that is
                # serve()'s job) and the fixture reset the singleton
                r = await client.get("/debug/compiles")
                assert r.status == 404
                assert "disabled" in (await r.json())["error"]
                # on: records show up with phase + cache disposition
                obs = compile_log.init(16)
                compile_log.set_phase("first_traffic")
                obs.record("live_fn", 1.25)
                r = await client.get("/debug/compiles")
                assert r.status == 200
                payload = await r.json()
                assert payload["totals"]["compiles"] >= 1
                rec = next(r for r in payload["records"]
                           if r["label"] == "live_fn")
                assert rec["phase"] == "first_traffic"
                assert rec["cache"] == "off"
                assert payload["storm"]["active"] is False
                # the /metrics snapshot merges the same section (the
                # Prometheus exposition is content-negotiated; the JSON
                # default carries the merged dict)
                m = await client.get("/metrics")
                msnap = await m.json()
                assert msnap["compiles"]["compiles_total"] >= 1
                assert msnap["compiles"]["by_phase"]["first_traffic"] >= 1
            finally:
                await client.close()
                provider.worker.stop()

        asyncio.run(go())

    def test_debug_kernels_endpoint(self, shared, tmp_path, monkeypatch):
        import asyncio

        cfg, params = shared
        monkeypatch.setenv(kernel_profiler.SAMPLE_ENV, "1")
        monkeypatch.setenv(kernel_profiler.SPILL_ENV,
                           str(tmp_path / "spill"))
        eng = make_engine(params, cfg)
        run_requests(eng, n=2, gen=6, seed_base=9)
        eng.kernel_sampler.close(eng.metrics)
        provider = self._provider(eng)
        build = self._app_client(provider, tmp_path)

        async def go():
            client = await build()
            try:
                r = await client.get("/debug/kernels?top_k=5")
                assert r.status == 200
                payload = await r.json()
                assert payload["period"] == 1
                assert payload["samples_total"] >= 1
                assert payload["kernels"]
                assert len(payload["kernels"]) <= 5
                assert "replicas" not in payload  # single engine
                r = await client.get("/debug/kernels?top_k=x")
                assert r.status == 400
            finally:
                await client.close()
                provider.worker.stop()

        asyncio.run(go())

    def test_debug_kernels_404_when_off(self, shared, tmp_path,
                                        monkeypatch):
        import asyncio

        cfg, params = shared
        monkeypatch.delenv(kernel_profiler.SAMPLE_ENV, raising=False)
        eng = make_engine(params, cfg)
        assert eng.kernel_sampler is None
        provider = self._provider(eng)
        build = self._app_client(provider, tmp_path)

        async def go():
            client = await build()
            try:
                r = await client.get("/debug/kernels")
                assert r.status == 404
                assert "KAFKA_TPU_PROFILE_SAMPLE" in \
                    (await r.json())["error"]
            finally:
                await client.close()
                provider.worker.stop()

        asyncio.run(go())

    def test_signals_v7_device_truth_sections(self, shared):
        cfg, params = shared
        eng = make_engine(params, cfg)
        eng.memory_monitor.plan = _plan(total=100, usable=120)
        run_requests(eng, n=1, gen=4, seed_base=11)
        compile_log.init(16)
        compile_log.get().record("sig_fn", 0.2)
        provider = self._provider(eng)
        try:
            sig = provider.signals()
            assert sig["version"] == 9
            assert sig["compiles"]["compiles_total"] >= 1
            assert sig["compiles"]["storm_active"] is False
            mem = sig["memory"]
            assert mem is not None
            assert mem["plan_skew"] == pytest.approx(1.0)
            assert mem["pressure"] == 0
            assert mem["replicas"][0]["replica"] == 0
            assert mem["replicas"][0]["source"] == "plan"
            assert mem["headroom_bytes"] == \
                mem["replicas"][0]["hbm_headroom_bytes"]
        finally:
            provider.worker.stop()

    def test_signals_sections_null_when_off(self, shared):
        cfg, params = shared
        eng = make_engine(params, cfg)
        # no poll has happened and no observatory exists: both device-
        # truth sections are null rather than fabricated
        provider = self._provider(eng)
        try:
            sig = provider.signals()
            assert sig["version"] == 9
            assert sig["compiles"] is None
            assert sig["memory"] is None
        finally:
            provider.worker.stop()


# ---------------------------------------------------------------------------
# bench phase smoke
# ---------------------------------------------------------------------------


class TestBenchSmoke:
    def test_device_truth_phase_runs(self, shared, monkeypatch):
        import random
        import sys

        # conftest forces the observatory off suite-wide; the bench phase
        # boots it via a bare init() (env-sized) and the rebuild-leg
        # assertions need a live ring.
        monkeypatch.setenv(compile_log.RING_ENV, "256")

        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        from bench import device_truth_phase

        cfg, params = shared
        eng = make_engine(params, cfg)
        args = SimpleNamespace(quick=True, batch=2, prompt_len=16)
        out = device_truth_phase(eng, cfg, args, random.Random(0))
        samp = out["sampling"]
        assert samp["tok_s_off"] > 0 and samp["tok_s_on"] > 0
        assert samp["samples"] >= 1 and samp["kernels_seen"] > 0
        assert 0.0 <= samp["overhead_frac"] < 1.0
        # the phase restores the engine's shipped-default state
        assert eng.kernel_sampler is None
        reb = out["rebuild_outage"]
        assert reb["warm_first_token_s"] > 0
        assert reb["cold_first_token_s"] > 0
        # the cold leg really compiled; the warm leg really did not
        assert reb["compiles_cold_leg"] > reb["compiles_warm_leg"]
