"""Long-context serving: TP×SP composed through the engine + big windows.

The judge-specified invariant (VERDICT r1 #7): an engine on a tp×sp
virtual mesh must match single-device logits/tokens on a prompt larger
than one device's sequence shard — the chunk rides the ring, earlier
chunks are read from the paged window, and TP shards heads, all in one
jitted prefill program.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.models import ModelConfig, forward, init_params
from kafka_tpu.parallel import MeshConfig, make_mesh
from kafka_tpu.parallel.ring_attention import ring_prefill_sharded
from kafka_tpu.ops.attention import causal_attention
from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(name="lc-test", vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=8,
                      num_kv_heads=4, head_dim=16, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(21))
    return cfg, params


class TestRingPrefillOp:
    def test_ring_with_context_matches_reference(self):
        """Chunk ring + replicated paged context == plain causal attention
        over (context + chunk)."""
        mesh = make_mesh(MeshConfig(sp=2, tp=4))
        rng = np.random.RandomState(0)
        B, S, C, Hq, Hkv, D = 1, 16, 24, 8, 4, 16
        start = 11  # context holds positions 0..10
        q = jnp.asarray(rng.randn(B, S, Hq, D), jnp.float32)
        kc = jnp.asarray(rng.randn(B, S, Hkv, D), jnp.float32)
        vc = jnp.asarray(rng.randn(B, S, Hkv, D), jnp.float32)
        k_ctx = jnp.asarray(rng.randn(B, C, Hkv, D), jnp.float32)
        v_ctx = jnp.asarray(rng.randn(B, C, Hkv, D), jnp.float32)
        q_pos = jnp.broadcast_to(
            start + jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
        ctx_pos = jnp.broadcast_to(
            jnp.arange(C, dtype=jnp.int32)[None, :], (B, C))
        ctx_valid = ctx_pos < start

        out = ring_prefill_sharded(
            mesh, q, kc, vc, q_pos, k_ctx, v_ctx, ctx_pos, ctx_valid)

        # reference: concatenate valid context + chunk, plain attention
        k_all = jnp.concatenate([k_ctx[:, :start], kc], axis=1)
        v_all = jnp.concatenate([v_ctx[:, :start], vc], axis=1)
        pos_all = jnp.concatenate([ctx_pos[:, :start], q_pos], axis=1)
        ref = causal_attention(q, k_all, v_all,
                               q_positions=q_pos, kv_positions=pos_all)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_ring_first_chunk_no_context(self):
        """All-invalid context (first chunk of a prompt) must be a no-op."""
        mesh = make_mesh(MeshConfig(sp=2, tp=4))
        rng = np.random.RandomState(1)
        B, S, C, Hq, Hkv, D = 1, 8, 16, 4, 2, 16
        q = jnp.asarray(rng.randn(B, S, Hq, D), jnp.float32)
        kc = jnp.asarray(rng.randn(B, S, Hkv, D), jnp.float32)
        vc = jnp.asarray(rng.randn(B, S, Hkv, D), jnp.float32)
        k_ctx = jnp.asarray(rng.randn(B, C, Hkv, D), jnp.float32)
        v_ctx = jnp.asarray(rng.randn(B, C, Hkv, D), jnp.float32)
        q_pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
        ctx_pos = jnp.broadcast_to(jnp.arange(C, dtype=jnp.int32)[None, :], (B, C))
        out = ring_prefill_sharded(
            mesh, q, kc, vc, q_pos, k_ctx, v_ctx, ctx_pos,
            jnp.zeros((B, C), bool))
        ref = causal_attention(q, kc, vc, q_positions=q_pos, kv_positions=q_pos)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)


class TestUlyssesPrefillOp:
    def test_ulysses_with_context_matches_reference(self):
        """Head-scatter CP + replicated paged context == plain causal
        attention over (context + chunk) — the same contract the ring
        satisfies (VERDICT r2 #8: Ulysses as a first-class alternative)."""
        from kafka_tpu.parallel.ring_attention import ulysses_prefill_sharded

        mesh = make_mesh(MeshConfig(sp=2, tp=4))
        rng = np.random.RandomState(7)
        B, S, C, Hq, Hkv, D = 1, 16, 24, 8, 4, 16
        start = 11  # context holds positions 0..10
        q = jnp.asarray(rng.randn(B, S, Hq, D), jnp.float32)
        kc = jnp.asarray(rng.randn(B, S, Hkv, D), jnp.float32)
        vc = jnp.asarray(rng.randn(B, S, Hkv, D), jnp.float32)
        k_ctx = jnp.asarray(rng.randn(B, C, Hkv, D), jnp.float32)
        v_ctx = jnp.asarray(rng.randn(B, C, Hkv, D), jnp.float32)
        q_pos = jnp.broadcast_to(
            start + jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
        ctx_pos = jnp.broadcast_to(
            jnp.arange(C, dtype=jnp.int32)[None, :], (B, C))
        ctx_valid = ctx_pos < start

        out = ulysses_prefill_sharded(
            mesh, q, kc, vc, q_pos, k_ctx, v_ctx, ctx_pos, ctx_valid)

        k_all = jnp.concatenate([k_ctx[:, :start], kc], axis=1)
        v_all = jnp.concatenate([v_ctx[:, :start], vc], axis=1)
        pos_all = jnp.concatenate([ctx_pos[:, :start], q_pos], axis=1)
        ref = causal_attention(q, k_all, v_all,
                               q_positions=q_pos, kv_positions=pos_all)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)


class TestEngineTPxSP:
    def test_tpxsp_engine_matches_single_device(self, model):
        """The composed test the dryrun also runs: tp=2 x sp=2 engine,
        multi-chunk prompt (each chunk larger than one sp shard), token-
        exact vs the single-device engine at f32."""
        cfg, params = model
        prompt = list(np.random.RandomState(3).randint(1, 128, size=50))

        ref_eng = InferenceEngine(
            cfg, params,
            EngineConfig(max_batch=2, page_size=8, num_pages=32,
                         max_pages_per_seq=16, prefill_buckets=(16, 32)),
            kv_dtype=jnp.float32,
        )
        ref = ref_eng.generate(prompt, max_new_tokens=8)

        mesh = make_mesh(MeshConfig(sp=2, tp=2))
        eng = InferenceEngine(
            cfg, params,
            EngineConfig(max_batch=2, page_size=8, num_pages=32,
                         max_pages_per_seq=16, prefill_buckets=(16, 32)),
            kv_dtype=jnp.float32,
            mesh=mesh,
        )
        assert eng.cfg.prefill_ring
        out = eng.generate(prompt, max_new_tokens=8)
        assert out.output_ids == ref.output_ids

    def test_ulysses_engine_matches_single_device(self, model):
        """cp_strategy='ulysses' through the ENGINE: same token-exact bar
        as the ring (multi-chunk prompt, tp=2 x sp=2 vs single device)."""
        cfg, params = model
        prompt = list(np.random.RandomState(4).randint(1, 128, size=50))

        ref_eng = InferenceEngine(
            cfg, params,
            EngineConfig(max_batch=2, page_size=8, num_pages=32,
                         max_pages_per_seq=16, prefill_buckets=(16, 32)),
            kv_dtype=jnp.float32,
        )
        ref = ref_eng.generate(prompt, max_new_tokens=8)

        mesh = make_mesh(MeshConfig(sp=2, tp=2))
        eng = InferenceEngine(
            cfg, params,
            EngineConfig(max_batch=2, page_size=8, num_pages=32,
                         max_pages_per_seq=16, prefill_buckets=(16, 32),
                         cp_strategy="ulysses"),
            kv_dtype=jnp.float32,
            mesh=mesh,
        )
        assert eng.cfg.cp_strategy == "ulysses"
        out = eng.generate(prompt, max_new_tokens=8)
        assert out.output_ids == ref.output_ids

    def test_ulysses_head_divisibility_rejected(self, model):
        cfg, params = model
        mesh = make_mesh(MeshConfig(sp=2, tp=2))
        # heads/tp = 1 is not divisible by sp=2
        bad_cfg = cfg.replace(num_heads=2, num_kv_heads=2)
        with pytest.raises(ValueError, match="ulysses needs the per-shard"):
            InferenceEngine(
                bad_cfg, params,
                EngineConfig(prefill_buckets=(16, 32),
                             cp_strategy="ulysses"),
                mesh=mesh,
            )

    def test_unknown_cp_strategy_rejected(self, model):
        cfg, params = model
        mesh = make_mesh(MeshConfig(sp=2, tp=2))
        with pytest.raises(ValueError, match="unknown cp_strategy"):
            InferenceEngine(
                cfg, params,
                EngineConfig(prefill_buckets=(16, 32), cp_strategy="spiral"),
                mesh=mesh,
            )

    def test_bucket_not_divisible_by_sp_rejected(self, model):
        cfg, params = model
        mesh = make_mesh(MeshConfig(sp=2, tp=2))
        with pytest.raises(ValueError, match="divisible by sp"):
            InferenceEngine(
                cfg, params,
                EngineConfig(prefill_buckets=(15, 32)),
                mesh=mesh,
            )


class Test32kWindow:
    def test_32k_prompt_serves_through_ring_prefill(self):
        """BASELINE config 5's shape, executed: a >32k-token prompt through
        chunked ring prefill on a tp=2 x sp=2 mesh against a 2048-page pool
        (window math: 2048 pages x 16 tokens/page = 32768-token window; the
        prompt occupies ceil(32701/16) = 2044 pages mid-flight).

        A micro model keeps the O(S*C) attention affordable on CPU (~30s);
        the model is exercised for *shape*, not numerics — ring-vs-single-
        device token exactness is proved at smaller length by TestEngineTPxSP
        with the identical code path.
        """
        cfg = ModelConfig(name="lc-32k", vocab_size=64, hidden_size=16,
                          intermediate_size=32, num_layers=1, num_heads=2,
                          num_kv_heads=2, head_dim=8, dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(2))
        ecfg = EngineConfig(max_batch=1, page_size=16, num_pages=2050,
                            max_pages_per_seq=2048, prefill_buckets=(2048,))
        assert ecfg.max_window == 32768
        mesh = make_mesh(MeshConfig(sp=2, tp=2))
        eng = InferenceEngine(cfg, params, ecfg, kv_dtype=jnp.float32,
                              mesh=mesh)
        assert eng.cfg.prefill_ring
        prompt = list(np.random.RandomState(5).randint(1, 64, size=32700))
        req = GenRequest(request_id="lc32k", prompt_ids=prompt,
                         max_new_tokens=8)
        eng.submit(req)
        eng.step()  # admits: 16 ring-chunk prefills + first decode
        assert req.seq is not None
        assert len(req.seq.pages) == -(-(len(prompt) + 1) // 16)  # 2044
        eng.run_to_completion()
        assert len(req.output_ids) == 8
        assert req.finish_reason == "length"
        # pool fully reclaimed after the request retires
        assert eng.pool.free_pages == ecfg.num_pages - 1

    def test_serving_config_32k_profile(self):
        """The deployable 32k profile: window math adds up and the prefill
        buckets divide by the sp degree (engine constructor contract)."""
        from kafka_tpu.server.config import ServingConfig

        p = ServingConfig.profile_32k()
        assert p.page_size * p.max_pages_per_seq == 32768
        assert p.num_pages > p.max_pages_per_seq
        assert p.sp_size > 1 and p.tp_size > 1
        assert all(b % p.sp_size == 0 for b in p.prefill_buckets)
        assert max(p.prefill_buckets) >= 2048


class TestBigWindow:
    def test_8k_window_prompt_serves_end_to_end(self, model):
        """Window size is a first-class config: an 8k+ window engine
        prefills a multi-thousand-token prompt in chunks and decodes
        greedily consistent with the uncached forward."""
        cfg, params = model
        ecfg = EngineConfig(
            max_batch=1, page_size=64, num_pages=140,
            max_pages_per_seq=130,  # window 8320
            prefill_buckets=(256, 1024),
        )
        eng = InferenceEngine(cfg, params, ecfg, kv_dtype=jnp.float32)
        assert ecfg.max_window > 8192
        prompt = list(np.random.RandomState(9).randint(1, 128, size=2500))
        req = eng.generate(prompt, max_new_tokens=4)
        assert len(req.output_ids) == 4
        # greedy consistency vs one uncached forward over prompt+output
        seq = prompt + req.output_ids
        x = jnp.asarray([seq], jnp.int32)
        pos = jnp.arange(len(seq), dtype=jnp.int32)[None, :]
        logits, _ = forward(params, cfg, x, pos)
        preds = np.asarray(jnp.argmax(logits[0], axis=-1))
        for i in range(len(prompt) - 1, len(seq) - 1):
            assert preds[i] == seq[i + 1], f"divergence at {i}"
