"""RemoteDBClient vs a stub PostgREST server (reference Supabase parity).

The stub implements the PostgREST subset the client speaks — eq filters,
select projection, order/limit, insert-with-representation, patch, delete,
rpc — over in-memory tables, so every client behavior is exercised against
real HTTP semantics.
"""

import asyncio

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from kafka_tpu.db.remote import RemoteDBClient, _flatten_content


class StubPostgrest:
    """Minimal PostgREST over in-memory lists of dicts."""

    def __init__(self):
        self.tables = {
            "threads": [], "oai_messages": [], "kafka_profiles": [],
            "profiles": [], "vm_api_keys": [], "playbooks": [],
        }
        self.rpc_calls = []
        self.fail_rpc = False
        self._seq = 0  # bigserial for oai_messages (server-assigned)

    def _filtered(self, table, query):
        rows = list(self.tables[table])
        for col, val in query.items():
            if col in ("select", "order", "limit"):
                continue
            if val.startswith("eq."):
                want = val[3:]
                rows = [r for r in rows if str(r.get(col)) == want]
        if "order" in query:
            col, _, direction = query["order"].partition(".")
            rows.sort(key=lambda r: r.get(col) or 0,
                      reverse=direction == "desc")
        if "limit" in query:
            rows = rows[: int(query["limit"])]
        return rows

    def app(self) -> web.Application:
        app = web.Application()

        async def table_get(request):
            table = request.match_info["table"]
            rows = self._filtered(table, dict(request.query))
            select = request.query.get("select", "*")
            if select != "*":
                cols = [c.strip() for c in select.split(",")]
                rows = [{c: r.get(c) for c in cols} for r in rows]
            return web.json_response(rows)

        async def table_post(request):
            table = request.match_info["table"]
            body = await request.json()
            rows = body if isinstance(body, list) else [body]
            for r in rows:
                # primary-key enforcement like real PostgREST: duplicate
                # ids conflict with 409
                if "id" in r and any(
                    x.get("id") == r["id"] for x in self.tables[table]
                ):
                    return web.json_response(
                        {"message": "duplicate key"}, status=409
                    )
                if table == "oai_messages" and "seq" not in r:
                    self._seq += 1
                    r["seq"] = self._seq
            self.tables[table].extend(rows)
            return web.json_response(rows, status=201)

        async def table_patch(request):
            table = request.match_info["table"]
            values = await request.json()
            for row in self._filtered(table, dict(request.query)):
                row.update(values)
            return web.json_response([])

        async def table_delete(request):
            table = request.match_info["table"]
            doomed = self._filtered(table, dict(request.query))
            self.tables[table] = [
                r for r in self.tables[table] if r not in doomed
            ]
            return web.json_response([])

        async def rpc(request):
            fn = request.match_info["fn"]
            args = await request.json()
            self.rpc_calls.append((fn, args))
            if self.fail_rpc:
                return web.json_response({"error": "boom"}, status=500)
            if fn == "generate_vm_api_key":
                return web.json_response(
                    f"vm_rpc_{args.get('p_thread_id')}"
                )
            return web.json_response(None)

        app.router.add_get("/rest/v1/{table}", table_get)
        app.router.add_post("/rest/v1/rpc/{fn}", rpc)
        app.router.add_post("/rest/v1/{table}", table_post)
        app.router.add_patch("/rest/v1/{table}", table_patch)
        app.router.add_delete("/rest/v1/{table}", table_delete)
        return app


def run_with_stub(fn):
    """Start the stub, build a client pointed at it, run fn(client, stub)."""
    stub = StubPostgrest()

    async def go():
        server = TestServer(stub.app())
        await server.start_server()
        db = RemoteDBClient(
            str(server.make_url("")), api_key="svc-key"
        )
        try:
            return await fn(db, stub)
        finally:
            await db.close()
            await server.close()

    return asyncio.run(go())


class TestThreadsAndMessages:
    def test_thread_crud_roundtrip(self):
        async def fn(db, stub):
            tid = await db.create_thread("t1", {"k": "v"})
            assert tid == "t1"
            assert await db.thread_exists("t1")
            assert not await db.thread_exists("nope")
            # idempotent create
            assert await db.create_thread("t1") == "t1"
            assert len(stub.tables["threads"]) == 1
            meta = await db.get_thread_metadata("t1")
            assert meta["metadata"] == {"k": "v"}
            listing = await db.list_threads()
            assert [t["thread_id"] for t in listing] == ["t1"]
            await db.delete_thread("t1")
            assert not await db.thread_exists("t1")

        run_with_stub(fn)

    def test_messages_roundtrip_ordered(self):
        async def fn(db, stub):
            await db.create_thread("t")
            await db.add_messages("t", [
                {"role": "user", "content": "one"},
                {"role": "assistant", "content": "two"},
            ])
            await db.add_message("t", {"role": "user", "content": "three"})
            msgs = await db.get_thread_messages("t")
            assert [m["content"] for m in msgs] == ["one", "two", "three"]
            await db.delete_thread_messages("t")
            assert await db.get_thread_messages("t") == []

        run_with_stub(fn)

    def test_multipart_content_flattened(self):
        async def fn(db, stub):
            await db.create_thread("t")
            await db.add_message("t", {
                "role": "user",
                "content": [
                    {"type": "text", "text": "hello "},
                    {"type": "image_url", "image_url": {"url": "x"}},
                    {"type": "text", "text": "world"},
                ],
            })
            msgs = await db.get_thread_messages("t")
            assert msgs[0]["content"] == "hello world"

        run_with_stub(fn)

    def test_sandbox_binding(self):
        async def fn(db, stub):
            await db.create_thread("t")
            assert await db.get_thread_sandbox_id("t") is None
            await db.update_thread_sandbox_id("t", "sb-9")
            assert await db.get_thread_sandbox_id("t") == "sb-9"

        run_with_stub(fn)


class TestConfigJoin:
    def test_full_join(self):
        async def fn(db, stub):
            stub.tables["profiles"].append(
                {"id": "user-1", "name": "Ada"})
            stub.tables["kafka_profiles"].append({
                "id": "kp-1", "user_id": "user-1",
                "global_prompt": "Be terse.", "memory_dsn": "dsn://x",
                "model": "llama-3.2-1b",
            })
            stub.tables["vm_api_keys"].append({
                "id": "vk-1", "thread_id": "t", "api_key": "vm_abc",
                "status": "active", "created_at": 1.0,
            })
            stub.tables["playbooks"].append({
                "id": "pb-1", "kafka_profile_id": "kp-1",
                "name": "deploy", "created_at": 1.0,
            })
            await db.create_thread("t")
            await db.set_thread_config("t", {
                "kafka_profile_id": "kp-1", "vm_api_key_id": "vk-1",
                "user_id": "user-1", "ignored_field": "x",
            })
            cfg = await db.get_thread_config("t")
            assert cfg["global_prompt"] == "Be terse."
            assert cfg["memory_dsn"] == "dsn://x"
            assert cfg["model"] == "llama-3.2-1b"
            assert cfg["vm_api_key"] == "vm_abc"
            assert cfg["user_id"] == "user-1"
            assert [p["name"] for p in cfg["playbooks"]] == ["deploy"]

        run_with_stub(fn)

    def test_config_for_unknown_thread_is_none(self):
        async def fn(db, stub):
            assert await db.get_thread_config("ghost") is None

        run_with_stub(fn)

    def test_config_with_no_profile_links(self):
        async def fn(db, stub):
            await db.create_thread("bare")
            cfg = await db.get_thread_config("bare")
            assert cfg["global_prompt"] is None
            assert cfg["vm_api_key"] is None
            assert cfg["playbooks"] == []

        run_with_stub(fn)


class TestVmApiKeys:
    def test_existing_active_key_reused(self):
        async def fn(db, stub):
            stub.tables["vm_api_keys"].append({
                "id": "vk", "thread_id": "t", "api_key": "vm_keep",
                "status": "active",
            })
            assert await db.get_or_create_vm_api_key("t") == "vm_keep"
            assert stub.rpc_calls == []  # no mint when one exists

        run_with_stub(fn)

    def test_minted_via_rpc(self):
        async def fn(db, stub):
            key = await db.get_or_create_vm_api_key("t9")
            assert key == "vm_rpc_t9"
            assert stub.rpc_calls == [
                ("generate_vm_api_key", {"p_thread_id": "t9"})
            ]
            # persisted for next time
            assert await db.get_or_create_vm_api_key("t9") == "vm_rpc_t9"
            assert len(stub.rpc_calls) == 1

        run_with_stub(fn)

    def test_rpc_failure_falls_back_to_local_key(self):
        async def fn(db, stub):
            stub.fail_rpc = True
            key = await db.get_or_create_vm_api_key("t")
            assert key.startswith("vm_")

        run_with_stub(fn)


class TestFlatten:
    def test_flatten_passthrough(self):
        assert _flatten_content("plain") == "plain"
        assert _flatten_content(None) is None
        assert _flatten_content([
            {"type": "text", "text": "a"}, "b",
            {"type": "tool", "x": 1},
        ]) == "ab"


class TestConfigReplaceContract:
    def test_set_is_replace_not_merge_and_none_clears(self):
        async def fn(db, stub):
            await db.create_thread("t")
            await db.set_thread_config("t", {"model": "m1", "user_id": "u1"})
            cfg = await db.get_thread_config("t")
            assert cfg["model"] == "m1" and cfg["user_id"] == "u1"
            # overlay replaced wholesale (model clears); the deployment-
            # managed link column survives a write that omits it
            await db.set_thread_config("t", {"global_prompt": "p"})
            cfg = await db.get_thread_config("t")
            assert cfg.get("model") is None
            assert cfg["user_id"] == "u1"
            assert cfg["global_prompt"] == "p"
            # explicit null detaches a link
            await db.set_thread_config("t", {"user_id": None})
            cfg = await db.get_thread_config("t")
            assert cfg["user_id"] is None
            # None clears the overlay
            await db.set_thread_config("t", None)
            cfg = await db.get_thread_config("t")
            assert cfg.get("global_prompt") is None

        run_with_stub(fn)
