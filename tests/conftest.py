"""Test configuration.

Forces JAX onto CPU with 8 virtual devices so sharding/mesh tests exercise
real 8-way SPMD partitioning without TPU hardware (the standard JAX recipe:
--xla_force_host_platform_device_count).

Environment quirk: this machine's sitecustomize registers the "axon" TPU
PJRT plugin and imports jax before any test code runs, so JAX_PLATFORMS in
os.environ is read too late — the platform must be overridden through
jax.config after import (safe while no backend has been initialized yet).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
# The suite is XLA:CPU COMPILE-bound (hundreds of jitted programs over
# tiny models), and tests don't need optimized code — skipping LLVM's
# expensive passes measured ~40% faster module runs with identical
# numerics (greedy token streams, chi-square distribution checks, and
# the llama forward-parity tests all pass under it).  Tests only: the
# serving path never sets this.
if "xla_llvm_disable_expensive_passes" not in flags:
    flags = (flags + " --xla_llvm_disable_expensive_passes=true").strip()
os.environ["XLA_FLAGS"] = flags
os.environ.setdefault("JAX_ENABLE_X64", "0")
# Hermetic suite: never dial the default remote MCP server from tests
# (individual tests override this to exercise the config parser).
os.environ.setdefault("KAFKA_TPU_MCP_SERVERS", "[]")
# NO persistent compile cache in tests: server boots would enable it
# (ServingConfig.compile_cache_dir), but serializing/deserializing CPU
# SPMD executables segfaults/aborts INSIDE XLA in this environment —
# observed three times at suite scale, in both put_executable_and_time
# (write) and get_executable_and_time (read, machine-feature mismatch
# from a migrated host).  An in-process crash is uncatchable and kills
# the whole run, so tests disable the cache outright ("" = off,
# server/app.py); the TPU serving path keeps it — TPU executable
# serialization has been exercised for rounds without incident.
os.environ["KAFKA_TPU_COMPILE_CACHE"] = ""

# NO compile observatory by default in tests: the observatory is a
# process-wide singleton, and build_tpu_provider boots it and leaves the
# phase at "first_traffic" on exit.  After any test touches that path,
# the suite's hundreds of tiny-model recompiles all read as live-traffic
# compiles, the storm detector latches, and every later engine's flight
# recorder reports a compile_storm anomaly — observed polluting
# test_metrics, test_autoscaler, and test_flight_recorder at suite
# scale.  Ring size 0 makes init() a no-op ("" would mean "use the
# default"); device-truth tests opt back in with an explicit init(size)
# or a monkeypatched env.
os.environ["KAFKA_TPU_COMPILE_RING"] = "0"

# The root cause of full-suite crashes (segfault/abort inside XLA:CPU
# compile, detonating at a shifting late-suite test): every JIT-compiled
# executable holds process memory mappings, the suite compiles thousands,
# and the count crosses vm.max_map_count (65530 default) near the end —
# mmap starts failing and LLVM/XLA dies uncatchably.  Measured: ~42k maps
# six minutes into the run, growing ~5k/min.  Two defenses: raise the
# sysctl when permitted AND opted in (the sysctl is HOST-GLOBAL kernel
# config, so mutating it is gated behind KAFKA_TPU_TEST_RAISE_MAP_COUNT=1
# and undone at session finish — see pytest_sessionfinish below), and drop
# compiled executables between test modules (fixture below), which is the
# always-on defense.
_PRIOR_MAP_COUNT = None
if os.environ.get("KAFKA_TPU_TEST_RAISE_MAP_COUNT") == "1":
    try:
        with open("/proc/sys/vm/max_map_count") as _f:
            _cur = int(_f.read())
        if _cur < 262144:
            with open("/proc/sys/vm/max_map_count", "w") as _f:
                _f.write("262144")
            _PRIOR_MAP_COUNT = _cur
    except (OSError, ValueError):
        pass  # not privileged / not Linux: the per-module purge still applies


def pytest_configure(config):
    """Marker registration (no pytest.ini in this repo).

    * ``slow`` — excluded from the tier-1 run (`-m 'not slow'`); its
      semantics are unchanged vs the seed, just registered now.
    * ``chaos`` — multi-PROCESS kill tests (subprocess spawn + kill +
      backoff waits).  Chaos tests that are also slow carry BOTH markers
      so tier-1 keeps its fast single-process subset; run the full matrix
      with ``pytest -m chaos``.
    """
    config.addinivalue_line(
        "markers", "slow: long-running; excluded from tier-1 (-m 'not slow')"
    )
    config.addinivalue_line(
        "markers",
        "chaos: cross-process fault-injection (kill subprocesses/workers)",
    )


# Compile-heavy integration modules, light -> heavy.  Everything NOT
# listed (the cheap unit modules: wire formats, tries, metrics, sandbox
# protocol, tracing, ...) runs first in its usual order; the listed
# modules are appended in THIS order, heaviest per-test at the very end.
# Time-to-signal ordering: failures in the cheap majority surface in the
# first minutes, and a CI/driver wall-clock budget that truncates the run
# cuts into the most expensive tail instead of a random alphabetical
# suffix.  Modules are already isolated (module-scoped fixtures, the
# _drop_xla_executables purge, monkeypatch-reverted env), so inter-module
# order is not load-bearing; intra-module order is unchanged.
_HEAVY_TAIL = (
    "test_flash_prefill.py",
    "test_fused_mlp.py",
    "test_kv_quant.py",
    "test_quant.py",
    "test_compaction.py",
    "test_llm_provider.py",
    "test_prefix_cache.py",
    "test_pallas_kernels.py",
    "test_constrained.py",
    "test_server.py",
    # autoscaler chaos e2e builds dp routers over the tiny model and
    # smoke-runs the bench traffic-ramp phase (compile-heavy rebuilds)
    "test_autoscaler.py",
    "test_dp_router.py",
    # disaggregated prefill/decode shares test_dp_router's dp=2 tiny
    # model and adds cross-replica ship compiles on top
    "test_disagg.py",
    "test_engine.py",
    # after test_engine: the tier tests share its tiny-model shapes, and
    # running them first would pre-warm the XLA cache under test_engine's
    # wall-clock-sensitive deadline tests (timeout would race length)
    "test_kv_tier.py",
    # object-store tier builds several engines over the same tiny-model
    # shapes (sleep on A / wake on B) — keep it with the tier tests on
    # the warm-cache side of test_engine
    "test_object_tier.py",
    # zero-copy movement (ISSUE 19) reuses the shipper pool shapes and
    # the object-tier fixtures — keep it with its neighbors on the
    # warm-cache side (its jax work is gather/scatter compiles only)
    "test_zero_copy.py",
    # store-guard fsck/outage acceptance builds the same engine shapes
    # (drain on A, scrub, wake on B) plus the bench store_outage smoke
    "test_store_guard.py",
    # flight-recorder integration shares the tiny-model shapes too and
    # arms wall-clock-sensitive delay failpoints — keep it off the cold
    # compile path like test_kv_tier
    "test_flight_recorder.py",
    # device-truth telemetry (ISSUE 18) drives real engines with the
    # kernel sampler tracing every step — jax.profiler windows on the
    # warm-cache side, same reasoning as test_flight_recorder
    "test_device_truth.py",
    "test_grammar_fsm.py",
    "test_speculative.py",
    "test_server_parallel.py",
    "test_parallel.py",
    "test_moe.py",
    "test_pp_ep.py",
    "test_vision.py",
    "test_checkpoint_serving.py",
    "test_llama_numerics.py",
    "test_long_context.py",
    "test_multihost.py",
)


def pytest_collection_modifyitems(config, items):
    """Time-to-signal ordering (see _HEAVY_TAIL): stable sort by
    (tail rank, original position) — unlisted modules keep their relative
    order up front, listed modules run last in list order."""
    rank = {name: i + 1 for i, name in enumerate(_HEAVY_TAIL)}
    pos = {id(item): i for i, item in enumerate(items)}
    items.sort(key=lambda item: (
        rank.get(item.path.name if hasattr(item, "path")
                 else item.fspath.basename, 0),
        pos[id(item)],
    ))


def pytest_sessionfinish(session, exitstatus):
    """Restore the host sysctl we raised (never leave kernel config
    mutated as a test side effect)."""
    if _PRIOR_MAP_COUNT is None:
        return
    try:
        with open("/proc/sys/vm/max_map_count", "w") as _f:
            _f.write(str(_PRIOR_MAP_COUNT))
    except OSError:
        pass

import gc  # noqa: E402

import pytest  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


@pytest.fixture(autouse=True, scope="module")
def _drop_xla_executables():
    """Per-module XLA executable purge (see max_map_count note above).

    Engines and jitted helpers from a finished module are garbage;
    clearing jax's caches and collecting frees their code mappings.  Live
    objects from module-scoped fixtures simply recompile on next use."""
    yield
    jax.clear_caches()
    gc.collect()
# DEFAULT matmul precision runs f32 einsums through a reduced-precision fast
# path (bf16 passes on TPU MXU, oneDNN on CPU) whose rounding is
# shape-dependent — decode-vs-full-forward token comparisons then flip on
# near-tied logits. Tests pin full f32 precision; production keeps DEFAULT.
jax.config.update("jax_default_matmul_precision", "highest")
