"""Test configuration.

Forces JAX onto CPU with 8 virtual devices so sharding/mesh tests exercise
real 8-way SPMD partitioning without TPU hardware (the standard JAX recipe:
--xla_force_host_platform_device_count).

Environment quirk: this machine's sitecustomize registers the "axon" TPU
PJRT plugin and imports jax before any test code runs, so JAX_PLATFORMS in
os.environ is read too late — the platform must be overridden through
jax.config after import (safe while no backend has been initialized yet).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
# Hermetic suite: never dial the default remote MCP server from tests
# (individual tests override this to exercise the config parser).
os.environ.setdefault("KAFKA_TPU_MCP_SERVERS", "[]")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# DEFAULT matmul precision runs f32 einsums through a reduced-precision fast
# path (bf16 passes on TPU MXU, oneDNN on CPU) whose rounding is
# shape-dependent — decode-vs-full-forward token comparisons then flip on
# near-tied logits. Tests pin full f32 precision; production keeps DEFAULT.
jax.config.update("jax_default_matmul_precision", "highest")
