"""Test configuration.

Forces JAX onto CPU with 8 virtual devices so sharding/mesh tests exercise
real 8-way SPMD partitioning without TPU hardware (the standard JAX recipe:
--xla_force_host_platform_device_count).  Must run before jax imports.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "0")
# DEFAULT matmul precision runs f32 einsums through a reduced-precision fast
# path (bf16 passes on TPU MXU, oneDNN on CPU) whose rounding is
# shape-dependent — decode-vs-full-forward token comparisons then flip on
# near-tied logits. Tests pin full f32 precision; production keeps DEFAULT.
import jax  # noqa: E402  (must come after the env setup above)

jax.config.update("jax_default_matmul_precision", "highest")
