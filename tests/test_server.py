"""API server tests: endpoint surface, SSE protocol (all four event kinds
+ [DONE]), thread persistence through HTTP, CRUD, and error paths.
Uses aiohttp's in-process test client with a scripted FakeLLM injected
through create_app's DI seams — no JAX, no network."""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from kafka_tpu.core.types import StreamChunk
from kafka_tpu.db import LocalDBClient
from kafka_tpu.llm.base import LLMProvider
from kafka_tpu.server import ServingConfig, create_app
from kafka_tpu.tools import Tool


def text_turn(*parts, cid="chatcmpl-s1"):
    chunks = [StreamChunk(role="assistant", id=cid)]
    chunks += [StreamChunk(content=p, id=cid) for p in parts]
    chunks.append(StreamChunk(
        finish_reason="stop", id=cid,
        usage={"prompt_tokens": 7, "completion_tokens": len(parts),
               "total_tokens": 7 + len(parts)},
    ))
    return chunks


def tool_turn(name, args, call_id="call_1", cid="chatcmpl-s2"):
    return [
        StreamChunk(role="assistant", id=cid),
        StreamChunk(tool_calls=[{
            "index": 0, "id": call_id, "type": "function",
            "function": {"name": name, "arguments": json.dumps(args)},
        }], id=cid),
        StreamChunk(
            finish_reason="tool_calls", id=cid,
            usage={"prompt_tokens": 11, "completion_tokens": 5,
                   "total_tokens": 16},
        ),
    ]


class FakeLLM(LLMProvider):
    provider_name = "fake"

    def __init__(self, turns):
        self.turns = list(turns)
        self.calls = []  # message lists, for asserting what the LLM saw

    async def stream_completion(self, messages, **kw):
        self.calls.append(messages)
        if not self.turns:
            script = text_turn("fallback")
        else:
            script = self.turns.pop(0)
        for chunk in script:
            yield chunk

    def get_available_models(self):
        return [{"id": "fake-model", "object": "model", "owned_by": "test",
                 "created": 0}]


def make_client(tmp_path, turns):
    """(client, llm, db) with the app fully wired around a FakeLLM."""
    llm = FakeLLM(turns)
    db = LocalDBClient(str(tmp_path / "server.db"))

    def add(a: int, b: int):
        return a + b

    async def build():
        app = await create_app(
            cfg=ServingConfig(db_path=str(tmp_path / "server.db")),
            llm_provider=llm,
            db=db,
            tools=[Tool(name="add", description="", handler=add)],
        )
        client = TestClient(TestServer(app))
        await client.start_server()
        return client

    return build(), llm, db


def parse_sse(text):
    events = []
    for line in text.splitlines():
        if not line.startswith("data: "):
            continue
        payload = line[len("data: "):]
        if payload == "[DONE]":
            events.append("[DONE]")
        else:
            events.append(json.loads(payload))
    return events


class TestBasics:
    def test_health_and_models(self, tmp_path):
        built, _, _ = make_client(tmp_path, [])

        async def go():
            client = await built
            try:
                h = await client.get("/health")
                assert h.status == 200
                hj = await h.json()
                assert hj["status"] == "ok" and hj["kafka_initialized"]
                m = await client.get("/v1/models")
                mj = await m.json()
                assert mj["data"][0]["id"] == "fake-model"
            finally:
                await client.close()

        asyncio.run(go())

    def test_invalid_body_400(self, tmp_path):
        built, _, _ = make_client(tmp_path, [])

        async def go():
            client = await built
            try:
                r = await client.post("/v1/chat/completions", json={"bad": 1})
                assert r.status == 400
            finally:
                await client.close()

        asyncio.run(go())


class TestThreadCRUD:
    def test_full_lifecycle(self, tmp_path):
        built, _, _ = make_client(tmp_path, [])

        async def go():
            client = await built
            try:
                r = await client.post("/v1/threads", json={"thread_id": "t-x"})
                assert r.status == 201
                assert (await r.json())["thread_id"] == "t-x"

                r = await client.get("/v1/threads")
                assert [t["thread_id"] for t in (await r.json())["threads"]] == ["t-x"]

                r = await client.get("/v1/threads/t-x")
                assert r.status == 200

                r = await client.put("/v1/threads/t-x/config",
                                     json={"model": "m2"})
                assert r.status == 200

                r = await client.get("/v1/threads/t-x/messages")
                assert (await r.json())["messages"] == []

                r = await client.delete("/v1/threads/t-x/messages")
                assert r.status == 200
                r = await client.delete("/v1/threads/t-x")
                assert r.status == 200
                r = await client.get("/v1/threads/t-x")
                assert r.status == 404
            finally:
                await client.close()

        asyncio.run(go())

    def test_opaque_fields_persist_through_thread_store(self, tmp_path):
        """thought_signature-style opaque fields on an incoming message
        survive request parsing, persistence, and replay (reference
        portkey.py:282-287 passthrough)."""
        built, llm, db = make_client(tmp_path, [text_turn("ok")])

        async def go():
            client = await built
            try:
                r = await client.post(
                    "/v1/threads/t-opq/chat/completions",
                    json={"model": "fake-model",
                          "messages": [{"role": "user", "content": "hi",
                                        "thought_signature": "sig-9"}]},
                )
                assert r.status == 200
                r = await client.get("/v1/threads/t-opq/messages")
                msgs = (await r.json())["messages"]
            finally:
                await client.close()
            user = next(m for m in msgs if m["role"] == "user")
            assert user.get("thought_signature") == "sig-9"

        asyncio.run(go())

    def test_missing_thread_404(self, tmp_path):
        built, _, _ = make_client(tmp_path, [])

        async def go():
            client = await built
            try:
                r = await client.get("/v1/threads/ghost/messages")
                assert r.status == 404
            finally:
                await client.close()

        asyncio.run(go())


class TestChatCompletions:
    def test_nonstreaming_collects_final(self, tmp_path):
        built, _, _ = make_client(tmp_path, [text_turn("hello ", "world")])

        async def go():
            client = await built
            try:
                r = await client.post("/v1/chat/completions", json={
                    "model": "fake-model",
                    "messages": [{"role": "user", "content": "hi"}],
                })
                assert r.status == 200
                body = await r.json()
                assert body["choices"][0]["message"]["content"] == "hello world"
                assert body["usage"]["total_tokens"] == 9
            finally:
                await client.close()

        asyncio.run(go())

    def test_streaming_protocol(self, tmp_path):
        built, _, _ = make_client(
            tmp_path,
            [tool_turn("add", {"a": 1, "b": 2}), text_turn("3", cid="chatcmpl-s3")],
        )

        async def go():
            client = await built
            try:
                r = await client.post("/v1/chat/completions", json={
                    "model": "fake-model", "stream": True,
                    "messages": [{"role": "user", "content": "1+2?"}],
                })
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/event-stream")
                events = parse_sse(await r.text())
            finally:
                await client.close()
            assert events[-1] == "[DONE]"
            kinds = [
                e.get("type") or e.get("object")
                for e in events if e != "[DONE]"
            ]
            assert "chat.completion.chunk" in kinds
            assert "tool_result" in kinds
            assert "tool_messages" in kinds
            assert kinds[-1] == "agent_done"
            # tool_messages batch precedes agent_done and contains the pair
            tm = next(e for e in events
                      if isinstance(e, dict) and e.get("type") == "tool_messages")
            roles = [m["role"] for m in tm["messages"]]
            # batch carries the tool cycle only; plain assistant text
            # streams live and is never batched (tests/test_sse_contract.py)
            assert roles == ["assistant", "tool"]

        asyncio.run(go())

    def test_thread_chat_persists_and_replays(self, tmp_path):
        built, llm, db = make_client(
            tmp_path,
            [text_turn("first"), text_turn("second", cid="chatcmpl-s4")],
        )

        async def go():
            client = await built
            try:
                for q in ("q1", "q2"):
                    r = await client.post(
                        "/v1/threads/t-chat/chat/completions",
                        json={"model": "fake-model",
                              "messages": [{"role": "user", "content": q}]},
                    )
                    assert r.status == 200
                r = await client.get("/v1/threads/t-chat/messages")
                msgs = (await r.json())["messages"]
            finally:
                await client.close()
            assert [m.get("content") for m in msgs] == [
                "q1", "first", "q2", "second"]

        asyncio.run(go())


class TestUsageAccounting:
    """ISSUE 3 satellite: the agent path reports REAL token usage (the
    reference returned zeros, SURVEY §5.1) — summed across every turn of
    a multi-turn tool loop, on both the non-streaming response and the
    terminal SSE frame (agent_done)."""

    # tool turn usage (11, 5, 16) + final text turn usage (7, 1, 8)
    EXPECTED = {"prompt_tokens": 18, "completion_tokens": 6,
                "total_tokens": 24}

    def test_thread_completion_sums_usage_across_tool_loop(self, tmp_path):
        built, _, _ = make_client(
            tmp_path,
            [tool_turn("add", {"a": 1, "b": 2}),
             text_turn("3", cid="chatcmpl-u2")],
        )

        async def go():
            client = await built
            try:
                r = await client.post(
                    "/v1/threads/t-usage/chat/completions",
                    json={"model": "fake-model",
                          "messages": [{"role": "user", "content": "1+2?"}]},
                )
                assert r.status == 200
                body = await r.json()
            finally:
                await client.close()
            # non-zero AND additive: both turns' engine usage is present
            assert body["usage"] == self.EXPECTED

        asyncio.run(go())

    def test_agent_done_carries_summed_usage_on_sse(self, tmp_path):
        built, _, _ = make_client(
            tmp_path,
            [tool_turn("add", {"a": 1, "b": 2}),
             text_turn("3", cid="chatcmpl-u3")],
        )

        async def go():
            client = await built
            try:
                r = await client.post(
                    "/v1/threads/t-usage-sse/chat/completions",
                    json={"model": "fake-model", "stream": True,
                          "messages": [{"role": "user", "content": "1+2?"}]},
                )
                assert r.status == 200
                events = parse_sse(await r.text())
            finally:
                await client.close()
            done = next(e for e in events if isinstance(e, dict)
                        and e.get("type") == "agent_done")
            assert done["usage"] == self.EXPECTED
            # per-turn usage frames still stream (OpenAI chunk contract)
            per_turn = [e["usage"] for e in events
                        if isinstance(e, dict) and e.get("usage")
                        and e.get("object") == "chat.completion.chunk"]
            assert len(per_turn) == 2

        asyncio.run(go())


class TestAgentRun:
    def test_agent_run_sse(self, tmp_path):
        built, _, _ = make_client(tmp_path, [text_turn("done deal")])

        async def go():
            client = await built
            try:
                r = await client.post("/v1/agent/run", json={
                    "messages": [{"role": "user", "content": "go"}],
                })
                assert r.status == 200
                events = parse_sse(await r.text())
            finally:
                await client.close()
            done = [e for e in events
                    if isinstance(e, dict) and e.get("type") == "agent_done"]
            assert done and done[0]["final_content"] == "done deal"

        asyncio.run(go())

    def test_thread_agent_run_creates_thread(self, tmp_path):
        built, _, db = make_client(tmp_path, [text_turn("ok")])

        async def go():
            client = await built
            try:
                r = await client.post("/v1/threads/t-agent/agent/run", json={
                    "messages": [{"role": "user", "content": "go"}],
                })
                assert r.status == 200
                await r.text()
                r = await client.get("/v1/threads/t-agent/messages")
                return (await r.json())["messages"]
            finally:
                await client.close()

        msgs = asyncio.run(go())
        assert [m["role"] for m in msgs] == ["user", "assistant"]


class TestAuthAndProfiles:
    """Playground parity tier (VERDICT r2 #10): optional bearer-token auth
    + profiles whose config new threads inherit (the reference gates its
    playground behind auth-provider.tsx and joins thread config through
    kafka profiles)."""

    def make_authed_client(self, tmp_path, token):
        llm = FakeLLM([text_turn("hi")])
        db = LocalDBClient(str(tmp_path / "authed.db"))

        async def build():
            app = await create_app(
                cfg=ServingConfig(db_path=str(tmp_path / "authed.db"),
                                  api_token=token),
                llm_provider=llm, db=db, tools=[],
            )
            client = TestClient(TestServer(app))
            await client.start_server()
            return client

        return build()

    def test_token_required_when_configured(self, tmp_path):
        built = self.make_authed_client(tmp_path, "sekrit")

        async def go():
            client = await built
            try:
                # /v1 surface rejects missing and wrong tokens
                r = await client.get("/v1/threads")
                assert r.status == 401
                r = await client.get(
                    "/v1/threads",
                    headers={"Authorization": "Bearer wrong"})
                assert r.status == 401
                r = await client.get("/metrics")
                assert r.status == 401
                # right token passes
                ok = {"Authorization": "Bearer sekrit"}
                r = await client.get("/v1/threads", headers=ok)
                assert r.status == 200
                # health and the playground page itself stay open
                assert (await client.get("/health")).status == 200
                assert (await client.get("/playground")).status == 200
            finally:
                await client.close()

        asyncio.run(go())

    def test_no_token_configured_stays_open(self, tmp_path):
        built, _, _ = make_client(tmp_path, [])

        async def go():
            client = await built
            try:
                assert (await client.get("/v1/threads")).status == 200
            finally:
                await client.close()

        asyncio.run(go())

    def test_profiles_crud_and_thread_inheritance(self, tmp_path):
        built, llm, db = make_client(tmp_path, [text_turn("ok")])

        async def go():
            client = await built
            try:
                r = await client.post("/v1/profiles", json={
                    "name": "research",
                    "config": {"global_prompt": "Always cite sources.",
                               "model": "tiny"},
                })
                assert r.status == 201
                profile = await r.json()
                assert profile["name"] == "research"

                r = await client.get("/v1/profiles")
                assert r.status == 200
                listed = (await r.json())["profiles"]
                assert [p["name"] for p in listed] == ["research"]

                # a thread created with the profile inherits its config
                r = await client.post("/v1/threads", json={
                    "profile_id": profile["profile_id"]})
                assert r.status == 201
                tid = (await r.json())["thread_id"]
                cfg = await db.get_thread_config(tid)
                assert cfg["global_prompt"] == "Always cite sources."
                assert cfg["profile_id"] == profile["profile_id"]

                # serving through the thread works (config consumed at
                # per-thread initialize: kafka/v1.py global_prompt section)
                r = await client.post(
                    f"/v1/threads/{tid}/agent/run",
                    json={"messages": [{"role": "user", "content": "go"}]})
                assert r.status == 200
                await r.text()
                # the profile's global_prompt reached the model
                sys_msgs = [m for m in llm.calls[-1]
                            if getattr(m, "role", m.get("role") if
                               isinstance(m, dict) else None) == "system"]
                joined = " ".join(
                    (m.content if hasattr(m, "content")
                     else m.get("content", "")) or "" for m in sys_msgs)
                assert "Always cite sources." in joined

                # unknown profile is a 400, not a silent no-config thread —
                # and the failed create must not leave an orphan thread
                before = len((await (await client.get(
                    "/v1/threads")).json())["threads"])
                r = await client.post("/v1/threads", json={
                    "profile_id": "profile_nope"})
                assert r.status == 400
                after = len((await (await client.get(
                    "/v1/threads")).json())["threads"])
                assert after == before
            finally:
                await client.close()

        asyncio.run(go())


class OverloadedLLM(FakeLLM):
    """FakeLLM reporting a full engine queue (admission_check seam)."""

    def __init__(self, turns, retry_after=7.0):
        super().__init__(turns)
        self.retry_after = retry_after

    def admission_check(self):
        return self.retry_after


class DrainRecordingLLM(FakeLLM):
    def __init__(self, turns):
        super().__init__(turns)
        self.drained_with = None

    async def drain(self, timeout_s):
        self.drained_with = timeout_s
        return True


class TestLifecycleHTTP:
    """429/Retry-After admission contract + graceful-drain surface."""

    def _build(self, tmp_path, llm):
        db = LocalDBClient(str(tmp_path / "lh.db"))

        async def build():
            app = await create_app(
                cfg=ServingConfig(db_path=str(tmp_path / "lh.db")),
                llm_provider=llm,
                db=db,
                tools=[],
            )
            client = TestClient(TestServer(app))
            await client.start_server()
            return client

        return build

    def test_queue_full_answers_429_with_retry_after(self, tmp_path):
        llm = OverloadedLLM([], retry_after=7.0)
        build = self._build(tmp_path, llm)

        async def go():
            client = await build()
            try:
                r = await client.post(
                    "/v1/chat/completions",
                    json={"messages": [{"role": "user", "content": "hi"}]},
                )
                assert r.status == 429
                assert r.headers["Retry-After"] == "7"
                body = await r.json()
                assert body["error"]["type"] == "server_overloaded"
                # CRUD endpoints stay open under overload
                t = await client.post("/v1/threads", json={})
                assert t.status == 201
            finally:
                await client.close()

        asyncio.run(go())

    def test_admitting_when_engine_has_room(self, tmp_path):
        llm = OverloadedLLM([text_turn("ok")], retry_after=None)
        build = self._build(tmp_path, llm)

        async def go():
            client = await build()
            try:
                r = await client.post(
                    "/v1/chat/completions",
                    json={"model": "fake-model",
                          "messages": [{"role": "user", "content": "hi"}]},
                )
                assert r.status == 200
            finally:
                await client.close()

        asyncio.run(go())

    def test_draining_flips_health_and_rejects_serving(self, tmp_path):
        llm = FakeLLM([])
        build = self._build(tmp_path, llm)

        async def go():
            client = await build()
            try:
                from kafka_tpu.server.app import STATE_KEY

                client.app[STATE_KEY]["draining"] = True
                h = await client.get("/health")
                assert h.status == 503
                assert (await h.json())["status"] == "draining"
                r = await client.post(
                    "/v1/agent/run",
                    json={"messages": [{"role": "user", "content": "hi"}]},
                )
                assert r.status == 503
                assert "Retry-After" in r.headers
                # reads stay open while draining (debugging/observability)
                t = await client.get("/v1/threads")
                assert t.status == 200
            finally:
                await client.close()

        asyncio.run(go())

    def test_shutdown_invokes_provider_drain(self, tmp_path):
        llm = DrainRecordingLLM([])
        build = self._build(tmp_path, llm)

        async def go():
            client = await build()
            from kafka_tpu.server.app import STATE_KEY

            app = client.app
            await client.close()  # server shutdown runs on_shutdown hooks
            assert llm.drained_with == app[STATE_KEY]["cfg"].drain_timeout_s
            assert app[STATE_KEY]["draining"] is True

        asyncio.run(go())


class TestResizeEndpoint:
    ADMIN = {"Authorization": "Bearer admin-secret"}

    def _resizable_llm(self, calls):
        llm = FakeLLM([])

        # fake resizable provider: has resize_dp + engine.rebuild
        class FakeEngine:
            def rebuild(self, dp):
                pass

        async def resize_dp(dp, drain_timeout_s=30.0):
            calls.append((dp, drain_timeout_s))
            return True

        llm.engine = FakeEngine()
        llm.resize_dp = resize_dp
        return llm

    def test_resize_refused_without_api_token(self, tmp_path):
        """The open-if-no-token dev default does not extend to the
        operator-destructive admin surface."""
        built, _, _ = make_client(tmp_path, [])

        async def go():
            client = await built
            try:
                r = await client.post("/admin/resize", json={"dp": 2})
                assert r.status == 403
                assert "API_TOKEN" in (await r.json())["error"]
            finally:
                await client.close()

        asyncio.run(go())

    def test_resize_without_dp_topology_is_501(self, tmp_path):
        db = LocalDBClient(str(tmp_path / "r0.db"))

        async def go():
            app = await create_app(
                cfg=ServingConfig(db_path=str(tmp_path / "r0.db"),
                                  api_token="admin-secret"),
                llm_provider=FakeLLM([]), db=db, tools=[],
            )
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.post("/admin/resize", json={"dp": 2},
                                      headers=self.ADMIN)
                assert r.status == 501
                assert "topology" in (await r.json())["error"]
            finally:
                await client.close()

        asyncio.run(go())

    def test_resize_validates_body_and_runs(self, tmp_path):
        calls = []
        llm = self._resizable_llm(calls)
        db = LocalDBClient(str(tmp_path / "r.db"))

        async def go():
            app = await create_app(
                cfg=ServingConfig(db_path=str(tmp_path / "r.db"),
                                  api_token="admin-secret"),
                llm_provider=llm, db=db, tools=[],
            )
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                # the static-token middleware still gates the route
                r = await client.post("/admin/resize", json={"dp": 2})
                assert r.status == 401
                r = await client.post("/admin/resize", json={"dp": 0},
                                      headers=self.ADMIN)
                assert r.status == 400
                r = await client.post("/admin/resize", json={},
                                      headers=self.ADMIN)
                assert r.status == 400
                r = await client.post("/admin/resize",
                                      json={"dp": 2, "drain_timeout_s": 1},
                                      headers=self.ADMIN)
                assert r.status == 200
                assert (await r.json()) == {"dp": 2, "clean": True}
                assert calls == [(2, 1.0)]
            finally:
                await client.close()

        asyncio.run(go())

    def test_resize_roles_passthrough(self, tmp_path):
        """ISSUE 13 satellite: an optional "roles" spec rides the same
        endpoint into resize_dp (absent = today's keep-current
        behavior, ""/null dissolves the pools)."""
        calls = []
        llm = FakeLLM([])

        class FakeEngine:
            def rebuild(self, dp, roles=None):
                pass

        async def resize_dp(dp, drain_timeout_s=30.0, **kw):
            calls.append((dp, kw.get("roles", "<absent>")))
            return True

        llm.engine = FakeEngine()
        llm.resize_dp = resize_dp
        db = LocalDBClient(str(tmp_path / "rr.db"))

        async def go():
            app = await create_app(
                cfg=ServingConfig(db_path=str(tmp_path / "rr.db"),
                                  api_token="admin-secret"),
                llm_provider=llm, db=db, tools=[],
            )
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.post(
                    "/admin/resize",
                    json={"dp": 3, "roles": "prefill:1,decode:2"},
                    headers=self.ADMIN,
                )
                assert r.status == 200
                assert (await r.json()) == {
                    "dp": 3, "clean": True,
                    "roles": "prefill:1,decode:2",
                }
                r = await client.post("/admin/resize",
                                      json={"dp": 2, "roles": None},
                                      headers=self.ADMIN)
                assert r.status == 200
                assert (await r.json())["roles"] is None
                r = await client.post("/admin/resize",
                                      json={"dp": 2, "roles": 7},
                                      headers=self.ADMIN)
                assert r.status == 400
                r = await client.post("/admin/resize", json={"dp": 2},
                                      headers=self.ADMIN)
                assert "roles" not in (await r.json())
                assert calls == [(3, "prefill:1,decode:2"), (2, None),
                                 (2, "<absent>")]
            finally:
                await client.close()

        asyncio.run(go())
