"""API server tests: endpoint surface, SSE protocol (all four event kinds
+ [DONE]), thread persistence through HTTP, CRUD, and error paths.
Uses aiohttp's in-process test client with a scripted FakeLLM injected
through create_app's DI seams — no JAX, no network."""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from kafka_tpu.core.types import StreamChunk
from kafka_tpu.db import LocalDBClient
from kafka_tpu.llm.base import LLMProvider
from kafka_tpu.server import ServingConfig, create_app
from kafka_tpu.tools import Tool


def text_turn(*parts, cid="chatcmpl-s1"):
    chunks = [StreamChunk(role="assistant", id=cid)]
    chunks += [StreamChunk(content=p, id=cid) for p in parts]
    chunks.append(StreamChunk(
        finish_reason="stop", id=cid,
        usage={"prompt_tokens": 7, "completion_tokens": len(parts),
               "total_tokens": 7 + len(parts)},
    ))
    return chunks


def tool_turn(name, args, call_id="call_1", cid="chatcmpl-s2"):
    return [
        StreamChunk(role="assistant", id=cid),
        StreamChunk(tool_calls=[{
            "index": 0, "id": call_id, "type": "function",
            "function": {"name": name, "arguments": json.dumps(args)},
        }], id=cid),
        StreamChunk(finish_reason="tool_calls", id=cid),
    ]


class FakeLLM(LLMProvider):
    provider_name = "fake"

    def __init__(self, turns):
        self.turns = list(turns)

    async def stream_completion(self, messages, **kw):
        if not self.turns:
            script = text_turn("fallback")
        else:
            script = self.turns.pop(0)
        for chunk in script:
            yield chunk

    def get_available_models(self):
        return [{"id": "fake-model", "object": "model", "owned_by": "test",
                 "created": 0}]


def make_client(tmp_path, turns):
    """(client, llm, db) with the app fully wired around a FakeLLM."""
    llm = FakeLLM(turns)
    db = LocalDBClient(str(tmp_path / "server.db"))

    def add(a: int, b: int):
        return a + b

    async def build():
        app = await create_app(
            cfg=ServingConfig(db_path=str(tmp_path / "server.db")),
            llm_provider=llm,
            db=db,
            tools=[Tool(name="add", description="", handler=add)],
        )
        client = TestClient(TestServer(app))
        await client.start_server()
        return client

    return build(), llm, db


def parse_sse(text):
    events = []
    for line in text.splitlines():
        if not line.startswith("data: "):
            continue
        payload = line[len("data: "):]
        if payload == "[DONE]":
            events.append("[DONE]")
        else:
            events.append(json.loads(payload))
    return events


class TestBasics:
    def test_health_and_models(self, tmp_path):
        built, _, _ = make_client(tmp_path, [])

        async def go():
            client = await built
            try:
                h = await client.get("/health")
                assert h.status == 200
                hj = await h.json()
                assert hj["status"] == "ok" and hj["kafka_initialized"]
                m = await client.get("/v1/models")
                mj = await m.json()
                assert mj["data"][0]["id"] == "fake-model"
            finally:
                await client.close()

        asyncio.run(go())

    def test_invalid_body_400(self, tmp_path):
        built, _, _ = make_client(tmp_path, [])

        async def go():
            client = await built
            try:
                r = await client.post("/v1/chat/completions", json={"bad": 1})
                assert r.status == 400
            finally:
                await client.close()

        asyncio.run(go())


class TestThreadCRUD:
    def test_full_lifecycle(self, tmp_path):
        built, _, _ = make_client(tmp_path, [])

        async def go():
            client = await built
            try:
                r = await client.post("/v1/threads", json={"thread_id": "t-x"})
                assert r.status == 201
                assert (await r.json())["thread_id"] == "t-x"

                r = await client.get("/v1/threads")
                assert [t["thread_id"] for t in (await r.json())["threads"]] == ["t-x"]

                r = await client.get("/v1/threads/t-x")
                assert r.status == 200

                r = await client.put("/v1/threads/t-x/config",
                                     json={"model": "m2"})
                assert r.status == 200

                r = await client.get("/v1/threads/t-x/messages")
                assert (await r.json())["messages"] == []

                r = await client.delete("/v1/threads/t-x/messages")
                assert r.status == 200
                r = await client.delete("/v1/threads/t-x")
                assert r.status == 200
                r = await client.get("/v1/threads/t-x")
                assert r.status == 404
            finally:
                await client.close()

        asyncio.run(go())

    def test_missing_thread_404(self, tmp_path):
        built, _, _ = make_client(tmp_path, [])

        async def go():
            client = await built
            try:
                r = await client.get("/v1/threads/ghost/messages")
                assert r.status == 404
            finally:
                await client.close()

        asyncio.run(go())


class TestChatCompletions:
    def test_nonstreaming_collects_final(self, tmp_path):
        built, _, _ = make_client(tmp_path, [text_turn("hello ", "world")])

        async def go():
            client = await built
            try:
                r = await client.post("/v1/chat/completions", json={
                    "model": "fake-model",
                    "messages": [{"role": "user", "content": "hi"}],
                })
                assert r.status == 200
                body = await r.json()
                assert body["choices"][0]["message"]["content"] == "hello world"
                assert body["usage"]["total_tokens"] == 9
            finally:
                await client.close()

        asyncio.run(go())

    def test_streaming_protocol(self, tmp_path):
        built, _, _ = make_client(
            tmp_path,
            [tool_turn("add", {"a": 1, "b": 2}), text_turn("3", cid="chatcmpl-s3")],
        )

        async def go():
            client = await built
            try:
                r = await client.post("/v1/chat/completions", json={
                    "model": "fake-model", "stream": True,
                    "messages": [{"role": "user", "content": "1+2?"}],
                })
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/event-stream")
                events = parse_sse(await r.text())
            finally:
                await client.close()
            assert events[-1] == "[DONE]"
            kinds = [
                e.get("type") or e.get("object")
                for e in events if e != "[DONE]"
            ]
            assert "chat.completion.chunk" in kinds
            assert "tool_result" in kinds
            assert "tool_messages" in kinds
            assert kinds[-1] == "agent_done"
            # tool_messages batch precedes agent_done and contains the pair
            tm = next(e for e in events
                      if isinstance(e, dict) and e.get("type") == "tool_messages")
            roles = [m["role"] for m in tm["messages"]]
            # batch carries the tool cycle only; plain assistant text
            # streams live and is never batched (tests/test_sse_contract.py)
            assert roles == ["assistant", "tool"]

        asyncio.run(go())

    def test_thread_chat_persists_and_replays(self, tmp_path):
        built, llm, db = make_client(
            tmp_path,
            [text_turn("first"), text_turn("second", cid="chatcmpl-s4")],
        )

        async def go():
            client = await built
            try:
                for q in ("q1", "q2"):
                    r = await client.post(
                        "/v1/threads/t-chat/chat/completions",
                        json={"model": "fake-model",
                              "messages": [{"role": "user", "content": q}]},
                    )
                    assert r.status == 200
                r = await client.get("/v1/threads/t-chat/messages")
                msgs = (await r.json())["messages"]
            finally:
                await client.close()
            assert [m.get("content") for m in msgs] == [
                "q1", "first", "q2", "second"]

        asyncio.run(go())


class TestAgentRun:
    def test_agent_run_sse(self, tmp_path):
        built, _, _ = make_client(tmp_path, [text_turn("done deal")])

        async def go():
            client = await built
            try:
                r = await client.post("/v1/agent/run", json={
                    "messages": [{"role": "user", "content": "go"}],
                })
                assert r.status == 200
                events = parse_sse(await r.text())
            finally:
                await client.close()
            done = [e for e in events
                    if isinstance(e, dict) and e.get("type") == "agent_done"]
            assert done and done[0]["final_content"] == "done deal"

        asyncio.run(go())

    def test_thread_agent_run_creates_thread(self, tmp_path):
        built, _, db = make_client(tmp_path, [text_turn("ok")])

        async def go():
            client = await built
            try:
                r = await client.post("/v1/threads/t-agent/agent/run", json={
                    "messages": [{"role": "user", "content": "go"}],
                })
                assert r.status == 200
                await r.text()
                r = await client.get("/v1/threads/t-agent/messages")
                return (await r.json())["messages"]
            finally:
                await client.close()

        msgs = asyncio.run(go())
        assert [m["role"] for m in msgs] == ["user", "assistant"]
