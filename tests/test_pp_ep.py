"""Pipeline and expert parallelism + multi-host init (VERDICT missing #7)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.models import ModelConfig, forward, init_params
from kafka_tpu.parallel import (
    MeshConfig,
    init_distributed,
    init_moe_params,
    make_mesh,
    moe_mlp_reference,
    moe_mlp_sharded,
    pp_forward,
    shard_moe_params,
    shard_params_pp,
)


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(name="pp-test", vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_layers=4, num_heads=8,
                      num_kv_heads=4, head_dim=16, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(31))
    return cfg, params


class TestPipelineParallel:
    def test_pp_forward_matches_single_device(self, model):
        cfg, params = model
        tokens = jnp.asarray(
            [np.random.RandomState(0).randint(1, 128, 12)], jnp.int32)
        pos = jnp.arange(12, dtype=jnp.int32)[None, :]
        ref, _ = forward(params, cfg, tokens, pos)

        mesh = make_mesh(MeshConfig(pp=4, tp=2))
        sharded = shard_params_pp(params, cfg, mesh)
        out = pp_forward(sharded, cfg, tokens, pos, mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)

    def test_pp_alone_without_tp(self, model):
        cfg, params = model
        tokens = jnp.asarray([[5, 9, 23, 54, 3]], jnp.int32)
        pos = jnp.arange(5, dtype=jnp.int32)[None, :]
        ref, _ = forward(params, cfg, tokens, pos)
        mesh = make_mesh(MeshConfig(pp=2))
        out = pp_forward(shard_params_pp(params, cfg, mesh), cfg,
                         tokens, pos, mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)

    def test_layers_must_divide_stages(self, model):
        cfg, params = model
        mesh = make_mesh(MeshConfig(pp=3))
        with pytest.raises(ValueError, match="divisible by pp"):
            pp_forward(params, cfg, jnp.zeros((1, 4), jnp.int32),
                       jnp.zeros((1, 4), jnp.int32), mesh)

    def test_weights_actually_stage_sharded(self, model):
        """Each pp rank must hold only L/pp layers' weights (the HBM win)."""
        cfg, params = model
        mesh = make_mesh(MeshConfig(pp=4, tp=2))
        sharded = shard_params_pp(params, cfg, mesh)
        wq = sharded["layers"]["wq"]
        shard_shapes = {s.data.shape for s in wq.addressable_shards}
        # 4 layers / 4 stages over pp, 8 heads / 2 over tp:
        # each device holds 1/(pp*tp) of the stacked weights
        assert shard_shapes == {(1, 64, 4, 16)}


class TestPipelineServing:
    """PP through the real engine (round-2 verdict item 2): KV-cached
    prefill + decode with the pool's layer axis stage-sharded — a model
    bigger than one device's HBM can actually *serve*, not just forward."""

    @pytest.fixture(scope="class")
    def served(self, model):
        from kafka_tpu.runtime import EngineConfig, InferenceEngine

        cfg, params = model
        ecfg = EngineConfig(max_batch=2, page_size=8, num_pages=32,
                            max_pages_per_seq=8, prefill_buckets=(8, 16, 32))
        mesh = make_mesh(MeshConfig(pp=2, tp=2))
        eng = InferenceEngine(cfg, params, ecfg, kv_dtype=jnp.float32,
                              mesh=mesh)
        ref = InferenceEngine(cfg, params, ecfg, kv_dtype=jnp.float32)
        return eng, ref

    def test_kv_pool_is_stage_sharded(self, served):
        eng, _ = served
        kp = eng.k_pool
        # 4 layers / pp=2, merged kv minor axis 4*16=64 / tp=2
        assert kp.sharding.shard_shape(kp.shape) == (2, kp.shape[1], 32)

    def test_weights_stage_sharded_in_engine(self, served):
        eng, _ = served
        wq = eng.params["layers"]["wq"]
        assert wq.sharding.shard_shape(wq.shape)[0] == 2  # L/pp

    def test_decode_token_exact_vs_single_device(self, served):
        from kafka_tpu.runtime import GenRequest

        eng, ref = served
        p = list(np.random.RandomState(11).randint(1, 128, 13))
        solo = ref.generate(list(p), max_new_tokens=6)
        for i in range(2):  # full batch through the pipeline
            eng.submit(GenRequest(request_id=f"q{i}", prompt_ids=list(p),
                                  max_new_tokens=6))
        done = eng.run_to_completion()
        for rid, r in done.items():
            assert r.output_ids == solo.output_ids, rid

    def test_chunked_prefill_across_buckets(self, served):
        """A prompt spanning multiple prefill chunks writes KV through the
        stage-sharded pool correctly (start-offset path)."""
        from kafka_tpu.runtime import GenRequest

        eng, ref = served
        p = list(np.random.RandomState(12).randint(1, 128, 41))  # 32+16
        solo = ref.generate(list(p), max_new_tokens=4)
        got = eng.generate(list(p), max_new_tokens=4)
        assert got.output_ids == solo.output_ids

    def test_pp_sp_compose_rejected(self, model):
        from kafka_tpu.runtime import EngineConfig, InferenceEngine

        cfg, params = model
        mesh = make_mesh(MeshConfig(pp=2, sp=2, tp=2))
        with pytest.raises(ValueError, match="ring"):
            InferenceEngine(cfg, params, EngineConfig(), mesh=mesh)


class TestExpertParallel:
    @pytest.mark.parametrize("top_k", [1, 2])
    def test_sharded_moe_matches_dense(self, top_k):
        params = init_moe_params(jax.random.PRNGKey(3), num_experts=8,
                                 hidden=32, ffn=64)
        x = jax.random.normal(jax.random.PRNGKey(4), (10, 32), jnp.float32)
        ref = moe_mlp_reference(x, params, top_k=top_k)
        mesh = make_mesh(MeshConfig(ep=8))
        out = moe_mlp_sharded(mesh, x, shard_moe_params(params, mesh),
                              top_k=top_k)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)

    def test_ep_composes_with_tp_axis_present(self):
        params = init_moe_params(jax.random.PRNGKey(5), num_experts=4,
                                 hidden=16, ffn=32)
        x = jax.random.normal(jax.random.PRNGKey(6), (6, 16), jnp.float32)
        ref = moe_mlp_reference(x, params, top_k=2)
        mesh = make_mesh(MeshConfig(ep=4, tp=2))
        out = moe_mlp_sharded(mesh, x, shard_moe_params(params, mesh), top_k=2)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


class TestDistributedInit:
    def test_single_process_noop(self, monkeypatch):
        for var in ("KAFKA_TPU_COORDINATOR", "KAFKA_TPU_NUM_PROCESSES",
                    "KAFKA_TPU_PROCESS_ID"):
            monkeypatch.delenv(var, raising=False)
        assert init_distributed() is False  # no config -> no coordinator wait
