"""On-device grammar FSM constrained decoding (ISSUE 7).

The load-bearing property: the device-FSM path is a pure latency
optimization — per-state allowed token sets are compiled from the exact
host-mask semantics (llm/constrained.allowed_ids_for), so the FSM path
and the host mask-fn path emit BIT-IDENTICAL token streams (greedy and
sampled) across random tool schemas, every tool_choice form, mixed
batches, and preemption churn, while the FSM path awaits ZERO device→host
round trips.  Constrained lanes may also speculate: the verify step masks
every position with the FSM state reached through the candidate prefix,
and rejected-tail FSM rollback mirrors the KV seq_len clamp.
"""

import json
import logging
import random
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.llm.constrained import (
    ToolCallMaskFn,
    allowed_ids_for,
    compile_grammar_for_mask_fn,
    compile_tool_call_grammar,
    validate_tool_call_json,
)
from kafka_tpu.models import ModelConfig, init_params
from kafka_tpu.models.tokenizer import ByteTokenizer
from kafka_tpu.runtime import EngineConfig, GenRequest, InferenceEngine

TOOLS = [
    {
        "type": "function",
        "function": {
            "name": "get_weather",
            "parameters": {
                "type": "object",
                "properties": {
                    "city": {"type": "string"},
                    "units": {"type": "string"},
                },
            },
        },
    },
    {
        "type": "function",
        "function": {
            "name": "get_time",
            "parameters": {"type": "object", "properties": {}},
        },
    },
]


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(name="gfsm-test", vocab_size=262, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(3))
    return cfg, params


@pytest.fixture(scope="module")
def tok():
    return ByteTokenizer()


@pytest.fixture(scope="module")
def grammar(tok):
    g = compile_tool_call_grammar(tok, TOOLS, vocab_size=262)
    assert g is not None
    return g


def make_engine(cfg, params, **kw):
    defaults = dict(max_batch=2, page_size=16, num_pages=64,
                    max_pages_per_seq=16, prefill_buckets=(16, 32, 64))
    defaults.update(kw)
    return InferenceEngine(cfg, params, EngineConfig(**defaults),
                           kv_dtype=jnp.float32)


def run_constrained(cfg, params, tok, grammar_or_none, tools=TOOLS,
                    prompt="call a tool", force_name=None, max_new=120,
                    temperature=0.0, seed=0, engine=None, **ecfg_kw):
    eng = engine or make_engine(cfg, params, **ecfg_kw)
    mask = ToolCallMaskFn(tok, tools, force_name=force_name)
    req = GenRequest(
        request_id=f"r-{id(mask)}", prompt_ids=tok.encode(prompt),
        max_new_tokens=max_new, temperature=temperature, seed=seed,
        stop_token_ids=tuple(tok.stop_ids), logits_mask_fn=mask,
        grammar=grammar_or_none,
    )
    eng.submit(req)
    eng.run_to_completion()
    return req, eng


def random_tools(rng: random.Random):
    """A random small tool schema (names/props from a safe alphabet)."""
    def word():
        return "".join(rng.choice("abcdefgh_") for _ in range(rng.randint(2, 8)))

    tools = []
    for _ in range(rng.randint(1, 3)):
        props = {word(): {"type": "string"}
                 for _ in range(rng.randint(0, 3))}
        params = {"type": "object", "properties": props}
        if rng.random() < 0.2:
            params["additionalProperties"] = True
        tools.append({"type": "function",
                      "function": {"name": word(), "parameters": params}})
    return tools


class TestCompiler:
    def test_rows_match_host_mask_along_trajectory(self, tok, grammar):
        """The compiled table's per-state allowed sets must equal the host
        mask fn's, position by position, along a random legal walk."""
        rng = random.Random(1)
        fn = ToolCallMaskFn(tok, TOOLS)
        out, state = [], 0
        for _ in range(150):
            host = {int(x) for x in fn(out)}
            dev = set(np.nonzero(grammar.allowed_row(state))[0].tolist())
            assert host == dev, sorted(host ^ dev)[:10]
            if host == {tok.eot_id}:
                return
            t = rng.choice(sorted(host))
            out.append(t)
            state = grammar.walk([t], start=state)
            assert state >= 0
        pytest.fail("walk never reached done")

    def test_walk_rejects_illegal_history(self, tok, grammar):
        bad = tok.encode("not json at all")
        assert grammar.walk(bad) == -1

    def test_dist_decreases_to_done(self, tok, grammar):
        """Every state has a distance-decreasing successor (the wrap-up
        guarantee), and done states sit at distance 0."""
        for s in range(grammar.num_states):
            d = int(grammar.dist[s])
            if d == 0:
                continue
            row = grammar.trans[s]
            succ = row[row >= 0]
            assert (grammar.dist[succ] < d).any(), s

    def test_table_cap_falls_back(self, tok):
        g = compile_tool_call_grammar(tok, TOOLS, vocab_size=262,
                                      max_table_bytes=1024)
        assert g is None

    def test_eot_outside_vocab_falls_back(self, tok):
        g = compile_tool_call_grammar(tok, TOOLS, vocab_size=16)
        assert g is None

    def test_env_gate_and_cache(self, tok, monkeypatch):
        mask = ToolCallMaskFn(tok, TOOLS)
        monkeypatch.setenv("KAFKA_TPU_GRAMMAR_ONDEVICE", "0")
        assert compile_grammar_for_mask_fn(mask, 262) is None
        monkeypatch.delenv("KAFKA_TPU_GRAMMAR_ONDEVICE")
        g1 = compile_grammar_for_mask_fn(mask, 262)
        g2 = compile_grammar_for_mask_fn(ToolCallMaskFn(tok, TOOLS), 262)
        assert g1 is not None and g1 is g2  # cached per (schema, vocab)

    def test_custom_mask_fn_not_lowered(self):
        assert compile_grammar_for_mask_fn(lambda out: None, 262) is None


class TestDifferentialEquivalence:
    """On-device FSM vs host mask-fn path: bit-identical token streams."""

    @pytest.mark.parametrize("temperature,seed", [
        (0.0, 0), (1.0, 1), (1.5, 2),
    ])
    def test_single_lane_bit_identical(self, model, tok, grammar,
                                       temperature, seed):
        cfg, params = model
        host, eh = run_constrained(cfg, params, tok, None,
                                   temperature=temperature, seed=seed)
        fsm, ef = run_constrained(cfg, params, tok, grammar,
                                  temperature=temperature, seed=seed)
        assert fsm.output_ids == host.output_ids
        assert fsm.constrained_roundtrips == 0
        assert host.constrained_roundtrips >= 0
        assert ef.metrics.constrained_ondevice_tokens == len(fsm.output_ids)
        text = tok.decode(
            [t for t in fsm.output_ids if t not in tok.stop_ids])
        assert validate_tool_call_json(text, TOOLS), text

    def test_random_schema_matrix(self, model, tok):
        """Random schemas x tool_choice forms, greedy: both paths emit the
        same stream while neither is in its wrap-up window (wrap TIMING
        legitimately differs — the FSM's jump-aware slack engages earlier
        than the host's fixed 4 chars on jump-heavy schemas), and the FSM
        path never awaits a host round trip."""
        cfg, params = model
        rng = random.Random(42)
        for case in range(3):
            tools = random_tools(rng)
            names = [t["function"]["name"] for t in tools]
            force = rng.choice(names) if rng.random() < 0.5 else None
            g = compile_tool_call_grammar(tok, tools, force_name=force,
                                          vocab_size=262)
            assert g is not None, tools
            host, _ = run_constrained(cfg, params, tok, None, tools=tools,
                                      force_name=force, seed=case)
            fsm, _ = run_constrained(cfg, params, tok, g, tools=tools,
                                     force_name=force, seed=case)
            # positions with budget_left > dist + wrap_slack are outside
            # BOTH wrap windows (the FSM's slack >= the host's 4): there
            # the masks are provably equal, so the streams must match
            state, wrap_free = 0, 0
            for i, t in enumerate(host.output_ids):
                if 120 - i <= int(g.dist[state]) + g.wrap_slack:
                    break
                wrap_free = i + 1
                state = g.walk([t], start=state)
                if state < 0:
                    break  # host sampled a stop token (not in the DFA)
            assert wrap_free >= 10, (case, wrap_free)  # non-vacuous
            assert (fsm.output_ids[:wrap_free]
                    == host.output_ids[:wrap_free]), (case, tools)
            assert fsm.constrained_roundtrips == 0
            text = tok.decode(
                [t for t in fsm.output_ids if t not in tok.stop_ids])
            assert validate_tool_call_json(text, tools), (text, tools)

    def test_mixed_batch_free_lane_unperturbed(self, model, tok, grammar):
        """A free lane co-scheduled with an FSM lane produces exactly its
        solo-run tokens (the all-True mask rows leave the sampler
        bit-identical), and the FSM lane matches its own solo run."""
        cfg, params = model
        eng = make_engine(cfg, params)
        solo_free = eng.generate(tok.encode("stream me a story"),
                                 max_new_tokens=48)
        solo_con, _ = run_constrained(cfg, params, tok, grammar)

        eng2 = make_engine(cfg, params)
        free = GenRequest(request_id="free",
                          prompt_ids=tok.encode("stream me a story"),
                          max_new_tokens=48)
        mask = ToolCallMaskFn(tok, TOOLS)
        con = GenRequest(request_id="con",
                         prompt_ids=tok.encode("call a tool"),
                         max_new_tokens=120,
                         stop_token_ids=tuple(tok.stop_ids),
                         logits_mask_fn=mask, grammar=grammar)
        eng2.submit(free)
        eng2.submit(con)
        eng2.run_to_completion()
        assert free.output_ids == solo_free.output_ids
        assert con.output_ids == solo_con.output_ids
        assert eng2.metrics.constrained_roundtrips == 0

    def test_preemption_churn_bit_identical(self, model, tok, grammar):
        """The FSM lane survives preemption (host replay reseeds the
        device state at re-prefill) and still reproduces its solo run."""
        cfg, params = model
        solo, _ = run_constrained(cfg, params, tok, grammar)
        # pool sized so the free lane (180-token prompt -> 12 pages at
        # prefill, growing toward 16) collides with the constrained lane
        # (~4 pages) while BOTH are mid-flight: 17 allocatable pages run
        # out and the youngest lane (con) gets preempted
        eng = make_engine(cfg, params, num_pages=18)
        free = GenRequest(request_id="free", prompt_ids=[5] * 180,
                          max_new_tokens=60)
        mask = ToolCallMaskFn(tok, TOOLS)
        con = GenRequest(request_id="con",
                         prompt_ids=tok.encode("call a tool"),
                         max_new_tokens=120,
                         stop_token_ids=tuple(tok.stop_ids),
                         logits_mask_fn=mask, grammar=grammar)
        eng.submit(free)
        eng.submit(con)  # youngest: the preemption victim
        eng.run_to_completion()
        assert eng.metrics.requests_preempted >= 1
        assert con.output_ids == solo.output_ids
        assert eng.metrics.constrained_roundtrips == 0

    def test_slot_reuse_after_cancel_resets_fsm_lane(self, model, tok,
                                                     grammar):
        """A free lane seated in a slot a cancelled FSM lane used must not
        inherit its automaton state."""
        cfg, params = model
        eng = make_engine(cfg, params, max_batch=1)
        mask = ToolCallMaskFn(tok, TOOLS)
        con = GenRequest(request_id="con",
                         prompt_ids=tok.encode("call a tool"),
                         max_new_tokens=120,
                         stop_token_ids=tuple(tok.stop_ids),
                         logits_mask_fn=mask, grammar=grammar)
        eng.submit(con)
        for _ in range(6):
            eng.step()
        eng.cancel("con")
        solo = make_engine(cfg, params, max_batch=1).generate(
            tok.encode("plain text"), max_new_tokens=24)
        free = GenRequest(request_id="free",
                          prompt_ids=tok.encode("plain text"),
                          max_new_tokens=24)
        eng.submit(free)
        eng.run_to_completion()
        assert free.output_ids == solo.output_ids


class TestWrapUp:
    @pytest.mark.parametrize("budget,seed", [(48, 11), (64, 12), (56, 13)])
    def test_tight_budget_still_parses(self, model, tok, grammar, budget,
                                       seed):
        """Device-side wrap-up (distance-decreasing transitions near the
        budget) closes the JSON before tokens run out, like the host
        path's wrap-up mode."""
        cfg, params = model
        req, _ = run_constrained(cfg, params, tok, grammar, prompt="go",
                                 max_new=budget, temperature=2.0, seed=seed)
        text = tok.decode(
            [t for t in req.output_ids if t not in tok.stop_ids])
        assert validate_tool_call_json(text, TOOLS), text

    def test_jump_aware_slack_closes_repetitive_greedy(self, model, tok):
        """A single-tool schema where greedy repeats `, "city": false`
        forever: each comma JUMPS the shortest-close distance by the whole
        forced key run, which strands a fixed-4 slack window (the host
        path demonstrably emits unparseable JSON here).  The compiled
        grammar's jump-aware wrap_slack must still close in budget."""
        cfg, params = model
        tools = [{"type": "function", "function": {
            "name": "get_weather",
            "parameters": {"type": "object",
                           "properties": {"city": {"type": "string"}}},
        }}]
        g = compile_tool_call_grammar(tok, tools, vocab_size=262)
        assert g is not None and g.wrap_slack > 4
        req, _ = run_constrained(cfg, params, tok, g, tools=tools,
                                 max_new=120)
        text = tok.decode(
            [t for t in req.output_ids if t not in tok.stop_ids])
        assert validate_tool_call_json(text, tools), text
        assert req.finish_reason == "stop"


class ForcedSpeculator:
    """Scripted proposal fn (deterministic engagement)."""

    def __init__(self, fn):
        self._fn = fn
        self.hist = []
        self.accept_ewma = 1.0
        self.observed = []

    def push(self, token):
        self.hist.append(token)

    def propose(self, k_max):
        return list(self._fn(self.hist, k_max))[:max(0, k_max)]

    def observe(self, accepted, proposed):
        self.observed.append((accepted, proposed))


class TestSpeculationOnConstrained:
    """Constrained lanes speculate (ISSUE 7 lifts the PR 5 exclusion):
    FSM rollback mirrors KV rollback, greedy output bit-identical to
    speculation off."""

    def _spec_engine(self, cfg, params, k=4):
        return make_engine(cfg, params, max_batch=2, page_size=8,
                           num_pages=64, max_pages_per_seq=8,
                           prefill_buckets=(8, 16, 32, 64),
                           speculative_k=k)

    def test_grammar_lane_gets_speculator(self, model, tok, grammar):
        cfg, params = model
        eng = self._spec_engine(cfg, params)
        mask = ToolCallMaskFn(tok, TOOLS)
        fsm_req = GenRequest(request_id="g", prompt_ids=tok.encode("x"),
                             stop_token_ids=tuple(tok.stop_ids),
                             logits_mask_fn=mask, grammar=grammar)
        host_req = GenRequest(request_id="h", prompt_ids=tok.encode("x"),
                              stop_token_ids=tuple(tok.stop_ids),
                              logits_mask_fn=ToolCallMaskFn(tok, TOOLS))
        eng.submit(fsm_req)
        eng.submit(host_req)
        assert fsm_req.spec is not None   # device-FSM lanes speculate
        assert host_req.spec is None      # host-masked lanes still don't
        eng.run_to_completion()

    def test_greedy_bit_identical_spec_on_off(self, model, tok, grammar):
        cfg, params = model
        base_req = None
        outs = {}
        for k in (0, 4):
            eng = self._spec_engine(cfg, params, k=k)
            mask = ToolCallMaskFn(tok, TOOLS)
            req = GenRequest(request_id=f"s{k}",
                             prompt_ids=tok.encode("call a tool"),
                             max_new_tokens=120,
                             stop_token_ids=tuple(tok.stop_ids),
                             logits_mask_fn=mask, grammar=grammar)
            eng.submit(req)
            eng.run_to_completion()
            outs[k] = list(req.output_ids)
            base_req = req
        assert outs[0] == outs[4]
        text = tok.decode(
            [t for t in base_req.output_ids if t not in tok.stop_ids])
        assert validate_tool_call_json(text, TOOLS), text

    def test_fsm_rollback_matches_kv_rollback(self, model, tok, grammar):
        """Corrupt-tail proposals force partial acceptance every round;
        the continuation must still be the non-speculative stream —
        possible only if the FSM state rolled back exactly with seq_len
        (a stale FSM state would shift every later mask)."""
        cfg, params = model
        base, _ = run_constrained(cfg, params, tok, grammar)
        eng = self._spec_engine(cfg, params, k=4)
        mask = ToolCallMaskFn(tok, TOOLS)
        req = GenRequest(request_id="cr",
                         prompt_ids=tok.encode("call a tool"),
                         max_new_tokens=120,
                         stop_token_ids=tuple(tok.stop_ids),
                         logits_mask_fn=mask, grammar=grammar)
        eng.submit(req)
        plen = len(req.prompt_ids)

        def cands(hist, k):
            n = len(hist) - plen
            out = list(base.output_ids[n:n + k])
            if len(out) >= 2:
                out[-1] = (out[-1] + 1) % 260  # corrupt the tail
            return out

        req.spec = ForcedSpeculator(cands)
        eng.run_to_completion()
        assert req.output_ids == base.output_ids
        snap = eng.metrics.speculation_snapshot()
        assert snap["speculation_accepted_tokens"] > 0
        assert snap["speculation_rejected_tokens"] > 0  # rollback happened

    def test_sampled_stream_matches_sequential(self, model, tok, grammar):
        """Temperature sampling through the fsm verify path still equals
        the sequential path (per-(seed, position) keys + exact-match
        acceptance compose with the per-position FSM masks)."""
        cfg, params = model
        base, _ = run_constrained(cfg, params, tok, grammar,
                                  temperature=1.2, seed=9)
        eng = self._spec_engine(cfg, params, k=3)
        mask = ToolCallMaskFn(tok, TOOLS)
        req = GenRequest(request_id="ts",
                         prompt_ids=tok.encode("call a tool"),
                         max_new_tokens=120, temperature=1.2, seed=9,
                         stop_token_ids=tuple(tok.stop_ids),
                         logits_mask_fn=mask, grammar=grammar)
        eng.submit(req)
        plen = len(req.prompt_ids)
        req.spec = ForcedSpeculator(
            lambda hist, k: list(base.output_ids[len(hist) - plen:
                                                 len(hist) - plen + k]))
        eng.run_to_completion()
        assert req.output_ids == base.output_ids


class TestOvertightCounter:
    def test_overtight_mask_counted_and_logged_once(self, model, caplog):
        """A mask fn returning an empty allow-list degrades the row to
        unconstrained (pre-existing sampler semantics) — now counted in
        constrained_mask_overtight and logged once per request."""
        cfg, params = model
        eng = make_engine(cfg, params)

        def tight(out):
            return [] if 1 <= len(out) <= 3 else None

        req = GenRequest(request_id="ot", prompt_ids=[3] * 4,
                         max_new_tokens=8, logits_mask_fn=tight)
        with caplog.at_level(logging.WARNING, logger="kafka_tpu.engine"):
            eng.submit(req)
            eng.run_to_completion()
        assert req.finish_reason == "length"
        assert len(req.output_ids) == 8  # generation continued
        assert eng.metrics.constrained_mask_overtight >= 2
        hits = [r for r in caplog.records
                if "over-tight constrained mask" in r.getMessage()]
        assert len(hits) == 1  # once per request
        snap = eng.metrics.snapshot()
        assert snap["constrained"]["constrained_mask_overtight"] >= 2


class TestConstrainedMetricRegistry:
    """CONSTRAINED_METRIC_KEYS must appear in BOTH runtime/metrics.py and
    server/prometheus.py, and neither file may invent constrained_*
    metrics outside the registry (the SITES/SPANS pattern)."""

    def _source(self, relpath):
        import os

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(root, relpath)) as f:
            return f.read()

    def test_registry_both_directions(self):
        from kafka_tpu.runtime.metrics import CONSTRAINED_METRIC_KEYS

        metrics_src = self._source("kafka_tpu/runtime/metrics.py")
        prom_src = self._source("kafka_tpu/server/prometheus.py")
        for key in CONSTRAINED_METRIC_KEYS:
            assert f'"{key}"' in metrics_src, (
                f"{key} missing from runtime/metrics.py"
            )
            assert f'"{key}"' in prom_src, (
                f"{key} missing from server/prometheus.py"
            )
        wired = set()
        for src in (metrics_src, prom_src):
            wired |= set(re.findall(r'"(constrained_[a-z_]+)"', src))
        undocumented = wired - set(CONSTRAINED_METRIC_KEYS)
        assert not undocumented, (
            f"constrained metrics outside the registry: {undocumented}"
        )

    def test_snapshot_carries_registry_keys(self):
        from kafka_tpu.runtime.metrics import (
            CONSTRAINED_METRIC_KEYS,
            EngineMetrics,
        )

        snap = EngineMetrics().snapshot()
        for key in CONSTRAINED_METRIC_KEYS:
            assert key in snap["constrained"]

    def test_prometheus_renders_constrained_families(self):
        from kafka_tpu.runtime.metrics import EngineMetrics
        from kafka_tpu.server.prometheus import render_prometheus

        m = EngineMetrics()
        m.constrained_roundtrips = 3
        m.constrained_mask_overtight = 1
        m.constrained_ondevice_tokens = 42
        text = render_prometheus(m.snapshot())
        assert "kafka_tpu_constrained_roundtrips_total 3" in text
        assert "kafka_tpu_constrained_overtight_total 1" in text
        assert "kafka_tpu_constrained_ondevice_tokens_total 42" in text


class TestBenchConstrainedSmoke:
    def test_bench_constrained_cpu_smoke(self, model):
        """bench.py constrained, tier-1 shape: on-device mode must report
        ~0 constrained round trips per call with bit-identical outputs —
        the ISSUE 7 acceptance criterion, runnable on any backend."""
        import sys
        from pathlib import Path

        sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
        from bench import constrained_phase

        cfg, params = model
        out = constrained_phase(cfg, params, n_lanes=3, gen_len=40,
                                page_size=8)
        assert out["outputs_match"], "FSM path changed token streams"
        assert out["roundtrips_per_call"]["ondevice"] == 0
        assert out["roundtrips_per_call"]["host"] > 0
        assert out["ondevice_tokens"] > 0
