"""Fault-contained object store (ISSUE 17).

The load-bearing claims:
  * the StoreGuard breaker state machine: trip at the consecutive-failure
    threshold, fast-fail while open, exactly one half-open probe after
    the window, close on probe success / reopen on probe failure —
    deadline timeouts and op errors accounted separately,
  * retry with bounded backoff recovers from transient faults (every
    protocol op is idempotent) and the counters say how often,
  * a failpoint storm at the tier level opens the breaker like a real
    outage: serving continues as re-prefill at baseline latency, the
    router/manifest probes are negatively cached (zero store RTT), and
    the wake path resumes after the half-open close — proven end-to-end
    by bench.py's ``store_outage`` phase (CPU smoke),
  * HTTPObjectStore speaks the S3 shape: byte round-trip and
    dedupe/refcount behavior identical to LocalFS through the stub
    server, torn bodies discarded + counted, 5xx absorbed by the guard's
    retry, ``If-None-Match`` conditional ref markers,
  * fsck repairs all three crash-window orphan classes in ``--repair``,
    touches nothing inside the grace window, and every surviving thread
    still wakes token-exact; ``scripts/objstore_fsck.py --dry-run``
    smoke-tested as a subprocess,
  * the new ``kv.object_head`` / ``kv.object_list`` failpoints keep
    engine invariants under error/delay chaos,
  * degradation seams: sleep_to_object returns honest partial results on
    a dead store; the autoscaler skips the pre-scale-in drain when the
    breaker is open.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafka_tpu.models import ModelConfig, init_params
from kafka_tpu.runtime import (
    EngineConfig,
    GenRequest,
    InferenceEngine,
    PagePool,
)
from kafka_tpu.runtime import failpoints as fp
from kafka_tpu.runtime.kv_tier import KVTierManager, LocalPageShipper
from kafka_tpu.runtime.object_tier import (
    _HEAD_TTL_S,
    HTTPObjectStore,
    LocalFSObjectStore,
    ObjectTier,
    build_object_store,
    fsck,
)
from kafka_tpu.runtime.prefix_cache import PrefixCache
from kafka_tpu.runtime.store_guard import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    CircuitBreaker,
    StoreGuard,
    StoreOpError,
    StoreTimeoutError,
    StoreUnavailableError,
)

from objstore_stub import StubS3Server


# ---------------------------------------------------------------------------
# breaker state machine
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestBreakerMatrix:
    def test_trips_at_threshold_not_before(self):
        clk = _Clock()
        br = CircuitBreaker(failure_threshold=3, open_window_s=10.0,
                            clock=clk)
        br.record_failure()
        br.record_failure()
        assert br.state == BREAKER_CLOSED and br.allow()
        br.record_failure()
        assert br.state == BREAKER_OPEN and br.opens == 1
        assert not br.allow()

    def test_success_resets_consecutive_count(self):
        br = CircuitBreaker(failure_threshold=2, clock=_Clock())
        br.record_failure()
        br.record_success()
        br.record_failure()
        assert br.state == BREAKER_CLOSED  # never two CONSECUTIVE

    def test_open_window_then_single_half_open_probe(self):
        clk = _Clock()
        br = CircuitBreaker(failure_threshold=1, open_window_s=5.0,
                            clock=clk)
        br.record_failure()
        assert br.state == BREAKER_OPEN
        clk.t = 4.9
        assert not br.allow()
        clk.t = 5.1
        assert br.allow()  # THE probe
        assert br.state == BREAKER_HALF_OPEN
        assert not br.allow()  # everyone else keeps fast-failing
        assert not br.allow()

    def test_probe_success_closes(self):
        clk = _Clock()
        br = CircuitBreaker(failure_threshold=1, open_window_s=1.0,
                            clock=clk)
        br.record_failure()
        clk.t = 2.0
        assert br.allow()
        br.record_success()
        assert br.state == BREAKER_CLOSED and br.allow()
        assert br.opens == 1

    def test_probe_failure_reopens_full_window(self):
        clk = _Clock()
        br = CircuitBreaker(failure_threshold=3, open_window_s=1.0,
                            clock=clk)
        for _ in range(3):
            br.record_failure()
        clk.t = 1.5
        assert br.allow()
        br.record_failure()  # probe failed: straight back to OPEN
        assert br.state == BREAKER_OPEN and br.opens == 2
        clk.t = 2.0  # only 0.5s since reopen
        assert not br.allow()
        clk.t = 2.6
        assert br.allow()

    def test_state_gauge_encoding(self):
        clk = _Clock()
        br = CircuitBreaker(failure_threshold=1, open_window_s=1.0,
                            clock=clk)
        assert br.state_gauge() == 0
        br.record_failure()
        assert br.state_gauge() == 2
        clk.t = 1.5
        br.allow()
        assert br.state_gauge() == 1


# ---------------------------------------------------------------------------
# guard: retry / deadline / accounting
# ---------------------------------------------------------------------------


class _FlakyStore:
    """Programmable backend: fail the next N ops, optionally hang."""

    def __init__(self):
        self.fail_next = 0
        self.hang_s = 0.0
        self.calls = 0
        self.data = {}

    def _op(self):
        self.calls += 1
        if self.hang_s:
            time.sleep(self.hang_s)
        if self.fail_next > 0:
            self.fail_next -= 1
            raise OSError("injected store fault")

    def put(self, key, data):
        self._op()
        self.data[key] = bytes(data)

    def get(self, key):
        self._op()
        return self.data.get(key)

    def head(self, key):
        self._op()
        return (len(self.data[key]), 0.0) if key in self.data else None

    def delete(self, key):
        self._op()
        self.data.pop(key, None)

    def list(self, prefix):
        self._op()
        return [k for k in self.data if k.startswith(prefix)]

    def usage(self):
        self._op()
        return len(self.data), sum(len(v) for v in self.data.values())

    def put_if_absent(self, key, data):
        self._op()
        if key in self.data:
            return False
        self.data[key] = bytes(data)
        return True


class TestGuardRetryDeadline:
    def test_transient_fault_absorbed_by_retry(self):
        st = _FlakyStore()
        g = StoreGuard(st, retries=2, backoff_s=0.0)
        st.fail_next = 2
        g.put("k", b"v")  # two failures, third attempt lands
        assert st.data["k"] == b"v"
        assert g.retries_total == 2
        assert g.breaker.state == BREAKER_CLOSED
        assert g.op_stats["put"][1] == 0  # no FINAL error recorded

    def test_exhausted_retries_raise_and_record(self):
        st = _FlakyStore()
        g = StoreGuard(st, retries=1, backoff_s=0.0,
                       breaker=CircuitBreaker(failure_threshold=10))
        st.fail_next = 5
        with pytest.raises(StoreOpError):
            g.get("k")
        assert g.retries_total == 1
        assert g.breaker.consecutive_failures == 1
        assert g.op_stats["get"][1] == 1

    def test_deadline_timeout_counted_separately(self):
        st = _FlakyStore()
        st.hang_s = 0.3
        g = StoreGuard(st, timeout_s=0.05, retries=0)
        with pytest.raises(StoreTimeoutError):
            g.head("k")
        assert g.timeouts_total == 1
        assert g.breaker.consecutive_failures == 1

    def test_open_breaker_fast_fails_without_store_call(self):
        st = _FlakyStore()
        g = StoreGuard(st, retries=0,
                       breaker=CircuitBreaker(failure_threshold=1,
                                              open_window_s=60.0))
        st.fail_next = 1
        with pytest.raises(StoreOpError):
            g.put("k", b"v")
        calls = st.calls
        with pytest.raises(StoreUnavailableError):
            g.get("k")
        with pytest.raises(StoreUnavailableError):
            g.usage()
        assert st.calls == calls  # the backend was never touched

    def test_half_open_probe_closes_through_guard(self):
        st = _FlakyStore()
        g = StoreGuard(st, retries=0,
                       breaker=CircuitBreaker(failure_threshold=1,
                                              open_window_s=0.05))
        st.fail_next = 1
        with pytest.raises(StoreOpError):
            g.put("k", b"v")
        assert g.breaker.state == BREAKER_OPEN
        time.sleep(0.06)
        g.put("k", b"v")  # the probe
        assert g.breaker.state == BREAKER_CLOSED
        assert g.snapshot()["breaker_opens"] == 1

    def test_stuck_workers_replaced_pool_recovers(self):
        # Four abandoned (hung-forever) backend calls used to pin every
        # deadline worker permanently: later ops — including the
        # breaker's half-open probe — queued behind them and timed out
        # without ever reaching the backend, so the breaker could never
        # close even after the store recovered.
        release = threading.Event()
        st = _FlakyStore()
        st.data["k"] = b"v"
        real_get = st.get
        hang_next = [4]

        def hung_get(key):
            if hang_next[0] > 0:
                hang_next[0] -= 1
                release.wait()
            return real_get(key)

        st.get = hung_get
        g = StoreGuard(st, timeout_s=0.05, retries=0,
                       breaker=CircuitBreaker(failure_threshold=100))
        try:
            for _ in range(4):
                with pytest.raises(StoreTimeoutError):
                    g.get("k")
            assert g.snapshot()["stuck_ops"] == 4
            # every worker is pinned: the next op must still reach the
            # (now healthy) backend instead of queueing behind them
            assert g.get("k") == b"v"
            assert g.pool_replacements == 1
            assert g.snapshot()["stuck_ops"] == 0
        finally:
            release.set()  # unstick the abandoned threads for clean exit

    def test_from_env_reads_knobs(self):
        env = {
            "KAFKA_TPU_KV_OBJECT_TIMEOUT_S": "1.5",
            "KAFKA_TPU_KV_OBJECT_RETRIES": "4",
            "KAFKA_TPU_KV_OBJECT_BACKOFF_S": "0.2",
            "KAFKA_TPU_KV_OBJECT_BREAKER_FAILURES": "7",
            "KAFKA_TPU_KV_OBJECT_BREAKER_OPEN_S": "30",
        }
        g = StoreGuard.from_env(_FlakyStore(), env=env)
        assert g.timeout_s == 1.5 and g.retries == 4
        assert g.backoff_s == 0.2
        assert g.breaker.failure_threshold == 7
        assert g.breaker.open_window_s == 30.0

    def test_build_object_store_wraps_and_picks_backend(self, tmp_path):
        g = build_object_store(str(tmp_path))
        assert isinstance(g, StoreGuard)
        assert isinstance(g.inner, LocalFSObjectStore)
        g2 = build_object_store("http://127.0.0.1:1/bucket")
        assert isinstance(g2.inner, HTTPObjectStore)


# ---------------------------------------------------------------------------
# tier-level containment (failpoints fire BEFORE the guard)
# ---------------------------------------------------------------------------


def _leaves(seed=7):
    rng = np.random.default_rng(seed)
    return ([rng.normal(size=(2, 8, 4)).astype(np.float32)],
            [rng.normal(size=(2, 8, 4)).astype(np.float32)])


def _guarded_tier(tmp_path, threshold=2, window=0.3):
    guard = StoreGuard(
        LocalFSObjectStore(str(tmp_path)), retries=0, backoff_s=0.0,
        breaker=CircuitBreaker(failure_threshold=threshold,
                               open_window_s=window),
    )
    return ObjectTier(guard, fingerprint="f", page_size=4), guard


class TestTierBreakerIntegration:
    def test_failpoint_storm_opens_breaker_then_recovers(self, tmp_path):
        obj, guard = _guarded_tier(tmp_path, threshold=2, window=0.2)
        k, v = _leaves()
        with fp.armed("kv.object_put", "error"):
            assert obj.put_run([1] * 8, k, v, 2) is None
            assert obj.put_run([2] * 8, k, v, 2) is None
        assert guard.breaker.state == BREAKER_OPEN
        assert not obj.available()
        # storm over, breaker still open: ops fast-fail (no store touch)
        assert obj.put_run([3] * 8, k, v, 2) is None
        assert obj.object_put_failures == 3
        # window elapses: the next op is the half-open probe and closes
        time.sleep(0.25)
        key = obj.put_run([4] * 8, k, v, 2)
        assert key is not None and obj.has_run(key)
        assert guard.breaker.state == BREAKER_CLOSED
        assert obj.available()
        snap = obj.snapshot()
        assert snap["store_breaker_opens"] == 1
        assert snap["store_breaker_state"] == 0

    def test_probe_failure_neg_cached_as_counted_miss(self, tmp_path):
        # the breaker stays CLOSED here (threshold 5, one failure), so
        # the failure TTL is the ordinary 0.5s head TTL — the sleep
        # below must outlast it
        obj, guard = _guarded_tier(tmp_path, threshold=5, window=0.6)
        toks = list(range(8))
        assert obj.write_manifest("t", toks, obj.manifest_runs([toks]))
        obj._manifest_cache.clear()
        with fp.armed("kv.object_head", "error", count=1):
            assert obj.read_manifest("t") is None  # the failed probe
        assert obj.probe_neg_cached == 1
        # store is healthy again, but inside the open window the
        # NEGATIVE cache answers — this read must not reach the store
        # (a successful probe would return the manifest)
        assert obj.read_manifest("t") is None
        assert obj.probe_neg_cached == 2
        # window over: the probe re-runs and the manifest is back
        time.sleep(0.65)
        man = obj.read_manifest("t")
        assert man is not None and man["tokens"] == toks

    def test_probe_failure_ttl_tracks_breaker_state(self, tmp_path):
        # the open window applies only while the breaker is actually
        # OPEN; an isolated blip with a closed breaker gets the ordinary
        # head TTL (and a recovery mid-window shrinks the TTL back)
        obj, guard = _guarded_tier(tmp_path, threshold=1, window=60.0)
        assert obj._probe_failure_ttl() == _HEAD_TTL_S
        guard.breaker.record_failure()  # trips OPEN at threshold 1
        assert obj._probe_failure_ttl() == 60.0
        guard.breaker.state = BREAKER_CLOSED  # store recovered
        assert obj._probe_failure_ttl() == _HEAD_TTL_S

    def test_closed_breaker_blip_expires_at_head_ttl(self, tmp_path):
        # a single transient head failure with a CLOSED breaker must not
        # hide the thread's warm state for the breaker's whole open
        # window (60s here) — only for the ordinary head TTL
        obj, guard = _guarded_tier(tmp_path, threshold=5, window=60.0)
        toks = list(range(8))
        assert obj.write_manifest("t", toks, obj.manifest_runs([toks]))
        obj._manifest_cache.clear()
        with fp.armed("kv.object_head", "error", count=1):
            assert obj.read_manifest("t") is None
        assert guard.breaker.state == BREAKER_CLOSED
        time.sleep(_HEAD_TTL_S + 0.1)
        man = obj.read_manifest("t")
        assert man is not None and man["tokens"] == toks

    def test_unguarded_tier_head_failure_still_contained(self, tmp_path):
        obj = ObjectTier(LocalFSObjectStore(str(tmp_path)),
                         fingerprint="f", page_size=4)
        k, v = _leaves()
        key = obj.put_run(list(range(8)), k, v, 2)
        with fp.armed("kv.object_head", "error"):
            assert obj.has_run(key) is False  # fails absent-shaped
        assert obj.has_run(key) is True

    def test_release_survives_dead_store(self, tmp_path):
        obj, guard = _guarded_tier(tmp_path, threshold=1, window=60.0)
        k, v = _leaves()
        key = obj.put_run(list(range(8)), k, v, 2)
        assert key is not None
        guard.breaker.record_failure()  # force the breaker open
        assert not obj.available()
        obj.release(key)  # must not raise on the engine path
        assert obj.objects_released == 1
        # local reference is gone; the store-side marker survives as a
        # crash-window orphan for fsck
        assert key not in obj._owned

    def test_sleep_to_object_partial_results_on_dead_store(self, tmp_path):
        ps = 4
        num_pages = 16

        class _Owner:
            def __init__(self):
                rng = np.random.default_rng(0)
                shape = (2, num_pages * ps, 8)
                self.k_pool = jnp.asarray(
                    rng.normal(size=shape).astype(np.float32))
                self.v_pool = jnp.asarray(
                    rng.normal(size=shape).astype(np.float32))

        owner = _Owner()
        pool = PagePool(num_pages=num_pages, page_size=ps)
        mgr = KVTierManager(LocalPageShipper(owner, ps),
                            host_budget_bytes=1 << 30, page_size=ps)
        guard = StoreGuard(
            LocalFSObjectStore(str(tmp_path)), retries=0, backoff_s=0.0,
            breaker=CircuitBreaker(failure_threshold=1,
                                   open_window_s=60.0),
        )
        mgr.attach_object(ObjectTier(guard, fingerprint="f",
                                     page_size=ps))
        cache = PrefixCache(pool, tier=mgr)
        tokens = list(range(8))
        pages = pool.alloc(2)
        cache.store("t1", tokens, pages)
        pool.release(pages)
        guard.breaker.record_failure()  # the store dies
        stats = cache.sleep_to_object()
        assert stats["enabled"] is True
        assert stats["runs_archived"] == 0
        assert stats["runs_failed"] >= 1
        assert stats["runs_skipped_store_down"] >= 1
        assert stats["manifests"] == 0
        assert stats["manifests_failed"] >= 1
        assert stats["breaker_state"] == "open"

    def test_autoscaler_skips_drain_on_open_breaker(self, tmp_path):
        from kafka_tpu.runtime.autoscaler import AutoscalerController

        obj, guard = _guarded_tier(tmp_path, threshold=1, window=60.0)

        class _Tier:
            object = obj

        class _Eng:
            kv_tier = _Tier()

        class _Ladder:
            def _engines(self):
                return [_Eng()]

        class _Shim:
            ladder = _Ladder()

        shim = _Shim()
        assert AutoscalerController._object_store_available(shim)
        guard.breaker.record_failure()
        assert not AutoscalerController._object_store_available(shim)


# ---------------------------------------------------------------------------
# HTTPObjectStore vs LocalFS differential (stub server, no network)
# ---------------------------------------------------------------------------


class TestHTTPDifferential:
    def test_round_trip_and_listing_parity(self, tmp_path):
        with StubS3Server() as srv:
            http_store = HTTPObjectStore(srv.url)
            fs_store = LocalFSObjectStore(str(tmp_path))
            payload = os.urandom(4096)
            for st in (http_store, fs_store):
                assert st.get("objects/x.npz") is None
                assert st.head("objects/x.npz") is None
                st.put("objects/x.npz", payload)
                assert st.get("objects/x.npz") == payload
                size, mtime = st.head("objects/x.npz")
                assert size == len(payload) and mtime > 0
                st.put("refs/x/a", b"")
                st.put("refs/x/b", b"")
                assert len(st.list("refs/x/")) == 2
                assert st.usage() == (1, len(payload))
                st.delete("refs/x/b")
                assert len(st.list("refs/x/")) == 1
                st.delete("objects/x.npz")
                assert st.get("objects/x.npz") is None
                st.delete("objects/x.npz")  # idempotent

    def test_tier_dedupe_refcount_identical_through_http(self, tmp_path):
        k, v = _leaves()
        toks = list(range(8))
        with StubS3Server() as srv:
            results = {}
            for name, mk in (
                ("http", lambda: HTTPObjectStore(srv.url)),
                ("fs", lambda: LocalFSObjectStore(str(tmp_path))),
            ):
                a = ObjectTier(mk(), fingerprint="f", page_size=4)
                b = ObjectTier(mk(), fingerprint="f", page_size=4)
                key = a.put_run(toks, k, v, 2)
                assert key is not None
                assert b.put_run(toks, k, v, 2) == key
                got = b.get_run(key)
                assert got is not None
                assert np.array_equal(got[0][0], k[0])
                st = a.store
                refs_before = len(st.list(f"refs/{key}/"))
                a.release(key)
                alive_after_one = st.head(f"objects/{key}.npz")
                b.release(key)
                alive_after_two = st.head(f"objects/{key}.npz")
                results[name] = (key, b.dedupe_hits, refs_before,
                                 alive_after_one is not None,
                                 alive_after_two is not None)
            assert results["http"] == results["fs"]
            assert results["http"][1] == 1  # dedupe fired
            assert results["http"][2] == 2  # two owners' markers
            assert results["http"][3] is True  # survives first release
            assert results["http"][4] is False  # last ref deletes

    def test_torn_body_discarded_counted_and_retried(self):
        with StubS3Server() as srv:
            st = HTTPObjectStore(srv.url)
            st.put("objects/t.npz", b"x" * 1024)
            srv.torn_next = 1
            g = StoreGuard(st, retries=1, backoff_s=0.0)
            # first attempt is torn (discarded + counted); the guard's
            # retry fetches the intact body
            assert g.get("objects/t.npz") == b"x" * 1024
            assert st.torn_bodies == 1
            assert g.retries_total == 1

    def test_5xx_absorbed_by_guard_retry(self):
        with StubS3Server() as srv:
            st = HTTPObjectStore(srv.url)
            g = StoreGuard(st, retries=2, backoff_s=0.0)
            srv.fail_requests = 2
            g.put("objects/f.npz", b"data")
            assert g.get("objects/f.npz") == b"data"
            assert g.retries_total == 2

    def test_conditional_ref_marker_put(self):
        with StubS3Server() as srv:
            st = HTTPObjectStore(srv.url)
            assert st.put_if_absent("refs/k/u1", b"") is True
            assert st.put_if_absent("refs/k/u1", b"") is False  # 412
            assert st.put_if_absent("refs/k/u2", b"") is True
            assert sorted(st.list("refs/k/")) == ["refs/k/u1",
                                                  "refs/k/u2"]

    def test_truncated_listing_followed_to_completion(self):
        # real S3 truncates ListObjectsV2 at 1000 keys; the client must
        # follow the continuation chain, not act on the first page
        with StubS3Server() as srv:
            srv.max_keys = 2
            st = HTTPObjectStore(srv.url)
            keys = [f"objects/{i:02d}.npz" for i in range(5)]
            for i, k in enumerate(keys):
                st.put(k, b"x" * (i + 1))
            assert sorted(st.list("objects/")) == keys
            count, nbytes = st.usage()
            assert count == 5 and nbytes == 1 + 2 + 3 + 4 + 5

    def test_fsck_sees_whole_store_through_paginated_listing(self):
        # the disaster a partial listing invites: live objects whose ref
        # markers fall outside the first page look like orphans and
        # --repair would delete shared-store state that is in use
        with StubS3Server() as srv:
            srv.max_keys = 2
            st = HTTPObjectStore(srv.url)
            for i in range(4):
                st.put(f"objects/live{i}.npz", b"x")
                st.put(f"refs/live{i}/u1", b"")
            old = time.time() - 7200
            for key in list(srv.objects):
                srv.set_mtime(key, old)
            report = fsck(st, grace_s=3600.0, repair=True)
            assert report["objects"] == 4 and report["refs"] == 4
            assert report["repaired"] == 0
            assert not report["refless_objects"]
            assert not report["dangling_refs"]
            for i in range(4):
                assert st.head(f"objects/live{i}.npz") is not None

    def test_fsck_walks_s3_shaped_flat_listing(self):
        with StubS3Server() as srv:
            st = HTTPObjectStore(srv.url)
            st.put("objects/live.npz", b"x")
            st.put("refs/live/u1", b"")
            st.put("refs/gone/u1", b"")  # dangling (no objects/gone.npz)
            st.put("objects/orphan.npz", b"y")  # ref-less
            old = time.time() - 7200
            for key in ("objects/live.npz", "refs/live/u1",
                        "refs/gone/u1", "objects/orphan.npz"):
                srv.set_mtime(key, old)
            report = fsck(st, grace_s=3600.0, repair=True)
            assert report["dangling_refs"] == ["refs/gone/u1"]
            assert report["refless_objects"] == ["objects/orphan.npz"]
            assert report["repaired"] == 2
            assert st.head("objects/live.npz") is not None
            assert st.head("objects/orphan.npz") is None
            assert st.head("refs/gone/u1") is None


# ---------------------------------------------------------------------------
# fsck: three orphan classes, grace window, wake-after-scrub
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(name="guard-test", vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(7))
    return cfg, params


def make_engine(cfg, params, obj_dir=None, **kw):
    defaults = dict(max_batch=2, page_size=8, num_pages=24,
                    max_pages_per_seq=16,
                    prefill_buckets=(8, 16, 32, 64, 128),
                    kv_host_tier_mb=64,
                    kv_object_dir=str(obj_dir) if obj_dir else None)
    defaults.update(kw)
    return InferenceEngine(cfg, params, EngineConfig(**defaults),
                           kv_dtype=jnp.float32)


def _age(path, seconds=7200):
    old = time.time() - seconds
    os.utime(path, (old, old))


def _seed_orphans(store_dir, aged=True):
    """Plant one orphan of each crash-window class; returns their paths."""
    obj = os.path.join(store_dir, "objects", "deadbeef" * 8 + ".npz")
    os.makedirs(os.path.dirname(obj), exist_ok=True)
    with open(obj, "wb") as f:
        f.write(b"refless payload")
    ref_dir = os.path.join(store_dir, "refs", "cafebabe" * 8)
    os.makedirs(ref_dir, exist_ok=True)
    ref = os.path.join(ref_dir, "000000000001")
    open(ref, "wb").close()
    man = os.path.join(store_dir, "threads", "ghost.0a0a0a0a.json")
    os.makedirs(os.path.dirname(man), exist_ok=True)
    with open(man, "w") as f:
        json.dump({"version": 1, "thread": "ghost", "tokens": [1, 2],
                   "runs": [{"key": "feedface" * 8, "tokens": 2}]}, f)
    if aged:
        for p in (obj, ref, man):
            _age(p)
    return obj, ref, man


class TestFsck:
    def test_dry_run_reports_everything_touches_nothing(self, tmp_path):
        store = LocalFSObjectStore(str(tmp_path))
        obj, ref, man = _seed_orphans(str(tmp_path))
        report = fsck(store, grace_s=3600.0, repair=False)
        assert len(report["refless_objects"]) == 1
        assert len(report["dangling_refs"]) == 1
        assert len(report["dead_manifests"]) == 1
        assert report["repaired"] == 0
        for p in (obj, ref, man):
            assert os.path.exists(p)

    def test_repair_fixes_all_three_classes(self, tmp_path):
        store = LocalFSObjectStore(str(tmp_path))
        obj, ref, man = _seed_orphans(str(tmp_path))
        report = fsck(store, grace_s=3600.0, repair=True)
        assert report["repaired"] == 3
        for p in (obj, ref, man):
            assert not os.path.exists(p)
        # a second pass finds a clean store
        report2 = fsck(store, grace_s=3600.0, repair=True)
        assert report2["repaired"] == 0
        assert not report2["refless_objects"]
        assert not report2["dangling_refs"]
        assert not report2["dead_manifests"]

    def test_grace_window_protects_fresh_state(self, tmp_path):
        store = LocalFSObjectStore(str(tmp_path))
        obj, ref, man = _seed_orphans(str(tmp_path), aged=False)
        report = fsck(store, grace_s=3600.0, repair=True)
        assert report["repaired"] == 0
        assert report["in_grace"] >= 3
        for p in (obj, ref, man):
            assert os.path.exists(p)

    def test_corrupt_manifest_counts_as_dead(self, tmp_path):
        store = LocalFSObjectStore(str(tmp_path))
        man = os.path.join(str(tmp_path), "threads", "bad.ffffffff.json")
        os.makedirs(os.path.dirname(man), exist_ok=True)
        with open(man, "w") as f:
            f.write("{not json")
        _age(man)
        report = fsck(store, grace_s=3600.0, repair=True)
        assert report["dead_manifests"] == ["threads/bad.ffffffff.json"]
        assert not os.path.exists(man)

    def test_dry_run_predicts_repair_manifest_deletions(self, tmp_path):
        """Same aliveness predicate in both modes: a manifest whose only
        object is refless-but-in-grace (kept by the grace window)
        survives --repair exactly as dry-run reports, while one whose
        only object is refless-and-aged is reported dead by BOTH modes —
        dry-run must never understate what --repair will delete."""
        store = LocalFSObjectStore(str(tmp_path))

        def plant(run_key, aged_obj):
            okey = os.path.join(str(tmp_path), "objects", run_key + ".npz")
            os.makedirs(os.path.dirname(okey), exist_ok=True)
            with open(okey, "wb") as f:
                f.write(b"payload")
            if aged_obj:
                _age(okey)
            man = os.path.join(str(tmp_path), "threads",
                               f"{run_key[:5]}.json")
            os.makedirs(os.path.dirname(man), exist_ok=True)
            with open(man, "w") as f:
                json.dump({"version": 1, "thread": run_key[:5],
                           "tokens": [1],
                           "runs": [{"key": run_key, "tokens": 1}]}, f)
            _age(man)  # the manifest is old: only aliveness can save it
            return okey, man

        fresh_obj, fresh_man = plant("aa" * 32, aged_obj=False)
        aged_obj, aged_man = plant("bb" * 32, aged_obj=True)
        dry = fsck(store, grace_s=3600.0, repair=False)
        rep = fsck(store, grace_s=3600.0, repair=True)
        assert dry["refless_objects"] == rep["refless_objects"]
        assert dry["dead_manifests"] == rep["dead_manifests"]
        assert dry["dead_manifests"] == ["threads/bbbbb.json"]
        assert os.path.exists(fresh_obj) and os.path.exists(fresh_man)
        assert not os.path.exists(aged_obj)
        assert not os.path.exists(aged_man)

    def test_surviving_threads_wake_token_exact_after_repair(
        self, model, tmp_path
    ):
        """The acceptance walk: real drained threads + all three orphan
        classes in one store; fsck --repair removes only the orphans and
        every surviving thread still wakes with
        cache_source="object_tier", token-exact vs a storeless
        re-prefill of the same resume."""
        cfg, params = model
        obj_dir = tmp_path / "store"
        # fully disjoint prompts: each thread must wake from ITS OWN
        # manifest, not cross-hit the other's just-woken pages
        prompts = [[40 * i + j for j in range(1, 21)] for i in range(2)]
        eng_a = make_engine(cfg, params, obj_dir=obj_dir)
        firsts = []
        for i in range(2):
            r = GenRequest(request_id=f"A{i}", prompt_ids=prompts[i],
                           max_new_tokens=4, prefix_key=f"fsck-t{i}")
            eng_a.submit(r)
            eng_a.run_to_completion()
            firsts.append(list(r.output_ids))
        sleep_stats = eng_a.sleep_to_object()
        assert sleep_stats["runs_archived"] >= 1
        del eng_a

        _seed_orphans(str(obj_dir))
        report = fsck(LocalFSObjectStore(str(obj_dir)), grace_s=3600.0,
                      repair=True)
        assert report["repaired"] == 3

        def resume_all(eng, label):
            outs = []
            for i in range(2):
                rr = GenRequest(
                    request_id=f"{label}{i}",
                    prompt_ids=prompts[i] + firsts[i] + [99],
                    max_new_tokens=4, prefix_key=f"fsck-t{i}")
                eng.submit(rr)
                eng.run_to_completion()
                outs.append(rr)
            return outs

        eng_b = make_engine(cfg, params, obj_dir=obj_dir)
        woken = resume_all(eng_b, "B")
        assert [r.cache_source for r in woken] == ["object_tier"] * 2
        eng_c = make_engine(cfg, params)  # storeless reference
        ref = resume_all(eng_c, "C")
        for w, r in zip(woken, ref):
            assert list(w.output_ids) == list(r.output_ids)


class TestJanitor:
    def test_background_janitor_repairs_then_stops(self, tmp_path):
        obj = ObjectTier(LocalFSObjectStore(str(tmp_path)),
                         fingerprint="f", page_size=4)
        _seed_orphans(str(tmp_path))
        obj.start_janitor(0.05, grace_s=0.0)
        deadline = time.monotonic() + 5.0
        while obj.scrub_repairs < 3 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert obj.scrub_repairs == 3
        assert obj.snapshot()["store_scrub_repairs"] == 3
        obj.stop_janitor()
        assert obj._janitor is None

    def test_interval_zero_is_off(self, tmp_path):
        obj = ObjectTier(LocalFSObjectStore(str(tmp_path)),
                         fingerprint="f", page_size=4)
        obj.start_janitor(0.0)
        assert obj._janitor is None

    def test_malformed_scrub_env_tolerated(self, model, tmp_path,
                                           monkeypatch):
        # engine construction must fall back to defaults (janitor off)
        # on bad knobs, like the KAFKA_TPU_KV_OBJECT_* guard knobs do
        monkeypatch.setenv("KAFKA_TPU_KV_OBJECT_SCRUB_S", "not-a-number")
        monkeypatch.setenv("KAFKA_TPU_KV_OBJECT_SCRUB_GRACE_S", "")
        cfg, params = model
        eng = make_engine(cfg, params, obj_dir=tmp_path)  # must not raise
        obj = eng.kv_tier.object
        assert obj is not None and obj._janitor is None

    def test_janitor_skips_while_breaker_open(self, tmp_path):
        obj, guard = _guarded_tier(tmp_path, threshold=1, window=60.0)
        _seed_orphans(str(tmp_path))
        guard.breaker.record_failure()
        obj.start_janitor(0.03, grace_s=0.0)
        time.sleep(0.2)
        obj.stop_janitor()
        assert obj.scrub_repairs == 0  # never walked the dead store


class TestFsckScriptSmoke:
    def test_dry_run_subprocess(self, tmp_path):
        _seed_orphans(str(tmp_path))
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = os.path.join(root, "scripts", "objstore_fsck.py")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, script, str(tmp_path), "--dry-run",
             "--grace", "3600"],
            capture_output=True, text=True, timeout=180, env=env,
        )
        assert proc.returncode == 1, proc.stderr  # orphans found, not fixed
        report = json.loads(proc.stdout)
        assert len(report["refless_objects"]) == 1
        assert len(report["dangling_refs"]) == 1
        assert len(report["dead_manifests"]) == 1
        assert report["repaired"] == 0
        # --dry-run beats --repair when both are passed
        proc2 = subprocess.run(
            [sys.executable, script, str(tmp_path), "--dry-run",
             "--repair", "--grace", "3600"],
            capture_output=True, text=True, timeout=180, env=env,
        )
        assert proc2.returncode == 1
        assert json.loads(proc2.stdout)["repaired"] == 0
        # repair exits 0 and a clean re-run stays 0
        proc3 = subprocess.run(
            [sys.executable, script, str(tmp_path), "--repair",
             "--grace", "3600"],
            capture_output=True, text=True, timeout=180, env=env,
        )
        assert proc3.returncode == 0, proc3.stdout
        assert json.loads(proc3.stdout)["repaired"] == 3


# ---------------------------------------------------------------------------
# chaos at the new failpoint sites (engine invariants preserved)
# ---------------------------------------------------------------------------


class TestChaosNewSites:
    @pytest.mark.parametrize("site,action,arg", [
        ("kv.object_head", "error", ""),
        ("kv.object_head", "delay", "0.02"),
        ("kv.object_list", "error", ""),
        ("kv.object_list", "delay", "0.02"),
    ])
    def test_engine_serves_through_site_chaos(self, model, tmp_path,
                                              site, action, arg):
        cfg, params = model
        eng = make_engine(cfg, params, obj_dir=tmp_path / "s")
        prompt = list(range(1, 17))
        with fp.armed(site, action, arg):
            for i in range(2):
                r = GenRequest(request_id=f"c{i}",
                               prompt_ids=prompt + [30 + i],
                               max_new_tokens=3, prefix_key=f"cs-{i}")
                eng.submit(r)
                eng.run_to_completion()
                assert r.finish_reason == "length"
        assert not eng.self_check()
        # and fsck under list chaos degrades to a partial report
        if site == "kv.object_list":
            with fp.armed(site, "error"):
                report = fsck(eng.kv_tier.object.store.inner,
                              grace_s=0.0, repair=False)
            assert report["errors"] >= 1
            assert report["repaired"] == 0


# ---------------------------------------------------------------------------
# the e2e outage containment proof (bench.py store_outage, CPU smoke)
# ---------------------------------------------------------------------------


class TestBenchStoreOutage:
    def test_store_outage_phase_cpu(self, model):
        import importlib.util

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "bench", os.path.join(root, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        sys.modules["bench"] = bench
        spec.loader.exec_module(bench)
        cfg, params = model
        out = bench.store_outage_phase(cfg, params, n_threads=5,
                                       common_len=96, suffix_len=16,
                                       gen_len=8, page_size=8)
        # store healthy: the first resume wakes from the object tier
        assert out["pre_outage_cache_source"] == "object_tier"
        # the storm opened the breaker...
        assert out["breaker_opened"] is True
        assert out["breaker_state_during"] == "open"
        # ...and no resume stalled on a store op: p99 within noise of
        # the storeless re-prefill baseline, full attainment throughout
        assert out["contained"], out["ttft_p99_ms"]
        assert out["attainment_during_outage"] == 1.0
        assert all(src != "object_tier"
                   for src in out["outage_cache_sources"])
        # the store came back: the half-open probe closed the breaker
        # and the drained thread woke from its manifest, token-exact
        assert out["recovered_cache_source"] == "object_tier"
        assert out["outputs_match"] is True
