"""Playground single-file client checks (VERDICT r3 next #7).

No browser/JS runtime exists in this environment, so these tests guard
what is mechanically checkable: the page serves, the UX surfaces the
verdict asked for are present (markdown renderer, tool-call cards,
per-completion segmentation, stop/abort), and the inline script is
lexically sound (an ordered scanner that understands JS strings, template
literals, comments, and regex literals balance-checks every bracket — a
stray brace would otherwise break the ENTIRE client silently).
"""

import re

import pytest

PLAYGROUND = "kafka_tpu/server/playground.html"


def _script(path=PLAYGROUND):
    html = open(path).read()
    m = re.search(r"<script>(.*)</script>", html, re.S)
    assert m, "no script block"
    return html, m.group(1)


def scan_js(js: str):
    """Ordered lexical scan: yields bracket tokens outside strings,
    template literals, comments, and regex literals."""
    i, n = 0, len(js)
    out = []
    # chars after which a `/` starts a regex, not division
    regex_prefix = set("=([{,;:!&|?+-*%~^<>\n")
    last_sig = "\n"
    while i < n:
        c = js[i]
        if c == "/" and i + 1 < n and js[i + 1] == "/":
            i = js.find("\n", i)
            i = n if i < 0 else i
            continue
        if c == "/" and i + 1 < n and js[i + 1] == "*":
            i = js.find("*/", i)
            assert i >= 0, "unterminated block comment"
            i += 2
            continue
        if c in "'\"":
            q = c
            i += 1
            while i < n and js[i] != q:
                i += 2 if js[i] == "\\" else 1
            assert i < n, f"unterminated string at ...{js[max(0,i-40):i]}"
            i += 1
            last_sig = q
            continue
        if c == "`":
            i += 1
            while i < n and js[i] != "`":
                if js[i] == "\\":
                    i += 2
                    continue
                if js[i] == "$" and i + 1 < n and js[i + 1] == "{":
                    # template expression: scan to matching }
                    depth = 1
                    i += 2
                    while i < n and depth:
                        if js[i] == "{":
                            depth += 1
                        elif js[i] == "}":
                            depth -= 1
                        i += 1
                    continue
                i += 1
            assert i < n, "unterminated template literal"
            i += 1
            last_sig = "`"
            continue
        if c == "/" and last_sig in regex_prefix:
            i += 1
            in_class = False
            while i < n and (in_class or js[i] != "/"):
                if js[i] == "\\":
                    i += 2
                    continue
                if js[i] == "[":
                    in_class = True
                elif js[i] == "]":
                    in_class = False
                i += 1
            assert i < n, "unterminated regex literal"
            i += 1
            while i < n and js[i].isalpha():
                i += 1
            last_sig = "/"  # regex result: treat like value
            continue
        if not c.isspace():
            last_sig = c
        if c in "{}()[]":
            out.append(c)
        i += 1
    return out


class TestPlaygroundFile:
    def test_script_brackets_balanced(self):
        _, js = _script()
        stack = []
        pairs = {"}": "{", ")": "(", "]": "["}
        for tok in scan_js(js):
            if tok in "{([":
                stack.append(tok)
            else:
                assert stack and stack[-1] == pairs[tok], (
                    f"unbalanced {tok!r} (stack tail {stack[-5:]})"
                )
                stack.pop()
        assert not stack, f"unclosed brackets: {stack}"

    def test_ux_surfaces_present(self):
        html, js = _script()
        # markdown renderer + tool cards + segmentation + stop/abort
        for marker in (
            "mdToHtml", "mdInline", "<pre><code>",     # markdown
            "toolCard", "card-head", "prettyJson",     # tool-call cards
            "completionId",                            # per-completion seg
            "AbortController", "aborter.abort",        # stop button
            "tool_messages", "tool_result",
            "agent_done", "[DONE]",                    # SSE contract
            "localStorage", "Authorization",           # auth bar
        ):
            assert marker in html, f"missing {marker!r}"

    def test_markdown_renderer_escapes_before_transform(self):
        """mdToHtml must escape raw HTML before inserting tags — the
        escHtml call has to appear inside the inline transformer."""
        _, js = _script()
        inline = js[js.index("function mdInline"):]
        inline = inline[:inline.index("}")]
        assert "escHtml(" in inline


class TestPlaygroundServed:
    def test_served_at_endpoint(self, tmp_path):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        from kafka_tpu.core.types import StreamChunk  # noqa: F401
        from kafka_tpu.db import LocalDBClient
        from kafka_tpu.llm.base import LLMProvider
        from kafka_tpu.server import ServingConfig, create_app

        class NullLLM(LLMProvider):
            provider_name = "null"

            async def stream_completion(self, messages, **kw):
                if False:
                    yield None

            def get_available_models(self):
                return []

        async def go():
            app = await create_app(
                cfg=ServingConfig(db_path=str(tmp_path / "t.db")),
                llm_provider=NullLLM(),
                db=LocalDBClient(str(tmp_path / "t.db")),
                tools=[], mcp_servers=[],
            )
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get("/playground")
                assert r.status == 200
                body = await r.text()
                assert "mdToHtml" in body and "toolCard" in body
            finally:
                await client.close()

        asyncio.run(go())
